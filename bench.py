"""Benchmark harness — emits ONE JSON line for the driver.

Flagship benchmark (BASELINE.md config 3 / north star): AlexNet fused
training-step throughput, samples/sec on one chip — forward + backward +
SGD update of the full 227x227x3 ImageNet geometry, batch 128.
``vs_baseline`` is 1.0 by convention: the reference published no numbers
(BASELINE.json :: published == {}), so the driver-recorded history of this
metric across rounds IS the baseline trend.

Falls back to the FC benchmark if the conv stack cannot run, and says so in
the JSON (``fallback_reason``) so a flagship regression is never silent.
"""

import json
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _throughput(workflow, x, labels, steps: int, warmup: int) -> float:
    """Shared timing protocol: warmed, device-synced samples/sec of the
    fused training step on fixed host inputs."""
    import numpy as np
    import jax
    from znicz_tpu.core import prng

    step = workflow.step
    batch = x.shape[0]
    mask = np.ones(batch, bool)
    params = step._params
    hyper = step.hyper_params()
    key = prng.get().key()
    for _ in range(warmup):
        params, _ = step._train_fn(params, hyper, key, x, labels, mask)
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, _ = step._train_fn(params, hyper, key, x, labels, mask)
    jax.block_until_ready(params)
    return batch * steps / (time.perf_counter() - t0)


def bench_alexnet_train(batch: int = 128, steps: int = 20, warmup: int = 3):
    """Samples/sec of the fused AlexNet training step on one chip."""
    import numpy as np
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.alexnet import build

    prng.seed_all(7)
    # loader dataset is minimal (8 samples): the bench feeds _train_fn its
    # own fixed batch below; the loader only has to satisfy initialize()
    w = build(max_epochs=1, minibatch_size=batch, n_classes=1000,
              input_size=227, n_train=8, n_valid=0,
              loader_config={"n_classes": 8})
    w.initialize(device=TPUDevice())
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 227, 227, 3)).astype(np.float32)
    labels = rng.integers(0, 1000, batch).astype(np.int32)
    return _throughput(w, x, labels, steps, warmup)


def bench_fc_train(batch: int = 1024, steps: int = 50, warmup: int = 5):
    """Fallback: samples/sec of the fused FC training step."""
    import numpy as np
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused

    prng.seed_all(7)
    w = build_fused(max_epochs=1, layers=(4096, 4096), minibatch_size=batch,
                    n_train=2 * batch, n_valid=0)
    w.initialize(device=TPUDevice())
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28)).astype(np.float32)
    labels = rng.integers(0, 10, batch).astype(np.int32)
    return _throughput(w, x, labels, steps, warmup)


def main():
    result = {"unit": "samples/sec", "vs_baseline": 1.0}
    try:
        result["value"] = round(bench_alexnet_train(), 1)
        result["metric"] = "alexnet_b128_train_samples_per_sec_per_chip"
    except Exception as exc:  # noqa: BLE001
        result["value"] = round(bench_fc_train(), 1)
        result["metric"] = "mnist_fc4096_train_samples_per_sec_per_chip"
        result["fallback_reason"] = f"alexnet bench failed: {exc!r}"[:200]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
