"""Benchmark harness — always lands a parseable JSON result line.

Measures all five BASELINE.md configs: MNIST-FC and AlexNet training
throughput (flagship, re-emitted as the final line), CIFAR ConvRELU and
Deconv-AE throughput, Kohonen SOM throughput, and MNIST-conv wall-clock
to 99% validation accuracy over the IDX file pipeline.  Throughput lines
carry ``mfu`` (analytic FLOPs model vs the chip's dense bf16 peak).
``vs_baseline`` is the cross-round trend — current value over the newest
driver-recorded ``BENCH_r*.json`` for the same metric (the reference
published no absolute numbers; BASELINE.json :: published == {}).  1.0
means "no prior round measured this metric".

Round-1 failure mode and the defenses against it (VERDICT.md items 1b/4):
the TPU claim through this sandbox's loopback relay can block for many
minutes or hang outright, and round 1's monolithic bench died printing
nothing.  Defenses:

- the TPU work runs in a SUBPROCESS under a hard timeout; ONE process
  claims the chip once and runs the cheap FC bench FIRST, flushing a full
  result line the moment it exists, then the AlexNet flagship;
- on timeout the parent still parses whatever lines the child flushed;
- one retry (claims have been observed to recover after minutes), then a
  clearly-marked CPU fallback so SOME number always lands;
- a persistent XLA compilation cache under .data/cache/jax makes repeat
  runs skip the 20-40s compiles.

The driver reads the LAST JSON line — the best number available; every
earlier line is a complete valid result on its own.
"""

import contextlib
import functools
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

CACHE_DIR = os.path.join(REPO, ".data", "cache", "jax")

#: wall-clock budgets (seconds); worst-case total stays under ~25 min.
#: Env-overridable for testing and driver tuning.
TPU_TIMEOUT = int(os.environ.get("BENCH_TPU_TIMEOUT", 780))
TPU_RETRY_TIMEOUT = int(os.environ.get("BENCH_TPU_RETRY_TIMEOUT", 480))
CPU_TIMEOUT = int(os.environ.get("BENCH_CPU_TIMEOUT", 300))


def _enable_compile_cache():
    # ISSUE 7: routed through the compile-latency plane so cache
    # hits/misses land in znicz_compile_cache_{hits,misses}_total and
    # every scenario line can report its compile-cost delta.  The env
    # override ($ZNICZ_TPU_COMPILE_CACHE) wins over the repo-local dir —
    # the compile_latency scenario uses that to point its probe children
    # at a fresh directory.
    import jax  # noqa: F401 — ensure() only configures once jax exists
    from znicz_tpu import compilecache

    os.environ.setdefault(compilecache.ENV_VAR, CACHE_DIR)
    compilecache.configure(min_compile_time_s=0.0)


@contextlib.contextmanager
def _maybe_profile():
    """jax trace around the timed region when BENCH_PROFILE names a
    directory; exception-safe so a mid-loop device failure (the wedging
    pool this repo's watcher exists for) never leaves a trace open."""
    import jax

    profile_dir = os.environ.get("BENCH_PROFILE")
    if not profile_dir:
        yield
        return
    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        try:
            from znicz_tpu.utils.profiling import summarize_trace
            for r in summarize_trace(profile_dir, top=12):
                print(f"# prof {r['total_ms']:9.2f} ms x{r['count']:<4} "
                      f"{r['op'][:100]}", file=sys.stderr)
        except Exception as exc:  # noqa: BLE001 — summary is best-effort
            print(f"# prof summary unavailable: {exc!r}", file=sys.stderr)


def _throughput(step, x, labels, K: int = 8, reps: int = 3) -> float:
    """Shared timing protocol: K minibatches per dispatch via the step's
    ``train_steps`` scan (amortizes the per-call dispatch latency, ~14 ms
    through this sandbox's TPU tunnel), inputs staged ON DEVICE first (the
    role of a real input pipeline), synced by a device->host metric read.
    ``jax.block_until_ready`` does NOT synchronize on the axon platform —
    round 2's numbers were dispatch rates, not throughput; the d2h read is
    the only honest fence."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    batch = x.shape[0]
    # one h2d of the base batch; the K rolled copies are built ON DEVICE
    # by a gather (np.roll(x, k)[i] == x[(i-k) % batch]) — at the r5 K
    # values host-side np.stack would peak at ~1.6 GB and push ~1 GB
    # through the TPU tunnel before timing starts
    xd, yd = jnp.asarray(x), jnp.asarray(labels)
    idx = jnp.asarray((np.arange(batch)[None, :] -
                       np.arange(K)[:, None]) % batch)
    xs = xd[idx]                          # (K, batch, ...)
    ys = yd[idx]                          # roll on the batch axis only —
    ms = jnp.ones((K, batch), bool)       # labels may be image targets
    jax.device_get(xs[0, 0, 0])          # fence the staging transfers

    metrics = step.train_steps(xs, ys, ms)      # compile + warm
    float(jax.device_get(metrics["loss"]))
    with _maybe_profile():
        t0 = time.perf_counter()
        for _ in range(reps):
            metrics = step.train_steps(xs, ys, ms)
        float(jax.device_get(metrics["loss"]))  # fences the whole chain
        dt = time.perf_counter() - t0
    return batch * K * reps / dt


@functools.lru_cache(maxsize=1)
def _last_hw_snapshot() -> dict:
    """The newest tracked HARDWARE bench record (docs/bench_hw_*.jsonl),
    compacted to metric/value/unit/mfu per line plus the capture
    timestamp — embedded verbatim into CPU-fallback artifacts so the
    driver record stands alone (VERDICT r5 item 7: a fallback line must
    not need a doc pointer to reach hardware truth)."""
    import glob
    import re

    def round_key(p):
        # order by the round number in the name — mtime is clone time on
        # a fresh checkout and says nothing about capture order
        m = re.search(r"bench_hw_r(\d+)", os.path.basename(p))
        return (int(m.group(1)) if m else -1, os.path.basename(p))

    paths = sorted(glob.glob(os.path.join(REPO, "docs", "bench_hw_*.jsonl")),
                   key=round_key)
    if not paths:
        return {}
    path = paths[-1]
    records = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(r, dict) or "metric" not in r:
                    continue
                rec = {k: r[k] for k in ("metric", "value", "unit", "mfu")
                       if k in r}
                records.append(rec)
    except OSError:
        return {}
    # capture time: the commit that introduced the record (stable across
    # checkouts), falling back to file mtime outside a git context
    try:
        ts = subprocess.run(
            ["git", "log", "-1", "--format=%cI", "--", path],
            capture_output=True, text=True, timeout=10,
            cwd=REPO).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        ts = ""
    if not ts:
        ts = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                           time.gmtime(os.path.getmtime(path)))
    return {
        "source": os.path.relpath(path, REPO),
        "timestamp": ts,
        "records": records,
    }


@functools.lru_cache(maxsize=1)
def _prev_round_values() -> dict:
    """metric -> newest driver-recorded result dict from BENCH_r*.json —
    ``vs_baseline`` reports the cross-round trend (the reference published
    no absolute numbers; BASELINE.json :: published == {})."""
    import glob

    vals = {}
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for line in str(doc.get("tail", "")).splitlines():
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(r, dict) and "metric" in r and "value" in r:
                vals[r["metric"]] = r                   # later rounds win
    return vals


#: compile-cost baseline for per-line deltas (ISSUE 7): totals as of the
#: previous _emit, so each scenario line carries ITS OWN compile bill
_compile_base = None


def _compile_totals():
    """Lifetime compile-cost totals: summed ``znicz_compile_seconds``
    (cold trace+compile+run wall time of wrapped programs and engine
    buckets) plus the persistent-cache hit/miss counters."""
    try:
        from znicz_tpu.observe import REGISTRY, compile_cache_stats
        snap = REGISTRY.snapshot_flat(skip_zero=False)
        cold = sum(v for k, v in snap.items()
                   if k.startswith("znicz_compile_seconds_sum"))
        hits, misses = compile_cache_stats()
        return {"cold_seconds": cold, "cache_hits": hits,
                "cache_misses": misses}
    except Exception as exc:  # noqa: BLE001 — telemetry must not cost
        print(f"# compile totals unavailable: {exc!r}", file=sys.stderr)
        return None


def _emit(metric: str, value: float, forwards=None, batch: int = 0,
          unit: str = "samples/sec", lower_is_better: bool = False,
          trend_valid: bool = True, **extra) -> dict:
    """Flush one complete result line (mfu only when on real TPU and the
    workflow has MXU-countable forwards).  ``vs_baseline`` is oriented so
    >1 always means improvement (prev/value for time-like metrics); 0.0
    marks a run that is not comparable (``trend_valid=False``, e.g. the
    wall-clock run gave up before the target), and prior non-comparable
    runs are likewise never used as the trend base."""
    import jax
    from znicz_tpu.utils import flops

    prev_entry = _prev_round_values().get(metric)
    trend = 1.0
    if not trend_valid:
        trend = 0.0
    elif prev_entry and prev_entry.get("reached_target", True) and \
            float(prev_entry["value"]) > 0:
        prev = float(prev_entry["value"])
        trend = round(prev / value, 3) if lower_is_better \
            else round(value / prev, 3)
    out = {"metric": metric, "value": round(value, 1), "unit": unit,
           "vs_baseline": trend, **extra}
    if forwards is not None and jax.default_backend() != "cpu":
        m = flops.mfu(value, forwards, batch)
        if m is not None:
            out["mfu"] = round(m, 4)
    # ISSUE 5: every scenario line carries the child's telemetry-plane
    # snapshot (compact name{labels} -> value; zero series dropped) so a
    # recorded bench artifact shows recompiles/stalls/step counts
    # without rerunning anything
    try:
        from znicz_tpu.observe import REGISTRY
        snap = REGISTRY.snapshot_flat()
        if snap:
            out["registry"] = snap
    except Exception as exc:  # noqa: BLE001 — telemetry must not cost
        print(f"# registry snapshot unavailable: {exc!r}",  # the line
              file=sys.stderr)
    # ISSUE 7 satellite: every line records the compile cost IT paid —
    # cold compile seconds + persistent-cache hit/miss deltas since the
    # previous line, so BENCH_r06 onward separates compile bill from
    # throughput without rerunning anything
    global _compile_base
    cur = _compile_totals()
    if cur is not None:
        base = _compile_base or {k: 0 for k in cur}
        out["compile"] = {
            "cold_seconds": round(cur["cold_seconds"] -
                                  base["cold_seconds"], 3),
            "cache_hits": cur["cache_hits"] - base["cache_hits"],
            "cache_misses": cur["cache_misses"] - base["cache_misses"]}
        _compile_base = cur
    print(json.dumps(out), flush=True)
    return out


# ---------------------------------------------------------------------------
# child: claims the device once, benches cheapest-first, flushes each line
# ---------------------------------------------------------------------------

def bench_fc(batch=1024, layers=(4096, 4096), K=256, reps=3):
    # K=256: the r4 FC trace (docs/TRACE_R4.md) measured 0.38 ms/step of
    # per-dispatch overhead at K=64 — 33% of the 1.165 ms wall step;
    # K=256 cuts it to ~0.09 ms (staging 256×3 MB ≈ 820 MB, well inside
    # HBM)
    import numpy as np
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused

    t0 = time.time()
    prng.seed_all(7)
    # bf16 momentum storage: at this batch the f32 w+v update traffic
    # rivals the matmul time (docs/TUNING.md); math stays f32, and the
    # state_dtype convergence/resume pins cover the narrowing
    w = build_fused(max_epochs=1, layers=layers, minibatch_size=batch,
                    n_train=2 * batch, n_valid=0,
                    optimizer_config={"state_dtype": "bfloat16"})
    w.initialize(device=TPUDevice())
    print(f"# fc: initialized in {time.time() - t0:.1f}s", file=sys.stderr)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28)).astype(np.float32)
    labels = rng.integers(0, 10, batch).astype(np.int32)
    sps = _throughput(w.step, x, labels, K, reps)
    _emit(f"mnist_fc{layers[0]}_train_samples_per_sec_per_chip", sps,
          w.forwards, batch, state_dtype="bfloat16")


def bench_alexnet(batch=128, K=16, reps=3):
    # K=16: ~3 ms/step of dispatch overhead at K=8 (18% of wall,
    # docs/TRACE_R4.md) halves; staging 16×79 MB ≈ 1.3 GB
    import numpy as np
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.alexnet import build

    t0 = time.time()
    prng.seed_all(7)
    # loader dataset is minimal (8 samples): the bench stages its own
    # device-resident batches below; the loader only satisfies initialize()
    # bf16 momentum storage: the 62M-param SGD update moves ~1.2 GB/step
    # of f32 state; the narrow velocity halves its share (docs/TUNING.md)
    w = build(max_epochs=1, minibatch_size=batch, n_classes=1000,
              input_size=227, n_train=8, n_valid=0,
              loader_config={"n_classes": 8},
              optimizer_config={"state_dtype": "bfloat16"})
    w.initialize(device=TPUDevice())
    print(f"# alexnet: initialized in {time.time() - t0:.1f}s",
          file=sys.stderr)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 227, 227, 3)).astype(np.float32)
    labels = rng.integers(0, 1000, batch).astype(np.int32)
    sps = _throughput(w.step, x, labels, K, reps)
    flagship = _emit("alexnet_b128_train_samples_per_sec_per_chip", sps,
                     w.forwards, batch, state_dtype="bfloat16")
    if os.environ.get("BENCH_ALEXNET_B256"):
        # ceiling probe (watcher-budget only — the driver's default
        # child budget must not pay this extra compile): 2x batch shows
        # what the conv stack sustains when fixed costs amortize, the
        # same A/B CIFAR runs at b2048.  AFTER the flagship emit so a
        # hang here can never lose the trend-tracked b128 line, and
        # named so main()'s "alexnet" flagship filter cannot pick it
        del w
        prng.seed_all(7)
        w2 = build(max_epochs=1, minibatch_size=2 * batch, n_classes=1000,
                   input_size=227, n_train=8, n_valid=0,
                   loader_config={"n_classes": 8},
                   optimizer_config={"state_dtype": "bfloat16"})
        w2.initialize(device=TPUDevice())
        x2 = rng.normal(size=(2 * batch, 227, 227, 3)).astype(np.float32)
        l2 = rng.integers(0, 1000, 2 * batch).astype(np.int32)
        _emit("ceiling_alexnet_b256_train_samples_per_sec_per_chip",
              _throughput(w2.step, x2, l2, max(K // 2, 4), reps),
              w2.forwards, 2 * batch, state_dtype="bfloat16")
    return flagship


def bench_cifar(batch=512, K=64, reps=3):
    """BASELINE.md config 2: CIFAR-10 ConvRELU + MaxPooling + GDConv.

    Two batch sizes: b512 is the cross-round continuity config; the r4
    trace (docs/TRACE_R4.md) showed ~65% of its wall step was
    per-dispatch overhead (32 tiny param/momentum copies + dispatch
    latency), so K rises 16→64 to amortize it; the 4x batch line shows
    what the conv path sustains when the MXU work amortizes the
    elementwise soup (K=16 there keeps staging at ~400 MB)."""
    import numpy as np
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.cifar_conv import build

    for b, k in ((batch, K), (4 * batch, max(K // 4, 2))):
        # (b512, K=64) and (b2048, K=16): equal samples per dispatch,
        # so the fixed cost amortizes identically and the A/B isolates
        # the per-sample compute efficiency
        t0 = time.time()
        prng.seed_all(7)
        w = build(max_epochs=1, minibatch_size=b, n_train=b, n_valid=0,
                  loader_name="synthetic_image",
                  loader_config={"n_classes": 10})
        w.initialize(device=TPUDevice())
        print(f"# cifar b{b}: initialized in {time.time() - t0:.1f}s",
              file=sys.stderr)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(b, 32, 32, 3)).astype(np.float32)
        labels = rng.integers(0, 10, b).astype(np.int32)
        sps = _throughput(w.step, x, labels, k, reps)
        _emit(f"cifar_convrelu_b{b}_train_samples_per_sec_per_chip", sps,
              w.forwards, b)


def bench_deconv_ae(batch=64, K=64, reps=3):
    # K=64: the deconv step is 0.45 ms in-loop (docs/TRACE_R4.md);
    # dispatch overhead dominates at K=8; staging 64×3 MB ≈ 200 MB
    """BASELINE.md config 4 at ImagenetAE-representative scale: 64x64x3
    input, 64/128-kernel strided conv encoder, mirrored deconv decoder.
    (The r1-r3 32x32x1/32-kernel toy measured model smallness, not the
    deconv path — VERDICT r3 weak #3.)"""
    import numpy as np
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.autoencoder import build_deep

    t0 = time.time()
    prng.seed_all(7)
    w = build_deep(max_epochs=1, minibatch_size=batch,
                   sample_shape=(64, 64, 3), n_kernels=(64, 128),
                   n_train=batch, n_valid=0)
    w.initialize(device=TPUDevice())
    print(f"# deconv_ae: initialized in {time.time() - t0:.1f}s",
          file=sys.stderr)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 64, 64, 3)).astype(np.float32)
    sps = _throughput(w.step, x, x, K, reps)   # identity targets (MSE)
    _emit(f"deconv_ae64_b{batch}_train_samples_per_sec_per_chip", sps,
          w.forwards, batch)


def bench_transformer(batch=8, seq=2048, d=512, n_layers=6, heads=8,
                      vocab=32000, K=4, reps=3):
    """Beyond-parity headline: decoder-transformer training throughput
    (ring-attention-capable stack on a 1-chip mesh), tokens/sec/chip.
    Tries the Pallas flash-attention core first; if the kernel fails to
    lower on this backend, retries with the XLA attention path so the
    phase still lands a number (``attention`` reports which ran)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root as root_cfg
    from znicz_tpu.parallel import transformer as tfm
    from znicz_tpu.parallel.mesh import make_mesh

    t0 = time.time()
    mesh = make_mesh({"data": 1, "seq": 1, "model": 1})
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)
    labels = jnp.asarray(np.roll(np.asarray(tokens), -1, axis=1))
    from znicz_tpu.ops.pallas.attention import supported as flash_ok
    attempted_flash = (tfm._flash_eligible(mesh, False) and
                       flash_ok(seq, d // heads))
    attention = "flash" if attempted_flash else "xla"
    try:
        prng.seed_all(7)
        params = tfm.init_params(prng.get(), n_layers, d, heads, 4 * d,
                                 vocab)
        # loss_chunks=16: the (16384, 32000) f32 logits are ~2 GB and
        # the CE stack multiplies that through log_softmax + AD
        # residuals — chunked remat keeps one 1024-token chunk live
        # (docs/TUNING.md)
        step, _ = tfm.make_train_step(mesh, n_layers, d, heads, 4 * d,
                                      vocab, lr=1e-3, donate=True,
                                      loss_chunks=16)
        params, loss = step(params, tokens, labels)   # compile + warm
        float(jax.device_get(loss))
    except Exception as exc:  # noqa: BLE001 — flash may not lower here
        print(f"# transformer flash path failed ({exc!r}); retrying "
              f"with XLA attention", file=sys.stderr)
        attention = "xla"
        prev = root_cfg.common.engine.get("flash_attention", True)
        root_cfg.common.engine.flash_attention = False
        try:
            prng.seed_all(7)
            params = tfm.init_params(prng.get(), n_layers, d, heads,
                                     4 * d, vocab)
            step, _ = tfm.make_train_step(mesh, n_layers, d, heads,
                                          4 * d, vocab, lr=1e-3,
                                          donate=True, loss_chunks=16)
            params, loss = step(params, tokens, labels)
            float(jax.device_get(loss))
        finally:
            root_cfg.common.engine.flash_attention = prev
    print(f"# transformer ({attention}): initialized in "
          f"{time.time() - t0:.1f}s", file=sys.stderr)
    with _maybe_profile():
        t0 = time.perf_counter()
        for _ in range(K * reps):
            params, loss = step(params, tokens, labels)
        float(jax.device_get(loss))
        dt = time.perf_counter() - t0
    tps = batch * seq * K * reps / dt
    # MFU via the standard 6*N*T estimate (params N dominated by matmuls)
    n_params = sum(int(np.prod(np.shape(p)))
                   for p in jax.tree.leaves(params))
    from znicz_tpu.utils import flops as flops_mod
    peak = flops_mod.peak_flops()
    extra = {}
    if peak and jax.default_backend() != "cpu":
        extra["mfu"] = round(6.0 * n_params * tps / peak, 4)
        # the embedding LOOKUP does no matmul FLOPs (gather fwd /
        # scatter-add bwd), so 6N with emb included over-credits ~1.4x
        # at this vocab/d; report the matmul-only figure alongside for
        # honest accounting (the r3 gate tracks "mfu")
        extra["mfu_matmul_only"] = round(
            6.0 * (n_params - vocab * d) * tps / peak, 4)
    if attention == "xla" and jax.default_backend() != "cpu":
        # the headline kernel must never silently die on hardware
        # (VERDICT r3 weak #5) — make the degradation loud, and say
        # which kind it was: a lowering failure leaves an error on
        # stderr; an ineligible/disabled geometry never attempted flash
        extra["warning"] = (
            "flash attention did not lower on TPU — XLA fallback "
            "measured; see stderr for the lowering error"
            if attempted_flash else
            "flash attention ineligible for this config (disabled or "
            "unsupported geometry) — XLA attention measured")
        print(f"# WARNING: transformer measured with XLA attention on "
              f"real TPU ({'lowering failure' if attempted_flash else 'flash ineligible'})",
              file=sys.stderr)
    _emit(f"transformer_l{n_layers}d{d}s{seq}_train_tokens_per_sec_per_chip",
          tps, unit="tokens/sec", attention=attention, **extra)


def bench_pallas_parity():
    """VERDICT r3 item 4: every Pallas kernel family executed COMPILED
    (interpret=False) on the real chip against its oracle — one
    ``pallas_hw_parity`` line, per-kernel ok/FAIL, lowering failure is a
    FAIL (never a silent fallback)."""
    import jax

    if jax.default_backend() == "cpu":
        print("# pallas_hw_parity skipped: no TPU backend", file=sys.stderr)
        return
    from znicz_tpu.utils.pallas_hw import run_parity

    t0 = time.time()
    kernels = run_parity(interpret=False)
    n_ok = sum(1 for v in kernels.values() if v == "ok")
    print(f"# pallas_hw_parity: {n_ok}/{len(kernels)} in "
          f"{time.time() - t0:.1f}s", file=sys.stderr)
    _emit("pallas_hw_parity_kernels_ok", float(n_ok), unit="kernels",
          total=len(kernels), kernels=kernels)


def bench_kohonen(n_train=4000, minibatch=500, epochs=3):
    """BASELINE.md config 5: Kohonen SOM winner-take-all training.  The
    SOM trainer is its own accelerated unit (not a FusedTrainStep); runs
    in epoch-scan mode (one compiled dispatch per class pass), so this
    measures the scanned unit-graph hot loop end to end."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models.kohonen import build

    t0 = time.time()
    prev_scan = root.common.engine.get("scan_epoch", False)
    root.common.engine.scan_epoch = True
    try:
        # warm-up: one throwaway epoch compiles the SOM kernels (same
        # shapes), matching the compile-then-time protocol of _throughput
        prng.seed_all(7)
        warm = build(max_epochs=1, shape=(16, 16), minibatch_size=minibatch,
                     n_train=n_train, sample_shape=(16,), min_delta=0.0)
        warm.initialize(device=TPUDevice())
        warm.run()
        prng.seed_all(7)
        w = build(max_epochs=epochs, shape=(16, 16),
                  minibatch_size=minibatch, n_train=n_train,
                  sample_shape=(16,), min_delta=0.0)
        w.initialize(device=TPUDevice())
        print(f"# kohonen: initialized+warmed in {time.time() - t0:.1f}s",
              file=sys.stderr)
        t0 = time.perf_counter()
        w.run()
        # the run's last device work is async; fence on the weights read
        w.trainer.weights.map_read()
        dt = time.perf_counter() - t0
    finally:
        root.common.engine.scan_epoch = prev_scan
    _emit("kohonen_som256_train_samples_per_sec_per_chip",
          n_train * epochs / dt,
          # 16 KB weight table, ~KB-scale per-step traffic: the SOM is
          # dispatch-latency-bound, not MXU/HBM-bound — scan mode exists
          # to collapse dispatches (roofline: docs/BENCH_LOG.md)
          bound="dispatch-latency", scan_mode=True)


def bench_mnist_wallclock(n_train=6000, n_valid=1000, target_pct=1.0,
                          max_epochs=25):
    """BASELINE.md headline metric: MNIST-conv wall-clock to 99% validation
    accuracy over the IDX file pipeline (synthesized digits stand in for
    the undownloadable real files; same byte format, same loader path)."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models.mnist_conv import build

    t0 = time.time()
    prng.seed_all(7)
    target = int(n_valid * target_pct / 100.0)
    # one compiled scan per class pass — per-minibatch dispatch latency
    # (~14 ms through the sandbox tunnel) leaves the wall-clock entirely
    prev_scan = root.common.engine.get("scan_epoch", False)
    root.common.engine.scan_epoch = True
    w = build(max_epochs=max_epochs, minibatch_size=200, n_train=n_train,
              n_valid=n_valid)
    w.decision.target_metric = target
    try:
        w.initialize(device=TPUDevice())
        print(f"# mnist_wallclock: initialized in {time.time() - t0:.1f}s",
              file=sys.stderr)
        t0 = time.perf_counter()
        w.run()
        wall = time.perf_counter() - t0
    finally:
        root.common.engine.scan_epoch = prev_scan
    hist = w.decision.metrics_history
    reached = hist[-1]["metric_validation"] <= target
    _emit("mnist_conv_wallclock_to_99pct_sec", wall, unit="s",
          lower_is_better=True, trend_valid=bool(reached),
          epochs=len(hist),
          final_validation_errors=int(hist[-1]["metric_validation"]),
          reached_target=bool(reached),
          # accuracy is against SYNTHESIZED stand-in digits (no network
          # in the sandbox) — pipeline-valid, not comparable to the
          # reference's published accuracy on real MNIST bytes
          synthesized_data=True)


def bench_serve(duration_s=4.0, clients=8, max_batch=32):
    """serve/ plane scenario: threaded clients hammer the in-process
    micro-batcher + bucketed engine (CPU — this measures the serving
    machinery, not the chip) and the line reports sustained QPS with the
    p95 request latency and observed coalescing from the serving
    metrics.  Zero steady-state recompiles is asserted, not assumed."""
    import threading

    import numpy as np
    import jax
    import jax.numpy as jnp

    from znicz_tpu.serve import BatchEngine, MicroBatcher

    rng = np.random.default_rng(0)
    w1 = jnp.asarray(rng.normal(0, 0.1, (64, 256)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(0, 0.1, (256, 16)).astype(np.float32))

    @jax.jit
    def mlp(x):
        return jnp.tanh(x @ w1) @ w2

    engine = BatchEngine(mlp, max_batch=max_batch, input_shape=(64,))
    engine.warmup()
    compiles = engine.compile_count
    batcher = MicroBatcher(engine, max_wait_ms=2.0, max_queue=512,
                           default_timeout_s=60.0)
    stop_at = time.perf_counter() + duration_s
    errors = []

    def client(cid):
        crng = np.random.default_rng(cid)
        x = crng.normal(size=(1, 64)).astype(np.float32)
        try:
            while time.perf_counter() < stop_at:
                batcher.predict(x)
        except Exception as exc:  # noqa: BLE001 — surface below
            errors.append(repr(exc))

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 60)
    elapsed = time.perf_counter() - t0
    batcher.stop()
    if errors:
        raise RuntimeError(f"serve bench clients failed: {errors[:3]}")
    if engine.compile_count != compiles:
        raise RuntimeError(
            f"steady-state recompiled: {compiles} -> {engine.compile_count}")
    snap = batcher.metrics.snapshot()
    sizes = {int(k): v for k, v in snap["batch_size_histogram"].items()}
    mean_batch = sum(k * v for k, v in sizes.items()) / \
        max(sum(sizes.values()), 1)
    _emit("serve_engine_qps", snap["completed"] / elapsed,
          unit="requests/sec",
          p95_latency_ms=snap["latency"]["p95_ms"],
          p50_latency_ms=snap["latency"]["p50_ms"],
          clients=clients, mean_coalesced_batch=round(mean_batch, 2),
          max_coalesced_batch=max(sizes) if sizes else 0,
          compile_count=engine.compile_count, cpu=True)


def bench_generate(slots=4, max_len=128, n_requests=16, max_new=24,
                   n_layers=2, d=64, heads=4, ff=128, vocab=64):
    """Generative serving scenario (ISSUE 10): seeded mixed-length
    requests stream through the KV-cache continuous batcher (CPU — this
    measures the decode plane's machinery) and the line reports
    sustained tokens/sec with TTFT p50/p95 from the generate metrics.
    Steady-state compile delta == 0 after warmup is asserted AFTER the
    line lands — a broken zero-recompile contract must fail the
    scenario loudly, not ride a JSON field nobody greps."""
    import numpy as np

    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.serve import ContinuousBatcher, KVDecoder

    params = init_params(np.random.default_rng(7), n_layers, d, heads,
                         ff, vocab)
    decoder = KVDecoder(params, heads=heads, max_len=max_len,
                        batch=slots)
    decoder.warmup()
    compiles_after_warmup = decoder.compile_count
    batcher = ContinuousBatcher(decoder, max_queue=n_requests,
                                default_timeout_s=120.0)
    rng = np.random.default_rng(1)
    t0 = time.perf_counter()
    streams = []
    for i in range(n_requests):
        prompt = rng.integers(0, vocab,
                              size=int(rng.integers(4, 32))).tolist()
        streams.append(batcher.submit(
            prompt, max_new_tokens=max_new, temperature=0.8, top_k=8,
            seed=i))
    total_tokens = sum(len(s.result(timeout_s=300)) for s in streams)
    elapsed = time.perf_counter() - t0
    batcher.stop()
    snap = batcher.metrics.snapshot()
    compile_delta = decoder.compile_count - compiles_after_warmup
    _emit("generate_tokens_per_sec", total_tokens / elapsed,
          unit="tokens/sec",
          ttft_p50_ms=snap["ttft"]["p50_ms"],
          ttft_p95_ms=snap["ttft"]["p95_ms"],
          requests=n_requests, slots=slots,
          completed=snap["completed"],
          steady_state_compile_delta=compile_delta, cpu=True)
    assert snap["completed"] == n_requests, \
        (f"generate ledger broke: {snap['completed']} of {n_requests} "
         f"requests completed ({snap})")
    assert compile_delta == 0, \
        (f"steady-state decode recompiled: {compiles_after_warmup} -> "
         f"{decoder.compile_count}")


def bench_generate_longtail(slots=8, page=16, max_len=256, n_layers=2,
                            d=48, heads=4, ff=96, vocab=64,
                            arena_pages=73, spec_k=4):
    """Long-tail mix arm (ISSUE 12): short+long greedy requests through
    three decode planes over identical traffic — the PR 10 contiguous
    shared-bucket baseline, the block-paged arena, and paged +
    speculative (1-layer truncated draft).  The line reports tokens/sec
    for all three, the slot ceiling and peak cache bytes at the paged
    arena's resident-row budget, and the speculation acceptance rate.

    Methodology: each arm runs the traffic once to PRIME its compiled
    shapes (only the shapes this traffic actually dispatches — no full
    warmup sweep), then once timed; the steady-state compile delta over
    the timed pass is asserted 0 AFTER the line lands.  Exactness rides
    along: the speculative stream must be token-identical to plain
    paged decode (the ISSUE pin), and paged-vs-contiguous agreement is
    reported."""
    import numpy as np

    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.serve import (ContinuousBatcher, KVDecoder,
                                 PagedKVDecoder, truncate_draft)

    params = init_params(np.random.default_rng(7), n_layers, d, heads,
                         ff, vocab)
    rng = np.random.default_rng(2)
    reqs = []
    for _ in range(16):                  # the short majority
        plen = int(rng.integers(4, 12))
        reqs.append((rng.integers(0, vocab, size=plen).tolist(), 16))
    for _ in range(4):                   # the long tail
        reqs.append((rng.integers(0, vocab, size=16).tolist(), 176))
    reqs = [reqs[i] for i in rng.permutation(len(reqs))]

    def run(decoder, draft=None):
        batcher = ContinuousBatcher(decoder, max_queue=len(reqs),
                                    default_timeout_s=600.0,
                                    draft=draft, spec_k=spec_k)
        t0 = time.perf_counter()
        streams = [batcher.submit(p, max_new_tokens=m)
                   for p, m in reqs]
        outs = [s.result(timeout_s=600) for s in streams]
        elapsed = time.perf_counter() - t0
        snap = batcher.metrics.snapshot()
        bucket = batcher._bucket        # contiguous shared-cache rows
        batcher.stop()
        assert snap["completed"] == len(reqs), \
            (f"long-tail ledger broke: {snap['completed']} of "
             f"{len(reqs)} completed ({snap})")
        # peak concurrently-live slots off the step-counter intervals
        # (deterministic — no wall-clock sampling)
        events = sorted([(s.first_token_step, 1) for s in streams] +
                        [(s.finish_step, -1) for s in streams])
        peak = cur = 0
        for _, delta in events:
            cur += delta
            peak = max(peak, cur)
        tokens = sum(len(o) for o in outs)
        return outs, tokens / elapsed, snap, peak, bucket

    row_bytes = n_layers * heads * (d // heads) * 2 * 4  # K+V, f32

    contig = KVDecoder(params, heads=heads, max_len=max_len,
                       batch=slots)
    run(contig)                                          # prime
    c0 = contig.compile_count
    outs_c, tps_c, snap_c, _, bucket_c = run(contig)
    delta_c = contig.compile_count - c0

    pdec = PagedKVDecoder(params, heads=heads, max_len=max_len,
                          batch=slots, page=page,
                          arena_pages=arena_pages)
    run(pdec)                                            # prime
    p0 = pdec.compile_count
    outs_p, tps_p, snap_p, peak_slots, _ = run(pdec)
    delta_p = pdec.compile_count - p0

    draft = PagedKVDecoder(truncate_draft(params, 1), heads=heads,
                           max_len=max_len, batch=slots, page=page)
    run(pdec, draft=draft)                               # prime
    s0 = pdec.compile_count + draft.compile_count
    outs_s, tps_s, snap_s, _, _ = run(pdec, draft=draft)
    delta_s = pdec.compile_count + draft.compile_count - s0

    judged = snap_s["spec_accepted"] + snap_s["spec_rejected"]
    arena_rows = (arena_pages - 1) * page
    _emit("generate_longtail_tokens_per_sec", tps_p,
          unit="tokens/sec",
          contiguous_tokens_per_sec=round(tps_c, 1),
          paged_speedup=round(tps_p / tps_c, 3),
          spec_tokens_per_sec=round(tps_s, 1),
          spec_speedup=round(tps_s / tps_c, 3),
          spec_acceptance_rate=round(
              snap_s["spec_accepted"] / judged, 3) if judged else 0.0,
          ttft_p50_ms=snap_p["ttft"]["p50_ms"],
          ttft_p95_ms=snap_p["ttft"]["p95_ms"],
          slot_ceiling_paged=peak_slots,
          slot_ceiling_contiguous=arena_rows // bucket_c,
          peak_cache_bytes_paged=pdec.ledger.peak_used * page *
          row_bytes,
          peak_cache_bytes_contiguous=slots * bucket_c * row_bytes,
          paged_matches_contiguous=outs_p == outs_c,
          requests=len(reqs), slots=slots, page=page,
          arena_pages=arena_pages,
          steady_state_compile_delta=delta_c + delta_p + delta_s,
          cpu=True)
    # the speculation exactness pin and the zero-recompile contract
    # fail the scenario loudly AFTER the line lands
    assert outs_s == outs_p, \
        "speculative greedy decode diverged from plain paged decode"
    assert delta_c == delta_p == delta_s == 0, \
        (f"steady-state recompiled: contiguous {delta_c}, paged "
         f"{delta_p}, speculative {delta_s}")


def bench_fleet(n_requests=24, max_new=8, flood_clients=8):
    """Serving-fleet scenario (ISSUE 13), over REAL worker processes:

    - **router overhead**: the same greedy generation is timed straight
      against one worker and then through the fleet router — the line's
      headline is the routed p95 (stable, trendable) and the
      ``overhead_*`` fields carry the direct-vs-routed deltas the
      ISSUE asks for;
    - **autoscaler reaction**: a thread flood saturates the single
      worker's admission queue until the fleet saturation rule
      breaches, and the second line reports breach-to-new-worker-READY
      wall time (boot + warmup + readiness gate — the real scale-up
      latency an SLO burn-down sees).

    The zero-lost ledger and the scale-up itself are asserted AFTER the
    lines land."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    from znicz_tpu.fleet import Autoscaler, FleetRouter, WorkerPool
    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.utils.export import export_lm

    tmp = tempfile.mkdtemp(prefix="znicz_bench_fleet_")
    pool = router = None
    try:
        charmap = list("abcdefghijklmnopqrstuvwxyz .,!?")
        params = init_params(np.random.default_rng(11), 2, 32, 4, 64,
                             len(charmap))
        pkg = os.path.join(tmp, "lm.npz")
        export_lm(params, pkg, heads=4, charmap=charmap,
                  name="bench_lm")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   ZNICZ_TPU_COMPILE_CACHE="off")
        pool = WorkerPool(
            pkg, plane="generate", env=env,
            worker_args=("--slots", "2", "--max-len", "64"),
            run_dir=os.path.join(tmp, "fleet"))
        w0 = pool.spawn()
        if not pool.wait_all_ready(timeout_s=240):
            raise RuntimeError(f"fleet worker never ready: "
                               f"{pool.snapshot()}")
        pool.start_probes()

        def timed(base: str, n: int) -> np.ndarray:
            lats = []
            for i in range(n + 3):
                body = _json.dumps({"prompt": "ab",
                                    "max_tokens": max_new,
                                    "timeout_s": 60}).encode()
                req = urllib.request.Request(
                    base + "/generate", data=body,
                    headers={"Content-Type": "application/json"})
                t0 = time.perf_counter()
                with urllib.request.urlopen(req, timeout=120) as r:
                    lines = [_json.loads(raw) for raw in r]
                dt = time.perf_counter() - t0
                if not lines or not lines[-1].get("done") or \
                        "error" in lines[-1]:
                    raise RuntimeError(f"bench stream did not "
                                       f"complete: {lines}")
                if i >= 3:              # 3 primes per arm, same shape
                    lats.append(dt)
            return np.asarray(lats) * 1000.0

        direct = timed(w0.base, n_requests)
        router = FleetRouter(pool)
        port = router.start()
        base = f"http://127.0.0.1:{port}"
        routed = timed(base, n_requests)
        _emit("fleet_router_p95_ms", float(np.percentile(routed, 95)),
              unit="ms", lower_is_better=True,
              direct_p95_ms=round(float(np.percentile(direct, 95)), 2),
              overhead_p95_ms=round(float(np.percentile(routed, 95) -
                                          np.percentile(direct, 95)),
                                    2),
              overhead_p50_ms=round(float(np.percentile(routed, 50) -
                                          np.percentile(direct, 50)),
                                    2),
              requests=n_requests, cpu=True)

        # -- autoscaler reaction: flood one worker, time breach->ready
        scaler = Autoscaler(pool, min_workers=1, max_workers=2,
                            queue_high=3.0, breach_for_s=0.25,
                            cooldown_s=5.0, idle_down_s=3600.0)
        stop_flood = threading.Event()
        flood_errors: list = []

        def flood() -> None:
            import urllib.error

            body = _json.dumps({"prompt": "ab", "max_tokens": 48,
                                "timeout_s": 120}).encode()
            while not stop_flood.is_set():
                req = urllib.request.Request(
                    base + "/generate", data=body,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=180) as r:
                        for _ in r:
                            pass
                except urllib.error.HTTPError as exc:
                    exc.read()
                    if exc.code != 503:     # backpressure is the
                        flood_errors.append(  # EXPECTED overload answer
                            f"HTTP {exc.code}")
                    time.sleep(0.1)
                except Exception as exc:  # noqa: BLE001 — surfaced
                    flood_errors.append(repr(exc))   # after the line
                    time.sleep(0.1)

        threads = [threading.Thread(target=flood, daemon=True)
                   for _ in range(flood_clients)]
        for t in threads:
            t.start()
        t0 = time.monotonic()
        while scaler.last_reaction_s is None and \
                time.monotonic() - t0 < 240:
            scaler.tick()
            time.sleep(0.25)
        reaction = scaler.last_reaction_s
        stop_flood.set()
        for t in threads:
            t.join(timeout=240)
        scaler.stop()
        snap = router.snapshot()
        _emit("fleet_autoscale_reaction_sec",
              float(reaction if reaction else 0.0), unit="seconds",
              lower_is_better=True,
              trend_valid=reaction is not None,
              workers=pool.worker_count(), scale_ups=scaler.scale_ups,
              router_ledger={k: snap[k] for k in
                             ("admitted", "completed", "failed",
                              "rejected", "client_gone")},
              cpu=True)
        # asserted AFTER the lines land (the scenario contract)
        assert reaction is not None and reaction > 0.0, \
            "autoscaler never reacted to the queue-saturation breach"
        assert pool.worker_count() == 2 and pool.ready_count() == 2, \
            f"scale-up did not land: {pool.snapshot()}"
        assert snap["admitted"] == snap["completed"] + \
            snap["failed"] + snap["client_gone"], \
            f"router ledger does not close: {snap}"
        assert not flood_errors, \
            f"flood clients failed hard: {flood_errors[:3]}"
    finally:
        if router is not None:
            router.stop()
        if pool is not None:
            pool.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_train_while_serve(n_requests=16, max_new=8):
    """Train-while-serve scenario (ISSUE 14), over REAL processes: the
    same greedy generation is timed through the fleet router with the
    trainer IDLE, with the trainer CO-RESIDENT (supervised, consuming
    the live feedback spool on the same box), and MID-ROLLOUT (while
    the publish-triggered zero-downtime update replaces workers) —
    the three serving-latency regimes the continuous-learning loop
    creates.  A second line reports publish-to-adopted latency (the
    manifest wall stamp to fleet convergence).  Ledger equality and
    steady-state compile delta 0 are asserted AFTER the lines land."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    from znicz_tpu.fleet import FleetRouter, WorkerPool
    from znicz_tpu.fleet.rollout import RollingUpdate
    from znicz_tpu.learn.publish import latest_manifest
    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.resilience.elastic import run_elastic
    from znicz_tpu.resilience.supervisor import SupervisorPolicy
    from znicz_tpu.utils.export import export_lm

    tmp = tempfile.mkdtemp(prefix="znicz_bench_learn_")
    pool = router = None
    trainer_box: dict = {}
    try:
        charmap = list("abcdefgh .,!?")
        params = init_params(np.random.default_rng(11), 2, 32, 4, 64,
                             len(charmap))
        pkg = os.path.join(tmp, "lm.npz")
        export_lm(params, pkg, heads=4, charmap=charmap,
                  name="bench_lm")
        spool = os.path.join(tmp, "spool")
        pub = os.path.join(tmp, "publish")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   ZNICZ_TPU_COMPILE_CACHE="off")
        pool = WorkerPool(
            pkg, plane="generate", env=env,
            worker_args=("--slots", "2", "--max-len", "64",
                         "--feedback-spool", spool),
            run_dir=os.path.join(tmp, "fleet"))
        pool.spawn()
        pool.spawn()
        if not pool.wait_all_ready(timeout_s=240):
            raise RuntimeError(f"fleet workers never ready: "
                               f"{pool.snapshot()}")
        pool.start_probes()
        router = FleetRouter(pool)
        rollout = RollingUpdate(pool)
        router.attach_rollout(rollout)
        base = f"http://127.0.0.1:{router.start()}"

        def one_request() -> float:
            body = _json.dumps({"prompt": "ab", "max_tokens": max_new,
                                "timeout_s": 60}).encode()
            req = urllib.request.Request(
                base + "/generate", data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=120) as r:
                lines = [_json.loads(raw) for raw in r]
            if not lines or not lines[-1].get("done") or \
                    "error" in lines[-1]:
                raise RuntimeError(f"bench stream did not complete: "
                                   f"{lines}")
            return time.perf_counter() - t0

        def timed(n: int) -> np.ndarray:
            return np.asarray([one_request()
                               for _ in range(n + 3)][3:]) * 1000.0

        # -- arm 1: trainer idle (also seeds the feedback spool) -----
        idle = timed(n_requests)

        # -- arm 2: trainer co-resident ------------------------------
        trainer_argv = [
            "znicz_tpu/learn/trainer_workflow.py",
            "-o", f"root.learn.spool_dir={spool}",
            "-o", f"root.learn.package={pkg}",
            "-o", f"root.learn.publish_dir={pub}",
            "-o", "root.learn.publish_every=4",
            "-o", "root.learn.max_epochs=4",
            "-o", "root.learn.records_per_epoch=6",
            "-o", "root.learn.seq_len=8",
            "-o", "root.learn.minibatch_size=4",
            "-o", "root.learn.wait_timeout_s=300",
            "--random-seed", "11"]

        def train() -> None:
            try:
                trainer_box["report"] = run_elastic(
                    trainer_argv, os.path.join(tmp, "snaps"),
                    workers=1, spmd=False, env=env,
                    run_dir=os.path.join(tmp, "trainer"),
                    policy=SupervisorPolicy(max_restarts=1))
            except Exception as exc:  # noqa: BLE001 — surfaced below
                trainer_box["error"] = exc

        trainer = threading.Thread(target=train, daemon=True)
        trainer.start()
        time.sleep(2.0)               # past the trainer's jax boot
        co = timed(n_requests)

        # -- arm 3: mid-rollout (publish-triggered) ------------------
        deadline = time.monotonic() + 300
        manifest = None
        while time.monotonic() < deadline:
            if "error" in trainer_box:
                raise RuntimeError(f"trainer failed: "
                                   f"{trainer_box['error']!r}")
            manifest = latest_manifest(pub)
            if manifest is not None:
                break
            one_request()             # keep the spool fed meanwhile —
            time.sleep(0.2)           # THROTTLED: an unthrottled loop
            #                           starves the co-resident trainer
            #                           of the box (the learn smoke
            #                           lesson) and the publish never
            #                           comes
        if manifest is None:
            raise RuntimeError("trainer never published")
        rollout.start(manifest["package"])
        roll_lats = []
        while rollout.rolling and len(roll_lats) < 400:
            roll_lats.append(one_request())
        report = rollout.join()
        adopted_s = max(0.0, time.time() - float(manifest["ts"]))
        roll = np.asarray(roll_lats) * 1000.0 if roll_lats else \
            np.asarray([0.0])
        _emit("train_while_serve_p95_ms",
              float(np.percentile(co, 95)), unit="ms",
              lower_is_better=True,
              idle_p95_ms=round(float(np.percentile(idle, 95)), 2),
              rollout_p95_ms=round(float(np.percentile(roll, 95)), 2),
              co_resident_overhead_p50_ms=round(
                  float(np.percentile(co, 50) -
                        np.percentile(idle, 50)), 2),
              rollout_requests=len(roll_lats),
              requests=n_requests, cpu=True)
        _emit("learn_publish_to_adopted_sec", adopted_s,
              unit="seconds", lower_is_better=True,
              trend_valid=report.get("state") == "done",
              epoch=manifest.get("epoch"), cpu=True)
        # asserted AFTER the lines land (the scenario contract)
        assert report.get("state") == "done", \
            f"publish-triggered rollout failed: {report}"
        trainer.join(timeout=240)
        assert trainer_box.get("report") is not None and \
            trainer_box["report"].completed, \
            f"trainer did not complete: {trainer_box}"
        snap = router.snapshot()
        assert snap["admitted"] == snap["completed"] + \
            snap["failed"] + snap["client_gone"], \
            f"router ledger does not close: {snap}"
        pool.probe_once()
        shas = {(w.fingerprint or {}).get("sha256")
                for w in pool.workers()}
        assert shas == {manifest["fingerprint"]["sha256"]}, \
            f"fleet not converged on the published package: " \
            f"{pool.snapshot()}"
        # steady state: fresh traffic compiles nothing
        def compile_counts():
            out = []
            for w in pool.workers():
                with urllib.request.urlopen(w.base + "/metrics",
                                            timeout=15) as r:
                    out.append(_json.loads(r.read())["decoder"]
                               ["compile_count"])
            return out

        before = compile_counts()
        for _ in range(3):
            one_request()
        assert before == compile_counts(), \
            "steady-state decode recompiled after the adoption"
    finally:
        if router is not None:
            router.stop()
        if pool is not None:
            pool.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_input_pipeline(epochs=3, minibatch=256, n_train=10240,
                         n_valid=2560, hidden=512, reps=2):
    """Input-pipeline scenario (ISSUE 4): sync vs prefetch=2 through the
    REAL Workflow.run loop on the mnist_fc shape (CPU by design — it
    measures the prefetch/staging machinery, not the chip).  Dataset
    pinning is disabled so every step ships its minibatch — the path the
    pipeline overlaps; the line reports samples/sec for both modes and
    the per-stage stall breakdown.  The bit-exactness contract is
    ASSERTED after the line flushes: a determinism break still lands the
    result but fails the scenario loudly (nonzero child exit)."""
    import time as _time

    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.standard_workflow import StandardWorkflow

    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": hidden},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]
    loader_cfg = {"n_classes": 10, "sample_shape": (28, 28),
                  "n_train": n_train, "n_valid": n_valid,
                  "minibatch_size": minibatch, "spread": 2.5, "noise": 1.0}

    def run_once(depth):
        prng.seed_all(7)
        w = StandardWorkflow(
            name=f"pipe{depth or 0}", layers=layers,
            loss_function="softmax", loader_name="synthetic_classifier",
            loader_config=loader_cfg,
            decision_config={"max_epochs": epochs},
            pipeline_config={"depth": depth} if depth else None)
        w.initialize(device=TPUDevice())
        t0 = _time.perf_counter()
        w.run()
        dt = _time.perf_counter() - t0
        hist = w.decision.metrics_history
        stats = w.input_pipeline.stats.snapshot() if depth else None
        w.stop()
        return (n_train + n_valid) * epochs / dt, hist, stats

    prev_limit = root.common.engine.get("dataset_on_device_max_bytes",
                                        1 << 30)
    root.common.engine.dataset_on_device_max_bytes = 0
    try:
        # sync first: its compiles also warm the persistent cache, so any
        # residual compile bias favors neither mode by the best-of-reps
        sync_sps, sync_hist = 0.0, None
        for _ in range(reps):
            sps, sync_hist, _ = run_once(None)
            sync_sps = max(sync_sps, sps)
        pre_sps, pre_hist, pre_stats = 0.0, None, None
        for _ in range(reps):
            sps, pre_hist, stats = run_once(2)
            if sps > pre_sps:
                pre_sps, pre_stats = sps, stats
    finally:
        root.common.engine.dataset_on_device_max_bytes = prev_limit
    _emit("input_pipeline_mnist_fc_prefetch2_samples_per_sec", pre_sps,
          cpu=True, sync_samples_per_sec=round(sync_sps, 1),
          speedup=round(pre_sps / sync_sps, 3),
          bit_exact=pre_hist == sync_hist,
          prefetch_depth=2, epochs=epochs,
          stalls={k: pre_stats[k] for k in
                  ("serve_s", "stage_s", "producer_starved_s",
                   "consumer_starved_s", "barrier_s")},
          bytes_staged=pre_stats["bytes_staged"],
          bound=pre_stats["bound"])
    # AFTER the emit so the throughput line always lands: a determinism
    # break must fail the scenario loudly, not ride a JSON field nobody
    # greps
    assert pre_hist == sync_hist, \
        "prefetched metric history diverged from the synchronous run"


def bench_zero_sharding(epochs=3, minibatch=32, n_train=640, n_valid=0,
                        hidden=128):
    """ZeRO shard_params scenario (ISSUE 15), CPU by design on a forced
    8-virtual-device platform (it measures the sharding machinery +
    accounting, not the chip; the child sets the platform before jax
    boots): the SAME seeded adam workflow runs replicated vs
    shard_params across dp mesh sizes, recording per-chip persistent
    state bytes (the znicz_zero_* gauges) and wall-clock throughput.
    The line lands first; the memory contract (per-chip bytes <= 1/n +
    padding) and the seeded-history parity are ASSERTED after it
    flushes, so a violation still records the measurement but fails the
    scenario loudly (nonzero child exit)."""
    import time as _time

    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.observe import registry
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    def gauge(name):
        return registry.REGISTRY.get(name).labels(unit="FusedStep").get()

    def run_once(n_dev, shard_params):
        prng.seed_all(31)
        w = build_fused(max_epochs=epochs, layers=(hidden,),
                        minibatch_size=minibatch, n_train=n_train,
                        n_valid=n_valid, mesh=data_parallel_mesh(n_dev),
                        optimizer="adam", shard_params=shard_params)
        w.initialize(device=TPUDevice())
        t0 = _time.perf_counter()
        w.run()
        dt = _time.perf_counter() - t0
        hist = [dict(h) for h in w.decision.metrics_history]
        bytes_per_chip = int(gauge("znicz_zero_param_bytes") +
                             gauge("znicz_zero_opt_state_bytes"))
        n_sharded = sum(1 for leaf in w.step._params
                        for k in leaf if w.step._leaf_sharded(k))
        w.stop()
        sps = (n_train + n_valid) * epochs / dt
        return sps, bytes_per_chip, hist, n_sharded

    matrix, violations = {}, []
    headline_sps = 0.0
    for n_dev in (2, 4, 8):
        rep_sps, rep_bytes, rep_hist, _ = run_once(n_dev, False)
        sp_sps, sp_bytes, sp_hist, n_sharded = run_once(n_dev, True)
        matrix[f"dp{n_dev}"] = {
            "replicated": {"samples_per_sec": round(rep_sps, 1),
                           "state_bytes_per_chip": rep_bytes},
            "shard_params": {"samples_per_sec": round(sp_sps, 1),
                             "state_bytes_per_chip": sp_bytes},
            "mem_ratio": round(sp_bytes / rep_bytes, 4),
            "hist_equal": sp_hist == rep_hist,
        }
        eps = 4 * (n_dev - 1) * n_sharded
        if sp_bytes > rep_bytes / n_dev + eps:
            violations.append(f"dp{n_dev}: {sp_bytes}B > "
                              f"{rep_bytes}/{n_dev}+{eps}B")
        if sp_hist != rep_hist:
            violations.append(f"dp{n_dev}: seeded history diverged")
        if n_dev == 8:
            headline_sps = sp_sps
    _emit("zero_shard_params_dp8_samples_per_sec", headline_sps,
          cpu=True, mesh_sizes=matrix,
          mem_ratio_dp8=matrix["dp8"]["mem_ratio"])
    # AFTER the emit so the measurement always lands: a broken memory
    # contract or history divergence must fail the scenario loudly
    assert not violations, "; ".join(violations)


def bench_quantized_collectives(epochs=3, minibatch=32, n_train=640,
                                n_valid=0, hidden=128):
    """Quantized-collectives scenario (ISSUE 18), CPU by design on the
    same forced 8-virtual-device platform as bench_zero_sharding (it
    measures the codec + accounting machinery, not the chip): the SAME
    seeded adam shard_params workflow runs exact vs int8+error-feedback
    across dp mesh sizes, recording bytes-on-wire vs step time and the
    seeded loss trajectory.  ``znicz_zero_gathered_bytes_total`` is
    recorded before (exact) and after (quantized) so the artifact holds
    the regather traffic the codec compressed.  The line lands first;
    the wire contract (int8 <= 0.27x exact on BOTH collectives) and the
    trajectory band are ASSERTED after it flushes."""
    import time as _time

    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.observe import registry
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    def counter(name, **labels):
        return registry.REGISTRY.get(name).labels(
            unit="FusedStep", **labels).get()

    def run_once(n_dev, qc):
        # the counters are process-cumulative: snapshot before the run
        # so each cell reports ITS traffic, not the session total
        gathered0 = counter("znicz_zero_gathered_bytes_total")
        qcomm0 = {coll: (counter("znicz_qcomm_bytes_on_wire_total",
                                 collective=coll),
                         counter("znicz_qcomm_bytes_exact_total",
                                 collective=coll))
                  for coll in ("grad_psum", "zero_gather")}
        prng.seed_all(31)
        w = build_fused(max_epochs=epochs, layers=(hidden,),
                        minibatch_size=minibatch, n_train=n_train,
                        n_valid=n_valid, mesh=data_parallel_mesh(n_dev),
                        optimizer="adam", shard_params=True,
                        quantized_collectives=qc)
        w.initialize(device=TPUDevice())
        t0 = _time.perf_counter()
        w.run()
        dt = _time.perf_counter() - t0
        hist = [h["metric_train"] for h in w.decision.metrics_history]
        gathered = int(counter("znicz_zero_gathered_bytes_total") -
                       gathered0)
        qcomm = {coll: (int(counter("znicz_qcomm_bytes_on_wire_total",
                                    collective=coll) - qcomm0[coll][0]),
                        int(counter("znicz_qcomm_bytes_exact_total",
                                    collective=coll) - qcomm0[coll][1]))
                 for coll in ("grad_psum", "zero_gather")}
        w.stop()
        sps = (n_train + n_valid) * epochs / dt
        return sps, hist, gathered, qcomm

    matrix, violations = {}, []
    headline_sps = 0.0
    for n_dev in (2, 4, 8):
        ex_sps, ex_hist, ex_gathered, _ = run_once(n_dev, None)
        q_sps, q_hist, q_gathered, qcomm = run_once(
            n_dev, {"mode": "int8", "error_feedback": True})
        matrix[f"dp{n_dev}"] = {
            "exact": {"samples_per_sec": round(ex_sps, 1),
                      "zero_gathered_bytes": ex_gathered,
                      "train_err_history": ex_hist},
            "int8_ef": {"samples_per_sec": round(q_sps, 1),
                        "zero_gathered_bytes": q_gathered,
                        "train_err_history": q_hist},
            "wire_ratio": {coll: round(wire / max(exact, 1), 4)
                           for coll, (wire, exact) in qcomm.items()},
        }
        for coll, (wire, exact) in qcomm.items():
            if not 0 < wire <= 0.27 * exact:
                violations.append(f"dp{n_dev}/{coll}: wire {wire}B > "
                                  f"0.27x exact {exact}B")
        # the seeded trajectory band: int8+EF may differ from exact by
        # quantization noise, never by a broken reduction — pin each
        # epoch's train-error count within 5% of the train set
        band = 0.05 * n_train
        for e, (a, b) in enumerate(zip(ex_hist, q_hist)):
            if abs(a - b) > band:
                violations.append(f"dp{n_dev}: epoch {e} train err "
                                  f"{b} vs exact {a} (band {band:.0f})")
        if n_dev == 8:
            headline_sps = q_sps
    _emit("qcomm_int8_dp8_samples_per_sec", headline_sps,
          cpu=True, mesh_sizes=matrix,
          wire_ratio_dp8=matrix["dp8"]["wire_ratio"])
    # AFTER the emit so the measurement always lands: a broken wire
    # contract or trajectory divergence must fail the scenario loudly
    assert not violations, "; ".join(violations)


def bench_metrics_overhead(epochs=3, minibatch=128, n_train=2560,
                           n_valid=640, hidden=256, pairs=20):
    """ISSUE 5 scenario: the telemetry plane's cost on the REAL
    Workflow.run loop (CPU by design — it measures the instrumentation
    machinery, not the chip).  Runs the same seeded mnist_fc-shaped
    workflow with probes+tracer enabled vs ``observe.set_enabled(False)``
    (the bare pre-ISSUE-5 walk).  ISSUE 6 raised the instrumented arm's
    load: it now also carries an attached watchtower (step-boundary
    registry sampling + the full five-rule SLO catalogue) so the <2%
    bound covers sampler + rule engine, not just probes + tracer.
    ISSUE 11 raised it again: the instrumented arm additionally runs a
    fleet MetricsExporter (the worker-side half of metric federation —
    periodic registry render + atomic file rewrite, exactly what an
    elastic rank pays under a supervising aggregator), so the bound
    covers the federation plane's per-worker cost too.

    Protocol, forced by this box's load profile: scheduler theft on the
    shared sandbox swings individual runs ±10-40% (sampled runs sit at
    ~24k sps with sporadic dips to ~14k), and theft only ever SLOWS a
    run down — so per-run throughput is a one-sided underestimate of
    the machine's capability.  The scenario interleaves many short
    bare/inst runs and alternates which arm goes first to cancel order
    bias.  The r05-era protocol compared the arms at their best-of-N
    (max) throughput; by ISSUE 6 the theft profile had worsened to the
    point where individual runs swing 2x+ and the two arms' maxima land
    on DIFFERENT theft luck (the best-of-N overhead measured -9.6%,
    +3.3%, +8.6% and +11.5% across identical reruns while the median
    flipped sign) — max no longer converges.  The asserted estimator is
    now the QUIETEST-QUARTILE pair median: a pair whose two adjacent
    runs were BOTH fast had theft touch neither arm, so its
    instrumented/bare ratio is the trustworthy one — rank pairs by
    combined runtime, keep the quietest quarter (>= 3 pairs), take the
    median ratio.  Best-of-N and the all-pair median ride along as
    diagnostics.  The line lands first; the <2% overhead contract and
    the bit-exact metric-history contract are ASSERTED after it
    flushes, so a violation still records the measurement but fails the
    scenario loudly (nonzero child exit)."""
    import statistics
    import tempfile
    import time as _time

    from znicz_tpu import observe
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.observe import federation as _fed
    from znicz_tpu.observe import watchtower as _wt
    from znicz_tpu.standard_workflow import StandardWorkflow

    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": hidden},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    ]
    loader_cfg = {"n_classes": 10, "sample_shape": (28, 28),
                  "n_train": n_train, "n_valid": n_valid,
                  "minibatch_size": minibatch, "spread": 2.5,
                  "noise": 1.0}

    mx_path = os.path.join(tempfile.gettempdir(),
                           f"znicz_bench_fleet_{os.getpid()}.json")

    def run_once(enabled):
        observe.set_enabled(enabled)
        prng.seed_all(7)
        w = StandardWorkflow(
            name="overhead", layers=layers, loss_function="softmax",
            loader_name="synthetic_classifier", loader_config=loader_cfg,
            decision_config={"max_epochs": epochs})
        w.initialize(device=TPUDevice())
        exporter = None
        if enabled:
            # ISSUE 6: the instrumented arm pays for the whole plane —
            # step-boundary sampling + the full rule catalogue evaluated
            # on every sample.  Occasional trips (recompile_storm sees
            # the 40 re-initializing runs sharing one registry as a
            # storm) are part of the measured load; trips never touch
            # the metric history, so bit_exact still must hold.
            tower = _wt.Watchtower()
            for make_rule in (_wt.step_latency_regression,
                              _wt.serve_queue_saturation,
                              _wt.nan_guard_trip_rate,
                              _wt.recompile_storm,
                              _wt.pipeline_consumer_starvation):
                tower.add_rule(make_rule())
            tower.attach(w)
            # ISSUE 11: plus the worker-side federation exporter at the
            # elastic supervisor's default cadence
            exporter = _fed.start_metrics_export(mx_path, interval_s=1.0)
        t0 = _time.perf_counter()
        w.run()
        dt = _time.perf_counter() - t0
        hist = w.decision.metrics_history
        w.stop()
        if exporter is not None:
            exporter.stop()
        return (n_train + n_valid) * epochs / dt, hist

    try:
        run_once(True)                   # warm the compile cache once
        run_once(False)
        ratios, bare, inst = [], [], []
        inst_hist = bare_hist = None
        for i in range(pairs):
            if i % 2:                    # alternate order: [b,s] / [s,b]
                s, inst_hist = run_once(True)
                b, bare_hist = run_once(False)
            else:
                b, bare_hist = run_once(False)
                s, inst_hist = run_once(True)
            bare.append(b)
            inst.append(s)
            ratios.append(s / b)
    finally:
        observe.set_enabled(True)
        with contextlib.suppress(OSError):
            os.remove(mx_path)
    bare_sps = max(bare)
    inst_sps = max(inst)
    best_of_n_pct = (1.0 - inst_sps / bare_sps) * 100.0
    # quietest-quartile estimator (see docstring): rank pairs by the
    # pair's combined wall time (1/sps + 1/sps), keep the least-stolen
    # quarter, judge the median instrumented/bare ratio there
    by_quiet = sorted(zip((1.0 / b + 1.0 / s
                           for b, s in zip(bare, inst)), ratios))
    quiet = [r for _, r in by_quiet[:max(3, pairs // 4)]]
    overhead_pct = (1.0 - statistics.median(quiet)) * 100.0
    _emit("metrics_overhead_instrumented_samples_per_sec", inst_sps,
          cpu=True, bare_samples_per_sec=round(bare_sps, 1),
          overhead_pct=round(overhead_pct, 3),
          quiet_pairs=len(quiet),
          best_of_n_overhead_pct=round(best_of_n_pct, 3),
          median_overhead_pct=round(
              (1.0 - statistics.median(ratios)) * 100.0, 3),
          bit_exact=inst_hist == bare_hist, epochs=epochs, pairs=pairs,
          ratio_spread=[round(min(ratios), 3), round(max(ratios), 3)])
    # AFTER the emit so the measurement always lands: a broken contract
    # must fail the scenario loudly, not ride a JSON field nobody greps
    assert inst_hist == bare_hist, \
        "instrumented metric history diverged from the bare run"
    assert overhead_pct < 2.0, \
        f"instrumentation overhead {overhead_pct:.2f}% >= 2%"


def bench_compile_probe():
    """One cold-or-warm boot measurement (the ``compile_probe`` child of
    the ``compile_latency`` scenario): whether it is cold or warm is
    decided by the cache directory the parent points
    ``$ZNICZ_TPU_COMPILE_CACHE`` at.  Measures the two boot paths the
    tentpole targets — the flagship training step's first dispatch
    (trace + compile + run) and the serve engine's full bucket sweep —
    and prints ONE JSON line with wall seconds + compile-cost counters."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.observe import compile_cache_stats
    from znicz_tpu.serve import BatchEngine

    # flagship-shaped training step (scaled to probe size: the number
    # that matters is the RATIO between two identical probes)
    prng.seed_all(7)
    w = build_fused(max_epochs=1, layers=(512, 512), minibatch_size=128,
                    n_train=256, n_valid=0)
    w.initialize(device=TPUDevice())
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.normal(size=(1, 128, 28, 28)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, (1, 128)).astype(np.int32))
    ms = jnp.ones((1, 128), bool)
    t0 = time.perf_counter()
    metrics = w.step.train_steps(xs, ys, ms)
    float(jax.device_get(metrics["loss"]))
    step_s = time.perf_counter() - t0

    # serve bucket sweep: an MLP big enough that XLA compile time
    # dominates the warm path's load-from-cache + run
    w1 = jnp.asarray(rng.normal(0, 0.1, (256, 512)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(0, 0.1, (512, 512)).astype(np.float32))
    w3 = jnp.asarray(rng.normal(0, 0.1, (512, 16)).astype(np.float32))

    @jax.jit
    def mlp(x):
        return jnp.tanh(jnp.tanh(x @ w1) @ w2) @ w3

    engine = BatchEngine(mlp, max_batch=32, input_shape=(256,))
    t0 = time.perf_counter()
    engine.warmup()
    serve_s = time.perf_counter() - t0
    hits, misses = compile_cache_stats()
    print(json.dumps({"probe": "compile", "step_first_dispatch_s":
                      round(step_s, 3), "serve_warmup_s": round(serve_s, 3),
                      "serve_buckets": len(engine.buckets),
                      "cache_hits": hits, "cache_misses": misses}),
          flush=True)


def _run_compile_probe(cache_dir: str) -> dict:
    """Run one ``compile_probe`` child against ``cache_dir``; returns
    its JSON line.  A fresh process per probe is the point: the in-
    process jit/trace caches must not exist, so the only warmth is the
    persistent cache."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ZNICZ_TPU_COMPILE_CACHE=cache_dir)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "compile_probe"], capture_output=True, text=True,
        timeout=CPU_TIMEOUT, env=env, cwd=REPO)
    for line in reversed((proc.stdout or "").strip().splitlines()):
        if line.startswith("{"):
            doc = json.loads(line)
            if doc.get("probe") == "compile":
                return doc
    tail = (proc.stderr or "").strip().splitlines()[-3:]
    raise RuntimeError(f"compile_probe produced no result "
                       f"(rc={proc.returncode}): {' | '.join(tail)}")


def bench_compile_latency():
    """ISSUE 7 scenario: cold-process vs warm-cache boot (CPU by design
    — it measures the compile-latency plane's machinery, not the chip).
    Two identical probe children share one FRESH cache directory: the
    first pays every compile cold and populates the cache, the second
    pays trace + cache-load only.  A third leg exports a forward
    package with AOT executables and boots the serve engine from them,
    pinning ``compile_count == 0``.  The line lands first; the
    acceptance contracts (warm serve sweep <= 50% of cold, zero-compile
    AOT boot) are ASSERTED after it flushes."""
    import shutil
    import tempfile

    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.serve import BatchEngine
    from znicz_tpu.standard_workflow import StandardWorkflow
    from znicz_tpu.utils.export import ExportedForward, export_forward

    cache_dir = tempfile.mkdtemp(prefix="znicz_cc_bench_")
    pkg_dir = tempfile.mkdtemp(prefix="znicz_aot_bench_")
    try:
        cold = _run_compile_probe(cache_dir)
        warm = _run_compile_probe(cache_dir)

        # AOT leg: export -> precompile -> engine boot with zero compiles
        prng.seed_all(23)
        w = StandardWorkflow(
            name="AotBench", loss_function="softmax",
            layers=[{"type": "all2all_tanh",
                     "->": {"output_sample_shape": 64}},
                    {"type": "softmax", "->": {"output_sample_shape": 10}}],
            loader_name="synthetic_classifier",
            loader_config={"n_classes": 10, "sample_shape": (32,),
                           "n_train": 64, "n_valid": 0,
                           "minibatch_size": 32},
            decision_config={"max_epochs": 1})
        w.initialize(device=TPUDevice())
        w.run()
        pkg = os.path.join(pkg_dir, "aot_bench.npz")
        export_forward(w, pkg, aot_max_batch=16)
        t0 = time.perf_counter()
        engine = BatchEngine(ExportedForward(pkg), max_batch=16)
        engine.warmup()
        aot_boot_s = time.perf_counter() - t0
        ratio = warm["serve_warmup_s"] / max(cold["serve_warmup_s"], 1e-9)
        _emit("compile_latency_warm_serve_boot_seconds",
              warm["serve_warmup_s"], unit="s", lower_is_better=True,
              cpu=True, warm_over_cold=round(ratio, 3),
              cold_serve_warmup_s=cold["serve_warmup_s"],
              serve_buckets=cold["serve_buckets"],
              step_first_dispatch_s={"cold": cold["step_first_dispatch_s"],
                                     "warm": warm["step_first_dispatch_s"]},
              cache_misses={"cold": cold["cache_misses"],
                            "warm": warm["cache_misses"]},
              cache_hits={"cold": cold["cache_hits"],
                          "warm": warm["cache_hits"]},
              aot_boot_s=round(aot_boot_s, 3),
              aot_boot_compile_count=engine.compile_count,
              aot_buckets=engine.aot_count)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(pkg_dir, ignore_errors=True)
    # AFTER the emit so the measurement always lands: a broken contract
    # must fail the scenario loudly, not ride a JSON field nobody greps
    assert engine.compile_count == 0, \
        f"AOT boot compiled {engine.compile_count} buckets (want 0)"
    assert ratio <= 0.5, \
        (f"warm serve bucket sweep at {ratio:.2f}x of cold "
         f"(want <= 0.5): persistent cache is not paying for itself")


def child_main(mode: str) -> None:
    if mode == "pipeline":
        # input-pipeline scenario: CPU by design (measures the prefetch
        # + staging machinery through the real run loop)
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_input_pipeline()
        return
    if mode == "serve":
        # serving-plane scenario: CPU by design (the parent pins
        # JAX_PLATFORMS=cpu), measures batcher+engine machinery
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_serve()
        return
    if mode == "generate":
        # generative-serving scenario: CPU by design (measures the
        # KV-cache decode + continuous-batching machinery)
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_generate()
        bench_generate_longtail()
        return
    if mode == "fleet":
        # serving-fleet scenario (ISSUE 13): router overhead +
        # autoscaler reaction over real worker subprocesses; the bench
        # child itself only routes (CPU, no model math in-process)
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_fleet()
        return
    if mode == "train_while_serve":
        # continuous-learning scenario (ISSUE 14): serving p95 with
        # the trainer idle vs co-resident vs mid-rollout, plus
        # publish-to-adopted latency — real worker + trainer
        # subprocesses; the bench child itself only routes
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_train_while_serve()
        return
    if mode == "metrics_overhead":
        # telemetry-plane scenario: CPU by design (measures the
        # observe instrumentation through the real run loop)
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_metrics_overhead()
        return
    if mode == "zero_sharding":
        # ZeRO shard_params scenario: a FORCED 8-virtual-device CPU
        # platform (must land in the env before the first jax backend
        # init) so dp mesh sizes 2/4/8 exercise the real sharded layout
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_zero_sharding()
        return
    if mode == "quantized_collectives":
        # quantized-collectives scenario: the same forced 8-virtual-
        # device CPU platform as zero_sharding (dp 2/4/8 int8 codec
        # matrix; the flag must land before the first jax backend init)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_quantized_collectives()
        return
    if mode == "compile_latency":
        # compile-latency scenario: orchestrates two compile_probe
        # children over a fresh shared cache dir + an AOT boot leg
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        bench_compile_latency()
        return
    if mode == "compile_probe":
        # one boot measurement; the cache dir arrives via
        # $ZNICZ_TPU_COMPILE_CACHE (set by the compile_latency parent)
        import jax

        jax.config.update("jax_platforms", "cpu")
        from znicz_tpu import compilecache

        compilecache.configure(min_compile_time_s=0.0)
        bench_compile_probe()
        return
    if mode == "cpu_fallback":
        # the axon sitecustomize pins jax_platforms via jax.config at
        # interpreter start — the env var alone does not stick
        import jax

        jax.config.update("jax_platforms", "cpu")
        _enable_compile_cache()
        # small geometry: a CPU figure must land inside CPU_TIMEOUT
        bench_fc(batch=256, layers=(1024, 1024), K=8, reps=2)
        return
    _enable_compile_cache()
    bench_fc()
    flagship = bench_alexnet()
    # remaining phases, round-4 evidence first (compiled Pallas parity,
    # flash transformer): every line above already landed, so a timeout
    # truncates the least-critical tail
    for phase in (bench_pallas_parity, bench_transformer, bench_cifar,
                  bench_deconv_ae, bench_kohonen,
                  bench_mnist_wallclock):
        try:
            phase()
        except Exception as exc:  # noqa: BLE001 — keep earlier results
            print(f"# {phase.__name__} failed: {exc!r}", file=sys.stderr)
    # the driver reads the LAST line as the headline: re-emit the flagship
    print(json.dumps(flagship), flush=True)


# ---------------------------------------------------------------------------
# parent orchestration
# ---------------------------------------------------------------------------

def _run_child(mode: str, timeout: int, platform=None):
    """Run a bench child; return (json lines parsed, note)."""
    env = dict(os.environ)
    if platform:
        env["JAX_PLATFORMS"] = platform
    stdout, note = "", None
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", mode],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=REPO)
        stdout = proc.stdout or ""
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            note = f"{mode}: rc={proc.returncode} {' | '.join(tail)}"[:300]
    except subprocess.TimeoutExpired as exc:
        stdout = exc.stdout or ""
        if isinstance(stdout, bytes):
            stdout = stdout.decode(errors="replace")
        note = f"{mode}: timeout after {timeout}s"
    results = []
    for line in stdout.strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                results.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return results, note


def _sentinel_report(results, label: str) -> None:
    """ISSUE 20: advisory perf-regression check for one scenario's
    fresh lines against the last recorded round (report-only — the
    hard gate is ``tools/bench_sentinel.py`` between recorded
    ``BENCH_r*.json`` artifacts; here a cliff just gets called out on
    stderr the moment the scenario lands instead of one round later)."""
    rows = {str(r["metric"]): r for r in results or []
            if isinstance(r, dict) and "metric" in r and "value" in r}
    if not rows:
        return
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_sentinel",
            os.path.join(REPO, "tools", "bench_sentinel.py"))
        sentinel = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sentinel)
        prev = {m: r for m, r in _prev_round_values().items()
                if m in rows}
        for f in sentinel.compare(prev, rows):
            if f["kind"] in ("regression", "improvement"):
                print(f"# sentinel [{label}]: {f['kind'].upper()} "
                      f"{f['metric']} {f.get('prev')} -> {f.get('new')} "
                      f"({f['detail']})", file=sys.stderr)
    except Exception as exc:  # noqa: BLE001 — advisory only
        print(f"# sentinel unavailable: {exc!r}", file=sys.stderr)


def main():
    notes = []
    # ISSUE 5 satellite: the r05 artifact tail showed the same metric
    # line duplicated VERBATIM (the child re-emits its flagship for the
    # standalone --child contract, and the parent's final re-emit could
    # repeat an already-last line).  The parent now prints each distinct
    # record once; a deliberate final re-emit that would repeat an
    # earlier line is labeled {"reemit": true} instead of silently
    # doubling the record.
    printed: list[str] = []

    def emit(r) -> None:
        line = json.dumps(r)
        if line not in printed:
            print(line, flush=True)
            printed.append(line)

    results, note = _run_child("tpu", TPU_TIMEOUT)
    if note:
        notes.append(note)
    for r in results:
        emit(r)

    if not any(r["metric"].startswith("alexnet") for r in results):
        more, note = _run_child("tpu", TPU_RETRY_TIMEOUT)
        if note:
            notes.append(note)
        for r in more:
            emit(r)
        results += more
    _sentinel_report(results, "tpu")

    if not results:
        results, note = _run_child("cpu_fallback", CPU_TIMEOUT,
                                   platform="cpu")
        if note:
            notes.append(note)
        for r in results:
            r["metric"] += "_CPU_FALLBACK"
            r["fallback_reason"] = "; ".join(notes)[:300] or "tpu failed"
            # a CPU fallback compared against itself says nothing: drop
            # the self-referential trend and embed the last tracked
            # hardware numbers inline so the artifact stands alone
            # (VERDICT r5 item 7)
            r.pop("vs_baseline", None)
            last_hw = _last_hw_snapshot()
            if last_hw:
                r["last_hw"] = last_hw
            emit(r)
        _sentinel_report(results, "cpu_fallback")

    # serving-plane / input-pipeline / metrics-overhead scenarios: their
    # own CPU children (independent of the chip pool), BEFORE the final
    # flagship re-emit so the driver's last-line contract is untouched
    for extra_mode in ("serve", "generate", "fleet",
                       "train_while_serve", "pipeline",
                       "zero_sharding", "quantized_collectives",
                       "metrics_overhead", "compile_latency"):
        # compile_latency's own legs each budget up to CPU_TIMEOUT (two
        # fresh-process probes + the AOT export leg) — its OUTER timeout
        # must exceed their sum or a slow-but-in-budget cold probe gets
        # the whole scenario killed mid-warm-probe.  generate runs the
        # base scenario PLUS the three-arm long-tail comparison (each
        # arm primes then times), so it gets a doubled budget too.
        # fleet boots real worker subprocesses (one cold + one
        # autoscaled) on top of its request sweeps — doubled budget
        # like generate; train_while_serve boots 2 workers + a
        # supervised trainer and waits out a publish + rollout
        budget = 4 * CPU_TIMEOUT if extra_mode == "compile_latency" \
            else 2 * CPU_TIMEOUT if extra_mode in (
                "generate", "fleet", "train_while_serve") \
            else CPU_TIMEOUT
        extra_results, note = _run_child(extra_mode, budget,
                                         platform="cpu")
        if note:
            notes.append(note)
        for r in extra_results:
            emit(r)
        _sentinel_report(extra_results, extra_mode)

    if results:
        # headline by NAME, not position: if the child was killed mid-tail
        # the last flushed line may be a tail benchmark, but the driver
        # reads the final line as the flagship metric
        flagships = [r for r in results
                     if r["metric"].startswith("alexnet")]
        best = flagships[-1] if flagships else results[-1]
        if notes and "fallback_reason" not in best:
            best["notes"] = "; ".join(notes)[:300]
        if printed and printed[-1] == json.dumps(best):
            pass            # already the last line — emitting once is
        else:               # the whole point (ISSUE 5 satellite)
            if json.dumps(best) in printed:
                best["reemit"] = True   # labeled repeat, never verbatim
            print(json.dumps(best), flush=True)
    else:
        print(json.dumps({
            "metric": "alexnet_b128_train_samples_per_sec_per_chip",
            "value": 0.0, "unit": "samples/sec", "vs_baseline": 0.0,
            "error": "; ".join(notes)[:500]}), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        child_main(sys.argv[2])
    else:
        main()
