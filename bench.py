"""Benchmark harness — emits ONE JSON line for the driver.

Current flagship benchmark: fused training-step throughput (samples/sec)
on the largest model the framework has landed; upgrades to the ImageNet
AlexNet workflow (BASELINE.md config 3) as soon as the conv stack is in.
``vs_baseline`` is 1.0 by convention: the reference published no numbers
(BASELINE.json :: published == {}), so the driver-recorded history of this
metric across rounds IS the baseline trend.
"""

import json
import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def bench_fc_train(batch: int = 1024, steps: int = 50, warmup: int = 5):
    """Samples/sec of the fused FC training step on one chip."""
    import numpy as np
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused

    prng.seed_all(7)
    w = build_fused(max_epochs=1, layers=(4096, 4096), minibatch_size=batch,
                    n_train=2 * batch, n_valid=0)
    w.initialize(device=TPUDevice())
    step = w.step
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28)).astype(np.float32)
    labels = rng.integers(0, 10, batch).astype(np.int32)
    mask = np.ones(batch, bool)
    params = step._params
    hyper = step.hyper_params()
    for _ in range(warmup):
        params, metrics = step._train_fn(params, hyper, x, labels, mask)
    import jax
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, metrics = step._train_fn(params, hyper, x, labels, mask)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    return batch * steps / dt


def main():
    sps = bench_fc_train()
    print(json.dumps({
        "metric": "mnist_fc4096_train_samples_per_sec_per_chip",
        "value": round(sps, 1),
        "unit": "samples/sec",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
