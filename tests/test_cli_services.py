"""CLI / Launcher / genetics / ensemble tests (SURVEY.md §3.3: Main,
Launcher, genetics, ensemble rows)."""

import json
import os
import textwrap

import numpy as np
import pytest

from znicz_tpu.__main__ import main as cli_main
from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.core.config import Tune, root, set_by_path
from znicz_tpu.launcher import Launcher
from znicz_tpu.models import wine
from znicz_tpu.utils.ensemble import Ensemble
from znicz_tpu.utils.genetics import Genetics


@pytest.fixture(autouse=True)
def _hermetic_site_config(monkeypatch, tmp_path_factory):
    """Isolate every CLI test from the developer machine's site-config
    layer (env var or ~/.config file)."""
    monkeypatch.setenv("ZNICZ_TPU_SITE_CONFIG", "")
    yield


WINE_WORKFLOW = textwrap.dedent("""
    import json
    from znicz_tpu.core.config import root
    from znicz_tpu.models import wine

    def run(load, main):
        epochs = root.wine.get("max_epochs", 3)
        w, _ = load(wine.build, max_epochs=epochs, n_train=60, n_valid=30,
                    minibatch_size=10)
        main()
        out = root.wine.get("result_file", None)
        if out:
            with open(out, "w") as f:
                json.dump({"epochs": len(w.decision.metrics_history),
                           "best": w.decision.best_metric}, f)
    """)


def test_launcher_load_main_contract():
    prng.seed_all(3)
    launcher = Launcher(device=TPUDevice())
    wine.run(lambda b, **kw: launcher.load(b, max_epochs=3, n_train=60,
                                           n_valid=30, minibatch_size=10,
                                           **kw),
             launcher.main)
    assert bool(launcher.workflow.decision.complete)
    assert len(launcher.workflow.decision.metrics_history) == 3


def test_launcher_snapshot_resume(tmp_path):
    prng.seed_all(3)
    w = wine.build(max_epochs=4, n_train=60, n_valid=30, minibatch_size=10,
                   snapshotter_config={"directory": str(tmp_path),
                                       "prefix": "w", "only_improved": False,
                                       "keep_all": True})
    w.initialize(device=TPUDevice())
    w.run()
    snap = tmp_path / "w_2.npz"
    assert snap.exists()

    prng.seed_all(3)
    launcher = Launcher(device=TPUDevice(), snapshot=str(snap))
    launcher.load(wine.build, max_epochs=4, n_train=60, n_valid=30,
                  minibatch_size=10)
    launcher.main()
    assert launcher.workflow.decision.metrics_history == \
        w.decision.metrics_history


def test_cli_end_to_end(tmp_path):
    wf = tmp_path / "wine_wf.py"
    wf.write_text(WINE_WORKFLOW)
    cfg = tmp_path / "wine_config.py"
    cfg.write_text("root.wine.max_epochs = 2\n")
    result_file = tmp_path / "result.json"
    rc = cli_main([str(wf), str(cfg), "--random-seed", "5", "-d", "tpu",
                   "-o", f"root.wine.result_file={result_file}"])
    assert rc == 0
    result = json.loads(result_file.read_text())
    assert result["epochs"] == 2
    assert result["best"] is not None
    del root.wine


def test_cli_optimize(tmp_path, capsys):
    """An lr-only Tune over a fused StandardWorkflow must route through
    the VMAPPED population evaluator (SURVEY.md §3.4 hyperparameter
    parallelism), not the sequential full-run loop."""
    wf = tmp_path / "wine_opt.py"
    wf.write_text(textwrap.dedent("""
        from znicz_tpu.core.config import root
        from znicz_tpu.models import wine

        def run(load, main):
            load(wine.build, max_epochs=2, n_train=60, n_valid=30,
                 minibatch_size=10, lr=float(root.wine_opt.lr))
            main()
        """))
    set_by_path(root, "wine_opt.lr", Tune(0.3, 0.01, 1.0))
    rc = cli_main([str(wf), "--optimize", "2", "-d", "tpu"])
    assert rc == 0
    assert "'_evaluator': 'vmapped'" in capsys.readouterr().out
    del root.wine_opt


def test_cli_optimize_structural_tune_falls_back(tmp_path, capsys):
    """A Tune that changes workflow STRUCTURE (hidden layer size) cannot
    batch — the probe must detect it and fall back to sequential runs."""
    wf = tmp_path / "wine_hidden.py"
    wf.write_text(textwrap.dedent("""
        from znicz_tpu.core.config import root
        from znicz_tpu.models import wine

        def run(load, main):
            load(wine.build, max_epochs=1, n_train=30, n_valid=10,
                 minibatch_size=10,
                 hidden=int(root.wine_hidden.hidden))
            main()
        """))
    set_by_path(root, "wine_hidden.hidden", Tune(8, 4, 16))
    rc = cli_main([str(wf), "--optimize", "1", "-d", "tpu"])
    assert rc == 0
    assert "'_evaluator': 'sequential'" in capsys.readouterr().out
    del root.wine_hidden


def test_genetics_pure_function():
    tunes = {"x": Tune(0.0, -10.0, 10.0), "y": Tune(0.0, -5.0, 5.0)}
    prng.seed_all(4)
    ga = Genetics(lambda ind: (ind["x"] - 3.0) ** 2 + ind["y"] ** 2,
                  tunes=tunes, population_size=12, mutation_rate=0.5)
    best, fit = ga.run(generations=8)
    assert fit < 1.0, (best, fit)
    assert abs(best["x"] - 3.0) < 1.5


def test_ga_evaluations_share_one_seed_and_private_stream(monkeypatch):
    """Every GA evaluation must see IDENTICAL session-stream state (fitness
    comparability), and the reseed must not restart the GA's own draws
    (r1 advisor: utils/genetics.py reseed drift)."""
    from znicz_tpu.utils import genetics as gmod

    set_by_path(root, "ga_seed_test.lr", Tune(0.3, 0.01, 1.0))
    seen = []

    class FakeModule:
        @staticmethod
        def run(load, main):
            # what a workflow does first: draw from the session stream
            seen.append(float(prng.get().uniform(0.0, 1.0, (1,))[0]))
            w, _ = load(lambda **kw: _FakeWorkflow())
            main()

    class _FakeDecision:
        best_metric = 1.0

    class _FakeWorkflow:
        decision = _FakeDecision()

        def initialize(self, **kw):
            pass

        def run(self):
            pass

        def stop(self):
            pass

    class _FakeLauncher:
        device = None

    prng.seed_all(9)
    gmod.optimize(FakeModule, _FakeLauncher(), generations=2,
                  population_size=4)
    # 8 evaluations + 1 vmap-compatibility probe build (the fake
    # workflow is not a fused StandardWorkflow, so the probe rejects it
    # after the base build and evaluation runs sequentially)
    assert len(seen) == 9
    assert len(set(seen)) == 1, \
        f"evaluations saw drifting session seeds: {seen}"
    del root.ga_seed_test

    # the GA's private stream is untouched by seed_all
    ga = Genetics(lambda ind: 0.0, tunes={"x": Tune(0.0, -1.0, 1.0)})
    before = float(ga._gen.uniform(0.0, 1.0, (1,))[0])
    prng.seed_all(9)
    after = float(ga._gen.uniform(0.0, 1.0, (1,))[0])
    assert before != after  # stream advanced, was not reset to the start


def _staged_fc_step(n_steps=6, batch=40):
    """Small fused FC workflow + device-staged train/valid batches."""
    import jax.numpy as jnp
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    prng.seed_all(51)
    w = build_fused(max_epochs=1, layers=(32,), minibatch_size=batch,
                    n_train=240, n_valid=80, mesh=data_parallel_mesh(4))
    w.initialize(device=TPUDevice())
    rng = np.random.default_rng(2)
    xs = jnp.asarray(rng.normal(size=(n_steps, batch, 28, 28)),
                     jnp.float32)
    # learnable rule so lr actually matters: label = quadrant sign pattern
    ys = jnp.asarray(
        (np.asarray(xs)[:, :, :14, :].sum((2, 3)) >
         np.asarray(xs)[:, :, 14:, :].sum((2, 3))).astype(np.int32))
    ms = jnp.ones((n_steps, batch), bool)
    return w, xs, ys, ms


def test_vmapped_population_matches_sequential_and_scales():
    """SURVEY.md §3.4 hyperparameter parallelism: the population is a
    batched axis.  Each vmapped individual's fitness equals the same
    hyperparams trained sequentially, and scoring P=8 individuals in one
    dispatch beats 8 sequential scans wall-clock."""
    import time

    import jax
    import jax.numpy as jnp
    from znicz_tpu.utils.genetics import make_population_evaluator

    w, xs, ys, ms = _staged_fc_step()
    step = w.step
    ex, ey, em = xs[0], ys[0], ms[0]
    evaluator = make_population_evaluator(step)
    P = 8
    lrs = np.linspace(0.0, 0.35, P).astype(np.float32)
    base = step.hyper_params()
    hyper_pop = jax.tree.map(
        lambda v: jnp.broadcast_to(jnp.float32(v), (P,)), base)
    for i in range(len(base)):
        hyper_pop[i]["lr"] = jnp.asarray(lrs)
        hyper_pop[i]["lr_b"] = jnp.asarray(lrs)

    t0 = time.perf_counter()
    fits = np.asarray(jax.device_get(evaluator(
        hyper_pop, xs, ys, ms, ex, ey, em)))
    t_vmap_cold = time.perf_counter() - t0
    assert fits.shape == (P,)
    # lr=0 learns nothing; a healthy lr must beat it
    assert fits.min() < fits[0], fits

    # parity: sequential per-individual scans give identical fitness
    def run_sequential(i):
        hyper_i = jax.tree.map(lambda v: v[i], hyper_pop)
        # fresh copies: _train_fn donates its params/key arguments
        params = jax.tree.map(jnp.copy, step._params)
        key_i = jax.random.fold_in(step._key, i)
        for k in range(xs.shape[0]):
            params, key_i, _ = step._train_fn(
                params, key_i, hyper_i, xs[k], ys[k], ms[k])
        return int(jax.device_get(step._eval_fn(params, ex, ey, em))
                   ["n_err"])

    run_sequential(0)           # warm: compiles _train_fn/_eval_fn
    t_seq = float("inf")
    for _rep in range(2):       # best-of-2: robust to transient host load
        t0 = time.perf_counter()
        seq = [run_sequential(i) for i in (0, 3, 7)]
        t_seq = min(t_seq, time.perf_counter() - t0)
    assert seq == [int(f) for f in fits[[0, 3, 7]]], (seq, fits)

    # scaling: one warmed batched dispatch for 8 beats 3 sequential runs
    t_vmap = float("inf")
    for _rep in range(2):
        t0 = time.perf_counter()
        jax.device_get(evaluator(hyper_pop, xs, ys, ms, ex, ey, em))
        t_vmap = min(t_vmap, time.perf_counter() - t0)
    assert t_vmap < t_seq, (t_vmap, t_seq, t_vmap_cold)


def test_ga_with_vmapped_evaluator_converges_to_good_lr():
    """Genetics(evaluate_many=...) scores whole generations in one
    compiled dispatch and still finds a working learning rate."""
    import jax
    import jax.numpy as jnp
    from znicz_tpu.utils.genetics import make_population_evaluator

    w, xs, ys, ms = _staged_fc_step()
    step = w.step
    ex, ey, em = xs[0], ys[0], ms[0]
    base = step.hyper_params()
    evaluator = make_population_evaluator(step)

    def evaluate_many(pop):
        P = len(pop)
        hyper_pop = jax.tree.map(
            lambda v: jnp.broadcast_to(jnp.float32(v), (P,)), base)
        lrs = jnp.asarray([ind["lr"] for ind in pop], jnp.float32)
        for i in range(len(base)):
            hyper_pop[i] = dict(hyper_pop[i], lr=lrs, lr_b=lrs)
        return np.asarray(jax.device_get(evaluator(
            hyper_pop, xs, ys, ms, ex, ey, em)))

    prng.seed_all(6)
    ga = Genetics(evaluate=None, evaluate_many=evaluate_many,
                  tunes={"lr": Tune(0.0, 0.0, 0.4)},
                  population_size=8, mutation_rate=0.5)
    best, fit = ga.run(generations=4)
    assert 0.0 < best["lr"] <= 0.4
    assert fit <= evaluate_many([{"lr": 0.0}] * 1)[0], (best, fit)


def test_ensemble_committee(tmp_path):
    ens = Ensemble(wine.build, n_members=3, base_seed=50, max_epochs=3,
                   n_train=60, n_valid=30, minibatch_size=10)
    ens.train(TPUDevice())
    report = ens.test_classification()
    assert report["n"] == 30
    # the committee must not be worse than the worst member
    assert report["committee_err"] <= max(report["member_errs"])
    # predictions shapes
    loader = ens.members[0].loader
    data = loader.original_data.map_read()[:8]
    assert ens.predict_classes(data).shape == (8,)
    assert ens.predict_mean(data).shape[0] == 8


def test_cli_ensemble_train(tmp_path, monkeypatch):
    """--ensemble-train N runs N seeded members and writes the summary
    JSON (reference: veles --ensemble-train)."""
    wf = tmp_path / "wine_ens.py"
    wf.write_text(WINE_WORKFLOW)
    monkeypatch.chdir(tmp_path)
    rc = cli_main([str(wf), "--ensemble-train", "3", "-d", "tpu",
                   "--random-seed", "7"])
    assert rc == 0
    out = json.loads((tmp_path / "ensemble_wine.json").read_text())
    assert out["n_members"] == 3
    assert len(out["members"]) == 3
    assert len({m["seed"] for m in out["members"]}) == 3
    assert out["best"] <= out["mean"]


def test_cli_ensemble_train_rejects_bad_usage(tmp_path, monkeypatch):
    wf = tmp_path / "wine_ens2.py"
    wf.write_text(WINE_WORKFLOW)
    monkeypatch.chdir(tmp_path)
    assert cli_main([str(wf), "--ensemble-train", "0", "-d", "tpu"]) == 2
    assert cli_main([str(wf), "--ensemble-train", "2", "-d", "tpu",
                     "--publish", "markdown"]) == 2


def test_forge_cli_roundtrip(tmp_path, capsys):
    """`znicz_tpu forge upload/list/fetch` — the reference's forge CLI
    over the local registry."""
    import numpy as np

    from znicz_tpu.__main__ import main

    pkg = tmp_path / "pkg.npz"
    np.savez(pkg, w=np.arange(4.0))
    reg = str(tmp_path / "registry")

    assert main(["forge", "--registry", reg, "upload", str(pkg),
                 "--name", "demo", "--version", "1.0"]) == 0
    assert main(["forge", "--registry", reg, "upload", str(pkg),
                 "--name", "demo", "--version", "1.10"]) == 0
    assert main(["forge", "--registry", reg, "list"]) == 0
    out = capsys.readouterr().out
    assert "demo: 1.0, 1.10" in out          # semantic version order

    dest = tmp_path / "fetched.npz"
    assert main(["forge", "--registry", reg, "fetch", "demo",
                 "-o", str(dest)]) == 0      # latest = 1.10
    assert dest.exists()
    with np.load(dest) as loaded:
        np.testing.assert_array_equal(loaded["w"], np.arange(4.0))


def test_forge_cli_errors_are_one_liners(tmp_path, capsys):
    """Registry failures exit 2 with a one-line stderr message, not a
    traceback (CLI convention)."""
    from znicz_tpu.__main__ import main

    reg = str(tmp_path / "reg")
    assert main(["forge", "--registry", reg, "fetch", "nosuch"]) == 2
    err = capsys.readouterr().err
    assert "forge:" in err and "nosuch" in err

    assert main(["forge", "--registry", reg, "upload",
                 str(tmp_path / "missing.npz"),
                 "--name", "x", "--version", "1"]) == 2
    assert "forge:" in capsys.readouterr().err


def test_site_config_layering(tmp_path, monkeypatch):
    """Reference layering: site config applies BEFORE workflow configs,
    so workflow-level settings win; $ZNICZ_TPU_SITE_CONFIG selects it."""
    site = tmp_path / "site_config.py"
    site.write_text("root.wine.max_epochs = 9\n"
                    "root.site_probe.marker = 'site'\n")
    wf = tmp_path / "wf.py"
    wf.write_text(WINE_WORKFLOW)
    cfg = tmp_path / "wine_config.py"
    cfg.write_text("root.wine.max_epochs = 2\n")   # overrides the site value
    result_file = tmp_path / "result.json"
    monkeypatch.setenv("ZNICZ_TPU_SITE_CONFIG", str(site))
    try:
        rc = cli_main([str(wf), str(cfg), "--random-seed", "5", "-d", "tpu",
                       "-o", f"root.wine.result_file={result_file}"])
        assert rc == 0
        assert json.loads(result_file.read_text())["epochs"] == 2
        assert root.site_probe.marker == "site"    # site layer did run
    finally:
        for key in ("site_probe", "wine"):
            if key in root:
                delattr(root, key)

    from znicz_tpu.__main__ import apply_site_config

    # explicit-but-missing path: loud error, not a silent skip
    monkeypatch.setenv("ZNICZ_TPU_SITE_CONFIG", str(tmp_path / "nope.py"))
    with pytest.raises(SystemExit, match="does not exist"):
        apply_site_config()
    # empty string disables the layer even if a home-dir file exists
    monkeypatch.setenv("ZNICZ_TPU_SITE_CONFIG", "")
    assert apply_site_config() is None
    # no env var + no home-dir file: silently none
    monkeypatch.delenv("ZNICZ_TPU_SITE_CONFIG")
    monkeypatch.setenv("HOME", str(tmp_path / "nohome"))
    assert apply_site_config() is None
