"""Composition fuzzing: random declarative layer stacks through
StandardWorkflow, asserting the fused one-XLA-program step produces the
SAME weight updates as the eager per-unit chain (autograd-composed
backward == hand-written backward) for arbitrary compositions — the
tier-2 analog of the per-op geometry fuzz.

Each example compiles a small program, so the example count is low; the
value is coverage of layer ADJACENCIES (conv->dropout->pool->fc etc.)
that the fixed model-zoo stacks never permute.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.standard_workflow import StandardWorkflow

SETTINGS = dict(max_examples=8, deadline=None, derandomize=True)

HYPER = {"learning_rate": 0.05, "gradient_moment": 0.5,
         "weights_decay": 1e-4}


@st.composite
def layer_stacks(draw):
    """A random (but always-valid) conv/pool/norm/fc stack ending in
    softmax, on an 8x8x2 input."""
    stack = []
    n_conv_blocks = draw(st.integers(0, 2))
    for _ in range(n_conv_blocks):
        kind = draw(st.sampled_from(["conv", "conv_relu", "conv_tanh",
                                     "conv_str"]))
        stack.append({"type": kind,
                      "->": {"n_kernels": draw(st.sampled_from([4, 8])),
                             "kx": 3, "ky": 3, "padding": (1, 1, 1, 1)},
                      "<-": dict(HYPER)})
        extra = draw(st.sampled_from(
            ["none", "max_pooling", "maxabs_pooling", "avg_pooling",
             "stochastic_pooling", "norm", "dropout"]))
        if extra in ("max_pooling", "maxabs_pooling", "avg_pooling",
                     "stochastic_pooling"):
            stack.append({"type": extra, "->": {"kx": 2, "ky": 2}})
        elif extra == "norm":
            stack.append({"type": "norm",
                          "->": {"alpha": 1e-4, "beta": 0.75, "k": 2.0,
                                 "n": 3}})
        elif extra == "dropout":
            stack.append({"type": "dropout", "->": {"dropout_ratio": 0.2}})
    n_fc = draw(st.integers(0, 2))
    for _ in range(n_fc):
        kind = draw(st.sampled_from(["all2all", "all2all_tanh",
                                     "all2all_relu", "all2all_str",
                                     "all2all_sigmoid"]))
        stack.append({"type": kind,
                      "->": {"output_sample_shape":
                             draw(st.sampled_from([8, 16]))},
                      "<-": dict(HYPER)})
    stack.append({"type": "softmax", "->": {"output_sample_shape": 3},
                  "<-": dict(HYPER)})
    seed = draw(st.integers(1, 2 ** 20))
    return stack, seed


def _build(stack, seed, fused=True):
    prng.seed_all(seed)
    return StandardWorkflow(
        name="fuzz", layers=[dict(d) for d in stack],
        loss_function="softmax", loader_name="synthetic_image",
        loader_config={"n_classes": 3, "sample_shape": (8, 8, 2),
                       "n_train": 24, "n_valid": 0, "minibatch_size": 12,
                       "spread": 2.0},
        decision_config={"max_epochs": 1}, fused=fused)


def _run_one_minibatch(w, fused):
    """The one-train-minibatch protocol shared by every fuzz test."""
    w.loader.run()
    if fused:
        w.step.run()
        w.step.sync_to_units()
    else:
        for f in w.forwards:
            f.run()
        w.evaluator.run()
        for gd in reversed(w.gds):
            gd.run()
    return w


def _one_step(stack, seed, fused, device):
    w = _build(stack, seed, fused)
    w.initialize(device=device)
    return _run_one_minibatch(w, fused)


@given(layer_stacks())
@settings(**SETTINGS)
def test_fused_matches_eager_for_random_stacks(case):
    stack, seed = case
    stochastic = any(d["type"] in ("dropout", "stochastic_pooling")
                     for d in stack)
    if stochastic:
        # dropout masks / stochastic-pool draws come from different PRNG
        # systems in the two execution shapes (host xorshift vs
        # counter-based) — exact update parity does not apply; instead
        # assert BOTH shapes actually trained: finite params that moved
        # from their init, captured AFTER initialize, BEFORE the step
        for fused, device in ((True, TPUDevice()), (False, NumpyDevice())):
            w = _build(stack, seed, fused)
            w.initialize(device=device)
            init = [f.weights.map_read().copy() for f in w.forwards
                    if f.weights]
            _run_one_minibatch(w, fused)
            trained = [f.weights.map_read() for f in w.forwards
                       if f.weights]
            assert any(not np.array_equal(a, b)
                       for a, b in zip(init, trained)), fused
            for t in trained:
                assert np.isfinite(t).all(), fused
        return
    we = _one_step(stack, seed, False, NumpyDevice())
    wf = _one_step(stack, seed, True, TPUDevice())
    checked = 0
    for i, (fe, ff) in enumerate(zip(we.forwards, wf.forwards)):
        if not fe.weights:
            continue
        np.testing.assert_allclose(
            ff.weights.map_read(), fe.weights.map_read(),
            rtol=2e-4, atol=2e-5,
            err_msg=f"layer {i} ({stack[i]['type']}) weights")
        np.testing.assert_allclose(
            ff.bias.map_read(), fe.bias.map_read(),
            rtol=2e-4, atol=2e-5,
            err_msg=f"layer {i} ({stack[i]['type']}) bias")
        checked += 1
    assert checked >= 1


@given(layer_stacks())
@settings(max_examples=4, deadline=None, derandomize=True)
def test_random_stacks_snapshot_roundtrip(case):
    """Any random stack snapshots and restores bit-exactly into a
    fresh differently-seeded workflow (the collect/restore contract
    holds for arbitrary compositions, not just the zoo models)."""
    import os
    import tempfile

    from znicz_tpu.snapshotter import (collect_state, restore_state,
                                       write_snapshot)

    stack, seed = case
    w = _one_step(stack, seed, True, TPUDevice())
    arrays, meta = collect_state(w)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "s.npz")
        write_snapshot(path, arrays, meta)
        # fresh build, DIFFERENT seed: restore must overwrite everything
        w2 = _one_step(stack, seed + 1, True, TPUDevice())
        restore_state(w2, path)
        w2.step.sync_to_units()
    for i, (fa, fb) in enumerate(zip(w.forwards, w2.forwards)):
        if fa.weights:
            np.testing.assert_array_equal(
                fb.weights.map_read(), fa.weights.map_read(),
                err_msg=f"layer {i} ({stack[i]['type']}) weights")
            np.testing.assert_array_equal(
                fb.bias.map_read(), fa.bias.map_read(),
                err_msg=f"layer {i} ({stack[i]['type']}) bias")


@st.composite
def ae_stacks(draw):
    """Random conv->deconv reconstruction geometry (kernel size, stride,
    kernel count, channels) — the deconv must exactly invert the conv's
    spatial map for the MSE-vs-input loss to typecheck."""
    k = draw(st.integers(2, 4))
    stride = draw(st.integers(1, 2))
    nk = draw(st.sampled_from([4, 8]))
    c = draw(st.integers(1, 2))
    # invertible geometry: (H - k) % stride == 0, else the conv drops
    # tail rows and the reconstruction cannot match the input shape
    H = k + stride * draw(st.integers(2, 4))
    lr = 0.002
    stack = [
        {"type": "conv", "->": {"n_kernels": nk, "kx": k, "ky": k,
                                "sliding": (stride, stride)},
         "<-": {"learning_rate": lr, "gradient_moment": 0.5}},
        {"type": "deconv", "->": {"n_kernels": nk, "kx": k, "ky": k,
                                  "sliding": (stride, stride),
                                  "n_channels": c},
         "<-": {"learning_rate": lr, "gradient_moment": 0.5}},
    ]
    seed = draw(st.integers(1, 2 ** 20))
    return stack, H, c, seed


@given(ae_stacks())
@settings(max_examples=6, deadline=None, derandomize=True)
def test_ae_fused_matches_eager_for_random_geometry(case):
    """Conv->deconv autoencoder: fused AD backward equals the
    hand-written GDDeconv/GDConv chain for random geometry (the adjoint
    pair composed end-to-end through the MSE evaluator)."""
    stack, H, c, seed = case

    def one_step(fused, device):
        prng.seed_all(seed)
        w = StandardWorkflow(
            name="aefuzz", layers=[dict(d) for d in stack],
            loss_function="mse", loader_name="synthetic_regression",
            loader_config={"sample_shape": (H, H, c), "identity": True,
                           "n_train": 24, "n_valid": 0,
                           "minibatch_size": 12},
            decision_config={"max_epochs": 1}, fused=fused)
        w.initialize(device=device)
        return _run_one_minibatch(w, fused)

    we = one_step(False, NumpyDevice())
    wf = one_step(True, TPUDevice())
    for i, (fe, ff) in enumerate(zip(we.forwards, wf.forwards)):
        np.testing.assert_allclose(
            ff.weights.map_read(), fe.weights.map_read(),
            rtol=3e-4, atol=3e-5,
            err_msg=f"layer {i} ({stack[i]['type']}) weights")
        if fe.bias:          # deconv carries no bias; conv does
            np.testing.assert_allclose(
                ff.bias.map_read(), fe.bias.map_read(),
                rtol=3e-4, atol=3e-5,
                err_msg=f"layer {i} ({stack[i]['type']}) bias")

    # snapshot roundtrip holds for the MSE/deconv composition too
    import os
    import tempfile

    from znicz_tpu.snapshotter import (collect_state, restore_state,
                                       write_snapshot)

    arrays, meta = collect_state(wf)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "s.npz")
        write_snapshot(path, arrays, meta)
        w2 = one_step(True, TPUDevice())
        restore_state(w2, path)
        w2.step.sync_to_units()
    for fa, fb in zip(wf.forwards, w2.forwards):
        np.testing.assert_array_equal(fb.weights.map_read(),
                                      fa.weights.map_read())


@given(layer_stacks())
@settings(max_examples=4, deadline=None, derandomize=True)
def test_pallas_engine_matches_xla_for_random_stacks(case):
    """root.common.engine.pallas must be output-preserving for arbitrary
    compositions, not just the fixed selection tests: the same random
    stack trained eagerly on the XLA paths and on the hand-written
    kernel paths (conv fwd + conv/deconv backward, interpret mode)
    produces the same weights."""
    from hypothesis import assume

    from znicz_tpu.core.config import root

    stack, seed = case
    # different PRNG systems — covered by the finite/moved fuzz; assume()
    # regenerates the example so the budget stays 4 real comparisons
    assume(not any(d["type"] in ("dropout", "stochastic_pooling")
                   for d in stack))

    def run(pallas):
        root.common.engine.pallas = pallas
        root.common.engine.pallas_interpret = pallas
        try:
            w = _build(stack, seed, fused=False)
            w.initialize(device=TPUDevice())
            return _run_one_minibatch(w, fused=False)
        finally:
            root.common.engine.pallas = False
            root.common.engine.pallas_interpret = False

    base = run(False)
    pall = run(True)
    checked = 0
    for i, (fb, fp) in enumerate(zip(base.forwards, pall.forwards)):
        if not fb.weights:
            continue
        np.testing.assert_allclose(
            fp.weights.map_read(), fb.weights.map_read(),
            rtol=2e-4, atol=2e-5,
            err_msg=f"layer {i} ({stack[i]['type']}) weights")
        np.testing.assert_allclose(
            fp.bias.map_read(), fb.bias.map_read(),
            rtol=2e-4, atol=2e-5,
            err_msg=f"layer {i} ({stack[i]['type']}) bias")
        checked += 1
    assert checked >= 1


@st.composite
def fused_flag_combos(draw):
    """A random fused-step flag combination (sharding layout x optimizer
    x EMA x narrow momenta) for the quantized-collectives gate."""
    layout = draw(st.sampled_from(["replicated", "shard_update",
                                   "shard_params"]))
    optimizer = draw(st.sampled_from(["sgd", "adam"]))
    ema_decay = draw(st.sampled_from([None, 0.9]))
    state_dtype = (draw(st.sampled_from([None, "bfloat16"]))
                   if optimizer == "sgd" else None)   # SGD-only knob
    seed = draw(st.integers(1, 2 ** 20))
    return layout, optimizer, ema_decay, state_dtype, seed


@given(fused_flag_combos())
@settings(max_examples=4, deadline=None, derandomize=True)
def test_quantized_collectives_across_flag_combos(case):
    """ISSUE 18 gate: the quantized-collective codec composes with the
    whole fused-step flag surface — for random shard_update/
    shard_params/optimizer/ema/state_dtype combinations, mode=off stays
    BIT-IDENTICAL to a build that never passed the config, and
    int8+error-feedback trains within a pinned validation-error band of
    the exact run (the fused step's exact path already psums grads
    explicitly, so the quantized run differs by codec noise only)."""
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    layout, optimizer, ema_decay, state_dtype, seed = case
    flags = {"shard_update": layout == "shard_update",
             "shard_params": layout == "shard_params"}

    def run(qc):
        prng.seed_all(seed)
        w = build_fused(
            max_epochs=2, layers=(16,), minibatch_size=16,
            n_train=96, n_valid=32, mesh=data_parallel_mesh(4),
            optimizer=optimizer,
            optimizer_config=({"state_dtype": state_dtype}
                              if state_dtype else None),
            ema_decay=ema_decay, quantized_collectives=qc, **flags)
        w.initialize(device=TPUDevice())
        w.run()
        return [h["metric_validation"]
                for h in w.decision.metrics_history]

    exact = run(None)
    assert run({"mode": "off"}) == exact, case
    quant = run({"mode": "int8", "chunk": 64, "error_feedback": True})
    assert len(quant) == len(exact), case
    band = max(3.0, 0.05 * 32)     # validation-error counts out of 32
    for e, q in zip(exact, quant):
        assert abs(e - q) <= band, (case, exact, quant)
