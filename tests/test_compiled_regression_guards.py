"""Structural (jaxpr-level) regression guards for the compiled-mode bug
classes the first on-chip Pallas parity sweep exposed (2026-07-31 01:01
UTC, docs/BENCH_LOG.md) — defects invisible to interpret-mode parity
because they live in Mosaic lowering or MXU default-precision semantics,
not in the math.  These tests pin the *structural property each fix
relies on*, so a refactor cannot silently reintroduce the bug class
between chip windows (suite-level compiled regression protection is
otherwise chip-gated; VERDICT r4 weak #7).

Bug classes covered:
1. Kohonen winner flips: default-precision MXU bf16 passes break exact
   ``d2 == dmin`` comparisons (40.8% of weights diverged on chip).
   Guard: every dot inside the SOM kernel runs Precision.HIGHEST.
2. Adam remote-compile crash: a scalar ``pow`` on SMEM operands crashes
   the Mosaic scalar-core compiler.  Guard: no pow of a traced scalar
   inside the kernel jaxpr (bias corrections precomputed outside).
3. Conv/deconv Mosaic strided-slice failure: stride>1 slices inside a
   kernel fail to lower.  Guard: no strided slice/dynamic-slice ops in
   any conv-family kernel jaxpr (the phase-split decomposition makes
   every in-kernel tap stride-1).
4. Flash-attention lse tiling: a 2-D ``(1, block_q)`` lse block is not
   a legal Mosaic tile.  Guard: lse/delta ride as rank-3 blocks with a
   trailing singleton.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _pallas_eqns(closed_jaxpr):
    """All equations inside every pallas_call kernel jaxpr, recursively
    (scan/cond bodies included so kernels under lax control flow are
    still found)."""
    found = []

    def walk(jaxpr, inside_kernel):
        for eqn in jaxpr.eqns:
            if inside_kernel:
                found.append(eqn)
            here = inside_kernel or eqn.primitive.name == "pallas_call"
            for val in eqn.params.values():
                for sub in _sub_jaxprs(val):
                    walk(sub, here)

    def _sub_jaxprs(val):
        import jax.extend.core as jex_core
        if isinstance(val, jex_core.ClosedJaxpr):
            return [val.jaxpr]
        if isinstance(val, jex_core.Jaxpr):
            return [val]
        if isinstance(val, (tuple, list)):
            out = []
            for v in val:
                out.extend(_sub_jaxprs(v))
            return out
        return []

    walk(closed_jaxpr.jaxpr, False)
    assert found, "no pallas_call found in the traced function"
    return found


def test_kohonen_kernel_dots_run_highest_precision():
    from znicz_tpu.ops.pallas.kohonen import som_step

    x = jnp.zeros((8, 6), jnp.float32)
    w = jnp.zeros((16, 6), jnp.float32)
    coords = jnp.zeros((16, 2), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda x, w, c: som_step(x, w, c, 0.1, 1.0, 8))(x, w, coords)
    dots = [e for e in _pallas_eqns(jaxpr)
            if e.primitive.name == "dot_general"]
    assert dots, "SOM kernel lost its MXU dots?"
    for eqn in dots:
        prec = eqn.params.get("precision")
        assert prec is not None and all(
            p == jax.lax.Precision.HIGHEST for p in np.ravel(prec)), (
            f"SOM kernel dot at default precision would flip winners on "
            f"the MXU (chip-measured 40.8% divergence): {eqn}")


def test_adam_kernel_has_no_scalar_pow():
    from znicz_tpu.ops.pallas.adam import fused_adam_update

    w = jnp.zeros((128, 256), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda w, g, m, v, t: fused_adam_update(
            w, g, m, v, t, 1e-3, 0.01, 0.9, 0.999, 1e-8, 32))(
        w, w, w, w, jnp.int32(3))
    banned = {"pow", "integer_pow"}
    inside = [e for e in _pallas_eqns(jaxpr)
              if e.primitive.name in banned]
    assert not inside, (
        f"pow inside the adam kernel crashes the Mosaic scalar-core "
        f"compiler (remote-compile HTTP 500) — precompute bias "
        f"corrections outside: {inside}")


@pytest.mark.parametrize("case", ["fwd", "bwd", "deconv"])
def test_conv_kernels_have_no_strided_slices(case):
    from znicz_tpu.ops import conv as conv_ops
    from znicz_tpu.ops import deconv as deconv_ops
    from znicz_tpu.ops.pallas import conv, conv_bwd

    sliding, padding = (2, 2), (1, 2, 1, 2)     # the Mosaic-hostile case
    x = jnp.zeros((3, 13, 13, 3), jnp.float32)
    w = jnp.zeros((5, 5, 3, 8), jnp.float32)
    b = jnp.zeros((8,), jnp.float32)
    out_shape = conv_ops.forward_linear(
        np, np.zeros(x.shape, np.float32), np.zeros(w.shape, np.float32),
        None, sliding, padding).shape
    if case == "fwd":
        fn = lambda x, w, b: conv.conv2d_im2col(      # noqa: E731
            x, w, b, sliding, padding)
        jaxpr = jax.make_jaxpr(fn)(x, w, b)
    elif case == "bwd":
        err = jnp.zeros(out_shape, jnp.float32)
        fn = lambda x, w, e: conv_bwd.conv2d_backward(  # noqa: E731
            x, w, e, sliding, padding)
        jaxpr = jax.make_jaxpr(fn)(x, w, err)
    else:
        xd = jnp.zeros(out_shape, jnp.float32)
        dec_shape = deconv_ops.output_shape_for(
            out_shape, w.shape, sliding, padding)
        fn = lambda x, w: conv_bwd.deconv2d(          # noqa: E731
            x, w, sliding, padding, dec_shape)
        jaxpr = jax.make_jaxpr(fn)(xd, w)
    for eqn in _pallas_eqns(jaxpr):
        if eqn.primitive.name == "slice":
            strides = eqn.params.get("strides")
            assert strides is None or all(s == 1 for s in strides), (
                f"stride>1 slice inside a conv kernel fails Mosaic "
                f"lowering — use the phase-split decomposition "
                f"(ops/pallas/conv.py::phase_split): {eqn}")
        # the current kernels index only via BlockSpecs and static
        # stride-1 taps; dynamic slicing inside the kernel is the other
        # Mosaic-hostile addressing mode, so its appearance at all is a
        # red flag
        assert eqn.primitive.name != "dynamic_slice", str(eqn)


def test_flash_lse_rides_rank3_with_trailing_singleton():
    from znicz_tpu.ops.pallas.attention import _call_fwd

    bh, t, dh = 2, 256, 64
    q = jnp.zeros((bh, t, dh), jnp.float32)
    o, lse = _call_fwd(q, q, q, False, True)
    assert o.shape == (bh, t, dh)
    assert lse.ndim == 3 and lse.shape == (bh, t, 1), (
        "lse must keep its trailing singleton: a 2-D (1, block_q) block "
        "is not a legal Mosaic tile (docs/TUNING.md)")
    # and the backward (which consumes lse and builds the same-shaped
    # delta) runs through the public custom-VJP entry
    from znicz_tpu.ops.pallas.attention import flash_attention
    q4 = jnp.zeros((1, t, 2, dh), jnp.float32)
    grads = jax.grad(lambda q: flash_attention(
        q, q, q, interpret=True).sum())(q4)
    assert grads.shape == q4.shape
