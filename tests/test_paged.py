"""Paged KV arena + speculative decoding + Pallas flash-decode
(ISSUE 12): paged attention reads pinned equal to contiguous-buffer
reads over randomized page tables/lengths, the interpret-mode Pallas
kernel pinned against the jnp reference within the established 2e-5
band, speculative greedy decode token-identical to non-speculative
decode (and through it to the full-pass logits oracle), page-budget
admission/eviction, the orphan sweep, and the exact page ledger after
the chaos drill."""

import threading
import time

import numpy as np
import pytest

pytest.importorskip("jax")

from znicz_tpu.serve import (ArenaExhausted, ContinuousBatcher,
                             GenerateMetrics, GenerationError, KVDecoder,
                             PagedKVDecoder, PageLedger, truncate_draft)

N_LAYERS, D, HEADS, FF, VOCAB = 2, 32, 4, 64, 31


@pytest.fixture(scope="module")
def params():
    from znicz_tpu.parallel.transformer import init_params

    return init_params(np.random.default_rng(3), N_LAYERS, D, HEADS, FF,
                       VOCAB)


@pytest.fixture(scope="module")
def contiguous(params):
    return KVDecoder(params, heads=HEADS, max_len=32, batch=1)


@pytest.fixture(scope="module")
def paged_cache(params):
    """One paged decoder per config for the module — compiled programs
    are request-independent, so tests share the compile cost."""
    cache: dict = {}

    def get(batch=2, page=8, arena_pages=None, max_len=32,
            use_pallas=False):
        key = (batch, page, arena_pages, max_len, use_pallas)
        if key not in cache:
            cache[key] = PagedKVDecoder(
                params, heads=HEADS, max_len=max_len, batch=batch,
                page=page, arena_pages=arena_pages,
                use_pallas=use_pallas)
        return cache[key]

    return get


def _drive_paged(dec, prompt, n_new, slot=0, scramble_rng=None):
    """Hand-drive one request through the paged plane (greedy),
    returning its tokens.  ``scramble_rng`` churns the free list with
    random alloc/free cycles first, so the request lands on an
    arbitrary, non-contiguous, non-monotone page set — the property
    the page table must make invisible."""
    if scramble_rng is not None:
        held = dec.ledger.alloc(
            int(scramble_rng.integers(1, dec.ledger.free - 4)))
        keep = scramble_rng.permutation(len(held))
        dec.ledger.release([held[i] for i in keep])
    pages = dec.ledger.alloc(dec.pages_for(len(prompt)))
    kv1, logits = dec.prefill(prompt,
                              bucket=dec.bucket_for(len(prompt)))
    dec.adopt_paged(kv1, pages)
    pos, out = len(prompt), []
    tok = int(np.argmax(logits))
    out.append(tok)
    for _ in range(n_new - 1):
        while len(pages) * dec.page < pos + 1:
            pages.extend(dec.ledger.alloc(1))
        pt = np.zeros((dec.batch, dec.view_bucket(len(pages))),
                      np.int32)
        pt[slot, :len(pages)] = pages
        pos_v = np.zeros(dec.batch, np.int32)
        tok_v = np.zeros(dec.batch, np.int32)
        pos_v[slot], tok_v[slot] = pos, tok
        lg = dec.decode_paged(pt, pos_v, tok_v)
        tok = int(np.argmax(lg[slot]))
        out.append(tok)
        pos += 1
    dec.ledger.release(pages)
    return out


# -- the tentpole pin: paged reads == contiguous reads ------------------------

def test_paged_decode_matches_contiguous_over_random_page_tables(
        params, contiguous, paged_cache):
    """Property-style: randomized prompts/lengths decoded through
    scrambled (non-contiguous, reused) page tables must reproduce the
    contiguous-buffer decode token for token — page layout is invisible
    to the math."""
    dec = paged_cache(batch=2, page=8, arena_pages=17)
    rng = np.random.default_rng(11)
    for trial in range(6):
        p_len = int(rng.integers(1, 12))
        n_new = int(rng.integers(2, 32 - p_len))
        prompt = rng.integers(0, VOCAB, size=p_len).tolist()
        want = contiguous.generate(prompt, n_new)
        got = _drive_paged(dec, prompt, n_new,
                           slot=int(rng.integers(0, dec.batch)),
                           scramble_rng=rng)
        assert got == want, (trial, prompt, n_new)
    assert dec.ledger.used == 0         # every trial returned its pages


def test_paged_logits_match_contiguous_within_band(params, contiguous,
                                                   paged_cache):
    dec = paged_cache(batch=2, page=8, arena_pages=17)
    prompt = [5, 7, 1, 30, 12]
    kv, lg_c = contiguous.prefill(prompt, bucket=16)
    pages = dec.ledger.alloc(dec.pages_for(len(prompt)))
    kv1, lg_p = dec.prefill(prompt, bucket=8)
    dec.adopt_paged(kv1, pages)
    np.testing.assert_allclose(lg_p, lg_c, rtol=2e-5, atol=2e-5)
    pos, tok = len(prompt), int(np.argmax(lg_c))
    for _ in range(6):
        kv, bl = contiguous.decode(kv, [pos], [tok])
        while len(pages) * dec.page < pos + 1:
            pages.extend(dec.ledger.alloc(1))   # grow = page append
        pt = np.zeros((2, dec.view_bucket(len(pages))), np.int32)
        pt[0, :len(pages)] = pages
        pl_ = dec.decode_paged(pt, np.array([pos, 0], np.int32),
                               np.array([tok, 0], np.int32))
        np.testing.assert_allclose(pl_[0], bl[0], rtol=2e-5, atol=2e-5)
        tok = int(np.argmax(bl[0]))
        pos += 1
    dec.ledger.release(pages)


# -- Pallas flash-decode kernel ----------------------------------------------

def test_pallas_decode_kernel_interpret_matches_jnp_reference():
    from znicz_tpu.ops.pallas.decode import (paged_flash_decode,
                                             reference, supported)

    rng = np.random.default_rng(0)
    for B, H, Dh, page, n_pages, P in ((3, 4, 8, 8, 10, 2),
                                       (2, 2, 16, 4, 7, 4),
                                       (1, 1, 8, 16, 3, 1)):
        q = rng.normal(size=(B, H, Dh)).astype(np.float32)
        k = rng.normal(size=(n_pages, page, H, Dh)).astype(np.float32)
        v = rng.normal(size=(n_pages, page, H, Dh)).astype(np.float32)
        pt = rng.integers(0, n_pages, size=(B, P)).astype(np.int32)
        lengths = rng.integers(1, P * page + 1, size=(B,)) \
            .astype(np.int32)
        o = paged_flash_decode(q, k, v, pt, lengths, interpret=True)
        r = reference(q, k, v, pt, lengths)
        np.testing.assert_allclose(np.asarray(o), np.asarray(r),
                                   rtol=2e-5, atol=2e-5)
    assert supported(8, 128) and not supported(7, 128) \
        and not supported(8, 64)
    with pytest.raises(ValueError, match="supported"):
        paged_flash_decode(q, k, v, pt, lengths, interpret=False)


def test_paged_decoder_with_pallas_kernel_matches_contiguous(
        params, contiguous, paged_cache):
    """The whole decode program with the kernel swapped in (interpret
    mode on CPU) still reproduces the contiguous greedy sequence and
    stays in the 2e-5 logits band."""
    dec = paged_cache(batch=1, page=8, arena_pages=9, use_pallas=True)
    prompt = [2, 9, 4, 17]
    want = contiguous.generate(prompt, 8)
    assert _drive_paged(dec, prompt, 8) == want


# -- speculative decoding -----------------------------------------------------

def test_speculative_greedy_token_identical_to_plain_decode(
        params, contiguous, paged_cache):
    """THE speculation pin: greedy decode with the draft+verify rounds
    is token-identical to non-speculative decode — and through PR 10's
    oracle pin, to the full-pass training forward."""
    target = paged_cache(batch=2, page=8, arena_pages=17)
    draft = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                           max_len=32, batch=2, page=8)
    batcher = ContinuousBatcher(target, draft=draft, spec_k=3,
                                default_timeout_s=60.0)
    try:
        prompts = [[5, 7, 1, 30, 12], [2, 9], [1, 2, 3, 4], [8]]
        want = [contiguous.generate(p, 10) for p in prompts]
        got = [batcher.submit(p, max_new_tokens=10).result(timeout_s=60)
               for p in prompts]
        assert got == want
        snap = batcher.metrics.snapshot()
        # every greedy round judges exactly k draft tokens
        assert snap["spec_accepted"] + snap["spec_rejected"] > 0
        assert (snap["spec_accepted"] + snap["spec_rejected"]) % 3 == 0
    finally:
        batcher.stop()


def test_speculative_sampled_requests_keep_seeded_distribution(
        params, paged_cache):
    """A temperature>0 request rides the verify pass's position-0
    logits — its exact decode distribution — so seeded sampling
    reproduces across speculative runs AND matches the non-speculative
    batcher."""
    target = paged_cache(batch=2, page=8, arena_pages=17)
    plain = ContinuousBatcher(target)
    try:
        want = plain.submit([7, 8, 9], max_new_tokens=6,
                            temperature=0.9, top_k=5,
                            seed=42).result(timeout_s=60)
    finally:
        plain.stop()
    draft = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                           max_len=32, batch=2, page=8)
    spec = ContinuousBatcher(target, draft=draft, spec_k=3)
    try:
        got = spec.submit([7, 8, 9], max_new_tokens=6, temperature=0.9,
                          top_k=5, seed=42).result(timeout_s=60)
    finally:
        spec.stop()
    assert got == want


def test_speculative_config_validation(params, paged_cache):
    target = paged_cache(batch=2, page=8, arena_pages=17)
    contig = KVDecoder(params, heads=HEADS, max_len=32, batch=2)
    draft = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                           max_len=32, batch=2, page=8)
    with pytest.raises(ValueError, match="Paged"):
        ContinuousBatcher(contig, draft=draft)
    bad_batch = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                               max_len=32, batch=3, page=8)
    with pytest.raises(ValueError, match="batch"):
        ContinuousBatcher(target, draft=bad_batch)
    with pytest.raises(ValueError, match="spec_k"):
        ContinuousBatcher(target, draft=draft, spec_k=0)
    with pytest.raises(ValueError, match="draft"):
        truncate_draft(params, N_LAYERS)        # not smaller


def test_speculative_request_to_the_max_len_boundary(params, contiguous):
    """Review regression: a request whose budget runs to the max_len
    boundary must not push the verify pass past the widest compiled
    page view (or past its own page budget) — rounds near the end
    degrade to plain decode instead, and the stream stays
    token-identical."""
    target = PagedKVDecoder(params, heads=HEADS, max_len=32, batch=2,
                            page=8, arena_pages=9)  # exactly 2x budget? 8 usable
    draft = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                           max_len=32, batch=2, page=8)
    batcher = ContinuousBatcher(target, draft=draft, spec_k=4,
                                default_timeout_s=60.0)
    try:
        prompt = [5, 7, 1, 30]
        got = batcher.submit(prompt, max_new_tokens=28) \
            .result(timeout_s=60)           # budget 32 == max_len
        assert got == contiguous.generate(prompt, 28)
        assert batcher.page_ledger()["pages_used"] == 0
    finally:
        batcher.stop()


def test_speculative_warmup_with_page_smaller_than_round(params):
    """Review regression: warmup(spec_k) must skip page views too
    narrow to ever hold a verify round (page < spec_k + 1) instead of
    crashing the boot — live traffic can never dispatch them."""
    dec = PagedKVDecoder(params, heads=HEADS, max_len=16, batch=1,
                         page=4)
    draft = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                           max_len=16, batch=1, page=4)
    dec.warmup(spec_k=4)
    draft.warmup()
    base = dec.compile_count + draft.compile_count
    batcher = ContinuousBatcher(dec, draft=draft, spec_k=4)
    try:
        assert len(batcher.submit([3, 1], max_new_tokens=10)
                   .result(timeout_s=60)) == 10
    finally:
        batcher.stop()
    assert dec.compile_count + draft.compile_count == base


def test_spec_counter_children_exist_at_boot(params, paged_cache):
    """Review regression: the init-time pre-touch must MATERIALIZE both
    spec counter series (a fleet delta rule needs the 0 baseline, not a
    missing key)."""
    from znicz_tpu.observe import REGISTRY

    target = paged_cache(batch=2, page=8, arena_pages=17)
    draft = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                           max_len=32, batch=2, page=8)
    batcher = ContinuousBatcher(target, draft=draft, spec_k=3)
    try:
        prom = REGISTRY.render_prometheus()
        assert 'znicz_generate_spec_tokens_total{event="accepted"}' \
            in prom
        assert 'znicz_generate_spec_tokens_total{event="rejected"}' \
            in prom
    finally:
        batcher.stop()


# -- arena admission / eviction / ledger --------------------------------------

def test_zero_recompiles_paged_and_speculative_steady_state(params):
    target = PagedKVDecoder(params, heads=HEADS, max_len=16, batch=2,
                            page=8)
    draft = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                           max_len=16, batch=2, page=8)
    target.warmup(spec_k=2)
    draft.warmup()
    base = target.compile_count + draft.compile_count
    batcher = ContinuousBatcher(target, draft=draft, spec_k=2)
    try:
        streams = [batcher.submit(list(range(1, 2 + i % 4)),
                                  max_new_tokens=3 + i % 5, seed=i)
                   for i in range(8)]
        for s in streams:
            assert len(s.result(timeout_s=60)) >= 3
    finally:
        batcher.stop()
    assert target.compile_count + draft.compile_count == base


def test_arena_backpressure_queues_until_pages_free(params):
    """Admission is gated on the PAGE budget, not the slot map: with
    arena room for only one live request's prompt, the second waits
    QUEUED (never failed) and runs once the first finishes and frees
    its pages."""
    dec = PagedKVDecoder(params, heads=HEADS, max_len=64, batch=2,
                         page=8, arena_pages=6)     # 5 usable pages
    batcher = ContinuousBatcher(dec, default_timeout_s=60.0)
    try:
        # 24-token prompts need 3 pages at admission and 5 by the end
        # (budget 40) — two cannot be resident together in 5 pages
        a = batcher.submit([1] * 24, max_new_tokens=16)
        b = batcher.submit([2] * 24, max_new_tokens=16)
        assert len(a.result(timeout_s=60)) == 16
        assert len(b.result(timeout_s=60)) == 16
        assert b.first_token_step >= a.finish_step  # truly serialized
        snap = batcher.metrics.snapshot()
        assert snap["completed"] == 2 and snap["failed"] == 0
        assert snap["pages_used"] == 0 and snap["pages_total"] == 5
    finally:
        batcher.stop()


def test_never_servable_budget_names_arena(params):
    dec = PagedKVDecoder(params, heads=HEADS, max_len=32, batch=1,
                         page=8, arena_pages=4)     # 3 usable pages
    batcher = ContinuousBatcher(dec)
    try:
        # within max_len (32) but 4 pages > the 3 the arena holds:
        # rejected at submit, naming the arena (400, not a burned slot)
        with pytest.raises(ValueError, match="arena"):
            batcher.submit([1] * 8, max_new_tokens=24)
    finally:
        batcher.stop()


def test_mid_generation_exhaustion_evicts_grower_loudly(params):
    """When the arena runs dry mid-growth the GROWING request gets the
    error sentinel naming the arena, frees its pages, and everything
    else keeps decoding."""
    dec = PagedKVDecoder(params, heads=HEADS, max_len=32, batch=2,
                         page=8, arena_pages=5)     # 4 usable pages
    metrics = GenerateMetrics()
    batcher = ContinuousBatcher(dec, default_timeout_s=60.0,
                                metrics=metrics)
    try:
        # both admit at 1 page each; growth collides around row 8
        a = batcher.submit([1, 2], max_new_tokens=28)
        b = batcher.submit([3, 4], max_new_tokens=28)
        results = []
        for s in (a, b):
            try:
                results.append(("ok", len(s.result(timeout_s=60))))
            except GenerationError as exc:
                assert "arena exhausted" in str(exc)
                results.append(("evicted", len(s.tokens)))
        kinds = sorted(k for k, _ in results)
        assert kinds == ["evicted", "ok"], results
        # the survivor decoded its whole budget
        assert [n for k, n in results if k == "ok"] == [28]
        snap = metrics.snapshot()
        assert snap["completed"] == 1 and snap["failed"] == 1
        assert batcher.page_ledger()["pages_used"] == 0
    finally:
        batcher.stop()


def test_page_ledger_exact_after_chaos_drill(params):
    """Seeded ``generate.step`` crashes under concurrent paged+spec
    traffic: every admitted request still gets exactly one terminal
    event AND the arena page ledger closes — ``pages_used == Σ live
    slot pages`` (== 0 once drained), no orphaned pages."""
    from znicz_tpu.resilience import faults

    target = PagedKVDecoder(params, heads=HEADS, max_len=32, batch=2,
                            page=8, arena_pages=17)
    draft = PagedKVDecoder(truncate_draft(params, 1), heads=HEADS,
                           max_len=32, batch=2, page=8)
    metrics = GenerateMetrics()
    batcher = ContinuousBatcher(target, draft=draft, spec_k=2,
                                default_timeout_s=60.0, metrics=metrics)
    plan = faults.FaultPlan(seed=13)
    for hit in (3, 8):
        plan.crash_at("generate.step", at_hit=hit)
    outcomes: dict = {}
    lock = threading.Lock()

    def client(cid):
        stream = batcher.submit([1 + cid % 5, 2], max_new_tokens=6,
                                seed=cid)
        while True:
            event = stream.next_event(timeout=30)
            if event.get("done") or "error" in event:
                with lock:
                    outcomes[cid] = event
                return

    try:
        with faults.active(plan):
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
            assert len(plan.log) == 2, plan.log
            # the worker survived and the arena still serves
            assert len(batcher.submit([1], max_new_tokens=3)
                       .result(timeout_s=30)) == 3
        led = batcher.page_ledger()
        assert led["pages_used"] == led["pages_owned"] == 0, led
        assert led.get("draft_pages_used") == 0, led
        snap = metrics.snapshot()
        assert snap["admitted"] == 7
        assert snap["admitted"] == snap["completed"] + snap["failed"] \
            + snap["abandoned"]
    finally:
        batcher.stop()


def test_page_ledger_primitives():
    led = PageLedger(5)
    assert led.total == 4 and led.free == 4
    pages = led.alloc(3)
    assert 0 not in pages and led.used == 3 and led.peak_used == 3
    with pytest.raises(ArenaExhausted):
        led.alloc(2)
    led.release(pages[:1])
    with pytest.raises(ValueError, match="double free"):
        led.release(pages[:1])
    assert led.reclaim(pages[1:2]) == 1     # pages[2] was orphaned
    assert led.used == 1
    with pytest.raises(ValueError):
        PageLedger(1)


def test_paged_decoder_validation(params, paged_cache):
    with pytest.raises(ValueError, match="arena_pages"):
        PagedKVDecoder(params, heads=HEADS, max_len=32, batch=1,
                       page=8, arena_pages=1)
    dec = paged_cache(batch=2, page=8, arena_pages=17)
    with pytest.raises(ValueError, match="page view"):
        dec.decode_paged(np.zeros((2, 1), np.int32),
                         np.array([8, 0], np.int32),
                         np.zeros(2, np.int32))    # row 8 of an 8-row view
    with pytest.raises(ValueError, match="bucket"):
        dec.decode_paged(np.zeros((2, 3), np.int32),
                         np.zeros(2, np.int32), np.zeros(2, np.int32))


# -- HTTP: over-limit prompt is a 400 naming the configured limit -------------

def test_http_over_limit_prompt_is_400_naming_max_len(params):
    import json
    import urllib.error
    import urllib.request

    from znicz_tpu.serve import GenerateServer

    charmap = list("abcdefghijklmnopqrstuvwxyz .,!?")
    dec = PagedKVDecoder(params, heads=HEADS, max_len=32, batch=2,
                         page=8)
    server = GenerateServer(ContinuousBatcher(dec), charmap=charmap)
    port = server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "a" * 40,
                             "max_tokens": 8}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 400
        msg = json.loads(err.value.read())["error"]
        # names the configured limit, not an opaque failure — and the
        # rejection happened at admission, before any prefill
        assert "max_len 32" in msg and "--max-len" in msg
        assert server.metrics.snapshot()["admitted"] == 0
        assert dec.prefill_count == 0
    finally:
        server.stop()


# -- draft export / load ------------------------------------------------------

def test_export_lm_draft_roundtrip(params, tmp_path):
    from znicz_tpu.utils.export import export_lm, load_lm, load_lm_draft

    path = str(tmp_path / "lm.npz")
    draft = truncate_draft(params, 1)
    export_lm(params, path, heads=HEADS,
              charmap=list("abcdefghijklmnopqrstuvwxyz .,!?"),
              name="tiny", draft_params=draft)
    p2, meta = load_lm(path)
    assert meta["draft"] == {"n_layers": 1, "d": D, "heads": HEADS,
                             "ff": FF, "vocab": VOCAB}
    # the target pytree is untouched by the draft riding along
    assert len(p2["blocks"]) == N_LAYERS
    np.testing.assert_array_equal(p2["emb"], params["emb"])
    d2, dmeta = load_lm_draft(path)
    assert dmeta["n_layers"] == 1 and len(d2["blocks"]) == 1
    np.testing.assert_array_equal(d2["blocks"][0]["w1"],
                                  params["blocks"][0]["w1"])
    # draft-less packages answer (None, None), not an error
    plain = str(tmp_path / "plain.npz")
    export_lm(params, plain, heads=HEADS)
    assert load_lm_draft(plain) == (None, None)


def test_units_export_lm_ships_truncated_draft(params, tmp_path):
    from znicz_tpu.units.lm import TransformerLMStep
    from znicz_tpu.utils.export import load_lm_draft

    class FakeLoader:
        vocab = list("abcdefghijklmnopqrstuvwxyz .,!?")
        vocab_size = VOCAB

    step = TransformerLMStep(loader=FakeLoader(), n_layers=N_LAYERS,
                             d=D, heads=HEADS, ff=FF)
    step._params = params
    path = step.export_lm(str(tmp_path / "lm.npz"), draft_layers=1)
    dparams, dmeta = load_lm_draft(path)
    assert dmeta["n_layers"] == 1 and dmeta["heads"] == HEADS
    np.testing.assert_array_equal(dparams["head"], params["head"])
