"""Analytic FLOPs model (znicz_tpu/utils/flops.py) — tier-1 checks
against hand-computed GEMM/conv counts."""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice
from znicz_tpu.utils import flops


def _fc_workflow():
    from znicz_tpu.models.mnist_fc import build_fused
    prng.seed_all(3)
    w = build_fused(max_epochs=1, layers=(64,), minibatch_size=10,
                    n_train=100, n_valid=0)
    w.initialize(device=NumpyDevice())
    return w


def test_fc_forward_flops():
    w = _fc_workflow()
    batch = 32
    # 784 -> 64 -> 10
    expect = 2.0 * batch * (784 * 64 + 64 * 10)
    got = sum(flops.forward_flops(f, batch) for f in w.forwards)
    assert got == expect


def test_train_step_is_3x_forward():
    w = _fc_workflow()
    assert flops.train_step_flops(w.forwards, 8) == \
        3.0 * sum(flops.forward_flops(f, 8) for f in w.forwards)


def test_conv_forward_flops():
    from znicz_tpu.units.conv import ConvRELU
    from znicz_tpu.core.memory import Array

    prng.seed_all(3)
    conv = ConvRELU(None, n_kernels=16, kx=3, ky=3)
    conv.input = Array(np.zeros((4, 8, 8, 2), np.float32))
    conv.initialize(device=NumpyDevice())
    conv.run()
    out = conv.output.shape  # (4, Ho, Wo, 16)
    expect = 2.0 * 4 * out[1] * out[2] * 16 * (3 * 3 * 2)
    assert flops.forward_flops(conv, 4) == expect


def test_mfu_uses_peak_table():
    w = _fc_workflow()
    m = flops.mfu(1000.0, w.forwards, 32, gen="v5e")
    step = flops.train_step_flops(w.forwards, 32)
    assert m == (1000.0 / 32) * step / 197e12
    assert flops.mfu(1000.0, w.forwards, 32, gen="unknown-gen") is None
