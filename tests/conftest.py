"""Test harness: run everything on a virtual 8-device CPU platform.

SPMD/collective logic is CI-testable without TPU hardware via
XLA's host-platform device-count override (SURVEY.md §5 tier-3); the axon
sitecustomize pins jax_platforms to the TPU plugin, so we must both set the
flag before backend initialization and override the platform back to cpu.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

# ISSUE 7: the persistent compilation cache defaults ON in production but
# stays OFF under the suite unless a test configures it explicitly
# (tests/test_compilecache.py does, against tmp dirs).  A process-shared
# on-disk cache couples hundreds of tests through ~/.cache for no extra
# coverage, and XLA's concurrent cache-write path segfaulted (rarely) under
# the threaded serve tests on this box — one crash would abort the whole
# tier-1 process.  setdefault: an explicit env override still wins.
os.environ.setdefault("ZNICZ_TPU_COMPILE_CACHE", "off")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
