"""Native C++ loader-core tests (SURVEY.md §3.2 PRNG row + §4.1
fill_minibatch): build-on-first-use, gather parity with numpy, xorshift
stream sanity, shuffle permutation validity."""

import numpy as np
import pytest

from znicz_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.normal(size=(500, 37)).astype(np.float32)
    idx = np.concatenate([rng.integers(0, 500, 90),
                          np.full(10, -1)]).astype(np.int64)
    dst = np.empty((100, 37), np.float32)
    native.gather_rows(src, idx, dst)
    ref = np.zeros_like(dst)
    ref[:90] = src[idx[:90]]
    np.testing.assert_array_equal(dst, ref)


def test_gather_rows_multi_dim_and_threads():
    rng = np.random.default_rng(1)
    src = rng.normal(size=(256, 8, 8, 3)).astype(np.float32)
    idx = rng.integers(0, 256, 128).astype(np.int64)
    d1 = np.empty((128, 8, 8, 3), np.float32)
    d8 = np.empty_like(d1)
    native.gather_rows(src, idx, d1, n_threads=1)
    native.gather_rows(src, idx, d8, n_threads=8)
    np.testing.assert_array_equal(d1, src[idx])
    np.testing.assert_array_equal(d8, d1)


def test_xorshift_stream():
    gen = native.XorShift128P(42)
    u = gen.uniform(100_000)
    assert 0.0 <= u.min() and u.max() < 1.0
    assert abs(u.mean() - 0.5) < 0.01
    # deterministic per seed, advancing state
    gen2 = native.XorShift128P(42)
    np.testing.assert_array_equal(gen2.uniform(100_000), u)
    assert not np.array_equal(gen.uniform(8), gen.uniform(8))
    assert not np.array_equal(native.XorShift128P(43).uniform(100),
                              native.XorShift128P(42).uniform(100))


def test_native_shuffle_is_permutation():
    gen = native.XorShift128P(7)
    idx = np.arange(1000, dtype=np.int64)
    gen.shuffle(idx)
    assert not np.array_equal(idx, np.arange(1000))
    np.testing.assert_array_equal(np.sort(idx), np.arange(1000))


def test_loader_uses_native_gather():
    """FullBatchLoader minibatches are identical with/without the native
    path (bit-identical contract)."""
    from znicz_tpu.core import prng
    from znicz_tpu.loader.synthetic import SyntheticClassifierLoader

    def serve(force_numpy):
        prng.seed_all(5)
        loader = SyntheticClassifierLoader(
            None, n_classes=4, sample_shape=(9,), n_train=100, n_valid=40,
            minibatch_size=32)
        loader.initialize(device=None)
        if force_numpy:
            # strided view breaks contiguity -> numpy fallback
            loader.original_data.mem = np.asfortranarray(
                loader.original_data.mem)
        outs = []
        for _ in range(6):
            loader.run()
            outs.append(loader.minibatch_data.mem.copy())
        return outs

    a = serve(False)
    b = serve(True)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


# -- native inference runtime (libVeles/libZnicz rebuild) --------------------

def _export_trained(build, tmp_path, name, **kw):
    import os

    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.utils.export import export_forward

    prng.seed_all(7)
    w = build(**kw)
    w.initialize(device=TPUDevice())
    w.run()
    return export_forward(w, os.path.join(str(tmp_path), name))


def test_native_infer_fc_matches_python(tmp_path):
    """The C++ runtime loads a forward package standalone (ZIP + NPY +
    manifest all parsed natively) and reproduces the Python
    ExportedForward on an FC+softmax model."""
    from znicz_tpu.models import wine
    from znicz_tpu.native.infer import NativeForward, available
    from znicz_tpu.utils.export import ExportedForward

    if not available():
        pytest.skip("no native compiler/zlib")
    path = _export_trained(wine.build, tmp_path, "wine.npz", max_epochs=2,
                           n_train=60, n_valid=30, minibatch_size=10)
    py = ExportedForward(path)
    cc = NativeForward(path)
    x = np.random.default_rng(0).normal(size=(16, 13)).astype(np.float32)
    np.testing.assert_allclose(cc(x), np.asarray(py(x)).reshape(16, -1),
                               rtol=2e-4, atol=2e-5)
    # softmax rows normalize
    np.testing.assert_allclose(cc(x).sum(axis=1), 1.0, rtol=1e-5)


def test_native_infer_conv_stack_matches_python(tmp_path):
    """conv_relu -> max_pooling (default window stride) -> conv_relu ->
    max_pooling -> all2all_relu -> softmax, end to end vs Python."""
    from znicz_tpu.models import mnist_conv
    from znicz_tpu.native.infer import NativeForward, available
    from znicz_tpu.utils.export import ExportedForward

    if not available():
        pytest.skip("no native compiler/zlib")
    path = _export_trained(mnist_conv.build, tmp_path, "conv.npz",
                           max_epochs=1, n_train=200, n_valid=50,
                           minibatch_size=50)
    py = ExportedForward(path)
    cc = NativeForward(path)
    x = np.random.default_rng(1).normal(
        size=(8,) + py.input_shape).astype(np.float32)
    np.testing.assert_allclose(cc(x), np.asarray(py(x)).reshape(8, -1),
                               rtol=2e-3, atol=2e-4)


def test_native_infer_rejects_unsupported_layer(tmp_path):
    """A package with a layer outside the v1 forward set fails to LOAD
    with the type named — never a silent wrong answer."""
    import json
    import os

    from znicz_tpu.native.infer import NativeForward, available

    if not available():
        pytest.skip("no native compiler/zlib")
    meta = {"format": "znicz_tpu.forward", "version": 1, "name": "bad",
            "ema": False, "input_shape": [4, 4, 2],
            "arch": [{"type": "deconv", "config": {"n_kernels": 2,
                                                   "kx": 3, "ky": 3}}]}
    path = os.path.join(str(tmp_path), "bad.npz")
    with open(path, "wb") as f:
        np.savez_compressed(f, __arch__=np.array(json.dumps(meta)))
    with pytest.raises(ValueError, match="deconv"):
        NativeForward(path)


def _raw_pkg(tmp_path, name, arch, arrays, input_shape=(4, 4, 2)):
    import json
    import os

    meta = {"format": "znicz_tpu.forward", "version": 1, "name": "t",
            "ema": False, "input_shape": list(input_shape), "arch": arch}
    path = os.path.join(str(tmp_path), name)
    with open(path, "wb") as f:
        np.savez_compressed(f, __arch__=np.array(json.dumps(meta)),
                            **arrays)
    return path


def test_native_infer_pooling_default_geometry(tmp_path):
    """A bare {"type": "max_pooling"} config means kx=ky=2 with stride =
    window (the Pooling units' Python defaults) — must load and match the
    oracle, not divide by zero."""
    from znicz_tpu.native.infer import NativeForward, available
    from znicz_tpu.ops import pooling as pool_ops

    if not available():
        pytest.skip("no native compiler/zlib")
    p = _raw_pkg(tmp_path, "pool.npz",
                 [{"type": "max_pooling", "config": {}}], {}, (5, 5, 3))
    x = np.random.default_rng(3).normal(size=(2, 5, 5, 3)).astype(
        np.float32)
    ref, _ = pool_ops.max_forward(np, x, 2, 2, 2, 2)
    np.testing.assert_allclose(NativeForward(p)(x), ref.reshape(2, -1),
                               rtol=1e-6)


def test_native_infer_weights_transposed(tmp_path):
    """weights_transposed fc layers (stored (out, in), applied as W.T —
    All2All.xla_apply_linear) are honored by a load-time transpose."""
    from znicz_tpu.native.infer import NativeForward, available

    if not available():
        pytest.skip("no native compiler/zlib")
    rng = np.random.default_rng(4)
    w_t = rng.normal(size=(6, 32)).astype(np.float32)   # (out, in)
    p = _raw_pkg(tmp_path, "wt.npz",
                 [{"type": "all2all",
                   "config": {"output_sample_shape": 6,
                              "weights_transposed": True}}],
                 {"0.weights": w_t}, (4, 4, 2))
    x = rng.normal(size=(3, 4, 4, 2)).astype(np.float32)
    ref = x.reshape(3, -1) @ w_t.T
    np.testing.assert_allclose(NativeForward(p)(x), ref, rtol=1e-5,
                               atol=1e-6)


def test_native_infer_malformed_packages_fail_closed(tmp_path):
    """Structurally broken packages fail at LOAD with a named reason —
    never UB, never a silent wrong answer."""
    from znicz_tpu.native.infer import NativeForward, available

    if not available():
        pytest.skip("no native compiler/zlib")
    cases = [
        # fc without weights
        ([{"type": "all2all", "config": {"output_sample_shape": 4}}], {}),
        # arch entry without a type key
        ([{"config": {}}], {}),
        # conv weights disagreeing with declared geometry
        ([{"type": "conv", "config": {"n_kernels": 4, "kx": 3, "ky": 3}}],
         {"0.weights": np.zeros((5, 5, 2, 4), np.float32)}),
        # fc weight rows != input features
        ([{"type": "all2all", "config": {"output_sample_shape": 4}}],
         {"0.weights": np.zeros((7, 4), np.float32)}),
    ]
    for i, (arch, arrays) in enumerate(cases):
        p = _raw_pkg(tmp_path, f"bad{i}.npz", arch, arrays)
        with pytest.raises(ValueError):
            NativeForward(p)


def test_native_infer_closed_handle_raises(tmp_path):
    from znicz_tpu.native.infer import NativeForward, available

    if not available():
        pytest.skip("no native compiler/zlib")
    rng = np.random.default_rng(5)
    p = _raw_pkg(tmp_path, "ok.npz",
                 [{"type": "all2all", "config": {"output_sample_shape": 3}}],
                 {"0.weights": rng.normal(size=(32, 3)).astype(np.float32)})
    nf = NativeForward(p)
    nf(np.zeros((1, 4, 4, 2), np.float32))
    nf.close()
    with pytest.raises(RuntimeError, match="closed"):
        nf(np.zeros((1, 4, 4, 2), np.float32))
