"""Native C++ loader-core tests (SURVEY.md §3.2 PRNG row + §4.1
fill_minibatch): build-on-first-use, gather parity with numpy, xorshift
stream sanity, shuffle permutation validity."""

import numpy as np
import pytest

from znicz_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.normal(size=(500, 37)).astype(np.float32)
    idx = np.concatenate([rng.integers(0, 500, 90),
                          np.full(10, -1)]).astype(np.int64)
    dst = np.empty((100, 37), np.float32)
    native.gather_rows(src, idx, dst)
    ref = np.zeros_like(dst)
    ref[:90] = src[idx[:90]]
    np.testing.assert_array_equal(dst, ref)


def test_gather_rows_multi_dim_and_threads():
    rng = np.random.default_rng(1)
    src = rng.normal(size=(256, 8, 8, 3)).astype(np.float32)
    idx = rng.integers(0, 256, 128).astype(np.int64)
    d1 = np.empty((128, 8, 8, 3), np.float32)
    d8 = np.empty_like(d1)
    native.gather_rows(src, idx, d1, n_threads=1)
    native.gather_rows(src, idx, d8, n_threads=8)
    np.testing.assert_array_equal(d1, src[idx])
    np.testing.assert_array_equal(d8, d1)


def test_xorshift_stream():
    gen = native.XorShift128P(42)
    u = gen.uniform(100_000)
    assert 0.0 <= u.min() and u.max() < 1.0
    assert abs(u.mean() - 0.5) < 0.01
    # deterministic per seed, advancing state
    gen2 = native.XorShift128P(42)
    np.testing.assert_array_equal(gen2.uniform(100_000), u)
    assert not np.array_equal(gen.uniform(8), gen.uniform(8))
    assert not np.array_equal(native.XorShift128P(43).uniform(100),
                              native.XorShift128P(42).uniform(100))


def test_native_shuffle_is_permutation():
    gen = native.XorShift128P(7)
    idx = np.arange(1000, dtype=np.int64)
    gen.shuffle(idx)
    assert not np.array_equal(idx, np.arange(1000))
    np.testing.assert_array_equal(np.sort(idx), np.arange(1000))


def test_loader_uses_native_gather():
    """FullBatchLoader minibatches are identical with/without the native
    path (bit-identical contract)."""
    from znicz_tpu.core import prng
    from znicz_tpu.loader.synthetic import SyntheticClassifierLoader

    def serve(force_numpy):
        prng.seed_all(5)
        loader = SyntheticClassifierLoader(
            None, n_classes=4, sample_shape=(9,), n_train=100, n_valid=40,
            minibatch_size=32)
        loader.initialize(device=None)
        if force_numpy:
            # strided view breaks contiguity -> numpy fallback
            loader.original_data.mem = np.asfortranarray(
                loader.original_data.mem)
        outs = []
        for _ in range(6):
            loader.run()
            outs.append(loader.minibatch_data.mem.copy())
        return outs

    a = serve(False)
    b = serve(True)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
