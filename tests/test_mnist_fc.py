"""Tier-2 functional test: the minimum end-to-end slice (SURVEY.md §8 step 2)
— an MNIST-shaped FC workflow (All2AllTanh -> All2AllSoftmax ->
EvaluatorSoftmax -> DecisionGD -> GDSoftmax -> GDTanh) converging under the
Repeater loop, deterministic across runs with the same seed.

Wiring mirrors the reference call stack (SURVEY.md §4.1):
Repeater -> Loader -> forwards -> Evaluator -> Decision -> gds (reverse) ->
Repeater, with end_point gated on ~decision.complete and gds skipped on
non-train minibatches.
"""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.plumbing import Repeater
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.loader.synthetic import SyntheticClassifierLoader
from znicz_tpu.units.all2all import All2AllSoftmax, All2AllTanh
from znicz_tpu.units.decision import DecisionGD
from znicz_tpu.units.evaluator import EvaluatorSoftmax
from znicz_tpu.units.gd import GDSoftmax, GDTanh
from znicz_tpu.units.nn_units import NNWorkflow


def build_fc_workflow(max_epochs=4, lr=0.05):
    w = NNWorkflow(name="MnistFC")
    w.repeater = Repeater(w)
    loader = w.loader = SyntheticClassifierLoader(
        w, n_classes=10, sample_shape=(28, 28), n_train=600, n_valid=200,
        minibatch_size=50, spread=2.5, noise=1.0)
    fc1 = All2AllTanh(w, output_sample_shape=64, name="fc1")
    fc2 = All2AllSoftmax(w, output_sample_shape=10, name="fc2")
    w.forwards = [fc1, fc2]
    ev = w.evaluator = EvaluatorSoftmax(w)
    dec = w.decision = DecisionGD(w, max_epochs=max_epochs)
    gd2 = GDSoftmax(w, learning_rate=lr, gradient_moment=0.9, name="gd2")
    gd1 = GDTanh(w, learning_rate=lr, gradient_moment=0.9, name="gd1")
    w.gds = [gd1, gd2]

    # control chain (reference §4.1 hot loop)
    w.repeater.link_from(w.start_point)
    loader.link_from(w.repeater)
    fc1.link_from(loader)
    fc2.link_from(fc1)
    ev.link_from(fc2)
    dec.link_from(ev)
    gd2.link_from(dec)
    gd1.link_from(gd2)
    w.repeater.link_from(gd1)
    # end after the full backward chain so the last minibatch is symmetric
    w.end_point.link_from(gd1)
    w.end_point.gate_block = ~dec.complete

    # gradient units run on train minibatches only
    for gd in (gd1, gd2):
        gd.gate_skip = Bool(lambda: int(loader.minibatch_class) != TRAIN)

    # data links
    fc1.link_attrs(loader, ("input", "minibatch_data"))
    fc2.link_attrs(fc1, ("input", "output"))
    ev.link_attrs(fc2, "output", "max_idx")
    ev.link_attrs(loader, ("labels", "minibatch_labels"),
                  ("batch_size", "minibatch_size"))
    dec.link_attrs(loader, "minibatch_class", "last_minibatch",
                   "class_lengths", "epoch_number", "minibatch_size")
    dec.link_attrs(ev, ("minibatch_n_err", "n_err"))
    dec.evaluator = ev
    gd2.link_from_forward(fc2)
    gd2.link_attrs(ev, "err_output")
    gd2.link_attrs(loader, ("batch_size", "minibatch_size"))
    gd1.link_from_forward(fc1)
    gd1.link_attrs(gd2, ("err_output", "err_input"))
    gd1.link_attrs(loader, ("batch_size", "minibatch_size"))
    return w


def run_workflow(device, seed=123, max_epochs=4):
    prng.seed_all(seed)
    w = build_fc_workflow(max_epochs=max_epochs)
    w.initialize(device=device)
    w.run()
    return w


@pytest.mark.parametrize("device_cls", [NumpyDevice, TPUDevice])
def test_fc_workflow_converges(device_cls):
    w = run_workflow(device_cls())
    dec = w.decision
    assert bool(dec.complete)
    assert len(dec.metrics_history) == 4
    # synthetic blobs are nearly separable: validation error must collapse
    first = dec.metrics_history[0]["metric_validation"]
    last = dec.metrics_history[-1]["metric_validation"]
    assert last < first, (first, last)
    assert dec.epoch_n_err_pt[1] < 15.0, dec.metrics_history


def test_fc_workflow_deterministic():
    h1 = run_workflow(TPUDevice(), seed=7, max_epochs=2)
    h2 = run_workflow(TPUDevice(), seed=7, max_epochs=2)
    assert h1.decision.metrics_history == h2.decision.metrics_history
    np.testing.assert_array_equal(h1.forwards[0].weights.map_read(),
                                  h2.forwards[0].weights.map_read())


def test_fc_workflow_backends_agree():
    """numpy oracle vs XLA backend: same seed, same epoch error counts
    (float32 GEMM on CPU-XLA matches numpy within integer-count tolerance)."""
    h_np = run_workflow(NumpyDevice(), seed=11, max_epochs=2)
    h_x = run_workflow(TPUDevice(), seed=11, max_epochs=2)
    for m_np, m_x in zip(h_np.decision.metrics_history,
                         h_x.decision.metrics_history):
        assert abs(m_np["metric_validation"] - m_x["metric_validation"]) <= 2


def test_evaluator_class_weights_scale_err_output():
    """class_weights scales each err_output row by its TRUE class's
    weight; n_err stays the unweighted count (reference semantics)."""
    from znicz_tpu.core.workflow import Workflow

    w = Workflow(name="cw")
    y = np.array([[0.7, 0.2, 0.1],
                  [0.1, 0.8, 0.1],
                  [0.3, 0.3, 0.4]], np.float32)
    labels = np.array([0, 2, 2], np.int32)
    weights = np.array([1.0, 1.0, 3.0], np.float32)

    def build_eval(**kw):
        ev = EvaluatorSoftmax(w, compute_confusion_matrix=False, **kw)
        ev.output.mem = y.copy()
        ev.labels.mem = labels.copy()
        ev.batch_size = 3
        ev.initialize(device=NumpyDevice())
        ev.run()
        return ev

    plain = build_eval()
    weighted = build_eval(class_weights=weights)
    scale = weights[labels][:, None]
    np.testing.assert_allclose(weighted.err_output.mem,
                               plain.err_output.mem * scale, rtol=1e-6)
    assert weighted.n_err == plain.n_err == 1


def test_class_weights_fused_matches_eager():
    """One weighted TRAIN minibatch through the eager unit chain and the
    fused AD step must produce identical weight updates — the class
    weighting enters via err_output scaling in one and via the loss term
    in the other."""
    from znicz_tpu.standard_workflow import StandardWorkflow

    cw = [0.5, 2.0, 1.0]
    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
         "<-": {"learning_rate": 0.1, "gradient_moment": 0.0}},
        {"type": "softmax", "->": {"output_sample_shape": 3},
         "<-": {"learning_rate": 0.1, "gradient_moment": 0.0}},
    ]
    loader_cfg = {"n_classes": 3, "sample_shape": (8,), "n_train": 60,
                  "n_valid": 0, "minibatch_size": 30, "spread": 2.0}

    def one_step(fused, device):
        prng.seed_all(123)
        w = StandardWorkflow(
            name="CW", layers=[dict(d) for d in layers],
            loss_function="softmax",
            evaluator_config={"class_weights": cw},
            loader_name="synthetic_classifier", loader_config=loader_cfg,
            decision_config={"max_epochs": 1}, fused=fused)
        w.initialize(device=device)
        w.loader.run()
        if fused:
            w.step.run()
            w.step.sync_to_units()
        else:
            for f in w.forwards:
                f.run()
            w.evaluator.run()
            for gd in reversed(w.gds):
                gd.run()
        return w

    we = one_step(False, NumpyDevice())
    wf = one_step(True, TPUDevice())
    for i, (fe, ff) in enumerate(zip(we.forwards, wf.forwards)):
        np.testing.assert_allclose(
            ff.weights.map_read(), fe.weights.map_read(),
            rtol=1e-4, atol=1e-5, err_msg=f"layer {i} weights")
        np.testing.assert_allclose(
            ff.bias.map_read(), fe.bias.map_read(),
            rtol=1e-4, atol=1e-5, err_msg=f"layer {i} bias")
    # and the weighting really changed the update (vs unweighted run)
    prng.seed_all(123)
    w0 = StandardWorkflow(
        name="CW0", layers=[dict(d) for d in layers],
        loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=loader_cfg,
        decision_config={"max_epochs": 1}, fused=True)
    w0.initialize(device=TPUDevice())
    w0.loader.run()
    w0.step.run()
    w0.step.sync_to_units()
    assert not np.allclose(w0.forwards[-1].weights.map_read(),
                           wf.forwards[-1].weights.map_read())


def test_class_weights_misconfiguration_fails_loudly():
    """Wrong-length weight vectors and misplaced/typo'd evaluator_config
    keys must raise, not train silently unweighted (XLA's clamped gather
    would otherwise hide both)."""
    import pytest

    from znicz_tpu.standard_workflow import StandardWorkflow

    layers = [{"type": "softmax", "->": {"output_sample_shape": 3},
               "<-": {"learning_rate": 0.1}}]
    cfg = {"n_classes": 3, "sample_shape": (6,), "n_train": 30,
           "n_valid": 0, "minibatch_size": 10}

    with pytest.raises(ValueError, match="not accepted"):
        StandardWorkflow(
            name="bad-key", layers=[dict(d) for d in layers],
            loss_function="softmax",
            evaluator_config={"class_weight": [1, 1, 1]},   # typo'd key
            loader_name="synthetic_classifier", loader_config=dict(cfg))

    prng.seed_all(5)
    w = StandardWorkflow(
        name="bad-len", layers=[dict(d) for d in layers],
        loss_function="softmax",
        evaluator_config={"class_weights": [1.0, 2.0]},     # 2 for 3
        loader_name="synthetic_classifier", loader_config=dict(cfg))
    with pytest.raises(ValueError, match="entries"):
        w.initialize(device=NumpyDevice())


def test_fused_confusion_matrix_matches_eager():
    """Fused workflows tally the same per-class-pass confusion matrixes
    the eager evaluator produces (Decision owns collection + reset)."""
    from znicz_tpu.loader.base import TRAIN, VALID
    from znicz_tpu.standard_workflow import StandardWorkflow

    layers = [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 12},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.0}},
        {"type": "softmax", "->": {"output_sample_shape": 4},
         "<-": {"learning_rate": 0.05, "gradient_moment": 0.0}},
    ]
    cfg = {"n_classes": 4, "sample_shape": (6,), "n_train": 80,
           "n_valid": 40, "minibatch_size": 20, "spread": 1.5}

    def run(fused, device):
        prng.seed_all(44)
        w = StandardWorkflow(
            name="conf", layers=[dict(d) for d in layers],
            loss_function="softmax", loader_name="synthetic_classifier",
            loader_config=dict(cfg), decision_config={"max_epochs": 1},
            fused=fused)
        w.initialize(device=device)
        w.run()
        return w

    we = run(False, NumpyDevice())
    wf = run(True, TPUDevice())
    for cls in (VALID, TRAIN):
        me = we.decision.confusion_matrixes[cls]
        mf = wf.decision.confusion_matrixes[cls]
        assert me is not None and mf is not None
        expected = cfg["n_train"] if cls == TRAIN else cfg["n_valid"]
        assert me.sum() == expected
        # column sums = per-class label counts: data-determined, exact on
        # any backend; cell values may differ by boundary-sample flips
        # between numpy and XLA float trajectories (precedent:
        # test_fc_workflow_backends_agree's +/-2 tolerance)
        np.testing.assert_array_equal(mf.sum(axis=0), me.sum(axis=0),
                                      err_msg=f"class {cls} label counts")
        assert np.abs(mf - me).sum() <= 4, (cls, mf, me)


def test_fused_confusion_matrix_survives_midpass_flush():
    """A probe calling flush_metrics() mid class pass must not
    double-count the already-published minibatches (deferred mode keeps
    cumulative sums; only the delta may fold in)."""
    from znicz_tpu.loader.base import TRAIN
    from znicz_tpu.standard_workflow import StandardWorkflow

    layers = [{"type": "softmax", "->": {"output_sample_shape": 3},
               "<-": {"learning_rate": 0.05}}]
    cfg = {"n_classes": 3, "sample_shape": (5,), "n_train": 60,
           "n_valid": 0, "minibatch_size": 20, "spread": 2.0}
    prng.seed_all(11)
    w = StandardWorkflow(
        name="flush", layers=layers, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=dict(cfg),
        decision_config={"max_epochs": 1}, fused=True)
    w.initialize(device=TPUDevice())
    # run the pass by hand, flushing after every minibatch
    while True:
        w.loader.run()
        w.step.run()
        w.step.flush_metrics()
        w.step.flush_metrics()      # repeated probe: still no double count
        if bool(w.loader.last_minibatch):
            break
    w.decision.run()
    mat = w.decision.confusion_matrixes[TRAIN]
    assert mat is not None and mat.sum() == cfg["n_train"], mat


def test_evaluator_mse_nearest_target_unit():
    """Direct nearest-target check: hand-set prototypes, outputs nearer
    the wrong prototype count as errors; padded rows do not."""
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.units.evaluator import EvaluatorMSE

    w = Workflow(name="nt")
    ev = EvaluatorMSE(w)
    protos = np.array([[0.0, 0.0], [10.0, 10.0]], np.float32)
    ev.output.mem = np.array([[0.1, 0.2],     # -> proto 0, label 0: ok
                              [9.0, 9.5],     # -> proto 1, label 0: ERR
                              [9.9, 9.9],     # padded row: would be an
                              ], np.float32)  # error if mask broke
    ev.target.mem = protos[[0, 0, 1]]
    # padded row's label DISAGREES with its nearest prototype, so a
    # batch_size-mask regression flips n_err to 2
    ev.labels.mem = np.array([0, 0, 0], np.int32)
    ev.class_targets.mem = protos
    ev.batch_size = 2
    ev.initialize(device=NumpyDevice())
    ev.run()
    assert ev._classifies
    assert ev.n_err == 1
    assert ev.rmse > 0.0
