"""Multi-host smoke (VERDICT r2 weak #6): ``launcher.multihost`` must
actually execute — two CPU processes join via ``jax.distributed`` and run
one cross-process psum, proving the coordinator wiring and the SPMD
peer-process model (SURVEY.md §3.4: every process runs the same
standalone path)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ.pop("XLA_FLAGS", None)       # 1 local cpu device per proc
    import jax
    jax.config.update("jax_platforms", "cpu")
    from znicz_tpu.launcher import multihost

    pid = int(sys.argv[1])
    multihost({coord!r}, num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2, jax.device_count()
    import numpy as np
    x = np.asarray([float(10 + pid)], np.float32)
    try:
        total = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x)
    except Exception as exc:
        if "aren't implemented on the CPU backend" in str(exc):
            # the coordinator wiring IS proven (process/device counts
            # above); only the cross-process collective itself is
            # unsupported by this XLA CPU build
            print("PSUM_UNSUPPORTED", flush=True)
            sys.exit(0)
        raise
    print("PSUM", float(total[0]), flush=True)
""")


def test_two_process_multihost_psum(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "child.py"
    script.write_text(CHILD.format(repo=REPO, coord=coord))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen([sys.executable, str(script), str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, (out, err)
        outs.append(out)
    if any("PSUM_UNSUPPORTED" in out for out in outs):
        pytest.skip("this XLA CPU build has no cross-process collectives "
                    "(coordinator wiring verified: 2 processes joined)")
    # 10 + 11 summed over the two processes, seen by both
    for out in outs:
        assert "PSUM 21.0" in out, outs
