"""Elastic multi-process training (ISSUE 9): the fleet supervisor
kills-and-resumes REAL worker processes.

The acceptance drill pins the cross-process analog of PR 2's in-process
contract: 2 CPU workers joined via ``launcher.multihost``, one
SIGKILL'd mid-epoch at a seeded step (``elastic.worker`` fault site,
armed through the ``ZNICZ_TPU_FAULT_PLAN`` worker env), supervised
resume at world size 1 AND at world size 2 — and the resumed metric
history is bit-identical to an uninterrupted run at the final world
size.  Satellites covered here: coordinator-connect retry, SIGTERM
snapshot-then-exit, rank-0-writes/all-ranks-verify snapshot election,
fault-plan env serialization, heartbeat hang detection.
"""

import glob
import importlib.util
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.launcher import (CoordinatorUnreachable, multihost,
                                wait_for_coordinator)
from znicz_tpu.observe import probe
from znicz_tpu.resilience import faults
from znicz_tpu.resilience.elastic import (ElasticExhausted, run_elastic,
                                          start_heartbeat)
from znicz_tpu.resilience.retry import RetryPolicy
from znicz_tpu.resilience.supervisor import SupervisorPolicy
from znicz_tpu.snapshotter import process_rank_world, verify_snapshot
from znicz_tpu.standard_workflow import StandardWorkflow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKFLOW = os.path.join(REPO, "tools", "elastic_workflow.py")
EPOCHS = 6

#: the drill's seeded randomness (ISSUE 9 acceptance: "SIGKILL one
#: mid-epoch at a seeded step"): the kill step is drawn from a seeded
#: generator; the victim is rank 0 BY DESIGN — killing the snapshot
#: WRITER is the harder case (it also takes the jax.distributed
#: coordinator service down with it), and it makes the resume point
#: deterministic: no other rank writes, so the newest snapshot is
#: exactly the one before the victim's seeded death, immune to
#: boot/compile skew between the workers
KILL_AT_HIT = int(np.random.default_rng(1234).integers(40, 70))
VICTIM_RANK = 0                                       # the writer


def worker_env(epochs=EPOCHS, snap_dir=None):
    """Env for worker subprocesses: single local CPU device per process
    (the 8-device XLA_FLAGS override would be inherited), compile cache
    off (XLA's concurrent cache-write path is flaky on shared dirs —
    see conftest), repo importable."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["ZNICZ_TPU_COMPILE_CACHE"] = "off"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ZNICZ_TPU_ELASTIC_EPOCHS"] = str(epochs)
    if snap_dir is not None:
        env["ZNICZ_TPU_SNAP_DIR"] = str(snap_dir)
    return env


def read_history(snap_dir, rank=0):
    with open(os.path.join(str(snap_dir), f"history_{rank}.json")) as f:
        return json.load(f)["history"]


def fast_policy(**kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("max_restarts", 2)
    return SupervisorPolicy(**kw)


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    yield
    faults.uninstall()


# -- fault-plan env serialization (satellite) --------------------------------

def test_fault_plan_env_roundtrip():
    plan = faults.FaultPlan(seed=9)
    plan.kill_at("elastic.worker", at_hit=33)
    plan.hang_at("workflow.step", at_hit=2, seconds=7.5, once=False)
    clone = faults.FaultPlan.from_env(plan.to_env())
    assert clone.seed == 9
    assert [(f.site, f.action, f.at_hit, f.seconds, f.once)
            for f in clone._faults] == \
        [("elastic.worker", "kill", 33, 30.0, True),
         ("workflow.step", "hang", 2, 7.5, False)]


def test_fault_plan_with_predicate_refuses_to_serialize():
    plan = faults.FaultPlan().crash_at("workflow.step",
                                       when=lambda **ctx: True)
    with pytest.raises(ValueError, match="predicate"):
        plan.to_env()


def test_fault_plan_env_install_is_loud_on_garbage(monkeypatch):
    monkeypatch.setenv(faults.PLAN_ENV_VAR, "{not json")
    with pytest.raises(ValueError, match="malformed"):
        faults.install_from_env()
    monkeypatch.delenv(faults.PLAN_ENV_VAR)
    assert faults.install_from_env() is None


def test_fault_plan_env_fires_in_subprocess(tmp_path):
    """The cross-process determinism contract: a plan serialized into a
    worker's env fires at exactly the armed hit in that process — the
    mechanism the elastic kill drill rides (jax-free, milliseconds)."""
    code = (
        "from znicz_tpu.resilience import faults\n"
        "plan = faults.install_from_env()\n"
        "assert plan is not None\n"
        "faults.fault_hook('drill.site')\n"
        "try:\n"
        "    faults.fault_hook('drill.site')\n"
        "    print('MISSED')\n"
        "except faults.FaultInjected as exc:\n"
        "    print('FIRED', exc)\n")
    env = worker_env()
    env[faults.PLAN_ENV_VAR] = \
        faults.FaultPlan(seed=3).crash_at("drill.site", at_hit=2).to_env()
    out = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "FIRED" in out.stdout and "hit 2" in out.stdout


# -- coordinator-connect retry (satellite) -----------------------------------

def test_wait_for_coordinator_exhaustion_names_the_address():
    policy = RetryPolicy(max_attempts=3, base_delay=0.01,
                         sleep=lambda s: None)
    with pytest.raises(CoordinatorUnreachable, match="127.0.0.1:1 "):
        wait_for_coordinator("127.0.0.1:1", policy)
    assert policy.total_attempts == 3


def test_wait_for_coordinator_retries_until_listener_up():
    """The race multihost() actually loses: rank N boots before the
    rank-0 coordinator binds.  The probe retries until the listener
    appears instead of handing jax.distributed a dead address (which
    this jaxlib answers with a process abort, not an exception)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    server = socket.socket()
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)

    def bind_late():
        time.sleep(0.3)
        server.bind(("127.0.0.1", port))
        server.listen(1)

    t = threading.Thread(target=bind_late, daemon=True)
    t.start()
    policy = RetryPolicy(max_attempts=40, base_delay=0.05, jitter=0.0)
    try:
        wait_for_coordinator(f"127.0.0.1:{port}", policy)
    finally:
        t.join()
        server.close()
    assert policy.total_retries >= 1


def test_multihost_rejects_malformed_coordinator():
    with pytest.raises(ValueError, match="host:port"):
        multihost("nonsense", num_processes=2, process_id=1)


# -- snapshot election (tentpole: rank 0 writes, all ranks verify) -----------

# one source of truth for the drill topology: the in-process election
# tests must exercise the SAME model/loader the subprocess drills run
_spec = importlib.util.spec_from_file_location("elastic_workflow",
                                               WORKFLOW)
_drill_module = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_drill_module)
LAYERS, LOADER = _drill_module.LAYERS, _drill_module.LOADER


def build_local(max_epochs, snap_dir, verify_timeout=0.3, seed=77):
    prng.seed_all(seed)
    w = StandardWorkflow(
        name="ElectTest", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": max_epochs},
        snapshotter_config={"directory": str(snap_dir), "prefix": "t",
                            "only_improved": False, "keep_all": True,
                            "verify_timeout": verify_timeout})
    w.initialize(device=TPUDevice())
    return w


def _published(snap_dir):
    return sorted(os.path.basename(p) for p in
                  glob.glob(os.path.join(str(snap_dir), "t_*.npz"))
                  if not p.endswith("_latest.npz"))


def test_rank_nonzero_never_writes_and_verifies_published(tmp_path,
                                                          monkeypatch):
    assert process_rank_world() == (0, 1)
    # rank 0 publishes the ground truth
    w0 = build_local(2, tmp_path)
    w0.run()
    assert _published(tmp_path) == ["t_1.npz", "t_2.npz"]
    written = {p: os.path.getmtime(os.path.join(str(tmp_path), p))
               for p in _published(tmp_path)}
    # an identical replicated rank-1 worker verifies instead of writing
    monkeypatch.setenv("ZNICZ_TPU_ELASTIC_RANK", "1")
    monkeypatch.setenv("ZNICZ_TPU_ELASTIC_WORLD", "2")
    assert process_rank_world() == (1, 2)
    w1 = build_local(2, tmp_path)
    w1.run()
    assert _published(tmp_path) == ["t_1.npz", "t_2.npz"]   # no new files
    for p, mtime in written.items():
        assert os.path.getmtime(os.path.join(str(tmp_path), p)) == mtime
    assert w1.snapshotter.verified_ok == 2
    assert w1.snapshotter.verified_failed == 0


def test_rank_nonzero_missing_snapshot_degrades_to_warning(tmp_path,
                                                           monkeypatch):
    """A dead rank 0 must not kill the verifiers: the wait times out,
    warns, and training continues (the fleet supervisor owns the
    failure)."""
    monkeypatch.setenv("ZNICZ_TPU_ELASTIC_RANK", "1")
    monkeypatch.setenv("ZNICZ_TPU_ELASTIC_WORLD", "2")
    w = build_local(2, tmp_path, verify_timeout=0.2)
    w.run()                                     # completes regardless
    assert len(w.decision.metrics_history) == 2
    assert _published(tmp_path) == []
    assert w.snapshotter.verified_failed == 2


# -- SIGTERM -> snapshot-then-exit (tentpole: launcher) ----------------------

def test_sigterm_worker_snapshots_and_exits_143(tmp_path):
    env = worker_env(epochs=200, snap_dir=tmp_path)   # far horizon
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", WORKFLOW], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not glob.glob(
                os.path.join(str(tmp_path), "ew_[0-9]*.npz")):
            if proc.poll() is not None:
                out, _ = proc.communicate()
                raise AssertionError(f"worker died early: {out}")
            time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 143, out
    assert "SIGTERM" in out
    snaps = glob.glob(os.path.join(str(tmp_path), "ew_[0-9]*.npz"))
    assert snaps and all(verify_snapshot(p) for p in snaps)
    # terminated-as-asked is NOT completion: no history epilogue
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           "history_0.json"))


# -- the acceptance drill ----------------------------------------------------

@pytest.fixture(scope="module")
def baseline_ws1(tmp_path_factory):
    """Uninterrupted single-process run of the drill workflow."""
    snap = tmp_path_factory.mktemp("base_ws1")
    out = subprocess.run(
        [sys.executable, "-m", "znicz_tpu", WORKFLOW],
        env=worker_env(snap_dir=snap), cwd=REPO, capture_output=True,
        text=True, timeout=300)
    assert out.returncode == 0, out.stdout
    return read_history(snap)


@pytest.fixture(scope="module")
def baseline_ws2(tmp_path_factory):
    """Uninterrupted 2-worker fleet (multihost-joined), no faults."""
    snap = tmp_path_factory.mktemp("base_ws2")
    report = run_elastic(
        [WORKFLOW], str(snap), workers=2, prefix="ew",
        policy=SupervisorPolicy(max_restarts=0, sleep=lambda s: None),
        env=worker_env(), term_grace=6.0, round_timeout=300.0)
    assert report.completed and report.restarts == 0
    h0 = read_history(snap, 0)
    if os.path.exists(os.path.join(str(snap), "history_1.json")):
        assert read_history(snap, 1) == h0, "replicated workers diverged"
    else:
        # rank 1 lagged past the straggler grace and was reaped after
        # rank 0 (the history owner) completed — still a clean round
        assert report.rounds[-1]["stragglers"] == [1]
    return h0


def test_uninterrupted_history_is_world_size_invariant(baseline_ws1,
                                                       baseline_ws2):
    """The drill workflow is replicated data-parallel: every world size
    computes the same history, which is what makes "bit-identical to an
    uninterrupted run at the final world size" one well-defined pin."""
    assert baseline_ws1 == baseline_ws2
    assert len(baseline_ws1) == EPOCHS
    # the loader is tuned so the error curve is NON-trivial: an all-zero
    # history would let a broken resume pass the bit-exactness assert
    assert any(row.get("metric_validation") for row in baseline_ws1)


@pytest.mark.parametrize("label,world_sizes", [("resume_ws1", [2, 1]),
                                               ("resume_ws2", [2, 2])])
def test_elastic_drill_seeded_kill_bit_exact_resume(tmp_path, label,
                                                    world_sizes,
                                                    baseline_ws1):
    """ISSUE 9 acceptance: 2 CPU workers, worker VICTIM_RANK SIGKILL'd
    mid-epoch at seeded step KILL_AT_HIT, fleet resumes at the new world
    size from the newest valid snapshot, and the final metric history is
    bit-identical to the uninterrupted run.  One flight artifact per
    restart; the znicz_elastic_* counters move by exactly the drill's
    event counts."""
    counts0 = probe.elastic_counts()
    snap = tmp_path / label
    plan = faults.FaultPlan(seed=1234).kill_at("elastic.worker",
                                               at_hit=KILL_AT_HIT)
    report = run_elastic(
        [WORKFLOW], str(snap), workers=2, world_sizes=world_sizes,
        prefix="ew", policy=fast_policy(),
        env=worker_env(), fault_plans={VICTIM_RANK: plan},
        term_grace=8.0, round_timeout=300.0)
    counts = probe.elastic_counts()
    assert report.completed
    assert report.restarts == 1
    assert report.world_size == world_sizes[-1]
    # the victim actually died of SIGKILL (returncode -9), mid-run
    assert any(d["cause"] == "signal" and d["code"] == -9
               for d in report.worker_deaths), report.worker_deaths
    assert len(report.resumed_from) == 1
    resumed_epoch = int(re.search(
        r"_(\d+)\.npz$", os.path.basename(report.resumed_from[0])).group(1))
    assert 0 < resumed_epoch < EPOCHS      # a genuinely mid-run snapshot
    # one flight artifact per restart, readable and elastic-stamped
    assert len(report.flights) == 1
    with open(report.flights[0]) as f:
        doc = json.load(f)
    assert doc["reason"] == "elastic_restart"
    assert doc["extra"]["world"] == 2
    # THE pin: resumed history == uninterrupted history, bit for bit
    final = read_history(snap)
    assert final == baseline_ws1, (resumed_epoch, final[:2])
    if world_sizes[-1] == 2:
        # completion is owned by rank 0: the replica either finished too
        # (identical history) or was reaped as a redundant straggler
        if os.path.exists(os.path.join(str(snap), "history_1.json")):
            assert read_history(snap, rank=1) == final
        else:
            assert report.rounds[-1]["stragglers"] == [1]
    # supervisor-side counters moved by exactly this drill's events
    assert counts["restarts"] - counts0["restarts"] == 1
    assert counts["resumes"] - counts0["resumes"] == 1
    assert counts["worker_deaths"] - counts0["worker_deaths"] >= 1
    assert counts["world_size"] == 0       # fleet down -> gauge zeroed


def test_elastic_hang_detected_by_progress_heartbeat(tmp_path,
                                                     baseline_ws1):
    """A worker whose process stays alive but whose step loop stalls
    (injected 120 s hang) is detected through the heartbeat's flat
    progress counter, killed, and the fleet resumes to a bit-exact
    completion."""
    plan = faults.FaultPlan(seed=7).hang_at("elastic.worker", at_hit=45,
                                            seconds=120.0)
    report = run_elastic(
        [WORKFLOW], str(tmp_path), workers=1, spmd=False, prefix="ew",
        policy=fast_policy(), env=worker_env(), fault_plans={0: plan},
        term_grace=1.0, progress_timeout=3.0, heartbeat_timeout=60.0,
        round_timeout=300.0)
    assert report.completed
    assert report.restarts == 1
    assert report.hang_events == 1
    assert read_history(tmp_path) == baseline_ws1


def test_supervisor_env_plan_is_scrubbed_from_workers(tmp_path,
                                                      baseline_ws1):
    """A fault plan in the SUPERVISOR'S environment must not leak into
    the workers: hit counters reset per process, so an inherited seeded
    kill would re-fire after every resume and the fleet could never
    complete.  With the scrub, this kill-at-hit-1 plan in the ambient
    env is inert and the fleet completes in one clean round."""
    env = worker_env()
    env[faults.PLAN_ENV_VAR] = \
        faults.FaultPlan().kill_at("elastic.worker", at_hit=1).to_env()
    report = run_elastic(
        [WORKFLOW], str(tmp_path), workers=1, spmd=False, prefix="ew",
        policy=fast_policy(max_restarts=0), env=env,
        round_timeout=300.0)
    assert report.completed and report.restarts == 0
    assert read_history(tmp_path) == baseline_ws1


def test_boot_hang_detected_by_boot_timeout(tmp_path):
    """A worker that wedges BEFORE its first step (where the progress
    watch is deliberately blind: a long first compile looks identical)
    is caught by the boot_timeout layer."""
    wedge = tmp_path / "wedge.py"
    wedge.write_text("import time\n"
                     "def run(load, main):\n"
                     "    time.sleep(300)\n")
    with pytest.raises(ElasticExhausted):
        run_elastic([str(wedge)], str(tmp_path / "s"), workers=1,
                    spmd=False, policy=fast_policy(max_restarts=0),
                    env=worker_env(), term_grace=1.0,
                    boot_timeout=8.0, round_timeout=120.0)


def test_elastic_cli_rejects_bad_fault_plan(capsys):
    from znicz_tpu.resilience.elastic import elastic_main

    with pytest.raises(SystemExit):
        elastic_main(["--snap-dir", "/tmp/x",
                      "--fault-plan", "nope", "wf.py"])
    with pytest.raises(SystemExit):
        elastic_main(["--snap-dir", "/tmp/x",
                      "--fault-plan", "0={not json", "wf.py"])
    err = capsys.readouterr().err
    assert "RANK=JSON" in err or "bad plan JSON" in err


def test_elastic_budget_exhausts(tmp_path):
    """A worker command that always dies spends the budget and raises —
    with a flight artifact per failed round (jax-free worker: python -c
    exit 3, so the whole soak is fast)."""
    report_dir = tmp_path / "runs"
    with pytest.raises(ElasticExhausted, match="gave up"):
        run_elastic(["--definitely-not-a-real-flag"], str(tmp_path),
                    workers=1, spmd=False,
                    policy=fast_policy(max_restarts=1),
                    run_dir=str(report_dir), env=worker_env(),
                    round_timeout=60.0)
    flights = glob.glob(os.path.join(str(report_dir), "flight_*.json"))
    assert len(flights) == 2               # one per failed round


def test_goodput_ledger_accounts_supervisor_wall(tmp_path, baseline_ws1):
    """ISSUE 20: across a seeded SIGKILL/restart drill the goodput
    ledger's categories tile the supervisor's wall time (productive +
    lost + snapshot + idle ≈ wall), the drill genuinely loses the
    killed round's post-snapshot remainder, and the ledger survives
    into the restart flight artifact as its own "goodput" plane."""
    plan = faults.FaultPlan(seed=77).kill_at("elastic.worker",
                                             at_hit=KILL_AT_HIT)
    t0 = time.monotonic()
    report = run_elastic(
        [WORKFLOW], str(tmp_path), workers=1, spmd=False, prefix="ew",
        policy=fast_policy(), env=worker_env(), fault_plans={0: plan},
        term_grace=2.0, round_timeout=300.0)
    wall = time.monotonic() - t0
    assert report.completed and report.restarts == 1
    assert read_history(tmp_path) == baseline_ws1    # resume still exact
    good = report.goodput
    assert set(good["totals"]) == {"productive", "lost", "snapshot",
                                   "idle"}
    rank0 = good["per_rank"]["0"]
    accounted = sum(rank0.values())
    # THE pin: the monotonic-cursor ledger tiles the supervisor's wall
    # (slack only for aggregator setup before the ledger starts and the
    # return path after its final flush)
    assert abs(accounted - wall) <= max(0.05 * wall, 2.0), (rank0, wall)
    assert rank0["productive"] > 0.0
    # the killed round ran PAST its newest snapshot before dying — that
    # remainder is the drill's genuine lost compute
    assert rank0["lost"] > 0.0, rank0
    assert all(v >= 0.0 for v in rank0.values())
    assert 0.0 < good["ratio"] <= 1.0
    # the probe families carry the same accounting (cumulative across
    # the process, so >= this drill's figures)
    totals = probe.goodput_totals()
    assert totals["productive"] >= rank0["productive"] - 1e-6
    assert totals["lost"] >= rank0["lost"] - 1e-6
    # the restart flight artifact embeds the ledger-at-failure
    assert report.flights
    with open(report.flights[0]) as f:
        doc = json.load(f)
    plane = doc["planes"]["goodput"]
    assert plane["per_rank"]["0"]["productive"] > 0.0
    assert set(plane["totals"]) == {"productive", "lost", "snapshot",
                                    "idle"}


# -- heartbeat plumbing ------------------------------------------------------

def test_heartbeat_thread_writes_progress(tmp_path):
    path = str(tmp_path / "hb")
    values = iter([3, 17, 17, 29])
    start_heartbeat(path, interval=0.02,
                    progress=lambda: next(values, 29))
    deadline = time.monotonic() + 10
    seen = set()
    while time.monotonic() < deadline and 29 not in seen:
        try:
            with open(path) as f:
                ts_text, _, progress = f.read().strip().partition(" ")
            float(ts_text)
            seen.add(int(progress))
        except (OSError, ValueError):
            pass
        time.sleep(0.01)
    assert 29 in seen, seen
