"""Seeded composition fuzz over the transformer step's flag surface:
every MATH-PRESERVING flag (loss_chunks, head_sharded, remat, donate,
shard_update) must leave the training trajectory unchanged vs the plain
step in ANY combination on ANY mesh — pairwise parity is pinned
elsewhere; this catches interaction bugs between the execution-strategy
switches.  Model-CHANGING flags (n_experts/top_k/aux) are fuzzed for
mesh invariance instead (tp1 == tp2 for the same config)."""

import pytest

# full SPMD training runs on the virtual 8-device CPU mesh take
# minutes per file; tier-1 (-m 'not slow') must fit its 870 s
# budget, so these ride the registered slow lane
pytestmark = pytest.mark.slow

import numpy as np

import jax

from znicz_tpu.core import prng
from znicz_tpu.parallel.mesh import make_mesh
from znicz_tpu.parallel import transformer as tfm

MESHES = (
    {"data": 2, "seq": 2, "model": 2},
    {"data": 4, "seq": 1, "model": 2},
    {"data": 2, "seq": 1, "model": 1},
    {"data": 1, "seq": 2, "model": 4},
)


def _run(mesh, masked, tokens, labels, mask, n_steps=3, **kw):
    n_layers, d, heads, ff, vocab = 2, 32, 4, 64, 16
    prng.seed_all(41)
    params = tfm.init_params(prng.get(), n_layers, d, heads, ff, vocab,
                             n_experts=kw.get("n_experts"))
    step, _ = tfm.make_train_step(mesh, n_layers, d, heads, ff, vocab,
                                  lr=0.2, masked=masked, **kw)
    args = (tokens, labels, mask) if masked else (tokens, labels)
    run = []
    for _ in range(n_steps):
        params, loss = step(params, *args)
        run.append(float(loss))
    return run, jax.device_get(jax.tree.leaves(params))


def test_math_preserving_flag_combinations(cpu_devices):
    rng = np.random.default_rng(99)
    tokens = rng.integers(0, 16, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % 16).astype(np.int32)
    mask = np.array([True, True, True, False])

    baselines = {}   # (mesh_axes, masked) -> (losses, params); the
                     # baseline is flag-independent so duplicates memoize
    for trial in range(6):
        mesh_axes = MESHES[int(rng.integers(len(MESHES)))]
        masked = bool(rng.integers(2))
        flags = {
            "loss_chunks": [None, 2, 3, 5][int(rng.integers(4))],
            "head_sharded": bool(rng.integers(2)),
            "remat": bool(rng.integers(2)),
            "donate": False,   # donation forbids plain-python rebinds
                               # of the SAME host params; covered by
                               # test_remat_and_donate_match_baseline
            "shard_update": bool(rng.integers(2)),
            "remat_policy":
                [None, "dots", "nothing"][int(rng.integers(3))],
        }
        mesh = make_mesh(mesh_axes)
        key = (tuple(sorted(mesh_axes.items())), masked)
        if key not in baselines:
            baselines[key] = _run(mesh, masked, tokens, labels, mask)
        base, base_p = baselines[key]
        got, got_p = _run(mesh, masked, tokens, labels, mask, **flags)
        np.testing.assert_allclose(
            got, base, rtol=2e-4, atol=2e-5,
            err_msg=f"trial {trial}: {mesh_axes} masked={masked} {flags}")
        for a, b in zip(got_p, base_p):
            np.testing.assert_allclose(
                a, b, rtol=3e-4, atol=3e-5,
                err_msg=f"trial {trial}: {mesh_axes} {flags}")


def test_model_changing_flags_mesh_invariant(cpu_devices):
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 16, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % 16).astype(np.int32)
    mask = np.array([True, True, False, False])

    for trial in range(3):
        flags = {
            "n_experts": int(rng.choice([2, 4])),
            "moe_top_k": int(rng.integers(1, 3)),
            "moe_aux_weight": float(rng.choice([0.0, 0.01])),
            "loss_chunks": [None, 4][int(rng.integers(2))],
            "head_sharded": bool(rng.integers(2)),
        }
        masked = bool(rng.integers(2))
        a, _ = _run(make_mesh({"data": 2, "seq": 2, "model": 1}),
                    masked, tokens, labels, mask, **flags)
        b, _ = _run(make_mesh({"data": 2, "seq": 2, "model": 2}),
                    masked, tokens, labels, mask, **flags)
        np.testing.assert_allclose(
            b, a, rtol=2e-4, atol=2e-5,
            err_msg=f"trial {trial}: masked={masked} {flags}")


def test_quantized_collectives_gate(cpu_devices):
    """ISSUE 18 gate over the quantized transformer path: mode=off is
    BIT-IDENTICAL to a step that never saw the config for random
    math-preserving flag combos on random meshes; int8 and bf16 (same
    explicit-psum semantics, different codec noise) track each other
    tightly; and on model=1 meshes the quantized trajectory matches the
    single-device FULL-BATCH run — the true-batch-mean pin the exact
    path's AD-transposed reduction does not satisfy (see
    make_train_step's reduction-semantics note)."""
    rng = np.random.default_rng(18)
    tokens = rng.integers(0, 16, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % 16).astype(np.int32)
    mask = np.array([True, True, True, False])

    for trial in range(3):
        mesh_axes = MESHES[int(rng.integers(len(MESHES)))]
        masked = bool(rng.integers(2))
        flags = {
            "loss_chunks": [None, 2][int(rng.integers(2))],
            "head_sharded": bool(rng.integers(2)),
            "shard_update": bool(rng.integers(2)),
        }
        mesh = make_mesh(mesh_axes)
        base, base_p = _run(mesh, masked, tokens, labels, mask, **flags)
        off, off_p = _run(mesh, masked, tokens, labels, mask,
                          quantized_collectives={"mode": "off"}, **flags)
        assert off == base, (trial, mesh_axes, masked, flags)
        for a, b in zip(off_p, base_p):
            np.testing.assert_array_equal(
                a, b, err_msg=f"trial {trial}: {mesh_axes} {flags}")

    # single-device full-batch reference: what a true batch-mean
    # gradient trajectory must reproduce regardless of the data/seq
    # split (the transformer codec path carries no EF residual, so the
    # int8 band is codec noise alone)
    ref, _ = _run(make_mesh({"data": 1, "seq": 1, "model": 1}),
                  False, tokens, labels, mask)
    for mesh_axes in ({"data": 2, "seq": 1, "model": 1},
                      {"data": 2, "seq": 2, "model": 1}):
        mesh = make_mesh(mesh_axes)
        runs = {}
        for mode in ("bf16", "int8"):
            runs[mode], _ = _run(
                mesh, False, tokens, labels, mask,
                quantized_collectives={"mode": mode, "chunk": 128})
        np.testing.assert_allclose(runs["int8"], runs["bf16"],
                                   rtol=0.05, err_msg=str(mesh_axes))
        np.testing.assert_allclose(runs["bf16"], ref, rtol=5e-3,
                                   err_msg=str(mesh_axes))
        np.testing.assert_allclose(runs["int8"], ref, rtol=0.05,
                                   err_msg=str(mesh_axes))
