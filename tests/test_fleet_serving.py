"""Serving-fleet tests (ISSUE 13): the front-end router (least-loaded
pick, bounded retry on idempotent admission failures, streaming relay
with the synthesized-terminal guarantee, X-Request-Id propagation),
the liveness/readiness split on both worker planes, the SLO autoscaler
(deterministic ticks over a fake pool), the rolling-update state
machine, and the acceptance chaos drill: a REAL 2-worker fleet under
threaded traffic rolls onto a new package while a seeded fault plan
SIGKILLs one worker mid-rollout — zero admitted requests lost, every
stream exactly one terminal event, the fleet converges on the new
fingerprint.

In-process tests ride tiny KVDecoder-backed GenerateServers (the
test_generate convention); only the drill spawns real worker
processes.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from znicz_tpu import observe
from znicz_tpu.observe import flight
from znicz_tpu.resilience import faults
from znicz_tpu.serve.continuous import ContinuousBatcher
from znicz_tpu.serve.server import GenerateServer, ServeServer

N_LAYERS, D, HEADS, FF = 2, 32, 4, 64
CHARMAP = list("abcdefghijklmnopqrstuvwxyz .,!?")


@pytest.fixture(autouse=True)
def _clean_globals():
    yield
    faults.uninstall()
    flight.configure()
    observe.set_enabled(True)


@pytest.fixture(scope="module")
def params():
    from znicz_tpu.parallel.transformer import init_params

    return init_params(np.random.default_rng(3), N_LAYERS, D, HEADS,
                       FF, len(CHARMAP))


def _gen_server(params, package_info=None, slots=2):
    from znicz_tpu.serve.kvcache import KVDecoder

    dec = KVDecoder(params, heads=HEADS, max_len=32, batch=slots)
    server = GenerateServer(ContinuousBatcher(dec), charmap=CHARMAP,
                            package_info=package_info)
    server.start()
    return server


def _pool(tmp_path, **kw):
    from znicz_tpu.fleet import WorkerPool

    pkg = tmp_path / "pool_pkg.npz"
    pkg.write_bytes(b"not a real package, fingerprint fodder")
    return WorkerPool(str(pkg), plane="generate", **kw)


def _post(url, doc, headers=(), timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json", **dict(headers)})
    return urllib.request.urlopen(req, timeout=timeout)


def _stream(url, doc, headers=(), timeout=60):
    with _post(url, doc, headers=headers, timeout=timeout) as r:
        return r.headers.get("X-Request-Id"), \
            [json.loads(line) for line in r]


def _settled(read, want, timeout=5.0):
    """Poll ``read()`` until it equals ``want`` — terminal ledger
    updates land a beat after the last byte reaches the client."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = read()
        if got == want:
            return got
        time.sleep(0.02)
    return read()


# -- satellite: liveness vs readiness split ----------------------------------

def test_generate_readiness_split_and_fingerprint(params):
    fp = {"sha256": "cafe" * 16, "file": "lm.npz", "bytes": 7}
    server = _gen_server(params, package_info=fp)
    base = f"http://127.0.0.1:{server.port}"
    try:
        with urllib.request.urlopen(base + "/livez", timeout=5) as r:
            assert r.status == 200
        with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
            doc = json.load(r)
            assert r.status == 200 and doc["status"] == "ready"
            assert doc["package"] == fp
        assert json.loads(urllib.request.urlopen(
            base + "/", timeout=5).read())["package"] == fp
        # draining: readiness drops, liveness stays up
        server.batcher.stop(drain=True)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/readyz", timeout=5)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == "draining"
        with urllib.request.urlopen(base + "/livez", timeout=5) as r:
            assert r.status == 200       # alive: do NOT replace me
    finally:
        server.stop()


def test_serve_readiness_split(params):
    del params
    server = ServeServer(lambda x: x * 2.0, max_batch=4,
                         package_info={"sha256": "00", "file": "f",
                                       "bytes": 1})
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with urllib.request.urlopen(base + "/livez", timeout=5) as r:
            assert r.status == 200
        with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
            assert json.load(r)["package"]["sha256"] == "00"
    finally:
        server.stop()


def test_request_id_honored_end_to_end(params):
    """A router-minted X-Request-Id must be adopted by the worker (not
    re-minted) on both planes, so cross-process spans share a track."""
    server = _gen_server(params)
    try:
        rid, lines = _stream(
            f"http://127.0.0.1:{server.port}/generate",
            {"prompt": "ab", "max_tokens": 2},
            headers=(("X-Request-Id", "feed-123"),))
        assert rid == "feed-123"
        assert lines[-1]["done"] is True
        from znicz_tpu.observe import TRACER
        from znicz_tpu.observe.federation import request_track

        track = request_track("feed-123")
        spans = [e for e in TRACER.export_dict()["traceEvents"]
                 if e.get("args") and e["args"].get("rid") == "feed-123"]
        assert spans and all(e["tid"] == track for e in spans)
    finally:
        server.stop()


# -- router: pick / retry / relay --------------------------------------------

def test_router_least_loaded_pick_and_exclude(tmp_path):
    from znicz_tpu.fleet import FleetRouter, NoReadyWorker

    pool = _pool(tmp_path)
    try:
        a = pool.adopt("http://127.0.0.1:1")
        b = pool.adopt("http://127.0.0.1:2")
        c = pool.adopt("http://127.0.0.1:3")
        router = FleetRouter(pool)
        a.ready, b.ready, c.ready = True, True, True
        a.depth, b.depth, c.depth = 5.0, 1.0, 3.0
        assert router.pick() is b
        b.inflight = 9                  # in-flight covers the scrape gap
        assert router.pick() is c
        c.retiring = True               # a draining worker leaves
        assert router.pick() is a       # rotation immediately
        assert router.pick(exclude={a.rank}) is b
        with pytest.raises(NoReadyWorker):
            router.pick(exclude={a.rank, b.rank})
    finally:
        pool.aggregator.close()


def test_router_retries_admission_failures_only(params, tmp_path):
    """503 queue-full and connection-refused move to another worker;
    a worker VERDICT (400) is relayed verbatim, never retried."""
    from znicz_tpu.fleet import FleetRouter

    class Refusing(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = json.dumps({"error": "queue full"}).encode()
            self.send_response(503)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    refuser = ThreadingHTTPServer(("127.0.0.1", 0), Refusing)
    threading.Thread(target=refuser.serve_forever, daemon=True).start()
    good = _gen_server(params)
    pool = _pool(tmp_path)
    router = None
    try:
        w_dead = pool.adopt("http://127.0.0.1:1")       # refused conn
        w_503 = pool.adopt(
            f"http://127.0.0.1:{refuser.server_address[1]}")
        w_good = pool.adopt(f"http://127.0.0.1:{good.port}")
        for w in (w_dead, w_503, w_good):
            w.ready = True
        # force pick order dead -> 503 -> good
        w_dead.depth, w_503.depth, w_good.depth = 0.0, 1.0, 2.0
        router = FleetRouter(pool, max_retries=2)
        port = router.start()
        rid, lines = _stream(f"http://127.0.0.1:{port}/generate",
                             {"prompt": "ab", "max_tokens": 2})
        assert lines[-1].get("done") and "error" not in lines[-1]
        snap = _settled(
            lambda: {k: router.snapshot()[k]
                     for k in ("retries", "completed")},
            {"retries": 2, "completed": 1})
        assert snap == {"retries": 2, "completed": 1}
        # a worker verdict must NOT be retried: unknown chars -> one 400
        w_dead.ready = w_503.ready = False
        before = router.snapshot()["retries"]
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(f"http://127.0.0.1:{port}/generate",
                  {"prompt": "éé", "max_tokens": 2})
        assert exc.value.code == 400
        assert router.snapshot()["retries"] == before
    finally:
        if router is not None:
            router.stop()
        refuser.shutdown()
        refuser.server_close()
        good.stop()
        pool.aggregator.close()


def test_router_rejects_when_rotation_empty(tmp_path):
    from znicz_tpu.fleet import FleetRouter

    pool = _pool(tmp_path)
    router = FleetRouter(pool, max_retries=1)
    port = router.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(f"http://127.0.0.1:{port}/predict", {"input": [[0.0]]})
        assert exc.value.code == 503
        assert exc.value.headers["Retry-After"] == "1"
        snap = router.snapshot()
        assert snap["rejected"] == 1 and snap["admitted"] == 0
        # router readiness mirrors rotation emptiness
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/readyz",
                                   timeout=5)
        assert exc.value.code == 503
    finally:
        router.stop()
        pool.aggregator.close()


def test_router_synthesizes_terminal_on_broken_stream(tmp_path):
    """A worker that dies mid-stream (the chaos shape) must still leave
    the client with EXACTLY ONE terminal event — synthesized by the
    router, since the worker can no longer honor its contract."""
    from znicz_tpu.fleet import FleetRouter

    class Breaking(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            for tok in (1, 2):
                self.wfile.write(
                    (json.dumps({"token": tok}) + "\n").encode())
                self.wfile.flush()
            # die without a terminal line (SIGKILL closes sockets
            # without ceremony)
            self.wfile.close()

    breaker = ThreadingHTTPServer(("127.0.0.1", 0), Breaking)
    threading.Thread(target=breaker.serve_forever, daemon=True).start()
    pool = _pool(tmp_path)
    router = FleetRouter(pool)
    try:
        w = pool.adopt(f"http://127.0.0.1:{breaker.server_address[1]}")
        w.ready = True
        port = router.start()
        _, lines = _stream(f"http://127.0.0.1:{port}/generate",
                           {"prompt": "ab", "max_tokens": 8})
        terminals = [ln for ln in lines if ln.get("done")]
        assert len(terminals) == 1 and "error" in terminals[0]
        assert [ln["token"] for ln in lines if "token" in ln] == [1, 2]
        assert _settled(lambda: router.snapshot()["failed"], 1) == 1
    finally:
        router.stop()
        breaker.shutdown()
        breaker.server_close()
        pool.aggregator.close()


def test_router_metric_families_live(params, tmp_path):
    from znicz_tpu.fleet import FleetRouter

    good = _gen_server(params)
    pool = _pool(tmp_path)
    router = FleetRouter(pool)
    try:
        w = pool.adopt(f"http://127.0.0.1:{good.port}")
        w.ready = True
        port = router.start()
        _stream(f"http://127.0.0.1:{port}/generate",
                {"prompt": "ab", "max_tokens": 2})
        prom = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.prom",
            timeout=5).read().decode()
        for family in ("znicz_router_requests_total",
                       "znicz_router_proxy_seconds",
                       "znicz_router_inflight",
                       "znicz_router_workers_ready",
                       "znicz_fleet_scale_workers"):
            assert family in prom, f"{family} missing"
    finally:
        router.stop()
        good.stop()
        pool.aggregator.close()


# -- autoscaler: deterministic control ---------------------------------------

class _FakeWorker:
    def __init__(self, rank):
        self.rank = rank
        self.ready = True
        self.retiring = False


class _FakePool:
    """The five-method pool surface Autoscaler declares."""

    def __init__(self, n=1):
        self.workers_ = [_FakeWorker(i) for i in range(n)]
        self._next = n
        self.events = []

    def worker_count(self):
        return len(self.workers_)

    def ready_workers(self):
        return [w for w in self.workers_
                if w.ready and not w.retiring]

    def ready_count(self):
        return len(self.ready_workers())

    def spawn(self, event=None, env_extra=None):
        w = _FakeWorker(self._next)
        self._next += 1
        self.workers_.append(w)
        self.events.append(("spawn", event))
        return w

    def wait_ready(self, worker, timeout_s=None,
                   expect_fingerprint=None):
        return True

    def retire(self, worker, drain=True, event=None, wait=True):
        worker.retiring = True
        self.workers_.remove(worker)
        self.events.append(("retire", event))
        return True

    def reap(self, worker):
        return True


def _scaler_fixture(queue_depth_box, n=1, **kw):
    from znicz_tpu.fleet import Autoscaler
    from znicz_tpu.observe.federation import FleetAggregator

    agg = FleetAggregator(min_refresh_s=0.0, stale_s=1e9)
    agg.add_source(0, lambda: (
        "# TYPE znicz_generate_queue_depth gauge\n"
        f"znicz_generate_queue_depth {queue_depth_box[0]}\n"))
    pool = _FakePool(n=n)
    scaler = Autoscaler(pool, agg, queue_high=8.0, breach_for_s=2.0,
                        cooldown_s=10.0, idle_down_s=20.0, **kw)
    return agg, pool, scaler


def test_autoscaler_scales_up_on_breach_with_cooldown():
    depth = [20.0]
    agg, pool, scaler = _scaler_fixture(depth, n=1, min_workers=1,
                                        max_workers=3)
    try:
        assert scaler.tick(now=1000.0) is None      # breach starts
        assert scaler.tick(now=1001.0) is None      # for_s not met
        assert scaler.tick(now=1003.0) == "up"      # continuous breach
        assert pool.worker_count() == 2
        assert scaler.tick(now=1005.0) is None      # cooldown holds
        assert scaler.tick(now=1014.0) == "up"      # still breaching
        assert pool.worker_count() == 3
        assert scaler.tick(now=1030.0) is None      # at max_workers
        assert pool.events == [("spawn", "up"), ("spawn", "up")]
    finally:
        agg.close()


def test_autoscaler_scales_down_after_idle_window_only():
    depth = [0.0]
    agg, pool, scaler = _scaler_fixture(depth, n=3, min_workers=1,
                                        max_workers=3)
    try:
        assert scaler.tick(now=2000.0) is None      # idle window opens
        assert scaler.tick(now=2010.0) is None      # 10s < idle_down_s
        depth[0] = 3.0                              # a burst (below the
        assert scaler.tick(now=2015.0) is None      # breach level)...
        depth[0] = 0.0                              # ...resets the
        assert scaler.tick(now=2016.0) is None      # hysteresis window
        assert scaler.tick(now=2030.0) is None      # 14s idle again
        assert scaler.tick(now=2037.0) == "down"    # 21s idle: retire 1
        assert pool.worker_count() == 2
        assert scaler.tick(now=2048.0) is None      # fresh window gates
        assert scaler.tick(now=2069.0) == "down"    # the next retire
        assert pool.worker_count() == 1
        assert scaler.tick(now=2095.0) is None      # min_workers floor
        assert pool.events == [("retire", "down"), ("retire", "down")]
    finally:
        agg.close()


def test_autoscaler_validates_bounds():
    from znicz_tpu.fleet import Autoscaler
    from znicz_tpu.observe.federation import FleetAggregator

    agg = FleetAggregator(min_refresh_s=0.0)
    try:
        with pytest.raises(ValueError):
            Autoscaler(_FakePool(), agg, min_workers=3, max_workers=2)
    finally:
        agg.close()


# -- rolling update: state machine over a fake pool --------------------------

class _RolloutPool(_FakePool):
    """Fake pool with the package/fingerprint surface rollout drives."""

    def __init__(self, n=2):
        super().__init__(n=n)
        self.package = "old.npz"
        self.fp = {"sha256": "old"}
        self.gate_ok = True
        for w in self.workers_:
            w.fingerprint = {"sha256": "old"}
            w.gone = False
            w.live = True
            w.proc = object()

    def set_package(self, package):
        self.package = package
        self.fp = {"sha256": f"fp:{os.path.basename(package)}"}
        return self.fp

    def workers(self):
        return list(self.workers_)

    def spawn(self, event=None, env_extra=None):
        w = super().spawn(event=event)
        w.fingerprint = dict(self.fp)   # boots the CURRENT package
        w.gone = False
        w.live = True
        w.proc = object()
        return w

    def wait_ready(self, worker, timeout_s=None,
                   expect_fingerprint=None):
        if not self.gate_ok:
            return False
        if expect_fingerprint is not None:
            return worker.fingerprint.get("sha256") == \
                expect_fingerprint.get("sha256")
        return True

    def retire(self, worker, drain=True, event=None, wait=True):
        worker.retiring = True
        self.events.append(("retire", event))
        if wait:
            return self.reap(worker)
        return True

    def reap(self, worker):
        worker.gone = True
        worker.live = False
        if worker in self.workers_:
            self.workers_.remove(worker)
        self.events.append(("reap", worker.rank))
        return True

    def probe_once(self):
        """The real probe loop's replace-on-unexpected-death shape."""
        for w in list(self.workers_):
            if not w.live and not w.retiring:
                w.gone = True
                self.workers_.remove(w)
                self.spawn(event="replace")


def test_rollout_one_at_a_time_and_converges():
    from znicz_tpu.fleet import RollingUpdate

    pool = _RolloutPool(n=2)
    ru = RollingUpdate(pool, converge_timeout_s=5.0)
    report = ru.run("new.npz")
    assert report["state"] == "done" and report["adopted"] == 2
    assert {w.fingerprint["sha256"] for w in pool.workers()} == \
        {"fp:new.npz"}
    # strict one-at-a-time interleave: retire(0), spawn, reap(0),
    # retire(1), spawn, reap(1) — never two old workers down at once
    kinds = [e[0] for e in pool.events]
    assert kinds == ["retire", "spawn", "reap", "retire", "spawn",
                     "reap"]
    assert ru.status()["history"][-1]["sha256"] == "fp:new.npz"


def test_rollout_skips_already_dead_worker():
    """A worker SIGKILL'd mid-rollout is converged through its crash
    replacement (which boots the NEW package — set_package flipped
    first), not re-rolled."""
    from znicz_tpu.fleet import RollingUpdate

    pool = _RolloutPool(n=2)
    pool.workers_[1].live = False       # the chaos victim: the fake
    #                                     probe loop replaces it during
    #                                     converge, on the new package
    ru = RollingUpdate(pool, converge_timeout_s=5.0)
    report = ru.run("new.npz")
    assert report["adopted"] == 1       # victim skipped, not adopted
    outcomes = [s["outcome"] for s in report["steps"]]
    assert "already_dead" in outcomes
    assert ("spawn", "replace") in pool.events
    assert {w.fingerprint["sha256"] for w in pool.workers()} == \
        {"fp:new.npz"}


def test_rollout_gate_failure_fails_safe():
    from znicz_tpu.fleet import RollingUpdate, RolloutError

    pool = _RolloutPool(n=2)
    pool.gate_ok = False                # replacements never gate ready
    ru = RollingUpdate(pool, converge_timeout_s=1.0)
    with pytest.raises(RolloutError):
        ru.run("bad.npz")
    status = ru.status()
    assert status["state"] == "failed" and status["error"]
    # only the FIRST target was touched — the rest keep serving
    untouched = [w for w in pool.workers()
                 if w.fingerprint["sha256"] == "old"]
    assert len(untouched) == 1


def test_rollout_refuses_overlap():
    from znicz_tpu.fleet import RollingUpdate

    pool = _RolloutPool(n=1)
    ru = RollingUpdate(pool)
    ru._state["state"] = "rolling"
    with pytest.raises(ValueError):
        ru.run("new.npz")


# -- the acceptance chaos drill (real processes) -----------------------------

def _build_pkg(tmp_path, seed, name):
    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.utils.export import export_lm

    p = init_params(np.random.default_rng(seed), N_LAYERS, D, HEADS,
                    FF, len(CHARMAP))
    path = str(tmp_path / f"{name}.npz")
    export_lm(p, path, heads=HEADS, charmap=CHARMAP, name=name)
    return path


def test_rollout_chaos_drill_zero_lost_requests(tmp_path):
    """The ISSUE 13 acceptance pin: N=2 real workers, continuous
    threaded traffic through the router, a full rolling weight update
    with a seeded SIGKILL (fault plan, ``generate.step``) landing on a
    worker mid-rollout.  Every admitted stream gets exactly one
    terminal event, the fleet converges on the new package's
    fingerprint, and steady-state decode recompiles nothing."""
    from znicz_tpu.fleet import FleetRouter, RollingUpdate, WorkerPool
    from znicz_tpu.utils.naming import package_fingerprint

    pkg_a = _build_pkg(tmp_path, 7, "lm_a")
    pkg_b = _build_pkg(tmp_path, 8, "lm_b")
    fp_b = package_fingerprint(pkg_b)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ZNICZ_TPU_COMPILE_CACHE="off")
    pool = WorkerPool(pkg_a, plane="generate",
                      worker_args=("--slots", "2", "--max-len", "48"),
                      env=env, run_dir=str(tmp_path / "fleet"),
                      probe_interval_s=0.25)
    router = None
    stop_traffic = threading.Event()
    results = []        # (kind, detail) per attempted request
    res_lock = threading.Lock()
    try:
        pool.spawn()
        # the seeded chaos victim: SIGKILL its own pid at the 25th
        # decode step — under the drill's continuous traffic that lands
        # squarely inside the rollout window (traffic only starts with
        # the rollout; worker 0 drains first, so the steps concentrate
        # here)
        plan = faults.FaultPlan(seed=13).kill_at("generate.step",
                                                 at_hit=25)
        pool.spawn(env_extra={faults.PLAN_ENV_VAR: plan.to_env()})
        assert pool.wait_all_ready(timeout_s=240), \
            f"workers never ready: {pool.snapshot()}"
        pool.start_probes()
        router = FleetRouter(pool, max_retries=2)
        port = router.start()
        rollout = RollingUpdate(pool, converge_timeout_s=240.0)

        def client(cid):
            rng = np.random.default_rng(cid)
            while not stop_traffic.is_set():
                prompt = "".join(
                    CHARMAP[i] for i in rng.integers(
                        0, 26, size=int(rng.integers(2, 6))))
                try:
                    _, lines = _stream(
                        f"http://127.0.0.1:{port}/generate",
                        {"prompt": prompt, "max_tokens": 6,
                         "timeout_s": 30}, timeout=90)
                except urllib.error.HTTPError as exc:
                    exc.read()
                    with res_lock:      # never admitted — not lost
                        results.append(("rejected", exc.code))
                    time.sleep(0.05)
                    continue
                except Exception as exc:  # noqa: BLE001 — a silent
                    with res_lock:        # stream IS a lost request
                        results.append(("broken", repr(exc)))
                    continue
                terminals = [ln for ln in lines if ln.get("done")]
                with res_lock:
                    if len(terminals) != 1:
                        results.append(("bad_terminal", lines))
                    elif "error" in terminals[0]:
                        results.append(("errored", terminals[0]))
                    else:
                        results.append(("completed", len(lines) - 1))

        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True) for c in range(4)]
        for t in threads:
            t.start()
        try:
            report = rollout.run(pkg_b)
        finally:
            time.sleep(1.0)             # a tail of traffic post-roll
            stop_traffic.set()
            for t in threads:
                t.join(timeout=120)
        assert report["state"] == "done", report
        # the workers the rollout retired drained CLEAN (exit 0, every
        # admitted request completed) — only the chaos victim may die
        reaps = [s for s in report["steps"]
                 if s["outcome"] in ("drained", "killed")]
        assert reaps and all(s["outcome"] == "drained"
                             for s in reaps), report
        # the seeded kill actually landed and was replaced on the NEW
        # package by the probe loop
        assert pool.replacements >= 1, pool.snapshot()
        # convergence: every live worker reports pkg_b's fingerprint
        pool.probe_once()
        fps = {(w.fingerprint or {}).get("sha256")
               for w in pool.workers()}
        assert fps == {fp_b["sha256"]}, pool.snapshot()
        # THE pin: no admitted request lost — every stream either
        # completed or carried exactly one terminal error; nothing
        # broke silently, nothing double-terminated
        with res_lock:
            kinds = {}
            for kind, _ in results:
                kinds[kind] = kinds.get(kind, 0) + 1
        assert kinds.get("broken", 0) == 0, (kinds, results[-10:])
        assert kinds.get("bad_terminal", 0) == 0, (kinds, results[-10:])
        assert kinds.get("completed", 0) >= 10, kinds
        # the router ledger closes: admitted == one terminal each
        assert _settled(
            lambda: (lambda s: s["admitted"] - s["completed"] -
                     s["failed"] - s["client_gone"])(router.snapshot()),
            0) == 0, router.snapshot()
        # steady state on the new fleet: a fresh request streams clean
        # and decode compiles nothing further
        stats0 = [json.loads(urllib.request.urlopen(
            w.base + "/metrics", timeout=10).read())["decoder"]
            ["compile_count"] for w in pool.ready_workers()]
        _, lines = _stream(f"http://127.0.0.1:{port}/generate",
                           {"prompt": "hello", "max_tokens": 4})
        assert lines[-1].get("done") and "error" not in lines[-1]
        stats1 = [json.loads(urllib.request.urlopen(
            w.base + "/metrics", timeout=10).read())["decoder"]
            ["compile_count"] for w in pool.ready_workers()]
        assert stats0 == stats1, (stats0, stats1)
    finally:
        stop_traffic.set()
        if router is not None:
            router.stop()
        pool.stop()
