"""Tier-3 core-framework tests (SURVEY.md §5): config, gates, unit graph,
memory mapping, prng determinism — the rebuild of veles/tests/ core tests."""

import pickle

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.config import Config, Tune, fix_config, root, walk_tunes
from znicz_tpu.core.memory import Array, roundup
from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.plumbing import Repeater
from znicz_tpu.core.units import TrivialUnit, Unit
from znicz_tpu.core.workflow import Workflow


# -- config -----------------------------------------------------------------

def test_config_tree_autovivify_and_update():
    cfg = Config("test")
    cfg.loader.minibatch_size = 60
    assert cfg.loader.minibatch_size == 60
    cfg.update({"decision": {"max_epochs": 3}, "lr": 0.01})
    assert cfg.decision.max_epochs == 3 and cfg.lr == 0.01
    assert "loader" in cfg and "missing" not in cfg
    assert not cfg.empty_subtree
    assert cfg.as_dict()["decision"] == {"max_epochs": 3}


def test_config_tune_fix_and_walk():
    cfg = Config("test")
    cfg.gd.learning_rate = Tune(0.01, 0.001, 0.1)
    cfg.gd.momentum = 0.9
    tunes = dict(walk_tunes(cfg))
    assert list(tunes) == ["gd.learning_rate"]
    fix_config(cfg)
    assert cfg.gd.learning_rate == 0.01


def test_root_defaults_exist():
    assert root.common.engine.get("backend") in ("auto", "tpu", "numpy")


# -- mutable gates ----------------------------------------------------------

def test_bool_assignment_and_composites():
    complete = Bool(False)
    improved = Bool(True)
    gate = ~complete & improved
    assert bool(gate)
    complete <<= True
    assert not bool(gate)  # composite re-evaluates operands live
    blocked = complete | Bool(False)
    assert bool(blocked)


# -- memory -----------------------------------------------------------------

def test_roundup():
    assert roundup(5, 4) == 8 and roundup(8, 4) == 8


def test_array_map_semantics_numpy_device():
    arr = Array(np.arange(6, dtype=np.float32).reshape(2, 3))
    arr.initialize(NumpyDevice())
    assert arr.map_read()[1, 2] == 5.0
    arr.map_write()[0, 0] = 42.0
    assert arr.mem[0, 0] == 42.0


def test_array_device_roundtrip():
    dev = TPUDevice()  # CPU jax device under the test platform
    arr = Array(np.ones((4, 4), dtype=np.float32))
    arr.initialize(dev)
    dv = arr.devmem
    assert dv.shape == (4, 4)
    # simulate a compiled-step output replacing the buffer
    arr.set_devmem(dv * 3.0)
    assert arr.map_read()[0, 0] == 3.0
    # host write flows back on next devmem access
    arr.map_write()[0, 0] = 7.0
    assert float(arr.devmem[0, 0]) == 7.0


def test_array_pickle_drops_device():
    dev = TPUDevice()
    arr = Array(np.full((2, 2), 5.0, np.float32))
    arr.initialize(dev)
    arr.set_devmem(arr.devmem + 1)
    restored = pickle.loads(pickle.dumps(arr))
    assert restored.mem[0, 0] == 6.0 and restored.device is None


# -- prng -------------------------------------------------------------------

def test_prng_determinism_and_state():
    gen = prng.get("t1")
    gen.seed(123)
    a = gen.uniform(-1, 1, (5,))
    state = gen.state_dict()
    b = gen.uniform(-1, 1, (5,))
    gen.load_state_dict(state)
    b2 = gen.uniform(-1, 1, (5,))
    np.testing.assert_array_equal(b, b2)
    gen.seed(123)
    np.testing.assert_array_equal(a, gen.uniform(-1, 1, (5,)))


def test_prng_keys_deterministic():
    gen = prng.get("t2")
    gen.seed(7)
    k1 = gen.key()
    gen.seed(7)
    k2 = gen.key()
    assert (np.asarray(k1) == np.asarray(k2)).all()


# -- unit graph -------------------------------------------------------------

class Recorder(Unit):
    """Appends its name to a shared trace on each run."""

    def __init__(self, workflow, trace, name):
        super().__init__(workflow, name=name)
        self.trace = trace

    def run(self):
        self.trace.append(self.name)


def test_control_chain_and_all_links_join():
    wf = Workflow(name="wf")
    trace = []
    a = Recorder(wf, trace, "a")
    b = Recorder(wf, trace, "b")
    c = Recorder(wf, trace, "c")  # fires only after BOTH a and b
    a.link_from(wf.start_point)
    b.link_from(wf.start_point)
    c.link_from(a)
    c.link_from(b)
    wf.end_point.link_from(c)
    wf.initialize(device=None)
    wf.run()
    assert trace == ["a", "b", "c"]
    assert wf.end_point.reached


def test_gate_skip_propagates_without_running():
    wf = Workflow(name="wf")
    trace = []
    a = Recorder(wf, trace, "a")
    b = Recorder(wf, trace, "b")
    a.link_from(wf.start_point)
    b.link_from(a)
    wf.end_point.link_from(b)
    a.gate_skip <<= True
    wf.initialize(device=None)
    wf.run()
    assert trace == ["b"]  # a skipped but signal propagated


def test_gate_block_stops_propagation():
    wf = Workflow(name="wf")
    trace = []
    a = Recorder(wf, trace, "a")
    a.link_from(wf.start_point)
    wf.end_point.link_from(a)
    a.gate_block <<= True
    wf.initialize(device=None)
    wf.run()
    assert trace == [] and not wf.end_point.reached


def test_repeater_loop_with_decision_gate():
    """The reference's training-loop shape: Repeater -> work -> decision,
    loop back to Repeater until `complete` flips, then end_point opens."""
    wf = Workflow(name="wf")
    trace = []

    class Decision(Unit):
        def __init__(self, workflow):
            super().__init__(workflow, name="decision")
            self.complete = Bool(False)
            self.n = 0

        def run(self):
            self.n += 1
            if self.n >= 3:
                self.complete <<= True

    rep = Repeater(wf)
    work = Recorder(wf, trace, "work")
    dec = Decision(wf)
    rep.link_from(wf.start_point)
    work.link_from(rep)
    dec.link_from(work)
    rep.link_from(dec)           # loop back-edge
    rep.gate_block = dec.complete
    wf.end_point.link_from(dec)
    wf.end_point.gate_block = ~dec.complete
    wf.initialize(device=None)
    wf.run()
    assert trace == ["work"] * 3
    assert wf.end_point.reached


def test_link_attrs_aliasing_two_way():
    wf = Workflow(name="wf")
    a = TrivialUnit(wf, name="a")
    b = TrivialUnit(wf, name="b")
    a.output = Array(np.zeros(3, np.float32))
    b.link_attrs(a, ("input", "output"))
    assert b.input is a.output
    a.output = Array(np.ones(3, np.float32))
    assert b.input is a.output  # live alias, not a snapshot
    b.input = Array(np.full(3, 2.0, np.float32))
    assert a.output.mem[0] == 2.0  # two-way write-back


def test_timing_table():
    wf = Workflow(name="wf")
    trace = []
    a = Recorder(wf, trace, "a")
    a.link_from(wf.start_point)
    wf.end_point.link_from(a)
    wf.initialize(device=None)
    wf.run()
    table = wf.timing_table()
    assert "a" in table and "runs" in table


def test_metrics_jsonl_sink(tmp_path):
    """root.common.metrics_file streams one JSON object per epoch
    (SURVEY §6.5 machine-readable metrics)."""
    import json

    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models import wine

    path = tmp_path / "metrics.jsonl"
    root.common.metrics_file = str(path)
    try:
        prng.seed_all(3)
        w = wine.build(max_epochs=3, n_train=60, n_valid=30,
                       minibatch_size=10)
        w.initialize(device=TPUDevice())
        w.run()
    finally:
        del root.common.metrics_file
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [rec["epoch"] for rec in lines] == [1, 2, 3]
    assert all("metric_validation" in rec and rec["workflow"] == "Wine"
               for rec in lines), lines
