"""Quantized-collective codec (ISSUE 18): config resolution, the
balanced chunk/byte math, quantize/dequantize round trips, the
pad-masked ZeRO slice gather (satellite: zero.pad_slice tails must not
ride into chunk absmax), the quantized psum on a real virtual mesh, and
the error-feedback residual identity."""

import numpy as np
import pytest

from znicz_tpu.parallel import qcomm


# -- resolve() ---------------------------------------------------------------

def test_resolve_off_and_none():
    assert qcomm.resolve({"mode": "off"}) is None
    assert qcomm.resolve({}) is None            # mode defaults to off


def test_resolve_modes_and_defaults():
    c = qcomm.resolve({"mode": "int8"})
    assert (c.mode, c.chunk, c.error_feedback) == \
        ("int8", qcomm.DEFAULT_CHUNK, True)
    c = qcomm.resolve({"mode": "bf16", "chunk": 256,
                       "error_feedback": False})
    assert (c.mode, c.chunk, c.error_feedback) == ("bf16", 256, False)


def test_resolve_rejects_typos():
    with pytest.raises(ValueError, match="unknown key"):
        qcomm.resolve({"mode": "int8", "chunks": 64})
    with pytest.raises(ValueError, match="mode"):
        qcomm.resolve({"mode": "fp8"})
    with pytest.raises(ValueError, match="chunk"):
        qcomm.resolve({"mode": "int8", "chunk": 0})


# -- chunk layout / byte math ------------------------------------------------

def test_chunk_layout_balanced():
    """Balanced chunking never pads more than n_chunks - 1 elements,
    covers the payload, and degenerates sanely at the edges."""
    for size in (1, 7, 64, 1000, 1024, 1025, 4096, 99991):
        for chunk in (1, 64, 1024):
            n, length = qcomm.chunk_layout(size, chunk)
            assert n * length >= size
            assert n * length - size < n
            assert length <= chunk


def test_wire_nbytes_ratio_bound():
    """int8 wire bytes stay under the 0.27x-of-exact acceptance bound
    for every payload size — including the bias-sized leaves a fixed
    chunk grid would pad ruinously."""
    codec = qcomm.resolve({"mode": "int8"})
    for size in (100, 1024, 1025, 785 * 128, 99991):
        ratio = qcomm.wire_nbytes(codec, size) / qcomm.exact_nbytes(size)
        assert 0.25 <= ratio <= 0.27, (size, ratio)
    # tiny bias-sized leaves pay one scale against few elements — the
    # ratio loosens but must still beat the bf16 fallback by a margin
    for size in (16, 23):
        ratio = qcomm.wire_nbytes(codec, size) / qcomm.exact_nbytes(size)
        assert ratio <= 0.35, (size, ratio)
    bf16 = qcomm.resolve({"mode": "bf16"})
    assert qcomm.wire_nbytes(bf16, 1000) == 2000
    assert qcomm.wire_nbytes(None, 1000) == qcomm.exact_nbytes(1000)


# -- quantize / dequantize round trip ----------------------------------------

def test_int8_roundtrip_error_bounded_per_chunk():
    """Dequantized int8 is within absmax/254 of the original PER CHUNK
    (half a quantization step of that chunk's scale) — the property the
    balanced per-chunk absmax buys over a single global scale."""
    rng = np.random.default_rng(0)
    codec = qcomm.Codec("int8", chunk=64)
    x = (rng.standard_normal(500) *
         np.repeat([1e-4, 1.0, 1e3, 1e-2, 10.0], 100)).astype(np.float32)
    payload, scales = qcomm.quantize_flat(x, codec)
    back = np.asarray(qcomm.dequantize_flat(payload, scales, x.size))
    n, length = qcomm.chunk_layout(x.size, 64)
    pad = np.pad(x, (0, n * length - x.size)).reshape(n, length)
    bound = np.abs(pad).max(axis=1) / 254.0 + 1e-12
    err = np.abs(np.pad(back - x, (0, n * length - x.size))
                 .reshape(n, length))
    assert (err <= bound[:, None] + 1e-7).all()


def test_bf16_roundtrip():
    rng = np.random.default_rng(1)
    codec = qcomm.Codec("bf16")
    x = rng.standard_normal(333).astype(np.float32)
    payload, scales = qcomm.quantize_flat(x, codec)
    assert scales is None and str(payload.dtype) == "bfloat16"
    back = np.asarray(qcomm.dequantize_flat(payload, scales, x.size))
    np.testing.assert_allclose(back, x, rtol=2 ** -8)


def test_valid_size_masks_tail_out_of_absmax():
    """Satellite: a zero.pad_slice tail (or stale buffer bytes) beyond
    ``valid_size`` must not enter any chunk's absmax — poisoning the
    tail with a huge value must leave payload, scales, and the
    dequantized valid prefix IDENTICAL."""
    rng = np.random.default_rng(2)
    codec = qcomm.Codec("int8", chunk=32)
    valid = 71                                   # non-aligned on purpose
    clean = np.zeros(96, np.float32)
    clean[:valid] = rng.standard_normal(valid)
    poisoned = clean.copy()
    poisoned[valid:] = 1e9
    p_clean, s_clean = qcomm.quantize_flat(clean, codec,
                                           valid_size=valid)
    p_poison, s_poison = qcomm.quantize_flat(poisoned, codec,
                                             valid_size=valid)
    np.testing.assert_array_equal(np.asarray(p_clean),
                                  np.asarray(p_poison))
    np.testing.assert_array_equal(np.asarray(s_clean),
                                  np.asarray(s_poison))
    back = np.asarray(qcomm.dequantize_flat(p_poison, s_poison, 96))
    np.testing.assert_allclose(back[:valid], clean[:valid],
                               atol=np.abs(clean).max() / 127.0)
    assert (back[valid:] == 0.0).all()


def test_all_pad_slice_quantizes_to_zeros_not_nan():
    """A rank whose slice is ENTIRELY pad (valid_size=0) must produce a
    zero payload with scale 1 — never a 0/0 NaN downstream."""
    codec = qcomm.Codec("int8", chunk=16)
    x = np.full(32, 7.0, np.float32)
    payload, scales = qcomm.quantize_flat(x, codec, valid_size=0)
    assert (np.asarray(payload) == 0).all()
    np.testing.assert_array_equal(np.asarray(scales),
                                  np.ones(2, np.float32))
    back = np.asarray(qcomm.dequantize_flat(payload, scales, 32))
    assert np.isfinite(back).all() and (back == 0.0).all()


# -- error feedback ----------------------------------------------------------

def test_error_feedback_residual_identity(cpu_devices):
    """psum_leaf's returned residual is exactly h - dequantize(own
    payload) with h = g + carried residual, and carrying it shrinks the
    accumulated error versus dropping it (the EQuARX convergence
    argument, measurable on one leaf)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from znicz_tpu.parallel.compat import shard_map
    from znicz_tpu.parallel.mesh import make_mesh

    codec = qcomm.Codec("int8", chunk=32)
    mesh = make_mesh({"data": 4})
    rng = np.random.default_rng(3)
    g = rng.standard_normal((4, 50)).astype(np.float32)
    r = 0.01 * rng.standard_normal((4, 50)).astype(np.float32)

    def body(gl, rl):
        s, nr = qcomm.psum_leaf(gl[0], "data", codec, rl[0])
        return s[None], nr[None]

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"), P("data")),
                   out_specs=(P("data"), P("data")))
    summed, new_r = map(np.asarray, jax.jit(fn)(g, r))
    # residual identity, checked against a host-side requantize of h
    h = g + r
    for k in range(4):
        payload, scales = qcomm.quantize_flat(h[k], codec)
        own = np.asarray(qcomm.dequantize_flat(payload, scales, 50))
        np.testing.assert_allclose(new_r[k], h[k] - own, atol=1e-6)
    # all ranks computed the same sum, equal to the dequantized total
    np.testing.assert_allclose(summed, np.tile(summed[:1], (4, 1)))
    np.testing.assert_allclose(summed[0], h.sum(0),
                               atol=4 * np.abs(h).max() / 127.0)


# -- quantized psum on a mesh ------------------------------------------------

def test_psum_tree_matches_exact_within_codec_noise(cpu_devices):
    """psum_tree over a 2-leaf pytree on an 8-way axis lands within the
    analytic per-chunk error bound of the exact psum for int8, and
    within bf16 rounding for bf16; every replica sees the identical
    sum (the local-sum-after-gather determinism argument)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from znicz_tpu.parallel.compat import shard_map
    from znicz_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 8})
    rng = np.random.default_rng(4)
    tree = {"w": rng.standard_normal((8, 13, 7)).astype(np.float32),
            "b": rng.standard_normal((8, 5)).astype(np.float32)}
    exact = {k: v.sum(0) for k, v in tree.items()}

    for mode, tol in (("int8", None), ("bf16", 2 ** -7)):
        codec = qcomm.Codec(mode, chunk=64)

        def body(t):
            local = jax.tree.map(lambda x: x[0], t)
            s, _ = qcomm.psum_tree(local, "data", codec)
            return jax.tree.map(lambda x: x[None], s)

        fn = shard_map(body, mesh=mesh, in_specs=(P("data"),),
                       out_specs=P("data"))
        out = jax.jit(fn)(tree)
        for k in tree:
            got = np.asarray(out[k])
            np.testing.assert_allclose(got, np.tile(got[:1],
                                       (8,) + (1,) * exact[k].ndim))
            atol = tol if tol is not None else \
                8 * np.abs(tree[k]).max() / 254.0 + 1e-6
            np.testing.assert_allclose(
                got[0], exact[k],
                atol=atol * (np.abs(exact[k]).max() if tol else 1.0))


# -- quantized ZeRO slice gather ---------------------------------------------

def test_gather_slices_non_aligned_leaf(cpu_devices):
    """The quantized regather reconstructs a NON-ALIGNED leaf (size %
    n != 0, so the trailing rank's slice carries a pad_slice tail)
    within per-chunk int8 error — and the pad tail does NOT dilute the
    trailing rank's scales: reconstruction error on the real elements
    obeys the same bound as the aligned case."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from znicz_tpu.parallel import zero
    from znicz_tpu.parallel.compat import shard_map
    from znicz_tpu.parallel.mesh import make_mesh

    codec = qcomm.Codec("int8", chunk=16)
    mesh = make_mesh({"data": 4})
    rng = np.random.default_rng(5)
    for size in (64, 61, 3):       # aligned, padded, mostly-pad ranks
        x = rng.standard_normal(size).astype(np.float32)
        like = jax.ShapeDtypeStruct((size,), np.float32)
        pad = (-size) % 4
        flat = np.pad(x, (0, pad))

        def body(f):
            rank = lax.axis_index("data")
            return zero.all_gather_slices(f, rank, 4, "data", like,
                                          codec=codec)

        fn = shard_map(body, mesh=mesh, in_specs=(P("data"),),
                       out_specs=P())
        got = np.asarray(jax.jit(fn)(flat))
        shard_len = (size + pad) // 4
        for k in range(4):
            lo, hi = k * shard_len, min((k + 1) * shard_len, size)
            if lo >= hi:
                continue
            bound = np.abs(x[lo:hi]).max() / 127.0 + 1e-7
            assert np.abs(got[lo:hi] - x[lo:hi]).max() <= bound, \
                (size, k)
