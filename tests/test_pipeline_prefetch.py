"""Async input pipeline (znicz_tpu/pipeline/): the prefetching producer +
overlapped H2D staging must be INVISIBLE to training semantics — bit-exact
metric histories vs the synchronous path in every feeding mode (direct
transfers, HBM-pinned indices, epoch-scan), bit-exact chaos
kill-and-resume through the resilience plane (drain-on-snapshot barrier),
bounded-queue backpressure, clean shutdown, and zero steady-state
recompiles on the step hot path."""

import threading
import time

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.config import root
from znicz_tpu.loader.synthetic import SyntheticClassifierLoader
from znicz_tpu.pipeline import (BatchPrefetcher, PrefetcherStopped,
                                attach_prefetcher)
from znicz_tpu.resilience import faults
from znicz_tpu.resilience.supervisor import SupervisorPolicy, run_supervised
from znicz_tpu.standard_workflow import StandardWorkflow
from znicz_tpu.web_status import WebStatus

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 24},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 6},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
]
LOADER = {"n_classes": 6, "sample_shape": (10, 10), "n_train": 240,
          "n_valid": 120, "minibatch_size": 40, "spread": 2.5, "noise": 1.0}


def build(max_epochs, snap_dir=None, seed=77, depth=None):
    """Fresh, initialized workflow (the supervisor's factory discipline:
    re-seed the global PRNG exactly like a fresh process would)."""
    prng.seed_all(seed)
    cfg = None
    if snap_dir is not None:
        cfg = {"directory": str(snap_dir), "prefix": "t",
               "only_improved": False, "keep_all": True}
    w = StandardWorkflow(
        name="PipeTest", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": max_epochs},
        snapshotter_config=cfg,
        pipeline_config={"depth": depth} if depth else None)
    w.initialize(device=TPUDevice())
    return w


def run_history(max_epochs, depth=None, **kw):
    w = build(max_epochs, depth=depth, **kw)
    w.run()
    hist = w.decision.metrics_history
    w.stop()
    return hist, w


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    yield
    faults.uninstall()


@pytest.fixture
def direct_transfers():
    """Force the batch-shipping path (no HBM dataset pinning) so the
    pipeline's staging leg actually carries the minibatches."""
    prev = root.common.engine.get("dataset_on_device_max_bytes", 1 << 30)
    root.common.engine.dataset_on_device_max_bytes = 0
    yield
    root.common.engine.dataset_on_device_max_bytes = prev


def fast_policy(**kw):
    kw.setdefault("sleep", lambda s: None)
    return SupervisorPolicy(**kw)


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name == BatchPrefetcher.THREAD_NAME and t.is_alive()]


# -- determinism: sync vs prefetched ----------------------------------------

def test_prefetch_bit_exact_direct_mode(direct_transfers):
    """ISSUE 4 acceptance: with prefetch depth >= 2 the epoch metric
    histories are bit-identical to the synchronous path (seeded, multiple
    epochs) — here over the direct batch-transfer feeding mode."""
    sync_hist, _ = run_history(4)
    for depth in (2, 3):
        hist, w = run_history(4, depth=depth)
        assert hist == sync_hist, f"depth={depth} diverged"
        snap = w.input_pipeline.stats.snapshot()
        assert snap["consumed"] == 4 * 9     # 6 train + 3 valid per epoch
        assert snap["bytes_staged"] > 0      # the staging leg really ran
        assert snap["max_fill"] <= depth


def test_prefetch_bit_exact_indexed_mode():
    """HBM-pinned dataset (serve_indices_only): the pipeline stages only
    indices + mask; histories still bit-exact."""
    sync_hist, ws = run_history(3)
    hist, wp = run_history(3, depth=2)
    assert ws.loader.serve_indices_only and wp.loader.serve_indices_only
    assert hist == sync_hist
    assert wp.input_pipeline.stats.snapshot()["bytes_staged"] > 0


def test_prefetch_bit_exact_scan_epoch_mode():
    """Epoch-scan feeding (one compiled scan per class pass): the consumer
    replays the captured class plan from the producer; bit-exact."""
    prev = root.common.engine.get("scan_epoch", False)
    root.common.engine.scan_epoch = True
    try:
        sync_hist, _ = run_history(3)
        hist, _ = run_history(3, depth=2)
    finally:
        root.common.engine.scan_epoch = prev
    assert hist == sync_hist


def test_pipeline_requires_fused():
    with pytest.raises(ValueError, match="fused=True"):
        StandardWorkflow(
            name="Bad", layers=LAYERS, loss_function="softmax",
            loader_name="synthetic_classifier", loader_config=LOADER,
            fused=False, pipeline_config={"depth": 2})


# -- resilience interop ------------------------------------------------------

def test_chaos_kill_and_resume_bit_exact_pipelined(tmp_path,
                                                   direct_transfers):
    """ISSUE 4 acceptance: a pipelined run killed at a seeded epoch and
    auto-resumed by the supervisor reproduces the SYNCHRONOUS run's
    metric history bit-exactly — the epoch-boundary barrier guarantees
    snapshots capture sync-mode loader/prng state, and restore drains +
    reseeds the pipeline."""
    sync_hist, _ = run_history(4)

    rng = np.random.default_rng(1234)
    crash_epoch = int(rng.integers(1, 4))
    snap_dir = tmp_path / "chaos"
    plan = faults.FaultPlan(seed=1234)
    plan.crash_at("workflow.step", when=lambda workflow, unit:
                  int(workflow.decision.epoch_number) == crash_epoch)
    with faults.active(plan):
        report = run_supervised(
            lambda: build(4, snap_dir, depth=2), str(snap_dir),
            fast_policy())
    assert plan.log, "the armed crash never fired"
    assert report.restarts == 1
    assert report.resumed_from, "supervisor did not resume from a snapshot"
    assert report.workflow.decision.metrics_history == sync_hist
    report.workflow.stop()


def test_worker_fault_kill_and_resume(tmp_path, direct_transfers):
    """A FaultPlan crash INSIDE the prefetch worker (site pipeline.fetch)
    re-raises on the consumer; the supervisor restarts, restores, and the
    resumed history is bit-exact vs the synchronous run."""
    sync_hist, _ = run_history(4)

    snap_dir = tmp_path / "chaos"
    plan = faults.FaultPlan(seed=99)
    plan.crash_at("pipeline.fetch", at_hit=14)   # mid-epoch-2 on the worker
    with faults.active(plan):
        report = run_supervised(
            lambda: build(4, snap_dir, depth=2), str(snap_dir),
            fast_policy())
    assert plan.log == [{"site": "pipeline.fetch", "action": "crash",
                         "hit": 14}]
    assert report.restarts == 1 and report.resumed_from
    assert report.workflow.decision.metrics_history == sync_hist
    report.workflow.stop()
    assert not _prefetch_threads(), "crashed run leaked a prefetch worker"


# -- backpressure / shutdown -------------------------------------------------

def _standalone_loader():
    prng.seed_all(5)
    loader = SyntheticClassifierLoader(
        None, n_classes=4, sample_shape=(8,), n_train=400, n_valid=0,
        minibatch_size=20)
    loader.initialize(device=NumpyDevice())
    return loader


def test_backpressure_bounds_queue():
    """The producer never runs more than ``depth`` batches ahead of the
    consumer: a slow consumer fills the bounded queue and the worker
    blocks (producer-starved accounting), it does not keep serving."""
    loader = _standalone_loader()
    pf = attach_prefetcher(loader, depth=2)
    try:
        pf.next_batch()                 # starts the worker
        deadline = time.monotonic() + 5.0
        while pf._queue.qsize() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.3)                 # give an unbounded producer rope
        assert pf._queue.qsize() == 2
        assert pf.stats.max_fill <= 2
        # queue(2) + one batch built and blocked on put + one consumed
        assert pf.stats.produced <= 2 + 1
        # draining hands the blocked batch straight through, in order
        offsets = [pf.next_batch().record["offset"] for _ in range(4)]
        assert offsets == [20, 40, 60, 80]
        # the blocked put has now completed: its wait shows up as
        # producer-starved stall time
        assert pf.stats.producer_starved_s > 0.1
    finally:
        pf.stop()


def test_clean_shutdown_on_stop(direct_transfers):
    """Workflow.stop() joins the worker thread (named so leak checks can
    find it); next_batch afterwards raises PrefetcherStopped."""
    w = build(2, depth=2)
    w.run()
    assert _prefetch_threads(), "worker should be parked at the barrier"
    w.stop()
    assert not _prefetch_threads(), "stop() leaked the prefetch worker"
    with pytest.raises(PrefetcherStopped):
        w.input_pipeline.next_batch()


def test_double_attach_refused():
    loader = _standalone_loader()
    attach_prefetcher(loader, depth=1)
    try:
        with pytest.raises(ValueError, match="already has a pipeline"):
            attach_prefetcher(loader, depth=1)
    finally:
        loader.pipeline.stop()


# -- hot-path hygiene / observability ----------------------------------------

def test_no_steady_state_recompiles(direct_transfers):
    """ISSUE 4 acceptance: staged feeding adds zero recompiles — the
    train/eval programs compile exactly once across a multi-epoch
    pipelined run (staged arrays arrive with the step's own shardings)."""
    w = build(3, depth=2)
    w.run()
    for fn in (w.step._train_fn, w.step._eval_fn):
        if hasattr(fn, "_cache_size"):
            assert fn._cache_size() == 1
    w.stop()


def test_timing_table_and_web_status(direct_transfers):
    """Stall accounting surfaces in Workflow.timing_table() and in
    WebStatus.register_pipeline's /status.json block."""
    w = build(2, depth=2)
    w.run()
    table = w.timing_table()
    for col in ("prod_stall", "cons_stall", "stage_s", "bound"):
        assert col in table, table
    status = WebStatus().register(w).register_pipeline(
        "train_input", w.input_pipeline)
    doc = status.snapshot()
    block = doc["pipeline"]["train_input"]
    assert block["depth"] == 2 and block["consumed"] == 2 * 9
    assert block["bound"] in ("producer-starved", "consumer-starved",
                              "transfer-bound", "balanced")
    w.stop()


def test_fill_batch_ring_reuses_buffers():
    """With a slot-detaching stager the pipelined fill path rotates
    depth+2 preallocated buffers instead of allocating per serve (the
    non-pipelined fill_minibatch keeps its defensive fresh-buffer
    copy).  Ring rotation is gated on the stager: without one the raw
    host buffers reach async dispatch, so fills stay fresh-per-serve."""
    loader = _standalone_loader()
    # trivial detaching stager: nothing staged, but the contract (slots
    # never escape to async dispatch) holds — rotation is enabled
    pf = attach_prefetcher(loader, stager=lambda rec, arrays: (None, 0),
                           depth=1)
    try:
        seen = []
        for _ in range(7):
            batch = pf.next_batch()
            seen.append(id(batch.arrays["data"]))
        assert len(set(seen)) == 3          # depth + 2 rotating slots
        # and values are exactly what the sync gather would produce
        batch = pf.next_batch()
        idx = batch.record["indices"][:batch.record["size"]]
        np.testing.assert_array_equal(
            batch.arrays["data"][:len(idx)],
            loader.original_data.mem[idx])
    finally:
        pf.stop()


def test_fill_batch_fresh_buffers_without_stager():
    """A stager-less pipeline must NOT rotate ring slots: the host
    buffers it hands over can be aliased by async dispatch (the hazard
    fill_minibatch's defensive copy exists for), so every serve gets a
    fresh buffer."""
    loader = _standalone_loader()
    pf = attach_prefetcher(loader, depth=1)
    try:
        # hold the arrays so a freed buffer's id cannot be recycled
        held = [pf.next_batch().arrays["data"] for _ in range(5)]
        assert len({id(a) for a in held}) == 5
    finally:
        pf.stop()
