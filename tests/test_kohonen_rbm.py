"""Kohonen SOM + RBM tests (SURVEY.md §3.1 kohonen/rbm rows): op-level
correctness, backend parity, and tier-2 sample convergence."""

import numpy as np
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.memory import Array
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.models import kohonen as kohonen_model, rbm as rbm_model
from znicz_tpu.ops import kohonen as k_ops
from znicz_tpu.units.kohonen import KohonenForward, KohonenTrainer


def test_kohonen_ops_winners_and_hits():
    w = np.array([[0.0, 0.0], [10.0, 10.0], [0.0, 10.0]], np.float32)
    x = np.array([[1.0, 1.0], [9.0, 9.0], [0.5, 9.5], [-1.0, 0.0]],
                 np.float32)
    idx = k_ops.winners(np, x, w)
    np.testing.assert_array_equal(idx, [0, 1, 2, 0])
    idx_x = np.asarray(k_ops.winners(jnp, jnp.asarray(x), jnp.asarray(w)))
    np.testing.assert_array_equal(idx_x, idx)
    np.testing.assert_array_equal(k_ops.hits(np, idx, 3), [2, 1, 1])
    np.testing.assert_array_equal(
        np.asarray(k_ops.hits(jnp, jnp.asarray(idx), 3)), [2, 1, 1])


def test_kohonen_update_moves_toward_data():
    coords = np.asarray(k_ops.grid_coords(np, 2, 2))
    w = np.zeros((4, 2), np.float32)
    x = np.full((8, 2), 4.0, np.float32)
    new_w, idx = k_ops.update(np, x, w, coords, alpha=0.1, sigma=1.0)
    # every neuron moves toward the data (winner most strongly)
    assert np.all(new_w > 0)
    d_before = np.abs(w - 4.0).sum()
    d_after = np.abs(new_w - 4.0).sum()
    assert d_after < d_before


def test_kohonen_trainer_backend_parity():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 3)).astype(np.float32)
    outs = []
    for device in (NumpyDevice(), TPUDevice()):
        prng.seed_all(9)
        w = Workflow(name="t")
        tr = KohonenTrainer(w, shape=(3, 3))
        tr.input = Array(x.copy())
        tr.batch_size = 16
        tr.initialize(device=device)
        tr.run()
        outs.append((tr.weights.map_read().copy(),
                     tr.winners.map_read().copy()))
    np.testing.assert_allclose(outs[0][0], outs[1][0], rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(outs[0][1], outs[1][1])


def test_kohonen_forward_hits_accumulate():
    prng.seed_all(4)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(10, 2)).astype(np.float32)
    w = Workflow(name="t")
    tr = KohonenTrainer(w, shape=(2, 2))
    tr.input = Array(x)
    tr.initialize(device=NumpyDevice())
    fwd = KohonenForward(w, shape=(2, 2))
    fwd.input = Array(x)
    fwd.weights = tr.weights
    fwd.batch_size = 10
    fwd.initialize(device=NumpyDevice())
    fwd.run()
    assert fwd.hits.sum() == 10
    fwd.run()
    assert fwd.hits.sum() == 20


def test_kohonen_demo_workflow_organizes():
    prng.seed_all(23)
    w = kohonen_model.build(max_epochs=6, shape=(6, 6), n_train=400)
    w.initialize(device=TPUDevice())
    w.run()
    dec = w.decision
    assert bool(dec.complete)
    deltas = [h["metric_train"] for h in dec.metrics_history]
    assert deltas[-1] < deltas[0], deltas
    # the map must separate the 4 clusters onto distinct winners
    data = w.loader.original_data.map_read()
    labels = w.loader.original_labels.map_read()
    weights = w.trainer.weights.map_read()
    centroids = np.stack([data[labels == c].mean(axis=0) for c in range(4)])
    win = k_ops.winners(np, centroids.reshape(4, -1), weights)
    assert len(set(win.tolist())) == 4, win


def test_rbm_workflow_reconstruction_improves():
    prng.seed_all(11)
    w = rbm_model.build(max_epochs=6)
    w.initialize(device=TPUDevice())
    w.run()
    dec = w.decision
    assert bool(dec.complete)
    hist = [h["metric_validation"] for h in dec.metrics_history]
    assert hist[-1] < hist[0], hist


def test_kohonen_scan_epoch_matches_eager():
    """Epoch-scan mode (one compiled dispatch per class pass) trains to
    the same weights and reports the same |ΔW| trajectory as the
    per-minibatch path — same seed, same data, same step order."""
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models.kohonen import build

    runs = {}
    for mode in ("eager", "scan"):
        prng.seed_all(77)
        root.common.engine.scan_epoch = (mode == "scan")
        try:
            w = build(max_epochs=4, shape=(6, 6), minibatch_size=40,
                      n_train=200, sample_shape=(3,), min_delta=0.0)
            w.initialize(device=TPUDevice())
            w.run()
        finally:
            root.common.engine.scan_epoch = False
        runs[mode] = {
            "weights": np.asarray(w.trainer.weights.map_read()).copy(),
            "deltas": [h["metric_train"] for h in
                       w.decision.metrics_history],
        }
        if mode == "scan":
            assert w.trainer._scan_fn is not None   # mode actually on
    np.testing.assert_allclose(runs["scan"]["weights"],
                               runs["eager"]["weights"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(runs["scan"]["deltas"],
                               runs["eager"]["deltas"], rtol=1e-4)


def test_kohonen_scan_min_delta_still_stops():
    """The Decision's |ΔW| convergence stop keeps working in scan mode
    (the pre-pass weight snapshot keeps the metric honest)."""
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models.kohonen import build

    prng.seed_all(5)
    root.common.engine.scan_epoch = True
    try:
        w = build(max_epochs=50, shape=(4, 4), minibatch_size=50,
                  n_train=100, sample_shape=(2,), alpha=0.05,
                  radius_decay=0.5, min_delta=0.2)
        w.initialize(device=TPUDevice())
        w.run()
    finally:
        root.common.engine.scan_epoch = False
    # must stop on the delta criterion well before max_epochs, with a
    # real (nonzero) first-epoch delta
    hist = [h["metric_train"] for h in w.decision.metrics_history]
    assert hist[0] > 0.01, hist
    assert len(hist) < 50, len(hist)


def test_kohonen_scan_midpass_falls_back_to_eager():
    """A class pass entered mid-way (restored loader state after resume)
    must still train: the scan guard only fires at offset 0, so the
    remainder of the pass goes through the per-minibatch path."""
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models.kohonen import build

    prng.seed_all(21)
    root.common.engine.scan_epoch = True
    try:
        w = build(max_epochs=3, shape=(4, 4), minibatch_size=25,
                  n_train=100, sample_shape=(2,), min_delta=0.0)
        w.initialize(device=TPUDevice())
        assert w.trainer._scan_fn is not None
        # simulate a resume that landed mid-pass: advance the loader two
        # minibatches without letting the trainer see them
        w.loader.run()
        w.loader.run()
        assert int(w.loader.minibatch_offset) > 0
        w0 = np.asarray(w.trainer.weights.map_read()).copy()
        w.trainer.run()          # mid-pass -> eager fallback, must train
        w1 = np.asarray(w.trainer.weights.map_read())
        assert np.abs(w1 - w0).max() > 0, "mid-pass minibatch not trained"
        assert not w.trainer._scan_in_flight
    finally:
        root.common.engine.scan_epoch = False
