"""Ring×flash composition tests: the Pallas flash kernel as ring
attention's per-block math (parallel/ring_attention.py::
ring_flash_attention), merged across ring steps by lse weight.  All
interpret-mode on the CPU mesh; the compiled path shares every kernel
with the plain flash family the hardware sweep covers."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from znicz_tpu.core import prng
from znicz_tpu.parallel.mesh import make_mesh
from znicz_tpu.parallel import transformer as tfm
from znicz_tpu.parallel.ring_attention import (ring_attention,
                                               ring_flash_attention)


def _dense_o_lse(q, k, v, causal):
    """Folded-layout dense oracle returning (o, lse) exactly as the
    kernel defines them (same -1e30 mask constant)."""
    dh = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(dh)
    if causal:
        t = s.shape[-1]
        qpos = jnp.arange(t)[:, None]
        kpos = jnp.arange(t)[None, :]
        s = jnp.where(kpos > qpos, jnp.float32(-1e30), s)
    lse = jax.nn.logsumexp(s, axis=-1, keepdims=True)
    o = jnp.einsum("bqk,bkd->bqd", jnp.exp(s - lse), v)
    return o, lse


def test_flash_lse_grads_match_dense_oracle():
    """flash_attention_lse: BOTH outputs differentiable — the lse
    cotangent folds into the shared backward kernel as Δ−dlse.  Loss
    touches o and lse with independent random weights so dlse ≠ 0."""
    from znicz_tpu.ops.pallas.attention import flash_attention_lse

    bh, t, dh = 2, 256, 64
    rng = np.random.default_rng(3)
    q, k, v, wo, wl = (jnp.asarray(
        rng.normal(size=sh).astype(np.float32)) for sh in
        [(bh, t, dh)] * 4 + [(bh, t, 1)])

    for causal in (False, True):
        def loss_flash(q, k, v):
            o, lse = flash_attention_lse(q, k, v, causal, True)
            return (o * wo).sum() + (lse * wl).sum()

        def loss_dense(q, k, v):
            o, lse = _dense_o_lse(q, k, v, causal)
            return (o * wo).sum() + (lse * wl).sum()

        lf, gf = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        ld, gd = jax.value_and_grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        # the loss is an f32 sum over bh*t*dh ≈ 33k terms: block-wise vs
        # dense accumulation order alone moves the scalar by ~1.6e-5
        # relative on some BLAS builds — 3e-5 still pins the math while
        # tolerating summation-order noise (grads keep their own band)
        np.testing.assert_allclose(float(lf), float(ld), rtol=3e-5)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


def _shard_ring(fn_inner, mesh, **kw):
    from znicz_tpu.parallel.transformer import shard_map

    spec = P(None, "seq", None, None)
    return shard_map(fn_inner, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, **kw)


def test_ring_flash_matches_dense_and_ring(cpu_devices):
    """ring_flash_attention over a 2-way sharded seq axis == dense
    attention on the full sequence == the dense-local ring path, values
    AND grads, causal and non-causal.  The vma relaxation the
    interpret-mode Pallas path needs comes from the parallel/compat.py
    shard_map shim; the grad parity against the no-pallas ring path is
    exactly the check that the relaxed psum transposition did not
    corrupt AD here."""
    mesh = make_mesh({"data": 1, "seq": 2, "model": 1})
    b, t, h, dh = 1, 512, 2, 64
    rng = np.random.default_rng(5)
    q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, dh))
                           .astype(np.float32)) for _ in range(3))

    for causal in (False, True):
        ringf = _shard_ring(
            lambda q, k, v: ring_flash_attention(
                q, k, v, "seq", causal=causal, interpret=True), mesh)
        ringd = _shard_ring(
            lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
            mesh)

        def dense(q, k, v):
            fold = q.transpose(0, 2, 1, 3).reshape(b * h, t, dh)
            o, _ = _dense_o_lse(fold,
                                k.transpose(0, 2, 1, 3).reshape(
                                    b * h, t, dh),
                                v.transpose(0, 2, 1, 3).reshape(
                                    b * h, t, dh), causal)
            return o.reshape(b, h, t, dh).transpose(0, 2, 1, 3)

        o_rf = ringf(q, k, v)
        np.testing.assert_allclose(np.asarray(o_rf),
                                   np.asarray(dense(q, k, v)),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(o_rf),
                                   np.asarray(ringd(q, k, v)),
                                   rtol=2e-4, atol=2e-4)

        # grads: scalar loss touching every output element
        wsum = jnp.asarray(rng.normal(size=(b, t, h, dh))
                           .astype(np.float32))
        g_rf = jax.grad(lambda *a: (ringf(*a) * wsum).sum(),
                        argnums=(0, 1, 2))(q, k, v)
        g_de = jax.grad(lambda *a: (dense(*a) * wsum).sum(),
                        argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_rf, g_de):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=3e-4, atol=3e-4)


def test_transformer_ring_flash_forward_matches_ring(cpu_devices):
    """The full-transformer composition on a seq=2 mesh: ring_flash's
    eval loss (forward through every block + psum'd CE) matches the
    dense-local ring path at several param draws.

    FORWARD-ONLY on purpose.  Interpret-mode Pallas needs
    ``check_vma=False`` on a multi-device mesh (the HLO interpreter's
    internal dynamic_slices trip the checker — verified directly), and
    the relaxed checker corrupts REPLICATED-param gradient reduction at
    seq>1 (measured: losses diverge from step 2).  The composition's AD
    itself is pinned by test_ring_flash_matches_dense_and_ring (grads
    through shard_map w.r.t. all inputs); replicated-grad integration
    runs compiled on real hardware where the checker stays ON."""
    from znicz_tpu.core.config import root
    from znicz_tpu.ops.pallas.attention import supported

    n_layers, d, heads, ff, vocab = 1, 128, 2, 64, 11
    assert supported(128, d // heads)     # t_loc=128 per seq shard
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, vocab, (2, 256)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)

    # plain sp, and sp COMPOSED with tp (heads sharded: tp2 leaves one
    # local head, dh=64 still passes the flash gate)
    for axes in ({"data": 1, "seq": 2, "model": 1},
                 {"data": 1, "seq": 2, "model": 2}):
        mesh = make_mesh(axes)
        losses = {}
        for name, flags in (
                ("ring", {"flash_attention": False}),
                ("ring_flash", {"flash_attention": True,
                                "pallas_interpret": True,
                                "ring_flash_interpret": True})):
            for key, val in flags.items():
                setattr(root.common.engine, key, val)
            try:
                ev = tfm.make_eval_loss(mesh, n_layers, d, heads, ff,
                                        vocab)
                run = []
                for seed in (13, 29, 57):
                    prng.seed_all(seed)
                    params = tfm.init_params(prng.get(), n_layers, d,
                                             heads, ff, vocab)
                    run.append(float(ev(params, tokens, labels)))
                losses[name] = run
            finally:
                root.common.engine.flash_attention = True
                root.common.engine.pallas_interpret = False
                root.common.engine.ring_flash_interpret = False
        np.testing.assert_allclose(losses["ring_flash"], losses["ring"],
                                   rtol=1e-4, atol=1e-5, err_msg=str(axes))
