"""Tier-1 tests for the conv/pooling/LRN op layer: numpy-im2col oracle vs
XLA-native lowering parity + numeric-derivative checks (SURVEY.md §5 —
the rebuild of the reference's ocl-vs-numpy kernel tests)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from znicz_tpu.ops import activations, conv as conv_ops, lrn as lrn_ops
from znicz_tpu.ops import pooling as pool_ops

GEOMS = [
    # (h, w, cin, cout, ky, kx, sliding, padding)
    (6, 7, 3, 4, 3, 3, (1, 1), (0, 0, 0, 0)),
    (8, 8, 2, 5, 3, 2, (2, 2), (1, 1, 1, 1)),
    (5, 9, 1, 2, 2, 4, (1, 3), (2, 0, 1, 3)),
]


@pytest.mark.parametrize("geom", GEOMS)
def test_conv_forward_numpy_vs_xla(geom):
    h, w, cin, cout, ky, kx, sl, pad = geom
    rng = np.random.default_rng(0)
    x = rng.normal(size=(3, h, w, cin)).astype(np.float32)
    wt = rng.normal(size=(ky, kx, cin, cout)).astype(np.float32) * 0.3
    b = rng.normal(size=(cout,)).astype(np.float32)
    want = conv_ops.forward(np, x, wt, b, sl, pad, activations.TANH)
    got = np.asarray(conv_ops.forward(jnp, jnp.asarray(x), jnp.asarray(wt),
                                      jnp.asarray(b), sl, pad,
                                      activations.TANH))
    assert want.shape == got.shape
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("geom", GEOMS)
def test_conv_backward_numpy_vs_xla(geom):
    h, w, cin, cout, ky, kx, sl, pad = geom
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, h, w, cin)).astype(np.float32)
    wt = rng.normal(size=(ky, kx, cin, cout)).astype(np.float32) * 0.3
    y = conv_ops.forward(np, x, wt, None, sl, pad, activations.LINEAR)
    err = rng.normal(size=y.shape).astype(np.float32)
    ein_np, gw_np, gb_np = conv_ops.backward(
        np, x, y, wt, err, sl, pad, activations.LINEAR)
    ein_x, gw_x, gb_x = conv_ops.backward(
        jnp, jnp.asarray(x), jnp.asarray(y), jnp.asarray(wt),
        jnp.asarray(err), sl, pad, activations.LINEAR)
    np.testing.assert_allclose(np.asarray(ein_x), ein_np, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw_x), gw_np, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb_x), gb_np, rtol=1e-4, atol=1e-4)


def test_conv_backward_matches_numeric():
    """Finite-difference check of the numpy oracle (err_input and grad_w)."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(2, 5, 5, 2)).astype(np.float64)
    wt = rng.normal(size=(3, 3, 2, 3)).astype(np.float64) * 0.4
    sl, pad = (2, 2), (1, 1, 1, 1)
    err = rng.normal(size=conv_ops.forward(np, x, wt, None, sl, pad).shape)

    def loss_x(xx):
        return (conv_ops.forward(np, xx, wt, None, sl, pad) * err).sum()

    def loss_w(ww):
        return (conv_ops.forward(np, x, ww, None, sl, pad) * err).sum()

    ein, gw, _ = conv_ops.backward(np, x, None, wt, err, sl, pad,
                                   activations.LINEAR,
                                   activation_applied=False)
    eps = 1e-6
    for arr, grad, loss in ((x, ein, loss_x), (wt, gw, loss_w)):
        flat = arr.ravel()
        for i in rng.choice(flat.size, 12, replace=False):
            old = flat[i]
            flat[i] = old + eps
            up = loss(arr)
            flat[i] = old - eps
            down = loss(arr)
            flat[i] = old
            np.testing.assert_allclose(grad.ravel()[i], (up - down) / (2 * eps),
                                       rtol=1e-4, atol=1e-6)


def test_ref_weights_roundtrip():
    rng = np.random.default_rng(3)
    w = rng.normal(size=(3, 2, 4, 5)).astype(np.float32)
    ref = conv_ops.ref_weights_view(w)
    assert ref.shape == (5, 3 * 2 * 4)
    np.testing.assert_array_equal(conv_ops.from_ref_weights(ref, 3, 2, 4), w)


POOL_GEOMS = [
    (6, 6, 2, 2, (2, 2)),     # exact tiling
    (7, 5, 3, 2, (2, 2)),     # partial border windows
    (5, 5, 2, 2, (1, 1)),     # overlapping
]


@pytest.mark.parametrize("geom", POOL_GEOMS)
@pytest.mark.parametrize("use_abs", [False, True])
def test_max_pooling_numpy_vs_xla(geom, use_abs):
    h, w, ky, kx, sl = geom
    rng = np.random.default_rng(4)
    x = rng.normal(size=(2, h, w, 3)).astype(np.float32)
    y_np, off_np = pool_ops.max_forward(np, x, ky, kx, *sl, use_abs=use_abs)
    y_x, off_x = pool_ops.max_forward(jnp, jnp.asarray(x), ky, kx, *sl,
                                      use_abs=use_abs)
    np.testing.assert_allclose(np.asarray(y_x), y_np, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(off_x), off_np)
    # winner offsets point at elements with the winning value
    n, oh, ow, c = y_np.shape
    flat = x.reshape(2, -1, 3)
    for ni in range(n):
        for ci in range(c):
            picked = flat[ni, off_np[ni, :, :, ci].ravel(), ci]
            np.testing.assert_allclose(picked, y_np[ni, :, :, ci].ravel())


@pytest.mark.parametrize("geom", POOL_GEOMS)
def test_avg_pooling_numpy_vs_xla_and_border_counts(geom):
    h, w, ky, kx, sl = geom
    rng = np.random.default_rng(5)
    x = rng.normal(size=(2, h, w, 3)).astype(np.float32)
    y_np = pool_ops.avg_forward(np, x, ky, kx, *sl)
    y_x = pool_ops.avg_forward(jnp, jnp.asarray(x), ky, kx, *sl)
    np.testing.assert_allclose(np.asarray(y_x), y_np, rtol=1e-5, atol=1e-6)
    # ones stay ones even in clipped border windows (count-correct divide)
    ones = np.ones((1, h, w, 1), np.float32)
    np.testing.assert_allclose(pool_ops.avg_forward(np, ones, ky, kx, *sl),
                               1.0, rtol=1e-6)


@pytest.mark.parametrize("geom", POOL_GEOMS)
@pytest.mark.parametrize("kind", ["max", "maxabs", "avg"])
def test_fast_pooling_matches_eager_values_and_grads(geom, kind):
    """The reduce_window fused-path pooling must match the patch-tensor
    eager path in VALUES and GRADIENTS on every border geometry — the
    flagship bench trains through the fast path."""
    import jax

    h, w, ky, kx, sl = geom
    rng = np.random.default_rng(6)
    x = rng.normal(size=(2, h, w, 3)).astype(np.float32)
    xj = jnp.asarray(x)
    if kind == "max":
        eager = lambda a: pool_ops.max_forward(jnp, a, ky, kx, *sl)[0]
        fast = lambda a: pool_ops.max_forward_fast(a, ky, kx, *sl)
    elif kind == "maxabs":
        eager = lambda a: pool_ops.max_forward(jnp, a, ky, kx, *sl,
                                               use_abs=True)[0]
        fast = lambda a: pool_ops.maxabs_forward_fast(a, ky, kx, *sl)
    else:
        eager = lambda a: pool_ops.avg_forward(jnp, a, ky, kx, *sl)
        fast = lambda a: pool_ops.avg_forward_fast(a, ky, kx, *sl)
    np.testing.assert_allclose(np.asarray(fast(xj)), np.asarray(eager(xj)),
                               rtol=1e-6, atol=1e-6)
    g_fast = jax.grad(lambda a: (fast(a) ** 2).sum())(xj)
    g_eager = jax.grad(lambda a: (eager(a) ** 2).sum())(xj)
    np.testing.assert_allclose(np.asarray(g_fast), np.asarray(g_eager),
                               rtol=1e-5, atol=1e-6)


def test_max_pool_scatter_roundtrip():
    rng = np.random.default_rng(6)
    x = rng.normal(size=(2, 6, 6, 2)).astype(np.float32)
    y, off = pool_ops.max_forward(np, x, 2, 2, 2, 2)
    err = rng.normal(size=y.shape).astype(np.float32)
    ein_np = pool_ops.scatter_backward(np, err, off, x.shape)
    ein_x = pool_ops.scatter_backward(jnp, jnp.asarray(err),
                                      jnp.asarray(off), x.shape)
    np.testing.assert_allclose(np.asarray(ein_x), ein_np, rtol=1e-6)
    assert abs(ein_np.sum() - err.sum()) < 1e-4  # scatter conserves mass


def test_avg_pool_backward_numpy_vs_xla():
    rng = np.random.default_rng(7)
    in_shape = (2, 7, 5, 3)
    err = rng.normal(size=(2, 3, 3, 3)).astype(np.float32)
    ein_np = pool_ops.avg_backward(np, err, in_shape, 3, 2, 2, 2)
    ein_x = pool_ops.avg_backward(jnp, jnp.asarray(err), in_shape, 3, 2, 2, 2)
    np.testing.assert_allclose(np.asarray(ein_x), ein_np, rtol=1e-5,
                               atol=1e-6)
    assert abs(ein_np.sum() - err.sum()) < 1e-4


def test_stochastic_pooling_determinism_and_expectation():
    rng = np.random.default_rng(8)
    x = np.abs(rng.normal(size=(2, 6, 6, 2))).astype(np.float32)
    u = rng.uniform(size=(2, 3, 3, 2)).astype(np.float32)
    y1, off1 = pool_ops.stochastic_forward(np, x, 2, 2, 2, 2, u, False, True)
    y2, off2 = pool_ops.stochastic_forward(np, x, 2, 2, 2, 2, u, False, True)
    np.testing.assert_array_equal(y1, y2)       # same uniforms => same sample
    np.testing.assert_array_equal(off1, off2)
    yj, _ = pool_ops.stochastic_forward(jnp, jnp.asarray(x), 2, 2, 2, 2,
                                        jnp.asarray(u), False, True)
    np.testing.assert_allclose(np.asarray(yj), y1, rtol=1e-6)
    # inference mode = expectation, between min and max of each window
    ye, off = pool_ops.stochastic_forward(np, x, 2, 2, 2, 2, None, False,
                                          False)
    assert off is None
    ymax, _ = pool_ops.max_forward(np, x, 2, 2, 2, 2)
    assert (ye <= ymax + 1e-6).all()
    assert (ye >= 0).all()


def test_stochastic_pooling_zero_total_window_in_bounds():
    """All-nonpositive windows must sample an in-bounds element (the window
    origin), so the backward scatter never indexes padded slots."""
    x = -np.ones((1, 3, 3, 1), np.float32)
    u = np.full((1, 2, 2, 1), 0.7, np.float32)
    y, off = pool_ops.stochastic_forward(np, x, 2, 2, 2, 2, u, False, True)
    assert (off < 9).all()
    np.testing.assert_allclose(y, -1.0)
    # backward scatter works on these offsets
    ein = pool_ops.scatter_backward(np, np.ones_like(y), off, x.shape)
    assert ein.shape == x.shape and abs(ein.sum() - 4.0) < 1e-6


def test_lrn_forward_backward_parity_and_numeric():
    rng = np.random.default_rng(9)
    x = rng.normal(size=(2, 4, 4, 8)).astype(np.float64)
    args = (1e-4, 0.75, 2.0, 5)
    y_np = lrn_ops.forward(np, x, *args)
    y_x = lrn_ops.forward(jnp, jnp.asarray(x), *args)
    np.testing.assert_allclose(np.asarray(y_x), y_np, rtol=1e-5, atol=1e-6)
    err = rng.normal(size=x.shape)
    ein_np = lrn_ops.backward(np, x, err, *args)
    ein_x = lrn_ops.backward(jnp, jnp.asarray(x), jnp.asarray(err), *args)
    np.testing.assert_allclose(np.asarray(ein_x), ein_np, rtol=1e-5,
                               atol=1e-6)
    # numeric check (exact derivative claim, SURVEY.md §3.2 LRN bwd)
    eps = 1e-6
    flat = x.ravel()
    for i in rng.choice(flat.size, 10, replace=False):
        old = flat[i]
        flat[i] = old + eps
        up = (lrn_ops.forward(np, x, *args) * err).sum()
        flat[i] = old - eps
        down = (lrn_ops.forward(np, x, *args) * err).sum()
        flat[i] = old
        np.testing.assert_allclose(ein_np.ravel()[i], (up - down) / (2 * eps),
                                   rtol=1e-4, atol=1e-7)


def test_lrn_backward_even_window_numeric():
    """Even n makes the channel window asymmetric; the backward must use
    the adjoint (mirrored) padding — regression for the even-n gradient."""
    rng = np.random.default_rng(14)
    x = rng.normal(size=(2, 3, 3, 6)).astype(np.float64)
    err = rng.normal(size=x.shape)
    for n in (2, 4):
        args = (1e-2, 0.75, 2.0, n)
        ein = lrn_ops.backward(np, x, err, *args)
        eps = 1e-6
        flat = x.ravel()
        for i in rng.choice(flat.size, 8, replace=False):
            old = flat[i]
            flat[i] = old + eps
            up = (lrn_ops.forward(np, x, *args) * err).sum()
            flat[i] = old - eps
            down = (lrn_ops.forward(np, x, *args) * err).sum()
            flat[i] = old
            np.testing.assert_allclose(
                ein.ravel()[i], (up - down) / (2 * eps),
                rtol=1e-4, atol=1e-7, err_msg=f"n={n}")


def test_lrn_autograd_matches_hand_backward():
    """The fused step differentiates the jnp forward with AD; pin that AD
    and the hand-written exact backward agree."""
    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.normal(size=(2, 3, 3, 6)).astype(np.float32))
    err = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))
    args = (1e-4, 0.75, 2.0, 5)
    _, vjp = jax.vjp(lambda xx: lrn_ops.forward(jnp, xx, *args), x)
    (ein_ad,) = vjp(err)
    ein_hand = lrn_ops.backward(jnp, x, err, *args)
    np.testing.assert_allclose(np.asarray(ein_ad), np.asarray(ein_hand),
                               rtol=1e-4, atol=1e-5)


def test_avg_pool_fast_grad_under_shard_map(cpu_devices):
    """Regression: reduce_window-sum with a TRACED init value fails
    linearization under shard_map ("Linearization failed to produce
    known values for all output primals") — the init must be a concrete
    scalar.  Found by the composition fuzzer; pinned here at op level."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from znicz_tpu.ops import pooling
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    mesh = data_parallel_mesh(4)

    def f(x):
        return pooling.avg_forward_fast(x, 2, 2, 2, 2).sum()

    def local(x):
        return jax.lax.psum(jax.grad(f)(x), "data")

    x = jnp.arange(8 * 6 * 6 * 3, dtype=jnp.float32).reshape(8, 6, 6, 3)
    g = jax.jit(shard_map(local, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data")))(x)
    # each input cell belongs to exactly one full 2x2 window -> grad sums
    # to the number of output cells per shard times psum over 4 replicas
    assert g.shape == x.shape
    np.testing.assert_allclose(np.asarray(g),
                               np.full(x.shape, 4 * 0.25), rtol=1e-6)


def test_stochastic_fast_path_matches_ad_route():
    """stochastic_forward_fast (masks + dilated pads backward) vs AD
    through the patch/take_along_axis route: same sampled winners, same
    values, gradient support identical, magnitudes within sum-order
    tolerance; uniform's cotangent is zero."""
    import jax

    rng = np.random.default_rng(7)
    for shape, ky, kx, sy, sx in [((2, 8, 8, 3), 3, 3, 2, 2),
                                  ((2, 6, 6, 2), 2, 2, 2, 2),
                                  ((1, 11, 11, 1), 2, 2, 4, 4)]:
        for use_abs in (False, True):
            x = rng.normal(size=shape).astype(np.float32)
            oh = pool_ops.pool_out_size(shape[1], ky, sy)
            ow = pool_ops.pool_out_size(shape[2], kx, sx)
            u = rng.uniform(size=(shape[0], oh, ow, shape[3])) \
                .astype(np.float32)
            xj, uj = jnp.asarray(x), jnp.asarray(u)
            yn, vjp_new = jax.vjp(
                lambda t, uu: pool_ops.stochastic_forward_fast(
                    t, uu, ky, kx, sy, sx, use_abs), xj, uj)
            yo, vjp_old = jax.vjp(
                lambda t: pool_ops.stochastic_forward(
                    jnp, t, ky, kx, sy, sx, uj, use_abs, True)[0], xj)
            np.testing.assert_array_equal(np.asarray(yn),
                                          np.asarray(yo))
            g = jnp.asarray(rng.normal(size=yn.shape).astype(np.float32))
            dn, du = vjp_new(g)
            do, = vjp_old(g)
            dn, do = np.asarray(dn), np.asarray(do)
            np.testing.assert_array_equal(dn != 0, do != 0)
            np.testing.assert_allclose(dn, do, rtol=1e-6, atol=1e-6)
            assert not np.asarray(du).any()
