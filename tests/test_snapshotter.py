"""Checkpoint/resume tests (SURVEY.md §4.3 + §5 tier-2): snapshot mid-run,
reload into a fresh workflow, continue, assert the metric history is
identical to an uninterrupted run — the reference's resume-exactness trick,
here over the array-based .npz state dict instead of object pickles."""

import os

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.snapshotter import collect_state, restore_state, write_snapshot
from znicz_tpu.standard_workflow import StandardWorkflow

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 24},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 6},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
]
LOADER = {"n_classes": 6, "sample_shape": (10, 10), "n_train": 240,
          "n_valid": 120, "minibatch_size": 40, "spread": 2.5, "noise": 1.0}


def build(max_epochs, snap_dir=None, fused=True, seed=77, **snap_kw):
    prng.seed_all(seed)
    cfg = None
    if snap_dir is not None:
        cfg = {"directory": str(snap_dir), "prefix": "t",
               "only_improved": False, "keep_all": True, **snap_kw}
    w = StandardWorkflow(
        name="SnapTest", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": max_epochs},
        snapshotter_config=cfg, fused=fused)
    w.initialize(device=TPUDevice())
    return w


@pytest.mark.parametrize("fused", [True, False])
def test_resume_is_bit_exact(tmp_path, fused):
    # uninterrupted 4-epoch run, snapshotting every epoch
    w_full = build(4, tmp_path, fused=fused)
    w_full.run()
    full_hist = w_full.decision.metrics_history
    assert len(full_hist) == 4
    snap2 = tmp_path / "t_2.npz"
    assert snap2.exists(), sorted(os.listdir(tmp_path))

    # fresh workflow, restore the epoch-2 snapshot, continue to epoch 4.
    # Same seed: the snapshot stores training state, not the dataset — the
    # loader must reload identical data (reference semantics; synthetic
    # data is seed-derived, a real-file loader would reread the files).
    w_res = build(4, fused=fused, seed=77)
    meta = restore_state(w_res, str(snap2))
    assert meta["loader"]["epoch_number"] == 2
    w_res.run()
    res_hist = w_res.decision.metrics_history
    assert res_hist == full_hist, (res_hist, full_hist)
    # final weights identical too (stop() syncs fused device params back)
    w_full.stop()
    w_res.stop()
    np.testing.assert_array_equal(
        w_full.forwards[0].weights.map_read(),
        w_res.forwards[0].weights.map_read())


def test_snapshot_roundtrip_arrays(tmp_path):
    w = build(1)
    w.run()
    arrays, meta = collect_state(w)
    assert any(k.startswith("forward.0.weights") for k in arrays)
    assert any(k.startswith("gd.0.gradient_weights") for k in arrays)
    path = str(tmp_path / "s.npz")
    write_snapshot(path, arrays, meta)
    w2 = build(1, seed=9)
    restore_state(w2, path)
    np.testing.assert_array_equal(w2.forwards[0].weights.map_read(),
                                  arrays["forward.0.weights"])
    np.testing.assert_array_equal(
        w2.gds[0].gradient_weights.map_read(),
        arrays["gd.0.gradient_weights"])


@pytest.mark.parametrize("from_dev,to_dev", [(1, 8), (8, 1)])
def test_elastic_resume_across_mesh_sizes(tmp_path, cpu_devices, from_dev,
                                          to_dev):
    """SURVEY.md §6.3: the framework's answer to the reference's slave
    churn is snapshot -> restore onto a DIFFERENT mesh size -> continue.
    Params are stored as host arrays and re-placed on the target mesh, so
    the epoch metrics after resume must match an uninterrupted run (data
    parallelism is the same math at any mesh size)."""
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    # uninterrupted 4-epoch reference run (1-device mesh)
    prng.seed_all(77)
    w_full = StandardWorkflow(
        name="SnapTest", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": 4}, fused=True,
        mesh=data_parallel_mesh(1))
    w_full.initialize(device=TPUDevice())
    w_full.run()
    full_hist = w_full.decision.metrics_history

    # full run on the source mesh, snapshotting every epoch; the epoch-2
    # snapshot is the "job killed mid-run" state an elastic restart sees
    prng.seed_all(77)
    w_a = StandardWorkflow(
        name="SnapTest", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": 4},
        snapshotter_config={"directory": str(tmp_path), "prefix": "e",
                            "only_improved": False, "keep_all": True},
        fused=True, mesh=data_parallel_mesh(from_dev))
    w_a.initialize(device=TPUDevice())
    w_a.run()
    snap = tmp_path / "e_2.npz"
    assert snap.exists()

    # resume onto the TARGET mesh size and finish (same seed: the
    # synthetic dataset derives from it and is not part of the snapshot)
    prng.seed_all(77)
    w_b = StandardWorkflow(
        name="SnapTest", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": 4}, fused=True,
        mesh=data_parallel_mesh(to_dev))
    w_b.initialize(device=TPUDevice())
    restore_state(w_b, str(snap))
    w_b.run()
    resumed = w_b.decision.metrics_history
    assert [h["metric_validation"] for h in resumed] == \
        [h["metric_validation"] for h in full_hist], (resumed, full_hist)
    w_full.stop()
    w_b.stop()
    np.testing.assert_allclose(w_b.forwards[0].weights.map_read(),
                               w_full.forwards[0].weights.map_read(),
                               rtol=1e-4, atol=1e-5)


def test_snapshot_kohonen_workflow(tmp_path):
    """Regression (r1 advisor): KohonenTrainer sits in ``forwards`` but has
    no ``bias`` — collect_state/restore_state must tolerate non-standard
    forwards instead of raising AttributeError."""
    from znicz_tpu.models import kohonen as kohonen_model

    prng.seed_all(23)
    w = kohonen_model.build(max_epochs=2, shape=(6, 6), n_train=200)
    w.initialize(device=TPUDevice())
    w.run()
    arrays, meta = collect_state(w)
    assert "forward.0.weights" in arrays
    assert "forward.0.bias" not in arrays
    path = str(tmp_path / "som.npz")
    write_snapshot(path, arrays, meta)

    prng.seed_all(9)
    w2 = kohonen_model.build(max_epochs=2, shape=(6, 6), n_train=200)
    w2.initialize(device=TPUDevice())
    restore_state(w2, path)
    np.testing.assert_array_equal(w2.trainer.weights.map_read(),
                                  arrays["forward.0.weights"])


def test_only_improved_and_latest_symlink(tmp_path):
    w = build(3, tmp_path, only_improved=True, keep_all=False)
    w.snapshotter.only_improved = True
    w.snapshotter.keep_all = False
    w.run()
    snaps = [f for f in os.listdir(tmp_path) if not f.endswith("latest.npz")]
    # non-improving epochs skipped + old snapshots pruned -> exactly one
    assert len(snaps) == 1, snaps
    latest = tmp_path / "t_latest.npz"
    if latest.exists():
        assert os.readlink(latest) == snaps[0]
