"""Tier-2 tests for the declarative StandardWorkflow builder (SURVEY.md §2
L7): layers=[{...}] -> full training graph, both execution shapes (fused
one-XLA-program and eager per-unit), softmax and mse losses."""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.loader.base import get_loader
from znicz_tpu.standard_workflow import StandardWorkflow


CONV_LAYERS = [
    {"type": "conv_relu", "->": {"n_kernels": 8, "kx": 3, "ky": 3,
                                 "padding": (1, 1, 1, 1)},
     "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
    {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
    {"type": "all2all_tanh", "->": {"output_sample_shape": 32},
     "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 5},
     "<-": {"learning_rate": 0.03, "gradient_moment": 0.9}},
]

IMAGE_LOADER = {"n_classes": 5, "sample_shape": (12, 12, 3), "n_train": 250,
                "n_valid": 100, "minibatch_size": 50, "spread": 2.5,
                "noise": 1.0}


def build_conv(fused, max_epochs=3, seed=21):
    prng.seed_all(seed)
    w = StandardWorkflow(
        name="ConvStd", layers=CONV_LAYERS, loss_function="softmax",
        loader_name="synthetic_image", loader_config=IMAGE_LOADER,
        decision_config={"max_epochs": max_epochs}, fused=fused)
    w.initialize(device=TPUDevice())
    w.run()
    return w


@pytest.mark.parametrize("fused", [True, False])
def test_conv_standard_workflow_converges(fused):
    w = build_conv(fused)
    dec = w.decision
    assert bool(dec.complete)
    assert len(dec.metrics_history) == 3
    first = dec.metrics_history[0]["metric_validation"]
    last = dec.metrics_history[-1]["metric_validation"]
    assert last < first, dec.metrics_history
    assert dec.epoch_n_err_pt[1] < 20.0, dec.metrics_history


def test_fused_and_eager_shapes_agree():
    """Both execution shapes, same seed: error trajectories in the same
    ballpark (backward math identity is pinned per-op elsewhere; here we
    check the builder wired both graphs correctly)."""
    w_f = build_conv(True, max_epochs=2, seed=33)
    w_e = build_conv(False, max_epochs=2, seed=33)
    # identical init: same seed -> same first-epoch forward weights
    np.testing.assert_array_equal(w_f.forwards[0].weights.map_read().shape,
                                  w_e.forwards[0].weights.map_read().shape)
    for m_f, m_e in zip(w_f.decision.metrics_history,
                        w_e.decision.metrics_history):
        assert abs(m_f["metric_validation"] - m_e["metric_validation"]) <= 8, \
            (w_f.decision.metrics_history, w_e.decision.metrics_history)


@pytest.mark.parametrize("fused", [True, False])
def test_mse_standard_workflow(fused):
    prng.seed_all(5)
    w = StandardWorkflow(
        name="RegStd",
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 24},
             "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
            {"type": "all2all", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
        ],
        loss_function="mse", loader_name="synthetic_regression",
        loader_config={"sample_shape": (16,), "target_shape": (4,),
                       "n_train": 256, "n_valid": 64, "minibatch_size": 32},
        decision_config={"max_epochs": 3}, fused=fused)
    w.initialize(device=TPUDevice())
    w.run()
    dec = w.decision
    assert bool(dec.complete)
    first = dec.metrics_history[0]["metric_validation"]
    last = dec.metrics_history[-1]["metric_validation"]
    assert last < first * 0.9, dec.metrics_history


def test_flat_shorthand_and_registry():
    assert get_loader("synthetic_classifier").LOADER_NAME == \
        "synthetic_classifier"
    with pytest.raises(KeyError):
        get_loader("nope")
    prng.seed_all(3)
    w = StandardWorkflow(
        name="Flat",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16,
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "output_sample_shape": 10}],
        loader_name="synthetic_classifier",
        loader_config={"minibatch_size": 20, "n_train": 100, "n_valid": 0},
        decision_config={"max_epochs": 1})
    w.initialize(device=TPUDevice())
    w.run()
    assert bool(w.decision.complete)
    assert w.forwards[0].output_sample_shape == (16,)


def test_bad_specs_raise():
    with pytest.raises(KeyError):
        StandardWorkflow(layers=[{"type": "wat"}],
                         loader_name="synthetic_classifier")
    with pytest.raises(ValueError):
        StandardWorkflow(
            layers=[{"type": "all2all", "output_sample_shape": 4}],
            loss_function="softmax", loader_name="synthetic_classifier")
    with pytest.raises(ValueError):
        StandardWorkflow(layers=[], loader_name="synthetic_classifier")
