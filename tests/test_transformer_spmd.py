"""Flagship sharded-transformer tests on the 8-device CPU mesh: the
dp x sp x tp train step runs and learns; the dp x pipe x expert step runs
and learns; both exercise every mesh axis the framework supports."""

import pytest

# full SPMD training runs on the virtual 8-device CPU mesh take
# minutes per file; tier-1 (-m 'not slow') must fit its 870 s
# budget, so these ride the registered slow lane
pytestmark = pytest.mark.slow

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.parallel.mesh import make_mesh
from znicz_tpu.parallel import transformer as tfm


def test_dp_sp_tp_train_step_learns(cpu_devices):
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    prng.seed_all(5)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 2, 32, 4, 64, 17
    params = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    step, _ = tfm.make_train_step(mesh, n_layers, d, heads, ff, vocab,
                                  lr=0.2)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, vocab, (4, 16)).astype(np.int32)
    # learnable synthetic rule: label = (token + 1) mod vocab
    labels = ((tokens + 1) % vocab).astype(np.int32)
    losses = []
    for _ in range(30):
        params, loss = step(params, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_dp_sp_tp_matches_tp1(cpu_devices):
    """The sharded step computes the same loss as a 1x1x1 mesh (same math,
    different partitioning)."""
    prng.seed_all(7)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 1, 16, 2, 32, 11
    params = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, vocab, (4, 8)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)

    losses = {}
    for name, axes in (("sharded", {"data": 2, "seq": 2, "model": 2}),
                       ("single", {"data": 1, "seq": 1, "model": 1})):
        step, _ = tfm.make_train_step(
            make_mesh(axes), n_layers, d, heads, ff, vocab, lr=0.1)
        p = {k: (v if not isinstance(v, list) else
                 [dict(b) for b in v]) for k, v in params.items()}
        _, loss = step(p, tokens, labels)
        losses[name] = float(loss)
    np.testing.assert_allclose(losses["sharded"], losses["single"],
                               rtol=2e-4)


def test_bf16_step_tracks_f32(cpu_devices):
    """Mixed precision (bf16 compute, f32 masters) trains the same
    function: per-step losses track the f32 oracle within bf16's ~3
    decimal digits, and params stay f32 throughout."""
    import jax
    import jax.numpy as jnp

    prng.seed_all(11)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 1, 16, 2, 32, 11
    params = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, vocab, (4, 8)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})

    losses = {}
    for name, cdt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        step, _ = tfm.make_train_step(mesh, n_layers, d, heads, ff, vocab,
                                      lr=0.1, compute_dtype=cdt)
        p = {k: (v if not isinstance(v, list) else
                 [dict(b) for b in v]) for k, v in params.items()}
        run = []
        for _ in range(5):
            p, loss = step(p, tokens, labels)
            run.append(float(loss))
        losses[name] = run
        assert all(leaf.dtype == jnp.float32
                   for leaf in jax.tree.leaves(p)), name
    np.testing.assert_allclose(losses["bf16"], losses["f32"], rtol=2e-2)


def test_dp_pp_ep_pipeline_step_learns(cpu_devices):
    mesh = make_mesh({"data": 2, "pipe": 2, "expert": 2})
    prng.seed_all(9)
    gen = prng.get()
    d, ff, n_experts = 16, 32, 4
    params = tfm.init_moe_pipeline_params(gen, n_stages=2, d=d, ff=ff,
                                          n_experts=n_experts)
    step, _ = tfm.make_pipeline_step(mesh, n_experts, lr=0.05)
    rng = np.random.default_rng(2)
    xs = rng.normal(size=(4, 8, d)).astype(np.float32)
    w_true = rng.normal(0, 0.3, (d, d)).astype(np.float32)
    ys = xs @ w_true + 0.5 * xs
    losses = []
    for _ in range(40):
        params, loss = step(params, xs, ys)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_flash_step_matches_ring_composition(cpu_devices):
    """The full composition — Pallas flash attention inside the
    shard_map'd train step, through jit and AD — executes (interpret
    mode) and trains identically to the ring/XLA attention path.

    Runs on a SINGLETON mesh on purpose: the interpret path needs
    ``check_vma=False`` (a Pallas HLO-interpreter limitation), under
    which psum transposition gains extra reductions — harmless only when
    every axis has size 1.  Multi-device semantics of the step itself are
    covered by the ring-path tests; the flash kernel is per-shard-local
    math.  t=128 exercises exactly one q block; dh=128 passes the flash
    gate (guarded below against geometry drift silently degrading this
    to ring-vs-ring)."""
    from znicz_tpu.core.config import root

    from znicz_tpu.ops.pallas.attention import supported

    prng.seed_all(13)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 1, 256, 2, 64, 11
    assert supported(128, d // heads)
    params = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, vocab, (4, 128)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)
    mesh = make_mesh({"data": 1, "seq": 1, "model": 1})

    losses = {}
    for name, flags in (("ring", {"flash_attention": False}),
                        ("flash", {"flash_attention": True,
                                   "pallas_interpret": True})):
        for key, val in flags.items():
            setattr(root.common.engine, key, val)
        try:
            step, _ = tfm.make_train_step(mesh, n_layers, d, heads, ff,
                                          vocab, lr=0.1)
            p = {k: (v if not isinstance(v, list) else
                     [dict(b) for b in v]) for k, v in params.items()}
            run = []
            for _ in range(3):
                p, loss = step(p, tokens, labels)
                run.append(float(loss))
            losses[name] = run
        finally:
            root.common.engine.flash_attention = True
            root.common.engine.pallas_interpret = False
    np.testing.assert_allclose(losses["flash"], losses["ring"],
                               rtol=1e-4, atol=1e-5)


def test_shard_update_transformer_matches_replicated(cpu_devices):
    """ZeRO-style update splitting on the transformer's replicated
    leaves trains identically to the plain update on a dp x sp x tp
    mesh."""
    prng.seed_all(19)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 2, 32, 4, 64, 17
    params = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    rng = np.random.default_rng(4)
    tokens = rng.integers(0, vocab, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})

    losses = {}
    for mode in (False, True):
        step, _ = tfm.make_train_step(mesh, n_layers, d, heads, ff,
                                      vocab, lr=0.2, shard_update=mode)
        p = {k: (v if not isinstance(v, list) else
                 [dict(b) for b in v]) for k, v in params.items()}
        run = []
        for _ in range(6):
            p, loss = step(p, tokens, labels)
            run.append(float(loss))
        losses[mode] = run
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-5, atol=1e-7)


def test_bf16_pipeline_step_tracks_f32(cpu_devices):
    """Mixed precision on the MoE pipeline step: bf16 losses track the
    f32 oracle, params stay f32."""
    import jax
    import jax.numpy as jnp

    prng.seed_all(25)
    gen = prng.get()
    d, ff, n_experts = 16, 32, 4
    params = tfm.init_moe_pipeline_params(gen, n_stages=2, d=d, ff=ff,
                                          n_experts=n_experts)
    mesh = make_mesh({"data": 2, "pipe": 2, "expert": 2})
    rng = np.random.default_rng(6)
    xs = rng.normal(size=(4, 8, d)).astype(np.float32)
    ys = xs * 0.5

    losses = {}
    for name, cdt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        step, _ = tfm.make_pipeline_step(mesh, n_experts, lr=0.05,
                                         compute_dtype=cdt)
        p = dict(params)
        run = []
        for _ in range(5):
            p, loss = step(p, xs, ys)
            run.append(float(loss))
        losses[name] = run
        assert all(leaf.dtype == jnp.float32
                   for leaf in jax.tree.leaves(p)), name
    np.testing.assert_allclose(losses["bf16"], losses["f32"], rtol=5e-2)


def _place_like(params, mesh, specs):
    """Sharded restore template: params' arrays device_put onto ``mesh``
    with ``specs``'s per-leaf PartitionSpecs (the shape both orbax
    roundtrip tests hand to load_pytree as ``like=``)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    flat_t, treedef = jax.tree.flatten(jax.tree.map(np.asarray, params))
    flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    return jax.tree.unflatten(treedef, [
        jax.device_put(leaf, NamedSharding(mesh, spec))
        for leaf, spec in zip(flat_t, flat_s)])


def test_orbax_checkpoint_roundtrip_across_meshes(tmp_path, cpu_devices):
    """Transformer params checkpoint via orbax and restore with sharding
    taken from the target tree: the template carries MESH_B shardings,
    so the restored leaves land distributed for the new mesh (not merely
    resharded by jit), and training continues with the same loss as on
    the original mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from znicz_tpu.parallel.checkpoint import load_pytree, save_pytree

    prng.seed_all(29)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 1, 32, 4, 64, 13
    p = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    rng = np.random.default_rng(8)
    tokens = rng.integers(0, vocab, (4, 8)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)

    mesh_a = make_mesh({"data": 2, "seq": 2, "model": 2})
    step_a, _ = tfm.make_train_step(mesh_a, n_layers, d, heads, ff, vocab,
                                    lr=0.1)
    for _ in range(3):
        p, _loss = step_a(p, tokens, labels)
    path = save_pytree(str(tmp_path / "ckpt"), p)

    # template placed on MESH_B with its param shardings — restore must
    # adopt them (the cross-mesh feature under test)
    mesh_b = make_mesh({"data": 4, "seq": 1, "model": 2})
    like = _place_like(p, mesh_b, tfm.param_specs(n_layers))
    restored = load_pytree(path, like=like)
    for a, b, want in zip(jax.tree.leaves(p), jax.tree.leaves(restored),
                          jax.tree.leaves(like)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert b.sharding == want.sharding    # mesh_b layout adopted

    # continue on mesh_b from the restored params; the loss must equal
    # continuing on the ORIGINAL mesh (same math, different layout)
    step_b, _ = tfm.make_train_step(mesh_b, n_layers, d, heads, ff, vocab,
                                    lr=0.1)
    _p2, loss_b = step_b(restored, tokens, labels)
    _p1, loss_ref = step_a(p, tokens, labels)
    np.testing.assert_allclose(float(loss_b), float(loss_ref), rtol=2e-4)


def test_remat_and_donate_match_baseline(cpu_devices):
    """remat=True (per-block jax.checkpoint) and donate=True (params
    buffers donated to the step) are pure execution-strategy switches:
    losses and updated params must match the plain step bit-for-bit
    variant by variant (remat recomputes the same f32/bf16 ops).

    NOTE: the CPU backend ignores donate_argnums, so the donate leg
    here pins only API/rebind safety; actual donation runs on the chip
    via bench_transformer (donate=True)."""
    import jax

    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    n_layers, d, heads, ff, vocab = 2, 32, 4, 64, 13
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, vocab, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)

    outs = {}
    for name, kw in (("plain", {}), ("remat", {"remat": True}),
                     ("donate", {"donate": True}),
                     ("remat_dots", {"remat_policy": "dots"}),
                     ("remat_dnb",
                      {"remat_policy": "dots_no_batch"}),
                     ("remat_nothing", {"remat_policy": "nothing"})):
        prng.seed_all(9)
        params = tfm.init_params(prng.get(), n_layers, d, heads, ff,
                                 vocab)
        step, _ = tfm.make_train_step(mesh, n_layers, d, heads, ff,
                                      vocab, lr=0.2, **kw)
        for _ in range(3):
            params, loss = step(params, tokens, labels)  # rebinds: donation-safe
        outs[name] = (float(loss),
                      np.asarray(jax.device_get(
                          jax.tree.leaves(params)[0])))
    for name in ("remat", "donate", "remat_dots", "remat_dnb",
                 "remat_nothing"):
        assert outs[name][0] == outs["plain"][0], (name, outs[name][0])
        np.testing.assert_array_equal(outs[name][1], outs["plain"][1])


def test_chunked_ce_matches_dense(cpu_devices):
    """loss_chunks=k computes the same loss/updated params as the dense
    CE path up to summation order (the (tokens, vocab) logits are never
    materialized — docs/TUNING.md); covers unmasked AND masked variants,
    including a token count that does not divide the chunk count (the
    zero-weight padding tail), on the full dp x sp x tp mesh."""
    import jax

    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    n_layers, d, heads, ff, vocab = 2, 32, 4, 64, 13
    rng = np.random.default_rng(6)
    tokens = rng.integers(0, vocab, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)
    mask = np.array([True, True, True, False])

    for masked in (False, True):
        outs = {}
        for name, chunks in (("dense", None), ("chunk4", 4),
                             ("chunk3", 3)):   # 3 does not divide 16·2
            prng.seed_all(11)
            params = tfm.init_params(prng.get(), n_layers, d, heads, ff,
                                     vocab)
            step, _ = tfm.make_train_step(
                mesh, n_layers, d, heads, ff, vocab, lr=0.2,
                masked=masked, loss_chunks=chunks)
            args = (tokens, labels, mask) if masked else (tokens, labels)
            for _ in range(3):
                params, loss = step(params, *args)
            outs[name] = (float(loss), jax.device_get(
                jax.tree.leaves(params)))
        for name in ("chunk4", "chunk3"):
            np.testing.assert_allclose(outs[name][0], outs["dense"][0],
                                       rtol=1e-6, atol=1e-7)
            for a, b in zip(outs[name][1], outs["dense"][1]):
                np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)

    # eval path shares the implementation
    prng.seed_all(11)
    params = tfm.init_params(prng.get(), n_layers, d, heads, ff, vocab)
    ev_d = tfm.make_eval_loss(mesh, n_layers, d, heads, ff, vocab)
    ev_c = tfm.make_eval_loss(mesh, n_layers, d, heads, ff, vocab,
                              loss_chunks=4)
    np.testing.assert_allclose(float(ev_c(params, tokens, labels)),
                               float(ev_d(params, tokens, labels)),
                               rtol=1e-6, atol=1e-7)


def test_head_sharded_matches_replicated(cpu_devices):
    """Megatron parallel cross-entropy (vocab-sharded head,
    head_sharded=True) trains identically to the replicated-head step
    on the full dp2 x sp2 x tp2 mesh — the full-vocab logits row never
    exists on any device; composes with loss_chunks; masked and
    unmasked; eval path shares the implementation."""
    import jax

    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    n_layers, d, heads, ff, vocab = 2, 32, 4, 64, 16   # vocab % tp == 0
    rng = np.random.default_rng(4)
    tokens = rng.integers(0, vocab, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)
    mask = np.array([True, True, False, False])

    for masked in (False, True):
        outs = {}
        for name, kw in (("repl", {}),
                         ("vshard", {"head_sharded": True}),
                         ("vshard_chunk", {"head_sharded": True,
                                           "loss_chunks": 4})):
            prng.seed_all(21)
            params = tfm.init_params(prng.get(), n_layers, d, heads, ff,
                                     vocab)
            step, _ = tfm.make_train_step(mesh, n_layers, d, heads, ff,
                                          vocab, lr=0.2, masked=masked,
                                          **kw)
            args = (tokens, labels, mask) if masked else (tokens, labels)
            for _ in range(3):
                params, loss = step(params, *args)
            outs[name] = (float(loss), jax.device_get(
                jax.tree.leaves(params)))
        for name in ("vshard", "vshard_chunk"):
            np.testing.assert_allclose(outs[name][0], outs["repl"][0],
                                       rtol=1e-5, atol=1e-6)
            for a, b in zip(outs[name][1], outs["repl"][1]):
                np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)

    prng.seed_all(21)
    params = tfm.init_params(prng.get(), n_layers, d, heads, ff, vocab)
    ev_r = tfm.make_eval_loss(mesh, n_layers, d, heads, ff, vocab)
    ev_v = tfm.make_eval_loss(mesh, n_layers, d, heads, ff, vocab,
                              head_sharded=True)
    np.testing.assert_allclose(float(ev_v(params, tokens, labels)),
                               float(ev_r(params, tokens, labels)),
                               rtol=1e-5, atol=1e-6)

    # indivisible vocab is refused loudly
    import pytest
    with pytest.raises(ValueError, match="divisible"):
        tfm.make_train_step(mesh, n_layers, d, heads, ff, 17,
                            head_sharded=True)


def test_orbax_roundtrip_head_sharded_to_replicated(tmp_path,
                                                    cpu_devices):
    """A checkpoint written from a VOCAB-SHARDED-head run restores into
    a replicated-head layout (and trains on, loss-equal): the elastic
    contract must hold across head layouts, not just mesh shapes —
    a tp-trained model must load on a single chip."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from znicz_tpu.parallel.checkpoint import load_pytree, save_pytree

    prng.seed_all(31)
    n_layers, d, heads, ff, vocab = 1, 32, 4, 64, 16
    p = tfm.init_params(prng.get(), n_layers, d, heads, ff, vocab)
    rng = np.random.default_rng(9)
    tokens = rng.integers(0, vocab, (4, 8)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)

    mesh_a = make_mesh({"data": 2, "seq": 2, "model": 2})
    step_a, _ = tfm.make_train_step(mesh_a, n_layers, d, heads, ff,
                                    vocab, lr=0.1, head_sharded=True)
    for _ in range(3):
        p, _loss = step_a(p, tokens, labels)
    path = save_pytree(str(tmp_path / "ckpt_vs"), p)

    mesh_b = make_mesh({"data": 2, "seq": 1, "model": 1})
    like = _place_like(p, mesh_b,
                       tfm.param_specs(n_layers, head_sharded=False))
    restored = load_pytree(path, like=like)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    step_b, _ = tfm.make_train_step(mesh_b, n_layers, d, heads, ff,
                                    vocab, lr=0.1, head_sharded=False)
    _p2, loss_b = step_b(restored, tokens, labels)
    _p1, loss_ref = step_a(p, tokens, labels)
    np.testing.assert_allclose(float(loss_b), float(loss_ref), rtol=2e-4)


def test_moe_ffn_transformer_tp_invariant_and_learns(cpu_devices):
    """n_experts swaps every block's dense FFN for the expert-parallel
    top-1 MoE FFN (experts sharded over the model axis).  The step must
    be tp-INVARIANT — identical losses with the 4 experts on one device
    vs split across model=2 — and must still learn the shift rule."""
    import jax

    n_layers, d, heads, ff, vocab, n_experts = 2, 32, 4, 64, 17, 4
    rng = np.random.default_rng(12)
    tokens = rng.integers(0, vocab, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)

    losses = {}
    for name, shape, aux_w in (
            ("tp1", {"data": 2, "seq": 2, "model": 1}, 0.0),
            ("tp2", {"data": 2, "seq": 2, "model": 2}, 0.0),
            ("tp1_aux", {"data": 2, "seq": 2, "model": 1}, 0.01),
            ("tp2_aux", {"data": 2, "seq": 2, "model": 2}, 0.01)):
        # the aux legs also carry the router z-loss so BOTH MoE
        # regularizers ride the tp-invariance pin
        mesh = make_mesh(shape)
        prng.seed_all(33)
        params = tfm.init_params(prng.get(), n_layers, d, heads, ff,
                                 vocab, n_experts=n_experts)
        step, _ = tfm.make_train_step(mesh, n_layers, d, heads, ff,
                                      vocab, lr=0.2,
                                      n_experts=n_experts,
                                      moe_aux_weight=aux_w,
                                      moe_zloss_weight=aux_w / 10)
        run = []
        for _ in range(15):
            params, loss = step(params, tokens, labels)
            run.append(float(loss))
        losses[name] = run
    np.testing.assert_allclose(losses["tp2"], losses["tp1"],
                               rtol=2e-4, atol=2e-5)
    # the load-balance aux is tp-invariant too, and actually present
    np.testing.assert_allclose(losses["tp2_aux"], losses["tp1_aux"],
                               rtol=2e-4, atol=2e-5)
    assert abs(losses["tp1_aux"][0] - losses["tp1"][0]) > 1e-4
    assert losses["tp1"][-1] < losses["tp1"][0] * 0.6, losses["tp1"]
    assert losses["tp1_aux"][-1] < losses["tp1_aux"][0] * 0.6

    # indivisible expert count is refused loudly
    import pytest
    with pytest.raises(ValueError, match="n_experts"):
        tfm.make_train_step(make_mesh({"data": 2, "seq": 2, "model": 2}),
                            n_layers, d, heads, ff, vocab, n_experts=3)
