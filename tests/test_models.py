"""Tier-2 sample-zoo tests: each models/ entry builds, trains a few epochs
on TPU/XLA, and its validation metric improves (SURVEY.md §5 tier-2 —
shrunk configs, seeded determinism)."""

import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.models import (alexnet, autoencoder, cifar_conv, mnist_conv,
                              wine)


def _train(build, seed=31, **kw):
    prng.seed_all(seed)
    w = build(**kw)
    w.initialize(device=TPUDevice())
    w.run()
    assert bool(w.decision.complete)
    return w.decision.metrics_history


def test_wine_sample():
    hist = _train(wine.build, max_epochs=10)
    assert hist[-1]["metric_validation"] <= hist[0]["metric_validation"]
    assert hist[-1]["metric_validation"] <= 3, hist


def test_mnist_conv_sample():
    hist = _train(mnist_conv.build, max_epochs=3, n_train=300, n_valid=100,
                  minibatch_size=50)
    assert hist[-1]["metric_validation"] < hist[0]["metric_validation"] or \
        hist[-1]["metric_validation"] == 0, hist


def test_cifar_conv_sample():
    hist = _train(cifar_conv.build, max_epochs=3, n_train=300, n_valid=100,
                  minibatch_size=50)
    assert hist[-1]["metric_validation"] < hist[0]["metric_validation"] or \
        hist[-1]["metric_validation"] == 0, hist


def test_autoencoder_sample():
    hist = _train(autoencoder.build, max_epochs=4, n_train=200, n_valid=64,
                  sample_shape=(12, 12, 1))
    assert hist[-1]["metric_validation"] < hist[0]["metric_validation"], hist


def test_alexnet_sample():
    """Shrunk AlexNet (67px input, soft dropout, separable data) must
    collapse validation error within 5 epochs — the north-star workflow's
    functional pin (BASELINE.md config 3)."""
    hist = _train(alexnet.build, seed=1, max_epochs=5, minibatch_size=50,
                  n_classes=10, input_size=67, n_train=300, n_valid=100,
                  lr=0.003, dropout=0.2, loader_config={"spread": 2.0})
    assert hist[-1]["metric_validation"] <= 0.2 * hist[0]["metric_validation"], \
        hist


def test_run_load_main_shape():
    """Samples expose the reference's run(load, main) CLI contract."""
    built = {}

    def load(builder, **kw):
        prng.seed_all(1)
        built["w"] = builder(max_epochs=1, n_train=60, n_valid=30,
                             minibatch_size=10, **kw)

    def main():
        built["w"].initialize(device=TPUDevice())
        built["w"].run()

    wine.run(load, main)
    assert bool(built["w"].decision.complete)
