"""Tier-2 sample-zoo tests: each models/ entry builds, trains a few epochs
on TPU/XLA, and the metric history matches EXACT pinned seeded values —
the reference's functional tests pin integer error counts the same way
(SURVEY.md §5 tier-2).  Any numeric drift in ops, loaders, PRNG streams or
the fused step fails these, not just "did it improve".

Values were captured on the virtual-CPU platform (tests/conftest.py) —
the platform every CI run uses — with f32 compute (the fused step's CPU
dtype), so they are bit-stable run to run.
"""

import time

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.models import (alexnet, autoencoder, cifar_conv, mnist_conv,
                              wine)


def _train(build, seed=31, **kw):
    prng.seed_all(seed)
    w = build(**kw)
    w.initialize(device=TPUDevice())
    w.run()
    assert bool(w.decision.complete)
    return w.decision.metrics_history


def _validation(hist):
    return [int(h["metric_validation"]) for h in hist]


def test_wine_sample():
    hist = _train(wine.build, max_epochs=10)
    assert _validation(hist) == [19, 0, 0, 0, 0, 0, 0, 0, 0, 0], hist
    assert int(hist[0]["metric_train"]) == 8, hist


def test_mnist_conv_sample():
    hist = _train(mnist_conv.build, max_epochs=3, n_train=300, n_valid=100,
                  minibatch_size=50)
    assert _validation(hist) == [94, 92, 90], hist
    assert [int(h["metric_train"]) for h in hist] == [268, 256, 263], hist


def test_cifar_conv_sample():
    hist = _train(cifar_conv.build, max_epochs=3, n_train=300, n_valid=100,
                  minibatch_size=50)
    assert _validation(hist) == [92, 90, 88], hist
    assert [int(h["metric_train"]) for h in hist] == [267, 271, 278], hist


def test_autoencoder_sample():
    hist = _train(autoencoder.build, max_epochs=4, n_train=200, n_valid=64,
                  sample_shape=(12, 12, 1))
    np.testing.assert_allclose(
        [h["metric_validation"] for h in hist],
        [1.2079215, 0.39782357, 0.32945922, 0.25455874],
        rtol=1e-5, err_msg=str(hist))


def test_alexnet_sample():
    """Shrunk AlexNet (67px input, soft dropout, separable data) must
    collapse validation error within 5 epochs — the north-star workflow's
    functional pin (BASELINE.md config 3)."""
    hist = _train(alexnet.build, seed=1, max_epochs=5, minibatch_size=50,
                  n_classes=10, input_size=67, n_train=300, n_valid=100,
                  lr=0.003, dropout=0.2, loader_config={"spread": 2.0})
    assert _validation(hist) == [90, 73, 38, 0, 0], hist


def test_mnist_conv_reaches_two_percent():
    """BASELINE.md config 2 ("MNIST-conv wall-clock to 99%") at CI scale:
    the full IDX pipeline at n_train=2000 must reach <= 2% validation
    error (10 of 500) within 12 epochs, wall-clock reported.  The early
    epochs are pinned exactly; the tail is thresholded (it sits at the
    scale of single samples)."""
    t0 = time.time()
    hist = _train(mnist_conv.build, max_epochs=12, n_train=2000,
                  n_valid=500, minibatch_size=100)
    wall = time.time() - t0
    val = _validation(hist)
    assert val[:6] == [451, 443, 411, 315, 228, 128], hist
    assert val[-1] <= 10, hist
    print(f"\nmnist_conv to {val[-1]}/500 errors in {len(hist)} epochs, "
          f"{wall:.1f}s wall")


def test_run_load_main_shape():
    """Samples expose the reference's run(load, main) CLI contract."""
    built = {}

    def load(builder, **kw):
        prng.seed_all(1)
        built["w"] = builder(max_epochs=1, n_train=60, n_valid=30,
                             minibatch_size=10, **kw)

    def main():
        built["w"].initialize(device=TPUDevice())
        built["w"].run()

    wine.run(load, main)
    assert bool(built["w"].decision.complete)


def test_approximator_sample():
    """Function-approximation MSE workflow (reference: Approximator
    sample): validation mse must follow the pinned seeded trajectory."""
    from znicz_tpu.models import approximator

    prng.seed_all(31)
    w = approximator.build(max_epochs=5)
    w.initialize(device=TPUDevice())
    w.run()
    np.testing.assert_allclose(
        [h["metric_validation"] for h in w.decision.metrics_history],
        [2.572527, 0.283226, 0.18658, 0.079837, 0.054828],
        rtol=1e-4, err_msg=str(w.decision.metrics_history))


def test_approximator_nearest_target_classification():
    """prototypes=P: nearest-target n_err (reference: the approximator
    samples' classification metric) on both eager backends AND the fused
    step (which recovers labels as the target's nearest prototype), and
    training drives it to zero."""
    from znicz_tpu.core.backends import NumpyDevice
    from znicz_tpu.models import approximator

    for device_cls in (NumpyDevice, TPUDevice):
        prng.seed_all(31)
        w = approximator.build(max_epochs=5, prototypes=5, fused=False)
        w.initialize(device=device_cls())
        assert w.evaluator.class_targets.shape == (5, 4)
        w.run()
        assert w.evaluator._classifies
        assert isinstance(w.evaluator.n_err, int)
        assert w.evaluator.n_err == 0, device_cls  # final batch classified
    eager_hist = [h["metric_validation"]
                  for h in w.decision.metrics_history]

    prng.seed_all(31)
    wf = approximator.build(max_epochs=5, prototypes=5)   # fused default
    wf.initialize(device=TPUDevice())
    wf.run()
    np.testing.assert_allclose(
        [h["metric_validation"] for h in wf.decision.metrics_history],
        eager_hist, rtol=1e-4)
    # deferred metrics: step.n_err is the LAST CLASS PASS's summed
    # nearest-target errors (400 train samples) — near-converged, a
    # handful at most, vs ~320 for an untrained net
    assert isinstance(wf.step.n_err, int)
    assert wf.step.n_err <= 10, wf.step.n_err


def test_fused_nearest_target_skipped_for_noisy_targets():
    """The fused label-recovery shortcut only engages when targets are
    PROVEN to be exact prototype rows; a loader with noisy targets must
    not emit a silently-wrong fused n_err."""
    from znicz_tpu.models import approximator

    prng.seed_all(31)
    w = approximator.build(max_epochs=1, prototypes=5)
    w.initialize(device=TPUDevice())
    # sabotage one stored target AFTER load: recovery assumption broken
    w.loader.original_targets.map_write()[0, 0] += 0.25
    assert not w.step._nt_recovery_valid()
    w.run()
    assert w.step.n_err == 0        # metric absent, attr untouched

    prng.seed_all(31)
    w2 = approximator.build(max_epochs=1, prototypes=5)
    w2.initialize(device=TPUDevice())
    assert w2.step._nt_recovery_valid()   # pristine loader: proven exact


def test_tv_channels_sample():
    """TvChannels sample: corner-logo identification with the Cutter
    cropping the logo region before the conv stack (the unit's first
    model-zoo consumer).  Pinned seeded trajectory."""
    from znicz_tpu.models import tv_channels

    prng.seed_all(31)
    w = tv_channels.build(max_epochs=6)
    w.initialize(device=TPUDevice())
    w.run()
    assert _validation(w.decision.metrics_history) == \
        [176, 178, 82, 37, 0, 0], w.decision.metrics_history
    assert w.forwards[0].output.shape == (50, 10, 10, 3)   # cropped


def test_tv_channels_eager_gd_cutter():
    """The eager chain routes gradients through GDCutter (zero-padding
    the cropped err back into frame geometry) and still converges."""
    from znicz_tpu.core.backends import NumpyDevice
    from znicz_tpu.models import tv_channels

    prng.seed_all(31)
    w = tv_channels.build(max_epochs=8, n_train=400, n_valid=100,
                          lr=0.05, fused=False)
    w.initialize(device=NumpyDevice())
    w.run()
    val = _validation(w.decision.metrics_history)
    assert val == [84, 88, 78, 10, 25, 16, 2, 0], val


def test_image_ae_sample():
    """ImagenetAE analog: conv->deconv reconstruction over the image-FILE
    pipeline (decode -> normalize -> identity targets), pinned seeded
    trajectory."""
    from znicz_tpu.models import image_ae

    prng.seed_all(31)
    w = image_ae.build(max_epochs=6)
    w.initialize(device=TPUDevice())
    w.run()
    np.testing.assert_allclose(
        [h["metric_validation"] for h in w.decision.metrics_history],
        [0.086547, 0.034062, 0.022606, 0.021269, 0.009212, 0.008824],
        rtol=1e-4, err_msg=str(w.decision.metrics_history))
    # identity-target contract: the arrays the pinned path consumes...
    np.testing.assert_array_equal(w.loader.original_targets.mem,
                                  w.loader.original_data.mem)
    # ...and the eager fill path's served copy (drive one fill directly)
    w.loader.serve_indices_only = False
    w.loader.fill_minibatch()
    assert np.any(w.loader.minibatch_data.mem)
    np.testing.assert_array_equal(w.loader.minibatch_targets.mem,
                                  w.loader.minibatch_data.mem)


def test_deep_autoencoder_sample():
    """ImagenetAE-scale builder (BASELINE.md config 4 at representative
    geometry): strided conv pyramid mirrors back to the input shape and
    the reconstruction improves over epochs.  (Exact pin omitted: this
    builder's bench geometry is 64x64x3 — the test uses a shrunk variant
    and pins the trend plus the round-trip shape contract.)"""
    prng.seed_all(7)
    w = autoencoder.build_deep(max_epochs=3, minibatch_size=16,
                               sample_shape=(16, 16, 3),
                               n_kernels=(8, 16), n_train=64)
    w.initialize(device=TPUDevice())
    w.run()
    hist = w.decision.metrics_history
    assert w.forwards[-1].output.shape[1:] == (16, 16, 3)
    spatial = [f.output.shape[1] for f in w.forwards]
    assert spatial == [8, 4, 8, 16], spatial      # halve, halve, mirror
    assert hist[-1]["metric_train"] < hist[0]["metric_train"], hist


def test_mnist_conv_bf16_convergence_pin():
    """Tier-2 convergence under the bf16 precision policy (VERDICT r3
    weak #4): the SAME seeded MNIST-conv run as the 2%-test, forced
    through compute_dtype=bfloat16, with its own exact pinned early
    trajectory and converged tail — so a precision-policy regression
    (e.g. an accumulation silently moved to bf16) fails CI as a degraded
    converged metric, not just a loose "tracks f32" check.  bf16
    rounding on this platform is deterministic: the pin is exact
    (captured twice, bit-identical)."""
    import jax.numpy as jnp

    prng.seed_all(31)
    w = mnist_conv.build(max_epochs=12, minibatch_size=100, n_train=2000,
                         n_valid=500)
    w.step.compute_dtype = jnp.bfloat16
    w.initialize(device=TPUDevice())
    w.run()
    val = [int(h["metric_validation"]) for h in w.decision.metrics_history]
    # f32 pin for the same seed/config: [451, 443, 411, 315, 228, 128]
    assert val[:6] == [451, 446, 411, 322, 227, 129], val
    assert val[-1] <= 10, val    # converged: <= 2% of 500
