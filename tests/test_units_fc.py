"""Tier-1 tests for the All2All / GD unit pairs: numpy-vs-xla backend parity
(the rebuild of the reference's ocl-vs-numpy cross-backend tests,
SURVEY.md §5) and wiring semantics."""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.memory import Array
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.units.all2all import (All2All, All2AllSoftmax, All2AllTanh,
                                     All2AllRELU)
from znicz_tpu.units.gd import GradientDescent, GDSoftmax, GDTanh
from znicz_tpu.units.nn_units import MatchingObject


def make_forward(cls, device, x, **kwargs):
    prng.seed_all(42)
    w = Workflow(name="t")
    unit = cls(w, **kwargs)
    unit.input = Array(x)
    unit.initialize(device=device)
    unit.run()
    return unit


@pytest.mark.parametrize("cls", [All2All, All2AllTanh, All2AllRELU,
                                 All2AllSoftmax])
def test_forward_backend_parity(cls):
    rng = np.random.default_rng(5)
    x = rng.normal(size=(8, 12)).astype(np.float32)
    u_np = make_forward(cls, NumpyDevice(), x, output_sample_shape=7)
    u_xla = make_forward(cls, TPUDevice(), x, output_sample_shape=7)
    np.testing.assert_allclose(u_xla.output.map_read(),
                               u_np.output.map_read(), rtol=1e-5, atol=1e-5)
    # same seed => identical weight init across backends
    np.testing.assert_array_equal(u_np.weights.map_read(),
                                  u_xla.weights.map_read())
    if cls is All2AllSoftmax:
        np.testing.assert_array_equal(u_np.max_idx.map_read(),
                                      u_xla.max_idx.map_read())


def make_gd_pair(fwd_cls, gd_cls, device, x, err, **gd_kwargs):
    prng.seed_all(43)
    w = Workflow(name="t")
    fwd = fwd_cls(w, output_sample_shape=err.shape[1])
    fwd.input = Array(x)
    fwd.initialize(device=device)
    fwd.run()
    gd = gd_cls(w, **gd_kwargs)
    gd.link_from_forward(fwd)
    gd.err_output = Array(err)
    gd.batch_size = x.shape[0]
    gd.initialize(device=device)
    gd.run()
    return fwd, gd


@pytest.mark.parametrize("fwd_cls,gd_cls", [
    (All2All, GradientDescent),
    (All2AllTanh, GDTanh),
    (All2AllSoftmax, GDSoftmax),
])
def test_gd_backend_parity(fwd_cls, gd_cls):
    rng = np.random.default_rng(6)
    x = rng.normal(size=(6, 10)).astype(np.float32)
    err = rng.normal(size=(6, 4)).astype(np.float32)
    kwargs = dict(learning_rate=0.1, weights_decay=0.01, gradient_moment=0.9)
    _, gd_np = make_gd_pair(fwd_cls, gd_cls, NumpyDevice(), x, err, **kwargs)
    _, gd_xla = make_gd_pair(fwd_cls, gd_cls, TPUDevice(), x, err, **kwargs)
    for attr in ("err_input", "weights", "bias", "gradient_weights",
                 "gradient_bias"):
        np.testing.assert_allclose(
            getattr(gd_xla, attr).map_read(), getattr(gd_np, attr).map_read(),
            rtol=1e-4, atol=1e-5, err_msg=attr)


def test_gd_matches_autograd():
    """Hand-written backward vs jax.grad of the composed forward loss —
    the TPU-native correctness oracle the reference never had."""
    import jax
    import jax.numpy as jnp
    from znicz_tpu.ops import linear as linops

    rng = np.random.default_rng(7)
    x = rng.normal(size=(5, 8)).astype(np.float32)
    err = rng.normal(size=(5, 3)).astype(np.float32)  # dL/dy for L = sum(y*err)
    # lr=1, no momentum/decay: gradient_weights == grad/batch after one step
    fwd, gd = make_gd_pair(All2AllTanh, GDTanh, NumpyDevice(), x, err,
                           learning_rate=1.0, gradient_moment=0.0,
                           weights_decay=0.0)
    w0 = gd.weights.map_read() + gd.gradient_weights.map_read()  # pre-update
    b0 = gd.bias.map_read() + gd.gradient_bias.map_read()

    def loss(x_, w_, b_):
        return (linops.forward(jnp, x_, w_, b_, "tanh") *
                jnp.asarray(err)).sum()

    gx, gw, gb = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(w0), jnp.asarray(b0))
    batch = x.shape[0]
    np.testing.assert_allclose(gd.err_input.map_read(), np.asarray(gx),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gd.gradient_weights.map_read() * batch,
                               np.asarray(gw), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gd.gradient_bias.map_read() * batch,
                               np.asarray(gb), rtol=1e-4, atol=1e-4)


def test_weights_transposed_gd_matches_natural():
    """A transposed-layout layer must compute and train identically to the
    natural layout (the reference's weights_transposed flag)."""
    rng = np.random.default_rng(8)
    x = rng.normal(size=(4, 6)).astype(np.float32)
    err = rng.normal(size=(4, 3)).astype(np.float32)
    w_init = rng.normal(size=(6, 3)).astype(np.float32)
    b_init = rng.normal(size=(3,)).astype(np.float32)

    def build(transposed):
        wf = Workflow(name="t")
        fwd = All2AllTanh(wf, output_sample_shape=3,
                          weights_transposed=transposed)
        fwd.input = Array(x)
        fwd.weights.mem = w_init.T.copy() if transposed else w_init.copy()
        fwd.bias.mem = b_init.copy()
        fwd.initialize(device=NumpyDevice())
        fwd.run()
        gd = GDTanh(wf, learning_rate=0.1, gradient_moment=0.5)
        gd.link_from_forward(fwd)
        gd.err_output = Array(err)
        gd.batch_size = x.shape[0]
        gd.initialize(device=NumpyDevice())
        gd.run()
        return fwd, gd

    fwd_n, gd_n = build(False)
    fwd_t, gd_t = build(True)
    np.testing.assert_allclose(fwd_t.output.map_read(),
                               fwd_n.output.map_read(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(gd_t.err_input.map_read(),
                               gd_n.err_input.map_read(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(gd_t.weights.map_read().T,
                               gd_n.weights.map_read(), rtol=1e-5, atol=1e-6)


def test_matching_registry_pairs_fwd_and_gd():
    assert MatchingObject.gd_for(
        All2AllTanh.__new__(All2AllTanh)) is GDTanh
    assert MatchingObject.forwards["softmax"] is All2AllSoftmax
    assert MatchingObject.gds["softmax"] is GDSoftmax
