"""Compile-latency plane tests (ISSUE 7): persistent-compilation-cache
round trips (a simulated second-process init HITS; corrupt/missing
cache dirs degrade to logged misses, never crashes), AOT package
export -> zero-compile serve boot (``compile_count == 0`` pinned,
outputs bit-identical AOT vs JIT), fingerprint-mismatch fallback, the
``aot`` CLI, the warmup summary line, the cache-miss-fed
``recompile_storm`` rule, and the Kohonen per-build re-trace fix."""

import json
import logging
import os
import shutil

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from znicz_tpu import compilecache  # noqa: E402
from znicz_tpu.observe import probe  # noqa: E402

#: every jax config knob configure() touches, restored by the fixture
_CACHE_KEYS = ("jax_enable_compilation_cache", "jax_compilation_cache_dir",
               "jax_persistent_cache_min_compile_time_secs",
               "jax_persistent_cache_min_entry_size_bytes",
               "jax_raise_persistent_cache_errors")


@pytest.fixture
def cc(monkeypatch):
    """A clean compilecache: no env override, no prior configure()
    decision; jax config + module state restored afterwards so the rest
    of the suite keeps whatever cache policy it booted with."""
    prev_cfg = {k: getattr(jax.config, k) for k in _CACHE_KEYS}
    prev_state = (compilecache._configured, compilecache._active_dir)
    monkeypatch.delenv(compilecache.ENV_VAR, raising=False)
    monkeypatch.delenv(compilecache.ENV_MIN_S, raising=False)
    compilecache._reset_for_tests()
    yield compilecache
    for k, v in prev_cfg.items():
        jax.config.update(k, v)
    compilecache._configured, compilecache._active_dir = prev_state
    # un-latch jax's cache-used/backing-store state too: without this
    # the rest of the suite keeps consulting whatever (deleted) tmp dir
    # the last test here enabled
    compilecache._reset_jax_cache_state()


def _fresh_fn(salt: float):
    """A jit program whose HLO is unique per ``salt`` — cache entries
    from other tests (or the suite's own warm cache) cannot collide."""
    c = jnp.float32(salt)

    def fn(x):
        return jnp.tanh(x * c) + c * 3.0, x @ (x.T * c)

    return jax.jit(fn)


# -- persistent cache --------------------------------------------------------

def test_env_layer_wins_and_creates_dir(cc, monkeypatch, tmp_path):
    target = tmp_path / "envcache"
    monkeypatch.setenv(compilecache.ENV_VAR, str(target))
    assert cc.configure() == str(target)
    assert target.is_dir()
    assert cc.active_dir() == str(target)
    assert jax.config.jax_compilation_cache_dir == str(target)


def test_explicit_arg_wins_over_env(cc, monkeypatch, tmp_path):
    monkeypatch.setenv(compilecache.ENV_VAR, str(tmp_path / "envcache"))
    explicit = tmp_path / "explicit"
    assert cc.configure(cache_dir=str(explicit)) == str(explicit)


def test_env_off_disables(cc, monkeypatch):
    monkeypatch.setenv(compilecache.ENV_VAR, "off")
    assert cc.configure() is None
    assert jax.config.jax_compilation_cache_dir == ""
    # disabled is still a decision: ensure() must not re-enable
    assert cc.ensure() is None


def test_config_tree_layer(cc, tmp_path):
    from znicz_tpu.core.config import root

    prev = root.common.engine.get("compile_cache_dir", None)
    root.common.engine.compile_cache_dir = str(tmp_path / "cfgcache")
    try:
        assert cc.configure() == str(tmp_path / "cfgcache")
    finally:
        root.common.engine.compile_cache_dir = prev


def test_ensure_is_idempotent(cc, monkeypatch, tmp_path):
    monkeypatch.setenv(compilecache.ENV_VAR, str(tmp_path / "e"))
    first = cc.ensure()
    assert first == str(tmp_path / "e")
    # a second ensure() (every Workflow.run calls it) is a no-op even
    # if the env changes mid-process — the decision was made
    monkeypatch.setenv(compilecache.ENV_VAR, str(tmp_path / "other"))
    assert cc.ensure() == first


def test_min_compile_time_change_applies_without_force(cc, tmp_path):
    cc.configure(cache_dir=str(tmp_path / "m"), min_compile_time_s=0.0)
    # idempotence is keyed on the WHOLE resolution, not just the dir —
    # a changed threshold must land in jax, not silently early-return
    cc.configure(cache_dir=str(tmp_path / "m"), min_compile_time_s=5.0)
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 5.0
    assert cc.active_dir() == str(tmp_path / "m")


def test_malformed_min_s_env_degrades_to_zero(cc, monkeypatch, tmp_path,
                                              caplog):
    monkeypatch.setenv(compilecache.ENV_VAR, str(tmp_path / "m"))
    monkeypatch.setenv(compilecache.ENV_MIN_S, "1s")
    with caplog.at_level(logging.WARNING, "znicz_tpu.compilecache"):
        assert cc.configure() == str(tmp_path / "m")
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
    assert any("is not a number" in r.message for r in caplog.records)


def test_suspended_blocks_cache_and_restores(cc, tmp_path):
    cc.configure(cache_dir=str(tmp_path / "s"))
    x = jnp.asarray(np.ones((2, 4), np.float32))
    _, misses0 = probe.compile_cache_stats()
    with cc.suspended():
        assert jax.config.jax_compilation_cache_dir == ""
        _fresh_fn(0.311)(x)
    # the suspended compile went past the persistent cache entirely
    assert probe.compile_cache_stats()[1] == misses0
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "s")
    _fresh_fn(0.433)(x)
    assert probe.compile_cache_stats()[1] > misses0  # cache back in play


def test_cache_round_trip_second_init_hits(cc, tmp_path):
    """The tentpole contract: a second process booting the same program
    against the same cache dir loads instead of compiling.  The second
    process is simulated by ``jax.clear_caches()`` + a fresh ``jit``
    wrapper — the only warmth left is the persistent cache."""
    cc.configure(cache_dir=str(tmp_path / "rt"))
    x = jnp.asarray(np.linspace(-1, 1, 32, dtype=np.float32).reshape(4, 8))
    hits0, misses0 = probe.compile_cache_stats()
    cold = [np.asarray(o) for o in _fresh_fn(0.731)(x)]
    hits1, misses1 = probe.compile_cache_stats()
    assert misses1 > misses0          # the cold compile was observed
    assert hits1 == hits0             # nothing to hit yet
    assert any(f.endswith("-cache") for f in os.listdir(tmp_path / "rt"))
    jax.clear_caches()
    warm = [np.asarray(o) for o in _fresh_fn(0.731)(x)]
    hits2, _ = probe.compile_cache_stats()
    assert hits2 > hits1              # warm init HIT, assertably
    for a, b in zip(cold, warm):
        np.testing.assert_array_equal(a, b)


def test_enable_after_cache_off_compiles_is_consulted(cc, monkeypatch,
                                                      tmp_path):
    """jax latches whether-the-cache-is-used once per process: a compile
    while the cache is off (the tier-1 conftest default) must not make a
    later configure() a silent no-op — configure resets jax's latched
    state so the new directory IS consulted.  (Found by exactly this
    ordering under the full suite.)"""
    monkeypatch.setenv(compilecache.ENV_VAR, "off")
    cc.configure()
    x = jnp.asarray(np.ones((2, 4), np.float32))
    _fresh_fn(0.271)(x)               # latches jax's cache-unused state
    cc.configure(cache_dir=str(tmp_path / "late"), force=True)
    _, misses0 = probe.compile_cache_stats()
    _fresh_fn(0.829)(x)
    _, misses1 = probe.compile_cache_stats()
    assert misses1 > misses0          # the late-enabled cache was consulted
    assert any(f.endswith("-cache")
               for f in os.listdir(tmp_path / "late"))


def test_unusable_cache_dir_degrades_to_logged_off(cc, tmp_path, caplog):
    blocker = tmp_path / "a_file"
    blocker.write_text("not a directory")
    with caplog.at_level(logging.WARNING, "znicz_tpu.compilecache"):
        assert cc.configure(cache_dir=str(blocker / "sub")) is None
    assert any("persistent caching disabled" in r.message
               for r in caplog.records)
    # jax still compiles and runs — degraded means slower, not broken
    out = _fresh_fn(0.113)(jnp.ones((2, 4), jnp.float32))
    assert np.isfinite(np.asarray(out[0])).all()


def test_corrupt_cache_entries_never_crash(cc, tmp_path):
    cache = tmp_path / "corrupt"
    cc.configure(cache_dir=str(cache))
    x = jnp.asarray(np.ones((3, 5), np.float32))
    want = [np.asarray(o) for o in _fresh_fn(0.557)(x)]
    for name in os.listdir(cache):
        if name.endswith("-cache"):
            (cache / name).write_bytes(b"garbage, not an executable")
    jax.clear_caches()
    # jax_raise_persistent_cache_errors is pinned False: the corrupt
    # entry is a logged miss and the program recompiles
    got = [np.asarray(o) for o in _fresh_fn(0.557)(x)]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_engine_boot_triggers_ensure(cc, monkeypatch, tmp_path):
    from znicz_tpu.serve import BatchEngine

    monkeypatch.setenv(compilecache.ENV_VAR, str(tmp_path / "boot"))
    assert not compilecache._configured
    BatchEngine(lambda x: x, max_batch=2, input_shape=(2,))
    assert compilecache.active_dir() == str(tmp_path / "boot")


# -- AOT serving artifacts ---------------------------------------------------

@pytest.fixture(scope="module")
def tiny_pkg(tmp_path_factory):
    """One trained-and-exported forward package shared by the AOT
    tests (each test copies it before mutating)."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.standard_workflow import StandardWorkflow
    from znicz_tpu.utils.export import export_forward

    prng.seed_all(23)
    w = StandardWorkflow(
        name="AotPkg", loss_function="softmax",
        layers=[{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
                {"type": "softmax", "->": {"output_sample_shape": 3}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 3, "sample_shape": (6,), "n_train": 60,
                       "n_valid": 0, "minibatch_size": 20},
        decision_config={"max_epochs": 1})
    w.initialize(device=TPUDevice())
    w.run()
    pkg = str(tmp_path_factory.mktemp("aot") / "tiny.npz")
    export_forward(w, pkg)
    return pkg


def _aot_copy(tiny_pkg, tmp_path, max_batch=4) -> str:
    from znicz_tpu.utils.export import attach_aot

    pkg = str(tmp_path / "pkg.npz")
    shutil.copy(tiny_pkg, pkg)
    attach_aot(pkg, max_batch=max_batch)
    return pkg


def test_aot_boot_zero_compiles_and_bit_identical(tiny_pkg, tmp_path):
    from znicz_tpu.serve import BatchEngine
    from znicz_tpu.utils.export import ExportedForward

    pkg = _aot_copy(tiny_pkg, tmp_path)
    fwd = ExportedForward(pkg)
    assert fwd.aot_fallback_reason is None
    assert sorted(fwd.precompiled_buckets) == [1, 2, 4]
    engine = BatchEngine(fwd, max_batch=4)
    assert engine.warmup() == 0               # THE zero-JIT boot contract
    assert engine.compile_count == 0
    assert engine.aot_count == 3
    assert engine.stats()["aot_count"] == 3
    # forward results bit-identical AOT vs JIT (same compiled HLO)
    jit_fwd = ExportedForward(pkg, aot=False)
    assert jit_fwd.precompiled_buckets == {}
    rng = np.random.default_rng(5)
    for n in (1, 2, 3, 4):                    # 3 pads to bucket 4
        x = rng.normal(size=(n, 6)).astype(np.float32)
        np.testing.assert_array_equal(engine.run(x), jit_fwd(x)[:n]
                                      if n in (1, 2, 4) else
                                      jit_fwd(np.concatenate(
                                          [x, np.zeros((1, 6),
                                                       np.float32)]))[:n])
    assert engine.compile_count == 0          # traffic compiled nothing


def test_aot_dispatch_skips_wrong_rank_input(tiny_pkg, tmp_path):
    """An input whose leading dim equals a precompiled bucket but whose
    RANK does not match (bucket,)+input_shape must take the general jit
    path — behavior with AOT present is identical to without (here:
    the same jit-path shape error, not a failure from inside a
    deserialized executable that was pinned to another rank)."""
    from znicz_tpu.utils.export import ExportedForward

    # max_batch=6 -> buckets (1, 2, 4, 6): bucket 6 COLLIDES with the
    # package's 1-D sample length 6
    pkg = _aot_copy(tiny_pkg, tmp_path, max_batch=6)
    fwd = ExportedForward(pkg)
    assert 6 in fwd.precompiled_buckets
    x1d = np.zeros(6, np.float32)       # un-batched: never a valid input
    jit_fwd = ExportedForward(pkg, aot=False)
    with pytest.raises(TypeError) as jit_err:
        jit_fwd(x1d)
    with pytest.raises(TypeError) as aot_err:
        fwd(x1d)
    assert str(aot_err.value) == str(jit_err.value)
    # and a rank-correct bucket-sized batch still rides the executable
    ok = np.zeros((6, 6), np.float32)
    np.testing.assert_array_equal(fwd(ok), jit_fwd(ok))


def test_aot_fingerprint_mismatch_falls_back_to_jit(tiny_pkg, tmp_path,
                                                    caplog):
    from znicz_tpu.serve import BatchEngine
    from znicz_tpu.utils.export import ExportedForward

    pkg = _aot_copy(tiny_pkg, tmp_path)
    with np.load(pkg, allow_pickle=False) as zf:
        meta = json.loads(str(zf["__arch__"]))
        arrays = {k: zf[k] for k in zf.files if k != "__arch__"}
    meta["aot"]["fingerprint"]["device_kind"] = "TPU v9"
    with open(pkg, "wb") as f:
        np.savez_compressed(f, __arch__=np.array(json.dumps(meta)),
                            **arrays)
    with caplog.at_level(logging.WARNING, "znicz_tpu.export"):
        fwd = ExportedForward(pkg)
    assert fwd.precompiled_buckets == {}
    assert "device_kind mismatch" in fwd.aot_fallback_reason
    assert any("AOT executables ignored" in r.message
               for r in caplog.records)
    # degraded, not broken: warmup JIT-compiles every bucket and serves
    engine = BatchEngine(fwd, max_batch=4)
    assert engine.warmup() == 3
    assert engine.aot_count == 0
    y = engine.run(np.zeros((2, 6), np.float32))
    assert y.shape == (2, 3)


def test_aot_corrupt_payload_falls_back(tiny_pkg, tmp_path):
    from znicz_tpu.utils.export import ExportedForward

    pkg = _aot_copy(tiny_pkg, tmp_path)
    with np.load(pkg, allow_pickle=False) as zf:
        meta = json.loads(str(zf["__arch__"]))
        arrays = {k: zf[k] for k in zf.files if k != "__arch__"}
    arrays["__aot__2"] = np.frombuffer(b"truncated rubbish", np.uint8)
    with open(pkg, "wb") as f:
        np.savez_compressed(f, __arch__=np.array(json.dumps(meta)),
                            **arrays)
    fwd = ExportedForward(pkg)
    assert fwd.precompiled_buckets == {}
    assert "deserialization failed" in fwd.aot_fallback_reason
    assert fwd(np.zeros((2, 6), np.float32)).shape == (2, 3)


def test_aot_cli_round_trip(tiny_pkg, tmp_path, capsys):
    from znicz_tpu.__main__ import main as cli_main
    from znicz_tpu.utils.export import ExportedForward

    pkg = str(tmp_path / "cli.npz")
    shutil.copy(tiny_pkg, pkg)
    rc = cli_main(["aot", pkg, "--max-batch", "4"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["buckets"] == [1, 2, 4]
    assert doc["platform"] == "cpu"
    assert sorted(ExportedForward(pkg).precompiled_buckets) == [1, 2, 4]


def test_aot_cli_rejects_non_package(tmp_path, capsys):
    from znicz_tpu.__main__ import main as cli_main

    bad = tmp_path / "bad.npz"
    np.savez(bad, x=np.zeros(3))
    assert cli_main(["aot", str(bad)]) == 2


def test_serve_cli_no_aot_flag(tiny_pkg, tmp_path):
    from znicz_tpu.serve.engine import load_backend

    pkg = _aot_copy(tiny_pkg, tmp_path)
    assert load_backend(pkg, aot=False).precompiled_buckets == {}
    assert sorted(load_backend(pkg).precompiled_buckets) == [1, 2, 4]


def test_export_forward_aot_max_batch(tiny_pkg, tmp_path):
    """export_forward(aot_max_batch=) is attach_aot at export time."""
    from znicz_tpu.utils.export import ExportedForward

    pkg = _aot_copy(tiny_pkg, tmp_path, max_batch=2)
    fwd = ExportedForward(pkg)
    assert sorted(fwd.precompiled_buckets) == [1, 2]
    assert fwd.meta["aot"]["max_batch"] == 2


# -- surfacing ---------------------------------------------------------------

def test_warmup_emits_single_summary_line(caplog):
    from znicz_tpu.serve import BatchEngine

    engine = BatchEngine(lambda x: np.asarray(x) * 2.0, max_batch=4,
                         input_shape=(3,))
    with caplog.at_level(logging.INFO, "BatchEngine"):
        engine.warmup()
    lines = [r.message for r in caplog.records
             if r.message.startswith("warmup:")]
    assert len(lines) == 1
    assert "3 buckets" in lines[0]
    assert "3 compiled" in lines[0]
    assert "0 aot-precompiled" in lines[0]


def test_recompile_storm_fed_by_cache_miss_counter():
    from znicz_tpu.observe import watchtower as wt

    rule = wt.recompile_storm(max_in_window=2.0, window_s=60.0,
                              metric="znicz_compile_cache_misses_total",
                              action=lambda r, v: None)
    tower = wt.Watchtower(step_every=1)
    tower.add_rule(rule)
    tower.observe_now(ts=1.0)
    for _ in range(4):
        probe.compile_cache_event("miss")
    tower.observe_now(ts=2.0)
    assert rule.matching
    assert rule.trips == 1                # 4 cold compiles in the window
    assert rule.last_value == 4.0


def test_compile_cache_counters_move_through_disabled_probes(cc, tmp_path):
    """Unlike the per-signal probes, cache accounting survives
    observe.set_enabled(False): the warm/cold contract must stay
    assertable through a bench's bare arm."""
    from znicz_tpu import observe

    observe.set_enabled(False)
    try:
        _, m0 = probe.compile_cache_stats()
        probe.compile_cache_event("miss")
        assert probe.compile_cache_stats()[1] == m0 + 1
    finally:
        observe.set_enabled(True)


# -- kohonen per-build re-trace (ISSUE 7 satellite) --------------------------

def test_kohonen_forward_builds_share_one_traced_program():
    from znicz_tpu.units.kohonen import KohonenForward, _winners_jit

    a, b = KohonenForward(None, shape=(4, 4)), KohonenForward(None,
                                                              shape=(4, 4))
    a.xla_init()
    b.xla_init()
    assert a._xla_fn is b._xla_fn is _winners_jit
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(5, 16)).astype(np.float32))
    w = jnp.asarray(np.random.default_rng(1).normal(
        size=(16, 16)).astype(np.float32))
    first = np.asarray(a._xla_fn(x, w))
    size_after_first = _winners_jit._cache_size()
    second = np.asarray(b._xla_fn(x, w))
    # the second build reuses the first build's traced program — the
    # per-build re-trace the old per-instance jit(lambda) paid is gone
    assert _winners_jit._cache_size() == size_after_first
    np.testing.assert_array_equal(first, second)
