"""Tier-3 tests: the fused/sharded training step (SURVEY.md §5 rebuild
translation — multi-device SPMD on the virtual 8-device CPU mesh).

- fused-vs-eager parity: one fused step produces the same weight update as
  the per-unit eager chain (autograd-composed backward == hand-written
  unit backward, through the full segment);
- mesh invariance: training on an 8-device mesh matches 1-device within
  float tolerance (psum math), and converges;
- determinism on the mesh.
"""

import numpy as np
import pytest

import jax

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.models.mnist_fc import build_eager, build_fused
from znicz_tpu.parallel.mesh import data_parallel_mesh, make_mesh


def test_fused_step_matches_eager_units():
    """Same seed => same data, same init; run exactly one TRAIN minibatch
    through both shapes and compare the updated weights."""
    # eager: skip valid passes by using a train-only loader
    prng.seed_all(77)
    we = build_eager(max_epochs=1, n_valid=0, n_train=200, minibatch_size=50)
    we.initialize(device=NumpyDevice())
    we.loader.run()
    for f in we.forwards:
        f.run()
    we.evaluator.run()
    for gd in reversed(we.gds):
        gd.run()

    prng.seed_all(77)
    wf = build_fused(max_epochs=1, n_valid=0, n_train=200, minibatch_size=50)
    wf.initialize(device=TPUDevice())
    wf.loader.run()
    wf.step.run()
    wf.step.sync_to_units()

    for i, (fe, ff) in enumerate(zip(we.forwards, wf.forwards)):
        np.testing.assert_allclose(
            ff.weights.map_read(), fe.weights.map_read(),
            rtol=1e-4, atol=1e-5, err_msg=f"layer {i} weights")
        np.testing.assert_allclose(
            ff.bias.map_read(), fe.bias.map_read(),
            rtol=1e-4, atol=1e-5, err_msg=f"layer {i} bias")
    # velocity buffers too (momentum state)
    for i, (ge, gf) in enumerate(zip(we.gds, wf.gds)):
        np.testing.assert_allclose(
            gf.gradient_weights.map_read(), ge.gradient_weights.map_read(),
            rtol=1e-4, atol=1e-5, err_msg=f"layer {i} velocity")


def run_fused(seed, mesh, max_epochs=3):
    prng.seed_all(seed)
    w = build_fused(max_epochs=max_epochs, mesh=mesh)
    w.initialize(device=TPUDevice())
    w.run()
    w.step.sync_to_units()
    return w


def test_fused_training_converges_on_8dev_mesh(cpu_devices):
    mesh = data_parallel_mesh(8)
    w = run_fused(31, mesh)
    hist = w.decision.metrics_history
    assert len(hist) == 3
    assert hist[-1]["metric_validation"] < hist[0]["metric_validation"]
    assert w.decision.epoch_n_err_pt[1] < 15.0, hist


def test_mesh_size_invariance(cpu_devices):
    """DP over 8 devices is the same math as 1 device (sync SPMD: batch
    split + psum == full-batch gradient), modulo float reduction order."""
    w1 = run_fused(13, data_parallel_mesh(1), max_epochs=2)
    w8 = run_fused(13, data_parallel_mesh(8), max_epochs=2)
    np.testing.assert_allclose(
        w8.forwards[0].weights.map_read(), w1.forwards[0].weights.map_read(),
        rtol=1e-3, atol=1e-4)
    assert [h["metric_validation"] for h in w1.decision.metrics_history] == \
        [h["metric_validation"] for h in w8.decision.metrics_history]


def test_fused_deterministic_on_mesh(cpu_devices):
    w_a = run_fused(17, data_parallel_mesh(8), max_epochs=2)
    w_b = run_fused(17, data_parallel_mesh(8), max_epochs=2)
    np.testing.assert_array_equal(w_a.forwards[0].weights.map_read(),
                                  w_b.forwards[0].weights.map_read())
    assert w_a.decision.metrics_history == w_b.decision.metrics_history


def test_make_mesh_axes(cpu_devices):
    mesh = make_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh({"data": 16})


def test_train_steps_scan_matches_sequential(cpu_devices):
    """The K-step scan (the bench's measurement path) is the SAME program
    as K sequential per-minibatch steps: identical final params and summed
    metrics; and the device hyper cache invalidates on an LR change."""
    import jax.numpy as jnp

    mesh = data_parallel_mesh(4)

    def fresh():
        prng.seed_all(23)
        w = build_fused(max_epochs=1, n_valid=0, n_train=240,
                        minibatch_size=40, mesh=mesh)
        w.initialize(device=TPUDevice())
        return w

    rng = np.random.default_rng(3)
    K = 5
    xs = rng.normal(size=(K, 40, 28, 28)).astype(np.float32)
    ys = rng.integers(0, 10, (K, 40)).astype(np.int32)
    ms = np.ones((K, 40), bool)

    w_seq = fresh()
    seq_sums = None
    for k in range(K):
        w_seq.step._params, w_seq.step._key, metrics = w_seq.step._train_fn(
            w_seq.step._params, w_seq.step._key,
            w_seq.step._hyper_device(), xs[k], ys[k], ms[k])
        host = jax.device_get(metrics)
        seq_sums = host if seq_sums is None else \
            jax.tree.map(np.add, seq_sums, host)

    w_scan = fresh()
    scan_sums = jax.device_get(w_scan.step.train_steps(
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(ms)))

    for leaf_seq, leaf_scan in zip(jax.tree.leaves(w_seq.step._params),
                                   jax.tree.leaves(w_scan.step._params)):
        np.testing.assert_allclose(np.asarray(leaf_seq),
                                   np.asarray(leaf_scan),
                                   rtol=1e-5, atol=1e-6)
    assert int(seq_sums["n_err"]) == int(scan_sums["n_err"])
    np.testing.assert_allclose(float(seq_sums["loss"]),
                               float(scan_sums["loss"]), rtol=1e-5)
    assert int(seq_sums["bs"]) == int(scan_sums["bs"]) == K * 40

    # hyper cache: an LR change must produce a DIFFERENT device pytree
    h0 = w_scan.step._hyper_device()
    for gd in w_scan.gds:
        gd.learning_rate *= 0.5
    h1 = w_scan.step._hyper_device()
    assert float(jax.device_get(h1[0]["lr"])) == \
        0.5 * float(jax.device_get(h0[0]["lr"]))


def test_scan_epoch_mode_matches_per_minibatch(cpu_devices):
    """root.common.engine.scan_epoch dispatches one compiled scan per
    class pass; Decision history and final weights must match the
    per-minibatch path (same key chain, same math, one dispatch)."""
    from znicz_tpu.core.config import root

    def run(scan):
        root.common.engine.scan_epoch = scan
        try:
            w = run_fused(41, data_parallel_mesh(4), max_epochs=3)
        finally:
            root.common.engine.scan_epoch = False
        return w

    base = run(False)
    scan = run(True)
    assert scan.step.scan_epoch and scan.step._scan_idx_fns
    assert [h["metric_validation"] for h in base.decision.metrics_history] \
        == [h["metric_validation"] for h in scan.decision.metrics_history]
    assert [h["metric_train"] for h in base.decision.metrics_history] \
        == [h["metric_train"] for h in scan.decision.metrics_history]
    np.testing.assert_allclose(scan.forwards[0].weights.map_read(),
                               base.forwards[0].weights.map_read(),
                               rtol=1e-4, atol=1e-5)


def test_scan_epoch_refuses_per_minibatch_lr_schedule(cpu_devices):
    """VERDICT r5 item 6: scan_epoch reads hyperparams once per class
    pass, so a linked per-minibatch (by_epoch=False) LearningRateAdjust
    would silently coarsen to a per-pass schedule — initialize must
    refuse with a diagnostic naming the offending unit.  The per-epoch
    variant stays allowed."""
    from znicz_tpu.core.config import root
    from znicz_tpu.units.lr_adjust import ExpPolicy, LearningRateAdjust

    def build(by_epoch):
        prng.seed_all(11)
        w = build_fused(max_epochs=1, mesh=data_parallel_mesh(2))
        adj = LearningRateAdjust(w, lr_policy=ExpPolicy(0.9),
                                 by_epoch=by_epoch)
        for gd in w.gds:
            adj.add_gd_unit(gd)
        adj.link_from(w.decision)
        if by_epoch:
            adj.decision = w.decision
        return w

    root.common.engine.scan_epoch = True
    try:
        w = build(by_epoch=False)
        with pytest.raises(ValueError, match="by_epoch=False.*coarsen"):
            w.initialize(device=TPUDevice())
        # by_epoch=True is pass-granular already: must initialize fine
        w_ok = build(by_epoch=True)
        w_ok.initialize(device=TPUDevice())
        assert w_ok.step._scan_idx_fns
    finally:
        root.common.engine.scan_epoch = False


def test_scan_epoch_single_minibatch_classes(cpu_devices):
    """Regression: when a class pass fits in ONE minibatch, the loader
    has already advanced to the next class (and possibly reshuffled) by
    the time the step dispatches — the plan must be the one captured at
    class start, not the next class's indices."""
    from znicz_tpu.core.config import root

    def run(scan):
        prng.seed_all(19)
        root.common.engine.scan_epoch = scan
        try:
            # valid (80) and train (160) each fit in one 160-row minibatch
            w = build_fused(max_epochs=3, n_train=160, n_valid=80,
                            minibatch_size=160,
                            mesh=data_parallel_mesh(4))
            w.initialize(device=TPUDevice())
            w.run()
            w.step.sync_to_units()
        finally:
            root.common.engine.scan_epoch = False
        return w

    base = run(False)
    scan = run(True)
    assert [h["metric_validation"] for h in base.decision.metrics_history] \
        == [h["metric_validation"] for h in scan.decision.metrics_history]
    np.testing.assert_allclose(scan.forwards[0].weights.map_read(),
                               base.forwards[0].weights.map_read(),
                               rtol=1e-4, atol=1e-5)


def test_scan_epoch_midpass_entry_falls_back(cpu_devices):
    """A class pass entered mid-way (restored loader state) must fall
    back to the per-minibatch path for the remainder instead of skipping
    the pass and publishing a None accumulator."""
    from znicz_tpu.core.config import root

    prng.seed_all(27)
    root.common.engine.scan_epoch = True
    try:
        w = build_fused(max_epochs=1, n_train=200, n_valid=0,
                        minibatch_size=40, mesh=data_parallel_mesh(4))
        w.initialize(device=TPUDevice())
    finally:
        root.common.engine.scan_epoch = False
    loader, step = w.loader, w.step
    # simulate a mid-pass restore: advance the loader two minibatches
    # without the step seeing them, then clear any device accumulator
    loader.run()
    loader.run()
    loader.run()
    assert int(loader.minibatch_offset) > 0
    step._acc = None
    before = np.asarray(jax.tree.leaves(step._params)[0])
    while True:                            # remaining minibatches of pass
        step.run()
        if loader.last_minibatch:
            break
        loader.run()
    # the WHOLE remainder trained (3 of 5 minibatches = 120 samples),
    # not just the first fallback minibatch (regression: _acc was
    # misused as the scan-in-flight marker and re-routed minibatch 2+
    # back into the no-op scan path)
    after = np.asarray(jax.tree.leaves(step._params)[0])
    assert not np.array_equal(before, after)
    assert step.minibatch_size == 120, step.minibatch_size
    assert step.loss > 0.0


def test_scan_epoch_mse_workflow(cpu_devices):
    """Epoch-scan parity for the MSE/regression path (targets pinned on
    device instead of labels)."""
    from znicz_tpu.core.config import root
    from znicz_tpu.models import autoencoder

    def run(scan):
        prng.seed_all(9)
        root.common.engine.scan_epoch = scan
        try:
            w = autoencoder.build(max_epochs=3, n_train=200, n_valid=64,
                                  minibatch_size=40, sample_shape=(12, 12, 1),
                                  mesh=data_parallel_mesh(4))
            w.initialize(device=TPUDevice())
            w.run()
        finally:
            root.common.engine.scan_epoch = False
        return [h["metric_validation"] for h in w.decision.metrics_history]

    base = run(False)
    scan = run(True)
    np.testing.assert_allclose(scan, base, rtol=1e-5)


def test_lr_schedule_no_recompile(cpu_devices):
    """Hyperparams are traced scalars: mutating gd.learning_rate between
    steps must not retrigger compilation."""
    prng.seed_all(5)
    w = build_fused(max_epochs=1, mesh=data_parallel_mesh(8))
    w.initialize(device=TPUDevice())
    w.loader.run()
    while int(w.loader.minibatch_class) != 2:
        w.loader.run()
    w.step.run()
    compiled = w.step._train_fn._cache_size()
    for gd in w.gds:
        gd.learning_rate *= 0.5
    w.loader.run()
    w.step.run()
    assert w.step._train_fn._cache_size() == compiled


def test_fused_step_bf16_compute_tracks_f32():
    """Force the bf16 compute path (dead on CPU by default) through a
    whole training run: losses track the f32 run loosely, params stay
    f32, and every unit's xla_apply survives bf16 inputs.  Uses a conv
    stack so conv/pool/LRN/dropout all see bf16."""
    import jax.numpy as jnp
    from znicz_tpu.models.mnist_conv import build

    losses = {}
    for name, cdt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        prng.seed_all(123)
        w = build(max_epochs=2, minibatch_size=50, n_train=200, n_valid=50,
                  loader_name="synthetic_image")
        w.step.compute_dtype = cdt
        w.initialize(device=TPUDevice())
        w.run()
        losses[name] = [h["metric_train"] for h in
                        w.decision.metrics_history]
        for leaf in jax.tree.leaves(w.step._params):
            assert leaf.dtype == jnp.float32, (name, leaf.dtype)
    assert len(losses["bf16"]) == len(losses["f32"])
    # bf16 rounding makes trajectories diverge step by step; the run must
    # still LEARN the same problem: final-epoch train errors in the same
    # ballpark as the f32 oracle (identical data + init)
    f32_final = losses["f32"][-1]
    bf16_final = losses["bf16"][-1]
    assert bf16_final <= max(1.5 * f32_final, f32_final + 10), losses


def test_hybrid_mesh_single_slice_fallback(cpu_devices):
    """make_hybrid_mesh: same axis names/sizes as the plain mesh on a
    single-slice platform (identical sharded program, only physical
    routing differs on real pods), with the dcn validation enforced."""
    import pytest

    from znicz_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh({"data": 2, "model": 4}, {"data": 2})
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (2, 4)

    # a collective over both axes executes on the hybrid-constructed mesh
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    def f(x):
        return jax.lax.psum(x, ("data", "model"))

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data", "model"),
                            out_specs=P()))(jnp.ones((2, 4)))
    assert float(out.ravel()[0]) == 8.0   # (1,1) replicated block

    with pytest.raises(ValueError, match="must divide"):
        make_hybrid_mesh({"data": 3}, {"data": 2})
    with pytest.raises(ValueError, match="not in axis_sizes"):
        make_hybrid_mesh({"data": 8}, {"pipe": 2})


def test_hybrid_mesh_multi_slice_assignment(cpu_devices):
    """Simulated multi-slice runtime (fake slice_index wrappers): the
    dcn axis spans slices outermost, surplus slices/devices are trimmed
    like the single-slice path, and dcn=1 stays inside one slice."""
    import pytest

    from znicz_tpu.parallel.mesh import make_hybrid_mesh

    class Dev:
        def __init__(self, d, sid):
            self._d = d
            self.slice_index = sid

        def __getattr__(self, name):
            return getattr(self._d, name)

        def __repr__(self):
            return f"<s{self.slice_index}:{self._d.id}>"

    devs = [Dev(d, i // 4) for i, d in enumerate(cpu_devices)]  # 2 slices

    mesh = make_hybrid_mesh({"data": 2, "model": 2}, {"data": 2},
                            devices=devs)
    assert mesh.devices.shape == (2, 2)
    # data (the dcn axis) is outermost: row 0 from slice 0, row 1 from 1
    rows = [[d.slice_index for d in row] for row in mesh.devices]
    assert rows == [[0, 0], [1, 1]], rows

    # dcn=1 on a multi-slice runtime: stays within one slice
    mesh1 = make_hybrid_mesh({"data": 4}, devices=devs)
    assert {d.slice_index for d in mesh1.devices.ravel()} == {0}
    # ...and refuses when no slice is big enough
    with pytest.raises(ValueError, match="no single slice"):
        make_hybrid_mesh({"data": 8}, devices=devs)
    # more dcn than slices: clear error
    with pytest.raises(ValueError, match="only"):
        make_hybrid_mesh({"data": 4}, {"data": 4}, devices=devs)
