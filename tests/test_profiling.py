"""Trace comparison tooling (utils/profiling.compare_traces) — the
evidence path for before/after kernel-level perf work."""


def test_compare_traces(tmp_path):
    """Two profiled runs diff at category level (envelope excluded)."""
    import jax
    import jax.numpy as jnp
    from znicz_tpu.utils.profiling import compare_traces

    for name, n in (("a", 64), ("b", 128)):
        d = str(tmp_path / name)
        jax.profiler.start_trace(d)
        x = jnp.ones((n, n))
        (x @ x).block_until_ready()
        jax.profiler.stop_trace()
    rows = compare_traces(str(tmp_path / "a"), str(tmp_path / "b"))
    assert rows and all(
        set(r) == {"category", "a_ms", "b_ms", "delta_ms"} for r in rows)
    assert not any(r["category"] == "while" for r in rows)


def test_compare_traces_one_sided_category(monkeypatch):
    """A category present in only one trace (an op class a rewrite
    added or fused away) diffs with its missing side at 0.0 — a
    legitimate outcome, never a KeyError (ISSUE 20 satellite)."""
    from znicz_tpu.utils import profiling

    sides = {
        "dir_a": [{"op": "fusion.1", "total_ms": 3.0},
                  {"op": "convolution.2", "total_ms": 2.0}],
        "dir_b": [{"op": "fusion.7", "total_ms": 1.5},
                  {"op": "all-reduce.1", "total_ms": 4.0}],
    }
    monkeypatch.setattr(profiling, "summarize_trace",
                        lambda logdir, top=None: sides[logdir])
    rows = profiling.compare_traces("dir_a", "dir_b")
    by_cat = {r["category"]: r for r in rows}
    # shared category diffs normally
    assert by_cat["fusion"]["a_ms"] == 3.0
    assert by_cat["fusion"]["b_ms"] == 1.5
    # one-sided categories: the missing side is 0.0, delta is the whole
    # total, in both directions
    assert by_cat["convolution"]["a_ms"] == 2.0
    assert by_cat["convolution"]["b_ms"] == 0.0
    assert by_cat["convolution"]["delta_ms"] == -2.0
    assert by_cat["all-reduce"]["a_ms"] == 0.0
    assert by_cat["all-reduce"]["b_ms"] == 4.0
    assert by_cat["all-reduce"]["delta_ms"] == 4.0
    # sorted by |delta|: the biggest one-sided category leads
    assert rows[0]["category"] == "all-reduce"
