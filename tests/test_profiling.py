"""Trace comparison tooling (utils/profiling.compare_traces) — the
evidence path for before/after kernel-level perf work."""


def test_compare_traces(tmp_path):
    """Two profiled runs diff at category level (envelope excluded)."""
    import jax
    import jax.numpy as jnp
    from znicz_tpu.utils.profiling import compare_traces

    for name, n in (("a", 64), ("b", 128)):
        d = str(tmp_path / name)
        jax.profiler.start_trace(d)
        x = jnp.ones((n, n))
        (x @ x).block_until_ready()
        jax.profiler.stop_trace()
    rows = compare_traces(str(tmp_path / "a"), str(tmp_path / "b"))
    assert rows and all(
        set(r) == {"category", "a_ms", "b_ms", "delta_ms"} for r in rows)
    assert not any(r["category"] == "while" for r in rows)
