"""Tier-2 tests for the SpamFilter and YaleFaces sample families plus
direct tier-1 coverage of the text bag-of-words loader (the reference's
research samples pin seeded metrics the same way — SURVEY.md §5)."""

import os

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.loader import text as text_mod
from znicz_tpu.models import spam, yale_faces


# ---------------------------------------------------------------------------
# text loader, directly
# ---------------------------------------------------------------------------

def test_corpus_round_trip(tmp_path):
    path = str(tmp_path / "c.txt")
    with open(path, "w", encoding="utf-8") as f:
        f.write("1\tbuy gold buy now\n\n0\thello old friend\n")
    docs, labels = text_mod.read_corpus(path)
    assert docs == [["buy", "gold", "buy", "now"],
                    ["hello", "old", "friend"]]
    assert labels.tolist() == [1, 0]


def test_vocabulary_order_and_vectorize():
    docs = [["b", "a", "b", "c"], ["a", "c", "c", "d"]]
    # counts: b=2 a=2 c=3 d=1 -> order: c(3), a(2), b(2) [alpha tie], d(1)
    vocab = text_mod.build_vocabulary(docs, vocab_size=3)
    assert vocab == {"c": 0, "a": 1, "b": 2}
    mat = text_mod.vectorize([["d", "c", "c", "a"]], vocab)
    np.testing.assert_allclose(
        mat, np.log1p([[2.0, 1.0, 0.0]]), rtol=1e-6)   # d is OOV: dropped


def test_synthesized_corpus_is_deterministic_and_separable(tmp_path):
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    text_mod.synthesize_text_corpus(d1, n_train=100, n_test=40)
    text_mod.synthesize_text_corpus(d2, n_train=100, n_test=40)
    for name in text_mod.FILES.values():
        with open(os.path.join(d1, name), encoding="utf-8") as f1, \
                open(os.path.join(d2, name), encoding="utf-8") as f2:
            assert f1.read() == f2.read()
    docs, labels = text_mod.read_corpus(os.path.join(d1, "train.txt"))
    assert sorted(set(labels.tolist())) == [0, 1]
    # nearest-class-mean over raw counts separates the two classes
    vocab = text_mod.build_vocabulary(docs, 300)
    mat = text_mod.vectorize(docs, vocab)
    means = np.stack([mat[labels == c].mean(0) for c in (0, 1)])
    pred = np.argmin(((mat[:, None] - means[None]) ** 2).sum(-1), axis=1)
    assert (pred == labels).mean() > 0.95


def test_torn_corpus_is_regenerated(tmp_path):
    """A synthesis interrupted between the train and test writes must be
    detected and repaired, not served with an empty VALID split."""
    d = str(tmp_path / "torn")
    text_mod.synthesize_text_corpus(d, n_train=50, n_test=20)
    os.remove(os.path.join(d, text_mod.FILES["test"]))
    loader = text_mod.TextBagOfWordsLoader(
        Workflow(name="torn"), data_dir=d, minibatch_size=10)
    loader._ensure_files()
    assert os.path.exists(os.path.join(d, text_mod.FILES["test"]))


def test_image_tree_regeneration_contract(tmp_path):
    from znicz_tpu.loader import image as image_mod

    d = str(tmp_path / "tree")
    image_mod.ensure_image_tree(d, n_classes=3, n_per_class=2,
                                size=(8, 8))
    vfile = os.path.join(d, ".synth_version")
    assert open(vfile).read().strip() == image_mod.SYNTH_VERSION
    # stale marker -> rebuilt; fresh marker -> untouched
    mtime = os.path.getmtime(vfile)
    image_mod.ensure_image_tree(d, n_classes=3, n_per_class=2,
                                size=(8, 8))
    assert os.path.getmtime(vfile) == mtime
    with open(vfile, "w") as f:
        f.write("0-stale")
    image_mod.ensure_image_tree(d, n_classes=3, n_per_class=2,
                                size=(8, 8))
    assert open(vfile).read().strip() == image_mod.SYNTH_VERSION
    # markerless non-empty tree = user data: never touched
    user = str(tmp_path / "user")
    os.makedirs(os.path.join(user, "class_a"))
    with open(os.path.join(user, "class_a", "x.txt"), "w") as f:
        f.write("sentinel")
    image_mod.ensure_image_tree(user)
    assert os.listdir(user) == ["class_a"]


def test_text_loader_serves_and_restores(tmp_path):
    d = str(tmp_path / "corpus")
    text_mod.synthesize_text_corpus(d, n_train=80, n_test=20)
    prng.seed_all(5)
    w = Workflow(name="t")
    loader = text_mod.TextBagOfWordsLoader(
        w, data_dir=d, vocab_size=64, minibatch_size=20)
    loader.initialize(device=TPUDevice())
    assert loader.class_lengths == [0, 20, 80]
    assert len(loader.vocab) == 64
    assert loader.original_data.shape == (100, 64)
    loader.run()
    assert loader.minibatch_data.mem.shape == (20, 64)
    served = loader.original_data.mem.copy()

    # state round-trip into a fresh loader over the same files
    state = loader.state_dict()
    prng.seed_all(99)                      # restore must not depend on prng
    loader2 = text_mod.TextBagOfWordsLoader(
        Workflow(name="t2"), data_dir=d, vocab_size=64, minibatch_size=20)
    loader2.initialize(device=TPUDevice())
    loader2.load_state_dict(state)
    assert loader2.vocab == loader.vocab
    np.testing.assert_allclose(loader2.original_data.mem, served,
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# sample workflows, pinned (tier-2)
# ---------------------------------------------------------------------------

def _train(build, seed=31, **kw):
    prng.seed_all(seed)
    w = build(**kw)
    w.initialize(device=TPUDevice())
    w.run()
    assert bool(w.decision.complete)
    return w


def test_spam_sample():
    w = _train(spam.build, max_epochs=5)
    hist = w.decision.metrics_history
    assert [int(h["metric_validation"]) for h in hist] == \
        [86, 0, 0, 0, 0], hist
    assert int(hist[0]["metric_train"]) == 28, hist
    assert w.loader.class_lengths == [0, 200, 600]
    assert len(w.loader.vocab) == 256


def test_yale_faces_sample():
    w = _train(yale_faces.build, max_epochs=5)
    hist = w.decision.metrics_history
    assert [int(h["metric_validation"]) for h in hist] == \
        [72, 6, 0, 0, 0], hist
    assert [int(h["metric_train"]) for h in hist][:2] == [139, 8], hist
    assert w.loader.n_classes == 15
    assert w.loader.class_lengths == [0, 75, 225]
