"""Char-LM workflow tests: the sequence loader's serving contract and
the transformer step as a workflow citizen (epochs, VALID passes,
Decision stopping, snapshot roundtrip) — the beyond-parity model family
riding the reference's control graph."""

import pytest

# full SPMD training runs on the virtual 8-device CPU mesh take
# minutes per file; tier-1 (-m 'not slow') must fit its 870 s
# budget, so these ride the registered slow lane
pytestmark = pytest.mark.slow

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.loader.base import TEST, TRAIN, VALID
from znicz_tpu.models import char_lm


def test_char_sequence_loader_contract(tmp_path):
    """Windows are next-char pairs from the right streams, classes carve
    the corpus deterministically, epochs reshuffle order not content."""
    from znicz_tpu.loader.sequence import CharSequenceLoader

    prng.seed_all(3)
    loader = CharSequenceLoader(None, data_dir=str(tmp_path / "corp"),
                                seq_len=16, minibatch_size=8,
                                valid_fraction=0.2)
    loader.initialize(device=None)
    assert loader.vocab_size > 5
    assert all(loader.class_lengths[c] > 0 for c in (TEST, VALID, TRAIN))
    seen_classes = []
    checked = 0
    for _ in range(100_000):
        loader.run()
        cls = int(loader.minibatch_class)
        if cls not in seen_classes:
            seen_classes.append(cls)
            # verify the first minibatch of each class pass in depth:
            # labels are data shifted by one within the SAME stream window
            data = loader.minibatch_data.mem
            labels = loader.minibatch_labels.mem
            stream = loader._streams[cls]
            for row in range(loader.minibatch_size):
                gi = loader.minibatch_indices.mem[row]
                off = int(loader._starts[gi])
                np.testing.assert_array_equal(data[row],
                                              stream[off:off + 16])
                np.testing.assert_array_equal(labels[row],
                                              stream[off + 1:off + 17])
                checked += 1
        if loader.epoch_number >= 1:
            break
    assert seen_classes == [TEST, VALID, TRAIN]   # reference class order
    assert checked >= 3


def test_char_lm_trains_and_stops(tmp_path):
    """Seeded run: validation CE per char collapses from ln(vocab) and
    the Decision's max_epochs stop fires."""
    prng.seed_all(11)
    w = char_lm.build(max_epochs=4, seq_len=32, minibatch_size=16,
                      n_layers=2, d=32, heads=2,
                      data_dir=str(tmp_path / "corp"))
    w.initialize(device=TPUDevice())
    w.run()
    h = w.decision.metrics_history
    assert len(h) == 4
    assert bool(w.decision.complete)
    first, last = h[0]["metric_validation"], h[-1]["metric_validation"]
    # epoch-1 VALID runs before any training: near-random CE, at least
    # ln(vocab) (the uniform-predictor floor)
    assert first > np.log(w.loader.vocab_size) - 0.2
    assert last < 0.5 * first, h                            # learned
    assert np.isfinite(last)


def test_char_lm_snapshot_roundtrip(tmp_path):
    """Params survive a snapshot/restore: the restored workflow's eval
    loss equals the original's (state_dict/load_state_dict contract)."""
    import jax

    prng.seed_all(11)
    w = char_lm.build(max_epochs=2, seq_len=32, minibatch_size=16,
                      data_dir=str(tmp_path / "corp"))
    w.initialize(device=TPUDevice())
    w.run()
    state = w.step.state_dict()

    prng.seed_all(99)    # different init — restore must overwrite it
    w2 = char_lm.build(max_epochs=2, seq_len=32, minibatch_size=16,
                       data_dir=str(tmp_path / "corp"))
    w2.initialize(device=TPUDevice())
    w2.step.load_state_dict(state)
    tokens = jax.numpy.asarray(
        np.arange(16 * 32, dtype=np.int32).reshape(16, 32)
        % w.loader.vocab_size)
    labels = jax.numpy.roll(tokens, -1, axis=1)
    mask = jax.numpy.ones(16, bool)
    a = float(jax.device_get(w.step._eval(w.step._params, tokens, labels,
                                          mask)))
    b = float(jax.device_get(w2.step._eval(w2.step._params, tokens,
                                           labels, mask)))
    assert abs(a - b) < 1e-5, (a, b)


def test_char_lm_sharded_mesh(tmp_path):
    """The LM step trains under a real dp x sp x tp mesh (params sharded
    by param_specs, minibatches placed P('data','seq'))."""
    from znicz_tpu.parallel.mesh import make_mesh

    prng.seed_all(11)
    w = char_lm.build(max_epochs=2, seq_len=32, minibatch_size=16,
                      n_layers=2, d=32, heads=4,
                      mesh=make_mesh({"data": 2, "seq": 2, "model": 2}),
                      data_dir=str(tmp_path / "corp"))
    w.initialize(device=TPUDevice())
    w.run()
    h = w.decision.metrics_history
    assert h[-1]["metric_validation"] < h[0]["metric_validation"], h


def test_char_lm_snapshotter_resume_bit_exact(tmp_path):
    """Full-machinery resume: run 4 epochs with the Snapshotter side
    chain, then rebuild fresh, restore_state from the epoch-2 snapshot,
    continue — the continued run's metric history matches the unbroken
    run's tail (the framework-wide bit-exact-resume contract, now
    covering state_dict-only forwards)."""
    from znicz_tpu.snapshotter import restore_state

    snap_dir = str(tmp_path / "snaps")
    corp = str(tmp_path / "corp")

    def fresh(max_epochs, with_snap):
        prng.seed_all(11)
        return char_lm.build(
            max_epochs=max_epochs, seq_len=32, minibatch_size=16,
            data_dir=corp,
            snapshotter_config={"prefix": "lm", "directory": snap_dir,
                                "only_improved": False, "keep_all": True}
            if with_snap else None)

    w = fresh(4, True)
    w.initialize(device=TPUDevice())
    w.run()
    full_hist = w.decision.metrics_history

    w2 = fresh(4, False)
    w2.initialize(device=TPUDevice())
    meta = restore_state(w2, str(tmp_path / "snaps" / "lm_2.npz"))
    assert meta["loader"]["epoch_number"] == 2
    w2.run()
    resumed = w2.decision.metrics_history
    # history restored up to epoch 2, then continued identically
    for a, b in zip(full_hist, resumed):
        assert a["epoch"] == b["epoch"]
        np.testing.assert_allclose(a["metric_validation"],
                                   b["metric_validation"], rtol=1e-5)
    assert len(resumed) == len(full_hist)


def test_char_lm_loss_chunks_trains(tmp_path):
    """The chunked-CE lever is reachable from the model zoo: same
    workflow, loss_chunks=4, CE per char still collapses (the chunk
    count only changes summation order)."""
    prng.seed_all(11)
    w = char_lm.build(max_epochs=3, seq_len=32, minibatch_size=16,
                      n_layers=2, d=32, heads=2,
                      data_dir=str(tmp_path / "corp"), loss_chunks=4)
    w.initialize(device=TPUDevice())
    w.run()
    h = w.decision.metrics_history
    assert h[-1]["metric_validation"] < \
        0.6 * np.log(w.loader.vocab_size)


def test_char_lm_moe_trains(tmp_path):
    """MoE FFN + aux + top-2 routing reachable from the model zoo: the
    char-LM workflow trains with 4 experts and the CE still collapses."""
    prng.seed_all(11)
    w = char_lm.build(max_epochs=3, seq_len=32, minibatch_size=16,
                      n_layers=2, d=32, heads=2,
                      data_dir=str(tmp_path / "corp"), n_experts=4,
                      moe_aux_weight=0.01, moe_top_k=2)
    w.initialize(device=TPUDevice())
    w.run()
    h = w.decision.metrics_history
    assert h[-1]["metric_validation"] < \
        0.7 * np.log(w.loader.vocab_size)
