"""Resilience plane (znicz_tpu/resilience/): chaos tests driving the
REAL code paths — the supervisor resumes a crashed training run
bit-exactly (the snapshotter's exactness contract makes recovery
verifiable), poison snapshots are rejected by checksum, retries back off
deterministically, the NaN guard degrades gracefully, and the watchdog
catches hung steps."""

import os

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.resilience import faults
from znicz_tpu.resilience.retry import AttemptTimeout, RetryPolicy
from znicz_tpu.resilience.supervisor import (SupervisorExhausted,
                                             SupervisorPolicy,
                                             find_latest_valid_snapshot,
                                             run_supervised)
from znicz_tpu.snapshotter import (SnapshotCorruptError, collect_state,
                                   restore_state, verify_snapshot,
                                   write_snapshot)
from znicz_tpu.standard_workflow import StandardWorkflow

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 24},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 6},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
]
LOADER = {"n_classes": 6, "sample_shape": (10, 10), "n_train": 240,
          "n_valid": 120, "minibatch_size": 40, "spread": 2.5, "noise": 1.0}


def build(max_epochs, snap_dir=None, seed=77, health=None, fused=True,
          defer_metrics=True):
    """Fresh, initialized workflow — the supervisor's factory discipline:
    re-seed the global PRNG exactly like a fresh process would."""
    prng.seed_all(seed)
    cfg = None
    if snap_dir is not None:
        cfg = {"directory": str(snap_dir), "prefix": "t",
               "only_improved": False, "keep_all": True}
    w = StandardWorkflow(
        name="ResTest", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": max_epochs},
        snapshotter_config=cfg, health_config=health, fused=fused,
        defer_metrics=defer_metrics)
    w.initialize(device=TPUDevice())
    return w


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    """A chaos test must never leak an armed plan into the suite."""
    yield
    faults.uninstall()


def fast_policy(**kw):
    kw.setdefault("sleep", lambda s: None)
    return SupervisorPolicy(**kw)


# -- retry policy ------------------------------------------------------------

def test_retry_succeeds_after_transient_failures():
    delays = []
    p = RetryPolicy(max_attempts=4, base_delay=0.1, multiplier=2.0,
                    sleep=delays.append, seed=3)
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise OSError("transient")
        return "done"

    assert p.call(flaky) == "done"
    assert calls[0] == 3
    assert len(delays) == 2
    # exponential shape survives the jitter band (+/-25%)
    assert 0.075 <= delays[0] <= 0.125
    assert 0.15 <= delays[1] <= 0.25
    assert p.total_retries == 2


def test_retry_jitter_is_seeded_deterministic():
    def schedule(seed):
        d = []
        p = RetryPolicy(max_attempts=5, base_delay=0.05, sleep=d.append,
                        seed=seed)
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 5:
                raise OSError("x")

        p.call(flaky)
        return d

    assert schedule(7) == schedule(7)
    assert schedule(7) != schedule(8)


def test_retry_exhaustion_reraises_last_error():
    p = RetryPolicy(max_attempts=3, sleep=lambda s: None)
    with pytest.raises(OSError, match="always"):
        p.call(lambda: (_ for _ in ()).throw(OSError("always")))
    assert p.total_attempts == 3


def test_retry_non_retryable_raises_immediately():
    p = RetryPolicy(max_attempts=5, sleep=lambda s: None)
    calls = [0]

    def broken():
        calls[0] += 1
        raise ValueError("a bug, not flakiness")

    with pytest.raises(ValueError):
        p.call(broken)
    assert calls[0] == 1


def test_retry_per_attempt_timeout():
    import time as _time

    p = RetryPolicy(max_attempts=2, timeout=0.15, base_delay=0.01,
                    sleep=lambda s: None)
    calls = [0]

    def wedges_once():
        calls[0] += 1
        if calls[0] == 1:
            _time.sleep(5.0)        # abandoned by the policy
        return "recovered"

    assert p.call(wedges_once) == "recovered"
    assert calls[0] == 2

    p2 = RetryPolicy(max_attempts=2, timeout=0.05, base_delay=0.01,
                     sleep=lambda s: None)
    with pytest.raises(AttemptTimeout):
        p2.call(lambda: _time.sleep(5.0))


# -- fault plan --------------------------------------------------------------

def test_fault_plan_hit_counting_and_once():
    plan = faults.FaultPlan(seed=0)
    plan.crash_at("site", at_hit=3)
    with faults.active(plan):
        faults.fault_hook("site")
        faults.fault_hook("site")
        with pytest.raises(faults.FaultInjected):
            faults.fault_hook("site")
        faults.fault_hook("site")            # once=True: disarmed now
    assert plan.hits["site"] == 4
    assert plan.log == [{"site": "site", "action": "crash", "hit": 3}]
    # no plan installed -> hooks are no-ops
    faults.fault_hook("site")
    assert faults.poison_hook("site", 1.5) == 1.5


def test_fault_plan_poison_nan():
    plan = faults.FaultPlan(seed=0)
    plan.nan_at("loss", at_hit=2)
    with faults.active(plan):
        assert faults.poison_hook("loss", 1.0) == 1.0
        poisoned = faults.poison_hook("loss", 1.0)
        assert np.isnan(poisoned)
        arr = faults.poison_hook("loss", np.ones(3))   # disarmed again
        np.testing.assert_array_equal(arr, 1.0)


def test_serve_engine_fault_hook():
    from znicz_tpu.serve.engine import BatchEngine

    eng = BatchEngine(lambda x: x * 2.0, max_batch=8)
    plan = faults.FaultPlan().crash_at("serve.run", at_hit=2)
    with faults.active(plan):
        np.testing.assert_allclose(eng.run(np.ones((2, 4))), 2.0)
        with pytest.raises(faults.FaultInjected):
            eng.run(np.ones((2, 4)))
        np.testing.assert_allclose(eng.run(np.ones((2, 4))), 2.0)


def test_restful_client_retries_through_server_fault():
    """predict_remote rides RetryPolicy: an injected engine crash kills
    the first request (connection-level failure at the client), the
    retry lands on a healed server."""
    from znicz_tpu.loader.restful import PredictionServer, predict_remote

    server = PredictionServer(lambda x: x + 1.0, max_batch=16)
    port = server.start()
    try:
        plan = faults.FaultPlan().crash_at("serve.run", at_hit=1)
        policy = RetryPolicy(max_attempts=3, base_delay=0.01,
                             retryable=(OSError,), seed=0)
        with faults.active(plan):
            out = predict_remote(f"http://127.0.0.1:{port}",
                                 [[1.0, 2.0]], policy=policy, timeout=5)
        np.testing.assert_allclose(out, [[2.0, 3.0]])
        assert policy.total_retries >= 1
    finally:
        server.stop()


# -- crash-safe snapshots ----------------------------------------------------

def test_snapshot_checksum_roundtrip_and_verify(tmp_path):
    w = build(1)
    w.run()
    arrays, meta = collect_state(w)
    path = str(tmp_path / "s.npz")
    write_snapshot(path, arrays, meta)
    assert verify_snapshot(path)
    w2 = build(1, seed=9)
    meta2 = restore_state(w2, path)
    assert int(meta2["checksum"]) > 0


def test_corrupt_snapshot_detected(tmp_path):
    w = build(1)
    w.run()
    arrays, meta = collect_state(w)
    path = str(tmp_path / "s.npz")
    write_snapshot(path, arrays, meta)
    blob = bytearray(open(path, "rb").read())
    mid = len(blob) // 2
    blob[mid:mid + 64] = b"\x00" * 64          # bit rot in the middle
    with open(path, "wb") as f:
        f.write(bytes(blob))
    assert not verify_snapshot(path)
    w2 = build(1, seed=9)
    with pytest.raises((SnapshotCorruptError, Exception)):
        restore_state(w2, path)


def test_checksum_mismatch_raises_on_restore(tmp_path):
    """A snapshot that is a VALID zip but carries tampered content must
    be caught by the checksum, not just by zip CRCs."""
    import json
    import numpy as _np

    w = build(1)
    w.run()
    arrays, meta = collect_state(w)
    path = str(tmp_path / "s.npz")
    write_snapshot(path, arrays, meta)
    with _np.load(path, allow_pickle=False) as zf:
        loaded_meta = json.loads(str(zf["__meta__"]))
        loaded = {k: zf[k] for k in zf.files if k != "__meta__"}
    key = next(k for k in loaded if k.startswith("forward."))
    loaded[key] = loaded[key] + 1.0            # tamper, then re-zip validly
    with open(path, "wb") as f:
        _np.savez_compressed(
            f, __meta__=_np.array(json.dumps(loaded_meta)), **loaded)
    assert not verify_snapshot(path)
    w2 = build(1, seed=9)
    with pytest.raises(SnapshotCorruptError, match="checksum"):
        restore_state(w2, path)


def test_snapshot_write_fault_retried(tmp_path):
    """One injected I/O failure in the write path is absorbed by the
    retry policy — the snapshot still lands and verifies."""
    w = build(1)
    w.run()
    arrays, meta = collect_state(w)
    path = str(tmp_path / "s.npz")
    plan = faults.FaultPlan().oserror_at("snapshot.write", at_hit=1)
    with faults.active(plan):
        write_snapshot(path, arrays, meta)
    assert plan.log and verify_snapshot(path)
    assert not os.path.exists(path + ".tmp")   # no temp litter


def test_failing_snapshot_write_keeps_previous_and_run_alive(tmp_path):
    """Write failures that exhaust the retries degrade gracefully: the
    run continues and the previously published snapshot stays the
    resume point."""
    plan = faults.FaultPlan()
    # epoch-1 snapshot publishes; every later attempt fails (3 armed
    # failures per retry round x 3 remaining epochs)
    for _ in range(9):
        plan.arm("snapshot.write", "oserror", when=lambda path:
                 not path.endswith("t_1.npz"))
    with faults.active(plan):
        w = build(4, tmp_path)
        w.run()
    assert len(w.decision.metrics_history) == 4    # training survived
    published = sorted(p for p in os.listdir(tmp_path)
                       if not p.endswith("_latest.npz"))
    assert published == ["t_1.npz"], published
    assert verify_snapshot(str(tmp_path / "t_1.npz"))


# -- supervised auto-resume (the acceptance chaos test) ----------------------

def test_supervised_resume_is_bit_exact_after_seeded_crash(tmp_path):
    """A training run killed at a SEEDED RANDOM epoch and auto-resumed by
    run_supervised reproduces the uninterrupted run's metric history
    bit-exactly (ISSUE 2 acceptance)."""
    full = build(4, tmp_path / "full")
    full.run()
    full_hist = full.decision.metrics_history
    assert len(full_hist) == 4

    rng = np.random.default_rng(1234)
    crash_epoch = int(rng.integers(1, 4))          # seeded "random" kill
    snap_dir = tmp_path / "chaos"
    plan = faults.FaultPlan(seed=1234)
    plan.crash_at("workflow.step", when=lambda workflow, unit:
                  int(workflow.decision.epoch_number) == crash_epoch)
    with faults.active(plan):
        report = run_supervised(lambda: build(4, snap_dir), str(snap_dir),
                                fast_policy())
    assert plan.log, "the armed crash never fired"
    assert report.restarts == 1
    assert report.resumed_from, "supervisor did not resume from a snapshot"
    hist = report.workflow.decision.metrics_history
    assert hist == full_hist, (crash_epoch, hist, full_hist)


def test_supervisor_rejects_corrupt_newest_snapshot(tmp_path):
    """ISSUE 2 acceptance: a corrupted NEWEST snapshot is detected by
    checksum and the supervisor falls back to the previous valid one."""
    full = build(4, tmp_path / "full")
    full.run()
    full_hist = full.decision.metrics_history

    snap_dir = tmp_path / "s"
    seed_run = build(3, snap_dir)                  # dies "mid-job" at 3
    seed_run.run()
    newest = snap_dir / "t_3.npz"
    blob = bytearray(newest.read_bytes())
    blob[len(blob) // 2:len(blob) // 2 + 128] = b"\xff" * 128
    newest.write_bytes(bytes(blob))
    assert not verify_snapshot(str(newest))

    rejected = []
    assert find_latest_valid_snapshot(str(snap_dir), rejected=rejected) \
        == str(snap_dir / "t_2.npz")
    assert rejected == [str(newest)]

    report = run_supervised(lambda: build(4, snap_dir), str(snap_dir),
                            fast_policy())
    assert str(newest) in report.rejected_snapshots
    assert report.resumed_from[0] == str(snap_dir / "t_2.npz")
    assert report.workflow.decision.metrics_history == full_hist


def test_supervisor_restart_budget_exhausts(tmp_path):
    plan = faults.FaultPlan()
    for _ in range(10):
        plan.crash_at("workflow.step", at_hit=None, once=True)
    with faults.active(plan):
        with pytest.raises(SupervisorExhausted):
            run_supervised(lambda: build(2, tmp_path), str(tmp_path),
                           fast_policy(max_restarts=2))


def test_supervisor_backoff_is_seeded_deterministic():
    a = SupervisorPolicy(seed=5)
    b = SupervisorPolicy(seed=5)
    assert [a.restart_delay(i) for i in (1, 2, 3)] == \
        [b.restart_delay(i) for i in (1, 2, 3)]


def test_watchdog_detects_injected_hang(tmp_path):
    """A hung step (no control-graph progress within step_timeout) is
    treated as a crash: the watchdog interrupts the injected hang, the
    supervisor restarts, and the final history still matches the
    uninterrupted run."""
    full = build(3, tmp_path / "full")
    full.run()
    full_hist = full.decision.metrics_history

    snap_dir = tmp_path / "hang"
    plan = faults.FaultPlan()
    plan.hang_at("workflow.step", seconds=60.0, when=lambda workflow, unit:
                 int(workflow.decision.epoch_number) == 1)
    with faults.active(plan):
        # step_timeout must sit above the worst single-step stall that is
        # NOT a hang (first-dispatch XLA compiles run ~1s on this mesh)
        report = run_supervised(
            lambda: build(3, snap_dir), str(snap_dir),
            fast_policy(step_timeout=2.0, hang_grace=5.0))
    assert plan.log and plan.log[0]["action"] == "hang"
    assert report.hang_events == 1
    assert report.restarts == 1
    assert report.workflow.decision.metrics_history == full_hist


def test_watchdog_captures_hung_stack_into_flight(tmp_path):
    """ISSUE 9 satellite: on hang detection the watchdog freezes the
    hung thread's stack (sys._current_frames) BEFORE interrupting it,
    and the flight artifact carries it — the post-mortem shows WHERE
    the step stalled (here: inside the injected hang's abort-wait in
    faults.py), not just that it did."""
    import json

    snap_dir = tmp_path / "hang"
    plan = faults.FaultPlan()
    plan.hang_at("workflow.step", seconds=60.0, when=lambda workflow, unit:
                 int(workflow.decision.epoch_number) == 1)
    with faults.active(plan):
        report = run_supervised(
            lambda: build(2, snap_dir), str(snap_dir),
            fast_policy(step_timeout=2.0, hang_grace=5.0))
    assert report.hang_events == 1
    assert report.flights, "no flight artifact dumped"
    with open(report.flights[0]) as f:
        doc = json.load(f)
    stack = doc["extra"].get("hung_stack")
    assert stack, "flight artifact carries no hung_stack"
    joined = "".join(stack)
    # the stack names the actual stall point: the injected hang's
    # cooperative wait inside the fault plan
    assert "faults.py" in joined and "_hang" in joined, joined[-2000:]


# -- NaN/Inf health guard ----------------------------------------------------

def test_health_guard_skip_batch_on_nan_loss(tmp_path):
    plan = faults.FaultPlan().nan_at("step.loss", at_hit=4)
    with faults.active(plan):
        w = build(3, health={"mode": "skip"})
        w.run()
    guard = w.health_guard
    assert plan.log, "the armed NaN never fired"
    assert guard.nan_trips == 1
    assert guard.skipped_batches == 1
    assert len(w.decision.metrics_history) == 3    # training completed
    w.stop()
    assert np.isfinite(w.forwards[0].weights.map_read()).all()
    snap = guard.snapshot()
    assert snap["mode"] == "skip" and snap["nan_trips"] == 1


def test_health_guard_skip_restores_poisoned_params(tmp_path):
    """NaN into the PARAMS (the observable effect of NaN grads): the
    poisoned pass publishes a non-finite loss, the guard restores the
    last CERTIFIED state, and training still completes with finite
    weights.  The hit lands in epoch 2 so at least two finite
    observations precede it — the double buffer needs one to capture
    and a later one to certify (an earlier hit is unrecoverable by
    design and only warns)."""
    plan = faults.FaultPlan().nan_at("step.params", at_hit=14)
    with faults.active(plan):
        w = build(3, health={"mode": "skip"})
        w.run()
    assert plan.log
    assert w.health_guard.nan_trips >= 1
    assert w.health_guard.skipped_batches >= 1
    w.stop()
    assert np.isfinite(w.forwards[0].weights.map_read()).all()
    assert np.isfinite(w.forwards[1].weights.map_read()).all()


def test_health_guard_skip_never_restores_uncertified_copy(tmp_path):
    """Double-buffer regression: the loss published at a step is a
    PRE-update forward, so the copy captured alongside a finite loss is
    not yet proven clean.  With per-minibatch metrics, poisoned params
    ride exactly one finite observation before the NaN surfaces — the
    guard must restore the older CERTIFIED copy, not the freshest one
    (a single-buffer guard restores the poison itself and wedges)."""
    plan = faults.FaultPlan().nan_at("step.params", at_hit=7)
    with faults.active(plan):
        w = build(3, health={"mode": "skip"}, defer_metrics=False)
        w.run()
    assert plan.log
    assert w.health_guard.nan_trips >= 1
    assert w.health_guard.skipped_batches >= 1
    assert len(w.decision.metrics_history) == 3
    w.stop()
    assert np.isfinite(w.forwards[0].weights.map_read()).all()
    assert np.isfinite(w.forwards[1].weights.map_read()).all()


def test_health_guard_rollback_mode(tmp_path):
    plan = faults.FaultPlan().nan_at("step.loss", at_hit=4)
    with faults.active(plan):
        w = build(3, health={"mode": "rollback",
                             "rollback": {"lr_cut": 0.5}})
        base_lr = float(w.gds[0].learning_rate)
        w.run()
    assert w.health_guard.rollbacks_forced == 1
    assert w.nn_rollback.rollback_count == 1
    assert float(w.gds[0].learning_rate) == base_lr * 0.5
    assert len(w.decision.metrics_history) == 3


def test_health_guard_counters_in_web_status():
    from znicz_tpu.web_status import WebStatus

    w = build(1, health={"mode": "skip"})
    w.run()
    status = WebStatus()
    status.register(w)
    status.register_health("trainer", w.health_guard)
    doc = status.snapshot()
    assert doc["health"]["trainer"]["nan_trips"] == 0
    assert doc["health"]["trainer"]["mode"] == "skip"


# -- progress counter (watchdog's heartbeat) ---------------------------------

def test_workflow_progress_counter_advances():
    w = build(1)
    assert w.signals_dispatched == 0
    w.run()
    assert w.signals_dispatched > 10


# -- extended chaos (slow lane: tools/chaos.sh runs it standalone) -----------

@pytest.mark.slow
def test_supervised_survives_repeated_crashes(tmp_path):
    """Three separate kills across one training job; every restart
    resumes from the newest valid snapshot and the final history is
    still bit-exact."""
    full = build(6, tmp_path / "full")
    full.run()
    full_hist = full.decision.metrics_history

    snap_dir = tmp_path / "multi"
    plan = faults.FaultPlan(seed=99)
    for epoch in (1, 3, 4):
        plan.crash_at("workflow.step",
                      when=lambda workflow, unit, e=epoch:
                      int(workflow.decision.epoch_number) == e)
    with faults.active(plan):
        report = run_supervised(lambda: build(6, snap_dir), str(snap_dir),
                                fast_policy(max_restarts=5))
    assert report.restarts == 3
    assert report.workflow.decision.metrics_history == full_hist
