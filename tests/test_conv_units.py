"""Tier-1 tests for the conv-stack units: numpy-vs-xla backend parity,
fwd/gd pairing, dropout/stochastic determinism (SURVEY.md §5 tier-1)."""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.memory import Array
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.units.activation import (ForwardTanh, BackwardTanh,
                                        ForwardLog, BackwardLog,
                                        ForwardSinCos, BackwardSinCos,
                                        ForwardTanhLog, BackwardTanhLog)
from znicz_tpu.units.conv import Conv, ConvTanh, ConvRELU, gabor_bank
from znicz_tpu.units.dropout import DropoutForward, DropoutBackward
from znicz_tpu.units.gd_conv import GradientDescentConv, GDTanhConv
from znicz_tpu.units.gd_pooling import (GDAvgPooling, GDMaxPooling)
from znicz_tpu.units.normalization import (LRNormalizerForward,
                                           LRNormalizerBackward)
from znicz_tpu.units.nn_units import MatchingObject
from znicz_tpu.units.pooling import (AvgPooling, MaxPooling, MaxAbsPooling,
                                     StochasticPooling)


def run_unit(cls, device, x, seed=42, init_attrs=(), **kwargs):
    prng.seed_all(seed)
    w = Workflow(name="t")
    unit = cls(w, **kwargs)
    unit.input = Array(x)
    for name, val in init_attrs:
        setattr(unit, name, Array(val))
    unit.initialize(device=device)
    unit.run()
    return unit


@pytest.mark.parametrize("cls", [Conv, ConvTanh, ConvRELU])
def test_conv_backend_parity(cls):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    kw = dict(n_kernels=5, kx=3, ky=3, sliding=(2, 2), padding=(1, 1, 1, 1))
    u_np = run_unit(cls, NumpyDevice(), x, **kw)
    u_x = run_unit(cls, TPUDevice(), x, **kw)
    np.testing.assert_array_equal(u_np.weights.map_read(),
                                  u_x.weights.map_read())
    np.testing.assert_allclose(u_x.output.map_read(), u_np.output.map_read(),
                               rtol=1e-4, atol=1e-5)
    assert u_np.output.shape == (2, 4, 4, 5)


def test_conv_gabor_filling_deterministic():
    prng.seed_all(7)
    b1 = gabor_bank(5, 5, 3, 8)
    prng.seed_all(7)
    b2 = gabor_bank(5, 5, 3, 8)
    np.testing.assert_array_equal(b1, b2)
    assert np.abs(b1).max() <= 0.1 + 1e-6


@pytest.mark.parametrize("fwd_cls,gd_cls", [
    (Conv, GradientDescentConv),
    (ConvTanh, GDTanhConv),
])
def test_gd_conv_backend_parity(fwd_cls, gd_cls):
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 6, 6, 2)).astype(np.float32)
    kw = dict(n_kernels=4, kx=3, ky=3, sliding=(1, 1), padding=(0, 0, 0, 0))

    def build(device):
        prng.seed_all(9)
        w = Workflow(name="t")
        fwd = fwd_cls(w, **kw)
        fwd.input = Array(x)
        fwd.initialize(device=device)
        fwd.run()
        gd = gd_cls(w, learning_rate=0.1, weights_decay=0.01,
                    gradient_moment=0.9)
        gd.link_from_forward(fwd)
        gd.err_output = Array(rng.normal(size=fwd.output.shape)
                              .astype(np.float32))
        gd.batch_size = x.shape[0]
        gd.initialize(device=device)
        gd.run()
        return gd

    rng = np.random.default_rng(1)          # same err stream for both
    gd_np = build(NumpyDevice())
    rng = np.random.default_rng(1)
    gd_x = build(TPUDevice())
    for attr in ("err_input", "weights", "bias", "gradient_weights",
                 "gradient_bias"):
        np.testing.assert_allclose(
            getattr(gd_x, attr).map_read(), getattr(gd_np, attr).map_read(),
            rtol=2e-4, atol=1e-4, err_msg=attr)


def test_matching_registry_has_conv_pairs():
    for key in ("conv", "conv_tanh", "conv_relu", "conv_str", "max_pooling",
                "avg_pooling", "stochastic_pooling", "norm", "dropout"):
        assert key in MatchingObject.forwards, key
        assert key in MatchingObject.gds, key


@pytest.mark.parametrize("cls", [MaxPooling, MaxAbsPooling, AvgPooling])
def test_pooling_backend_parity(cls):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(2, 7, 7, 3)).astype(np.float32)
    u_np = run_unit(cls, NumpyDevice(), x, kx=2, ky=2)
    u_x = run_unit(cls, TPUDevice(), x, kx=2, ky=2)
    np.testing.assert_allclose(u_x.output.map_read(), u_np.output.map_read(),
                               rtol=1e-5, atol=1e-6)
    if hasattr(u_np, "input_offset"):
        np.testing.assert_array_equal(u_np.input_offset.map_read(),
                                      u_x.input_offset.map_read())


def test_max_pooling_gd_scatter():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 6, 6, 2)).astype(np.float32)
    for device in (NumpyDevice(), TPUDevice()):
        w = Workflow(name="t")
        fwd = MaxPooling(w, kx=2, ky=2)
        fwd.input = Array(x)
        fwd.initialize(device=device)
        fwd.run()
        gd = GDMaxPooling(w)
        gd.link_from_forward(fwd)
        err = rng.normal(size=fwd.output.shape).astype(np.float32)
        gd.err_output = Array(err)
        gd.initialize(device=device)
        gd.run()
        ein = gd.err_input.map_read()
        assert ein.shape == x.shape
        np.testing.assert_allclose(ein.sum(), err.sum(), rtol=1e-4)
        rng = np.random.default_rng(3)  # reset for second device


def test_avg_pooling_gd_spread():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(1, 4, 4, 1)).astype(np.float32)
    w = Workflow(name="t")
    fwd = AvgPooling(w, kx=2, ky=2)
    fwd.input = Array(x)
    fwd.initialize(device=NumpyDevice())
    fwd.run()
    gd = GDAvgPooling(w)
    gd.link_from_forward(fwd)
    gd.err_output = Array(np.ones(fwd.output.shape, np.float32))
    gd.initialize(device=NumpyDevice())
    gd.run()
    np.testing.assert_allclose(gd.err_input.map_read(), 0.25, rtol=1e-6)


def test_stochastic_pooling_seed_reproducible():
    rng = np.random.default_rng(5)
    x = np.abs(rng.normal(size=(2, 6, 6, 2))).astype(np.float32)
    u1 = run_unit(StochasticPooling, NumpyDevice(), x, seed=11, kx=2, ky=2)
    u2 = run_unit(StochasticPooling, NumpyDevice(), x, seed=11, kx=2, ky=2)
    np.testing.assert_array_equal(u1.output.map_read(), u2.output.map_read())
    # forward_mode is deterministic expectation, backend-parity checkable
    prng.seed_all(12)
    w = Workflow(name="t")
    fwd = StochasticPooling(w, kx=2, ky=2)
    fwd.input = Array(x)
    fwd.forward_mode = True
    fwd.initialize(device=TPUDevice())
    fwd.run()
    fwd_np = StochasticPooling(Workflow(name="t2"), kx=2, ky=2)
    fwd_np.input = Array(x)
    fwd_np.forward_mode = True
    fwd_np.initialize(device=NumpyDevice())
    fwd_np.run()
    np.testing.assert_allclose(fwd.output.map_read(),
                               fwd_np.output.map_read(), rtol=1e-5, atol=1e-6)


def test_lrn_units_backend_parity():
    rng = np.random.default_rng(6)
    x = rng.normal(size=(2, 4, 4, 8)).astype(np.float32)
    u_np = run_unit(LRNormalizerForward, NumpyDevice(), x)
    u_x = run_unit(LRNormalizerForward, TPUDevice(), x)
    np.testing.assert_allclose(u_x.output.map_read(), u_np.output.map_read(),
                               rtol=1e-5, atol=1e-6)
    for device in (NumpyDevice(), TPUDevice()):
        w = Workflow(name="t")
        fwd = LRNormalizerForward(w)
        fwd.input = Array(x)
        fwd.initialize(device=device)
        fwd.run()
        gd = LRNormalizerBackward(w)
        gd.link_from_forward(fwd)
        gd.err_output = Array(np.ones_like(x))
        gd.initialize(device=device)
        gd.run()
        assert gd.err_input.shape == x.shape


def test_dropout_train_and_inference():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(4, 10)).astype(np.float32)
    u = run_unit(DropoutForward, NumpyDevice(), x, seed=13,
                 dropout_ratio=0.5)
    y = u.output.map_read()
    mask = u.mask.map_read()
    assert set(np.unique(mask)).issubset({0.0, 2.0})
    np.testing.assert_allclose(y, x * mask)
    # backward reuses the mask
    w = Workflow(name="t")
    gd = DropoutBackward(w)
    gd.link_from_forward(u)
    err = np.ones_like(x)
    gd.err_output = Array(err)
    gd.initialize(device=NumpyDevice())
    gd.run()
    np.testing.assert_allclose(gd.err_input.map_read(), mask)
    # inference: identity
    u.forward_mode = True
    u.run()
    np.testing.assert_allclose(u.output.map_read(), x)


@pytest.mark.parametrize("fwd_cls,bwd_cls", [
    (ForwardTanh, BackwardTanh),
    (ForwardLog, BackwardLog),
    (ForwardSinCos, BackwardSinCos),
    (ForwardTanhLog, BackwardTanhLog),
])
def test_activation_units_parity_and_numeric(fwd_cls, bwd_cls):
    rng = np.random.default_rng(8)
    x = rng.normal(size=(3, 8)).astype(np.float32) * 2.0
    u_np = run_unit(fwd_cls, NumpyDevice(), x)
    u_x = run_unit(fwd_cls, TPUDevice(), x)
    np.testing.assert_allclose(u_x.output.map_read(), u_np.output.map_read(),
                               rtol=1e-5, atol=1e-6)
    # backward vs central difference on the numpy path
    w = Workflow(name="t")
    gd = bwd_cls(w)
    gd.link_from_forward(u_np)
    err = np.ones_like(x)
    gd.err_output = Array(err)
    gd.initialize(device=NumpyDevice())
    gd.run()
    from znicz_tpu.ops import activations as act_ops
    eps = 1e-3
    num = (act_ops.forward(np, fwd_cls.ACTIVATION, x + eps) -
           act_ops.forward(np, fwd_cls.ACTIVATION, x - eps)) / (2 * eps)
    # skip points near piecewise kinks (tanhlog switchover)
    safe = np.abs(np.abs(x) - 1.0) > 1e-2
    np.testing.assert_allclose(gd.err_input.map_read()[safe], num[safe],
                               rtol=2e-2, atol=1e-3)
