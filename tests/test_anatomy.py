"""Step-time anatomy (ISSUE 20): the StepAnatomy accountant, the
split-dispatch fused/transformer producers' numerics parity, phase-sum
vs step-wall reconciliation, MFU gauge wiring, the per-rank straggler
rule, goodput note plumbing, and the bench perf-regression sentinel
(synthetic 20% cliff flagged; the real recorded r04->r05 pair passes).
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.observe import probe, registry
from znicz_tpu.observe.anatomy import TRAIN_PHASES, StepAnatomy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flat(**kw):
    return registry.REGISTRY.snapshot_flat(skip_zero=False, **kw)


# -- the accountant ----------------------------------------------------------

def test_step_anatomy_stamps_and_pretouch(monkeypatch):
    """Stamps charge cursor->now per phase (deterministic via injected
    nows), every child exists at construction, and finish() emits the
    step counter + MFU from the registered analytic FLOPs."""
    monkeypatch.setenv("ZNICZ_TPU_PEAK_FLOPS", "1e9")
    anat = StepAnatomy("anat_unit", TRAIN_PHASES)
    # pre-touch: all children live at 0 before any step
    flat = _flat()
    assert flat['znicz_anatomy_steps_total{plane="anat_unit"}'] == 0.0
    for phase in TRAIN_PHASES:
        assert flat['znicz_anatomy_phase_seconds_count'
                    f'{{plane="anat_unit",phase="{phase}"}}'] == 0.0
    assert flat['znicz_anatomy_mfu{plane="anat_unit"}'] == 0.0

    anat.set_flops(2e8)                  # with peak 1e9: mfu = 0.2/wall
    t0 = anat.begin()
    anat.stamp("zero_gather", now=t0 + 0.10)
    anat.stamp("grad", now=t0 + 0.60)
    anat.stamp("collective", now=t0 + 0.75)
    anat.stamp("update", now=t0 + 0.80)
    wall = anat.finish()
    flat = _flat()
    assert flat['znicz_anatomy_phase_seconds_sum'
                '{plane="anat_unit",phase="zero_gather"}'] == \
        pytest.approx(0.10)
    assert flat['znicz_anatomy_phase_seconds_sum'
                '{plane="anat_unit",phase="grad"}'] == pytest.approx(0.50)
    assert flat['znicz_anatomy_phase_seconds_sum'
                '{plane="anat_unit",phase="collective"}'] == \
        pytest.approx(0.15)
    assert flat['znicz_anatomy_steps_total{plane="anat_unit"}'] == 1.0
    # finish() measures the REAL wall (the injected nows are in its
    # future, so the measured step is tiny) — the MFU gauge still set
    assert wall >= 0.0
    assert flat['znicz_anatomy_mfu{plane="anat_unit"}'] > 0.0


def test_observe_phase_respects_probe_gate():
    probe.set_enabled(False)
    try:
        before = _flat().get(
            'znicz_anatomy_phase_seconds_count'
            '{plane="gated",phase="stage"}', 0.0)
        probe.anatomy_phase("gated", "stage", 0.5)
        after = _flat().get(
            'znicz_anatomy_phase_seconds_count'
            '{plane="gated",phase="stage"}', 0.0)
        assert after == before           # disabled plane records nothing
    finally:
        probe.set_enabled(True)
    probe.anatomy_phase("gated", "stage", 0.5)
    assert _flat()['znicz_anatomy_phase_seconds_count'
                   '{plane="gated",phase="stage"}'] == before + 1.0


# -- fused producer (dp + shard_params + int8) -------------------------------

def _run_fused(anatomy: bool, seed: int = 31):
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    prng.seed_all(seed)
    w = build_fused(max_epochs=2, layers=(32,), minibatch_size=16,
                    n_train=96, n_valid=32,
                    mesh=data_parallel_mesh(4), optimizer="adam",
                    shard_params=True, anatomy=anatomy,
                    quantized_collectives={"mode": "int8",
                                           "error_feedback": True})
    w.initialize(device=TPUDevice())
    w.run()
    hist = [h["metric_validation"] for h in w.decision.metrics_history]
    w.stop()
    return hist


def test_anatomy_phase_sum_matches_step_wall(monkeypatch):
    """ISSUE 20 acceptance: on the forced multi-device CPU mesh a
    dp+shard_params+int8 anatomy run attributes per-phase seconds
    summing to within 10% of the measured step wall, counts its steps,
    and reads a nonzero MFU against the pinned nominal peak."""
    monkeypatch.setenv("ZNICZ_TPU_PEAK_FLOPS", "1e12")
    base = _flat()
    base_phase = {k: v for k, v in base.items() if k.startswith(
        'znicz_anatomy_phase_seconds_sum{plane="fused"')}
    base_step = base.get(
        'znicz_anatomy_step_seconds_sum{plane="fused"}', 0.0)
    base_steps = base.get('znicz_anatomy_steps_total{plane="fused"}',
                          0.0)
    hist = _run_fused(anatomy=True)
    assert len(hist) == 2
    flat = _flat()
    phase_sum = sum(
        v - base_phase.get(k, 0.0) for k, v in flat.items()
        if k.startswith('znicz_anatomy_phase_seconds_sum{plane="fused"'))
    step_sum = flat['znicz_anatomy_step_seconds_sum{plane="fused"}'] \
        - base_step
    steps = flat['znicz_anatomy_steps_total{plane="fused"}'] - base_steps
    assert steps == 12                   # 2 epochs x 96/16 minibatches
    assert step_sum > 0.0
    assert abs(phase_sum - step_sum) <= 0.10 * step_sum, \
        (phase_sum, step_sum)
    # every train phase genuinely charged (shard_params => zero_gather,
    # int8 => the quantized collective dispatch)
    for phase in TRAIN_PHASES:
        assert flat['znicz_anatomy_phase_seconds_count'
                    f'{{plane="fused",phase="{phase}"}}'] >= steps
    assert flat['znicz_anatomy_mfu{plane="fused"}'] > 0.0
    # the families are live on the scrape surface and rank-label into
    # the fleet-merged view
    prom = registry.REGISTRY.render_prometheus()
    assert "znicz_anatomy_mfu" in prom
    assert "znicz_goodput_productive_seconds_total" in prom
    from znicz_tpu.observe import federation as fed
    agg = fed.FleetAggregator(min_refresh_s=0.0)
    agg.add_source(3, registry.REGISTRY.render_prometheus)
    try:
        merged = agg.snapshot_flat(skip_zero=False)
        assert any(k.startswith("znicz_anatomy_step_seconds_sum")
                   and 'rank="3"' in k for k in merged)
    finally:
        agg.close()


def test_anatomy_numerics_track_fused_path():
    """The split-dispatch programs compute the same training run as the
    fused single-program path to float tolerance (XLA fuses and
    reassociates differently across the program cuts, so bit-exactness
    is NOT the contract — closeness is)."""
    hist_fused = _run_fused(anatomy=False)
    hist_anat = _run_fused(anatomy=True)
    assert len(hist_anat) == len(hist_fused)
    # validation error percent per epoch: identical up to at most one
    # boundary sample flipping on ~1e-7 loss differences
    np.testing.assert_allclose(hist_anat, hist_fused,
                               atol=100.0 / 32 + 1e-9)


def test_anatomy_rejects_accumulation():
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    prng.seed_all(5)
    w = build_fused(max_epochs=1, layers=(16,), minibatch_size=16,
                    n_train=64, n_valid=16,
                    mesh=data_parallel_mesh(2), anatomy=True,
                    accumulate_steps=2)
    with pytest.raises(ValueError, match="accumulate"):
        w.initialize(device=TPUDevice())
    w.stop()


# -- transformer producer ----------------------------------------------------

def test_transformer_anatomy_loss_parity(cpu_devices, monkeypatch):
    """The transformer anatomy step applies the TRUE batch-mean
    gradient (local grads + one explicit psum, the quantized-collectives
    semantics — see the make_train_step docstring), so its reference is
    a SINGLE-SHARD full-batch run, which it must match to float
    tolerance — NOT the multi-shard exact path, whose AD-transposed
    per-replica grads follow a different (documented) trajectory.  All
    four phases and the MFU gauge populate."""
    import jax
    from znicz_tpu.parallel import transformer as tfm
    from znicz_tpu.parallel.mesh import make_mesh

    monkeypatch.setenv("ZNICZ_TPU_PEAK_FLOPS", "1e12")
    prng.seed_all(7)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 1, 16, 2, 32, 11
    params = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, vocab, (4, 8)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)
    meshes = {
        "plain": make_mesh({"data": 1, "seq": 1, "model": 1}),
        "anatomy": make_mesh({"data": 2, "seq": 1, "model": 1}),
    }

    losses = {}
    for name, anatomy in (("plain", False), ("anatomy", True)):
        step, _ = tfm.make_train_step(meshes[name], n_layers, d, heads,
                                      ff, vocab, lr=0.1, anatomy=anatomy)
        p = {k: (v if not isinstance(v, list) else
                 [dict(b) for b in v]) for k, v in params.items()}
        run = []
        for _ in range(5):
            p, loss = step(p, tokens, labels)
            run.append(float(jax.device_get(loss)))
        losses[name] = run
    np.testing.assert_allclose(losses["anatomy"], losses["plain"],
                               rtol=2e-4)
    assert losses["anatomy"][-1] < losses["anatomy"][0]
    flat = _flat()
    for phase in ("grad", "collective", "update"):
        assert flat['znicz_anatomy_phase_seconds_count'
                    f'{{plane="transformer",phase="{phase}"}}'] >= 5
    assert flat['znicz_anatomy_mfu{plane="transformer"}'] > 0.0


# -- goodput plumbing --------------------------------------------------------

def test_goodput_note_and_ratio():
    base = probe.goodput_totals()
    probe.goodput_pretouch(range(2))
    probe.goodput_note("productive", 0, 3.0)
    probe.goodput_note("idle", 1, 1.0)
    probe.goodput_note("productive", 0, -0.5)     # non-positive: ignored
    totals = probe.goodput_totals()
    assert totals["productive"] == pytest.approx(base["productive"] + 3.0)
    assert totals["idle"] == pytest.approx(base["idle"] + 1.0)
    with pytest.raises(ValueError, match="category"):
        probe.goodput_note("wasted", 0, 1.0)
    flat = _flat()
    spent = sum(totals.values())
    assert flat["znicz_goodput_ratio"] == \
        pytest.approx(totals["productive"] / spent)


# -- straggler rule ----------------------------------------------------------

def test_rank_straggler_rule_trips_deterministically():
    """ISSUE 20 acceptance: per-rank step-seconds spread — exactly the
    delayed rank's rule trips on deterministic tower ticks."""
    from znicz_tpu.observe import federation as fed
    from znicz_tpu.observe.registry import Registry

    regs = []
    for _ in range(3):
        r = Registry()
        r.histogram("znicz_anatomy_step_seconds", "step wall",
                    labelnames=("plane",), buckets=(0.05, 0.2, 1.0))
        regs.append(r)
    agg = fed.FleetAggregator(min_refresh_s=0.0)
    for i, r in enumerate(regs):
        agg.add_source(i, r.render_prometheus)
    rules = fed.add_straggler_rules(agg, spread=1.5, window_s=60.0,
                                    min_count=4)
    try:
        assert [r.name for r in rules] == \
            [f"rank_straggler[{i}]" for i in range(3)]
        ts = 5000.0
        for r in regs:
            r.get("znicz_anatomy_step_seconds").labels(plane="fused")
        agg.tower.observe_now(ts=ts)
        for _ in range(8):
            for i, r in enumerate(regs):
                r.get("znicz_anatomy_step_seconds") \
                    .labels(plane="fused") \
                    .observe(0.5 if i == 2 else 0.1)
        agg.tower.observe_now(ts=ts + 5)
        agg.tower.observe_now(ts=ts + 10)
        assert [r.trips > 0 for r in rules] == [False, False, True], \
            [(r.name, r.trips, r.last_value) for r in rules]
        # a healthy spread never trips: continue with uniform steps
        for _ in range(8):
            for r in regs:
                r.get("znicz_anatomy_step_seconds") \
                    .labels(plane="fused").observe(0.1)
        agg.tower.observe_now(ts=ts + 80)     # old spread aged out
        agg.tower.observe_now(ts=ts + 85)
        assert rules[2].trips == 1            # no re-trip once healthy
    finally:
        agg.close()


# -- bench sentinel ----------------------------------------------------------

def _sentinel():
    spec = importlib.util.spec_from_file_location(
        "bench_sentinel", os.path.join(REPO, "tools",
                                       "bench_sentinel.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _round_file(tmp_path, name, value, rc=0,
                metric="fc_train_samples_per_sec", unit="samples/sec"):
    doc = {"n": 1, "cmd": "bench", "rc": rc, "parsed": None,
           "tail": json.dumps({"metric": metric, "value": value,
                               "unit": unit, "vs_baseline": 1.0})}
    path = tmp_path / name
    path.write_text(json.dumps(doc))
    return str(path)


def test_sentinel_flags_synthetic_regression(tmp_path, capsys):
    sentinel = _sentinel()
    old = _round_file(tmp_path, "old.json", 1000.0)
    new = _round_file(tmp_path, "new.json", 800.0)   # -20% throughput
    assert sentinel.main([old, new]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "fc_train_samples_per_sec" in out
    # report-only always exits 0; an improvement or within-band move
    # never fails
    assert sentinel.main([old, new, "--report-only"]) == 0
    better = _round_file(tmp_path, "better.json", 1050.0)
    assert sentinel.main([old, better]) == 0
    # a wider band tolerates the same cliff
    assert sentinel.main([old, new, "--band", "0.25"]) == 0


def test_sentinel_orientation_and_one_sided(tmp_path):
    sentinel = _sentinel()
    assert sentinel.lower_is_better("serve_latency_p95", "seconds")
    assert not sentinel.lower_is_better("train_samples_per_sec",
                                        "samples/sec")
    # time-like metric regresses UP
    old = _round_file(tmp_path, "o.json", 1.0, metric="step_seconds",
                      unit="seconds")
    new = _round_file(tmp_path, "n.json", 1.3, metric="step_seconds",
                      unit="seconds")
    assert sentinel.main([old, new]) == 1
    # one-sided metrics report but never fail
    findings = sentinel.compare(
        {"only_old": {"value": 5.0, "unit": "samples/sec"}},
        {"only_new": {"value": 7.0, "unit": "samples/sec"}})
    kinds = {f["metric"]: f["kind"] for f in findings}
    assert kinds == {"only_old": "dropped", "only_new": "new"}


def test_sentinel_passes_real_recorded_rounds():
    """The recorded BENCH_r04 -> BENCH_r05 pair is an improvement and
    must pass the default band."""
    r04 = os.path.join(REPO, "BENCH_r04.json")
    r05 = os.path.join(REPO, "BENCH_r05.json")
    if not (os.path.exists(r04) and os.path.exists(r05)):
        pytest.skip("recorded bench rounds not present")
    sentinel = _sentinel()
    assert sentinel.main([r04, r05]) == 0
