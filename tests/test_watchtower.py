"""Watchtower + flight recorder (ISSUE 6): the retained time-series
ring stays bounded and reconstructs exactly, SLO rules trip
deterministically (including under seeded fault injection), metric
histories are bit-exact with the sampler on or off, the shared
histogram-quantile estimator replaces the serving plane's private
percentile code, the JSONL sink rotates at its byte bound, and a
seeded `workflow.step` crash under `run_supervised` leaves a valid
flight artifact carrying the crashing span, the fault's resilience
instant, and at least one time-series sample (the acceptance chaos
test)."""

import json
import logging
import os
import time

import numpy as np
import pytest

from znicz_tpu import observe
from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.core.logger import JsonlHandler
from znicz_tpu.observe import flight, probe, watchtower
from znicz_tpu.observe.registry import REGISTRY, Registry, \
    quantile_from_buckets
from znicz_tpu.observe.watchtower import (Rule, TimeSeriesRing,
                                          Watchtower, bucket_counts,
                                          match_keys)
from znicz_tpu.resilience import faults
from znicz_tpu.resilience.supervisor import SupervisorPolicy, \
    run_supervised
from znicz_tpu.serve.metrics import LatencyHistogram
from znicz_tpu.standard_workflow import StandardWorkflow
from znicz_tpu.web_status import WebStatus

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 24},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 6},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
]
LOADER = {"n_classes": 6, "sample_shape": (10, 10), "n_train": 240,
          "n_valid": 120, "minibatch_size": 40, "spread": 2.5,
          "noise": 1.0}


def build(max_epochs, snap_dir=None, seed=77, tower=None):
    prng.seed_all(seed)
    cfg = None
    if snap_dir is not None:
        cfg = {"directory": str(snap_dir), "prefix": "t",
               "only_improved": False, "keep_all": True}
    w = StandardWorkflow(
        name="TowerTest", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": max_epochs},
        snapshotter_config=cfg)
    w.initialize(device=TPUDevice())
    if tower is not None:
        tower.attach(w)
    return w


@pytest.fixture(autouse=True)
def _clean_globals():
    """No leaked fault plans, flight auto-dump config, or disabled
    plane between tests."""
    yield
    faults.uninstall()
    flight.configure()                   # dir=None: auto_dump off again
    observe.set_enabled(True)


# -- TimeSeriesRing ----------------------------------------------------------

def test_ring_stores_deltas_and_reconstructs():
    ring = TimeSeriesRing(capacity=8, registry=Registry())
    d1 = ring.sample(flat={"a_total": 1.0, "b": 5.0}, ts=10.0)
    d2 = ring.sample(flat={"a_total": 1.0, "b": 7.0}, ts=11.0)
    d3 = ring.sample(flat={"a_total": 2.0, "b": 7.0}, ts=12.0)
    assert d1 == {"a_total": 1.0, "b": 5.0}
    assert d2 == {"b": 7.0}              # only the changed key
    assert d3 == {"a_total": 2.0}
    assert ring.current() == {"a_total": 2.0, "b": 7.0}
    assert ring.series("b") == [(10.0, 5.0), (11.0, 7.0), (12.0, 7.0)]
    assert ring.series("b", window_s=1.5) == [(11.0, 7.0), (12.0, 7.0)]


def test_ring_bounded_under_10k_sample_soak():
    ring = TimeSeriesRing(capacity=64, registry=Registry())
    for i in range(10_000):
        ring.sample(flat={"soak_total": float(i), "const": 1.0},
                    ts=float(i))
    assert len(ring) == 64               # ring, not a log
    doc = ring.to_dict()
    assert len(doc["samples"]) == 64
    # evicted deltas folded into base: reconstruction is still exact
    replay = dict(doc["base"])
    for row in doc["samples"]:
        replay.update(row["delta"])
    assert replay == {"soak_total": 9999.0, "const": 1.0}
    assert doc["base_ts"] == 9935.0      # stamp of newest folded sample
    series = ring.series("soak_total")
    assert len(series) == 64 and series[-1] == (9999.0, 9999.0)


def test_ring_summary_and_counter_rate():
    ring = TimeSeriesRing(capacity=8, registry=Registry())
    for ts, v in ((0.0, 0.0), (5.0, 5.0), (10.0, 30.0)):
        ring.sample(flat={"ev_total": v, "depth": 10.0 - v}, ts=ts)
    s = ring.summary()
    assert s["ev_total"] == {"min": 0.0, "mean": pytest.approx(35 / 3),
                             "max": 30.0, "last": 30.0,
                             "rate_per_s": 3.0}
    assert "rate_per_s" not in s["depth"]          # gauges get no rate
    assert s["depth"]["min"] == -20.0 and s["depth"]["last"] == -20.0


def test_ring_nan_provider_recorded_as_zero_and_json_safe():
    """A dead scrape-time gauge provider reads NaN by design; the ring
    must neither bloat every delta (NaN != NaN) nor serialize a bare
    NaN token into /timeseries.json."""
    ring = TimeSeriesRing(capacity=8, registry=Registry())
    nan = float("nan")
    ring.sample(flat={"live": 3.0, "dead": 2.0}, ts=0.0)
    d2 = ring.sample(flat={"live": 3.0, "dead": nan}, ts=1.0)
    assert d2 == {"dead": 0.0}           # NaN == vanish, explicit zero
    d3 = ring.sample(flat={"live": 3.0, "dead": nan}, ts=2.0)
    assert d3 == {}                      # ...and stays quiet after
    assert ring.sample(flat={"never": nan}, ts=3.0) == {"live": 0.0}
    json.loads(json.dumps(ring.to_dict(), allow_nan=False))
    json.loads(json.dumps(ring.summary(), allow_nan=False))


def test_ring_to_dict_last_n_folds_head_into_base():
    ring = TimeSeriesRing(capacity=16, registry=Registry())
    for i in range(6):
        ring.sample(flat={"c_total": float(i)}, ts=float(i))
    doc = ring.to_dict(last_n=2)
    assert len(doc["samples"]) == 2
    assert doc["base"] == {"c_total": 3.0} and doc["base_ts"] == 3.0
    replay = dict(doc["base"])
    for row in doc["samples"]:
        replay.update(row["delta"])
    assert replay == ring.current()      # trimmed view replays exactly
    assert len(ring.to_dict()["samples"]) == 6   # untrimmed untouched


def test_rule_matching_flag_surfaces_dead_selectors():
    reg = Registry()
    tower = Watchtower(capacity=8, registry=reg)
    live = tower.add_rule(Rule("live", "depth", lambda v: False))
    dead = tower.add_rule(Rule("dead", "no_such_metric",
                               lambda v: False))
    reg.gauge("depth").set(1.0)
    tower.observe_now(ts=1.0)
    assert live.snapshot()["matching"] is True
    assert dead.snapshot()["matching"] is False
    assert dead.last_value is None       # never actually evaluated


def test_ring_capacity_validation():
    with pytest.raises(ValueError):
        TimeSeriesRing(capacity=0)
    with pytest.raises(ValueError):
        Watchtower(step_every=0)


def test_match_keys_exact_family_and_label_filter():
    flat = {"a_total": 1.0,
            'ev_total{kind="fault",site="x"}': 2.0,
            'ev_total{kind="nan",site="x"}': 3.0,
            "a_total_extra": 9.0}
    assert match_keys("a_total", flat) == ["a_total"]
    assert sorted(match_keys("ev_total", flat)) == \
        ['ev_total{kind="fault",site="x"}', 'ev_total{kind="nan",site="x"}']
    assert match_keys('ev_total{kind="fault"}', flat) == \
        ['ev_total{kind="fault",site="x"}']
    assert match_keys("missing", flat) == []


# -- Rule --------------------------------------------------------------------

def test_rule_reduces():
    def run(reduce, seq, window_s=100.0):
        r = Rule("r", "m", lambda v: False, reduce=reduce,
                 window_s=window_s)
        for ts, v in seq:
            r.observe(ts, v)
        return r.last_value

    seq = [(0.0, 4.0), (10.0, 2.0), (20.0, 8.0)]
    assert run("last", seq) == 8.0
    assert run("min", seq) == 2.0
    assert run("max", seq) == 8.0
    assert run("mean", seq) == pytest.approx(14 / 3)
    assert run("delta", seq) == 4.0
    assert run("rate", seq) == pytest.approx(4 / 20)
    assert run("ratio_to_first", seq) == 2.0
    with pytest.raises(ValueError):
        Rule("r", "m", lambda v: True, reduce="p999")
    with pytest.raises(ValueError):
        Rule("r", "m", lambda v: True, reduce="rate")   # needs window_s


def test_rule_window_keeps_trailing_anchor():
    r = Rule("r", "m", lambda v: False, reduce="delta", window_s=10.0)
    for ts, v in ((0.0, 0.0), (5.0, 1.0), (10.0, 2.0), (15.0, 3.0)):
        r.observe(ts, v)
    # cutoff is ts=5: the (5.0, 1.0) sample anchors the window's
    # trailing edge, so delta measures 15s-vs-5s, not vs a survivor
    assert r.last_value == 2.0


def test_rule_for_duration_and_rearm():
    r = Rule("r", "m", lambda v: v > 10.0, for_s=5.0)
    assert r.observe(0.0, 20.0) is None            # breach starts
    assert r.observe(4.0, 20.0) is None            # not held long enough
    assert r.observe(5.0, 20.0) == 20.0            # trip
    assert r.observe(6.0, 20.0) is None            # no storm: stays tripped
    assert r.trips == 1 and r.last_trip_ts == 5.0
    assert r.observe(7.0, 1.0) is None             # recovery re-arms
    assert r.observe(8.0, 20.0) is None
    assert r.observe(13.0, 20.0) == 20.0           # second full cycle
    assert r.trips == 2


def test_rule_trip_fires_counter_instant_and_action():
    reg = Registry()
    tower = Watchtower(capacity=8, registry=reg)
    fired = []
    tower.add_rule(Rule("boom", "depth", lambda v: v > 3.0,
                        action=lambda rule, v: fired.append((rule.name, v))))
    gauge = reg.gauge("depth")
    gauge.set(1.0)
    tower.observe_now(ts=1.0)
    assert fired == [] and tower.rules[0].trips == 0
    gauge.set(5.0)
    n_events = len(observe.TRACER)
    tower.observe_now(ts=2.0)
    assert fired == [("boom", 5.0)]
    trips = REGISTRY.snapshot_flat()
    assert trips['znicz_watchtower_trips_total{rule="boom"}'] >= 1.0
    names = [e["name"] for e in observe.TRACER.tail(
        len(observe.TRACER) - n_events)]
    assert "watchtower.trip" in names


def test_rule_action_exception_does_not_kill_sampler():
    reg = Registry()
    tower = Watchtower(capacity=8, registry=reg)

    def broken(rule, value):
        raise RuntimeError("boom")

    tower.add_rule(Rule("broken", "depth", lambda v: v > 0.0,
                        action=broken))
    reg.gauge("depth").set(1.0)
    tower.observe_now(ts=1.0)            # must not raise
    tower.observe_now(ts=2.0)
    assert tower.rules[0].trips == 1


def test_rule_trips_deterministically_under_seeded_fault_injection():
    """Seeded fault firings drive the resilience counter; a rule with a
    label filter on kind="fault" trips at exactly the sample where the
    third firing lands — same seed, same trip, every run."""
    base = REGISTRY.snapshot_flat().get(
        'znicz_resilience_events_total{kind="fault",site="tower.site"}',
        0.0)
    tower = Watchtower(capacity=32)
    rule = tower.add_rule(Rule(
        "fault_burst",
        'znicz_resilience_events_total{kind="fault",site="tower.site"}',
        lambda v: v >= base + 3.0))
    plan = faults.FaultPlan(seed=42)
    plan.oserror_at("tower.site", once=False)      # fire on every hit
    with faults.active(plan):
        for i in range(5):
            with pytest.raises(OSError):
                faults.fault_hook("tower.site")
            tower.observe_now(ts=float(i))
    assert rule.trips == 1
    assert rule.last_trip_ts == 2.0      # the third firing's sample


# -- windowed quantile rules -------------------------------------------------

def test_bucket_counts_from_flat_snapshot():
    reg = Registry()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    flat = reg.snapshot_flat(skip_zero=False, buckets=True)
    edges, counts = bucket_counts("lat_seconds", flat)
    assert edges == (0.1, 1.0)
    assert counts == (1.0, 2.0, 1.0)     # per-bucket, overflow last
    assert bucket_counts("missing", flat) is None
    # no buckets in the snapshot at all -> None, not a crash
    assert bucket_counts("lat_seconds", reg.snapshot_flat()) is None


def test_bucket_counts_sums_and_filters_labelsets():
    reg = Registry()
    h = reg.histogram("rt_seconds", buckets=(1.0,),
                      labelnames=("route",))
    h.labels(route="a").observe(0.5)
    h.labels(route="a").observe(2.0)
    h.labels(route="b").observe(0.5)
    flat = reg.snapshot_flat(skip_zero=False, buckets=True)
    _, summed = bucket_counts("rt_seconds", flat)
    assert summed == (2.0, 1.0)          # both labelsets
    _, only_a = bucket_counts('rt_seconds{route="a"}', flat)
    assert only_a == (1.0, 1.0)


def test_rule_quantile_validation():
    with pytest.raises(ValueError):      # quantile reduce needs quantile=
        Rule("r", "m", lambda v: True, reduce="window_quantile",
             window_s=10.0)
    with pytest.raises(ValueError):      # scalar reduce rejects one
        Rule("r", "m", lambda v: True, reduce="last", quantile=0.95)
    with pytest.raises(ValueError):      # quantile out of (0, 1)
        Rule("r", "m", lambda v: True, reduce="window_quantile",
             window_s=10.0, quantile=1.5)
    with pytest.raises(ValueError):      # windowed reduce needs window_s
        Rule("r", "m", lambda v: True, reduce="quantile_ratio",
             quantile=0.95)
    with pytest.raises(ValueError):      # window bound must hold 2+
        Rule("r", "m", lambda v: True, max_window=1)


def test_rule_window_entry_bound():
    r = Rule("r", "m", lambda v: False, reduce="mean", window_s=1e9,
             max_window=8)
    for i in range(1000):
        r.observe(float(i), float(i))
    assert len(r._window) == 8           # count-bounded, not just time
    assert r.last_value == pytest.approx(sum(range(992, 1000)) / 8)


def test_window_quantile_rule_trips_through_observe_now():
    """The sampler feeds histogram-family rules bucket-count vectors;
    the p95 of only the WINDOW's observations trips the rule as soon
    as slow observations land, however long the fast history is."""
    reg = Registry()
    tower = Watchtower(capacity=32, registry=reg)
    rule = tower.add_rule(Rule(
        "slow_p95", "lat_seconds", lambda p: p > 1.0,
        reduce="window_quantile", quantile=0.95, window_s=100.0,
        min_count=4))
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    for _ in range(4):
        h.observe(0.05)
    tower.observe_now(ts=0.0)
    assert rule.last_value is None       # one entry: no delta yet
    for _ in range(4):
        h.observe(0.05)
    tower.observe_now(ts=1.0)
    assert rule.trips == 0
    assert rule.last_value is not None and rule.last_value <= 0.1
    for _ in range(8):
        h.observe(5.0)
    tower.observe_now(ts=2.0)
    assert rule.trips == 1               # window p95 now in (1, 10]
    assert rule.last_value > 1.0


def test_quantile_ratio_detects_midrun_regression():
    """quantile_ratio judges the newer half-window's p95 against the
    older half's — the trailing-baseline regression detector the
    lifetime `_p95` estimate cannot be (cumulative buckets damp a
    mid-run regression in proportion to process age)."""
    edges = (0.1, 1.0, 10.0)

    def entry(fast, slow):               # (<=0.1, <=1, <=10, +Inf)
        return (edges, (float(fast), 0.0, float(slow), 0.0))

    r = Rule("reg", "lat_seconds", lambda x: x > 2.0,
             reduce="quantile_ratio", quantile=0.95, window_s=100.0,
             min_count=4)
    tripped = []
    for ts, (f, s) in enumerate(
            ((0, 0), (8, 0), (16, 0), (16, 8), (16, 16))):
        tripped.append(r.observe(float(ts), entry(f, s)))
    # at the trip: older half e0->e2 is 16 fast obs, newer half
    # e2->e3 is 8 slow obs — ratio blows past the factor
    assert tripped[:3] == [None, None, None]
    assert tripped[3] is not None and tripped[3] > 2.0
    assert tripped[4] is None            # stays tripped, no storm
    assert r.trips == 1
    # a re-declared histogram (different edges) is dropped, not
    # mis-subtracted: the window collapses to < 2 comparable entries
    r2 = Rule("reg2", "m", lambda x: True, reduce="window_quantile",
              quantile=0.5, window_s=100.0)
    r2.observe(0.0, (edges, (4.0, 0.0, 0.0, 0.0)))
    assert r2.observe(1.0, ((0.5,), (4.0, 4.0))) is None
    assert r2.last_value is None


def test_step_latency_regression_factory_shape():
    r = watchtower.step_latency_regression(factor=3.0)
    assert r.metric == "znicz_workflow_step_seconds"
    assert r.reduce == "quantile_ratio" and r.quantile == 0.95
    assert r.predicate(3.5) and not r.predicate(2.5)
    assert r.snapshot()["quantile"] == 0.95


# -- sampler determinism + workflow attachment -------------------------------

def test_metric_history_bit_exact_with_sampler_on_off():
    bare = build(2)
    bare.run()
    bare_hist = bare.decision.metrics_history
    bare.stop()

    tower = Watchtower(step_every=4)
    for make_rule in (watchtower.step_latency_regression,
                      watchtower.serve_queue_saturation,
                      watchtower.nan_guard_trip_rate,
                      watchtower.recompile_storm,
                      watchtower.pipeline_consumer_starvation):
        tower.add_rule(make_rule())
    sampled = build(2, tower=tower)
    sampled.run()
    sampled_hist = sampled.decision.metrics_history
    sampled.stop()

    assert len(tower.ring) > 0, "attached tower never sampled"
    assert sampled_hist == bare_hist     # sampling only READS


def test_on_step_strides_and_detach():
    tower = Watchtower(capacity=8, registry=Registry(), step_every=4)
    w = build(1, tower=tower)
    try:
        assert tower in w.watchtowers
        for _ in range(8):
            tower.on_step()
        assert len(tower.ring) == 2      # every 4th delivery
        tower.detach(w)
        assert w.watchtowers == []
    finally:
        w.stop()


def test_observe_now_noop_while_plane_disabled():
    tower = Watchtower(capacity=8, registry=Registry())
    observe.set_enabled(False)
    assert tower.observe_now() is None
    assert len(tower.ring) == 0
    observe.set_enabled(True)
    assert tower.observe_now() is not None
    assert len(tower.ring) == 1


def test_background_sampler_thread():
    tower = Watchtower(capacity=16, registry=Registry())
    tower.start(interval_s=0.005)
    try:
        with pytest.raises(RuntimeError):
            tower.start(interval_s=0.005)
        deadline = time.monotonic() + 5.0
        while len(tower.ring) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(tower.ring) >= 2
    finally:
        tower.stop()
    assert tower._thread is None
    tower.stop()                         # idempotent


# -- shared histogram quantiles (satellite) ----------------------------------

def test_quantile_from_buckets_matches_serve_percentiles():
    lat = LatencyHistogram()
    rng = np.random.default_rng(7)
    samples = rng.gamma(2.0, 0.015, size=500)      # seconds, ~30ms scale
    for s in samples:
        lat.record(float(s))
    for p in (50.0, 95.0, 99.0):
        shared = quantile_from_buckets(
            lat.edges, lat.counts, p / 100.0,
            overflow_hi=max(lat.edges[-1], lat.sum_ms / lat.total))
        assert lat.percentile(p) == pytest.approx(shared)
    # sanity vs the true sample quantile: same bucket neighbourhood
    true_p95_ms = float(np.quantile(samples, 0.95)) * 1000.0
    assert lat.percentile(95.0) == pytest.approx(true_p95_ms, rel=0.5)


def test_quantiles_in_snapshot_flat():
    reg = Registry()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.2, 0.3, 0.5, 2.0):
        h.observe(v)
    flat = reg.snapshot_flat()
    assert flat["lat_seconds_count"] == 5
    for key in ("lat_seconds_p50", "lat_seconds_p95", "lat_seconds_p99"):
        assert key in flat
    assert 0.1 <= flat["lat_seconds_p50"] <= 1.0   # 3rd of 5 samples
    child = h._solo()
    assert child.quantile(0.5) == flat["lat_seconds_p50"]
    assert Registry().histogram("empty", buckets=(1.0,))._solo() \
        .quantile(0.95) == 0.0


def test_quantile_from_buckets_edge_cases():
    assert quantile_from_buckets((1.0, 2.0), (0, 0, 0), 0.95) == 0.0
    # all mass in the overflow bucket interpolates toward overflow_hi
    v = quantile_from_buckets((1.0, 2.0), (0, 0, 4), 0.5,
                              overflow_hi=10.0)
    assert 2.0 < v <= 10.0
    # ... and clamps to the last edge without one
    assert quantile_from_buckets((1.0, 2.0), (0, 0, 4), 0.5) == 2.0


# -- cold-compile metrics (satellite) ----------------------------------------

def test_time_compiles_records_first_call_only():
    calls = []

    def fn(x):
        calls.append(x)
        return x * 2

    key = 'znicz_compile_seconds_count{fn="TowerTestFn"}'
    base = REGISTRY.snapshot_flat().get(key, 0.0)
    wrapped = probe.time_compiles("TowerTestFn", fn)
    assert probe.time_compiles("TowerTestFn", None) is None
    assert wrapped(3) == 6 and wrapped(4) == 8
    assert calls == [3, 4]
    flat = REGISTRY.snapshot_flat()
    assert flat[key] == base + 1.0       # only the cold call lands
    assert wrapped._cache_size() == 0    # no _cache_size on a plain fn
    names = [e["name"] for e in observe.TRACER.tail(16)]
    assert "compile.cold" in names


# -- JSONL sink rotation (satellite) -----------------------------------------

def test_jsonl_sink_rotates_at_byte_bound(tmp_path):
    path = str(tmp_path / "events.jsonl")
    handler = JsonlHandler(path, max_bytes=512)
    log = logging.getLogger("znicz_tpu.test_rotation")
    log.propagate = False
    log.setLevel(logging.INFO)
    log.addHandler(handler)
    try:
        for i in range(50):
            log.info("rotation probe %04d padding-padding-padding", i)
    finally:
        log.removeHandler(handler)
        handler.close()
    assert os.path.isfile(path) and os.path.isfile(path + ".1")
    assert os.path.getsize(path) <= 512
    assert os.path.getsize(path + ".1") <= 512
    with open(path) as f:
        lines = [json.loads(ln) for ln in f]
    assert lines, "live file empty after rollover"
    assert lines[-1]["msg"].startswith("rotation probe 0049")
    with open(path + ".1") as f:
        for ln in f:
            json.loads(ln)               # rollover file is intact JSONL


def test_jsonl_unbounded_by_default(tmp_path):
    path = str(tmp_path / "events.jsonl")
    handler = JsonlHandler(path)
    log = logging.getLogger("znicz_tpu.test_rotation2")
    log.propagate = False
    log.setLevel(logging.INFO)
    log.addHandler(handler)
    try:
        for i in range(50):
            log.info("unbounded %04d", i)
    finally:
        log.removeHandler(handler)
        handler.close()
    assert not os.path.exists(path + ".1")
    with open(path) as f:
        assert len(f.readlines()) == 50


# -- flight recorder ---------------------------------------------------------

#: the pinned artifact schema: a reader of flight/2 may rely on exactly
#: these keys being present ("planes" — registered live-subsystem
#: snapshot providers — is the /1 -> /2 addition, ISSUE 11)
FLIGHT_KEYS = {"schema", "reason", "ts", "iso", "host", "pid", "extra",
               "spans", "timeseries", "metrics", "planes", "config",
               "log_tail"}


def test_flight_artifact_schema_pinned(tmp_path):
    path = flight.dump(dir=str(tmp_path), reason="schema pin",
                       extra={"k": 1})
    assert os.path.basename(path).startswith("flight_")
    doc = flight.load(path)
    assert doc["schema"] == "znicz_tpu.flight/2"
    assert set(doc) == FLIGHT_KEYS
    assert doc["reason"] == "schema pin" and doc["extra"] == {"k": 1}
    assert doc["pid"] == os.getpid()
    ts = doc["timeseries"]
    assert {"capacity", "base_ts", "base", "samples", "summary",
            "rules"} <= set(ts)
    assert len(ts["samples"]) >= 1       # dump takes a fresh sample
    assert isinstance(doc["metrics"], dict) and doc["metrics"]
    assert "argv" in doc["config"]
    assert not os.path.exists(path + ".tmp")   # atomic publish


def test_flight_load_rejects_non_artifacts(tmp_path):
    bogus = tmp_path / "x.json"
    bogus.write_text('{"schema": "something/else"}')
    with pytest.raises(ValueError):
        flight.load(str(bogus))


def test_flight_span_window_limit(tmp_path):
    for i in range(40):
        observe.instant("flight.filler", i=i)
    doc = flight.build_artifact("window", last_spans=8)
    assert len(doc["spans"]) == 8
    assert doc["spans"][-1]["name"] in ("flight.filler",)


def test_auto_dump_gated_and_rate_limited(tmp_path):
    assert flight.auto_dump("unconfigured") is None
    assert not list(tmp_path.iterdir())
    flight.configure(dir=str(tmp_path), min_interval_s=3600.0)
    first = flight.auto_dump("fault", site="x")
    assert first is not None and os.path.isfile(first)
    assert flight.auto_dump("fault", site="x") is None   # rate-limited
    flight.configure()                   # opt back out
    assert flight.auto_dump("fault") is None


def test_fault_firing_auto_dumps_when_configured(tmp_path):
    flight.configure(dir=str(tmp_path), min_interval_s=0.0)
    plan = faults.FaultPlan(seed=0)
    plan.oserror_at("flight.site", at_hit=1)
    with faults.active(plan):
        with pytest.raises(OSError):
            faults.fault_hook("flight.site")
    dumps = sorted(tmp_path.glob("flight_*_fault.json"))
    assert len(dumps) == 1
    doc = flight.load(str(dumps[0]))
    assert doc["reason"] == "fault"
    assert doc["extra"]["site"] == "flight.site"


def test_flight_cli_pretty_print_and_json(tmp_path, capsys):
    path = flight.dump(dir=str(tmp_path), reason="cli check")
    assert flight.flight_main([path]) == 0
    out = capsys.readouterr().out
    assert "cli check" in out and "timeseries:" in out
    assert flight.flight_main([path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == flight.SCHEMA
    assert flight.flight_main([]) == 2
    assert flight.flight_main([str(tmp_path / "missing.json")]) == 1


# -- the acceptance chaos test -----------------------------------------------

def test_supervised_crash_leaves_valid_flight_artifact(tmp_path):
    """Seeded workflow.step crash under run_supervised: the supervisor
    dumps a flight BEFORE restore-and-resume, and the artifact carries
    the crashing span (error-marked), the fault's resilience instant,
    and >= 1 time-series sample (ISSUE 6 acceptance)."""
    tower = Watchtower(step_every=4)
    snap_dir = tmp_path / "chaos"
    plan = faults.FaultPlan(seed=1234)
    plan.crash_at("workflow.step", when=lambda workflow, unit:
                  int(workflow.decision.epoch_number) == 1)
    with faults.active(plan):
        report = run_supervised(
            lambda: build(3, snap_dir, tower=tower), str(snap_dir),
            SupervisorPolicy(sleep=lambda s: None))
    assert plan.log, "the armed crash never fired"
    assert report.restarts == 1
    assert len(report.flights) == 1
    path = report.flights[0]
    assert os.path.dirname(path) == str(snap_dir)

    doc = flight.load(path)              # schema-checked read
    assert doc["reason"] == "restart"
    assert doc["extra"]["error_type"] == "FaultInjected"
    assert len(doc["timeseries"]["samples"]) >= 1

    spans = doc["spans"]
    crashing = [e for e in spans if e["name"] == "workflow.step"
                and e.get("args", {}).get("error")]
    assert crashing, "flight lost the crashing step span"
    instants = [e for e in spans if e["name"] == "resilience.fault"]
    assert instants, "flight lost the fault's resilience instant"
    # the fault instant precedes the crashing span's END on the ring:
    # same timeline, ordered
    assert spans.index(instants[-1]) <= spans.index(crashing[-1]) + 1

    # the supervised run still finishes training after the dump
    assert len(report.workflow.decision.metrics_history) == 3
    report.workflow.stop()


def test_supervisor_flight_recorder_opt_out(tmp_path):
    plan = faults.FaultPlan(seed=7)
    plan.crash_at("workflow.step", at_hit=5)
    snap_dir = tmp_path / "noflight"
    with faults.active(plan):
        report = run_supervised(
            lambda: build(2, snap_dir), str(snap_dir),
            SupervisorPolicy(sleep=lambda s: None,
                             flight_recorder=False))
    assert report.restarts == 1 and report.flights == []
    assert not list(snap_dir.glob("flight_*.json"))
    report.workflow.stop()


# -- scrape surfaces ---------------------------------------------------------

def test_status_json_and_timeseries_endpoint():
    observe.WATCHTOWER.observe_now()
    status = WebStatus()
    doc = status.snapshot()
    assert "watchtower" in doc
    assert doc["watchtower"]["samples"] == len(observe.WATCHTOWER.ring)
    json.dumps(doc)                      # wire-serializable

    import urllib.request
    port = status.start()
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/timeseries.json", timeout=10)
        assert resp.status == 200
        ts_doc = json.load(resp)
        assert ts_doc["capacity"] == observe.WATCHTOWER.ring.capacity
        assert ts_doc["samples"], "served ring is empty"
        replay = dict(ts_doc["base"])
        for row in ts_doc["samples"]:
            replay.update(row["delta"])
        assert replay == observe.WATCHTOWER.ring.current()
    finally:
        status.stop()
