"""Generative serving plane (ISSUE 10): KV-cache incremental decode
pinned bit-equivalent to the training transformer's full forward pass,
bucketed zero-steady-state-recompile decode programs, seeded sampling,
the continuous batcher's slot lifecycle (late join, deadline, abort,
backpressure, exact terminal-event ledger), the kill-mid-decode chaos
drill, the streaming HTTP front end, the `python -m znicz_tpu generate`
CLI, and LM package export/load."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

pytest.importorskip("jax")

from znicz_tpu.serve import (ContinuousBatcher, GenerateMetrics,
                             GenerateServer, GenerationError, KVDecoder,
                             QueueFull, TokenSampler)

N_LAYERS, D, HEADS, FF, VOCAB = 2, 32, 4, 64, 31
CHARMAP = list("abcdefghijklmnopqrstuvwxyz .,!?")
assert len(CHARMAP) == VOCAB


@pytest.fixture(scope="module")
def params():
    from znicz_tpu.parallel.transformer import init_params

    return init_params(np.random.default_rng(3), N_LAYERS, D, HEADS, FF,
                       VOCAB)


@pytest.fixture(scope="module")
def decoder_cache(params):
    """One decoder per (batch, max_len) for the whole module — program
    caches are request-independent, so tests share the compile cost."""
    cache: dict = {}

    def get(batch: int = 1, max_len: int = 32) -> KVDecoder:
        key = (batch, max_len)
        if key not in cache:
            cache[key] = KVDecoder(params, heads=HEADS, max_len=max_len,
                                   batch=batch)
        return cache[key]

    return get


class _SlowDecoder:
    """Delegating proxy that stretches each decode step — deadline /
    abort / join tests need steps slow enough to act between."""

    def __init__(self, decoder: KVDecoder, delay_s: float) -> None:
        self._decoder = decoder
        self._delay_s = delay_s

    def __getattr__(self, name):
        return getattr(self._decoder, name)

    def decode(self, kv, pos, token):
        time.sleep(self._delay_s)
        return self._decoder.decode(kv, pos, token)


# -- sampling ----------------------------------------------------------------

def test_sampler_greedy_and_determinism():
    logits = np.array([0.1, 2.0, 0.3, 1.9], np.float32)
    assert TokenSampler(temperature=0.0).sample(logits) == 1
    assert TokenSampler(temperature=0.9, top_k=1).sample(logits) == 1
    a = [TokenSampler(seed=7, temperature=0.8, top_k=3).sample(logits)
         for _ in range(5)]
    b = [TokenSampler(seed=7, temperature=0.8, top_k=3).sample(logits)
         for _ in range(5)]
    assert a == b                       # fixed seed reproduces exactly
    s = TokenSampler(seed=1, temperature=1.0, top_k=2)
    draws = {s.sample(logits) for _ in range(50)}
    assert draws <= {1, 3}              # top-2 of the logits only
    with pytest.raises(ValueError):
        TokenSampler(temperature=-1.0)
    with pytest.raises(ValueError):
        TokenSampler(top_k=-2)


# -- the correctness anchor: KV decode == full forward passes ----------------

def test_greedy_kv_decode_matches_full_forward_oracle(params,
                                                      decoder_cache):
    """THE pin for the whole cache: greedy decode of N tokens through
    prefill + incremental decode must reproduce N full forward passes
    through the REAL training transformer (``make_logits_fn``, sharing
    ``_forward_hidden`` with the train/eval steps) token for token,
    with per-step logits matching to float32 rounding."""
    from znicz_tpu.parallel.mesh import make_mesh
    from znicz_tpu.parallel.transformer import make_logits_fn

    mesh = make_mesh({"data": 1, "seq": 1, "model": 1})
    oracle = make_logits_fn(mesh, N_LAYERS, D, HEADS, FF, VOCAB)
    prompt = [5, 7, 1, 30, 12]
    n_new = 12

    toks = list(prompt)
    oracle_tokens, oracle_logits = [], []
    for _ in range(n_new):
        lg = np.asarray(oracle(params, np.asarray([toks], np.int32)))
        lg = lg[0, -1]
        t = int(np.argmax(lg))
        oracle_tokens.append(t)
        oracle_logits.append(lg)
        toks.append(t)

    dec = decoder_cache(batch=1, max_len=32)
    kv, logits = dec.prefill(prompt,
                             bucket=dec.bucket_for(len(prompt) + n_new))
    kv_tokens, kv_logits = [], []
    pos = len(prompt)
    for i in range(n_new):
        t = int(np.argmax(logits))
        kv_tokens.append(t)
        kv_logits.append(np.asarray(logits))
        if i + 1 < n_new:
            kv, batch_logits = dec.decode(kv, [pos], [t])
            logits = batch_logits[0]
            pos += 1

    # bit-identical decoded sequence — the generative correctness pin
    assert kv_tokens == oracle_tokens
    for lg_o, lg_k in zip(oracle_logits, kv_logits):
        np.testing.assert_allclose(lg_k, lg_o, rtol=2e-5, atol=2e-5)

    # generate() is the same path end to end
    out = dec.generate(prompt, n_new, TokenSampler(temperature=0.0))
    assert out == oracle_tokens


def test_prefill_last_position_logits_match_oracle(params,
                                                   decoder_cache):
    from znicz_tpu.parallel.mesh import make_mesh
    from znicz_tpu.parallel.transformer import make_logits_fn

    mesh = make_mesh({"data": 1, "seq": 1, "model": 1})
    oracle = make_logits_fn(mesh, N_LAYERS, D, HEADS, FF, VOCAB)
    prompt = [2, 9, 4, 17, 8, 23, 1]
    lg_o = np.asarray(oracle(params,
                             np.asarray([prompt], np.int32)))[0, -1]
    _, lg_k = decoder_cache(batch=1, max_len=32).prefill(prompt)
    np.testing.assert_allclose(lg_k, lg_o, rtol=2e-5, atol=2e-5)


# -- bucket policy / compile accounting --------------------------------------

def test_zero_recompiles_across_mixed_lengths_within_bucket(params):
    dec = KVDecoder(params, heads=HEADS, max_len=16, batch=1)
    dec.generate([1, 2, 3], 9)          # lands in bucket 16, compiles
    base = dec.compile_count
    for prompt_len, n_new in ((2, 10), (5, 11), (7, 9), (1, 12)):
        dec.generate(list(range(1, prompt_len + 1)), n_new)
    assert dec.compile_count == base    # mixed lengths, zero recompiles


def test_warmup_compiles_every_bucket_once(params):
    dec = KVDecoder(params, heads=HEADS, max_len=8, batch=2)
    n = dec.warmup()
    # prefill + decode + adopt per bucket (1, 2, 4, 8)
    assert n == dec.compile_count == 3 * len(dec.buckets)
    assert dec.warmup() == n            # idempotent: nothing recompiles


def test_decode_past_cache_bucket_raises_not_corrupts(params,
                                                      decoder_cache):
    dec = decoder_cache(batch=1, max_len=32)
    kv, _ = dec.prefill([1, 2, 3], bucket=4)
    with pytest.raises(ValueError, match="outside cache bucket"):
        dec.decode(kv, [4], [0])        # row 4 of a 4-row cache


def test_grow_preserves_generation(params, decoder_cache):
    """A cache grown mid-request decodes the same tokens as one
    allocated at the big bucket from the start (padding is masked)."""
    dec = decoder_cache(batch=1, max_len=32)
    prompt = [3, 1, 4, 1, 5]
    straight = dec.generate(prompt, 8)  # bucket 16 from the start
    kv, logits = dec.prefill(prompt, bucket=8)
    grown_tokens, pos = [], len(prompt)
    kv = dec.grow(kv, 16)
    for i in range(8):
        t = int(np.argmax(logits))
        grown_tokens.append(t)
        if i + 1 < 8:
            kv, bl = dec.decode(kv, [pos], [t])
            logits = bl[0]
            pos += 1
    assert grown_tokens == straight


def test_decoder_refuses_moe_and_bad_input(params):
    moe = {"emb": params["emb"], "head": params["head"],
           "blocks": [{**params["blocks"][0], "ew1": np.zeros((2, D, FF))}]}
    with pytest.raises(NotImplementedError, match="MoE"):
        KVDecoder(moe, heads=HEADS, max_len=8)
    dec = KVDecoder(params, heads=HEADS, max_len=8, batch=1)
    with pytest.raises(ValueError, match="token ids"):
        dec.prefill([VOCAB + 5])
    with pytest.raises(ValueError, match="max_len"):
        dec.bucket_for(9)
    with pytest.raises(ValueError, match="empty"):
        dec.prefill([])


# -- continuous batching -----------------------------------------------------

def test_late_request_joins_running_batch_without_drain(params,
                                                        decoder_cache):
    """ISSUE acceptance: a request arriving mid-generation joins the
    running decode batch at the next step and finishes while the
    earlier long request is still decoding — pinned on the batcher's
    step counter, not wall clock."""
    dec = decoder_cache(batch=3, max_len=64)
    batcher = ContinuousBatcher(dec, default_timeout_s=60.0)
    try:
        long_req = batcher.submit([1, 2, 3], max_new_tokens=40)
        while batcher.step_count < 5:
            time.sleep(0.005)
        late = batcher.submit([4, 5], max_new_tokens=4)
        late_tokens = late.result(timeout_s=60)
        long_tokens = long_req.result(timeout_s=60)
        assert len(late_tokens) == 4 and len(long_tokens) == 40
        # the late joiner entered AFTER the long request started and
        # finished BEFORE it — continuous, not drain-per-batch
        assert late.first_token_step >= 5
        assert late.finish_step < long_req.finish_step
        # TTFT is steps-not-drain: far fewer steps than the long run
        assert late.finish_step - late.first_token_step <= 4
        snap = batcher.metrics.snapshot()
        assert snap["admitted"] == snap["completed"] == 2
        assert snap["ttft"]["count"] == 2
    finally:
        batcher.stop()


def test_steady_state_continuous_traffic_zero_recompiles(params):
    dec = KVDecoder(params, heads=HEADS, max_len=16, batch=2)
    dec.warmup()
    base = dec.compile_count
    batcher = ContinuousBatcher(dec, default_timeout_s=60.0)
    try:
        streams = [batcher.submit(list(range(1, 2 + i % 4)),
                                  max_new_tokens=3 + i % 5, seed=i,
                                  temperature=0.5, top_k=4)
                   for i in range(8)]
        for s in streams:
            assert len(s.result(timeout_s=60)) >= 3
    finally:
        batcher.stop()
    assert dec.compile_count == base    # warmed buckets, mixed lengths


def test_seeded_generation_reproduces_across_batcher_runs(params,
                                                          decoder_cache):
    dec = decoder_cache(batch=2, max_len=32)
    out = []
    for _ in range(2):
        batcher = ContinuousBatcher(dec)
        try:
            out.append(batcher.submit(
                [7, 8, 9], max_new_tokens=6, temperature=0.9, top_k=5,
                seed=42).result(timeout_s=60))
        finally:
            batcher.stop()
    assert out[0] == out[1]


def test_deadline_mid_generation_gets_error_sentinel(params,
                                                     decoder_cache):
    dec = _SlowDecoder(decoder_cache(batch=2, max_len=64), 0.01)
    batcher = ContinuousBatcher(dec, default_timeout_s=60.0)
    try:
        s = batcher.submit([1] * 4, max_new_tokens=60, timeout_s=0.08)
        with pytest.raises(GenerationError, match="deadline"):
            s.result(timeout_s=30)
        assert 0 < len(s.tokens) < 60   # partial stream, then sentinel
        snap = batcher.metrics.snapshot()
        assert snap["failed"] == 1 and snap["completed"] == 0
    finally:
        batcher.stop()


def test_cancel_frees_slot_and_counts_abandoned(params, decoder_cache):
    dec = _SlowDecoder(decoder_cache(batch=2, max_len=64), 0.01)
    batcher = ContinuousBatcher(dec, default_timeout_s=60.0)
    try:
        s = batcher.submit([2] * 4, max_new_tokens=60)
        time.sleep(0.05)
        s.cancel()
        tokens = s.result(timeout_s=30)     # "done"/aborted, not error
        assert 0 < len(tokens) < 60
        snap = batcher.metrics.snapshot()
        assert snap["abandoned"] == 1
        # slot is free again: a follow-up request completes
        assert len(batcher.submit([1, 2], max_new_tokens=3)
                   .result(timeout_s=30)) == 3
    finally:
        batcher.stop()


def test_backpressure_and_never_admissible(params, decoder_cache):
    dec = _SlowDecoder(decoder_cache(batch=1, max_len=32), 0.02)
    batcher = ContinuousBatcher(dec, max_queue=1,
                                default_timeout_s=60.0)
    try:
        running = batcher.submit([1, 2], max_new_tokens=30)
        time.sleep(0.05)                # occupies the only slot
        queued = batcher.submit([3, 4], max_new_tokens=2)
        with pytest.raises(QueueFull):
            batcher.submit([5, 6], max_new_tokens=2)
        assert batcher.metrics.snapshot()["rejected"] == 1
        # over-budget request is bad input (400), not backpressure
        with pytest.raises(ValueError, match="max_len"):
            batcher.submit([1] * 10, max_new_tokens=30)
        assert len(running.result(60)) == 30
        assert len(queued.result(60)) == 2
    finally:
        batcher.stop()


def test_stop_drain_services_everything_admitted(params, decoder_cache):
    dec = decoder_cache(batch=2, max_len=32)
    batcher = ContinuousBatcher(dec)
    streams = [batcher.submit([1 + i], max_new_tokens=8)
               for i in range(5)]
    assert batcher.stop(drain=True)
    for s in streams:
        assert len(s.result(timeout_s=1)) == 8
    with pytest.raises(QueueFull):
        batcher.submit([1], max_new_tokens=2)


def test_stop_without_drain_fails_loudly(params, decoder_cache):
    dec = _SlowDecoder(decoder_cache(batch=1, max_len=32), 0.02)
    batcher = ContinuousBatcher(dec)
    active = batcher.submit([1, 2], max_new_tokens=25)
    time.sleep(0.05)
    queued = batcher.submit([3], max_new_tokens=4)
    assert batcher.stop(drain=False)
    for s in (active, queued):
        with pytest.raises(GenerationError, match="shut down"):
            s.result(timeout_s=1)


# -- chaos: kill mid-decode (ISSUE satellite) --------------------------------

def test_chaos_kill_mid_decode_exactly_one_terminal_per_request(
        params, decoder_cache):
    """Seeded ``generate.step`` crashes mid-decode: every admitted
    request still gets EXACTLY ONE terminal event (tokens then an error
    sentinel, or a clean end) — never silence, never a duplicate — the
    worker survives, and the ledger closes with ``==``."""
    from znicz_tpu.resilience import faults

    dec = decoder_cache(batch=2, max_len=32)
    metrics = GenerateMetrics()
    batcher = ContinuousBatcher(dec, default_timeout_s=60.0,
                                metrics=metrics)
    plan = faults.FaultPlan(seed=13)
    for hit in (3, 8):                  # two seeded mid-decode kills
        plan.crash_at("generate.step", at_hit=hit)
    outcomes: dict = {}
    lock = threading.Lock()

    def client(cid):
        stream = batcher.submit([1 + cid % 5, 2], max_new_tokens=6,
                                seed=cid)
        terminal = None
        n_events = 0
        while True:
            event = stream.next_event(timeout=30)   # raises on silence
            n_events += 1
            if event.get("done") or "error" in event:
                terminal = event
                break
            assert n_events < 100       # a stream must terminate
        with lock:
            assert cid not in outcomes  # exactly one terminal observed
            outcomes[cid] = terminal

    try:
        with faults.active(plan):
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
            assert len(plan.log) == 2, plan.log     # both kills fired
            # the worker survived: fresh traffic still serves
            assert len(batcher.submit([1], max_new_tokens=3)
                       .result(timeout_s=30)) == 3
    finally:
        batcher.stop()
    assert len(outcomes) == 6
    errs = [o for o in outcomes.values() if "error" in o]
    oks = [o for o in outcomes.values() if o.get("done") and
           "error" not in o]
    assert len(errs) >= 1 and len(oks) >= 1
    snap = metrics.snapshot()
    # exact ledger — every admitted request reached one terminal state
    assert snap["admitted"] == 7
    assert snap["admitted"] == snap["completed"] + snap["failed"] + \
        snap["abandoned"]
    assert snap["failed"] == len(errs)


# -- HTTP front end ----------------------------------------------------------

@pytest.fixture()
def gen_server(params, decoder_cache):
    dec = decoder_cache(batch=2, max_len=32)
    server = GenerateServer(ContinuousBatcher(dec), charmap=CHARMAP,
                            name="tiny")
    port = server.start()
    yield server, f"http://127.0.0.1:{port}"
    server.stop()


def _post(url, doc, timeout=30):
    return urllib.request.urlopen(urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}), timeout=timeout)


def test_generate_http_streams_ndjson_with_terminal_line(gen_server):
    server, base = gen_server
    with _post(f"{base}/generate", {"prompt": "hi", "max_tokens": 6,
                                    "temperature": 0.7, "top_k": 5,
                                    "seed": 3}) as r:
        assert r.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(raw) for raw in r]
    assert len(lines) == 7
    assert all("token" in ln and "text" in ln for ln in lines[:-1])
    assert lines[-1] == {"done": True, "reason": "length",
                         "n_tokens": 6}
    # non-stream mode returns the identical seeded generation
    with _post(f"{base}/generate", {"prompt": "hi", "max_tokens": 6,
                                    "temperature": 0.7, "top_k": 5,
                                    "seed": 3, "stream": False}) as r:
        doc = json.loads(r.read())
    assert doc["tokens"] == [ln["token"] for ln in lines[:-1]]
    assert doc["text"] == "".join(ln["text"] for ln in lines[:-1])
    snap = json.loads(urllib.request.urlopen(f"{base}/metrics",
                                             timeout=10).read())
    assert snap["generate"]["completed"] == 2
    assert snap["generate"]["tokens"] == 12
    assert snap["decoder"]["vocab"] == VOCAB
    prom = urllib.request.urlopen(f"{base}/metrics.prom",
                                  timeout=10).read().decode()
    assert "znicz_generate_tokens_total" in prom
    assert "znicz_generate_ttft_seconds" in prom


def test_generate_http_rejects_bad_input(gen_server):
    _, base = gen_server
    for doc, match in (({"max_tokens": 4}, "prompt"),
                       ({"prompt": "ü"}, "vocab"),
                       ({"tokens": [999]}, "token ids"),
                       ({"prompt": "hi", "max_tokens": 0}, "max_new")):
        try:
            _post(f"{base}/generate", doc)
            raise AssertionError(f"{doc} accepted")
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
            assert match in json.loads(exc.read())["error"]
    try:
        _post(f"{base}/nope", {"prompt": "hi"})
        raise AssertionError("bad path accepted")
    except urllib.error.HTTPError as exc:
        assert exc.code == 404
    meta = json.loads(urllib.request.urlopen(base, timeout=10).read())
    assert meta["model"]["kind"] == "lm" and meta["slots"] == 2


def test_generate_http_draining_healthz_and_503(params, decoder_cache):
    dec = _SlowDecoder(decoder_cache(batch=1, max_len=32), 0.02)
    server = GenerateServer(ContinuousBatcher(dec), charmap=CHARMAP)
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    try:
        stream = server.batcher.submit([1, 2], max_new_tokens=25)
        stopper = threading.Thread(target=server.stop)
        stopper.start()
        time.sleep(0.1)                 # stop() blocked in the drain
        try:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
            raise AssertionError("healthz should be 503 during drain")
        except urllib.error.HTTPError as exc:
            assert exc.code == 503
            assert json.loads(exc.read())["status"] == "draining"
        stopper.join(timeout=60)
        assert not stopper.is_alive()
        assert len(stream.result(timeout_s=1)) == 25    # drained
    finally:
        server.stop()


# -- LM package export / load ------------------------------------------------

def test_export_lm_roundtrip_and_validation(params, tmp_path):
    from znicz_tpu.utils.export import export_lm, load_lm

    path = str(tmp_path / "lm.npz")
    export_lm(params, path, heads=HEADS, charmap=CHARMAP, name="tiny")
    p2, meta = load_lm(path)
    assert meta["format"] == "znicz_tpu.lm/1"
    assert (meta["n_layers"], meta["d"], meta["heads"], meta["ff"],
            meta["vocab"]) == (N_LAYERS, D, HEADS, FF, VOCAB)
    assert meta["charmap"] == CHARMAP
    np.testing.assert_array_equal(p2["emb"], params["emb"])
    np.testing.assert_array_equal(p2["blocks"][1]["w2"],
                                  params["blocks"][1]["w2"])
    with pytest.raises(ValueError, match="heads"):
        export_lm(params, str(tmp_path / "bad.npz"), heads=5)
    with pytest.raises(ValueError, match="charmap"):
        export_lm(params, str(tmp_path / "bad.npz"), heads=HEADS,
                  charmap=["a"])
    # a forward package is not an LM package — loud, typed refusal
    np.savez(str(tmp_path / "fwd.npz"), __arch__=np.array("{}"))
    with pytest.raises(ValueError, match="not an LM package"):
        load_lm(str(tmp_path / "fwd.npz"))


def test_transformer_lm_step_export_hook(params, tmp_path):
    """The units-layer handoff: an initialized TransformerLMStep
    packages its live params + the loader's charmap."""
    from znicz_tpu.units.lm import TransformerLMStep
    from znicz_tpu.utils.export import load_lm

    class FakeLoader:
        vocab = CHARMAP
        vocab_size = VOCAB

    step = TransformerLMStep(loader=FakeLoader(), n_layers=N_LAYERS,
                             d=D, heads=HEADS, ff=FF)
    with pytest.raises(ValueError, match="initialized"):
        step.export_lm(str(tmp_path / "lm.npz"))
    step._params = params
    path = step.export_lm(str(tmp_path / "lm.npz"))
    p2, meta = load_lm(path)
    assert meta["charmap"] == CHARMAP and meta["heads"] == HEADS
    np.testing.assert_array_equal(p2["head"], params["head"])


def test_char_lm_run_exports_lm_package_when_configured(tmp_path):
    """models/char_lm.py's post-run epilogue: with
    root.common.engine.lm_export set, the trained step's params land as
    an LM package (and without it, nothing is written)."""
    from znicz_tpu.core.config import root
    from znicz_tpu.models import char_lm

    calls = []

    class FakeStep:
        def export_lm(self, path):
            calls.append(path)
            return path

    class FakeWorkflow:
        step = FakeStep()

    def load(builder, **kw):
        assert builder is char_lm.build
        return FakeWorkflow(), False

    target = str(tmp_path / "out.npz")
    old = root.common.engine.get("lm_export", "")
    try:
        root.common.engine.lm_export = ""
        char_lm.run(load, lambda: None)
        assert calls == []
        root.common.engine.lm_export = target
        char_lm.run(load, lambda: None)
        assert calls == [target]
    finally:
        root.common.engine.lm_export = old


# -- CLI ---------------------------------------------------------------------

def test_cli_generate_oneshot(params, tmp_path, capsys):
    from znicz_tpu.__main__ import main as cli_main
    from znicz_tpu.utils.export import export_lm

    pkg = str(tmp_path / "lm.npz")
    export_lm(params, pkg, heads=HEADS, charmap=CHARMAP)
    rc = cli_main(["generate", pkg, "--prompt", "hello",
                   "--max-tokens", "8", "--max-len", "32"])
    out = capsys.readouterr()
    assert rc == 0
    # eight streamed characters plus the closing newline (the charmap
    # has no newline, so the count is exact even for spaces)
    assert len(out.out) == 9 and out.out.endswith("\n")
    stats = json.loads(out.err.strip().splitlines()[-1])
    assert stats["n_tokens"] == 8 and stats["prompt_tokens"] == 5
    # deterministic greedy: a second run prints the same text
    cli_main(["generate", pkg, "--prompt", "hello", "--max-tokens", "8",
              "--max-len", "32"])
    assert capsys.readouterr().out == out.out


def test_cli_generate_rejects_bad_package(tmp_path, capsys):
    from znicz_tpu.__main__ import main as cli_main

    assert cli_main(["generate", "/nonexistent/lm.npz",
                     "--prompt", "x"]) == 2
    assert "cannot load" in capsys.readouterr().out
    np.savez(str(tmp_path / "fwd.npz"), __arch__=np.array("{}"))
    assert cli_main(["generate", str(tmp_path / "fwd.npz"),
                     "--prompt", "x"]) == 2
    assert "not an LM package" in capsys.readouterr().out
