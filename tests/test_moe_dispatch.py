"""Token-dispatch (all_to_all) MoE tests: parallel/moe.py::
moe_ffn_dispatch — the token-sharded expert-parallel regime where each
token travels to its expert's device and back — against a dense
single-device oracle, values AND grads, plus the capacity-overflow drop
semantics.  Main-stack MoE (tokens replicated over model) is covered in
test_transformer_spmd.py."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from znicz_tpu.parallel.mesh import make_mesh
from znicz_tpu.parallel.moe import moe_ffn_dispatch
from znicz_tpu.parallel.transformer import shard_map


def _setup(rng, n_dev, e_local, d, ff, t_total):
    E = n_dev * e_local
    return (jnp.asarray(rng.normal(size=(t_total, d)).astype(np.float32)),
            jnp.asarray(rng.normal(size=(d, E)).astype(np.float32)),
            jnp.asarray(rng.normal(size=(E, d, ff)).astype(np.float32)
                        * 0.3),
            jnp.asarray(rng.normal(size=(E, ff)).astype(np.float32)),
            jnp.asarray(rng.normal(size=(E, ff, d)).astype(np.float32)
                        * 0.3),
            jnp.asarray(rng.normal(size=(E, d)).astype(np.float32)))


def _dense_oracle(x, gate, w1, b1, w2, b2):
    """Single-device top-1 MoE (jnp, differentiable): every token by its
    argmax expert, scaled by that expert's softmax prob."""
    s = x @ gate
    probs = jax.nn.softmax(s, axis=-1)
    choice = s.argmax(-1)
    gate_val = jnp.take_along_axis(probs, choice[:, None], 1)[:, 0]
    h = jax.nn.gelu(jnp.einsum("td,edf->etf", x, w1) + b1[:, None, :])
    y_e = jnp.einsum("etf,efd->etd", h, w2) + b2[:, None, :]
    sel = jax.nn.one_hot(choice, w1.shape[0], dtype=x.dtype).T
    return (y_e * sel[:, :, None]).sum(0) * gate_val[:, None]


def _sharded(mesh, capacity_factor):
    def local(x, gate, w1, b1, w2, b2):
        y, _ = moe_ffn_dispatch(x, gate, w1, b1, w2, b2, jax.nn.gelu,
                                axis_name="expert",
                                capacity_factor=capacity_factor)
        return y
    return shard_map(local, mesh=mesh,
                     in_specs=(P("expert"), P(), P("expert"),
                               P("expert"), P("expert"), P("expert")),
                     out_specs=P("expert"))


def test_dispatch_matches_dense_oracle_values_and_grads(cpu_devices):
    mesh = make_mesh({"expert": 4})
    n_dev, e_local, d, ff, t_total = 4, 2, 8, 16, 32
    rng = np.random.default_rng(3)
    x, gate, w1, b1, w2, b2 = _setup(rng, n_dev, e_local, d, ff, t_total)
    # capacity_factor = E: provably lossless (even if every local token
    # picks the same expert, the bucket holds them all)
    fn = _sharded(mesh, float(n_dev * e_local))

    y = fn(x, gate, w1, b1, w2, b2)
    y_ref = _dense_oracle(x, gate, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-5, atol=2e-5)

    wsum = jnp.asarray(rng.normal(size=y.shape).astype(np.float32))
    args = (x, gate, w1, b1, w2, b2)
    g = jax.grad(lambda *a: (fn(*a) * wsum).sum(),
                 argnums=tuple(range(6)))(*args)
    g_ref = jax.grad(lambda *a: (_dense_oracle(*a) * wsum).sum(),
                     argnums=tuple(range(6)))(*args)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_dispatch_capacity_drops_overflow_tokens(cpu_devices):
    """With capacity 1 slot per (expert, source), a second local token
    routed to the same expert contributes ZERO output (switch
    semantics), while first-arrival tokens match the oracle."""
    mesh = make_mesh({"expert": 2})
    n_dev, e_local, d, ff = 2, 1, 4, 8
    t_total = 8                                  # 4 per device
    rng = np.random.default_rng(5)
    x, gate, w1, b1, w2, b2 = _setup(rng, n_dev, e_local, d, ff, t_total)
    # capacity = ceil(0.5 * 4 / 2) = 1
    fn = _sharded(mesh, 0.5)
    y = np.asarray(fn(x, gate, w1, b1, w2, b2))
    y_ref = np.asarray(_dense_oracle(x, gate, w1, b1, w2, b2))

    choice = np.asarray(jnp.argmax(x @ gate, -1))
    seen = set()
    n_dropped = 0
    for t in range(t_total):
        dev, e = t // 4, int(choice[t])
        key = (dev, e)
        if key in seen:
            np.testing.assert_allclose(y[t], 0.0, atol=1e-6)
            n_dropped += 1
        else:
            np.testing.assert_allclose(y[t], y_ref[t], rtol=2e-5,
                                       atol=2e-5)
            seen.add(key)
    assert n_dropped > 0, "test vector never overflowed — regenerate"


def _dense_top2_oracle(x, gate, w1, b1, w2, b2):
    """Single-device GShard top-2 oracle (renormalized combine) shared
    by the dense-masked and dispatch top-2 parity tests."""
    E = w1.shape[0]
    s = x @ gate
    probs = jax.nn.softmax(s, axis=-1)
    _, idx = jax.lax.top_k(s, 2)                      # (t, 2)
    g2 = jnp.take_along_axis(probs, idx, 1)
    g2 = g2 / g2.sum(-1, keepdims=True)
    h = jax.nn.gelu(jnp.einsum("td,edf->etf", x, w1) + b1[:, None, :])
    y_e = jnp.einsum("etf,efd->etd", h, w2) + b2[:, None, :]
    out = 0.0
    for k in range(2):
        sel = jax.nn.one_hot(idx[:, k], E, dtype=x.dtype).T
        out = out + (y_e * sel[:, :, None]).sum(0) * g2[:, k:k + 1]
    return out


def test_dense_masked_top2_matches_oracle(cpu_devices):
    """moe_ffn top_k=2 (GShard renormalized combine) on the replicated-
    token regime matches a single-device oracle, values and grads, and
    is expert-shard invariant (same result with E experts on one device
    vs split over 4)."""
    d, ff, E, t_total = 8, 16, 4, 16
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(t_total, d)).astype(np.float32))
    gate = jnp.asarray(rng.normal(size=(d, E)).astype(np.float32))
    w1 = jnp.asarray(rng.normal(size=(E, d, ff)).astype(np.float32) * 0.3)
    b1 = jnp.asarray(rng.normal(size=(E, ff)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(E, ff, d)).astype(np.float32) * 0.3)
    b2 = jnp.asarray(rng.normal(size=(E, d)).astype(np.float32))
    oracle = _dense_top2_oracle

    from znicz_tpu.parallel.moe import moe_ffn

    outs = {}
    for name, n_dev in (("ep1", 1), ("ep4", 4)):
        mesh = make_mesh({"expert": n_dev})
        fn = shard_map(
            lambda x, gate, w1, b1, w2, b2: moe_ffn(
                x, gate, w1, b1, w2, b2, jax.nn.gelu,
                axis_name="expert", top_k=2)[0],
            mesh=mesh,
            in_specs=(P(), P(), P("expert"), P("expert"), P("expert"),
                      P("expert")),
            out_specs=P())
        outs[name] = fn(x, gate, w1, b1, w2, b2)
        g = jax.grad(lambda *a: (fn(*a) ** 2).sum(),
                     argnums=(0, 2))(x, gate, w1, b1, w2, b2)
        g_ref = jax.grad(lambda *a: (oracle(*a) ** 2).sum(),
                         argnums=(0, 2))(x, gate, w1, b1, w2, b2)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-4)
    y_ref = oracle(x, gate, w1, b1, w2, b2)
    for name in outs:
        np.testing.assert_allclose(np.asarray(outs[name]),
                                   np.asarray(y_ref), rtol=2e-5,
                                   atol=2e-5)


def test_dispatch_top2_matches_dense_top2_oracle(cpu_devices):
    """top_k=2 dispatch: each token occupies two bucket slots and the
    combine is GShard-renormalized — matches the dense top-2 oracle
    (values + grads) at lossless capacity."""
    mesh = make_mesh({"expert": 4})
    n_dev, e_local, d, ff, t_total = 4, 1, 8, 16, 32
    E = n_dev * e_local
    rng = np.random.default_rng(11)
    x, gate, w1, b1, w2, b2 = _setup(rng, n_dev, e_local, d, ff, t_total)

    def local(x, gate, w1, b1, w2, b2):
        y, _ = moe_ffn_dispatch(x, gate, w1, b1, w2, b2, jax.nn.gelu,
                                axis_name="expert",
                                capacity_factor=float(E), top_k=2)
        return y
    fn = shard_map(local, mesh=mesh,
                   in_specs=(P("expert"), P(), P("expert"), P("expert"),
                             P("expert"), P("expert")),
                   out_specs=P("expert"))

    oracle = _dense_top2_oracle

    y = fn(x, gate, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(oracle(x, gate, w1, b1, w2,
                                                 b2)),
                               rtol=2e-5, atol=2e-5)
    g = jax.grad(lambda *a: (fn(*a) ** 2).sum(),
                 argnums=(0, 1, 2))(x, gate, w1, b1, w2, b2)
    g_ref = jax.grad(lambda *a: (oracle(*a) ** 2).sum(),
                     argnums=(0, 1, 2))(x, gate, w1, b1, w2, b2)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_router_z_loss_value_and_presence(cpu_devices):
    """router_z_loss pins to a hand-computed mean(logsumexp^2), and a
    z-loss-ONLY training config (aux weight 0) changes the transformer
    loss — so the regularizer cannot silently become a no-op while the
    balance aux masks it."""
    from znicz_tpu.parallel.moe import router_z_loss
    from znicz_tpu.parallel import transformer as tfm
    from znicz_tpu.core import prng

    rng = np.random.default_rng(3)
    s = rng.normal(size=(5, 7)).astype(np.float32)
    want = float(np.mean(
        np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) ** 2
        + 2 * s.max(-1) * np.log(np.exp(s - s.max(-1, keepdims=True))
                                 .sum(-1))
        + s.max(-1) ** 2))
    got = float(router_z_loss(jnp.asarray(s)))
    np.testing.assert_allclose(got, want, rtol=1e-5)

    tokens = rng.integers(0, 16, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % 16).astype(np.int32)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    losses = {}
    for name, zw in (("off", 0.0), ("on", 0.01)):
        prng.seed_all(21)
        params = tfm.init_params(prng.get(), 2, 32, 4, 64, 16,
                                 n_experts=4)
        step, _ = tfm.make_train_step(mesh, 2, 32, 4, 64, 16, lr=0.2,
                                      n_experts=4, moe_zloss_weight=zw)
        _, loss = step(params, tokens, labels)
        losses[name] = float(loss)
    assert abs(losses["on"] - losses["off"]) > 1e-4, losses
