"""Unified telemetry plane (znicz_tpu/observe/): the shared metrics
registry (Counter/Gauge/Histogram, labels, Prometheus text exposition),
the bounded-ring span tracer (Chrome-trace export), the automatic
probes wired through the workflow run loop, and the scrape surfaces
(`WebStatus` `/metrics` + `/trace.json`, `snapshot()` merge).  The
plane's contract with training: instrumentation disabled reduces the
walk to the bare loop with bit-exact metric histories, and the ring
buffer stays bounded under a 10k-step soak."""

import json
import logging
import math
import re
import threading
import urllib.request

import pytest

from znicz_tpu import observe
from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.core.logger import EVENT_LOGGER, configure, event_log
from znicz_tpu.observe import probe
from znicz_tpu.observe.registry import Registry
from znicz_tpu.observe.trace import Tracer
from znicz_tpu.resilience import faults
from znicz_tpu.standard_workflow import StandardWorkflow
from znicz_tpu.web_status import WebStatus

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 24},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 6},
     "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
]
LOADER = {"n_classes": 6, "sample_shape": (10, 10), "n_train": 240,
          "n_valid": 120, "minibatch_size": 40, "spread": 2.5,
          "noise": 1.0}


def run_workflow(max_epochs=2, seed=77, name="ObserveTest"):
    prng.seed_all(seed)
    w = StandardWorkflow(
        name=name, layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={"max_epochs": max_epochs})
    w.initialize(device=TPUDevice())
    w.run()
    return w


@pytest.fixture(autouse=True)
def _observe_on():
    """Every test leaves the plane the way production boots it."""
    yield
    observe.set_enabled(True)


# -- registry primitives ----------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = Registry()
    c = reg.counter("c_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.get() == 3.5
    g = reg.gauge("g")
    g.set(4.0)
    g.dec(1.5)
    assert g.get() == 2.5
    g.set_function(lambda: 9.0)
    assert g.get() == 9.0
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    d = h._solo().hist_dict()
    assert d["count"] == 3 and d["sum"] == pytest.approx(5.55)
    assert d["buckets"] == {"0.1": 1, "1": 1, "+Inf": 1}


def test_registry_get_or_create_idempotent_and_type_safe():
    reg = Registry()
    a = reg.counter("x_total")
    assert reg.counter("x_total") is a          # same family back
    with pytest.raises(ValueError):
        reg.gauge("x_total")                    # re-typed -> error
    reg.counter("lbl_total", labelnames=("site",))
    with pytest.raises(ValueError):
        reg.counter("lbl_total", labelnames=("other",))
    reg.histogram("lat", buckets=(0.1, 1.0))
    with pytest.raises(ValueError):
        reg.histogram("lat", buckets=(5.0, 10.0))  # silent re-bucketing
    assert reg.histogram("lat", buckets=(0.1, 1.0)) is not None


def test_registry_labels():
    reg = Registry()
    fam = reg.counter("ev_total", labelnames=("kind", "site"))
    fam.labels(kind="fault", site="a").inc()
    fam.labels(kind="fault", site="a").inc()
    fam.labels(kind="retry", site="b").inc(3)
    snap = reg.snapshot()["ev_total"]
    got = {tuple(sorted(v["labels"].items())): v["value"]
           for v in snap["values"]}
    assert got[(("kind", "fault"), ("site", "a"))] == 2
    assert got[(("kind", "retry"), ("site", "b"))] == 3
    with pytest.raises(ValueError):
        fam.labels(kind="fault")                # missing label
    with pytest.raises(ValueError):
        fam.inc()                               # labeled family, no labels


def test_registry_gauge_provider_failure_is_nan_not_crash():
    reg = Registry()
    g = reg.gauge("live")

    def dead():
        raise RuntimeError("provider torn down")

    g.set_function(dead)
    assert math.isnan(g.get())
    assert "live" in reg.render_prometheus()         # scrape survives


def test_snapshot_flat_drops_zero_series():
    reg = Registry()
    reg.counter("a_total").inc(2)
    reg.counter("zero_total")
    h = reg.histogram("lat", buckets=(1.0,))
    h.observe(0.5)
    flat = reg.snapshot_flat()
    assert flat["a_total"] == 2
    assert "zero_total" not in flat
    assert flat["lat_count"] == 1 and flat["lat_sum"] == 0.5


# -- Prometheus text exposition ---------------------------------------------

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? (-?[0-9.e+-]+|nan|inf)$")
_META = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?$")


def _parse_exposition(text):
    """Minimal format-0.0.4 checker: every line is HELP/TYPE metadata or
    a sample; every sample belongs to a declared family.  Returns
    {family: type} and {sample_name: [(labels_str, value)]}."""
    types, samples = {}, {}
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("#"):
            assert _META.match(line), f"bad metadata line: {line!r}"
            if line.startswith("# TYPE"):
                _, _, name, mtype = line.split(" ", 3)
                types[name] = mtype
            continue
        m = _SAMPLE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, labels, value = m.groups()
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                base = name[:-len(suffix)]
        assert base in types, f"sample {name!r} has no TYPE declaration"
        samples.setdefault(name, []).append((labels or "", float(value)))
    return types, samples


def test_render_prometheus_parses_and_histogram_is_cumulative():
    reg = Registry()
    reg.counter("req_total", "requests", labelnames=("code",)) \
       .labels(code="200").inc(7)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.05, 0.5, 20.0):
        h.observe(v)
    types, samples = _parse_exposition(reg.render_prometheus())
    assert types == {"req_total": "counter", "lat_seconds": "histogram"}
    assert samples['req_total'] == [('{code="200"}', 7.0)]
    buckets = samples["lat_seconds_bucket"]
    counts = [v for _, v in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert buckets[-1][0] == '{le="+Inf"}'
    assert buckets[-1][1] == samples["lat_seconds_count"][0][1] == 4.0
    assert samples["lat_seconds_sum"][0][1] == pytest.approx(20.6)


def test_global_registry_stable_metric_names():
    """The catalogue names docs/OBSERVABILITY.md promises are what a
    scraper keys dashboards on — pin them."""
    import znicz_tpu.pipeline.prefetcher          # noqa: F401 — declares
    import znicz_tpu.serve.metrics                # noqa: F401 — declares
    text = observe.REGISTRY.render_prometheus()
    types, _ = _parse_exposition(text)
    for name, mtype in (
            ("znicz_workflow_step_seconds", "histogram"),
            ("znicz_workflow_signals_total", "counter"),
            ("znicz_unit_runs_total", "counter"),
            ("znicz_unit_run_seconds_total", "counter"),
            ("znicz_recompiles_total", "counter"),
            ("znicz_resilience_events_total", "counter"),
            ("znicz_pipeline_bytes_staged_total", "counter"),
            ("znicz_pipeline_queue_fill", "gauge"),
            ("znicz_serve_requests_total", "counter"),
            ("znicz_serve_latency_seconds", "histogram"),
            ("znicz_serve_qps", "gauge")):
        assert types.get(name) == mtype, (name, types.get(name))


# -- tracer ------------------------------------------------------------------

def test_tracer_ring_bounded_under_10k_step_soak():
    tr = Tracer(capacity=512)
    for step in range(10_000):
        with tr.span("workflow.step", step=step):
            pass
    assert len(tr) == 512                       # memory flat, newest kept
    doc = tr.export_dict()
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 512
    assert spans[-1]["args"]["step"] == 9_999   # newest window survives


def test_tracer_export_chrome_trace_shape(tmp_path):
    tr = Tracer()
    with tr.span("workflow.step", step=1):
        tr.instant("resilience.fault", site="workflow.step")
    out = tmp_path / "trace.json"
    n = tr.export(str(out))
    assert n == 2
    doc = json.loads(out.read_text())
    assert doc["displayTimeUnit"] == "ms"
    evs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] != "M"}
    span = evs["workflow.step"]
    inst = evs["resilience.fault"]
    assert span["ph"] == "X" and span["dur"] >= 0 and \
        span["cat"] == "workflow"
    assert inst["ph"] == "i" and inst["s"] == "t" and \
        inst["args"]["site"] == "workflow.step"
    # the instant fired INSIDE the span: same timeline, nested stamps
    assert span["ts"] <= inst["ts"] <= span["ts"] + span["dur"]
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert names == ["znicz_tpu"]


def test_tracer_disabled_is_noop():
    tr = Tracer(enabled=False)
    s1 = tr.span("a")
    s2 = tr.span("b", k=1)
    assert s1 is s2                             # shared no-op singleton
    with s1:
        pass
    tr.instant("x")
    tr.complete("y", 0.0, 1.0)
    assert len(tr) == 0


# -- probes -------------------------------------------------------------------

class _FakeJitted:
    def __init__(self):
        self.size = 0

    def _cache_size(self):
        return self.size


def test_watch_compiles_counts_cache_growth():
    before = observe.TRACER.enabled
    fn = _FakeJitted()
    probe.watch_compiles("test_fake_step", fn, object())  # non-jit dropped
    try:
        assert probe.check_recompiles() == 0    # baseline swallowed
        fn.size = 1                             # first compile
        assert probe.check_recompiles() == 1
        assert probe.check_recompiles() == 0    # steady state
        fn.size = 3                             # surprise recompiles
        assert probe.check_recompiles() == 2
        fam = observe.REGISTRY.get("znicz_recompiles_total")
        assert fam.labels(fn="test_fake_step").get() == 3
    finally:
        probe.unwatch_compiles("test_fake_step")
        observe.TRACER.enabled = before


def test_watch_compiles_per_instance_keys_share_a_label():
    """Two live steps of one class watch independently (separate keys,
    one metric label); a dead step's entry is reaped via its weakrefs
    instead of masking the survivor."""
    before = observe.TRACER.enabled
    a, b = _FakeJitted(), _FakeJitted()
    probe.watch_compiles("fake-a", a, label="test_fake_shared")
    probe.watch_compiles("fake-b", b, label="test_fake_shared")
    fam = observe.REGISTRY.get("znicz_recompiles_total")
    base = fam.labels(fn="test_fake_shared").get()
    try:
        a.size = 1
        b.size = 2
        assert probe.check_recompiles() == 3    # both still polled
        assert fam.labels(fn="test_fake_shared").get() == base + 3
        del b                                   # one step dies
        a.size = 2
        assert probe.check_recompiles() == 1    # survivor still watched
        assert "fake-b" not in probe._watched   # dead entry reaped
    finally:
        probe.unwatch_compiles("fake-a")
        probe.unwatch_compiles("fake-b")
        observe.TRACER.enabled = before


def test_resilience_events_share_counter_and_timeline():
    fam = observe.REGISTRY.get("znicz_resilience_events_total")
    child = fam.labels(kind="fault", site="observe.test")
    base_counter = child.get()
    base_ring = len(observe.TRACER)
    plan = faults.FaultPlan(seed=0).crash_at("observe.test", at_hit=1)
    try:
        with faults.active(plan):
            with pytest.raises(faults.FaultInjected):
                faults.fault_hook("observe.test")
    finally:
        faults.uninstall()
    assert child.get() == base_counter + 1
    newest = list(observe.TRACER._events)[-1]
    assert newest[0] == "i" and newest[1] == "resilience.fault"
    assert len(observe.TRACER) == base_ring + 1


def test_disabled_plane_stops_probes_but_scrape_still_parses():
    events = observe.REGISTRY.get("znicz_resilience_events_total")
    staged = observe.REGISTRY.get("znicz_pipeline_bytes_staged_total")
    child = events.labels(kind="fault", site="observe.disabled")
    observe.set_enabled(False)
    assert not probe.enabled() and not observe.TRACER.enabled
    ev_before, st_before = child.get(), staged.get()
    ring_before = len(observe.TRACER)
    probe.resilience_event("fault", site="observe.disabled")
    probe.staged_bytes(100)
    assert probe.check_recompiles() == 0
    assert child.get() == ev_before and staged.get() == st_before
    assert len(observe.TRACER) == ring_before
    # families stay registered while values hold still: a scrape during
    # a disabled window still parses
    _parse_exposition(observe.REGISTRY.render_prometheus())


# -- workflow integration -----------------------------------------------------

def test_workflow_run_populates_registry_and_trace():
    w = run_workflow(max_epochs=2, name="ObserveRunA")
    try:
        types, samples = _parse_exposition(
            observe.REGISTRY.render_prometheus())
        # step-latency histogram moved, one observation per dispatch
        count = samples["znicz_workflow_step_seconds_count"][0][1]
        assert count >= w.signals_dispatched > 0
        # per-unit counters mirror the units' own timers
        fam = observe.REGISTRY.get("znicz_unit_runs_total")
        for u in w.units:
            if u._run_count:
                assert fam.labels(workflow="ObserveRunA",
                                  unit=u.name).get() == u._run_count
        # the jitted step registered with the recompile watcher and its
        # first compile was observed
        rec = observe.REGISTRY.get("znicz_recompiles_total")
        assert rec.labels(fn="FusedTrainStep").get() >= 1
        # step spans landed on the timeline
        names = {e[1] for e in observe.TRACER._events}
        assert "workflow.step" in names and "workflow.run" in names
    finally:
        w.stop()


def test_timing_table_reads_from_registry():
    w = run_workflow(max_epochs=2, name="ObserveTimingB")
    try:
        table = w.timing_table()
        fam = observe.REGISTRY.get("znicz_unit_runs_total")
        for u in w.units:
            if u._run_count:
                assert u.name in table
                assert fam.labels(workflow="ObserveTimingB",
                                  unit=u.name).get() == u._run_count
    finally:
        w.stop()


def test_timing_table_falls_back_to_unit_timers_when_disabled():
    """observe.set_enabled(False) must not blank the table — the units'
    local timers (pre-telemetry behavior) are the fallback source."""
    observe.set_enabled(False)
    try:
        w = run_workflow(max_epochs=2, name="ObserveDisabledTable")
        table = w.timing_table()
        w.stop()
    finally:
        observe.set_enabled(True)
    for u in w.units:
        if u._run_count:
            assert u.name in table, table


def test_add_unit_invalidates_cached_observer_labels():
    """A unit that ran standalone (workflow="") and is then adopted must
    donate to the adopting workflow's series, not the stale label."""
    from znicz_tpu.core.units import Unit
    from znicz_tpu.core.workflow import Workflow

    class Tick(Unit):
        def run(self):
            pass

    prng.seed_all(1)
    t = Tick(name="AdoptedTick")
    t._timed_run()                       # caches workflow="" children
    w = Workflow(name="ObserveAdopter")
    w.add_unit(t)
    assert t._observers is None          # cache dropped on adoption
    t._timed_run()
    fam = observe.REGISTRY.get("znicz_unit_runs_total")
    assert fam.labels(workflow="ObserveAdopter",
                      unit="AdoptedTick").get() == 1
    assert fam.labels(workflow="", unit="AdoptedTick").get() == 1


def test_serve_metrics_mirrors_honor_master_switch():
    from znicz_tpu.serve.metrics import ServingMetrics

    reqs = observe.REGISTRY.get("znicz_serve_requests_total")
    lat = observe.REGISTRY.get("znicz_serve_latency_seconds")
    done = reqs.labels(event="completed")
    base_done, base_lat = done.get(), lat._solo().hist_dict()["count"]
    m = ServingMetrics()
    observe.set_enabled(False)
    try:
        m.on_admit()
        m.on_batch(4)
        m.on_complete(0.01)
    finally:
        observe.set_enabled(True)
    assert m.admitted == 1 and m.completed == 1   # instance truth moves
    assert done.get() == base_done                # shared plane holds
    assert lat._solo().hist_dict()["count"] == base_lat
    m.on_complete(0.01)                           # re-enabled -> moves
    assert done.get() == base_done + 1


def test_metric_history_bit_exact_with_plane_disabled():
    """ISSUE 5 acceptance: spans/probes off => the bare pre-telemetry
    walk, bit-exact metric histories (same discipline as the pipeline
    prefetch bit-exactness harness)."""
    w_on = run_workflow(max_epochs=3, seed=91, name="ObserveOn")
    hist_on = w_on.decision.metrics_history
    w_on.stop()
    observe.set_enabled(False)
    try:
        w_off = run_workflow(max_epochs=3, seed=91, name="ObserveOff")
        hist_off = w_off.decision.metrics_history
        w_off.stop()
    finally:
        observe.set_enabled(True)
    assert hist_on == hist_off
    # toggling mid-run sequence changes nothing either
    w_again = run_workflow(max_epochs=3, seed=91, name="ObserveOn2")
    assert w_again.decision.metrics_history == hist_on
    w_again.stop()


# -- WebStatus merge + endpoints ---------------------------------------------

def test_web_status_snapshot_merges_all_blocks_without_collisions():
    w = run_workflow(max_epochs=1, name="ObserveMergeC")
    status = (WebStatus()
              .register(w)
              .register_serving("front", lambda: {"qps": 1.5})
              .register_health("trainer", lambda: {"nan_trips": 0})
              .register_pipeline("train_input", lambda: {"depth": 2}))
    try:
        doc = status.snapshot()
    finally:
        w.stop()
    assert set(doc) == {"workflows", "serving", "health", "pipeline",
                        "metrics", "watchtower"}   # disjoint, no collisions
    assert doc["workflows"][0]["name"] == "ObserveMergeC"
    assert doc["serving"] == {"front": {"qps": 1.5}}
    assert doc["health"] == {"trainer": {"nan_trips": 0}}
    assert doc["pipeline"] == {"train_input": {"depth": 2}}
    assert doc["metrics"]["znicz_workflow_signals_total"]["type"] == \
        "counter"
    json.dumps(doc)                               # wire-serializable


def test_web_status_dead_provider_isolated():
    def dead():
        raise RuntimeError("boom")

    doc = WebStatus().register_serving("dead", dead).snapshot()
    assert "error" in doc["serving"]["dead"]
    assert "metrics" in doc                       # the plane still rides


def test_metrics_and_trace_endpoints():
    w = run_workflow(max_epochs=1, name="ObserveHttpD")
    status = WebStatus().register(w)
    port = status.start()
    base = f"http://127.0.0.1:{port}"
    try:
        resp = urllib.request.urlopen(base + "/metrics")
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        types, samples = _parse_exposition(resp.read().decode())
        assert types["znicz_workflow_step_seconds"] == "histogram"
        assert samples["znicz_workflow_signals_total"][0][1] > 0

        resp = urllib.request.urlopen(base + "/trace.json")
        assert resp.status == 200
        doc = json.load(resp)
        assert any(e["name"] == "workflow.step"
                   for e in doc["traceEvents"])

        doc = json.load(urllib.request.urlopen(base + "/status.json"))
        assert "metrics" in doc and doc["workflows"]
    finally:
        status.stop()
        w.stop()


# -- structured JSONL log stream ---------------------------------------------

def test_jsonl_log_handler_interleaves_events_and_log_lines(tmp_path):
    path = tmp_path / "run.jsonl"
    configure(jsonl_path=str(path))
    try:
        logging.getLogger("znicz_tpu.test").warning("plain %s", "line")
        event_log("compile.recompile", {"fn": "step", "new": 1})
        observe.instant("resilience.restart", attempt=2)
    finally:
        root_logger = logging.getLogger()
        for h in list(root_logger.handlers):
            if getattr(h, "baseFilename", None) == str(path):
                root_logger.removeHandler(h)
                h.close()
    docs = [json.loads(line) for line in
            path.read_text().strip().splitlines()]
    assert len(docs) == 3
    assert docs[0]["msg"] == "plain line" and docs[0]["level"] == "WARNING"
    assert docs[0]["logger"] == "znicz_tpu.test"
    assert docs[1]["event"] == "compile.recompile"
    assert docs[1]["args"] == {"fn": "step", "new": 1}
    assert docs[1]["logger"] == EVENT_LOGGER
    # tracer instants ride the same stream (trace -> event_log)
    assert docs[2]["event"] == "resilience.restart"
    assert docs[2]["args"] == {"attempt": 2}


# -- CLI ----------------------------------------------------------------------

def test_cli_trace_subcommand_usage():
    from znicz_tpu.__main__ import main
    assert main(["trace"]) == 2
    assert main(["trace", "out.json"]) == 2
