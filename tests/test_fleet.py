"""Fleet telemetry tests (ISSUE 11): the federation parser/exporter/
aggregator, fleet-level SLO rules over the rank-merged view, the seeded
single-rank fault drill with fleet flight embedding, per-request
distributed tracing through the serving plane, trace merging, and the
concurrent-scrape soak.

jax is only touched by the tests that run a real KVDecoder (the drill,
span linking, and the scrape soak); everything else is stdlib-fast.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu import observe
from znicz_tpu.observe import federation as fed
from znicz_tpu.observe import flight
from znicz_tpu.observe.registry import Registry
from znicz_tpu.resilience import faults

N_LAYERS, D, HEADS, FF, VOCAB = 2, 32, 4, 64, 31
CHARMAP = list("abcdefghijklmnopqrstuvwxyz .,!?")


@pytest.fixture(autouse=True)
def _clean_globals():
    """No leaked fault plans, flight config, or disabled plane."""
    yield
    faults.uninstall()
    flight.configure()
    observe.set_enabled(True)


@pytest.fixture(scope="module")
def decoder():
    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.serve.kvcache import KVDecoder

    params = init_params(np.random.default_rng(3), N_LAYERS, D, HEADS,
                         FF, VOCAB)
    return KVDecoder(params, heads=HEADS, max_len=32, batch=2)


def _two_serve_registries():
    """Two private per-'worker' registries with the serve families the
    fleet rules watch."""
    regs = []
    for _ in range(2):
        r = Registry()
        r.gauge("znicz_serve_queue_depth", "q")
        r.histogram("znicz_serve_latency_seconds", "lat",
                    buckets=(0.01, 0.1, 1.0))
        r.counter("znicz_recompiles_total", "rc", labelnames=("fn",))
        regs.append(r)
    return regs


# -- prometheus text ingestion ------------------------------------------------

def test_parse_prometheus_round_trip():
    r = Registry()
    r.counter("znicz_a_total", "with labels",
              labelnames=("event",)).labels(event="ok").inc(3)
    r.gauge("znicz_g", "a gauge").set(7.5)
    h = r.histogram("znicz_h_seconds", "a histogram", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    families, samples = fed.parse_prometheus(r.render_prometheus())
    assert families["znicz_a_total"]["type"] == "counter"
    assert families["znicz_h_seconds"]["type"] == "histogram"
    assert families["znicz_g"]["help"] == "a gauge"
    flat = {f"{name}{{{inner}}}" if inner else name: v
            for _, name, inner, v in samples}
    assert flat['znicz_a_total{event="ok"}'] == 3.0
    assert flat["znicz_g"] == 7.5
    # cumulative buckets, the exposition convention
    assert flat['znicz_h_seconds_bucket{le="0.1"}'] == 1.0
    assert flat['znicz_h_seconds_bucket{le="+Inf"}'] == 2.0
    assert flat["znicz_h_seconds_count"] == 2.0
    # histogram children attach to the declared family
    assert all(fam == "znicz_h_seconds" for fam, name, _, _ in samples
               if name.startswith("znicz_h_seconds"))


def test_parse_prometheus_rejects_torn_text():
    # a scrape torn mid-line must fail loudly, never half-merge
    with pytest.raises(ValueError):
        fed.parse_prometheus("znicz_ok_total 1\nznicz_torn_total 12.3.4")
    with pytest.raises(ValueError):
        fed.parse_prometheus('znicz_unclosed{a="b" 3')


def test_parse_prometheus_foreign_exposition_shapes():
    # trailing timestamps are valid 0.0.4 (foreign exporters emit
    # them): the VALUE is the first field after the labels, never the
    # stamp — and label values may carry spaces and raw braces
    _, samples = fed.parse_prometheus(
        'znicz_x_total{a="b c",q="x}y"} 5 1700000000\n'
        "znicz_plain 2 1700000000\n")
    assert samples[0] == ("znicz_x_total", "znicz_x_total",
                          'a="b c",q="x}y"', 5.0)
    assert samples[1] == ("znicz_plain", "znicz_plain", "", 2.0)


def test_inject_rank():
    assert fed.inject_rank("", 0) == 'rank="0"'
    assert fed.inject_rank('le="0.5"', 2) == 'le="0.5",rank="2"'
    # an aggregator-of-aggregators must not double-tag
    assert fed.inject_rank('rank="1"', 2) == 'rank="1"'


# -- worker-side exporter -----------------------------------------------------

def test_metrics_exporter_envelope_and_final_write(tmp_path,
                                                   monkeypatch):
    monkeypatch.setenv("ZNICZ_TPU_ELASTIC_RANK", "3")
    observe.counter("znicz_fleet_test_export_total", "t").inc(2)
    path = str(tmp_path / "m.json")
    exporter = fed.MetricsExporter(path, interval_s=30.0)
    exporter.start()
    deadline = time.monotonic() + 10.0
    while not os.path.exists(path) and time.monotonic() < deadline:
        time.sleep(0.01)
    exporter.stop()                     # also publishes a final write
    with open(path) as f:
        doc = json.load(f)
    assert doc["schema"] == fed.EXPORT_SCHEMA
    assert doc["rank"] == 3 and doc["pid"] == os.getpid()
    assert doc["ts"] <= time.time()
    _, samples = fed.parse_prometheus(doc["prom"])
    assert any(name == "znicz_fleet_test_export_total" and v == 2.0
               for _, name, _, v in samples)


# -- aggregator merge ---------------------------------------------------------

def test_aggregator_merges_with_rank_labels():
    r0, r1 = _two_serve_registries()
    r0.get("znicz_serve_queue_depth").set(4)
    r1.get("znicz_serve_queue_depth").set(9)
    r1.get("znicz_recompiles_total").labels(fn="step").inc(2)
    agg = fed.FleetAggregator(min_refresh_s=0.0)
    agg.add_source(0, r0.render_prometheus)
    agg.add_source(1, r1.render_prometheus)
    try:
        flat = agg.snapshot_flat(skip_zero=False, buckets=True)
        assert flat['znicz_serve_queue_depth{rank="0"}'] == 4.0
        assert flat['znicz_serve_queue_depth{rank="1"}'] == 9.0
        assert flat['znicz_recompiles_total{fn="step",rank="1"}'] == 2.0
        assert flat['znicz_fleet_worker_up{rank="0"}'] == 1.0
        # the merged exposition re-parses and declares each family once
        prom = agg.render_prometheus()
        families, samples = fed.parse_prometheus(prom)
        assert prom.count("# TYPE znicz_serve_queue_depth gauge") == 1
        assert families["znicz_fleet_worker_up"]["type"] == "gauge"
        ranks = {inner for _, name, inner, _ in samples
                 if name == "znicz_serve_queue_depth"}
        assert ranks == {'rank="0"', 'rank="1"'}
        # JSON views carry per-rank health without the bulky flat dump
        doc = agg.metrics_doc()
        assert doc["workers"]["0"]["ok"] and "flat" not in \
            doc["workers"]["0"]
        assert doc["flat"]['znicz_serve_queue_depth{rank="1"}'] == 9.0
        status = agg.status_doc()
        assert set(status["workers"]) == {"0", "1"}
        assert "rules" in status["watchtower"]
    finally:
        agg.close()


def test_aggregator_staleness_drops_gauges_keeps_counters(tmp_path):
    r0, _ = _two_serve_registries()
    r0.get("znicz_serve_queue_depth").set(64)
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps(
        {"schema": fed.EXPORT_SCHEMA, "rank": 1,
         "ts": time.time() - 3600.0,     # an hour-dead worker
         "prom": "# TYPE znicz_serve_queue_depth gauge\n"
                 "znicz_serve_queue_depth 99\n"
                 "# TYPE znicz_recompiles_total counter\n"
                 "znicz_recompiles_total 40\n"}))
    agg = fed.FleetAggregator(min_refresh_s=0.0, stale_s=5.0)
    agg.add_source(0, r0.render_prometheus)
    agg.add_file_source(1, str(stale))
    agg.add_file_source(2, str(tmp_path / "never_written.json"))
    try:
        flat = agg.snapshot_flat(skip_zero=False)
        # the dead rank's GAUGE must not read saturated forever...
        assert 'znicz_serve_queue_depth{rank="1"}' not in flat
        # ...but its COUNTER carries forward: vanishing it to 0 and
        # snapping back on recovery would read as lifetime-sized
        # in-window growth and falsely trip every delta rule
        assert flat['znicz_recompiles_total{rank="1"}'] == 40.0
        assert flat['znicz_fleet_worker_up{rank="1"}'] == 0.0
        assert flat['znicz_fleet_worker_up{rank="2"}'] == 0.0
        assert flat['znicz_serve_queue_depth{rank="0"}'] == 64.0
        workers = agg.status_doc()["workers"]
        assert workers["1"]["ok"]                 # parsed, just stale
        assert not workers["2"]["ok"] and workers["2"]["error"]
    finally:
        agg.close()


def test_transient_scrape_failure_keeps_serving_cached_data():
    """One failed scrape must not vanish a live worker's series (the
    snap-back would falsely trip delta rules); the cached data serves
    until it ages past stale_s."""
    r0, _ = _two_serve_registries()
    r0.get("znicz_serve_queue_depth").set(7)
    r0.get("znicz_recompiles_total").labels(fn="step").inc(3)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 2:
            raise OSError("transient connect failure")
        return r0.render_prometheus()

    agg = fed.FleetAggregator(min_refresh_s=0.0, stale_s=60.0)
    agg.add_source(0, flaky)
    try:
        assert agg.snapshot_flat(
            skip_zero=False)['znicz_serve_queue_depth{rank="0"}'] == 7.0
        flat = agg.snapshot_flat(skip_zero=False)     # the failing pass
        assert calls["n"] == 2
        assert flat['znicz_serve_queue_depth{rank="0"}'] == 7.0
        assert flat['znicz_recompiles_total{fn="step",rank="0"}'] == 3.0
        assert flat['znicz_fleet_worker_up{rank="0"}'] == 1.0
        workers = agg.status_doc()["workers"]         # 3rd: recovers
        assert workers["0"]["ok"] and calls["n"] == 3
    finally:
        agg.close()


# -- fleet SLO rules over the merged view -------------------------------------

def test_fleet_rules_total_and_per_rank():
    r0, r1 = _two_serve_registries()
    agg = fed.FleetAggregator(min_refresh_s=0.0)
    agg.add_source(0, r0.render_prometheus)
    agg.add_source(1, r1.render_prometheus)
    trips = []
    total = agg.add_rule(fed.fleet_queue_saturation(
        depth=50, for_s=0.0, action=lambda r, v: trips.append(v)))
    per_rank = agg.add_rule_per_rank(
        lambda r: fed.any_rank_recompile_storm(r, max_in_window=3,
                                               window_s=60.0))
    try:
        ts = 1000.0
        r0.get("znicz_serve_queue_depth").set(10)
        r1.get("znicz_serve_queue_depth").set(10)
        # touch the recompile child so the baseline sample records its
        # 0 — a delta rule needs the before, not just the after
        r1.get("znicz_recompiles_total").labels(fn="step")
        agg.tower.observe_now(ts=ts)
        assert total.trips == 0
        # rank 1 saturates: the FLEET total (10 + 60) crosses, and only
        # rank 1's recompile rule sees its storm
        r1.get("znicz_serve_queue_depth").set(60)
        r1.get("znicz_recompiles_total").labels(fn="step").inc(5)
        agg.tower.observe_now(ts=ts + 5)
        assert total.trips == 1 and trips == [70.0]
        assert [r.trips for r in per_rank] == [0, 1]
    finally:
        agg.close()


def test_fleet_p95_latency_across_ranks():
    r0, r1 = _two_serve_registries()
    agg = fed.FleetAggregator(min_refresh_s=0.0)
    agg.add_source(0, r0.render_prometheus)
    agg.add_source(1, r1.render_prometheus)
    rule = agg.add_rule(fed.fleet_latency_slo(p95_s=0.5, window_s=60.0,
                                              min_count=4))
    try:
        ts = 2000.0
        agg.tower.observe_now(ts=ts)
        # rank 0 fast, rank 1 slow and busier: the fleet p95 over the
        # rank-MERGED bucket deltas lands in rank 1's bucket
        for _ in range(4):
            r0.get("znicz_serve_latency_seconds").observe(0.005)
        for _ in range(16):
            r1.get("znicz_serve_latency_seconds").observe(0.9)
        agg.tower.observe_now(ts=ts + 5)
        assert rule.trips == 1
        assert rule.last_value == pytest.approx(0.91, abs=0.2)
    finally:
        agg.close()


def test_seeded_single_rank_fault_trips_fleet_rule_and_flight(
        decoder, tmp_path):
    """The acceptance drill: a seeded fault on ONE rank's decode loop
    trips a rank-filtered fleet rule, and the trip's flight artifact
    embeds BOTH workers' last snapshots plus the live admission
    ledger."""
    from znicz_tpu.serve.continuous import ContinuousBatcher

    flight.configure(dir=str(tmp_path), min_interval_s=0.0)
    # rank 0 = a REAL worker in this process (global registry); rank 1 =
    # a quiet synthetic peer
    _, r1 = _two_serve_registries()
    agg = fed.FleetAggregator(min_refresh_s=0.0)
    agg.add_source(0, observe.REGISTRY.render_prometheus)
    agg.add_source(1, r1.render_prometheus)
    rule = agg.add_rule(observe.Rule(
        "fleet_rank0_failures",
        'znicz_generate_requests_total{event="failed",rank="0"}',
        lambda d: d > 0, window_s=60.0, reduce="delta",
        description="rank 0 failed a generation"))
    batcher = ContinuousBatcher(decoder, default_timeout_s=30.0)
    try:
        # touch the failed-event child so the pre-fault baseline sample
        # records its current value (delta rules need the before)
        observe.counter("znicz_generate_requests_total",
                        labelnames=("event",)).labels(event="failed")
        agg.tower.observe_now(ts=3000.0)        # pre-fault baseline
        faults.install(faults.FaultPlan(seed=11).crash_at(
            "generate.step", at_hit=1))
        stream = batcher.submit([1, 2, 3], max_new_tokens=4)
        with pytest.raises(Exception):
            stream.result(timeout_s=30.0)       # the error sentinel
        agg.tower.observe_now(ts=3005.0)
        assert rule.trips == 1 and rule.last_value >= 1.0
        artifacts = sorted(tmp_path.glob("flight_*.json"))
        assert artifacts, "rule trip did not auto-dump a fleet flight"
        doc = flight.load(str(artifacts[-1]))
        assert set(doc["planes"]["fleet"]) == {"0", "1"}
        rank0 = doc["planes"]["fleet"]["0"]
        assert any(k.startswith("znicz_generate_requests_total")
                   for k in rank0["flat"])
        ledger = doc["planes"]["generate_ledger"]
        assert ledger["admitted"] == \
            ledger["completed"] + ledger["failed"] + ledger["abandoned"]
        assert ledger["failed"] >= 1
    finally:
        batcher.stop(drain=False)
        agg.close()


# -- per-request distributed tracing ------------------------------------------

def test_request_phase_spans_share_rid_and_track(decoder):
    from znicz_tpu.serve.continuous import ContinuousBatcher

    batcher = ContinuousBatcher(decoder, default_timeout_s=30.0)
    try:
        stream = batcher.submit([4, 5], max_new_tokens=3)
        stream.result(timeout_s=30.0)
    finally:
        batcher.stop()
    rid = stream.request_id
    assert rid                           # minted at admission
    spans = [e for e in observe.TRACER.export_dict()["traceEvents"]
             if (e.get("args") or {}).get("rid") == rid]
    names = {e["name"] for e in spans}
    assert {"generate.queue", "generate.prefill",
            "generate.decode"} <= names
    assert len({e["tid"] for e in spans}) == 1   # one request track
    assert {e["tid"] for e in spans} == {fed.request_track(rid)}
    decode = next(e for e in spans if e["name"] == "generate.decode")
    assert decode["args"]["n_tokens"] == 3
    # phases are ordered on the shared clock: queue ends before decode
    queue = next(e for e in spans if e["name"] == "generate.queue")
    assert queue["ts"] <= decode["ts"]
    # batched per-step spans carry the step counter
    steps = [e for e in observe.TRACER.export_dict()["traceEvents"]
             if e["name"] == "generate.decode_step"]
    assert steps and all("step" in e["args"] for e in steps)


def test_micro_batcher_request_spans():
    from znicz_tpu.serve.batcher import MicroBatcher

    class _Engine:
        max_batch = 8
        input_shape = None

        def run(self, x):
            return np.asarray(x) * 2.0

    b = MicroBatcher(_Engine(), max_wait_ms=1.0)
    try:
        out = b.submit([[1.0, 2.0]], request_id="test-rid-1").result(
            timeout=10)
        assert out.tolist() == [[2.0, 4.0]]
    finally:
        b.stop()
    spans = [e for e in observe.TRACER.export_dict()["traceEvents"]
             if (e.get("args") or {}).get("rid") == "test-rid-1"]
    assert [e["name"] for e in spans] == ["serve.request"]
    assert spans[0]["tid"] == fed.request_track("test-rid-1")
    infer = [e for e in observe.TRACER.export_dict()["traceEvents"]
             if e["name"] == "serve.infer"]
    assert infer and infer[-1]["args"]["rows"] >= 1


def test_generate_server_request_id_and_stream_span(decoder):
    from znicz_tpu.serve.continuous import ContinuousBatcher
    from znicz_tpu.serve.server import GenerateServer

    batcher = ContinuousBatcher(decoder, default_timeout_s=30.0)
    server = GenerateServer(batcher, charmap=CHARMAP, port=0)
    port = server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "ab", "max_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            rid = r.headers["X-Request-Id"]
            lines = [json.loads(raw) for raw in r]
        assert rid and lines[-1]["done"]
        spans = [e for e in observe.TRACER.export_dict()["traceEvents"]
                 if (e.get("args") or {}).get("rid") == rid]
        names = {e["name"] for e in spans}
        assert {"generate.queue", "generate.prefill", "generate.decode",
                "generate.stream"} <= names
        assert len({e["tid"] for e in spans}) == 1
        # non-stream replies carry the id in the body too
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "c", "max_tokens": 2,
                             "stream": False}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            doc = json.load(r)
        assert doc["request_id"] == r.headers["X-Request-Id"]
    finally:
        server.stop()


# -- trace merging ------------------------------------------------------------

def _worker_trace(rank, origin, names):
    t = observe.Tracer(capacity=64)
    for name in names:
        with t.span(name):
            pass
    doc = t.export_dict()
    doc["rank"] = rank
    doc["origin_unix_ts"] = origin
    return doc


def test_merge_traces_aligns_clocks_and_ranks():
    a = _worker_trace(0, 1000.0, ["w0.step"])
    b = _worker_trace(1, 1002.5, ["w1.step"])
    merged = fed.merge_traces([a, b])
    ev0 = next(e for e in merged["traceEvents"] if e["name"] == "w0.step")
    ev1 = next(e for e in merged["traceEvents"] if e["name"] == "w1.step")
    assert ev0["pid"] == 0 and ev1["pid"] == 1
    # rank 1's origin is 2.5s later: its events shift +2.5e6 us
    raw1 = next(e for e in b["traceEvents"] if e["name"] == "w1.step")
    assert ev1["ts"] == pytest.approx(raw1["ts"] + 2.5e6, abs=1.0)
    pnames = {e["pid"]: e["args"]["name"]
              for e in merged["traceEvents"]
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert pnames == {0: "rank 0", 1: "rank 1"}
    assert merged["origins"] == {"0": 1000.0, "1": 1002.5}


def test_fleet_trace_cli_merges_files(tmp_path, capsys):
    p0, p1 = str(tmp_path / "t0.json"), str(tmp_path / "t1.json")
    with open(p0, "w") as f:
        json.dump(_worker_trace(0, 500.0, ["a.x"]), f)
    with open(p1, "w") as f:
        json.dump(_worker_trace(1, 501.0, ["b.x"]), f)
    out = str(tmp_path / "merged.json")
    assert fed.fleet_trace_main([p0, p1, "-o", out]) == 0
    with open(out) as f:
        merged = json.load(f)
    assert {e["pid"] for e in merged["traceEvents"]
            if e["ph"] != "M"} == {0, 1}
    assert fed.fleet_trace_main([str(tmp_path / "missing.json"),
                                 "-o", out]) == 2


def test_tracer_export_carries_fleet_anchors(monkeypatch):
    monkeypatch.setenv("ZNICZ_TPU_ELASTIC_RANK", "7")
    doc = observe.Tracer(capacity=8).export_dict()
    assert doc["rank"] == 7
    assert doc["origin_unix_ts"] == pytest.approx(time.time(), abs=60.0)


# -- satellite: rank-tagged JSONL sink ----------------------------------------

def test_jsonl_sink_carries_fleet_rank(tmp_path, monkeypatch):
    import logging

    from znicz_tpu.core import logger as zlogger

    monkeypatch.setenv("ZNICZ_TPU_ELASTIC_RANK", "2")
    path = str(tmp_path / "rank_tagged.jsonl")
    zlogger.configure(jsonl_path=path)
    try:
        logging.getLogger("znicz_tpu.fleet_test").warning("tagged line")
        observe.instant("fleet.test_event", detail=1)
        with open(path) as f:
            docs = [json.loads(line) for line in f]
    finally:
        # detach the handler: _jsonl_paths is process-global and the
        # tmp path dies with this test
        for h in list(logging.getLogger().handlers):
            if isinstance(h, zlogger.JsonlHandler) and \
                    h.baseFilename == path:
                logging.getLogger().removeHandler(h)
                h.close()
        zlogger._jsonl_paths.discard(path)
    line = next(d for d in docs if d["msg"] == "tagged line")
    assert line["rank"] == 2
    event = next(d for d in docs if d.get("event") == "fleet.test_event")
    assert event["rank"] == 2


# -- satellite: flight planes -------------------------------------------------

def test_flight_planes_register_unregister_and_degrade(tmp_path):
    flight.register_plane("fleet_test_ok", lambda: {"n": 1})
    flight.register_plane("fleet_test_dead",
                          lambda: (_ for _ in ()).throw(RuntimeError("x")))
    try:
        doc = flight.load(flight.dump(dir=str(tmp_path), reason="p"))
        assert doc["schema"] == "znicz_tpu.flight/2"
        assert doc["planes"]["fleet_test_ok"] == {"n": 1}
        assert "RuntimeError" in doc["planes"]["fleet_test_dead"]["error"]
    finally:
        flight.unregister_plane("fleet_test_ok")
        flight.unregister_plane("fleet_test_dead")
    # conditional unregister: a stale owner must not evict the newer one
    newer = dict.fromkeys              # any distinct callables
    flight.register_plane("fleet_test_cond", newer)
    flight.unregister_plane("fleet_test_cond", fn=lambda: None)
    assert flight._planes["fleet_test_cond"] is newer
    flight.unregister_plane("fleet_test_cond", fn=newer)
    assert "fleet_test_cond" not in flight._planes


def test_old_flight_schema_still_loads(tmp_path):
    legacy = tmp_path / "flight_old.json"
    legacy.write_text(json.dumps({"schema": "znicz_tpu.flight/1",
                                  "reason": "legacy"}))
    assert flight.load(str(legacy))["reason"] == "legacy"


# -- HTTP surfaces ------------------------------------------------------------

def test_webstatus_mounts_fleet_and_standalone_server():
    from znicz_tpu.web_status import WebStatus

    r0, _ = _two_serve_registries()
    r0.get("znicz_serve_queue_depth").set(3)
    agg = fed.FleetAggregator(min_refresh_s=0.0)
    agg.add_source(0, r0.render_prometheus)
    ws = WebStatus(port=0).register_fleet(agg)
    port = ws.start()
    try:
        base = f"http://127.0.0.1:{port}"
        prom = urllib.request.urlopen(base + "/fleet/metrics.prom",
                                      timeout=10).read().decode()
        assert 'znicz_serve_queue_depth{rank="0"} 3' in prom
        doc = json.load(urllib.request.urlopen(
            base + "/fleet/status.json", timeout=10))
        assert doc["workers"]["0"]["ok"]
        trace_doc = json.load(urllib.request.urlopen(
            base + "/fleet/trace.json", timeout=10))
        assert trace_doc["missing"] == [0]      # callable: no trace
        # unmounted paths still behave (fall through to the dashboard)
        assert urllib.request.urlopen(base + "/status.json",
                                      timeout=10).status == 200
    finally:
        ws.stop()
    fleet_port = agg.serve(port=0)
    try:
        doc = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{fleet_port}/fleet/metrics", timeout=10))
        assert doc["flat"]['znicz_serve_queue_depth{rank="0"}'] == 3.0
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{fleet_port}/fleet/nope", timeout=10)
    finally:
        agg.close()


# -- satellite: concurrent scrape soak under live decode traffic --------------

def test_concurrent_scrape_soak_under_decode_traffic(decoder):
    """Threaded soak of /metrics, /metrics.prom, /trace.json and
    /timeseries.json while generations stream: no 500s, no torn
    Prometheus text (every body parses whole, cumulative buckets stay
    monotone)."""
    from znicz_tpu.serve.continuous import ContinuousBatcher
    from znicz_tpu.serve.server import GenerateServer
    from znicz_tpu.web_status import WebStatus

    batcher = ContinuousBatcher(decoder, default_timeout_s=30.0)
    server = GenerateServer(batcher, charmap=CHARMAP, port=0)
    gport = server.start()
    status = WebStatus(port=0)
    sport = status.start()
    errors: list = []
    stop = threading.Event()

    def client(seed: int) -> None:
        while not stop.is_set():
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{gport}/generate",
                    data=json.dumps({"tokens": [1 + seed, 2],
                                     "max_tokens": 4}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as r:
                    for _ in r:
                        pass
            except Exception as exc:  # noqa: BLE001
                errors.append(f"client: {exc!r}")
                return

    def scraper(url: str, check_prom: bool) -> None:
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=30) as r:
                    body = r.read().decode()
                    if r.status != 200:
                        errors.append(f"{url} -> {r.status}")
                        return
                if check_prom:
                    _, samples = fed.parse_prometheus(body)
                    by_family: dict = {}
                    for _, name, inner, v in samples:
                        if name.endswith("_bucket"):
                            by_family.setdefault(
                                name + inner.split("le=")[0], []).append(v)
                    for counts in by_family.values():
                        if counts != sorted(counts):
                            errors.append(f"non-monotone buckets in "
                                          f"{url}")
                            return
                else:
                    json.loads(body)
            except Exception as exc:  # noqa: BLE001
                errors.append(f"{url}: {exc!r}")
                return

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(3)]
    for url, is_prom in (
            (f"http://127.0.0.1:{gport}/metrics", False),
            (f"http://127.0.0.1:{gport}/metrics.prom", True),
            (f"http://127.0.0.1:{gport}/trace.json", False),
            (f"http://127.0.0.1:{sport}/timeseries.json", False),
            (f"http://127.0.0.1:{sport}/metrics", True)):
        threads.append(threading.Thread(target=scraper,
                                        args=(url, is_prom)))
    for t in threads:
        t.start()
    time.sleep(4.0)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    status.stop()
    server.stop()
    assert not errors, errors[:5]
    snap = batcher.metrics.snapshot()
    assert snap["completed"] >= 3       # traffic actually flowed
    assert snap["admitted"] == snap["completed"] + snap["failed"] + \
        snap["abandoned"]
