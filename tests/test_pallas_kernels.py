"""Pallas kernel parity tests (SURVEY.md §5 tier-1: "Pallas-vs-XLA
cross-check, the analog of ocl-vs-numpy") — interpreter mode on the CPU
mesh; the same calls lower to Mosaic on real TPU."""

import numpy as np
import pytest

import jax.numpy as jnp

from znicz_tpu.ops import lrn as lrn_ops, sgd as sgd_ops
from znicz_tpu.ops.pallas import (dropout_forward, fused_sgd_update,
                                  lrn_backward, lrn_forward)


def test_fused_sgd_matches_oracle():
    rng = np.random.default_rng(0)
    for shape in ((64, 128), (7, 33), (3, 5, 16)):
        w = rng.normal(size=shape).astype(np.float32)
        g = rng.normal(size=shape).astype(np.float32)
        v = rng.normal(size=shape).astype(np.float32) * 0.1
        args = (0.05, 1e-3, 0.3, 0.9, 32.0)
        w_ref, v_ref = sgd_ops.update(jnp, jnp.asarray(w), jnp.asarray(g),
                                      jnp.asarray(v), *args)
        w_pl, v_pl = fused_sgd_update(jnp.asarray(w), jnp.asarray(g),
                                      jnp.asarray(v), *args, interpret=True)
        np.testing.assert_allclose(np.asarray(w_pl), np.asarray(w_ref),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(v_pl), np.asarray(v_ref),
                                   rtol=1e-6, atol=1e-7)


def test_fused_sgd_traced_hyperparams():
    """Hyperparams as traced scalars (the LR-schedule path)."""
    import jax
    rng = np.random.default_rng(1)
    w = rng.normal(size=(16, 32)).astype(np.float32)
    g = rng.normal(size=(16, 32)).astype(np.float32)
    v = np.zeros((16, 32), np.float32)

    def step(lr):
        return fused_sgd_update(jnp.asarray(w), jnp.asarray(g),
                                jnp.asarray(v), lr, 0.0, 0.0, 0.9, 8.0,
                                interpret=True)

    w1, _ = jax.jit(step)(jnp.float32(0.1))
    w_ref, _ = sgd_ops.update(jnp, jnp.asarray(w), jnp.asarray(g),
                              jnp.asarray(v), 0.1, 0.0, 0.0, 0.9, 8.0)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w_ref), rtol=1e-6)


def test_dropout_kernel_semantics():
    """Masking math via injected bits (the CPU interpreter's emulated TPU
    PRNG yields zeros, so in-kernel bit generation is TPU-only)."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(32, 128)).astype(np.float32)
    bits = rng.integers(0, 2 ** 32, size=x.shape, dtype=np.uint32)
    ratio = 0.4
    y, mask = dropout_forward(jnp.asarray(x), seed=7, ratio=ratio,
                              bits=jnp.asarray(bits), interpret=True)
    y, mask = np.asarray(y), np.asarray(mask)
    scale = 1.0 / (1.0 - ratio)
    assert set(np.unique(mask)).issubset({0.0, np.float32(scale)})
    np.testing.assert_allclose(y, x * mask, rtol=1e-6)
    # drop rate within statistical tolerance of the threshold
    drop_rate = (mask == 0).mean()
    assert abs(drop_rate - ratio) < 0.06, drop_rate
    # bit-exact vs the threshold rule
    np.testing.assert_array_equal(
        mask != 0, bits > np.uint32(ratio * (2 ** 32 - 1)))


def test_pallas_sgd_in_fused_workflow():
    """End-to-end: the fused training step with the Pallas SGD backend
    reproduces the default XLA-fused run bit-for-bit."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models import wine

    def run():
        prng.seed_all(17)
        w = wine.build(max_epochs=2, n_train=60, n_valid=30,
                       minibatch_size=10)
        w.initialize(device=TPUDevice())
        w.run()
        w.stop()
        return w

    base = run()
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        pallas = run()
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    assert base.decision.metrics_history == pallas.decision.metrics_history
    np.testing.assert_allclose(
        base.forwards[0].weights.map_read(),
        pallas.forwards[0].weights.map_read(), rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("n", [4, 5])
def test_lrn_kernels_match_oracle(n):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 3, 3, 16)).astype(np.float32)
    err = rng.normal(size=x.shape).astype(np.float32)
    args = (1e-4, 0.75, 2.0, n)
    y_ref = lrn_ops.forward(np, x, *args)
    y_pl = lrn_forward(jnp.asarray(x), *args, interpret=True)
    np.testing.assert_allclose(np.asarray(y_pl), y_ref, rtol=1e-5,
                               atol=1e-6)
    e_ref = lrn_ops.backward(np, x, err, *args)
    e_pl = lrn_backward(jnp.asarray(x), jnp.asarray(err), *args,
                        interpret=True)
    np.testing.assert_allclose(np.asarray(e_pl), e_ref, rtol=1e-4,
                               atol=1e-5)


# -- round-3 parity tail: conv, stochastic pooling, kohonen ------------------

from znicz_tpu.ops import conv as conv_ops, kohonen as k_ops
from znicz_tpu.ops import pooling as pool_ops
from znicz_tpu.ops.pallas import conv2d_im2col, som_step, stochastic_pool

CONV_GEOMS = [
    # (h, w, cin, cout, k, sliding, padding)
    (8, 8, 3, 16, 3, (1, 1), (0, 0, 0, 0)),
    (9, 7, 4, 8, 3, (2, 2), (1, 1, 1, 1)),
    (12, 12, 2, 8, 5, (2, 2), (2, 1, 0, 2)),   # asymmetric 4-tuple pad
    (6, 6, 8, 32, 1, (1, 1), (0, 0, 0, 0)),    # 1x1
]


@pytest.mark.parametrize("geom", CONV_GEOMS)
def test_pallas_conv_matches_oracle(geom):
    h, w, cin, cout, k, sliding, padding = geom
    rng = np.random.default_rng(7)
    x = rng.normal(size=(3, h, w, cin)).astype(np.float32)
    wts = rng.normal(size=(k, k, cin, cout)).astype(np.float32) * 0.1
    b = rng.normal(size=(cout,)).astype(np.float32)
    ref = conv_ops.forward_linear(np, x, wts, b, sliding, padding)
    out = conv2d_im2col(jnp.asarray(x), jnp.asarray(wts), jnp.asarray(b),
                        sliding, padding, interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)
    # and without bias
    ref0 = conv_ops.forward_linear(np, x, wts, None, sliding, padding)
    out0 = conv2d_im2col(jnp.asarray(x), jnp.asarray(wts), None,
                         sliding, padding, interpret=True)
    np.testing.assert_allclose(np.asarray(out0), ref0, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("use_abs", [False, True])
def test_pallas_stochastic_pool_matches_oracle(use_abs):
    """Injected-bits path vs ops.pooling.stochastic_forward with the SAME
    uniforms: identical winners and values (inverse-CDF strict-compare
    semantics)."""
    rng = np.random.default_rng(8)
    x = rng.normal(size=(2, 7, 5, 4)).astype(np.float32)
    ky = kx = 3
    sy = sx = 2
    patch, valid, _ = pool_ops.patches(np, x, ky, kx, sy, sx, pad_value=0.0)
    n, oh, ow, K, c = patch.shape
    bits = rng.integers(0, 2 ** 32, size=(n * oh * ow, c), dtype=np.uint32)
    # the kernel's 24-bit uniform mapping (Mosaic-compatible cast path)
    u = ((bits >> 8).astype(np.float32) * 2.0 ** -24)
    y_ref, off_ref = pool_ops.stochastic_forward(
        np, x, ky, kx, sy, sx, u.reshape(n, oh, ow, c), use_abs, train=True)
    vtile = np.broadcast_to(valid.reshape(1, oh * ow, K), (n, oh * ow, K))
    y_pl, tap = stochastic_pool(
        jnp.asarray(patch.reshape(n * oh * ow, K, c)),
        jnp.asarray(vtile.reshape(n * oh * ow, K)), seed=0,
        use_abs=use_abs, bits=jnp.asarray(bits), interpret=True)
    np.testing.assert_allclose(np.asarray(y_pl).reshape(n, oh, ow, c),
                               y_ref, rtol=1e-6)
    off_pl = pool_ops.offsets_of(
        np, np.asarray(tap).reshape(n, oh, ow, c), x.shape, ky, kx, sy, sx)
    np.testing.assert_array_equal(off_pl, off_ref)


def test_pallas_stochastic_pool_prng_branch_plumbing():
    """Exercise the bits=None in-kernel-PRNG branch end to end under the
    interpreter: the emulated TPU PRNG yields zero bits, so u == 0 and
    the strict-compare inverse CDF must select tap 0 everywhere — which
    pins the seed/SMEM spec, prng_seed/bitcast plumbing and the zero-mass
    fallback in one go (real-hardware randomness is covered by the
    selection test on TPU runs)."""
    rng = np.random.default_rng(10)
    x = rng.normal(size=(2, 6, 6, 4)).astype(np.float32)
    patch, valid, _ = pool_ops.patches(np, x, 2, 2, 2, 2, pad_value=0.0)
    n, oh, ow, K, c = patch.shape
    vtile = np.broadcast_to(valid.reshape(1, oh * ow, K), (n, oh * ow, K))
    from znicz_tpu.utils.pallas_hw import tpu_interpret_params

    interp = tpu_interpret_params()
    if interp is None:
        pytest.skip("no TPU-emulating pallas interpreter in this jax")
    y, tap = stochastic_pool(
        jnp.asarray(patch.reshape(n * oh * ow, K, c)),
        jnp.asarray(vtile.reshape(n * oh * ow, K)), seed=3,
        interpret=interp)
    np.testing.assert_array_equal(np.asarray(tap), 0)
    np.testing.assert_allclose(np.asarray(y),
                               patch.reshape(n * oh * ow, K, c)[:, 0, :],
                               rtol=1e-6)


def test_pallas_som_step_matches_oracle():
    rng = np.random.default_rng(9)
    B, D, sy, sx = 32, 6, 5, 4
    x = rng.normal(size=(B, D)).astype(np.float32)
    w = rng.normal(size=(sy * sx, D)).astype(np.float32)
    coords = np.asarray(k_ops.grid_coords(np, sy, sx))
    for bs in (B, 20):   # full batch + padded tail
        mask = (np.arange(B) < bs) if bs < B else None
        w_ref, idx_ref = k_ops.update(np, x, w, coords, 0.3, 1.5, mask)
        w_pl, idx_pl = som_step(jnp.asarray(x), jnp.asarray(w),
                                jnp.asarray(coords), 0.3, 1.5, bs,
                                interpret=True)
        np.testing.assert_allclose(np.asarray(w_pl), w_ref, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_array_equal(np.asarray(idx_pl), idx_ref)


def test_pallas_conv_unit_selection():
    """root.common.engine.pallas routes Conv.xla_run through the im2col
    kernel with identical outputs."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.units.conv import Conv

    def run_once():
        prng.seed_all(12)
        w = Workflow(name="c")
        conv = Conv(w, n_kernels=8, kx=3, ky=3, sliding=(2, 2),
                    padding=(1, 1, 1, 1))
        from znicz_tpu.core.memory import Array
        conv.input = Array()
        conv.input.mem = np.random.default_rng(5).normal(
            size=(4, 9, 9, 3)).astype(np.float32)
        conv.initialize(device=TPUDevice())
        conv.xla_run()
        return np.asarray(conv.output.map_read())

    base = run_once()
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        pallas = run_once()
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    np.testing.assert_allclose(pallas, base, rtol=1e-5, atol=1e-6)


def test_pallas_kohonen_trainer_selection():
    """SOM demo trains identically through the fused Pallas step."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models import kohonen as km

    def run_once():
        prng.seed_all(21)
        w = km.build(max_epochs=2, shape=(5, 5), n_train=200)
        w.initialize(device=TPUDevice())
        w.run()
        return np.asarray(w.trainer.weights.map_read())

    base = run_once()
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        pallas = run_once()
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    np.testing.assert_allclose(pallas, base, rtol=1e-4, atol=1e-5)


def test_pallas_stochastic_pooling_unit_selection():
    """The stochastic pooling unit's Pallas path emits values from the
    right windows with offsets consistent with the emitted values."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.core.memory import Array
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.units.pooling import StochasticPooling

    prng.seed_all(33)
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        w = Workflow(name="sp")
        unit = StochasticPooling(w, kx=2, ky=2, sliding=(2, 2))
        unit.input = Array()
        x = np.random.default_rng(6).normal(
            size=(3, 6, 6, 4)).astype(np.float32)
        unit.input.mem = x
        unit.initialize(device=TPUDevice())
        unit.xla_run()
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    y = np.asarray(unit.output.map_read())
    off = np.asarray(unit.input_offset.map_read())
    flat = x.reshape(3, -1, 4)
    n, oh, ow, c = y.shape
    for ni in range(n):
        for ci in range(c):
            picked = flat[ni, off[ni, :, :, ci].ravel(), ci]
            np.testing.assert_allclose(picked, y[ni, :, :, ci].ravel(),
                                       rtol=1e-6)


def test_flash_attention_matches_dense():
    """Flash forward == dense-softmax oracle (causal and full), and the
    custom-VJP gradients match autograd-through-the-oracle."""
    import jax

    from znicz_tpu.ops import attention as att
    from znicz_tpu.ops.pallas import flash_attention

    rng = np.random.default_rng(4)
    b, t, h, dh = 2, 256, 2, 64
    q = rng.normal(size=(b, t, h, dh)).astype(np.float32)
    k = rng.normal(size=(b, t, h, dh)).astype(np.float32)
    v = rng.normal(size=(b, t, h, dh)).astype(np.float32)

    for causal in (False, True):
        def oracle(q, k, v):
            return att.attention(jnp, q, k, v, causal=causal).sum()

        def flash(q, k, v):
            return flash_attention(q, k, v, causal=causal,
                                   interpret=True).sum()

        o_ref = att.attention(jnp, jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=causal)
        o_pl = flash_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=causal,
                               interpret=True)
        np.testing.assert_allclose(np.asarray(o_pl), np.asarray(o_ref),
                                   rtol=2e-5, atol=2e-5)
        g_ref = jax.grad(oracle, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        g_pl = jax.grad(flash, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for a, b_ in zip(g_pl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)


def test_flash_attention_supported_gate():
    from znicz_tpu.ops.pallas.attention import supported

    assert supported(2048, 64)
    assert supported(256, 128)
    assert not supported(100, 64)      # t not q-blockable
    assert not supported(256, 48)      # head dim not lane-aligned
    assert not supported(1 << 20, 64)  # VMEM budget


def test_fused_adam_matches_oracle():
    from znicz_tpu.ops import adam as adam_ops
    from znicz_tpu.ops.pallas import fused_adam_update

    rng = np.random.default_rng(9)
    for shape in ((64, 128), (7, 33), (3, 5, 16)):
        w = rng.normal(size=shape).astype(np.float32)
        g = rng.normal(size=shape).astype(np.float32)
        m = rng.normal(size=shape).astype(np.float32) * 0.1
        v = np.abs(rng.normal(size=shape)).astype(np.float32) * 0.01
        args = (3.0, 0.01, 0.001, 0.9, 0.999, 1e-8, 32.0)
        w_ref, m_ref, v_ref = adam_ops.update(
            jnp, jnp.asarray(w), jnp.asarray(g), jnp.asarray(m),
            jnp.asarray(v), *args)
        w_pl, m_pl, v_pl = fused_adam_update(
            jnp.asarray(w), jnp.asarray(g), jnp.asarray(m),
            jnp.asarray(v), *args, interpret=True)
        for got, want in ((w_pl, w_ref), (m_pl, m_ref), (v_pl, v_ref)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-6)


def test_pallas_adam_workflow_matches_xla():
    """optimizer=adam + engine.pallas: the fused step runs the Pallas
    adam kernel (interpret mode) and matches the XLA path's training."""
    from znicz_tpu.core.config import root
    from znicz_tpu.core import prng as prng_mod
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.standard_workflow import StandardWorkflow

    def run(pallas: bool):
        prng_mod.seed_all(66)
        root.common.engine.pallas = pallas
        root.common.engine.pallas_interpret = pallas
        try:
            w = StandardWorkflow(
                name="PAdam", loss_function="softmax", layers=[
                    {"type": "all2all_tanh",
                     "->": {"output_sample_shape": 8}},
                    {"type": "softmax", "->": {"output_sample_shape": 3}}],
                loader_name="synthetic_classifier",
                loader_config={"n_classes": 3, "sample_shape": (4,),
                               "n_train": 30, "n_valid": 0,
                               "minibatch_size": 30},
                decision_config={"max_epochs": 3}, optimizer="adam")
            w.initialize(device=TPUDevice())
            w.run()
            w.step.sync_to_units()
            return np.asarray(w.forwards[0].weights.map_read()).copy()
        finally:
            root.common.engine.pallas = False
            root.common.engine.pallas_interpret = False

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5,
                               atol=1e-6)


# -- round-4 parity tail: conv backward (col2im-as-gather) + deconv pair -----

from znicz_tpu.ops import activations, deconv as deconv_ops
from znicz_tpu.ops.pallas import (conv2d_backward, deconv2d,
                                  deconv2d_backward)


@pytest.mark.parametrize("geom", CONV_GEOMS)
def test_pallas_conv_backward_matches_oracle(geom):
    """err_input/grad_w/grad_b vs the XLA vjp oracle (the linear part of
    ops.conv.backward) across strides and asymmetric padding."""
    h, w, cin, cout, k, sliding, padding = geom
    rng = np.random.default_rng(11)
    x = rng.normal(size=(3, h, w, cin)).astype(np.float32)
    wts = rng.normal(size=(k, k, cin, cout)).astype(np.float32) * 0.1
    out_shape = conv_ops.forward_linear(np, x, wts, None, sliding,
                                        padding).shape
    err = rng.normal(size=out_shape).astype(np.float32)
    ei_ref, gw_ref, gb_ref = conv_ops.backward(
        jnp, jnp.asarray(x), None, jnp.asarray(wts), jnp.asarray(err),
        sliding, padding, activations.LINEAR, activation_applied=False)
    ei_pl, gw_pl, gb_pl = conv2d_backward(
        jnp.asarray(x), jnp.asarray(wts), jnp.asarray(err), sliding,
        padding, interpret=True)
    np.testing.assert_allclose(np.asarray(ei_pl), np.asarray(ei_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_pl), np.asarray(gw_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb_pl), np.asarray(gb_ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("geom", CONV_GEOMS)
def test_pallas_deconv_matches_oracle(geom):
    """deconv2d forward == ops.deconv.forward; deconv2d_backward ==
    ops.deconv.backward (err_input + grad_w), same geometries."""
    h, w, cin, cout, k, sliding, padding = geom
    rng = np.random.default_rng(12)
    wts = rng.normal(size=(k, k, cin, cout)).astype(np.float32) * 0.1
    oh = conv_ops.out_size(h, k, sliding[0], *(padding[0], padding[1]))
    ow = conv_ops.out_size(w, k, sliding[1], *(padding[2], padding[3]))
    x = rng.normal(size=(3, oh, ow, cout)).astype(np.float32)
    out_shape = deconv_ops.output_shape_for(x.shape, wts.shape, sliding,
                                            padding)
    y_ref = deconv_ops.forward(jnp, jnp.asarray(x), jnp.asarray(wts),
                               sliding, padding, out_shape)
    y_pl = deconv2d(jnp.asarray(x), jnp.asarray(wts), sliding, padding,
                    out_shape, interpret=True)
    np.testing.assert_allclose(np.asarray(y_pl), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-5)
    err = rng.normal(size=out_shape).astype(np.float32)
    ei_ref, gw_ref = deconv_ops.backward(
        jnp, jnp.asarray(x), jnp.asarray(wts), jnp.asarray(err), sliding,
        padding)
    ei_pl, gw_pl = deconv2d_backward(
        jnp.asarray(x), jnp.asarray(wts), jnp.asarray(err), sliding,
        padding, interpret=True)
    np.testing.assert_allclose(np.asarray(ei_pl), np.asarray(ei_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_pl), np.asarray(gw_ref),
                               rtol=1e-4, atol=1e-4)


def test_pallas_gd_conv_unit_selection():
    """root.common.engine.pallas routes GradientDescentConv (incl. the
    tanh activation correction) through the hand-written backward with
    identical training effect."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.core.memory import Array
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.units.conv import ConvTanh
    from znicz_tpu.units.gd_conv import GDTanhConv

    def run_once():
        prng.seed_all(14)
        rng = np.random.default_rng(2)
        w = Workflow(name="g")
        fwd = ConvTanh(w, n_kernels=6, kx=3, ky=3, sliding=(2, 2),
                       padding=(1, 1, 1, 1))
        fwd.input = Array(rng.normal(size=(3, 8, 8, 2)).astype(np.float32))
        fwd.initialize(device=TPUDevice())
        fwd.run()
        gd = GDTanhConv(w, learning_rate=0.1, weights_decay=0.01,
                        gradient_moment=0.9)
        gd.link_from_forward(fwd)
        gd.err_output = Array(rng.normal(size=fwd.output.shape)
                              .astype(np.float32))
        gd.batch_size = 3
        gd.initialize(device=TPUDevice())
        gd.run()
        return {a: np.asarray(getattr(gd, a).map_read()).copy()
                for a in ("err_input", "weights", "bias",
                          "gradient_weights", "gradient_bias")}

    base = run_once()
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        pallas = run_once()
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    for attr, want in base.items():
        np.testing.assert_allclose(pallas[attr], want, rtol=1e-4,
                                   atol=1e-5, err_msg=attr)


def test_pallas_deconv_unit_selection():
    """root.common.engine.pallas routes Deconv + GDDeconv through the
    hand-written transposed-conv pair with identical results."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.core.memory import Array
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.units.deconv import Deconv
    from znicz_tpu.units.gd_deconv import GDDeconv

    def run_once():
        prng.seed_all(15)
        rng = np.random.default_rng(4)
        w = Workflow(name="d")
        fwd = Deconv(w, n_kernels=6, kx=3, ky=3, n_channels=2,
                     sliding=(2, 2), padding=(1, 1, 1, 1))
        fwd.input = Array(rng.normal(size=(2, 4, 4, 6)).astype(np.float32))
        fwd.initialize(device=TPUDevice())
        fwd.run()
        gd = GDDeconv(w, learning_rate=0.1, gradient_moment=0.9)
        gd.link_from_forward(fwd)
        gd.err_output = Array(rng.normal(size=fwd.output.shape)
                              .astype(np.float32))
        gd.batch_size = 2
        gd.initialize(device=TPUDevice())
        gd.run()
        return {"out": np.asarray(fwd.output.map_read()).copy(),
                "err_input": np.asarray(gd.err_input.map_read()).copy(),
                "weights": np.asarray(gd.weights.map_read()).copy(),
                "vel": np.asarray(gd.gradient_weights.map_read()).copy()}

    base = run_once()
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        pallas = run_once()
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    for attr, want in base.items():
        np.testing.assert_allclose(pallas[attr], want, rtol=1e-4,
                                   atol=1e-5, err_msg=attr)


def test_pallas_hw_parity_sweep_interpret():
    """The compiled-mode hardware sweep (bench.py::bench_pallas_parity)
    must cover every kernel family and pass fully under the interpreter —
    so a chip-window run can only fail for hardware/lowering reasons."""
    from znicz_tpu.utils.pallas_hw import run_parity, tpu_interpret_params

    res = run_parity(interpret=True)
    assert set(res) == {"sgd", "adam", "dropout", "lrn", "fc_gemm",
                        "conv_fwd", "conv_bwd", "deconv",
                        "stochastic_pool", "kohonen", "flash_attention",
                        "conv_fwd_bf16", "flash_attention_bf16",
                        "sgd_bf16state"}
    skipped = {k for k, v in res.items() if v.startswith("skipped:")}
    if tpu_interpret_params() is None:
        # pre-InterpretParams jax: exactly the in-kernel-PRNG pair may
        # skip under the interpreter (they still run compiled on chip)
        assert skipped <= {"dropout", "stochastic_pool"}, res
    else:
        assert not skipped, res
    bad = {k: v for k, v in res.items()
           if v != "ok" and k not in skipped}
    assert not bad, bad


# -- round-4 parity tail 2: the blocked FC GEMM (matrix_multiplication) ------

from znicz_tpu.ops import linear as lin_ops
from znicz_tpu.ops.pallas import fc_backward, fc_forward

FC_GEOMS = [(32, 784, 100), (7, 13, 3), (129, 200, 257), (8, 128, 128)]


@pytest.mark.parametrize("geom", FC_GEOMS)
@pytest.mark.parametrize("act", ["linear", "tanh", "relu", "strict_relu",
                                 "sigmoid"])
def test_pallas_fc_gemm_matches_oracle(geom, act):
    """Blocked-GEMM fc forward/backward vs ops.linear across padded and
    exact-block geometries and every fused activation."""
    B, F, O = geom
    rng = np.random.default_rng(13)
    x = rng.normal(size=(B, F)).astype(np.float32)
    w = (rng.normal(size=(F, O)) * 0.05).astype(np.float32)
    b = rng.normal(size=(O,)).astype(np.float32)
    y_ref = lin_ops.forward(np, x, w, b, act)
    y_pl = fc_forward(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), act,
                      interpret=True)
    np.testing.assert_allclose(np.asarray(y_pl), y_ref, rtol=1e-4,
                               atol=1e-4)
    e = rng.normal(size=(B, O)).astype(np.float32)
    refs = lin_ops.backward(np, x, y_ref, w, e, act)
    outs = fc_backward(jnp.asarray(x), jnp.asarray(y_ref), jnp.asarray(w),
                       jnp.asarray(e), act, interpret=True)
    for name, got, want in zip(("err_input", "grad_w", "grad_b"), outs,
                               refs):
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4,
                                   atol=2e-3, err_msg=name)


def test_pallas_fc_unit_selection():
    """engine.pallas routes All2AllTanh + GDTanh through the blocked
    GEMM kernels with identical training effect."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.core.memory import Array
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.units.all2all import All2AllTanh
    from znicz_tpu.units.gd import GDTanh

    def run_once():
        prng.seed_all(19)
        rng = np.random.default_rng(7)
        w = Workflow(name="fc")
        fwd = All2AllTanh(w, output_sample_shape=24)
        fwd.input = Array(rng.normal(size=(16, 33)).astype(np.float32))
        fwd.initialize(device=TPUDevice())
        fwd.run()
        gd = GDTanh(w, learning_rate=0.1, weights_decay=0.01,
                    gradient_moment=0.9)
        gd.link_from_forward(fwd)
        gd.err_output = Array(rng.normal(size=fwd.output.shape)
                              .astype(np.float32))
        gd.batch_size = 16
        gd.initialize(device=TPUDevice())
        gd.run()
        return {a: np.asarray(getattr(gd, a).map_read()).copy()
                for a in ("err_input", "weights", "bias",
                          "gradient_weights", "gradient_bias")}

    base = run_once()
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        pallas = run_once()
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    for attr, want in base.items():
        np.testing.assert_allclose(pallas[attr], want, rtol=2e-4,
                                   atol=2e-5, err_msg=attr)


def test_pallas_gd_override_cleared_on_numpy_reinit():
    """A gd unit initialized under engine.pallas on XLA, then
    re-initialized onto the numpy backend, must run the numpy oracle —
    not the stale Pallas closure (GradientDescentBase.numpy_init)."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import NumpyDevice, TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.core.memory import Array
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.units.all2all import All2AllTanh
    from znicz_tpu.units.gd import GDTanh

    prng.seed_all(23)
    rng = np.random.default_rng(9)
    w = Workflow(name="t")
    fwd = All2AllTanh(w, output_sample_shape=8)
    fwd.input = Array(rng.normal(size=(4, 12)).astype(np.float32))
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        fwd.initialize(device=TPUDevice())
        fwd.run()
        gd = GDTanh(w, learning_rate=0.1)
        gd.link_from_forward(fwd)
        gd.err_output = Array(rng.normal(size=fwd.output.shape)
                              .astype(np.float32))
        gd.batch_size = 4
        gd.initialize(device=TPUDevice())
        gd.run()
        assert "_backward" in gd.__dict__      # override installed
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    gd.initialize(device=NumpyDevice())
    assert "_backward" not in gd.__dict__      # override dropped
    gd.run()                                   # numpy oracle, no jax
    assert isinstance(gd.err_input.mem, np.ndarray)


def test_fused_sgd_narrow_state():
    """bf16 velocity storage through the kernel: f32 math in-tile, one
    narrow store, velocity dtype preserved (both tiled and fallback
    shapes)."""
    rng = np.random.default_rng(5)
    for shape in ((64, 128), (3, 5, 16)):
        w = jnp.asarray(rng.normal(size=shape), jnp.float32)
        g = jnp.asarray(rng.normal(size=shape), jnp.float32)
        v = jnp.asarray(rng.normal(size=shape) * 0.1, jnp.bfloat16)
        args = (0.05, 1e-3, 0.3, 0.9, 32.0)
        w_ref, v_ref = sgd_ops.update(jnp, w, g, v.astype(jnp.float32),
                                      *args)
        w_pl, v_pl = fused_sgd_update(w, g, v, *args, interpret=True)
        assert v_pl.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(w_pl), np.asarray(w_ref),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(v_pl, dtype=np.float32),
            np.asarray(v_ref.astype(jnp.bfloat16), dtype=np.float32),
            rtol=1e-5, atol=1e-6)
