"""Pallas kernel parity tests (SURVEY.md §5 tier-1: "Pallas-vs-XLA
cross-check, the analog of ocl-vs-numpy") — interpreter mode on the CPU
mesh; the same calls lower to Mosaic on real TPU."""

import numpy as np
import pytest

import jax.numpy as jnp

from znicz_tpu.ops import lrn as lrn_ops, sgd as sgd_ops
from znicz_tpu.ops.pallas import (dropout_forward, fused_sgd_update,
                                  lrn_backward, lrn_forward)


def test_fused_sgd_matches_oracle():
    rng = np.random.default_rng(0)
    for shape in ((64, 128), (7, 33), (3, 5, 16)):
        w = rng.normal(size=shape).astype(np.float32)
        g = rng.normal(size=shape).astype(np.float32)
        v = rng.normal(size=shape).astype(np.float32) * 0.1
        args = (0.05, 1e-3, 0.3, 0.9, 32.0)
        w_ref, v_ref = sgd_ops.update(jnp, jnp.asarray(w), jnp.asarray(g),
                                      jnp.asarray(v), *args)
        w_pl, v_pl = fused_sgd_update(jnp.asarray(w), jnp.asarray(g),
                                      jnp.asarray(v), *args, interpret=True)
        np.testing.assert_allclose(np.asarray(w_pl), np.asarray(w_ref),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(v_pl), np.asarray(v_ref),
                                   rtol=1e-6, atol=1e-7)


def test_fused_sgd_traced_hyperparams():
    """Hyperparams as traced scalars (the LR-schedule path)."""
    import jax
    rng = np.random.default_rng(1)
    w = rng.normal(size=(16, 32)).astype(np.float32)
    g = rng.normal(size=(16, 32)).astype(np.float32)
    v = np.zeros((16, 32), np.float32)

    def step(lr):
        return fused_sgd_update(jnp.asarray(w), jnp.asarray(g),
                                jnp.asarray(v), lr, 0.0, 0.0, 0.9, 8.0,
                                interpret=True)

    w1, _ = jax.jit(step)(jnp.float32(0.1))
    w_ref, _ = sgd_ops.update(jnp, jnp.asarray(w), jnp.asarray(g),
                              jnp.asarray(v), 0.1, 0.0, 0.0, 0.9, 8.0)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w_ref), rtol=1e-6)


def test_dropout_kernel_semantics():
    """Masking math via injected bits (the CPU interpreter's emulated TPU
    PRNG yields zeros, so in-kernel bit generation is TPU-only)."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(32, 128)).astype(np.float32)
    bits = rng.integers(0, 2 ** 32, size=x.shape, dtype=np.uint32)
    ratio = 0.4
    y, mask = dropout_forward(jnp.asarray(x), seed=7, ratio=ratio,
                              bits=jnp.asarray(bits), interpret=True)
    y, mask = np.asarray(y), np.asarray(mask)
    scale = 1.0 / (1.0 - ratio)
    assert set(np.unique(mask)).issubset({0.0, np.float32(scale)})
    np.testing.assert_allclose(y, x * mask, rtol=1e-6)
    # drop rate within statistical tolerance of the threshold
    drop_rate = (mask == 0).mean()
    assert abs(drop_rate - ratio) < 0.06, drop_rate
    # bit-exact vs the threshold rule
    np.testing.assert_array_equal(
        mask != 0, bits > np.uint32(ratio * (2 ** 32 - 1)))


def test_pallas_sgd_in_fused_workflow():
    """End-to-end: the fused training step with the Pallas SGD backend
    reproduces the default XLA-fused run bit-for-bit."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.core.config import root
    from znicz_tpu.models import wine

    def run():
        prng.seed_all(17)
        w = wine.build(max_epochs=2, n_train=60, n_valid=30,
                       minibatch_size=10)
        w.initialize(device=TPUDevice())
        w.run()
        w.stop()
        return w

    base = run()
    root.common.engine.pallas = True
    root.common.engine.pallas_interpret = True
    try:
        pallas = run()
    finally:
        root.common.engine.pallas = False
        root.common.engine.pallas_interpret = False
    assert base.decision.metrics_history == pallas.decision.metrics_history
    np.testing.assert_allclose(
        base.forwards[0].weights.map_read(),
        pallas.forwards[0].weights.map_read(), rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("n", [4, 5])
def test_lrn_kernels_match_oracle(n):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 3, 3, 16)).astype(np.float32)
    err = rng.normal(size=x.shape).astype(np.float32)
    args = (1e-4, 0.75, 2.0, n)
    y_ref = lrn_ops.forward(np, x, *args)
    y_pl = lrn_forward(jnp.asarray(x), *args, interpret=True)
    np.testing.assert_allclose(np.asarray(y_pl), y_ref, rtol=1e-5,
                               atol=1e-6)
    e_ref = lrn_ops.backward(np, x, err, *args)
    e_pl = lrn_backward(jnp.asarray(x), jnp.asarray(err), *args,
                        interpret=True)
    np.testing.assert_allclose(np.asarray(e_pl), e_ref, rtol=1e-4,
                               atol=1e-5)
