"""serve/ subsystem tests: bucketed engine (zero steady-state
recompiles), micro-batcher contract (coalescing, backpressure,
deadlines, oversize chunking, graceful drain — every admitted request
gets exactly one response), serving metrics, the HTTP front end, and the
``python -m znicz_tpu serve`` CLI."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from znicz_tpu.serve import (BatchEngine, DeadlineExceeded, MicroBatcher,
                             QueueFull, ServeServer, ServingMetrics,
                             bucket_sizes)


class RecordingModel:
    """``x * 2`` callable that records every batch shape it executes."""

    def __init__(self, delay_s: float = 0.0, input_shape=(3,)) -> None:
        self.shapes = []
        self.delay_s = delay_s
        self.input_shape = tuple(input_shape)
        self.meta = {"name": "recording"}

    def __call__(self, x):
        self.shapes.append(np.asarray(x).shape)
        if self.delay_s:
            time.sleep(self.delay_s)
        return np.asarray(x) * 2.0


def make_batcher(delay_s=0.0, max_batch=8, max_wait_ms=1.0, **kw):
    model = RecordingModel(delay_s=delay_s)
    engine = BatchEngine(model, max_batch=max_batch)
    return MicroBatcher(engine, max_wait_ms=max_wait_ms, **kw), model


# -- engine ------------------------------------------------------------------

def test_bucket_sizes_powers_of_two_plus_ceiling():
    assert bucket_sizes(16) == (1, 2, 4, 8, 16)
    assert bucket_sizes(12) == (1, 2, 4, 8, 12)
    assert bucket_sizes(1) == (1,)
    with pytest.raises(ValueError):
        bucket_sizes(0)


def test_engine_pads_to_buckets_and_slices_back():
    model = RecordingModel()
    engine = BatchEngine(model, max_batch=8)
    for n in (1, 3, 5, 8, 3):
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        y = engine.run(x)
        assert y.shape == (n, 3)
        np.testing.assert_allclose(y, x * 2)
    # executed shapes are bucket shapes, and a repeated bucket reuses it
    assert [s[0] for s in model.shapes] == [1, 4, 8, 8, 4]
    assert engine.compile_count == 3            # buckets 1, 4, 8
    assert engine.run_count == 5
    assert engine.rows_served == 1 + 3 + 5 + 8 + 3


def test_engine_warmup_then_zero_recompiles():
    jax = pytest.importorskip("jax")
    traces = []

    @jax.jit
    def model(x):
        traces.append(x.shape)          # trace-time only: one per compile
        return x * 3.0

    engine = BatchEngine(model, max_batch=8, input_shape=(4,))
    assert engine.warmup() == len(engine.buckets) == 4
    assert len(traces) == 4             # jit really compiled once a bucket
    rng = np.random.default_rng(0)
    for n in (1, 2, 3, 5, 7, 8, 6, 4):
        x = rng.normal(size=(n, 4)).astype(np.float32)
        np.testing.assert_allclose(engine.run(x), x * 3.0, rtol=1e-6)
    assert engine.compile_count == 4    # flat after warmup...
    assert len(traces) == 4             # ...and jit agrees: no recompiles


def test_engine_rejects_oversize_and_bad_shape():
    engine = BatchEngine(RecordingModel(), max_batch=4)
    with pytest.raises(ValueError, match="max_batch"):
        engine.run(np.zeros((5, 3), np.float32))
    with pytest.raises(ValueError, match="input shape"):
        engine.run(np.zeros((2, 7), np.float32))


def test_engine_skips_padding_for_dynamic_backends():
    model = RecordingModel()
    model.static_shapes = False         # the NativeForward contract
    engine = BatchEngine(model, max_batch=8)
    engine.run(np.zeros((3, 3), np.float32))
    assert [s[0] for s in model.shapes] == [3]   # exact size, no pad
    assert engine.compile_count == 0


# -- micro-batcher contract --------------------------------------------------

def test_batcher_coalesces_requests_queued_behind_a_batch():
    batcher, model = make_batcher(delay_s=0.05, max_batch=8)
    try:
        # the worker picks up the first request alone; the rest arrive
        # while the engine sleeps and must coalesce into ONE batch
        first = batcher.submit(np.full((1, 3), 0.0, np.float32))
        time.sleep(0.02)
        rest = [batcher.submit(np.full((1, 3), float(i + 1), np.float32))
                for i in range(5)]
        outs = [f.result(timeout=10) for f in [first] + rest]
        for i, out in enumerate(outs):
            np.testing.assert_allclose(out, np.full((1, 3), 2.0 * i))
        sizes = {int(k): v
                 for k, v in batcher.metrics.snapshot()
                 ["batch_size_histogram"].items()}
        assert max(sizes) >= 5          # the stragglers rode one batch
    finally:
        batcher.stop()


def test_deadline_expired_request_gets_timeout_error_not_silent_drop():
    batcher, _ = make_batcher(delay_s=0.15, max_batch=8)
    try:
        slow = batcher.submit(np.zeros((1, 3), np.float32))
        time.sleep(0.02)                # worker is inside the 150 ms run
        doomed = batcher.submit(np.zeros((1, 3), np.float32),
                                timeout_s=0.05)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10)
        assert slow.result(timeout=10).shape == (1, 3)
        snap = batcher.metrics.snapshot()
        assert snap["timed_out"] == 1
        assert snap["completed"] == 1
    finally:
        batcher.stop()


def test_queue_full_rejects_immediately():
    batcher, _ = make_batcher(delay_s=0.1, max_batch=8, max_queue=1)
    try:
        served = batcher.submit(np.zeros((1, 3), np.float32))
        time.sleep(0.03)                # worker popped it, engine busy
        queued = batcher.submit(np.zeros((1, 3), np.float32))
        t0 = time.monotonic()
        with pytest.raises(QueueFull):
            batcher.submit(np.zeros((1, 3), np.float32))
        assert time.monotonic() - t0 < 0.5      # fast failure, no wait
        assert batcher.metrics.snapshot()["rejected"] == 1
        for f in (served, queued):
            assert f.result(timeout=10) is not None
    finally:
        batcher.stop()


def test_oversize_request_is_chunked_and_reassembled_in_order():
    batcher, model = make_batcher(max_batch=4)
    try:
        x = np.arange(11 * 3, dtype=np.float32).reshape(11, 3)
        out = batcher.predict(x)
        assert out.shape == (11, 3)
        np.testing.assert_allclose(out, x * 2)  # rows in submission order
        assert max(s[0] for s in model.shapes) <= 4
        snap = batcher.metrics.snapshot()
        assert snap["admitted"] == 1 and snap["completed"] == 1
    finally:
        batcher.stop()


def test_shutdown_drains_inflight_requests():
    batcher, _ = make_batcher(delay_s=0.03, max_batch=1)
    futures = [batcher.submit(np.full((1, 3), float(i), np.float32))
               for i in range(5)]
    batcher.stop(drain=True)            # rejects new, services queued
    for i, f in enumerate(futures):
        np.testing.assert_allclose(f.result(timeout=0.1),
                                   np.full((1, 3), 2.0 * i))
    with pytest.raises(QueueFull):
        batcher.submit(np.zeros((1, 3), np.float32))


def test_stop_without_drain_fails_queued_loudly():
    batcher, _ = make_batcher(delay_s=0.1, max_batch=1)
    first = batcher.submit(np.zeros((1, 3), np.float32))
    time.sleep(0.03)
    queued = batcher.submit(np.zeros((1, 3), np.float32))
    batcher.stop(drain=False)
    assert first.result(timeout=10) is not None     # in-flight finishes
    with pytest.raises(QueueFull):
        queued.result(timeout=10)


def test_expired_chunk_at_queue_head_cannot_overflow_the_batch():
    """Coalescing must size-check the chunk it actually takes, not the
    queue head: an expired head chunk being skipped must not let a
    larger chunk behind it push the batch past max_batch."""
    batcher, _ = make_batcher(delay_s=0.1, max_batch=8)
    try:
        busy = batcher.submit(np.zeros((1, 3), np.float32))
        time.sleep(0.02)                # worker inside the 100 ms run
        c1 = batcher.submit(np.full((5, 3), 1.0, np.float32))
        doomed = batcher.submit(np.zeros((2, 3), np.float32),
                                timeout_s=0.03)     # expires mid-run
        c3 = batcher.submit(np.full((8, 3), 3.0, np.float32))
        np.testing.assert_allclose(c1.result(timeout=10),
                                   np.full((5, 3), 2.0))
        np.testing.assert_allclose(c3.result(timeout=10),
                                   np.full((8, 3), 6.0))
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10)
        assert busy.result(timeout=10) is not None
        snap = batcher.metrics.snapshot()
        assert snap["errors"] == 0      # no oversize batch hit the engine
        assert max(int(k) for k in snap["batch_size_histogram"]) <= 8
    finally:
        batcher.stop()


def test_mismatched_widths_fail_the_batch_not_the_worker():
    """With no declared input_shape the width check happens at
    concatenation; a mismatched batch must fail its requests and leave
    the worker serving."""
    def bare_model(x):                  # no input_shape attribute
        time.sleep(0.03)
        return np.asarray(x) * 2.0

    engine = BatchEngine(bare_model, max_batch=8)
    batcher = MicroBatcher(engine, max_wait_ms=5.0)
    try:
        busy = batcher.submit(np.zeros((1, 3), np.float32))
        time.sleep(0.01)                # next two coalesce behind it
        a = batcher.submit(np.zeros((1, 3), np.float32))
        b = batcher.submit(np.zeros((1, 5), np.float32))
        assert busy.result(timeout=10) is not None
        failures = 0
        for f in (a, b):
            try:
                f.result(timeout=10)
            except Exception:
                failures += 1
        assert failures >= 1            # the mismatch surfaced loudly
        out = batcher.predict(np.ones((1, 3), np.float32))   # still alive
        np.testing.assert_allclose(out, np.full((1, 3), 2.0))
    finally:
        batcher.stop()


def test_cancelled_future_does_not_kill_the_worker():
    batcher, _ = make_batcher(delay_s=0.05, max_batch=8)
    try:
        busy = batcher.submit(np.zeros((1, 3), np.float32))
        time.sleep(0.01)                # worker inside the engine run
        gone = batcher.submit(np.full((1, 3), 5.0, np.float32))
        assert gone.cancel()            # client walks away pre-service
        assert busy.result(timeout=10) is not None
        # the worker survived servicing the cancelled chunk
        after = batcher.predict(np.full((1, 3), 7.0, np.float32))
        np.testing.assert_allclose(after, np.full((1, 3), 14.0))
    finally:
        batcher.stop()


def test_non_positive_timeout_is_rejected_not_infinite():
    batcher, _ = make_batcher()
    try:
        for bad in (0, -1):
            with pytest.raises(ValueError, match="timeout_s"):
                batcher.submit(np.zeros((1, 3), np.float32), timeout_s=bad)
    finally:
        batcher.stop()


def test_never_admittable_request_is_bad_input_not_backpressure():
    """A request needing more chunks than the whole queue can hold must
    fail as ValueError (HTTP 400), not a retryable-looking QueueFull."""
    batcher, _ = make_batcher(max_batch=2, max_queue=3)
    try:
        with pytest.raises(ValueError, match="never|whole queue"):
            batcher.submit(np.zeros((8, 3), np.float32))   # 4 chunks > 3
        assert batcher.metrics.snapshot()["rejected"] == 0
    finally:
        batcher.stop()


def test_engine_failure_fails_the_batch_but_not_the_batcher():
    class Flaky(RecordingModel):
        def __call__(self, x):
            if float(np.asarray(x).ravel()[0]) < 0:
                raise RuntimeError("poison batch")
            return super().__call__(x)

    engine = BatchEngine(Flaky(), max_batch=4)
    batcher = MicroBatcher(engine, max_wait_ms=1.0)
    try:
        bad = batcher.submit(np.full((1, 3), -1.0, np.float32))
        with pytest.raises(RuntimeError, match="poison"):
            bad.result(timeout=10)
        good = batcher.predict(np.full((1, 3), 1.0, np.float32))
        np.testing.assert_allclose(good, np.full((1, 3), 2.0))
        assert batcher.metrics.snapshot()["errors"] == 1
    finally:
        batcher.stop()


def test_failed_request_ledger_closes_exactly():
    """ISSUE 10 small fix: `errors` counts failed BATCHES; `failed`
    counts failed REQUESTS (whatever the cause — engine error,
    deadline, shutdown flush), so admitted == completed + failed holds
    with equality, not >=."""
    class Flaky(RecordingModel):
        def __call__(self, x):
            if float(np.asarray(x).ravel()[0]) < 0:
                raise RuntimeError("poison batch")
            return super().__call__(x)

    engine = BatchEngine(Flaky(), max_batch=4)
    batcher = MicroBatcher(engine, max_wait_ms=1.0)
    try:
        bad = batcher.submit(np.full((1, 3), -1.0, np.float32))
        with pytest.raises(RuntimeError):
            bad.result(timeout=10)      # rides its own poisoned batch
        ok = batcher.submit(np.full((1, 3), 1.0, np.float32))
        assert ok.result(timeout=10) is not None
        # a deadline lapse is also a failed request in the ledger
        busy = batcher.submit(np.full((1, 3), 2.0, np.float32))
        doomed = None
        engine.model.delay_s = 0.15
        busy2 = batcher.submit(np.full((1, 3), 3.0, np.float32))
        time.sleep(0.02)
        doomed = batcher.submit(np.full((1, 3), 4.0, np.float32),
                                timeout_s=0.05)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10)
        for f in (busy, busy2):
            assert f.result(timeout=10) is not None
    finally:
        batcher.stop()
    snap = batcher.metrics.snapshot()
    assert snap["errors"] == 1          # one poisoned batch
    assert snap["timed_out"] == 1
    assert snap["failed"] == 2          # the poisoned + the timed out
    assert snap["admitted"] == snap["completed"] + snap["failed"]


# -- acceptance load test ----------------------------------------------------

def test_load_concurrent_clients_coalesce_with_zero_recompiles():
    """ISSUE acceptance: >= 8 threaded clients, coalesced batches > 1,
    zero engine recompiles after bucket warmup, and every admitted
    request gets exactly one correct response."""
    jax = pytest.importorskip("jax")
    traces = []

    @jax.jit
    def model(x):
        traces.append(x.shape)
        return x * 2.0

    engine = BatchEngine(model, max_batch=16, input_shape=(4,))
    engine.warmup()
    compiles_after_warmup = engine.compile_count
    traces_after_warmup = len(traces)
    batcher = MicroBatcher(engine, max_wait_ms=5.0, max_queue=256,
                           default_timeout_s=60.0)
    n_clients, per_client = 8, 20
    errors, results = [], {}

    def client(cid):
        rng = np.random.default_rng(cid)
        try:
            for i in range(per_client):
                n = int(rng.integers(1, 4))
                x = rng.normal(size=(n, 4)).astype(np.float32)
                y = batcher.predict(x)
                np.testing.assert_allclose(y, x * 2.0, rtol=1e-6)
                results[(cid, i)] = y.shape
        except Exception as exc:  # noqa: BLE001 — surface in main thread
            errors.append((cid, repr(exc)))

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    batcher.stop()
    assert not errors, errors
    # exactly one response per admitted request, no drops, no duplicates
    assert len(results) == n_clients * per_client
    snap = batcher.metrics.snapshot()
    assert snap["admitted"] == snap["completed"] == n_clients * per_client
    assert snap["rejected"] == 0 and snap["timed_out"] == 0
    # real coalescing happened
    sizes = {int(k): v for k, v in snap["batch_size_histogram"].items()}
    assert max(sizes) > 1, f"no coalescing observed: {sizes}"
    # zero recompiles after warmup — engine counter AND jit trace count
    assert engine.compile_count == compiles_after_warmup
    assert len(traces) == traces_after_warmup
    assert snap["latency"]["count"] == n_clients * per_client
    assert snap["qps"] > 0


# -- metrics -----------------------------------------------------------------

def test_latency_histogram_percentiles_land_in_bucket():
    m = ServingMetrics()
    for ms in (1.2, 1.4, 1.6, 1.8, 90.0):
        m.on_complete(ms / 1000.0)
    snap = m.snapshot()["latency"]
    assert snap["count"] == 5
    assert 1.0 <= snap["p50_ms"] <= 2.0         # bucket (1, 2]
    assert 50.0 <= snap["p99_ms"] <= 100.0      # bucket (50, 100]
    assert snap["buckets_ms"]["2"] == 4
    assert snap["buckets_ms"]["100"] == 1


def test_metrics_snapshot_is_json_roundtrippable():
    m = ServingMetrics()
    m.on_admit(2)
    m.on_batch(2)
    m.on_dequeue(2)
    m.on_complete(0.003)
    doc = json.loads(json.dumps(m.snapshot()))
    assert doc["admitted"] == 1 and doc["queue_depth"] == 0
    assert doc["batch_size_histogram"] == {"2": 1}


# -- HTTP front end + web_status + CLI --------------------------------------

def _http_json(url, data=None, timeout=10):
    req = urllib.request.Request(
        url, data=None if data is None else json.dumps(data).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_serve_server_endpoints():
    server = ServeServer(RecordingModel(), max_batch=8, max_wait_ms=1.0)
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    try:
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        out = _http_json(f"{base}/predict", {"input": x.tolist()})
        np.testing.assert_allclose(np.asarray(out["output"]), x * 2)
        assert _http_json(f"{base}/healthz")["status"] == "ok"
        snap = _http_json(f"{base}/metrics")
        assert snap["serving"]["completed"] == 1
        # 4 warmup batches (one per bucket) + the one request
        assert snap["engine"]["run_count"] == 5
        assert snap["engine"]["compile_count"] == 4
        assert snap["engine"]["buckets"] == [1, 2, 4, 8]
        meta = _http_json(f"{base}/")
        assert meta["n_requests"] == 1 and meta["max_batch"] == 8
        # malformed request -> 400; wrong path -> 404
        for path, data, code in (("/predict", {"wrong": 1}, 400),
                                 ("/nope", {"input": [[0.0] * 3]}, 404)):
            try:
                _http_json(f"{base}{path}", data)
                raise AssertionError(f"{path} accepted")
            except urllib.error.HTTPError as exc:
                assert exc.code == code
    finally:
        server.stop()


def test_serve_server_backpressure_maps_to_503():
    server = ServeServer(RecordingModel(delay_s=0.3), max_batch=1,
                         max_queue=1, max_wait_ms=1.0)
    port = server.start()
    url = f"http://127.0.0.1:{port}/predict"
    doc = {"input": [[0.0] * 3]}
    background = [threading.Thread(target=_http_json, args=(url, doc))
                  for _ in range(2)]
    try:
        background[0].start()           # worker picks this up
        time.sleep(0.1)
        background[1].start()           # sits in the queue: now full
        time.sleep(0.1)
        try:
            _http_json(url, doc)
            raise AssertionError("admitted past a full queue")
        except urllib.error.HTTPError as exc:
            assert exc.code == 503
            assert exc.headers.get("Retry-After") == "1"
    finally:
        for t in background:
            t.join(timeout=30)
        server.stop()


def test_stop_drains_before_closing_listener():
    """During ServeServer.stop(drain=True) the listener must stay up so
    /healthz reports 503 draining (load balancers bleed traffic off)
    instead of connection-refused."""
    server = ServeServer(RecordingModel(delay_s=0.3), max_batch=1,
                         max_wait_ms=1.0)
    port = server.start()
    fut = server.batcher.submit(np.zeros((1, 3), np.float32))
    time.sleep(0.05)                    # worker inside the 300 ms run
    stopper = threading.Thread(target=server.stop)
    stopper.start()
    time.sleep(0.1)                     # stop() is blocked in the drain
    try:
        _http_json(f"http://127.0.0.1:{port}/healthz")
        raise AssertionError("healthz should be 503 during drain")
    except urllib.error.HTTPError as exc:
        assert exc.code == 503
        assert json.loads(exc.read())["status"] == "draining"
    stopper.join(timeout=30)
    assert not stopper.is_alive()
    assert fut.result(timeout=1) is not None    # drained, not dropped


def test_server_rejects_conflicting_max_batch():
    engine = BatchEngine(RecordingModel(), max_batch=8)
    with pytest.raises(ValueError, match="max_batch"):
        ServeServer(engine, max_batch=128)
    server = ServeServer(engine, max_batch=8)   # matching value is fine
    assert server.engine is engine
    server.batcher.stop()


def test_web_status_reports_serving_metrics():
    from znicz_tpu.web_status import WebStatus

    server = ServeServer(RecordingModel(), max_batch=4)
    server.batcher.predict(np.zeros((1, 3), np.float32))
    ws = WebStatus().register_serving("recording", server)
    snap = ws.snapshot()
    assert snap["serving"]["recording"]["serving"]["completed"] == 1
    assert snap["serving"]["recording"]["engine"]["max_batch"] == 4
    server.batcher.stop()


def _export_tiny_package(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.standard_workflow import StandardWorkflow
    from znicz_tpu.utils.export import export_forward

    prng.seed_all(23)
    w = StandardWorkflow(
        name="SrvCLI", loss_function="softmax",
        layers=[{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
                {"type": "softmax", "->": {"output_sample_shape": 3}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 3, "sample_shape": (6,), "n_train": 60,
                       "n_valid": 0, "minibatch_size": 20},
        decision_config={"max_epochs": 1})
    w.initialize(device=TPUDevice())
    w.run()
    pkg = str(tmp_path / "srv_cli.npz")
    export_forward(w, pkg)
    return pkg


def test_cli_serve_smoke_over_exported_package(tmp_path, capsys):
    from znicz_tpu.__main__ import main as cli_main

    pkg = _export_tiny_package(tmp_path)
    rc = cli_main(["serve", pkg, "--port", "0", "--max-batch", "8",
                   "--smoke-test"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["smoke"] == "ok"
    # warmup compiled every bucket; the smoke request recompiled nothing
    assert doc["metrics"]["engine"]["compile_count"] == 4
    assert doc["metrics"]["serving"]["completed"] == 1


def test_cli_serve_missing_package_fails_cleanly(capsys):
    from znicz_tpu.__main__ import main as cli_main

    assert cli_main(["serve", "/nonexistent/pkg.npz"]) == 2
    assert "cannot load" in capsys.readouterr().out


# -- chaos: kill-mid-request against an AOT-booted engine (ISSUE 9) ----------

def test_chaos_kill_mid_request_aot_boot_exact_terminal_responses(tmp_path):
    """Elastic-PR satellite: an AOT-booted engine (zero-JIT,
    ``compile_count == 0``) is crashed mid-traffic by injected
    ``serve.run`` faults.  Every admitted request still gets EXACTLY ONE
    terminal response (a result or an error — never silence, never a
    duplicate), and after the drain the engine has still compiled
    nothing: crash recovery must not smuggle recompiles into the
    zero-JIT serving contract."""
    pytest.importorskip("jax")
    from znicz_tpu.resilience import faults
    from znicz_tpu.utils.export import ExportedForward, attach_aot

    pkg = _export_tiny_package(tmp_path)
    attach_aot(pkg, max_batch=8)
    fwd = ExportedForward(pkg)
    assert fwd.aot_fallback_reason is None
    engine = BatchEngine(fwd, max_batch=8, input_shape=(6,))
    assert engine.warmup() == 0                 # AOT boot: nothing to JIT
    assert engine.compile_count == 0
    batcher = MicroBatcher(engine, max_wait_ms=2.0, max_queue=256,
                           default_timeout_s=60.0)
    plan = faults.FaultPlan(seed=11)
    for hit in (4, 9, 15):                      # three seeded mid-run kills
        plan.crash_at("serve.run", at_hit=hit)
    n_clients, per_client = 6, 8
    outcomes: dict = {}
    lock = threading.Lock()

    def client(cid):
        rng = np.random.default_rng(cid)
        for i in range(per_client):
            n = int(rng.integers(1, 5))
            x = rng.normal(size=(n, 6)).astype(np.float32)
            try:
                y = batcher.predict(x)
                kind = ("ok", y.shape)
            except Exception as exc:  # noqa: BLE001 — terminal error
                kind = ("error", type(exc).__name__)
            with lock:
                # exactly-once: a duplicate terminal response would
                # overwrite and be caught by the count below
                assert (cid, i) not in outcomes
                outcomes[(cid, i)] = kind

    with faults.active(plan):
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        batcher.stop(drain=True)
    assert len(plan.log) == 3, plan.log         # every armed kill fired
    assert len(outcomes) == n_clients * per_client
    oks = sum(1 for kind in outcomes.values() if kind[0] == "ok")
    errs = sum(1 for kind in outcomes.values() if kind[0] == "error")
    assert errs >= 1 and oks >= 1
    snap = batcher.metrics.snapshot()
    # ledger closes EXACTLY (ISSUE 10 small fix): "errors" counts the 3
    # failed BATCHES; "failed" counts the REQUESTS that rode them, so
    # admitted == completed + failed with no slack — nothing timed out,
    # nothing vanished in the drain
    assert snap["errors"] == 3
    assert snap["admitted"] == snap["completed"] + snap["failed"]
    assert snap["failed"] == errs
    assert snap["failed"] >= snap["errors"]
    assert snap["timed_out"] == 0
    assert snap["completed"] >= oks             # oversize requests chunk
    # THE satellite pin: chaos + drain never compiled anything
    assert engine.compile_count == 0
    assert engine.stats()["aot_count"] >= 1
