"""Interactive (stream-fed) loader, RESTful prediction serving, and the
DeviceBenchmark utility (SURVEY.md §3.3 Loaders ``interactive.py``/
``restful.py`` rows; §3.3 Accelerated units ``DeviceBenchmark`` row)."""

import json
import urllib.request

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.loader.interactive import InteractiveLoader


def make_interactive(**kwargs):
    prng.seed_all(31)
    w = Workflow(name="t")
    loader = InteractiveLoader(w, sample_shape=(6,), n_classes=3, **kwargs)
    loader.initialize(device=NumpyDevice())
    return loader


def test_interactive_loader_serves_fed_samples():
    loader = make_interactive(capacity=32, minibatch_size=8)
    rng = np.random.default_rng(0)
    data = rng.normal(size=(8, 6)).astype(np.float32)
    labels = np.arange(8, dtype=np.int32) % 3
    assert loader.feed(data, labels) == 8

    loader.run()
    assert loader.minibatch_class == TRAIN
    assert loader.minibatch_size == 8
    # every served row must be one of the fed samples with its label
    served = loader.minibatch_data.mem
    served_labels = loader.minibatch_labels.mem
    for row, lab in zip(served, served_labels):
        match = np.where((data == row).all(axis=1))[0]
        assert len(match) >= 1
        assert lab == labels[match[0]]


def test_interactive_loader_ring_wraps_and_grows():
    loader = make_interactive(capacity=16, minibatch_size=4)
    a = np.full((2, 6), 1.0, np.float32)
    loader.feed(a, np.zeros(2, np.int32))
    loader.run()
    assert set(np.unique(loader.minibatch_data.mem)) == {1.0}
    # feeding more samples makes them visible to later minibatches
    b = np.full((14, 6), 2.0, np.float32)
    loader.feed(b, np.ones(14, np.int32))
    assert loader.available == 16
    seen = set()
    for _ in range(8):
        loader.run()
        seen |= set(np.unique(loader.minibatch_data.mem))
    assert seen == {1.0, 2.0}


def test_interactive_loader_rejects_shape_and_empty():
    loader = make_interactive(capacity=8, minibatch_size=4)
    try:
        loader.feed(np.zeros((2, 5), np.float32))
        raise AssertionError("shape mismatch accepted")
    except ValueError:
        pass
    try:
        loader.run()
        raise AssertionError("served before any feed")
    except RuntimeError:
        pass


def test_interactive_online_training_learns(tmp_path):
    """Online training: a fused workflow trains on streamed batches."""
    from znicz_tpu.standard_workflow import StandardWorkflow

    prng.seed_all(17)
    w = StandardWorkflow(
        name="Online", loss_function="softmax",
        layers=[{"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
                {"type": "softmax", "->": {"output_sample_shape": 3}}],
        loader_name="interactive",
        loader_config={"sample_shape": (6,), "n_classes": 3,
                       "capacity": 96, "minibatch_size": 24},
        decision_config={"max_epochs": 6})
    rng = np.random.default_rng(5)
    centers = rng.normal(0, 2.0, (3, 6)).astype(np.float32)
    labels = rng.integers(0, 3, 96).astype(np.int32)
    data = centers[labels] + rng.normal(0, 0.3, (96, 6)).astype(np.float32)
    w.loader.feed(data, labels)
    w.initialize(device=TPUDevice())
    w.run()
    hist = w.decision.metrics_history
    assert hist[-1]["metric_train"] < hist[0]["metric_train"]


def _train_tiny_exported(tmp_path):
    from znicz_tpu.standard_workflow import StandardWorkflow
    from znicz_tpu.utils.export import ExportedForward, export_forward

    prng.seed_all(23)
    w = StandardWorkflow(
        name="Srv", loss_function="softmax",
        layers=[{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
                {"type": "softmax", "->": {"output_sample_shape": 3}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 3, "sample_shape": (6,), "n_train": 60,
                       "n_valid": 0, "minibatch_size": 20},
        decision_config={"max_epochs": 1})
    w.initialize(device=TPUDevice())
    w.run()
    pkg = str(tmp_path / "srv.npz")
    export_forward(w, pkg)
    return ExportedForward(pkg), pkg


def test_prediction_server_serves_exported_model(tmp_path):
    from znicz_tpu.loader.restful import PredictionServer

    model, pkg = _train_tiny_exported(tmp_path)
    server = PredictionServer(pkg, max_batch=16)
    port = server.start()
    try:
        x = np.random.default_rng(1).normal(size=(4, 6)).astype(np.float32)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps({"input": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            out = np.asarray(json.loads(r.read())["output"])
        np.testing.assert_allclose(out, model(x), rtol=1e-5, atol=1e-6)
        # metadata endpoint reports the package and request count
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=5) as r:
            meta = json.loads(r.read())
        assert meta["model"]["name"] == "Srv"
        assert meta["n_requests"] == 1
        # malformed request -> 400, not a crash
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict", data=b"{}",
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(bad, timeout=5)
            raise AssertionError("malformed request accepted")
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
    finally:
        server.stop()


def test_device_benchmark_reports_throughput():
    from znicz_tpu.core.accelerated_units import DeviceBenchmark

    result = DeviceBenchmark(size=128, reps=2).run(device=TPUDevice())
    assert result["gflops"] > 0
    assert result["size"] == 128
