"""Direct tests for the file-backed loader subsystem (VERDICT r2 weak #4:
~760 loader lines had zero direct coverage): IDX round-trips, the
streaming and full-batch image loaders over a synthesized PNG tree, the
fitted-normalizer registry incl. snapshot state, and the AlexNet
``file_image`` real-data path."""

import os

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.loader import mnist as mnist_mod
from znicz_tpu.loader.base import VALID, TRAIN
from znicz_tpu.loader.image import (FileImageLoader, FullBatchImageLoader,
                                    synthesize_image_dataset)
from znicz_tpu.loader.normalization import (NORMALIZER_REGISTRY,
                                            normalizer_factory,
                                            normalizer_from_state)


# -- IDX format -------------------------------------------------------------

@pytest.mark.parametrize("gz", [False, True])
@pytest.mark.parametrize("dtype,shape", [
    (np.uint8, (7, 28, 28)), (np.int32, (5,)), (np.float32, (3, 4, 2)),
])
def test_idx_roundtrip(tmp_path, gz, dtype, shape):
    rng = np.random.default_rng(1)
    arr = (rng.normal(0, 50, shape) + 100).astype(dtype)
    path = str(tmp_path / ("a.idx" + (".gz" if gz else "")))
    mnist_mod.write_idx(path, arr)
    back = mnist_mod.read_idx(path)
    assert back.dtype == dtype
    np.testing.assert_array_equal(back, arr)


def test_idx_reader_finds_gz_sibling(tmp_path):
    arr = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)
    mnist_mod.write_idx(str(tmp_path / "b.idx.gz"), arr)
    np.testing.assert_array_equal(
        mnist_mod.read_idx(str(tmp_path / "b.idx")), arr)


def test_idx_rejects_non_idx(tmp_path):
    path = tmp_path / "junk"
    path.write_bytes(b"\x01\x02\x03\x04garbage")
    with pytest.raises(ValueError, match="not an IDX file"):
        mnist_mod.read_idx(str(path))


def test_mnist_synthesis_version_bump_regenerates(tmp_path, monkeypatch):
    d = str(tmp_path / "mnist")
    prng.seed_all(2)
    w = Workflow(name="m")
    loader = mnist_mod.MnistLoader(w, data_dir=d, n_train=50, n_valid=20,
                                   minibatch_size=10,
                                   synth_sizes=(60, 30))
    loader.load_data()
    first = os.path.getmtime(os.path.join(d, ".synth_version"))
    # same version: files reused
    loader2 = mnist_mod.MnistLoader(Workflow(name="m2"), data_dir=d,
                                    n_train=50, n_valid=20,
                                    minibatch_size=10, synth_sizes=(60, 30))
    loader2.load_data()
    assert os.path.getmtime(os.path.join(d, ".synth_version")) == first
    # stale version marker: regenerated
    with open(os.path.join(d, ".synth_version"), "w") as f:
        f.write("0-stale")
    loader3 = mnist_mod.MnistLoader(Workflow(name="m3"), data_dir=d,
                                    n_train=50, n_valid=20,
                                    minibatch_size=10, synth_sizes=(60, 30))
    loader3.load_data()
    assert open(os.path.join(d, ".synth_version")).read() == \
        mnist_mod.SYNTH_VERSION
    np.testing.assert_array_equal(loader3.original_labels.mem,
                                  loader.original_labels.mem)


# -- directory-per-class image loaders --------------------------------------

@pytest.fixture(scope="module")
def png_tree(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("imgs"))
    synthesize_image_dataset(d, n_classes=4, n_per_class=10, size=(12, 10))
    return d


def make_image_loader(cls, d, seed=44, **kw):
    prng.seed_all(seed)
    w = Workflow(name="i")
    loader = cls(w, data_dir=d, sample_shape=(12, 10, 3),
                 valid_fraction=0.2, minibatch_size=8, **kw)
    loader.initialize(device=TPUDevice())
    return loader


def test_file_image_loader_end_to_end(png_tree):
    loader = make_image_loader(FileImageLoader, png_tree)
    assert loader.class_names == [f"class_{i:03d}" for i in range(4)]
    assert loader.class_lengths == [0, 8, 32]   # 20% of 10 per class
    seen_classes = []
    for _ in range(1 + 4):                       # 1 valid + 4 train batches
        loader.run()
        seen_classes.append(int(loader.minibatch_class))
        count = loader.minibatch_size
        data = loader.minibatch_data.mem[:count]
        labels = loader.minibatch_labels.mem[:count]
        assert data.shape[1:] == (12, 10, 3)
        assert np.isfinite(data).all()
        assert ((labels >= 0) & (labels < 4)).all()
        # normalized stream: roughly centered (mean_disp over [0,255])
        assert abs(float(data.mean())) < 0.5
    assert seen_classes == [VALID] + [TRAIN] * 4
    assert loader.epoch_ended


def test_file_image_split_is_deterministic_and_disjoint(png_tree):
    a = make_image_loader(FileImageLoader, png_tree, seed=44)
    b = make_image_loader(FileImageLoader, png_tree, seed=44)
    assert a._paths == b._paths
    np.testing.assert_array_equal(a._labels, b._labels)
    c = make_image_loader(FileImageLoader, png_tree, seed=45)
    assert set(c._paths) == set(a._paths)        # same files, another split
    assert c._paths != a._paths
    # valid/train partitions never overlap
    v = set(a._paths[:a.class_lengths[VALID]])
    t = set(a._paths[a.class_lengths[VALID]:])
    assert not v & t


def test_full_batch_image_loader_matches_streaming(png_tree):
    stream = make_image_loader(FileImageLoader, png_tree, seed=44)
    full = make_image_loader(FullBatchImageLoader, png_tree, seed=44)
    stream.run()
    full.run()
    np.testing.assert_allclose(full.minibatch_data.mem,
                               stream.minibatch_data.mem, rtol=1e-6)
    np.testing.assert_array_equal(full.minibatch_labels.mem,
                                  stream.minibatch_labels.mem)


def test_image_loader_state_roundtrip(png_tree):
    loader = make_image_loader(FileImageLoader, png_tree, seed=44)
    loader.run()
    state = loader.state_dict()
    assert "normalizer" in state and "meta" in state["normalizer"]
    fresh = make_image_loader(FileImageLoader, png_tree, seed=45)
    fresh.load_state_dict(state)
    np.testing.assert_allclose(fresh.normalizer.mean,
                               loader.normalizer.mean)
    assert fresh.epoch_number == loader.epoch_number


# -- normalizer registry ----------------------------------------------------

@pytest.mark.parametrize("name", sorted(NORMALIZER_REGISTRY))
def test_normalizer_fit_apply_reverse_state(name):
    rng = np.random.default_rng(5)
    data = (rng.normal(100, 40, (32, 6, 5)).astype(np.float32))
    norm = normalizer_factory(name)
    assert not norm.fitted
    norm.analyze(data)
    assert norm.fitted
    out = norm.normalize(data)
    assert out.shape == data.shape
    if name != "none":
        assert abs(float(out.mean())) < abs(float(data.mean()))
    if name != "exp":   # sigmoid saturates: only approximate inverse
        np.testing.assert_allclose(norm.denormalize(out), data,
                                   rtol=1e-3, atol=1e-2)
    # state roundtrip preserves the fit exactly
    meta, arrays = norm.state_dict()
    import json
    json.dumps(meta)   # meta must be JSON-able (snapshot header contract)
    back = normalizer_from_state(meta, arrays)
    np.testing.assert_allclose(back.normalize(data), out, rtol=1e-6)


def test_unfitted_normalizer_raises():
    norm = normalizer_factory("linear")
    with pytest.raises(RuntimeError, match="not fitted"):
        norm.normalize(np.zeros((2, 2), np.float32))


# -- snapshot integration + the AlexNet real-data path ----------------------

def test_mnist_workflow_snapshot_roundtrip(tmp_path):
    """Regression: loaders used to put the live normalizer OBJECT into
    state_dict, crashing the snapshotter's JSON header write."""
    from znicz_tpu.models import mnist_conv
    from znicz_tpu.snapshotter import (collect_state, restore_state,
                                       write_snapshot)

    prng.seed_all(31)
    w = mnist_conv.build(max_epochs=1, n_train=200, n_valid=100,
                         minibatch_size=50)
    w.initialize(device=TPUDevice())
    w.run()
    arrays, meta = collect_state(w)
    path = str(tmp_path / "m.npz")
    write_snapshot(path, arrays, meta)

    prng.seed_all(9)
    w2 = mnist_conv.build(max_epochs=1, n_train=200, n_valid=100,
                          minibatch_size=50)
    w2.initialize(device=TPUDevice())
    restore_state(w2, path)
    assert w2.loader.normalizer.vmin == w.loader.normalizer.vmin
    np.testing.assert_array_equal(w2.forwards[0].weights.map_read(),
                                  arrays["forward.0.weights"])


def test_restored_normalizer_renormalizes_fullbatch_data(tmp_path):
    """Full-batch loaders normalize at load time, BEFORE a snapshot
    restore swaps the normalizer in — the restore must re-normalize the
    served data with the restored stats (weights were trained under
    them), not leave the locally fitted scaling in place."""
    prng.seed_all(2)
    d = str(tmp_path / "mnist")
    loader = mnist_mod.MnistLoader(Workflow(name="a"), data_dir=d,
                                   n_train=60, n_valid=20,
                                   minibatch_size=10, synth_sizes=(80, 30),
                                   normalization_type="mean_disp")
    loader.load_data()
    state = loader.state_dict()

    # a loader over a DIFFERENT subset fits different per-pixel stats...
    loader2 = mnist_mod.MnistLoader(Workflow(name="b"), data_dir=d,
                                    n_train=30, n_valid=20,
                                    minibatch_size=10, synth_sizes=(80, 30),
                                    normalization_type="mean_disp")
    loader2.load_data()
    before = loader2.original_data.mem.copy()
    # ...until the snapshot normalizer is restored: data re-normalized
    state.pop("shuffled", None)
    loader2.load_state_dict({"normalizer": state["normalizer"],
                             "shuffled": {},
                             **{k: v for k, v in state.items()
                                if k not in ("normalizer", "shuffled")}})
    after = loader2.original_data.mem
    test_x, _ty, train_x, _y = loader2._load_raw()
    raw = np.concatenate([test_x, train_x]).astype(np.float32)
    ref = loader.normalizer.normalize(raw)[..., None]
    np.testing.assert_allclose(after, ref, rtol=1e-6)
    np.testing.assert_array_equal(loader2.normalizer.mean,
                                  loader.normalizer.mean)
    assert not np.allclose(after, before)   # restore actually re-scaled


def test_alexnet_file_image_epoch(tmp_path):
    """The AlexNet ``file_image`` build trains one epoch end to end over
    a real PNG tree (decode -> fitted mean_disp -> fused step)."""
    from znicz_tpu.models import alexnet

    d = str(tmp_path / "tree")
    synthesize_image_dataset(d, n_classes=4, n_per_class=12, size=(32, 32))
    prng.seed_all(3)
    w = alexnet.build(max_epochs=1, minibatch_size=8, n_classes=4,
                      input_size=32, loader_name="file_image",
                      loader_config={"data_dir": d, "valid_fraction": 0.25,
                                     "fit_samples": 16})
    w.initialize(device=TPUDevice())
    w.run()
    hist = w.decision.metrics_history
    assert len(hist) == 1
    assert w.loader.normalizer.fitted
    assert hist[0]["metric_validation"] <= 12.0   # 4 classes x 3 valid


def test_augmentation_mirror_and_crop(png_tree):
    """Reference ImageLoader's mirror/crop options: random on TRAIN
    (seeded, reproducible), deterministic center-crop + no mirror on
    VALID; served shape follows the crop."""
    d = png_tree

    def serve(seed, mb_class):
        prng.seed_all(seed)
        loader = FileImageLoader(
            Workflow(name=f"aug{seed}{mb_class}"), data_dir=d,
            sample_shape=(12, 10, 3), valid_fraction=0.25,
            minibatch_size=8, mirror=True, crop=(8, 8))
        loader.initialize(device=TPUDevice())
        # serve until we reach the requested class
        for _ in range(100):
            loader.run()
            if int(loader.minibatch_class) == mb_class:
                return loader.minibatch_data.mem.copy(), loader
        raise AssertionError("class never served")

    assert FileImageLoader(Workflow(name="p"), data_dir=d,
                           crop=(8, 8)).augmenting

    a1, loader = serve(7, TRAIN)
    a2, _ = serve(7, TRAIN)
    np.testing.assert_array_equal(a1, a2)          # seeded: reproducible
    assert a1.shape[1:] == (8, 8, 3)               # served crop shape
    b1, _ = serve(8, TRAIN)
    assert not np.array_equal(a1, b1)              # different stream

    # VALID: center crop, no mirror — the served rows must equal the
    # plain decode -> center-crop -> normalize of the same files
    v1, vloader = serve(7, VALID)
    idx = vloader.minibatch_indices.mem[:vloader.minibatch_size]
    from znicz_tpu.loader.image import _decode
    expected = np.stack([_decode(vloader._paths[i], (12, 10, 3))
                         for i in idx])
    expected = expected[:, 2:10, 1:9]              # center (12-8)//2=2, (10-8)//2=1
    expected = vloader.normalizer.normalize(expected)
    np.testing.assert_allclose(v1[:len(idx)], expected, rtol=1e-6)

    with pytest.raises(ValueError, match="exceeds"):
        FileImageLoader(Workflow(name="bad"), data_dir=d,
                        sample_shape=(12, 10, 3), crop=(16, 8))


def test_augmenting_full_batch_loader_trains_unpinned(png_tree):
    """full_batch_image + augmentation: the fused step must NOT pin the
    dataset (per-serve crops would be skipped), and the workflow still
    trains end to end."""
    from znicz_tpu.standard_workflow import StandardWorkflow

    d = png_tree
    prng.seed_all(11)
    w = StandardWorkflow(
        name="AugTrain",
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 32},
                 "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
                {"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}}],
        loss_function="softmax", loader_name="full_batch_image",
        loader_config={"data_dir": d, "sample_shape": (12, 10, 3),
                       "valid_fraction": 0.25, "minibatch_size": 10,
                       "mirror": True, "crop": (10, 8)},
        decision_config={"max_epochs": 6}, fused=True)
    w.initialize(device=TPUDevice())
    assert w.loader.augmenting
    assert w.step._dataset_dev is None              # pinning skipped
    w.run()
    hist = [int(h["metric_validation"]) for h in w.decision.metrics_history]
    assert hist[-1] < hist[0], hist                 # still learns


def test_ensemble_over_augmenting_loader(png_tree):
    """Ensemble evaluation must consume the SERVED view of an augmenting
    loader (center-crop + normalize), not the raw stored dataset."""
    from znicz_tpu.standard_workflow import StandardWorkflow
    from znicz_tpu.utils.ensemble import Ensemble

    d = png_tree

    def build():
        return StandardWorkflow(
            name="AugEns",
            layers=[{"type": "softmax", "->": {"output_sample_shape": 4},
                     "<-": {"learning_rate": 0.05,
                            "gradient_moment": 0.9}}],
            loss_function="softmax", loader_name="full_batch_image",
            loader_config={"data_dir": d, "sample_shape": (12, 10, 3),
                           "valid_fraction": 0.25, "minibatch_size": 10,
                           "mirror": True, "crop": (10, 8)},
            decision_config={"max_epochs": 3}, fused=True)

    ens = Ensemble(build, n_members=2, base_seed=50).train(TPUDevice())
    result = ens.test_classification()
    # shapes lined up (served geometry) and the committee scored
    assert result["n"] == ens.members[0].loader.class_lengths[1]
    assert 0 <= result["committee_err"] <= result["n"]
    assert len(result["member_errs"]) == 2


def test_alexnet_augment_recipe(tmp_path):
    """alexnet.build(loader_config={'augment': True}): the canonical
    crop+mirror recipe — decode at input+29, serve random input-size
    crops on TRAIN (Krizhevsky et al. 2012; the reference pipeline's
    augmentation options)."""
    from znicz_tpu.models import alexnet

    d = str(tmp_path / "tree")
    synthesize_image_dataset(d, n_classes=4, n_per_class=10, size=(61, 61))
    prng.seed_all(1)
    w = alexnet.build(max_epochs=1, minibatch_size=8, n_classes=4,
                      input_size=32, loader_name="file_image",
                      loader_config={"data_dir": d, "augment": True,
                                     "valid_fraction": 0.25,
                                     "fit_samples": 8})
    w.initialize(device=TPUDevice())
    assert w.loader.sample_shape == (61, 61, 3)       # decode size
    assert w.loader.crop == (32, 32) and w.loader.mirror
    assert w.loader.served_shape == (32, 32, 3)
    w.loader.run()
    assert w.loader.minibatch_data.mem.shape[1:] == (32, 32, 3)
    w.run()
    assert bool(w.decision.complete)


def test_alexnet_augment_rejects_non_image_loader():
    from znicz_tpu.models import alexnet

    with pytest.raises(ValueError, match="image-file loader"):
        alexnet.build(loader_config={"augment": True})


def test_scan_epoch_falls_back_for_augmenting_loader(png_tree):
    """scan_epoch needs the pinned dataset, which augmenting loaders
    refuse — the workflow must silently run the per-minibatch path (with
    augmentation applied) instead of crashing or skipping crops."""
    from znicz_tpu.core.config import root
    from znicz_tpu.standard_workflow import StandardWorkflow

    d = png_tree
    root.common.engine.scan_epoch = True
    try:
        prng.seed_all(11)
        w = StandardWorkflow(
            name="AugScan",
            layers=[{"type": "softmax", "->": {"output_sample_shape": 4},
                     "<-": {"learning_rate": 0.05}}],
            loss_function="softmax", loader_name="full_batch_image",
            loader_config={"data_dir": d, "sample_shape": (12, 10, 3),
                           "valid_fraction": 0.25, "minibatch_size": 10,
                           "mirror": True, "crop": (10, 8)},
            decision_config={"max_epochs": 3}, fused=True)
        w.initialize(device=TPUDevice())
        assert w.step._dataset_dev is None       # no pin, no scan fns
        assert not w.step._scan_idx_fns
        w.run()
    finally:
        root.common.engine.scan_epoch = False
    hist = [int(h["metric_validation"]) for h in w.decision.metrics_history]
    assert hist[-1] <= hist[0], hist


def test_augmented_training_resume_is_bit_exact(tmp_path):
    """Mid-run resume through an AUGMENTING loader reproduces the exact
    crop/mirror sequence: the augmentation stream is part of the
    snapshotted PRNG state, so the continued run is bit-identical."""
    from znicz_tpu.snapshotter import restore_state
    from znicz_tpu.standard_workflow import StandardWorkflow

    d = str(tmp_path / "tree")
    synthesize_image_dataset(d, n_classes=4, n_per_class=10, size=(12, 10))

    def build(snap_cfg=None):
        prng.seed_all(91)
        return StandardWorkflow(
            name="AugResume",
            layers=[{"type": "softmax", "->": {"output_sample_shape": 4},
                     "<-": {"learning_rate": 0.05,
                            "gradient_moment": 0.9}}],
            loss_function="softmax", loader_name="full_batch_image",
            loader_config={"data_dir": d, "sample_shape": (12, 10, 3),
                           "valid_fraction": 0.25, "minibatch_size": 10,
                           "mirror": True, "crop": (10, 8)},
            decision_config={"max_epochs": 4},
            snapshotter_config=snap_cfg, fused=True)

    snap_dir = tmp_path / "snaps"
    w_full = build({"directory": str(snap_dir), "prefix": "a",
                    "only_improved": False, "keep_all": True})
    w_full.initialize(device=TPUDevice())
    w_full.run()
    full_hist = w_full.decision.metrics_history
    assert len(full_hist) == 4

    w_res = build()
    w_res.initialize(device=TPUDevice())
    meta = restore_state(w_res, str(snap_dir / "a_2.npz"))
    assert meta["loader"]["epoch_number"] == 2
    w_res.run()
    assert w_res.decision.metrics_history == full_hist, \
        (w_res.decision.metrics_history, full_hist)
    w_full.stop()
    w_res.stop()
    np.testing.assert_array_equal(
        w_full.forwards[0].weights.map_read(),
        w_res.forwards[0].weights.map_read())
