"""ISSUE 14 — train-while-serve: the continuous-learning subsystem.

Covers the feedback spool's crash-safety + exactly-once cursor, the
streaming loader's determinism and snapshot replay, publish/adopt
machinery, the fleet-status satellite, the fingerprint cache
satellite, and the ACCEPTANCE overlap chaos drill: one trainer + two
serve workers on one box, training, serving, a seeded mid-stream
trainer SIGKILL and a seeded worker SIGKILL all overlapping a
publish-triggered rollout — ledger closes exactly, fleet converges on
the trainer's newest fingerprint, the resumed trainer's history is
bit-identical to an uninterrupted run, steady-state compile delta 0.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.learn.bridge import AdoptionBridge
from znicz_tpu.learn.publish import (latest_manifest, manifest_path,
                                     publish_package)
from znicz_tpu.learn.spool import (FeedbackSpool, SpoolGone, SpoolReader,
                                   SpoolTimeout, initial_cursor,
                                   list_segments, read_cursor_file)
from znicz_tpu.loader.spool import SpoolSequenceLoader
from znicz_tpu.observe import REGISTRY

CHARMAP = list("abcdefgh ")


def _fill_spool(directory, n=120, seed=7, lo=10, hi=40):
    sp = FeedbackSpool(directory)
    rng = np.random.default_rng(seed)
    for i in range(n):
        sp.append_generate(
            f"r{i}", rng.integers(0, len(CHARMAP), 6).tolist(),
            rng.integers(0, len(CHARMAP), int(rng.integers(lo, hi)))
            .tolist())
    sp.close()
    return sp


def _counter_value(name: str) -> float:
    snap = REGISTRY.snapshot_flat(skip_zero=False)
    return sum(v for k, v in snap.items() if k.startswith(name))


# ---------------------------------------------------------------------------
# spool primitives
# ---------------------------------------------------------------------------

def test_spool_round_trip_exactly_once(tmp_path):
    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=10)
    reader = SpoolReader(spool)
    c0 = initial_cursor(spool)
    recs, c1 = reader.read(c0, 10, wait_s=1.0)
    assert [r["rid"] for r in recs] == [f"r{i}" for i in range(10)]
    # exactly-once replay from a saved cursor
    again, c1b = reader.read(dict(c0), 10, wait_s=1.0)
    assert again == recs and c1b == c1
    # split reads land on the same cursor
    a, ca = reader.read(dict(c0), 4, wait_s=1.0)
    b, cb = reader.read(ca, 6, wait_s=1.0)
    assert a + b == recs and cb == c1
    # nothing more: bounded wait raises, never blocks forever
    with pytest.raises(SpoolTimeout):
        reader.read(c1, 1, wait_s=0.1)


def test_spool_torn_final_line_skipped_counted_replayed(tmp_path):
    """Satellite: a SIGKILL-torn final line is skipped with a counted
    ``znicz_learn_spool_torn_total``, never a loader crash, and the
    durable cursor replays exactly once."""
    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=9)
    seg = os.path.join(spool, "seg_00000000.jsonl")
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:          # SIGKILL mid-append: the last
        f.truncate(size - 5)             # record loses its tail
    # a respawned worker appends AFTER the fragment (newline-prefix
    # protocol: only the fragment is lost, not the new record)
    FeedbackSpool(spool).append_generate("r9", [1], [2, 3])
    torn0 = _counter_value("znicz_learn_spool_torn_total")
    reader = SpoolReader(spool)
    c0 = initial_cursor(spool)
    recs, c1 = reader.read(c0, 9, wait_s=1.0)
    assert [r["rid"] for r in recs] == \
        [f"r{i}" for i in range(8)] + ["r9"]
    assert _counter_value("znicz_learn_spool_torn_total") == torn0 + 1
    # exactly-once: the replay sees the identical record set (the torn
    # skip is part of the byte-stable stream)
    again, c1b = reader.read(dict(c0), 9, wait_s=1.0)
    assert again == recs and c1b == c1


def test_spool_rotation_retention_and_gone(tmp_path):
    spool = str(tmp_path / "spool")
    sp = FeedbackSpool(spool, segment_bytes=200, max_segments=3)
    for i in range(40):
        sp.append_generate(f"r{i}", list(range(8)), list(range(8)))
    segs = list_segments(spool)
    assert len(segs) <= 4 and segs[0] > 0   # old segments dropped
    assert _counter_value(
        "znicz_learn_spool_dropped_segments_total") > 0
    reader = SpoolReader(spool)
    with pytest.raises(SpoolGone):
        reader.read({"seg": 0, "offset": 0, "records": 0}, 1,
                    wait_s=0.1)
    # a cold start anchors at the oldest RETAINED segment
    recs, _ = reader.read(initial_cursor(spool), 3, wait_s=1.0)
    assert len(recs) == 3


def test_spool_end_cursor_canonical_across_later_rotation(tmp_path):
    """Review regression: a read satisfied exactly at a segment's end
    must return (seg, end) whether or not a later rotation exists —
    else a snapshot's stored span fails its replay check after the
    spool rolls (a false 'spool bytes changed' on every elastic
    resume)."""
    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=6)
    reader = SpoolReader(spool)
    recs, end = reader.read(initial_cursor(spool), 6, wait_s=1.0)
    assert end["seg"] == 0
    # the spool rolls AFTER the snapshot stored `end`
    tiny = FeedbackSpool(spool, segment_bytes=1, max_segments=4)
    tiny.append_generate("later", [1], [2])
    assert list_segments(spool)[-1] > 0
    again, end2 = reader.read(initial_cursor(spool), 6, wait_s=1.0)
    assert again == recs and end2 == end, \
        "end cursor drifted across the rotation"


def test_spool_lag_does_not_recount_torn(tmp_path):
    """Review regression: lag probes re-scan the backlog every epoch
    and must not re-increment the torn counter for the same dead
    line."""
    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=4)
    seg = os.path.join(spool, "seg_00000000.jsonl")
    with open(seg, "r+b") as f:
        f.truncate(os.path.getsize(seg) - 3)
    FeedbackSpool(spool).append_generate("after", [1], [2, 3])
    reader = SpoolReader(spool)
    before = _counter_value("znicz_learn_spool_torn_total")
    assert reader.lag(initial_cursor(spool)) == 4   # 3 intact + after
    assert reader.lag(initial_cursor(spool)) == 4
    assert _counter_value("znicz_learn_spool_torn_total") == before
    # the consuming read still counts it (once per consume)
    reader.read(initial_cursor(spool), 4, wait_s=1.0)
    assert _counter_value("znicz_learn_spool_torn_total") == before + 1


def test_spool_multi_writer_shared_order(tmp_path):
    """Two writer processes (simulated: two instances) interleave
    whole records into one total order both readers agree on."""
    spool = str(tmp_path / "spool")
    a, b = FeedbackSpool(spool), FeedbackSpool(spool)
    for i in range(20):
        (a if i % 2 else b).append_generate(f"w{i}", [i], [i, i])
    reader = SpoolReader(spool)
    recs, c = reader.read(initial_cursor(spool), 20, wait_s=1.0)
    assert sorted(r["rid"] for r in recs) == \
        sorted(f"w{i}" for i in range(20))
    again, c2 = reader.read(initial_cursor(spool), 20, wait_s=1.0)
    assert [r["rid"] for r in again] == [r["rid"] for r in recs]
    assert c2 == c


# ---------------------------------------------------------------------------
# streaming loader
# ---------------------------------------------------------------------------

def _make_loader(spool, **kw):
    kw.setdefault("seq_len", 8)
    kw.setdefault("records_per_epoch", 4)
    kw.setdefault("minibatch_size", 4)
    kw.setdefault("wait_timeout_s", 2.0)
    ld = SpoolSequenceLoader(None, spool_dir=spool, charmap=CHARMAP,
                             **kw)
    ld._common_init()
    return ld


def test_loader_deterministic_stream(tmp_path):
    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=200)
    prng.seed_all(3)
    first = _make_loader(spool)
    seen = []
    for _ in range(30):
        first._serve()
        seen.append((first.minibatch_data.mem.copy(),
                     first.epoch_number, first.minibatch_size))
    assert first.epoch_number > 2          # crossed epoch boundaries
    prng.seed_all(3)
    second = _make_loader(spool)
    for i in range(30):
        second._serve()
        assert np.array_equal(second.minibatch_data.mem, seen[i][0])
        assert second.epoch_number == seen[i][1]
        assert second.minibatch_size == seen[i][2]
    # the durable cursor file tracks the epoch floor
    cur = read_cursor_file(spool)
    assert cur is not None and cur["records"] > 0


def test_loader_snapshot_replay_exactly_once(tmp_path):
    """The snapshot cursor re-reads the exact stream span: a resumed
    loader serves bit-identical batches (the elastic-resume
    exactly-once pin, loader-level)."""
    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=200)
    prng.seed_all(3)
    ld = _make_loader(spool)
    state, pr = None, None
    while state is None:
        ld._serve()
        if ld.epoch_ended and ld.epoch_number == 2:
            state = ld.state_dict()
            pr = prng.state_dict()
    post = []
    for _ in range(10):
        ld._serve()
        post.append((ld.minibatch_data.mem.copy(),
                     ld.minibatch_labels.mem.copy()))
    prng.seed_all(3)                      # cold boot, then restore
    resumed = _make_loader(spool)
    prng.load_state_dict(pr)
    resumed.load_state_dict(state)
    for i in range(10):
        resumed._serve()
        assert np.array_equal(resumed.minibatch_data.mem, post[i][0])
        assert np.array_equal(resumed.minibatch_labels.mem, post[i][1])


def test_loader_restore_refuses_changed_charmap(tmp_path):
    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=40)
    prng.seed_all(3)
    ld = _make_loader(spool)
    ld._serve()
    state = ld.state_dict()
    state["charmap"] = list("xy")
    with pytest.raises(ValueError, match="charmap"):
        ld.load_state_dict(state)


def test_loader_pipelined_matches_sync(tmp_path):
    """The spool loader through the async BatchPrefetcher serves the
    byte-identical stream (the ISSUE 4 determinism contract extended
    to the streaming dataset)."""
    from znicz_tpu.pipeline import attach_prefetcher

    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=200)
    prng.seed_all(9)
    sync = _make_loader(spool)
    stream = []
    for _ in range(24):
        sync._serve()
        stream.append((sync.minibatch_data.mem.copy(),
                       sync.epoch_number, sync.minibatch_size))
    prng.seed_all(9)
    piped = _make_loader(spool)
    attach_prefetcher(piped, depth=2)
    try:
        for i in range(24):
            piped.numpy_run()
            assert np.array_equal(piped.minibatch_data.mem,
                                  stream[i][0]), f"batch {i} diverged"
            assert piped.epoch_number == stream[i][1]
    finally:
        piped.stop()


def test_records_trained_counter_moves(tmp_path):
    spool = str(tmp_path / "spool")
    _fill_spool(spool, n=40)
    before = _counter_value("znicz_learn_records_trained_total")
    prng.seed_all(3)
    _make_loader(spool)
    assert _counter_value("znicz_learn_records_trained_total") >= \
        before + 4


# ---------------------------------------------------------------------------
# fingerprint cache (satellite)
# ---------------------------------------------------------------------------

def test_package_fingerprint_cached_until_file_changes(tmp_path,
                                                       monkeypatch):
    import hashlib

    from znicz_tpu.utils import naming

    pkg = tmp_path / "pkg.npz"
    pkg.write_bytes(b"a" * 4096)
    calls = {"n": 0}
    real = hashlib.sha256

    def counting_sha256(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(naming.hashlib, "sha256", counting_sha256)
    fp1 = naming.package_fingerprint(str(pkg))
    fp2 = naming.package_fingerprint(str(pkg))
    assert fp1 == fp2 and calls["n"] == 1   # probe polling: no re-hash
    # an atomic replace (mtime/size move) re-hashes
    tmp = tmp_path / "pkg.npz.tmp"
    tmp.write_bytes(b"b" * 8192)
    os.replace(tmp, pkg)
    fp3 = naming.package_fingerprint(str(pkg))
    assert calls["n"] == 2
    assert fp3["sha256"] != fp1["sha256"] and fp3["bytes"] == 8192
    # mutation returned to the caller must not poison the cache
    fp3["sha256"] = "poison"
    assert naming.package_fingerprint(str(pkg))["sha256"] != "poison"


# ---------------------------------------------------------------------------
# publish + bridge + fleet-status satellite
# ---------------------------------------------------------------------------

class _FakeStep:
    """export_lm stand-in: writes deterministic bytes per 'epoch'."""

    def __init__(self):
        self.exports = 0

    def export_lm(self, path):
        self.exports += 1
        with open(path, "wb") as f:
            f.write(b"model-bytes-%d" % self.exports)
        return path


def test_publish_manifest_and_counter(tmp_path):
    step = _FakeStep()
    before = _counter_value("znicz_learn_publishes_total")
    doc = publish_package(step, str(tmp_path / "pub"), epoch=2, seq=1)
    assert os.path.isfile(doc["package"])
    assert os.path.isfile(manifest_path(str(tmp_path / "pub")))
    read = latest_manifest(str(tmp_path / "pub"))
    assert read == doc
    assert read["fingerprint"]["sha256"]
    assert _counter_value("znicz_learn_publishes_total") == before + 1


def test_publish_retention_bounds_the_dir(tmp_path):
    """Review regression: superseded packages are unlinked past
    ``keep`` — a long-running trainer must not grow the disk one dead
    package per K epochs.  The manifest's current package always
    survives."""
    pub = str(tmp_path / "pub")
    step = _FakeStep()
    for epoch in range(2, 13, 2):
        doc = publish_package(step, pub, epoch=epoch,
                              seq=epoch // 2, keep=2)
    pkgs = sorted(n for n in os.listdir(pub)
                  if n.startswith("lm_e") and n.endswith(".npz"))
    assert pkgs == ["lm_e00010.npz", "lm_e00012.npz"]
    assert os.path.isfile(doc["package"])
    assert latest_manifest(pub)["epoch"] == 12


class _FakePool:
    def __init__(self, sha):
        self.expected_fingerprint = {"sha256": sha}


class _FakeRollout:
    def __init__(self, pool, outcome="done"):
        self.pool = pool
        self.outcome = outcome
        self.started: list = []
        self.rolling = False

    def start(self, package):
        from znicz_tpu.utils.naming import package_fingerprint

        self.started.append(package)
        if self.outcome == "done":
            self.pool.expected_fingerprint = \
                package_fingerprint(package)

    def join(self, timeout_s=0):
        return {"state": self.outcome, "error": None
                if self.outcome == "done" else "gate failed"}

    def status(self):
        return {"state": "idle"}


def test_bridge_adopts_each_new_fingerprint_once(tmp_path):
    pub = str(tmp_path / "pub")
    step = _FakeStep()
    publish_package(step, pub, epoch=2, seq=1)
    pool = _FakePool("old-sha")
    rollout = _FakeRollout(pool)
    bridge = AdoptionBridge(pub, pool, rollout, poll_s=0.05)
    report = bridge.poll_once()
    assert report["state"] == "done" and len(rollout.started) == 1
    assert bridge.adoptions == 1 and bridge.last_adoption_s is not None
    # same manifest again: fleet already on it — no second rollout
    assert bridge.poll_once() is None and len(rollout.started) == 1
    # a NEW publish adopts again
    publish_package(step, pub, epoch=4, seq=2)
    assert bridge.poll_once()["state"] == "done"
    assert bridge.adoptions == 2


def test_bridge_failed_adoption_waits_for_new_publish(tmp_path):
    pub = str(tmp_path / "pub")
    step = _FakeStep()
    publish_package(step, pub, epoch=2, seq=1)
    pool = _FakePool("old-sha")
    rollout = _FakeRollout(pool, outcome="failed")
    bridge = AdoptionBridge(pub, pool, rollout, poll_s=0.05)
    assert bridge.poll_once()["state"] == "failed"
    assert bridge.failures == 1
    # the same bad sha is not retried (no rollout storm)...
    assert bridge.poll_once() is None and len(rollout.started) == 1
    # ...but a fresh publish is
    publish_package(step, pub, epoch=4, seq=2)
    bridge.poll_once()
    assert len(rollout.started) == 2


def test_fleet_status_surfaces_package_and_rollout_top_level(tmp_path):
    """Satellite: /fleet/status.json carries the fleet's current
    package fingerprint + rollout state top-level, so the learn bridge
    and operators gate adoption on one field."""
    from znicz_tpu.fleet.rollout import RollingUpdate
    from znicz_tpu.fleet.router import FleetRouter
    from znicz_tpu.fleet.workers import WorkerPool

    pkg = tmp_path / "pkg.npz"
    pkg.write_bytes(b"some-package-bytes")
    pool = WorkerPool(str(pkg), run_dir=str(tmp_path / "fleet"))
    try:
        router = FleetRouter(pool)
        router.attach_rollout(RollingUpdate(pool))
        doc = pool.aggregator.status_doc()
        assert doc["package"]["fingerprint"]["sha256"] == \
            pool.expected_fingerprint["sha256"]
        assert doc["package"]["converged"] is False   # no workers yet
        assert doc["rollout"]["state"] == "idle"
        assert "steps" not in doc["rollout"]
        # providers must not break the JSON surface
        json.dumps(doc)
        # a dead provider degrades to an error block, never a crash
        pool.aggregator.register_status_provider(
            "learn", lambda: (_ for _ in ()).throw(RuntimeError("x")))
        assert "error" in pool.aggregator.status_doc()["learn"]
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# the ACCEPTANCE overlap chaos drill
# ---------------------------------------------------------------------------

def _export_base_package(tmp) -> str:
    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.utils.export import export_lm

    params = init_params(np.random.default_rng(31), 2, 32, 4, 64,
                         len(CHARMAP))
    pkg = os.path.join(tmp, "lm.npz")
    export_lm(params, pkg, heads=4, charmap=CHARMAP, name="lm_v1")
    return pkg


def _trainer_argv(spool, pkg, pub):
    return ["znicz_tpu/learn/trainer_workflow.py",
            "-o", f"root.learn.spool_dir={spool}",
            "-o", f"root.learn.package={pkg}",
            "-o", f"root.learn.publish_dir={pub}",
            "-o", "root.learn.publish_every=2",
            "-o", "root.learn.max_epochs=4",
            "-o", "root.learn.records_per_epoch=6",
            # drill traffic records are 8 ids (2 prompt + 6 tokens):
            # the window (seq_len + 1) must fit inside one record
            "-o", "root.learn.seq_len=6",
            # 3 minibatches per epoch, so the run is long enough in
            # control-graph signals for the seeded at_hit=40 kill to
            # land mid-epoch (1 mb/epoch finished under the trigger)
            "-o", "root.learn.minibatch_size=2",
            "-o", "root.learn.wait_timeout_s=120",
            "--random-seed", "11"]


def _post_stream(base, prompt, max_tokens=6, timeout=90):
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                         "timeout_s": 60}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return [json.loads(raw) for raw in r]


def test_overlap_chaos_drill_train_serve_kill_rollout(tmp_path):
    """ISSUE 14 acceptance: trainer + 2 serve workers, training,
    serving, a seeded trainer SIGKILL and a seeded worker SIGKILL
    overlapping publish-triggered rollouts — zero lost admitted
    requests, fleet converges on the newest published fingerprint,
    resumed trainer history bit-identical to an uninterrupted run,
    post-drill steady-state compile delta 0."""
    from znicz_tpu.fleet.rollout import RollingUpdate
    from znicz_tpu.fleet.router import FleetRouter
    from znicz_tpu.fleet.workers import WorkerPool
    from znicz_tpu.resilience import faults
    from znicz_tpu.resilience.elastic import run_elastic
    from znicz_tpu.resilience.supervisor import SupervisorPolicy

    tmp = str(tmp_path)
    pkg = _export_base_package(tmp)
    spool = os.path.join(tmp, "spool")
    os.makedirs(spool)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ZNICZ_TPU_COMPILE_CACHE="off")
    pool = WorkerPool(
        pkg, plane="generate", env=env,
        worker_args=("--slots", "2", "--max-len", "48",
                     "--feedback-spool", spool),
        run_dir=os.path.join(tmp, "fleet"))
    router = None
    stop_traffic = threading.Event()
    results: list = []
    res_lock = threading.Lock()
    trainer_box: dict = {}
    try:
        pool.spawn()
        # the chaos victim: a seeded generate.step SIGKILL sized to
        # land while traffic + the publish-triggered rollout overlap
        victim_plan = faults.FaultPlan(seed=13).kill_at(
            "generate.step", at_hit=90).to_env()
        pool.spawn(env_extra={faults.PLAN_ENV_VAR: victim_plan})
        assert pool.wait_all_ready(timeout_s=240), pool.snapshot()
        pool.start_probes()
        router = FleetRouter(pool)
        rollout = RollingUpdate(pool)
        router.attach_rollout(rollout)
        port = router.start()
        base = f"http://127.0.0.1:{port}"
        pub = os.path.join(tmp, "publish")
        bridge = AdoptionBridge(pub, pool, rollout, poll_s=0.25)
        bridge.start()

        def client(cid: int) -> None:
            n = 0
            while not stop_traffic.wait(0.05):
                n += 1
                try:
                    lines = _post_stream(base,
                                         "ab" if cid % 2 else "cd")
                except urllib.error.HTTPError as exc:
                    exc.read()
                    with res_lock:
                        results.append(("rejected", exc.code))
                    continue
                except Exception as exc:  # noqa: BLE001 — judged below
                    with res_lock:
                        results.append(("broken", repr(exc)))
                    continue
                terminals = [ln for ln in lines if ln.get("done")]
                with res_lock:
                    if len(terminals) != 1 or lines[-1] != terminals[0]:
                        results.append(("bad_terminal", lines))
                    elif "error" in terminals[0]:
                        results.append(("errored", terminals[0]))
                    else:
                        results.append(("completed", n))

        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True) for c in range(3)]
        for t in threads:
            t.start()

        def train() -> None:
            # seeded mid-epoch SIGKILL; the supervisor resumes from
            # the newest snapshot with the spool cursor inside it
            plan = faults.FaultPlan(seed=5).kill_at("elastic.worker",
                                                    at_hit=40)
            try:
                trainer_box["report"] = run_elastic(
                    _trainer_argv(spool, pkg, pub),
                    os.path.join(tmp, "snaps"), workers=1, spmd=False,
                    env=env, fault_plans={0: plan},
                    run_dir=os.path.join(tmp, "trainer"),
                    policy=SupervisorPolicy(max_restarts=3))
            except Exception as exc:  # noqa: BLE001 — judged below
                trainer_box["error"] = exc

        trainer = threading.Thread(target=train, daemon=True)
        trainer.start()
        # the loop: traffic feeds the spool, the trainer trains +
        # publishes, the bridge rolls the fleet — wait for the FINAL
        # adoption (epoch-4 publish) to converge
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            if "error" in trainer_box:
                raise AssertionError(
                    f"trainer supervision failed: "
                    f"{trainer_box['error']!r}")
            manifest = latest_manifest(pub)
            if "report" in trainer_box and manifest is not None and \
                    not rollout.rolling and \
                    (pool.expected_fingerprint or {}).get("sha256") == \
                    manifest["fingerprint"]["sha256"]:
                break
            time.sleep(0.5)
        else:
            raise AssertionError(
                f"loop never converged: trainer={trainer_box}, "
                f"manifest={latest_manifest(pub)}, "
                f"rollout={rollout.status()}")
        time.sleep(1.0)                   # post-adoption traffic tail
        stop_traffic.set()
        for t in threads:
            t.join(timeout=120)
        bridge.stop()

        # -- the trainer was killed AND resumed, bit-exactly ---------
        report = trainer_box["report"]
        assert report.completed and report.restarts >= 1, \
            report.as_dict()
        assert report.resumed_from, "resume never used a snapshot"
        drill_history = json.load(open(os.path.join(
            tmp, "snaps", "history_0.json")))

        # -- zero lost admitted requests -----------------------------
        with res_lock:
            kinds: dict = {}
            for kind, _ in results:
                kinds[kind] = kinds.get(kind, 0) + 1
        assert not kinds.get("broken") and \
            not kinds.get("bad_terminal"), \
            f"lost/garbled streams: {kinds}; tail {results[-6:]}"
        assert kinds.get("completed", 0) >= 8, \
            f"too little completed traffic to trust the drill: {kinds}"
        ledger = router.snapshot()
        assert ledger["admitted"] == ledger["completed"] + \
            ledger["failed"] + ledger["client_gone"], ledger

        # -- the seeded worker kill fired and was replaced -----------
        assert pool.replacements >= 1, \
            "the victim worker's seeded SIGKILL never fired"
        assert bridge.adoptions >= 1 and bridge.last_adoption_s > 0

        # -- fleet converged on the trainer's NEWEST fingerprint -----
        manifest = latest_manifest(pub)
        assert manifest["epoch"] == 4
        pool.probe_once()
        shas = {(w.fingerprint or {}).get("sha256")
                for w in pool.workers()}
        assert shas == {manifest["fingerprint"]["sha256"]}, \
            f"torn mix after the drill: {pool.snapshot()}"
        status = pool.aggregator.status_doc()
        assert status["package"]["converged"] is True

        # -- steady state: compile delta 0 ---------------------------
        def get_json(url):
            with urllib.request.urlopen(url, timeout=15) as r:
                return json.loads(r.read())

        bases = [w.base for w in pool.workers()]
        before = [get_json(b + "/metrics")["decoder"]["compile_count"]
                  for b in bases]
        for _ in range(3):
            lines = _post_stream(base, "ef", max_tokens=4)
            assert lines[-1].get("done") and "error" not in lines[-1]
        after = [get_json(b + "/metrics")["decoder"]["compile_count"]
                 for b in bases]
        assert before == after, f"steady state recompiled: " \
                                f"{before} -> {after}"
    finally:
        stop_traffic.set()
        if router is not None:
            router.stop()
        pool.stop()

    # -- resumed history bit-identical to an uninterrupted run -------
    # the spool is frozen now (workers stopped): a clean trainer over
    # the SAME stream from the same origin must reproduce the drill
    # trainer's history exactly — the spool's append-time total order
    # is what makes "the next R records after cursor C" time-invariant
    from znicz_tpu.resilience.elastic import run_elastic
    from znicz_tpu.resilience.supervisor import SupervisorPolicy

    clean = run_elastic(
        _trainer_argv(spool, pkg, os.path.join(tmp, "publish_clean")),
        os.path.join(tmp, "snaps_clean"), workers=1, spmd=False,
        env=env, run_dir=os.path.join(tmp, "trainer_clean"),
        policy=SupervisorPolicy(max_restarts=1))
    assert clean.completed and clean.restarts == 0
    clean_history = json.load(open(os.path.join(
        tmp, "snaps_clean", "history_0.json")))
    assert drill_history == clean_history, (
        f"resumed trainer history diverged from the uninterrupted "
        f"run:\n{drill_history}\nvs\n{clean_history}")
    # and the published weights are byte-identical too
    clean_manifest = latest_manifest(os.path.join(tmp,
                                                  "publish_clean"))
    assert clean_manifest["fingerprint"]["sha256"] == \
        latest_manifest(os.path.join(tmp, "publish"))["fingerprint"][
            "sha256"]


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_learn_cli_rejects_bad_args(tmp_path, capsys):
    from znicz_tpu.learn.cli import learn_main

    pkg = tmp_path / "lm.npz"
    pkg.write_bytes(b"x")
    assert learn_main([str(pkg), "--workers", "0"]) == 2
    assert "--workers" in capsys.readouterr().err


def test_main_dispatches_learn(monkeypatch):
    import znicz_tpu.__main__ as main_mod

    called = {}

    def fake_learn_main(argv):
        called["argv"] = argv
        return 0

    import znicz_tpu.learn.cli as cli_mod
    monkeypatch.setattr(cli_mod, "learn_main", fake_learn_main)
    assert main_mod.main(["learn", "pkg.npz", "--workers", "2"]) == 0
    assert called["argv"] == ["pkg.npz", "--workers", "2"]
