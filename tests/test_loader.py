"""Tier-1 tests for the loader subsystem: epoch/class structure, tail
padding, deterministic shuffling (SURVEY.md §5 tier-3 loader tests)."""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.loader.base import VALID, TRAIN
from znicz_tpu.loader.synthetic import (SyntheticClassifierLoader,
                                        SyntheticRegressionLoader)


def make_loader(**kwargs):
    prng.seed_all(99)
    w = Workflow(name="t")
    loader = SyntheticClassifierLoader(
        w, n_classes=4, sample_shape=(6,), **kwargs)
    loader.initialize(device=NumpyDevice())
    return loader


def test_epoch_class_order_and_padding():
    # train=100, valid=40, minibatch=30 -> valid: 30+10pad, train: 30*3+10pad
    loader = make_loader(n_train=100, n_valid=40, minibatch_size=30)
    seen = []
    for _ in range(2 + 4):
        loader.run()
        seen.append((loader.minibatch_class, loader.minibatch_size,
                     loader.last_minibatch))
    assert seen == [
        (VALID, 30, False), (VALID, 10, True),
        (TRAIN, 30, False), (TRAIN, 30, False), (TRAIN, 30, False),
        (TRAIN, 10, True),
    ]
    assert loader.epoch_ended and loader.epoch_number == 1
    # padded tail rows are zeroed, indices -1
    assert np.all(loader.minibatch_indices.mem[10:] == -1)
    assert np.all(loader.minibatch_data.mem[10:] == 0)


def test_train_shuffles_per_epoch_deterministically():
    def epoch_indices(seed):
        prng.seed_all(seed)
        w = Workflow(name="t")
        loader = SyntheticClassifierLoader(
            w, n_classes=2, sample_shape=(3,), n_train=20, n_valid=0,
            minibatch_size=20)
        loader.initialize(device=NumpyDevice())
        out = []
        for _ in range(2):
            loader.run()
            out.append(loader.minibatch_indices.mem.copy())
        return out

    a1, a2 = epoch_indices(5)
    b1, b2 = epoch_indices(5)
    np.testing.assert_array_equal(a1, b1)   # deterministic across runs
    np.testing.assert_array_equal(a2, b2)
    assert not np.array_equal(a1, a2)       # reshuffled across epochs


def test_regression_loader_serves_targets():
    prng.seed_all(3)
    w = Workflow(name="t")
    loader = SyntheticRegressionLoader(w, sample_shape=(8,), target_shape=(2,),
                                       n_train=32, n_valid=8,
                                       minibatch_size=16)
    loader.initialize(device=NumpyDevice())
    loader.run()
    assert loader.minibatch_targets.shape == (16, 2)
    assert loader.minibatch_class == VALID
    idx = loader.minibatch_indices.mem[:loader.minibatch_size]
    np.testing.assert_array_equal(
        loader.minibatch_targets.mem[:8],
        loader.original_targets.mem[idx])
