"""Deconv/GDDeconv tests (SURVEY.md §3.1-§3.2 deconv rows): adjoint
identity vs the conv ops, numpy-vs-xla parity, gradient numeric check, and
the tier-2 conv autoencoder workflow."""

import numpy as np
import pytest

import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.memory import Array
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.ops import conv as conv_ops, deconv as deconv_ops
from znicz_tpu.standard_workflow import StandardWorkflow
from znicz_tpu.units.conv import Conv
from znicz_tpu.units.deconv import Deconv
from znicz_tpu.units.gd_deconv import GDDeconv


GEOM = dict(sliding=(2, 2), padding=(1, 1, 1, 1))


def test_deconv_is_conv_adjoint():
    """<conv(x), e> == <x, deconv(e)> for every geometry — the defining
    property of the transposed conv."""
    rng = np.random.default_rng(0)
    for sliding, padding in [((1, 1), (0, 0, 0, 0)), ((2, 2), (1, 1, 1, 1)),
                             ((2, 1), (1, 0, 2, 1))]:
        x = rng.normal(size=(2, 9, 8, 3)).astype(np.float64)
        w = rng.normal(size=(3, 3, 3, 5)).astype(np.float64)
        y = conv_ops.forward_linear(np, x, w, None, sliding, padding)
        e = rng.normal(size=y.shape)
        back = deconv_ops.forward(np, e, w, sliding, padding, x.shape)
        np.testing.assert_allclose((y * e).sum(), (x * back).sum(), rtol=1e-10)


def test_deconv_op_backend_parity():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 4, 4, 5)).astype(np.float32)
    w = rng.normal(size=(3, 3, 2, 5)).astype(np.float32)
    out_shape = deconv_ops.output_shape_for(x.shape, w.shape, **GEOM)
    y_np = deconv_ops.forward(np, x, w, GEOM["sliding"], GEOM["padding"],
                              out_shape)
    y_x = deconv_ops.forward(jnp, jnp.asarray(x), jnp.asarray(w),
                             GEOM["sliding"], GEOM["padding"], out_shape)
    np.testing.assert_allclose(np.asarray(y_x), y_np, rtol=1e-4, atol=1e-5)
    err = rng.normal(size=out_shape).astype(np.float32)
    ein_np, gw_np = deconv_ops.backward(np, x, w, err, **GEOM)
    ein_x, gw_x = deconv_ops.backward(jnp, jnp.asarray(x), jnp.asarray(w),
                                      jnp.asarray(err), **GEOM)
    np.testing.assert_allclose(np.asarray(ein_x), ein_np, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw_x), gw_np, rtol=1e-4, atol=1e-4)


def test_deconv_backward_numeric():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(1, 3, 3, 2)).astype(np.float64)
    w = rng.normal(size=(3, 3, 1, 2)).astype(np.float64)
    out_shape = deconv_ops.output_shape_for(x.shape, w.shape, (1, 1),
                                            (0, 0, 0, 0))
    err = rng.normal(size=out_shape)
    ein, gw = deconv_ops.backward(np, x, w, err, (1, 1), (0, 0, 0, 0))
    eps = 1e-6
    for arr, grad in ((x, ein), (w, gw)):
        flat = arr.ravel()
        for i in rng.choice(flat.size, 6, replace=False):
            old = flat[i]
            flat[i] = old + eps
            up = (deconv_ops.forward(np, x, w, (1, 1), (0, 0, 0, 0),
                                     out_shape) * err).sum()
            flat[i] = old - eps
            down = (deconv_ops.forward(np, x, w, (1, 1), (0, 0, 0, 0),
                                       out_shape) * err).sum()
            flat[i] = old
            np.testing.assert_allclose(grad.ravel()[i],
                                       (up - down) / (2 * eps), rtol=1e-6)


@pytest.mark.parametrize("device_cls", [NumpyDevice, TPUDevice])
def test_deconv_unit_standalone_and_gd(device_cls):
    prng.seed_all(5)
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 4, 4, 6)).astype(np.float32)
    w = Workflow(name="t")
    fwd = Deconv(w, n_kernels=6, kx=3, ky=3, n_channels=2, **GEOM)
    fwd.input = Array(x)
    fwd.initialize(device=device_cls())
    fwd.run()
    assert fwd.output.shape == (2, 7, 7, 2)
    gd = GDDeconv(w, learning_rate=0.1, gradient_moment=0.9)
    gd.link_from_forward(fwd)
    gd.err_output = Array(rng.normal(size=fwd.output.shape)
                          .astype(np.float32))
    gd.batch_size = 2
    gd.initialize(device=device_cls())
    w_before = fwd.weights.map_read().copy()
    gd.run()
    assert gd.err_input.shape == x.shape
    assert not np.allclose(fwd.weights.map_read(), w_before)


def test_deconv_tied_weights_follow_conv():
    prng.seed_all(6)
    rng = np.random.default_rng(4)
    x = rng.normal(size=(1, 8, 8, 1)).astype(np.float32)
    w = Workflow(name="t")
    conv = Conv(w, n_kernels=3, kx=3, ky=3)
    conv.input = Array(x)
    conv.initialize(device=NumpyDevice())
    conv.run()
    de = Deconv(w, n_kernels=3, kx=3, ky=3)
    de.link_conv_attrs(conv)
    de.input = Array(conv.output.map_read().copy())
    de.initialize(device=NumpyDevice())
    de.run()
    assert de.output.shape == x.shape
    assert de.weights.map_read() is not None
    with pytest.raises(RuntimeError):
        de.param_arrays()


@pytest.mark.parametrize("fused", [True, False])
def test_conv_autoencoder_workflow(fused):
    """Tier-2: conv -> deconv autoencoder on identity targets (reference:
    Deconv autoencoder workflow, BASELINE config 4)."""
    prng.seed_all(17)
    w = StandardWorkflow(
        name="ConvAE",
        layers=[
            {"type": "conv", "->": {"n_kernels": 4, "kx": 3, "ky": 3},
             "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
            {"type": "deconv", "->": {"n_kernels": 4, "kx": 3, "ky": 3,
                                      "n_channels": 1},
             "<-": {"learning_rate": 0.01, "gradient_moment": 0.9}},
        ],
        loss_function="mse", loader_name="synthetic_regression",
        loader_config={"sample_shape": (8, 8, 1), "identity": True,
                       "n_train": 128, "n_valid": 64, "minibatch_size": 32},
        decision_config={"max_epochs": 5}, fused=fused)
    w.initialize(device=TPUDevice())
    w.run()
    dec = w.decision
    assert bool(dec.complete)
    first = dec.metrics_history[0]["metric_validation"]
    last = dec.metrics_history[-1]["metric_validation"]
    assert last < first * 0.7, dec.metrics_history
