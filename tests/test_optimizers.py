"""Optimizer registry for the fused step (AdamW beyond the reference's
SGD+momentum): optax-oracle parity, convergence, snapshot round-trip,
and the fused-only guard."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.standard_workflow import StandardWorkflow


LAYERS = [{"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
          {"type": "softmax", "->": {"output_sample_shape": 4}}]


def build_adam(max_epochs=2, seed=55, lr=0.01, wd=0.001, **kwargs):
    prng.seed_all(seed)
    return StandardWorkflow(
        name="AdamWf", loss_function="softmax", layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 16},
             "<-": {"learning_rate": lr, "learning_rate_bias": lr,
                    "weights_decay": wd, "weights_decay_bias": wd}},
            {"type": "softmax",
             "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": lr, "learning_rate_bias": lr,
                    "weights_decay": wd, "weights_decay_bias": wd}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 4, "sample_shape": (6,), "n_train": 40,
                       "n_valid": 0, "minibatch_size": 40},
        decision_config={"max_epochs": max_epochs},
        optimizer="adam", **kwargs)


def test_fused_adam_matches_optax():
    """One-minibatch dataset: the fused adam trajectory equals optax's
    adamw applied to gradients of the same loss (shuffling only permutes
    rows within the single batch; the summed loss/grads are invariant)."""
    import optax

    lr, wd = 0.01, 0.001
    w = build_adam(max_epochs=5, lr=lr, wd=wd)
    w.initialize(device=TPUDevice())
    step = w.step
    # capture the (only) minibatch the workflow will train on — via the
    # HBM-pinned dataset + indices (serve_indices_only mode leaves
    # minibatch_data unfilled)
    w.loader.run()
    idx = np.maximum(np.asarray(w.loader.minibatch_indices.mem), 0)
    x0 = np.asarray(w.loader.original_data.mem)[idx].copy()
    y0 = np.asarray(w.loader.original_labels.mem)[idx].copy()
    params0 = [{k: np.asarray(jax.device_get(v)) for k, v in leaf.items()}
               for leaf in step._params]

    w.run()
    step.sync_to_units()
    trained = [{k: np.asarray(jax.device_get(v)) for k, v in leaf.items()}
               for leaf in step._params]
    # the capture above consumed epoch 0's only minibatch, so training
    # covered the remaining epochs; every epoch trains on the same rows
    # (one-minibatch dataset — reshuffling only permutes within it)
    n_steps = int(trained[0]["t"])
    assert n_steps >= 3

    # optax oracle on the identical loss geometry
    trainable = [{k: jnp.asarray(v) for k, v in leaf.items()
                  if k in ("w", "b")} for leaf in params0]

    def loss_fn(ps):
        out, logits_tail = step._forward_chain(ps, jnp.asarray(x0),
                                               train=True)
        loss, _ = step._loss_and_metrics(
            out, logits_tail, jnp.asarray(y0),
            jnp.ones(len(x0), bool))
        return loss / len(x0)

    opt = optax.adamw(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=wd)
    state = opt.init(trainable)
    ps = trainable
    for _ in range(n_steps):
        grads = jax.grad(loss_fn)(ps)
        updates, state = opt.update(grads, state, ps)
        ps = optax.apply_updates(ps, updates)
    for got, want in zip(trained, ps):
        for k in ("w", "b"):
            np.testing.assert_allclose(got[k], np.asarray(want[k]),
                                       rtol=2e-4, atol=1e-6,
                                       err_msg=k)


def test_adam_learns_faster_than_tiny_sgd():
    """Sanity: adam with its adaptive step actually trains (errors drop
    to ~0 on separable synthetic clusters)."""
    w = build_adam(max_epochs=12, lr=0.02)
    w.initialize(device=TPUDevice())
    w.run()
    hist = [h["metric_train"] for h in w.decision.metrics_history]
    assert hist[-1] <= hist[0] * 0.5, hist


def test_adam_snapshot_resume_bit_exact(tmp_path):
    """Interrupt/resume with adam state (second moments + step count)
    reproduces the uninterrupted run bit-exactly."""
    from znicz_tpu.snapshotter import collect_state, restore_state, \
        write_snapshot

    def final_weights(w):
        w.step.sync_to_units()
        return [np.asarray(f.weights.map_read()).copy()
                for f in w.forwards]

    # uninterrupted: 6 epochs
    w_full = build_adam(max_epochs=6, seed=99)
    w_full.initialize(device=TPUDevice())
    w_full.run()
    want = final_weights(w_full)

    # interrupted at 3, resumed to 6
    w_a = build_adam(max_epochs=3, seed=99)
    w_a.initialize(device=TPUDevice())
    w_a.run()
    arrays, meta = collect_state(w_a)
    snap = str(tmp_path / "adam.npz")
    write_snapshot(snap, arrays, meta)

    # same seed: the synthetic DATASET is generated at build time from
    # the prng (snapshots restore streams + shuffle order, not data)
    w_b = build_adam(max_epochs=6, seed=99)
    w_b.initialize(device=TPUDevice())
    restore_state(w_b, snap)
    # the snapshot was taken after w_a COMPLETED (max_epochs reached);
    # extending the run means lifting both the epoch cap and the stored
    # completion gate — exactly what continuing w_a in-process needs too
    w_b.decision.max_epochs = 6
    w_b.decision.complete.set(False)
    w_b.run()
    got = final_weights(w_b)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_adam_requires_fused():
    with pytest.raises(ValueError, match="requires fused"):
        build_adam(fused=False)


def test_unknown_optimizer_rejected():
    from znicz_tpu.parallel.step import FusedTrainStep

    with pytest.raises(ValueError, match="unknown optimizer"):
        FusedTrainStep(optimizer="rmsprop")


def test_cross_optimizer_resume_rejected(tmp_path):
    from znicz_tpu.snapshotter import collect_state, restore_state, \
        write_snapshot

    w_a = build_adam(max_epochs=1, seed=42)
    w_a.initialize(device=TPUDevice())
    w_a.run()
    arrays, meta = collect_state(w_a)
    assert meta["optimizer"] == "adam"
    snap = str(tmp_path / "x.npz")
    write_snapshot(snap, arrays, meta)

    prng.seed_all(42)
    # same architecture, default (sgd) optimizer
    w_b = StandardWorkflow(
        name="AdamWf", loss_function="softmax", layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
            {"type": "softmax", "->": {"output_sample_shape": 4}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 4, "sample_shape": (6,), "n_train": 40,
                       "n_valid": 0, "minibatch_size": 40},
        decision_config={"max_epochs": 1})
    w_b.initialize(device=TPUDevice())
    with pytest.raises(ValueError, match="snapshot optimizer"):
        restore_state(w_b, snap)


def test_adam_rejects_l1():
    prng.seed_all(8)
    w = StandardWorkflow(
        name="L1Adam", loss_function="softmax", layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
             "<-": {"l1_vs_l2": 0.5}},
            {"type": "softmax", "->": {"output_sample_shape": 4}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 4, "sample_shape": (6,), "n_train": 40,
                       "n_valid": 0, "minibatch_size": 40},
        decision_config={"max_epochs": 1}, optimizer="adam")
    with pytest.raises(ValueError, match="l1_vs_l2 is SGD-only"):
        w.initialize(device=TPUDevice())


def test_shard_update_matches_replicated(cpu_devices):
    """ZeRO-style sharded update (reduce-scatter grads, shard-local
    optimizer state, all-gather params — arXiv:2004.13336) trains
    identically to the replicated update on an 8-device mesh, for both
    optimizers."""
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    for opt in ("sgd", "adam"):
        weights = {}
        for mode in (False, True):
            prng.seed_all(31)
            w = build_fused(max_epochs=3, layers=(23,), minibatch_size=32,
                            n_train=160, n_valid=64,
                            mesh=data_parallel_mesh(8),
                            optimizer=opt, shard_update=mode)
            w.initialize(device=TPUDevice())
            w.run()
            w.step.sync_to_units()
            weights[mode] = {
                "w": [np.asarray(f.weights.map_read()).copy()
                      for f in w.forwards],
                "v": [np.asarray(g.gradient_weights.map_read()).copy()
                      for g in w.gds],
                "hist": [h["metric_validation"]
                         for h in w.decision.metrics_history],
            }
        assert weights[True]["hist"] == weights[False]["hist"], opt
        for a, b in zip(weights[True]["w"], weights[False]["w"]):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6,
                                       err_msg=opt)
        # momentum buffers reassemble from shards to the same state
        for a, b in zip(weights[True]["v"], weights[False]["v"]):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6,
                                       err_msg=opt)


def test_shard_update_adam_snapshot_roundtrip(tmp_path, cpu_devices):
    """Sharded optimizer state snapshots in the param shape and restores
    into a sharded run bit-exactly."""
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh
    from znicz_tpu.snapshotter import collect_state, restore_state, \
        write_snapshot

    def build(n):
        prng.seed_all(13)
        return build_fused(max_epochs=n, layers=(16,), minibatch_size=16,
                           n_train=64, n_valid=0,
                           mesh=data_parallel_mesh(8),
                           optimizer="adam", shard_update=True)

    w_full = build(4)
    w_full.initialize(device=TPUDevice())
    w_full.run()
    w_full.step.sync_to_units()
    want = [np.asarray(f.weights.map_read()).copy()
            for f in w_full.forwards]

    w_a = build(2)
    w_a.initialize(device=TPUDevice())
    w_a.run()
    arrays, meta = collect_state(w_a)
    # state arrays carry the PARAM shape, not the shard layout
    assert arrays["step.opt.0.sw"].shape == \
        w_a.forwards[0].weights.shape
    snap = str(tmp_path / "z.npz")
    write_snapshot(snap, arrays, meta)

    w_b = build(4)
    w_b.initialize(device=TPUDevice())
    restore_state(w_b, snap)
    w_b.decision.max_epochs = 4
    w_b.decision.complete.set(False)
    w_b.run()
    w_b.step.sync_to_units()
    got = [np.asarray(f.weights.map_read()).copy() for f in w_b.forwards]
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_shard_update_snapshot_restores_across_layouts(tmp_path,
                                                       cpu_devices):
    """State is stored in param shape, so a sharded-update run restores
    into a replicated one on a different mesh size (the elastic-resume
    story) and continues identically."""
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh
    from znicz_tpu.snapshotter import collect_state, restore_state, \
        write_snapshot

    def build(n_epochs, n_dev, shard):
        prng.seed_all(7)
        return build_fused(max_epochs=n_epochs, layers=(16,),
                           minibatch_size=16, n_train=64, n_valid=0,
                           mesh=data_parallel_mesh(n_dev),
                           optimizer="adam", shard_update=shard)

    # sharded over 8 devices, interrupted at 2 epochs
    w_a = build(2, 8, True)
    w_a.initialize(device=TPUDevice())
    w_a.run()
    arrays, meta = collect_state(w_a)
    snap = str(tmp_path / "x.npz")
    write_snapshot(snap, arrays, meta)

    # oracle: continue the SAME layout to 4 epochs
    w_o = build(4, 8, True)
    w_o.initialize(device=TPUDevice())
    w_o.run()
    w_o.step.sync_to_units()
    want = [np.asarray(f.weights.map_read()).copy()
            for f in w_o.forwards]

    # resume REPLICATED on a 2-device mesh from the sharded snapshot
    w_b = build(4, 2, False)
    w_b.initialize(device=TPUDevice())
    restore_state(w_b, snap)
    w_b.decision.max_epochs = 4
    w_b.decision.complete.set(False)
    w_b.run()
    w_b.step.sync_to_units()
    got = [np.asarray(f.weights.map_read()).copy() for f in w_b.forwards]
    for a, b in zip(got, want):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_clip_norm_matches_manual_oracle():
    """Global-norm clipping: one fused SGD step (zero momentum) equals
    w - lr * clip(g_mean); a huge threshold is a no-op."""
    import jax
    import jax.numpy as jnp

    def build(clip):
        prng.seed_all(91)
        return StandardWorkflow(
            name="ClipWf", loss_function="softmax", layers=[
                {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
                 "<-": {"learning_rate": 0.1, "learning_rate_bias": 0.1,
                        "gradient_moment": 0.0,
                        "gradient_moment_bias": 0.0}},
                {"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": {"learning_rate": 0.1, "learning_rate_bias": 0.1,
                        "gradient_moment": 0.0,
                        "gradient_moment_bias": 0.0}}],
            loader_name="synthetic_classifier",
            loader_config={"n_classes": 4, "sample_shape": (6,),
                           "n_train": 40, "n_valid": 0,
                           "minibatch_size": 40},
            decision_config={"max_epochs": 1}, clip_norm=clip)

    results = {}
    for clip in (0.5, 1e9):
        w = build(clip)
        w.initialize(device=TPUDevice())
        step = w.step
        w.loader.run()
        idx = np.maximum(np.asarray(w.loader.minibatch_indices.mem), 0)
        x0 = np.asarray(w.loader.original_data.mem)[idx]
        y0 = np.asarray(w.loader.original_labels.mem)[idx]
        p0 = [{k: np.asarray(jax.device_get(v))
               for k, v in leaf.items()} for leaf in step._params]
        step.run()
        p1 = [{k: np.asarray(jax.device_get(v))
               for k, v in leaf.items()} for leaf in step._params]
        results[clip] = (p0, p1, x0, y0, step)

    p0, p1, x0, y0, step = results[0.5]
    trainable = [{k: jnp.asarray(l[k]) for k in ("w", "b")} for l in p0]

    def loss_fn(ps):
        out, lt = step._forward_chain(ps, jnp.asarray(x0), train=True)
        loss, _ = step._loss_and_metrics(out, lt, jnp.asarray(y0),
                                         jnp.ones(len(x0), bool))
        return loss / len(x0)

    grads = jax.grad(loss_fn)(trainable)
    gnorm = float(jnp.sqrt(sum(jnp.sum(g * g)
                               for l in grads for g in l.values())))
    assert gnorm > 0.5          # threshold actually binds
    scale = 0.5 / gnorm
    for li, leaf in enumerate(grads):
        for k in ("w", "b"):
            want = p0[li][k] - 0.1 * scale * np.asarray(leaf[k])
            np.testing.assert_allclose(p1[li][k], want, rtol=1e-5,
                                       atol=1e-7, err_msg=f"{li}.{k}")
    # huge threshold: same update as the raw gradient
    p0u, p1u, _, _, _ = results[1e9]
    for li, leaf in enumerate(grads):
        for k in ("w", "b"):
            want = p0u[li][k] - 0.1 * np.asarray(leaf[k])
            np.testing.assert_allclose(p1u[li][k], want, rtol=1e-5,
                                       atol=1e-7)


def test_clip_norm_requires_fused():
    with pytest.raises(ValueError, match="clip_norm requires fused"):
        StandardWorkflow(
            name="x", loss_function="softmax",
            layers=[{"type": "softmax",
                     "->": {"output_sample_shape": 3}}],
            loader_name="synthetic_classifier",
            loader_config={"n_classes": 3, "sample_shape": (4,),
                           "n_train": 30, "n_valid": 0,
                           "minibatch_size": 30},
            decision_config={"max_epochs": 1}, fused=False, clip_norm=1.0)


def test_clip_norm_rejects_nonpositive():
    for bad in (0.0, -1.0):
        with pytest.raises(ValueError, match="must be positive"):
            StandardWorkflow(
                name="x", loss_function="softmax",
                layers=[{"type": "softmax",
                         "->": {"output_sample_shape": 3}}],
                loader_name="synthetic_classifier",
                loader_config={"n_classes": 3, "sample_shape": (4,),
                               "n_train": 30, "n_valid": 0,
                               "minibatch_size": 30},
                decision_config={"max_epochs": 1}, clip_norm=bad)


def _accum_build(minibatch, accumulate, optimizer="sgd", n_train=64,
                 max_epochs=3):
    prng.seed_all(61)
    return StandardWorkflow(
        name="AccWf", loss_function="softmax", layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 12},
             "<-": {"learning_rate": 0.05, "learning_rate_bias": 0.05,
                    "gradient_moment": 0.9, "gradient_moment_bias": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": {"learning_rate": 0.05, "learning_rate_bias": 0.05,
                    "gradient_moment": 0.9, "gradient_moment_bias": 0.9}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 4, "sample_shape": (6,),
                       "n_train": n_train, "n_valid": 0,
                       "minibatch_size": minibatch, "shuffle_limit": 0},
        decision_config={"max_epochs": max_epochs}, optimizer=optimizer,
        accumulate_steps=accumulate)


@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
def test_accumulation_matches_big_minibatch(optimizer):
    """Accumulating 4 minibatches of 16 applies the same updates as one
    minibatch of 64 over the same (unshuffled) data — summed grads and
    sample counts are identical, so the trajectories match."""
    import jax

    weights = {}
    for minibatch, accumulate in ((64, 1), (16, 4)):
        w = _accum_build(minibatch, accumulate, optimizer)
        w.initialize(device=TPUDevice())
        w.run()
        w.step.sync_to_units()
        weights[(minibatch, accumulate)] = [
            np.asarray(f.weights.map_read()).copy() for f in w.forwards]
        assert w.step._grad_acc is None       # no dangling accumulation
    for a, b in zip(weights[(64, 1)], weights[(16, 4)]):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6,
                                   err_msg=optimizer)


def test_accumulation_ragged_tail_applies_at_epoch_end():
    """A train pass shorter than accumulate_steps still applies its
    gradients at the pass boundary (no leak into the next epoch)."""
    w = _accum_build(16, 4, n_train=48, max_epochs=4)
    w.initialize(device=TPUDevice())
    w.run()
    assert w.step._grad_acc is None
    hist = [h["metric_train"] for h in w.decision.metrics_history]
    assert hist[-1] < hist[0], hist


def test_accumulation_requires_fused():
    with pytest.raises(ValueError, match="accumulate_steps requires"):
        StandardWorkflow(
            name="x", loss_function="softmax",
            layers=[{"type": "softmax",
                     "->": {"output_sample_shape": 3}}],
            loader_name="synthetic_classifier",
            loader_config={"n_classes": 3, "sample_shape": (4,),
                           "n_train": 30, "n_valid": 0,
                           "minibatch_size": 30},
            decision_config={"max_epochs": 1}, fused=False,
            accumulate_steps=2)


def test_accumulation_composes_with_shard_update(cpu_devices):
    """accumulate_steps + ZeRO shard_update trains identically to
    accumulate_steps with the replicated update."""
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh
    from znicz_tpu.parallel.step import FusedTrainStep

    weights = {}
    for shard in (False, True):
        prng.seed_all(41)
        w = build_fused(max_epochs=3, layers=(16,), minibatch_size=16,
                        n_train=64, n_valid=0,
                        mesh=data_parallel_mesh(8), optimizer="adam",
                        shard_update=shard, accumulate_steps=2)
        w.initialize(device=TPUDevice())
        w.run()
        w.step.sync_to_units()
        assert w.step._grad_acc is None
        weights[shard] = [np.asarray(f.weights.map_read()).copy()
                          for f in w.forwards]
    for a, b in zip(weights[True], weights[False]):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_pallas_kernels_compose_with_accumulation(cpu_devices):
    """engine.pallas (interpret) composed with accumulate_steps on an
    8-device mesh trains to the same weights as the XLA path.

    (pallas x shard_update is deliberately NOT covered here: the Pallas
    HLO interpreter cannot evaluate kernels whose operands VARY over
    mesh axes under the vma checker — the same interpreter-only
    limitation as multi-device interpret-mode flash attention; the
    Mosaic path on real TPU does not route through the interpreter.)"""
    from znicz_tpu.core.config import root
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    def run(pallas: bool):
        prng.seed_all(47)
        root.common.engine.pallas = pallas
        root.common.engine.pallas_interpret = pallas
        try:
            w = build_fused(max_epochs=2, layers=(12,), minibatch_size=16,
                            n_train=64, n_valid=0,
                            mesh=data_parallel_mesh(8), optimizer="adam",
                            accumulate_steps=2)
            w.initialize(device=TPUDevice())
            w.run()
            w.step.sync_to_units()
            return [np.asarray(f.weights.map_read()).copy()
                    for f in w.forwards]
        finally:
            root.common.engine.pallas = False
            root.common.engine.pallas_interpret = False

    for a, b in zip(run(True), run(False)):
        # kernel-vs-XLA op ordering drifts a few ULPs per apply; over
        # multiple applies that accumulates to ~1e-5 absolute
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)


def test_ema_matches_manual_average():
    """ema_decay maintains ew = d*ew + (1-d)*w after every optimizer
    apply, seeded exactly — verified against a manually tracked average
    over the per-step parameter trajectory."""
    import jax

    from znicz_tpu.models.mnist_fc import build_fused

    d = 0.8
    prng.seed_all(61)
    w = build_fused(max_epochs=1, layers=(16,), minibatch_size=20,
                    n_train=100, n_valid=0, ema_decay=d)
    w.initialize(device=TPUDevice())
    assert all("ew" in leaf for leaf in w.step._params)

    manual = [np.asarray(jax.device_get(leaf["w"]))
              for leaf in w.step._params]
    for _ in range(5):
        w.loader.run()
        w.step.run()
        for i, leaf in enumerate(w.step._params):
            cur = np.asarray(jax.device_get(leaf["w"]))
            manual[i] = d * manual[i] + (1 - d) * cur
    ema = w.step.ema_params()
    for i, leaf in enumerate(ema):
        np.testing.assert_allclose(leaf["w"], manual[i], rtol=1e-5,
                                   atol=1e-6, err_msg=f"layer {i}")
        assert "b" in leaf


def test_ema_snapshots_and_restores():
    """The EMA mirror rides extra_state_arrays: a snapshot/restore into
    a fresh differently-seeded workflow reproduces it bit-exactly."""
    import os
    import tempfile

    from znicz_tpu.snapshotter import (collect_state, restore_state,
                                       write_snapshot)
    from znicz_tpu.standard_workflow import StandardWorkflow

    def build(seed):
        prng.seed_all(seed)
        return StandardWorkflow(
            name="ema", layers=[{"type": "softmax",
                                 "->": {"output_sample_shape": 3},
                                 "<-": {"learning_rate": 0.1}}],
            loss_function="softmax", loader_name="synthetic_classifier",
            loader_config={"n_classes": 3, "sample_shape": (6,),
                           "n_train": 60, "n_valid": 0,
                           "minibatch_size": 20},
            decision_config={"max_epochs": 1}, ema_decay=0.9)

    w = build(5)
    w.initialize(device=TPUDevice())
    w.run()
    ema = w.step.ema_params()
    arrays, meta = collect_state(w)
    assert any(".ew" in k for k in arrays)

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "s.npz")
        write_snapshot(path, arrays, meta)
        w2 = build(6)
        w2.initialize(device=TPUDevice())
        restore_state(w2, path)
    ema2 = w2.step.ema_params()
    for a, b in zip(ema, ema2):
        np.testing.assert_array_equal(a["w"], b["w"])

    # validation: ema_decay must be in (0, 1), and requires fused
    import pytest
    with pytest.raises(ValueError, match="ema_decay"):
        StandardWorkflow(
            name="bad", layers=[{"type": "softmax",
                                 "->": {"output_sample_shape": 3}}],
            loader_name="synthetic_classifier",
            loader_config={"n_classes": 3, "sample_shape": (6,)},
            fused=False, ema_decay=0.9)
    with pytest.raises(ValueError, match=r"in \(0, 1\)"):
        StandardWorkflow(
            name="oob", layers=[{"type": "softmax",
                                 "->": {"output_sample_shape": 3}}],
            loader_name="synthetic_classifier",
            loader_config={"n_classes": 3, "sample_shape": (6,)},
            ema_decay=1.5)
    # restoring an EMA snapshot into a non-EMA workflow fails loudly
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "s.npz")
        write_snapshot(path, arrays, meta)
        w3 = StandardWorkflow(
            name="noema", layers=[{"type": "softmax",
                                   "->": {"output_sample_shape": 3},
                                   "<-": {"learning_rate": 0.1}}],
            loss_function="softmax", loader_name="synthetic_classifier",
            loader_config={"n_classes": 3, "sample_shape": (6,),
                           "n_train": 60, "n_valid": 0,
                           "minibatch_size": 20},
            decision_config={"max_epochs": 1})
        prng.seed_all(8)
        w3.initialize(device=TPUDevice())
        with pytest.raises(ValueError, match="EMA weight mirrors"):
            restore_state(w3, path)


def test_export_forward_with_ema_weights(tmp_path):
    """export_forward(use_ema=True) ships the Polyak mirrors; the loaded
    package predicts with them (serving view), while the default export
    keeps the raw weights."""
    from znicz_tpu.standard_workflow import StandardWorkflow
    from znicz_tpu.utils.export import ExportedForward, export_forward

    prng.seed_all(21)
    w = StandardWorkflow(
        name="emaexp", layers=[{"type": "softmax",
                                "->": {"output_sample_shape": 3},
                                "<-": {"learning_rate": 0.2}}],
        loss_function="softmax", loader_name="synthetic_classifier",
        loader_config={"n_classes": 3, "sample_shape": (6,),
                       "n_train": 90, "n_valid": 0,
                       "minibatch_size": 30},
        decision_config={"max_epochs": 2}, ema_decay=0.7)
    w.initialize(device=TPUDevice())
    w.run()

    raw_path = export_forward(w, str(tmp_path / "raw.npz"))
    ema_path = export_forward(w, str(tmp_path / "ema.npz"), use_ema=True)
    import json
    raw_w = np.load(raw_path)["0.weights"]
    with np.load(ema_path) as pkg:
        ema_w = pkg["0.weights"]
        assert json.loads(str(pkg["__arch__"]))["ema"] is True
    with np.load(raw_path) as pkg:
        assert json.loads(str(pkg["__arch__"]))["ema"] is False
    assert not np.array_equal(raw_w, ema_w)        # mirrors lag raw
    np.testing.assert_allclose(ema_w, w.step.ema_params()[0]["w"])
    # the loaded EMA package runs inference
    x = np.zeros((4, 6), np.float32)
    out = ExportedForward(ema_path)(x)
    assert out.shape == (4, 3)

    # without ema_decay the flag fails loudly
    import pytest
    prng.seed_all(22)
    w2 = StandardWorkflow(
        name="noema2", layers=[{"type": "softmax",
                                "->": {"output_sample_shape": 3},
                                "<-": {"learning_rate": 0.2}}],
        loss_function="softmax", loader_name="synthetic_classifier",
        loader_config={"n_classes": 3, "sample_shape": (6,),
                       "n_train": 30, "n_valid": 0,
                       "minibatch_size": 10},
        decision_config={"max_epochs": 1})
    w2.initialize(device=TPUDevice())
    w2.run()
    with pytest.raises(ValueError, match="ema_decay"):
        export_forward(w2, str(tmp_path / "x.npz"), use_ema=True)
    # and before initialize: clear error, not a TypeError deep inside
    prng.seed_all(23)
    w3 = StandardWorkflow(
        name="uninit", layers=[{"type": "softmax",
                                "->": {"output_sample_shape": 3}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 3, "sample_shape": (6,)},
        ema_decay=0.9)
    with pytest.raises(ValueError, match="initialized"):
        export_forward(w3, str(tmp_path / "y.npz"), use_ema=True)


def test_everything_on_composition(tmp_path, cpu_devices):
    """Capstone: adam + ZeRO update sharding + global clipping + gradient
    accumulation + EMA mirrors, on the 8-device mesh, trains finitely and
    snapshot/restores bit-exactly."""
    from znicz_tpu.parallel.mesh import data_parallel_mesh
    from znicz_tpu.snapshotter import (collect_state, restore_state,
                                       write_snapshot)

    def build(seed):
        prng.seed_all(seed)
        return StandardWorkflow(
            name="allon", loss_function="softmax", layers=[
                {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
                 "<-": {"learning_rate": 0.01, "weights_decay": 1e-3}},
                {"type": "softmax", "->": {"output_sample_shape": 4},
                 "<-": {"learning_rate": 0.01, "weights_decay": 1e-3}}],
            loader_name="synthetic_classifier",
            loader_config={"n_classes": 4, "sample_shape": (6,),
                           "n_train": 64, "n_valid": 32,
                           "minibatch_size": 16},
            decision_config={"max_epochs": 2},
            mesh=data_parallel_mesh(8), optimizer="adam",
            shard_update=True, clip_norm=1.0, accumulate_steps=2,
            ema_decay=0.9)

    w = build(77)
    w.initialize(device=TPUDevice())
    w.run()
    hist = [h["metric_validation"] for h in w.decision.metrics_history]
    assert len(hist) == 2 and all(np.isfinite(hist))
    ema = w.step.ema_params()
    assert all(np.isfinite(leaf["w"]).all() for leaf in ema)

    arrays, meta = collect_state(w)
    snap = str(tmp_path / "allon.npz")
    write_snapshot(snap, arrays, meta)
    w2 = build(78)
    w2.initialize(device=TPUDevice())
    restore_state(w2, snap)
    for a, b in zip(ema, w2.step.ema_params()):
        np.testing.assert_array_equal(a["w"], b["w"])
    w.step.sync_to_units()
    w2.step.sync_to_units()
    np.testing.assert_array_equal(w.forwards[0].weights.map_read(),
                                  w2.forwards[0].weights.map_read())


# -- narrow optimizer-state storage (state_dtype) ---------------------------

def build_sgd_momentum(max_epochs=3, seed=55, state_dtype=None):
    """SGD+momentum workflow; momentum matters (gradient_moment=0.9)."""
    prng.seed_all(seed)
    hp = {"learning_rate": 0.05, "learning_rate_bias": 0.05,
          "gradient_moment": 0.9, "gradient_moment_bias": 0.9,
          "weights_decay": 1e-4, "weights_decay_bias": 1e-4}
    cfg = {"state_dtype": state_dtype} if state_dtype else None
    return StandardWorkflow(
        name="SgdState", loss_function="softmax", layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": dict(hp)},
            {"type": "softmax", "->": {"output_sample_shape": 4},
             "<-": dict(hp)}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 4, "sample_shape": (6,), "n_train": 40,
                       "n_valid": 0, "minibatch_size": 40},
        decision_config={"max_epochs": max_epochs},
        optimizer="sgd", optimizer_config=cfg)


def test_state_dtype_bf16_tracks_f32():
    """bf16 momentum storage: velocity leaves live narrow inside the
    step, the unit-facing buffers stay f32, and the 6-epoch trajectory
    tracks the f32 run closely (math is f32 — only persistence narrows)."""
    runs = {}
    for sd in (None, "bfloat16"):
        w = build_sgd_momentum(max_epochs=6, seed=91, state_dtype=sd)
        w.initialize(device=TPUDevice())
        want = jnp.bfloat16 if sd else jnp.float32
        assert w.step._params[0]["vw"].dtype == want
        w.run()
        w.step.sync_to_units()
        assert w.forwards[0].weights.map_read().dtype == np.float32
        assert np.asarray(
            w.gds[0].gradient_weights.map_read()).dtype == np.float32
        runs[sd] = [np.asarray(f.weights.map_read()).copy()
                    for f in w.forwards]
    for a, b in zip(runs[None], runs["bfloat16"]):
        np.testing.assert_allclose(a, b, rtol=0.05, atol=5e-3)


def test_state_dtype_snapshot_resume_bit_exact(tmp_path):
    """f32 snapshot of bf16 momenta widens exactly, so interrupt/resume
    under state_dtype reproduces the uninterrupted run bit-exactly."""
    from znicz_tpu.snapshotter import collect_state, restore_state, \
        write_snapshot

    def final_weights(w):
        w.step.sync_to_units()
        return [np.asarray(f.weights.map_read()).copy()
                for f in w.forwards]

    w_full = build_sgd_momentum(max_epochs=6, seed=17,
                                state_dtype="bfloat16")
    w_full.initialize(device=TPUDevice())
    w_full.run()
    want = final_weights(w_full)

    w_a = build_sgd_momentum(max_epochs=3, seed=17,
                             state_dtype="bfloat16")
    w_a.initialize(device=TPUDevice())
    w_a.run()
    arrays, meta = collect_state(w_a)
    snap = str(tmp_path / "sgdstate.npz")
    write_snapshot(snap, arrays, meta)

    w_b = build_sgd_momentum(max_epochs=6, seed=17,
                             state_dtype="bfloat16")
    w_b.initialize(device=TPUDevice())
    restore_state(w_b, snap)
    w_b.decision.max_epochs = 6
    w_b.decision.complete.set(False)
    w_b.run()
    got = final_weights(w_b)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_state_dtype_rejected_for_adam():
    with pytest.raises(ValueError, match="state_dtype"):
        build_adam(optimizer_config={"state_dtype": "bfloat16"})


def test_state_dtype_shard_update_scan(cpu_devices):
    """state_dtype composes with the ZeRO-sharded update and scan-epoch
    dispatch: momenta stay narrow through _flat_shard_put (it must not
    widen them — the scan carry would then flip dtypes and crash) and the
    sharded bf16-state run tracks the replicated one."""
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    weights = {}
    for mode in (False, True):
        prng.seed_all(31)
        w = build_fused(max_epochs=3, layers=(23,), minibatch_size=32,
                        n_train=160, n_valid=64,
                        mesh=data_parallel_mesh(8),
                        optimizer="sgd", shard_update=mode,
                        optimizer_config={"state_dtype": "bfloat16"})
        w.step.scan_epoch = True
        w.initialize(device=TPUDevice())
        assert w.step._params[0]["vw"].dtype == jnp.bfloat16, \
            "narrowing undone by the sharded placement"
        w.run()
        w.step.sync_to_units()
        weights[mode] = [np.asarray(f.weights.map_read()).copy()
                        for f in w.forwards]
    for a, b in zip(weights[True], weights[False]):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)
