"""ZeRO-grade persistent parameter sharding (ISSUE 15): shard_params
parity with the replicated and shard_update paths, the cross-layout
snapshot matrix, per-chip memory accounting, the zero-retrace pin, and
the zero.py gather primitives."""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.core.config import root
from znicz_tpu.models.mnist_fc import build_fused
from znicz_tpu.observe import registry
from znicz_tpu.parallel.mesh import data_parallel_mesh
from znicz_tpu.snapshotter import (collect_state, restore_state,
                                   write_snapshot)

LAYOUTS = {
    "replicated": {},
    "shard_update": {"shard_update": True},
    "shard_params": {"shard_params": True},
}


def _build(n_epochs, n_dev, layout, optimizer="adam", seed=7, **kw):
    prng.seed_all(seed)
    return build_fused(max_epochs=n_epochs, layers=(16,),
                       minibatch_size=16, n_train=64, n_valid=0,
                       mesh=data_parallel_mesh(n_dev),
                       optimizer=optimizer, **LAYOUTS[layout], **kw)


def _weights(w):
    w.step.sync_to_units()
    return [np.asarray(f.weights.map_read()).copy() for f in w.forwards]


def _gauge(name):
    return registry.REGISTRY.get(name).labels(unit="FusedStep").get()


def test_shard_params_matches_replicated(cpu_devices):
    """shard_params trains within the repo's established
    sharded-vs-replicated pins for both optimizers (seeded metric
    history EXACTLY equal; weights/momenta at the existing
    test_shard_update_matches_replicated tolerances) — and matches the
    shard_update path BIT-FOR-BIT: the on-demand gather is exact data
    movement and the shard update is the same elementwise math on the
    same slices."""
    for opt in ("sgd", "adam"):
        runs = {}
        for layout in LAYOUTS:
            prng.seed_all(31)
            w = build_fused(max_epochs=3, layers=(23,),
                            minibatch_size=32, n_train=160, n_valid=64,
                            mesh=data_parallel_mesh(8), optimizer=opt,
                            **LAYOUTS[layout])
            w.initialize(device=TPUDevice())
            w.run()
            w.step.sync_to_units()
            runs[layout] = {
                "w": [np.asarray(f.weights.map_read()).copy()
                      for f in w.forwards],
                "v": [np.asarray(g.gradient_weights.map_read()).copy()
                      for g in w.gds],
                "hist": [h["metric_validation"]
                         for h in w.decision.metrics_history],
            }
        base = runs["replicated"]
        for layout in ("shard_update", "shard_params"):
            assert runs[layout]["hist"] == base["hist"], (opt, layout)
            for key, rtol, atol in (("w", 2e-5, 1e-6), ("v", 2e-5, 1e-6)):
                for a, b in zip(runs[layout][key], base[key]):
                    np.testing.assert_allclose(
                        a, b, rtol=rtol, atol=atol,
                        err_msg=f"{opt}/{layout}/{key}")
        # the new mode vs the existing sharded path: bit-identical
        for key in ("w", "v"):
            for a, b in zip(runs["shard_params"][key],
                            runs["shard_update"][key]):
                np.testing.assert_array_equal(a, b, err_msg=f"{opt}/{key}")


def test_cross_layout_snapshot_matrix(tmp_path, cpu_devices):
    """Satellite 3: snapshots are layout-independent — a run interrupted
    in ANY layout resumes in ANY OTHER layout on the same mesh with
    BIT-IDENTICAL final weights and the same seeded history (snapshots
    store param-shaped host arrays; gather_params re-places them in
    whatever layout the resuming step uses)."""
    # one oracle serves every same-mesh cell: the three layouts are
    # bit-identical (pinned above)
    w_o = _build(4, 8, "replicated")
    w_o.initialize(device=TPUDevice())
    w_o.run()
    want = _weights(w_o)
    want_hist = [h["metric_train"] for h in w_o.decision.metrics_history]

    matrix = [("shard_params", "replicated"),
              ("shard_params", "shard_update"),
              ("replicated", "shard_params"),
              ("shard_update", "shard_params"),
              ("shard_params", "shard_params")]
    for src, dst in matrix:
        w_a = _build(2, 8, src)
        w_a.initialize(device=TPUDevice())
        w_a.run()
        arrays, meta = collect_state(w_a)
        # state arrays always carry the PARAM shape, never the layout
        assert arrays["step.opt.0.sw"].shape == \
            w_a.forwards[0].weights.shape, src
        snap = str(tmp_path / f"{src}_{dst}.npz")
        write_snapshot(snap, arrays, meta)

        w_b = _build(4, 8, dst)
        w_b.initialize(device=TPUDevice())
        restore_state(w_b, snap)
        w_b.decision.max_epochs = 4
        w_b.decision.complete.set(False)
        w_b.run()
        got = _weights(w_b)
        for a, b in zip(got, want):
            np.testing.assert_array_equal(a, b, err_msg=f"{src}->{dst}")
        hist = [h["metric_train"]
                for h in w_b.decision.metrics_history]
        assert hist[-2:] == want_hist[-2:], (src, dst)


def test_cross_layout_elastic_resume_other_world_size(tmp_path,
                                                      cpu_devices):
    """The elastic leg of the matrix (PR 9 drill pattern): a
    shard_params run interrupted on an 8-wide mesh resumes REPLICATED on
    a 2-wide mesh — and vice versa — and continues within the repo's
    established cross-world-size pins (gradient psums group differently
    across mesh sizes, so the continuation is allclose, not bit-equal;
    same strength as test_shard_update_snapshot_restores_across_layouts)."""
    for src, n_src, dst, n_dst in (("shard_params", 8, "replicated", 2),
                                   ("replicated", 2, "shard_params", 8)):
        w_a = _build(2, n_src, src)
        w_a.initialize(device=TPUDevice())
        w_a.run()
        arrays, meta = collect_state(w_a)
        snap = str(tmp_path / f"ws_{src}_{dst}.npz")
        write_snapshot(snap, arrays, meta)

        # oracle: continue at the SOURCE world size and layout
        w_o = _build(4, n_src, src)
        w_o.initialize(device=TPUDevice())
        w_o.run()
        want = _weights(w_o)

        w_b = _build(4, n_dst, dst)
        w_b.initialize(device=TPUDevice())
        restore_state(w_b, snap)
        w_b.decision.max_epochs = 4
        w_b.decision.complete.set(False)
        w_b.run()
        for a, b in zip(_weights(w_b), want):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7,
                                       err_msg=f"{src}@{n_src}->"
                                               f"{dst}@{n_dst}")


def test_shard_params_memory_gauges(cpu_devices):
    """Acceptance: per-chip znicz_zero_param_bytes +
    znicz_zero_opt_state_bytes under shard_params reads <= 1/n of the
    replicated figure plus the padding epsilon, and the gathered-bytes
    counter advances by exactly the static per-dispatch figure."""
    n = 8
    totals = {}
    for layout in ("replicated", "shard_params"):
        w = _build(1, n, layout)
        w.initialize(device=TPUDevice())
        totals[layout] = (_gauge("znicz_zero_param_bytes") +
                          _gauge("znicz_zero_opt_state_bytes"))
        if layout != "shard_params":
            continue
        # padding epsilon: at most (n - 1) f32 elements per sharded leaf
        n_sharded = sum(1 for leaf in w.step._params
                        for k in leaf if w.step._leaf_sharded(k))
        eps = 4 * (n - 1) * n_sharded
        assert totals["shard_params"] <= \
            totals["replicated"] / n + eps, totals
        before = _gauge("znicz_zero_gathered_bytes_total")
        w.loader.run()
        w.step.run()
        after = _gauge("znicz_zero_gathered_bytes_total")
        assert after - before == w.step._zero_gather_nbytes > 0
    # replicated steps report full bytes per chip and gather nothing
    assert totals["replicated"] > 0


def test_shard_params_zero_retrace(cpu_devices):
    """Acceptance: the gather chain compiles into the ONE train/eval
    program — steady-state compile delta 0 (no per-step retrace)."""
    prng.seed_all(11)
    w = build_fused(max_epochs=3, layers=(16,), minibatch_size=16,
                    n_train=64, n_valid=32, mesh=data_parallel_mesh(8),
                    optimizer="adam", shard_params=True)
    w.initialize(device=TPUDevice())
    w.run()
    # the small synthetic dataset rides the HBM-pinned index-fed path
    train_fn = w.step._train_fn_idx or w.step._train_fn
    eval_fn = w.step._eval_fn_idx or w.step._eval_fn
    assert train_fn._cache_size() == 1
    assert eval_fn._cache_size() == 1


def test_shard_params_composes_with_accumulation_and_ema(cpu_devices):
    """accumulate_steps and the EMA mirror ride shard_params unchanged:
    seeded histories match the replicated run exactly, EMA weights at
    the standard sharded-vs-replicated tolerance, and the shard_update
    run bit-for-bit (the EMA mirrors live sharded too)."""
    runs = {}
    for layout in LAYOUTS:
        prng.seed_all(17)
        w = build_fused(max_epochs=2, layers=(12,), minibatch_size=16,
                        n_train=96, n_valid=32,
                        mesh=data_parallel_mesh(4), optimizer="sgd",
                        accumulate_steps=2, ema_decay=0.9,
                        **LAYOUTS[layout])
        w.initialize(device=TPUDevice())
        w.run()
        runs[layout] = {
            "hist": [h["metric_validation"]
                     for h in w.decision.metrics_history],
            "ema": w.step.ema_params(),
        }
    assert runs["shard_params"]["hist"] == runs["replicated"]["hist"]
    for a, b in zip(runs["shard_params"]["ema"],
                    runs["replicated"]["ema"]):
        np.testing.assert_allclose(a["w"], b["w"], rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(a["b"], b["b"], rtol=2e-5, atol=1e-6)
    for a, b in zip(runs["shard_params"]["ema"],
                    runs["shard_update"]["ema"]):
        np.testing.assert_array_equal(a["w"], b["w"])
        np.testing.assert_array_equal(a["b"], b["b"])


def test_snapshot_d2h_batched(cpu_devices, monkeypatch):
    """Satellite 1: the snapshot path's D2H traffic is batched — the
    number of jax.device_get calls in collect_state does NOT scale with
    layer count (one batched fetch for sync_to_units' sharded leaves,
    one for the PRNG key, one for extra_state_arrays)."""
    import jax as jax_mod

    def counted_build(layers):
        prng.seed_all(13)
        w = build_fused(max_epochs=1, layers=layers, minibatch_size=16,
                        n_train=32, n_valid=0,
                        mesh=data_parallel_mesh(4), optimizer="adam",
                        shard_params=True, ema_decay=0.9)
        w.initialize(device=TPUDevice())
        w.loader.run()
        w.step.run()
        real = jax_mod.device_get
        calls = []
        monkeypatch.setattr(jax_mod, "device_get",
                            lambda *a, **kw: calls.append(1) or
                            real(*a, **kw))
        collect_state(w)
        monkeypatch.setattr(jax_mod, "device_get", real)
        return len(calls)

    shallow = counted_build((8,))
    deep = counted_build((8, 8, 8))
    assert deep == shallow, (shallow, deep)


def test_all_gather_slices_matches_psum_regather(cpu_devices):
    """zero.all_gather_slices reconstructs exactly what psum_regather
    does — including the padded (size % n != 0) case — and the
    via_psum fallback routes through the psum path."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from znicz_tpu.parallel import zero
    from znicz_tpu.parallel.compat import shard_map
    from znicz_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 4})
    for size in (64, 61):          # aligned + padded
        x = np.arange(size, dtype=np.float32).reshape(-1)
        like = jax.ShapeDtypeStruct((size,), np.float32)
        pad = (-size) % 4
        flat = np.pad(x, (0, pad))

        def body(f):
            rank = lax.axis_index("data")
            a = zero.all_gather_slices(f, rank, 4, "data", like)
            b = zero.all_gather_slices(f, rank, 4, "data", like,
                                       via_psum=True)
            return a, b

        fn = shard_map(body, mesh=mesh, in_specs=(P("data"),),
                       out_specs=(P(), P()))
        a, b = jax.jit(fn)(flat)
        np.testing.assert_array_equal(np.asarray(a), x)
        np.testing.assert_array_equal(np.asarray(b), x)


def test_pad_slice_skips_noop_pad(cpu_devices):
    """Satellite 2: pad_slice emits NO pad op when the size already
    divides by n (the aligned common case), and still pads otherwise."""
    import jax
    import jax.numpy as jnp
    from znicz_tpu.parallel import zero

    aligned = str(jax.make_jaxpr(
        lambda x: zero.pad_slice(x, jnp.int32(0), 4))(
            np.zeros((8, 8), np.float32)))
    ragged = str(jax.make_jaxpr(
        lambda x: zero.pad_slice(x, jnp.int32(0), 4))(
            np.zeros((7, 9), np.float32)))
    assert "pad" not in aligned
    assert "pad" in ragged


def test_shard_params_via_psum_fallback_matches(cpu_devices):
    """engine.zero_gather_via_psum routes the gather chain through the
    vma-safe psum_regather and trains identically."""
    hists = {}
    for via in (False, True):
        prev = root.common.engine.get("zero_gather_via_psum", False)
        root.common.engine.zero_gather_via_psum = via
        try:
            w = _build(2, 4, "shard_params", seed=23)
            w.initialize(device=TPUDevice())
            w.run()
            hists[via] = ([h["metric_train"]
                           for h in w.decision.metrics_history],
                          _weights(w))
        finally:
            root.common.engine.zero_gather_via_psum = prev
    assert hists[True][0] == hists[False][0]
    for a, b in zip(hists[True][1], hists[False][1]):
        np.testing.assert_array_equal(a, b)


def test_shard_params_scan_epoch_and_state_dtype(cpu_devices):
    """shard_params composes with scan-epoch dispatch (the gather chain
    re-runs inside each scanned minibatch) and narrow SGD momenta:
    identical weights to the shard_update run bit-for-bit, and the
    gathered-bytes counter advances per SCANNED minibatch, not per
    dispatch."""
    import jax.numpy as jnp

    weights = {}
    for layout in ("shard_update", "shard_params"):
        prng.seed_all(31)
        w = build_fused(max_epochs=2, layers=(23,), minibatch_size=32,
                        n_train=160, n_valid=64,
                        mesh=data_parallel_mesh(8), optimizer="sgd",
                        optimizer_config={"state_dtype": "bfloat16"},
                        **LAYOUTS[layout])
        w.step.scan_epoch = True
        w.initialize(device=TPUDevice())
        assert w.step._params[0]["vw"].dtype == jnp.bfloat16
        before = _gauge("znicz_zero_gathered_bytes_total")
        w.run()
        w.step.sync_to_units()
        if layout == "shard_params":
            per_dispatch = w.step._zero_gather_nbytes
            delta = _gauge("znicz_zero_gathered_bytes_total") - before
            assert per_dispatch > 0 and delta > per_dispatch, \
                (delta, per_dispatch)
        weights[layout] = [np.asarray(f.weights.map_read()).copy()
                           for f in w.forwards]
    for a, b in zip(weights["shard_params"], weights["shard_update"]):
        np.testing.assert_array_equal(a, b)


# -- transformer step ---------------------------------------------------------

def test_transformer_shard_params_matches_shard_update(cpu_devices):
    """The transformer step's shard_params mode is bit-identical to its
    shard_update pin (both update per-data-rank slices of the same
    psum-convention gradients; shard_params just PERSISTS the slices
    and regathers on demand instead of after the update)."""
    import jax
    from znicz_tpu.parallel import transformer as tfm
    from znicz_tpu.parallel.mesh import make_mesh

    prng.seed_all(19)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 2, 32, 4, 64, 17
    params = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    rng = np.random.default_rng(4)
    tokens = rng.integers(0, vocab, (4, 16)).astype(np.int32)
    labels = ((tokens + 1) % vocab).astype(np.int32)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    specs = tfm.param_specs(n_layers)
    shapes = tfm.param_shapes(n_layers, d, ff, vocab)

    res = {}
    for mode in ("shard_update", "shard_params"):
        step, _ = tfm.make_train_step(
            mesh, n_layers, d, heads, ff, vocab, lr=0.2,
            shard_update=(mode == "shard_update"),
            shard_params=(mode == "shard_params"))
        p = {k: (v if not isinstance(v, list) else [dict(b) for b in v])
             for k, v in params.items()}
        if mode == "shard_params":
            p = tfm.shard_params_host(p, specs, 2)
        losses = []
        for _ in range(6):
            p, loss = step(p, tokens, labels)
            losses.append(float(loss))
        host = jax.device_get(p)
        if mode == "shard_params":
            host = tfm.unshard_params_host(host, specs, shapes)
        res[mode] = (losses, host)

    assert res["shard_params"][0] == res["shard_update"][0]
    for a, b in zip(jax.tree.leaves(res["shard_params"][1]),
                    jax.tree.leaves(res["shard_update"][1])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_transformer_shard_params_host_roundtrip(cpu_devices):
    """shard_params_host -> unshard_params_host is the identity,
    including odd (padded) leaf sizes."""
    from znicz_tpu.parallel import transformer as tfm

    prng.seed_all(3)
    gen = prng.get()
    n_layers, d, heads, ff, vocab = 1, 16, 2, 32, 11   # 11: pads at n=4
    params = tfm.init_params(gen, n_layers, d, heads, ff, vocab)
    specs = tfm.param_specs(n_layers)
    shapes = tfm.param_shapes(n_layers, d, ff, vocab)
    flat = tfm.shard_params_host(params, specs, 4)
    back = tfm.unshard_params_host(flat, specs, shapes)
    import jax
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_transformer_shard_params_rejects_shard_update(cpu_devices):
    from znicz_tpu.parallel import transformer as tfm
    from znicz_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="subsumes"):
        tfm.make_train_step(make_mesh({"data": 2, "seq": 1, "model": 1}),
                            1, 16, 2, 32, 8, shard_update=True,
                            shard_params=True)


# -- ISSUE 18: error-feedback residual snapshot/restore ----------------------

QC = {"mode": "int8", "chunk": 64, "error_feedback": True}


def test_ef_residual_snapshot_resume_bit_exact(tmp_path, cpu_devices):
    """ISSUE 18: error-feedback residuals are real state — a quantized
    int8+EF run interrupted mid-training resumes BIT-IDENTICAL to the
    uninterrupted run on the same mesh, in both the replicated and
    shard_params layouts (the per-rank rw/rb slabs snapshot as-is and
    restore into the same ranks; dropping them instead would fork the
    trajectory at the first post-resume step)."""
    for layout in ("replicated", "shard_params"):
        w_o = _build(4, 8, layout, quantized_collectives=QC)
        w_o.initialize(device=TPUDevice())
        w_o.run()
        want = _weights(w_o)
        want_hist = [h["metric_train"]
                     for h in w_o.decision.metrics_history]

        w_a = _build(2, 8, layout, quantized_collectives=QC)
        w_a.initialize(device=TPUDevice())
        w_a.run()
        arrays, meta = collect_state(w_a)
        # the residual slabs ride the snapshot, one rank row per device
        assert arrays["step.opt.0.rw"].shape == \
            (8,) + w_a.forwards[0].weights.shape, layout
        assert "step.opt.0.rb" in arrays and "step.opt.1.rw" in arrays
        snap = str(tmp_path / f"ef_{layout}.npz")
        write_snapshot(snap, arrays, meta)

        w_b = _build(4, 8, layout, quantized_collectives=QC)
        w_b.initialize(device=TPUDevice())
        restore_state(w_b, snap)
        w_b.decision.max_epochs = 4
        w_b.decision.complete.set(False)
        w_b.run()
        for a, b in zip(_weights(w_b), want):
            np.testing.assert_array_equal(a, b, err_msg=layout)
        hist = [h["metric_train"]
                for h in w_b.decision.metrics_history]
        assert hist[-2:] == want_hist[-2:], layout


def test_ef_cross_mode_restore_matrix(tmp_path, cpu_devices):
    """The quantized <-> exact cells of the restore matrix, with the
    layout flipping at the same time: a quantized shard_params snapshot
    restores into an exact replicated build (the residuals have no home
    there — dropped, the run completes), and an exact replicated
    snapshot restores into a quantized shard_params build (residuals
    start at zero and the EF gauge goes live as training continues)."""
    # quantized shard_params -> exact replicated
    w_a = _build(2, 8, "shard_params", quantized_collectives=QC)
    w_a.initialize(device=TPUDevice())
    w_a.run()
    arrays, meta = collect_state(w_a)
    assert "step.opt.0.rw" in arrays
    snap = str(tmp_path / "qc_to_exact.npz")
    write_snapshot(snap, arrays, meta)
    w_b = _build(4, 8, "replicated")
    w_b.initialize(device=TPUDevice())
    restore_state(w_b, snap)
    w_b.decision.max_epochs = 4
    w_b.decision.complete.set(False)
    w_b.run()
    assert all("rw" not in leaf for leaf in w_b.step._params)
    assert all(np.isfinite(a).all() for a in _weights(w_b))

    # exact replicated -> quantized shard_params
    w_c = _build(2, 8, "replicated")
    w_c.initialize(device=TPUDevice())
    w_c.run()
    arrays, meta = collect_state(w_c)
    assert not any(k.endswith(".rw") for k in arrays)
    snap2 = str(tmp_path / "exact_to_qc.npz")
    write_snapshot(snap2, arrays, meta)
    w_d = _build(4, 8, "shard_params", quantized_collectives=QC)
    w_d.initialize(device=TPUDevice())
    restore_state(w_d, snap2)
    w_d.decision.max_epochs = 4
    w_d.decision.complete.set(False)
    w_d.run()
    assert all(np.isfinite(a).all() for a in _weights(w_d))
    assert _gauge("znicz_qcomm_residual_norm") > 0


def test_ef_residual_cross_world_fold(tmp_path, cpu_devices):
    """Restoring EF residuals at a DIFFERENT world size folds the rank
    SUM — the only quantity the deferred-error correction depends on —
    onto rank 0, and training continues finite from there."""
    w_a = _build(2, 8, "shard_params", quantized_collectives=QC)
    w_a.initialize(device=TPUDevice())
    w_a.run()
    arrays, meta = collect_state(w_a)
    want_sum = arrays["step.opt.0.rw"].sum(axis=0)
    assert np.abs(want_sum).max() > 0            # EF actually accrued
    snap = str(tmp_path / "ef_fold.npz")
    write_snapshot(snap, arrays, meta)

    w_b = _build(4, 2, "replicated", quantized_collectives=QC)
    w_b.initialize(device=TPUDevice())
    restore_state(w_b, snap)
    got = np.asarray(w_b.step._params[0]["rw"])
    assert got.shape[0] == 2
    np.testing.assert_allclose(got[0], want_sum, rtol=1e-6, atol=1e-7)
    assert np.abs(got[1]).max() == 0.0
    w_b.decision.max_epochs = 4
    w_b.decision.complete.set(False)
    w_b.run()
    assert all(np.isfinite(a).all() for a in _weights(w_b))
