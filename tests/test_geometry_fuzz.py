"""Property-based geometry fuzzing over the conv/pooling/deconv op family
(tier-1 hardening beyond the fixed-shape parity tests): for RANDOM
kernel/stride/padding combinations, the numpy im2col oracle, the XLA
lowering, and torch must agree, and the backward must be the exact adjoint
of the forward.  Catches the padding/stride edge cases fixed-shape suites
never reach (e.g. stride > kernel, clipped border windows, negative-crop
deconv geometry).

Hypothesis settings: deterministic (derandomize), small example counts —
each example compiles nothing (numpy + torch only on the heavy paths), so
the suite stays fast.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from znicz_tpu.ops import activations, conv, deconv, pooling  # noqa: E402

SETTINGS = dict(max_examples=25, deadline=None, derandomize=True)


def geometry(draw):
    ky = draw(st.integers(1, 4))
    kx = draw(st.integers(1, 4))
    sy = draw(st.integers(1, 3))
    sx = draw(st.integers(1, 3))
    pt, pb, pl, pr = (draw(st.integers(0, 2)) for _ in range(4))
    h = draw(st.integers(max(ky - pt - pb, 1), 9))
    w = draw(st.integers(max(kx - pl - pr, 1), 9))
    return ky, kx, sy, sx, pt, pb, pl, pr, h, w


@st.composite
def conv_cases(draw):
    ky, kx, sy, sx, pt, pb, pl, pr, h, w = geometry(draw)
    # the conv needs at least one output position
    oh = conv.out_size(h, ky, sy, pt, pb)
    ow = conv.out_size(w, kx, sx, pl, pr)
    if oh < 1 or ow < 1:
        h = max(h, ky + sy)
        w = max(w, kx + sx)
    c = draw(st.integers(1, 3))
    nk = draw(st.integers(1, 4))
    n = draw(st.integers(1, 3))
    seed = draw(st.integers(0, 2 ** 31 - 1))
    return n, h, w, c, nk, ky, kx, (sy, sx), (pt, pb, pl, pr), seed


@given(conv_cases())
@settings(**SETTINGS)
def test_conv_oracle_matches_torch(case):
    n, h, w, c, nk, ky, kx, sliding, padding, seed = case
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, h, w, c))
    wt = rng.normal(size=(ky, kx, c, nk))
    ours = conv.forward_linear(np, x, wt, None, sliding, padding)
    pt, pb, pl, pr = padding
    xt = F.pad(torch.from_numpy(np.moveaxis(x, 3, 1).copy()),
               (pl, pr, pt, pb))
    gold = F.conv2d(xt, torch.from_numpy(wt.transpose(3, 2, 0, 1).copy()),
                    stride=sliding)
    np.testing.assert_allclose(ours, np.moveaxis(gold.numpy(), 1, 3),
                               rtol=1e-10, atol=1e-10)


@given(conv_cases())
@settings(**SETTINGS)
def test_conv_backward_is_exact_adjoint(case):
    """<W(x), e> == <x, W^T(e)>: the backward err_input is the adjoint of
    the forward for EVERY geometry; grad_w likewise via <W_w(x), e> ==
    <w, grad_w(x, e)> (bilinearity in the weights)."""
    n, h, w, c, nk, ky, kx, sliding, padding, seed = case
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, h, w, c))
    wt = rng.normal(size=(ky, kx, c, nk))
    y = conv.forward_linear(np, x, wt, None, sliding, padding)
    e = rng.normal(size=y.shape)
    err_input, grad_w, grad_b = conv.backward(
        np, x, None, wt, e, sliding, padding,
        activation=activations.LINEAR, activation_applied=False)
    np.testing.assert_allclose((y * e).sum(), (x * err_input).sum(),
                               rtol=1e-9)
    np.testing.assert_allclose((y * e).sum(), (wt * grad_w).sum(),
                               rtol=1e-9)
    np.testing.assert_allclose(grad_b, e.sum(axis=(0, 1, 2)), rtol=1e-10)


@st.composite
def pool_cases(draw):
    ky = draw(st.integers(1, 4))
    kx = draw(st.integers(1, 4))
    sy = draw(st.integers(1, 4))          # stride may exceed kernel
    sx = draw(st.integers(1, 4))
    h = draw(st.integers(1, 9))
    w = draw(st.integers(1, 9))
    n = draw(st.integers(1, 3))
    c = draw(st.integers(1, 3))
    seed = draw(st.integers(0, 2 ** 31 - 1))
    return n, h, w, c, ky, kx, sy, sx, seed


@given(pool_cases())
@settings(**SETTINGS)
def test_max_pool_matches_torch_everywhere(case):
    n, h, w, c, ky, kx, sy, sx, seed = case
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, h, w, c))
    y, offsets = pooling.max_forward(np, x, ky, kx, sy, sx)
    kh, kw = min(ky, h), min(kx, w)       # torch requires kernel <= input
    if (kh, kw) != (ky, kx):
        return                            # znicz clips internally; skip
    gold = F.max_pool2d(torch.from_numpy(np.moveaxis(x, 3, 1).copy()),
                        (ky, kx), stride=(sy, sx), ceil_mode=True)
    gold = np.moveaxis(gold.numpy(), 1, 3)
    if gold.shape != y.shape:
        # torch ceil_mode drops a window that starts in the implicit
        # padding; znicz never emits fully-out-of-bounds windows, so the
        # shared prefix must still agree
        gold = gold[:, :y.shape[1], :y.shape[2], :]
    np.testing.assert_allclose(y, gold, rtol=0, atol=0)
    # every recorded winner offset is a real in-bounds input cell
    assert offsets.min() >= 0 and offsets.max() < h * w


@given(pool_cases())
@settings(**SETTINGS)
def test_pool_backward_is_exact_adjoint(case):
    n, h, w, c, ky, kx, sy, sx, seed = case
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, h, w, c))
    # max: scatter through offsets is the adjoint of the selection gather
    y, offsets = pooling.max_forward(np, x, ky, kx, sy, sx)
    e = rng.normal(size=y.shape)
    back = pooling.scatter_backward(np, e, offsets, x.shape)
    g = np.zeros_like(x)
    # direct perturbation check on the winning cells only
    np.testing.assert_allclose((back * x).sum(), (e * y).sum(), rtol=1e-9)
    del g
    # avg: uniform spread is the adjoint of the count-normalized sum
    ya = pooling.avg_forward(np, x, ky, kx, sy, sx)
    ea = rng.normal(size=ya.shape)
    back_a = pooling.avg_backward(np, ea, x.shape, ky, kx, sy, sx)
    np.testing.assert_allclose((back_a * x).sum(), (ea * ya).sum(),
                               rtol=1e-9)


@st.composite
def deconv_cases(draw):
    ky = draw(st.integers(1, 4))
    kx = draw(st.integers(1, 4))
    sy = draw(st.integers(1, 3))
    sx = draw(st.integers(1, 3))
    pt = draw(st.integers(0, min(1, ky - 1)))
    pl = draw(st.integers(0, min(1, kx - 1)))
    oh = draw(st.integers(1, 5))
    ow = draw(st.integers(1, 5))
    n = draw(st.integers(1, 2))
    c = draw(st.integers(1, 3))
    nk = draw(st.integers(1, 3))
    seed = draw(st.integers(0, 2 ** 31 - 1))
    return n, oh, ow, c, nk, ky, kx, (sy, sx), (pt, pt, pl, pl), seed


@given(deconv_cases())
@settings(**SETTINGS)
def test_deconv_is_conv_adjoint(case):
    """Deconv forward is the exact adjoint of conv forward with shared
    geometry: <conv(x), e> == <x, deconv(e)> for every case."""
    n, oh, ow, c, nk, ky, kx, sliding, padding, seed = case
    h = deconv.min_output_size(oh, ky, sliding[0], padding[0], padding[1])
    w = deconv.min_output_size(ow, kx, sliding[1], padding[2], padding[3])
    if h < 1 or w < 1:
        return
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, h, w, c))
    wt = rng.normal(size=(ky, kx, c, nk))
    y = conv.forward_linear(np, x, wt, None, sliding, padding)
    assert y.shape == (n, oh, ow, nk)
    e = rng.normal(size=y.shape)
    back = deconv.forward(np, e, wt, sliding, padding, x.shape)
    np.testing.assert_allclose((y * e).sum(), (x * back).sum(), rtol=1e-9)


@st.composite
def lrn_cases(draw):
    n = draw(st.integers(1, 2))
    h = draw(st.integers(1, 4))
    w = draw(st.integers(1, 4))
    c = draw(st.integers(1, 12))
    win = draw(st.integers(1, 7))
    beta = draw(st.sampled_from([0.5, 0.75, 1.0]))
    seed = draw(st.integers(0, 2 ** 31 - 1))
    return n, h, w, c, win, beta, seed


@given(lrn_cases())
@settings(**SETTINGS)
def test_lrn_backward_matches_central_differences(case):
    """LRN is nonlinear — fuzz the hand-derived backward against central
    differences of the forward for random window sizes/betas (incl.
    window > channels and even windows, where the adjoint padding
    asymmetry matters)."""
    from znicz_tpu.ops import lrn

    n, h, w, c, win, beta, seed = case
    alpha, k = 1e-2, 2.0
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, h, w, c))
    e = rng.normal(size=x.shape)
    grad = lrn.backward(np, x, e, alpha, beta, k, win)
    # directional derivative along a random direction
    d = rng.normal(size=x.shape)
    eps = 1e-6
    fp = (lrn.forward(np, x + eps * d, alpha, beta, k, win) * e).sum()
    fm = (lrn.forward(np, x - eps * d, alpha, beta, k, win) * e).sum()
    np.testing.assert_allclose((grad * d).sum(), (fp - fm) / (2 * eps),
                               rtol=1e-4, atol=1e-7)


@given(st.sampled_from([activations.TANH, activations.RELU,
                        activations.STRICT_RELU, activations.SIGMOID,
                        activations.LOG, activations.SINCOS,
                        activations.TANHLOG, activations.LINEAR]),
       st.integers(0, 2 ** 31 - 1))
@settings(**SETTINGS)
def test_activation_backward_matches_central_differences(name, seed):
    """Every activation's backward against central differences, fuzzed
    over random inputs (the standalone units' derivative_from_input
    path)."""
    rng = np.random.default_rng(seed)
    v = rng.normal(size=(3, 16)) * 2.0
    # keep away from the kink/switch points where the one-sided
    # derivative is ill-defined (strict relu at 0, tanhlog at |v|=d)
    if name == activations.STRICT_RELU:
        v = v + np.sign(v) * 0.05
    if name == activations.TANHLOG:
        d = activations.TANHLOG_D
        v = np.where(abs(abs(v) - d) < 0.05, v + 0.1 * np.sign(v), v)
    e = rng.normal(size=v.shape)
    y = activations.forward(np, name, v)
    # the production path of the standalone units (ActivationBackward):
    # derivative_from_input covers log/sincos/tanhlog and falls back to
    # the from-output form for the rest
    grad = e * activations.derivative_from_input(np, name, v, y.copy())
    dd = rng.normal(size=v.shape)
    eps = 1e-6
    fp = (activations.forward(np, name, v + eps * dd) * e).sum()
    fm = (activations.forward(np, name, v - eps * dd) * e).sum()
    np.testing.assert_allclose((grad * dd).sum(), (fp - fm) / (2 * eps),
                               rtol=2e-4, atol=1e-6, err_msg=name)


@st.composite
def pool_fuzz_cases(draw):
    ky = draw(st.integers(1, 4))
    kx = draw(st.integers(1, 4))
    sy = draw(st.integers(1, 4))
    sx = draw(st.integers(1, 4))
    # h/w may be SMALLER than the kernel (single clipped window) —
    # pool_out_size returns 1 and the taps path pads up to the kernel
    h = draw(st.integers(1, 12))
    w = draw(st.integers(1, 12))
    n = draw(st.integers(1, 2))
    c = draw(st.integers(1, 3))
    quantize = draw(st.booleans())
    seed = draw(st.integers(0, 2 ** 31 - 1))
    return n, h, w, c, ky, kx, sy, sx, quantize, seed


@given(pool_fuzz_cases())
@settings(**SETTINGS)
def test_maxpool_fast_paths_match_reduce_window_fuzz(case):
    """Random geometry fuzz for the no-select-and-scatter max-pool paths
    (reshape + strided-taps dispatch): values exact vs reduce_window,
    gradient support identical, magnitudes within sum-order tolerance."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n, h, w, c, ky, kx, sy, sx, quantize, seed = case
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, h, w, c)).astype(np.float32)
    if quantize:
        x = np.round(x)
    xj = jnp.asarray(x)

    def ref(t):
        pb, pr = pooling._border_pad(h, w, ky, kx, sy, sx)
        return lax.reduce_window(
            t, -jnp.inf, lax.max, (1, ky, kx, 1), (1, sy, sx, 1),
            ((0, 0), (0, pb), (0, pr), (0, 0)))

    y_new, vjp_new = jax.vjp(
        lambda t: pooling.max_forward_fast(t, ky, kx, sy, sx), xj)
    y_old, vjp_old = jax.vjp(ref, xj)
    np.testing.assert_array_equal(np.asarray(y_new), np.asarray(y_old))
    g = jnp.asarray(rng.normal(size=y_new.shape).astype(np.float32))
    dn = np.asarray(vjp_new(g)[0])
    do = np.asarray(vjp_old(g)[0])
    np.testing.assert_array_equal(dn != 0, do != 0)
    np.testing.assert_allclose(dn, do, rtol=1e-6, atol=1e-6)
