"""Tests for the aux-unit long tail (SURVEY.md §3.1): LR schedules,
rollback, mean/disp normalization, cutter, resizable FC, zero-filling."""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.backends import NumpyDevice, TPUDevice
from znicz_tpu.core.memory import Array
from znicz_tpu.core.workflow import Workflow
from znicz_tpu.standard_workflow import StandardWorkflow
from znicz_tpu.units.cutter import Cutter, GDCutter
from znicz_tpu.units.lr_adjust import (ArbitraryStepPolicy, ExpPolicy,
                                       InvPolicy, LearningRateAdjust,
                                       StepExpPolicy)
from znicz_tpu.units.mean_disp_normalizer import MeanDispNormalizer
from znicz_tpu.units.nn_rollback import NNRollback
from znicz_tpu.units.resizable_all2all import ResizableAll2All
from znicz_tpu.units.weights_zerofilling import ZeroFiller


def test_lr_policies():
    assert ExpPolicy(0.5)(1.0, 2) == 0.25
    assert abs(InvPolicy(1.0, 1.0)(1.0, 1) - 0.5) < 1e-9
    assert StepExpPolicy(0.1, 10)(1.0, 25) == pytest.approx(0.01)
    pol = ArbitraryStepPolicy([(0.1, 2), (0.01, 3)])
    assert [pol(1.0, i) for i in range(7)] == \
        [0.1, 0.1, 0.01, 0.01, 0.01, 0.01, 0.01]


def test_lr_adjust_mutates_gds():
    class FakeGD:
        learning_rate = 0.1
        learning_rate_bias = 0.2

    gd = FakeGD()
    adj = LearningRateAdjust(None, lr_policy=ExpPolicy(0.5))
    adj.add_gd_unit(gd)
    adj.run()
    assert gd.learning_rate == 0.1
    adj.run()
    assert gd.learning_rate == 0.05
    assert gd.learning_rate_bias == 0.1


def test_lr_adjust_in_training_loop():
    """Schedule takes effect inside the fused step (no recompile needed)."""
    prng.seed_all(12)
    w = StandardWorkflow(
        name="LRTest",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16,
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "output_sample_shape": 4,
                 "<-": {"learning_rate": 0.1}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 4, "sample_shape": (8,), "n_train": 80,
                       "n_valid": 0, "minibatch_size": 20},
        decision_config={"max_epochs": 3})
    adj = LearningRateAdjust(w, lr_policy=ExpPolicy(0.5), by_epoch=True)
    adj.decision = w.decision
    for gd in w.gds:
        adj.add_gd_unit(gd)
    # wire into the loop: decision -> adj -> repeater
    w.repeater.links_from.clear()
    w.decision.links_to.remove(w.repeater)
    adj.link_from(w.decision)
    w.repeater.link_from(adj)
    w.initialize(device=TPUDevice())
    w.run()
    # epochs 1 and 2 end with an adjustment (iterations 0, 1); the walk
    # stops at end_point on epoch 3's completion before the adjuster fires
    assert w.gds[0].learning_rate == pytest.approx(0.1 * 0.5)


def test_mean_disp_normalizer_backends():
    rng = np.random.default_rng(0)
    x = (rng.normal(size=(6, 4, 4, 2)) * 3 + 1).astype(np.float32)
    outs = []
    for device in (NumpyDevice(), TPUDevice()):
        w = Workflow(name="t")
        u = MeanDispNormalizer(w)
        u.input = Array(x.copy())
        u.fit(x)
        u.initialize(device=device)
        u.run()
        outs.append(u.output.map_read())
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)
    # normalized range is within [-1, 1] per feature by construction
    assert np.abs(outs[0]).max() <= 1.0 + 1e-5


def test_cutter_and_gd():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    for device in (NumpyDevice(), TPUDevice()):
        w = Workflow(name="t")
        cut = Cutter(w, offset=(2, 1), size=(4, 5))
        cut.input = Array(x.copy())
        cut.initialize(device=device)
        cut.run()
        np.testing.assert_array_equal(cut.output.map_read(),
                                      x[:, 2:6, 1:6, :])
        gd = GDCutter(w)
        gd.link_from_forward(cut)
        err = rng.normal(size=cut.output.shape).astype(np.float32)
        gd.err_output = Array(err)
        gd.initialize(device=device)
        gd.run()
        ein = gd.err_input.map_read()
        np.testing.assert_array_equal(ein[:, 2:6, 1:6, :], err)
        assert ein.sum() == pytest.approx(err.sum(), rel=1e-6)


def test_resizable_all2all():
    prng.seed_all(3)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 6)).astype(np.float32)
    w = Workflow(name="t")
    u = ResizableAll2All(w, output_sample_shape=5)
    u.input = Array(x)
    u.initialize(device=TPUDevice())
    u.run()
    w_before = u.weights.map_read().copy()
    y_before = u.output.map_read().copy()
    u.resize(8)
    u.run()
    assert u.output.shape == (4, 8)
    np.testing.assert_array_equal(u.weights.map_read()[:, :5], w_before)
    np.testing.assert_allclose(u.output.map_read()[:, :5], y_before,
                               rtol=1e-5, atol=1e-6)
    u.resize(3)
    u.run()
    assert u.output.shape == (4, 3)
    np.testing.assert_array_equal(u.weights.map_read(), w_before[:, :3])


def test_zero_filler():
    prng.seed_all(4)
    rng = np.random.default_rng(5)
    w = Workflow(name="t")
    u = ResizableAll2All(w, output_sample_shape=4)
    u.input = Array(rng.normal(size=(2, 6)).astype(np.float32))
    u.initialize(device=NumpyDevice())
    mask = np.ones((6, 4), np.float32)
    mask[2:4, :] = 0.0
    zf = ZeroFiller(w)
    zf.add_target(u, mask)
    zf.run()
    assert np.all(u.weights.map_read()[2:4, :] == 0.0)
    assert np.all(u.weights.map_read()[0] != 0.0)
    with pytest.raises(ValueError):
        zf.add_target(u, np.ones((3, 3)))


def test_nn_rollback_restores_and_cuts_lr():
    prng.seed_all(6)
    w = StandardWorkflow(
        name="RbTest",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8,
                 "<-": {"learning_rate": 0.1}},
                {"type": "softmax", "output_sample_shape": 3,
                 "<-": {"learning_rate": 0.1}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 3, "sample_shape": (6,), "n_train": 60,
                       "n_valid": 30, "minibatch_size": 10},
        decision_config={"max_epochs": 2})
    w.initialize(device=TPUDevice())
    w.run()
    rb = NNRollback(w, lr_cut=0.5, fail_iterations=1)
    rb.link_workflow_state(w)
    # simulate: improvement -> store
    w.decision.epoch_ended.set(True)
    w.decision.improved.set(True)
    rb.run()
    good = w.forwards[0].weights.map_read().copy()
    # corrupt weights, then a failing epoch triggers restore + lr cut
    w.step.sync_to_units()
    w.forwards[0].weights.map_invalidate()
    w.forwards[0].weights.mem = np.full_like(good, np.nan)
    w.step._params = w.step.gather_params()
    w.decision.improved.set(False)
    rb.run()
    assert rb.rollback_count == 1
    np.testing.assert_array_equal(w.forwards[0].weights.map_read(), good)
    assert w.gds[0].learning_rate == pytest.approx(0.05)
    # training continues from the restored state: one more fused step
    # runs with the CUT learning rate (hyper cache re-reads the gd units)
    import jax

    assert float(jax.device_get(
        w.step._hyper_device()[0]["lr"])) == pytest.approx(0.05)
    w.loader.run()
    w.step.run()
    w.step.flush_metrics()
    assert np.isfinite(w.step.loss)
    w.step.sync_to_units()
    assert np.isfinite(w.forwards[0].weights.map_read()).all()


# -- diversity diagnostic (SURVEY §3.1) --------------------------------------

def test_diversity_groups_duplicate_kernels():
    from znicz_tpu.units.diversity import (Diversity, get_similar_kernels,
                                           kernels_of, similarity_matrix)

    rng = np.random.default_rng(4)
    w = rng.normal(size=(6, 20)).astype(np.float32)
    w[3] = w[0] * 2.0 + 0.1          # correlated with kernel 0
    w[5] = w[2] * 0.5                # correlated with kernel 2
    sim = similarity_matrix(w)
    np.testing.assert_allclose(np.diag(sim), 1.0, rtol=1e-5)
    groups = get_similar_kernels(w, threshold=0.95)
    assert [0, 3] in groups and [2, 5] in groups
    assert get_similar_kernels(rng.normal(size=(6, 20)), 0.95) == []


def test_diversity_unit_reports_on_workflow():
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.units.all2all import All2All
    from znicz_tpu.units.diversity import Diversity
    from znicz_tpu.core.memory import Array

    prng.seed_all(8)
    w = Workflow(name="d")
    fc = All2All(w, output_sample_shape=8)
    fc.input = Array()
    fc.input.mem = np.zeros((4, 10), np.float32)
    fc.initialize(device=NumpyDevice())
    # plant duplicates: two output kernels share a column direction
    wm = fc.weights.map_read().copy()
    wm[:, 5] = wm[:, 1] * 3.0
    fc.weights.map_invalidate()
    fc.weights.mem = wm
    unit = Diversity(w, threshold=0.95).link_forwards([fc])
    unit.run()
    assert 0 in unit.report
    assert [1, 5] in unit.report[0]


# -- publishing (SURVEY §3.3) ------------------------------------------------

def test_publisher_markdown_and_html(tmp_path):
    from znicz_tpu.models import wine
    from znicz_tpu.utils.publishing import Publisher

    prng.seed_all(3)
    w = wine.build(max_epochs=2, n_train=60, n_valid=30, minibatch_size=10)
    w.initialize(device=TPUDevice())
    w.run()
    md = Publisher(backend="markdown",
                   directory=str(tmp_path)).publish(w)
    text = open(md).read()
    assert "training report" in text
    assert "metric_validation" in text
    assert "Timing" in text and "Config" in text
    assert str(int(w.decision.best_metric)) in text
    ht = Publisher(backend="html", directory=str(tmp_path)).publish(w)
    html_text = open(ht).read()
    assert html_text.startswith("<!doctype html>")
    assert "metric_validation" in html_text


def test_cli_publish_flag(tmp_path, monkeypatch):
    import textwrap
    from znicz_tpu.__main__ import main as cli_main

    wf = tmp_path / "wf.py"
    wf.write_text(textwrap.dedent("""
        from znicz_tpu.models import wine
        def run(load, main):
            load(wine.build, max_epochs=1, n_train=60, n_valid=30,
                 minibatch_size=10)
            main()
        """))
    monkeypatch.chdir(tmp_path)
    rc = cli_main([str(wf), "--publish", "markdown", "-d", "tpu",
                   "--random-seed", "4"])
    assert rc == 0
    assert (tmp_path / "winedemo_report.md").exists() or \
        any(p.suffix == ".md" for p in tmp_path.iterdir()), \
        list(tmp_path.iterdir())
