"""Service-layer tests: plotters, ImageSaver, web status, forward export +
forge (SURVEY.md §3.3 Graphics/Web/Forge rows, §4.5 inference path)."""

import json
import os
import urllib.request

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.backends import TPUDevice
from znicz_tpu.core.memory import Array
from znicz_tpu.models import kohonen as kohonen_model, wine
from znicz_tpu.plotting import (AccumulatingPlotter, Histogram, ImagePlotter,
                                MatrixPlotter)
from znicz_tpu.units.image_saver import ImageSaver
from znicz_tpu.units.nn_plotting import (KohonenHits, KohonenInputMaps,
                                         KohonenNeighborMap, MultiHistogram,
                                         Weights2D, tile_filters)
from znicz_tpu.utils.export import (ExportedForward, export_forward,
                                    forge_fetch, forge_list, forge_publish)
from znicz_tpu.web_status import WebStatus


def _trained_wine(seed=3, **kw):
    prng.seed_all(seed)
    w = wine.build(max_epochs=3, n_train=60, n_valid=30, minibatch_size=10,
                   **kw)
    w.initialize(device=TPUDevice())
    w.run()
    w.stop()
    return w


def test_plotters_render_files(tmp_path):
    w = _trained_wine()
    acc = AccumulatingPlotter(None, name="err_curve",
                              directory=str(tmp_path))
    for v in (5.0, 3.0, 1.0):
        acc.input = v
        acc.run()
    assert acc.render_count == 3 and os.path.exists(acc.last_path)

    mat = MatrixPlotter(None, name="confusion", directory=str(tmp_path))
    mat.input = np.array([[5, 1], [0, 7]])
    mat.run()
    assert os.path.exists(mat.last_path)

    img = ImagePlotter(None, name="sample", directory=str(tmp_path))
    img.input = np.zeros((8, 8, 1), np.float32)
    img.run()
    hist = Histogram(None, name="whist", directory=str(tmp_path))
    hist.input = w.forwards[0].weights
    hist.run()
    w2d = Weights2D(None, name="w2d", directory=str(tmp_path),
                    sample_shape=(13, 1))
    w2d.input = w.forwards[0].weights
    w2d.run()
    mh = MultiHistogram(None, name="mh", directory=str(tmp_path))
    mh.inputs = [f.weights for f in w.forwards]
    mh.run()
    assert len(os.listdir(tmp_path)) == 6


def test_tile_filters_shapes():
    grid = tile_filters(np.random.default_rng(0).normal(size=(16, 9))
                        .astype(np.float32))
    assert grid.shape == (3 * 5 - 1, 3 * 5 - 1)
    conv_grid = tile_filters(np.random.default_rng(0)
                             .normal(size=(3, 3, 2, 4)).astype(np.float32))
    assert conv_grid.shape == (2 * 4 - 1, 2 * 4 - 1)


def test_kohonen_plotters(tmp_path):
    prng.seed_all(23)
    w = kohonen_model.build(max_epochs=2, shape=(4, 4), n_train=200)
    w.initialize(device=TPUDevice())
    w.run()
    w.forward.batch_size = 50
    w.forward.input = w.loader.minibatch_data
    w.forward.run()
    for cls, attr in ((KohonenHits, "forward"), (KohonenInputMaps, "trainer"),
                      (KohonenNeighborMap, "trainer")):
        p = cls(None, name=cls.__name__, directory=str(tmp_path))
        setattr(p, attr, getattr(w, attr))
        p.run()
        assert os.path.exists(p.last_path)


def test_image_saver(tmp_path):
    prng.seed_all(9)
    saver = ImageSaver(None, directory=str(tmp_path), limit=4)
    rng = np.random.default_rng(0)
    saver.input = Array(rng.normal(size=(10, 6, 6, 1)).astype(np.float32))
    probs = np.full((10, 3), 0.2, np.float32)
    probs[:, 0] = 0.6                      # predict class 0 for everyone
    saver.output = Array(probs)
    saver.labels = Array(np.arange(10, dtype=np.int32) % 3)
    saver.minibatch_size = 10
    saver.minibatch_class = 2
    saver.epoch_number = 1
    saver.run()
    saver.flush()
    assert 0 < len(saver.saved_paths) <= 4
    for p in saver.saved_paths:
        assert os.path.exists(p)


def test_web_status_endpoint():
    w = _trained_wine()
    ws = WebStatus(port=0).register(w)
    port = ws.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status.json", timeout=5) as r:
            payload = json.loads(r.read())
        assert payload["workflows"][0]["name"] == "Wine"
        assert payload["workflows"][0]["complete"] is True
        assert len(payload["workflows"][0]["history"]) == 3
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=5) as r:
            assert b"Wine" in r.read()
    finally:
        ws.stop()


def test_export_and_forge_roundtrip(tmp_path):
    w = _trained_wine()
    pkg = str(tmp_path / "wine.npz")
    export_forward(w, pkg)
    model = ExportedForward(pkg)
    loader = w.loader
    data = loader.original_data.map_read()[:12]
    probs = model(data)
    assert probs.shape == (12, 3)
    # exported forward == the workflow's own eval forward (the fused chain
    # returns pre-softmax logits when the loss composes log_softmax)
    import jax
    w.step.sync_to_units()
    ref, logits_tail = w.step._forward_chain(w.step._params, data,
                                             train=False)
    assert logits_tail
    np.testing.assert_allclose(probs, np.asarray(jax.nn.softmax(ref, axis=1)),
                               rtol=1e-5, atol=1e-6)

    repo = str(tmp_path / "forge")
    forge_publish(pkg, repo, "wine", "1.0",
                  metrics={"best": w.decision.best_metric})
    forge_publish(pkg, repo, "wine", "1.1")
    assert forge_list(repo) == {"wine": ["1.0", "1.1"]}
    fetched = forge_fetch(repo, "wine")          # latest
    np.testing.assert_allclose(fetched(data), probs, rtol=1e-6)


# -- forge registry (SURVEY §3.3) --------------------------------------------

def test_forge_upload_fetch_roundtrip(tmp_path):
    import numpy as np
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models import wine
    from znicz_tpu.utils.export import ExportedForward
    from znicz_tpu.utils.forge import ForgeRegistry

    prng.seed_all(3)
    w = wine.build(max_epochs=2, n_train=60, n_valid=30, minibatch_size=10)
    w.initialize(device=TPUDevice())
    w.run()
    w.stop()

    reg = ForgeRegistry(str(tmp_path / "registry"))
    entry = reg.upload_workflow(w, "wine", "1.0")
    assert entry["metadata"]["workflow"] == "WineDemo" or \
        entry["metadata"]["workflow"] == w.name
    assert reg.list_packages() == {"wine": ["1.0"]}
    # immutability
    import pytest
    with pytest.raises(FileExistsError):
        reg.upload_workflow(w, "wine", "1.0")
    reg.upload_workflow(w, "wine", "1.1")
    # latest fetch + checksum + inference parity with a direct export
    from znicz_tpu.utils.export import export_forward
    direct = str(tmp_path / "direct.npz")
    export_forward(w, direct)
    dest = reg.fetch("wine", dest=str(tmp_path / "got.npz"))
    loaded = ExportedForward(dest)
    x = np.asarray(w.loader.original_data.map_read()[:8], np.float32)
    np.testing.assert_allclose(loaded(x), ExportedForward(direct)(x),
                               rtol=1e-6)
    # in-place fetch serves the registry file itself (no copy)
    in_place = reg.fetch("wine")
    assert in_place.startswith(str(tmp_path / "registry"))
    with pytest.raises(KeyError):
        reg.fetch("nonexistent")
    with pytest.raises(KeyError):
        reg.fetch("wine", "9.9")


def test_forge_detects_corruption(tmp_path):
    import numpy as np
    from znicz_tpu.utils.forge import ForgeRegistry

    pkg = tmp_path / "pkg.npz"
    np.savez(pkg, a=np.arange(3))
    reg = ForgeRegistry(str(tmp_path / "reg"))
    reg.upload(str(pkg), "thing", "0.1")
    # corrupt the stored file
    stored = tmp_path / "reg" / "thing-0.1.npz"
    stored.write_bytes(b"corrupted")
    import pytest
    with pytest.raises(IOError, match="sha256"):
        reg.fetch("thing", dest=str(tmp_path / "out.npz"))


def test_launcher_profile_trace(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.launcher import Launcher
    from znicz_tpu.models import wine

    prng.seed_all(3)
    launcher = Launcher(device=TPUDevice(),
                        profile_dir=str(tmp_path / "trace"))
    launcher.load(wine.build, max_epochs=1, n_train=60, n_valid=30,
                  minibatch_size=10)
    launcher.main()
    import os
    found = []
    for base, _dirs, files in os.walk(tmp_path / "trace"):
        found += files
    assert found, "no profiler trace files written"


def test_trace_summary_reports_top_ops(tmp_path):
    """summarize_trace turns a jax.profiler dump into a top-ops table
    (CPU traces summarize the host plane with python frames dropped)."""
    import jax
    import jax.numpy as jnp

    from znicz_tpu.utils.profiling import format_summary, summarize_trace

    d = str(tmp_path / "trace")
    with jax.profiler.trace(d):
        x = jnp.ones((128, 128))
        for _ in range(3):
            x = jnp.tanh(x @ x)
        jax.block_until_ready(x)
    rows = summarize_trace(d, top=10)
    assert rows and all(r["total_ms"] >= 0 for r in rows)
    assert not any(r["op"].startswith("$") for r in rows)
    text = format_summary(rows)
    assert "total_ms" in text and len(text.splitlines()) == len(rows) + 1


def test_manhole_repl_session():
    """Live-REPL service (the reference's manhole): expressions echo
    their repr, statements exec with stdout captured, errors return a
    traceback without killing the session.  The socket is AF_UNIX with
    0600 permissions — other local uids must not reach the exec REPL."""
    import os
    import socket
    import stat
    import time

    from znicz_tpu.utils.manhole import Manhole

    hole = Manhole(namespace={"answer": 41})
    path = hole.start()
    try:
        mode = os.stat(path).st_mode
        assert stat.S_ISSOCK(mode)
        assert stat.S_IMODE(mode) == 0o600            # owner-only
        assert stat.S_IMODE(os.stat(os.path.dirname(path)).st_mode) == 0o700
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(5)
        conn.connect(path)
        for line in ("answer + 1", "x = answer * 2", "print(x)", "1/0"):
            conn.sendall(line.encode() + b"\n")
        time.sleep(0.5)
        out = conn.recv(65536).decode()
        assert "manhole" in out                       # banner
        assert "42" in out                            # expression repr
        assert "82" in out                            # statement stdout
        assert "ZeroDivisionError" in out             # traceback, not death
        conn.sendall(b"answer\n")                     # session survived
        time.sleep(0.3)
        assert "41" in conn.recv(65536).decode()
        conn.close()
    finally:
        hole.stop()
    # teardown: listener closed, serving thread exited, socket unlinked
    assert hole._sock.fileno() == -1
    assert not hole._thread.is_alive()
    assert not os.path.exists(path)


def test_launcher_serves_manhole():
    """Launcher with manhole_path="" (auto private socket) serves the
    live workflow namespace during the run and tears it down after."""
    import socket
    import time

    from znicz_tpu.launcher import Launcher
    from znicz_tpu.models import wine

    prng.seed_all(3)
    launcher = Launcher(device=TPUDevice(), manhole_path="")
    launcher.load(wine.build, max_epochs=1, n_train=60, n_valid=30,
                  minibatch_size=10)

    # probe the manhole DURING the run, from the decision's epoch hook
    seen = {}
    wf = launcher.workflow
    orig_run = wf.decision.run

    def probing_run():
        orig_run()
        if launcher.manhole is not None and "reply" not in seen:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(5)
            conn.connect(launcher.manhole.path)
            conn.sendall(b"wf.name\n")
            time.sleep(0.3)
            seen["reply"] = conn.recv(65536).decode()
            conn.close()

    wf.decision.run = probing_run
    launcher.main()
    assert "Wine" in seen.get("reply", ""), seen
    assert launcher.manhole._sock.fileno() == -1      # torn down
