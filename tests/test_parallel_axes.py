"""Multi-axis parallelism tests on the virtual 8-device CPU mesh
(SURVEY.md §5 tier-3): each strategy is pinned exactly equal to its
single-device dense formulation — ring attention (sp), Megatron column/row
(tp), top-1 MoE (ep), GPipe microbatching (pp)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from znicz_tpu.ops import attention as att_ops
from znicz_tpu.parallel.mesh import make_mesh
from znicz_tpu.parallel.moe import moe_ffn
from znicz_tpu.parallel.pipeline import pipeline_apply
from znicz_tpu.parallel.ring_attention import ring_attention
from znicz_tpu.parallel import tp


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(cpu_devices, causal):
    mesh = make_mesh({"seq": 4})
    rng = np.random.default_rng(0)
    b, t, h, dh = 2, 32, 4, 16
    q, k, v = (rng.normal(size=(b, t, h, dh)).astype(np.float32)
               for _ in range(3))
    dense = att_ops.attention(np, q, k, v, causal=causal)

    ring = shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, "seq", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"))
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-4, atol=2e-5)


def test_mha_numpy_vs_jnp():
    rng = np.random.default_rng(1)
    b, t, d, heads = 2, 8, 32, 4
    x = rng.normal(size=(b, t, d)).astype(np.float32)
    params = {f"w{n}": rng.normal(0, 0.1, (d, d)).astype(np.float32)
              for n in "qkvo"}
    params.update({f"b{n}": rng.normal(0, 0.1, (d,)).astype(np.float32)
                   for n in "qkvo"})
    y_np = att_ops.mha_forward(np, x, params, heads, causal=True)
    y_x = att_ops.mha_forward(jnp, jnp.asarray(x),
                              {k: jnp.asarray(v) for k, v in params.items()},
                              heads, causal=True)
    np.testing.assert_allclose(np.asarray(y_x), y_np, rtol=2e-4, atol=2e-5)


def test_ring_mha_matches_dense_mha(cpu_devices):
    """The unit-level ring MHA wrapper equals the dense MHA op."""
    from znicz_tpu.parallel.ring_attention import ring_mha_forward
    mesh = make_mesh({"seq": 4})
    rng = np.random.default_rng(7)
    b, t, d, heads = 2, 16, 32, 4
    x = rng.normal(size=(b, t, d)).astype(np.float32)
    params = {f"w{n}": rng.normal(0, 0.1, (d, d)).astype(np.float32)
              for n in "qkvo"}
    dense = att_ops.mha_forward(np, x, params, heads, causal=True)
    f = shard_map(
        lambda x_, p_: ring_mha_forward(x_, p_, heads, "seq", causal=True),
        mesh=mesh, in_specs=(P(None, "seq"), P()), out_specs=P(None, "seq"))
    out = jax.jit(f)(x, params)
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-4, atol=2e-5)


def test_tensor_parallel_mlp_matches_dense(cpu_devices):
    mesh = make_mesh({"model": 4})
    rng = np.random.default_rng(2)
    n, d, ff = 8, 16, 32
    x = rng.normal(size=(n, d)).astype(np.float32)
    w1 = rng.normal(0, 0.1, (d, ff)).astype(np.float32)
    b1 = rng.normal(0, 0.1, (ff,)).astype(np.float32)
    w2 = rng.normal(0, 0.1, (ff, d)).astype(np.float32)
    b2 = rng.normal(0, 0.1, (d,)).astype(np.float32)
    dense = np.maximum(x @ w1 + b1, 0.0) @ w2 + b2

    f = shard_map(
        lambda x_, w1_, b1_, w2_, b2_: tp.mlp(
            x_, w1_, b1_, w2_, b2_, lambda a: jnp.maximum(a, 0.0), "model"),
        mesh=mesh,
        in_specs=(P(), P(None, "model"), P("model"), P("model", None), P()),
        out_specs=P())
    out = jax.jit(f)(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-4, atol=2e-5)


def test_moe_expert_parallel_top1(cpu_devices):
    mesh = make_mesh({"expert": 4})
    rng = np.random.default_rng(3)
    tokens, d, ff, E = 16, 8, 16, 8      # 2 experts per device
    x = rng.normal(size=(tokens, d)).astype(np.float32)
    gate_w = rng.normal(0, 1.0, (d, E)).astype(np.float32)
    w1 = rng.normal(0, 0.1, (E, d, ff)).astype(np.float32)
    b1 = rng.normal(0, 0.1, (E, ff)).astype(np.float32)
    w2 = rng.normal(0, 0.1, (E, ff, d)).astype(np.float32)
    b2 = rng.normal(0, 0.1, (E, d)).astype(np.float32)

    # dense single-device oracle
    scores = x @ gate_w
    probs = np.exp(scores - scores.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    choice = scores.argmax(1)
    oracle = np.zeros_like(x)
    for t in range(tokens):
        e = choice[t]
        h = np.maximum(x[t] @ w1[e] + b1[e], 0.0)
        oracle[t] = (h @ w2[e] + b2[e]) * probs[t, e]

    f = shard_map(
        lambda x_, g_, w1_, b1_, w2_, b2_: moe_ffn(
            x_, g_, w1_, b1_, w2_, b2_,
            lambda a: jnp.maximum(a, 0.0), "expert")[0],
        mesh=mesh,
        in_specs=(P(), P(), P("expert"), P("expert"), P("expert"),
                  P("expert")),
        out_specs=P())
    out = jax.jit(f)(x, gate_w, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), oracle, rtol=2e-4, atol=2e-5)


def test_pipeline_matches_sequential(cpu_devices):
    mesh = make_mesh({"pipe": 4})
    rng = np.random.default_rng(4)
    n_micro, mb, d = 6, 4, 8
    xs = rng.normal(size=(n_micro, mb, d)).astype(np.float32)
    # 4 stages of tanh(x @ W_s + b_s), stacked on the leading axis
    ws = rng.normal(0, 0.5, (4, d, d)).astype(np.float32)
    bs = rng.normal(0, 0.1, (4, d)).astype(np.float32)

    seq = xs.copy()
    for s in range(4):
        seq = np.tanh(seq @ ws[s] + bs[s])

    def stage_fn(params, x):
        w, b = params
        return jnp.tanh(x @ w[0] + b[0])

    f = shard_map(
        lambda xs_, w_, b_: pipeline_apply(stage_fn, (w_, b_), xs_, 4,
                                           "pipe"),
        mesh=mesh,
        in_specs=(P(), P("pipe"), P("pipe")),
        out_specs=P())
    out = jax.jit(f)(xs, ws, bs)
    np.testing.assert_allclose(np.asarray(out), seq, rtol=2e-4, atol=2e-5)
