"""Tier-1 tests for the pure op layer (SURVEY.md §5: per-op parity vs a
numpy re-derivation + numeric-derivative checks)."""

import numpy as np
import jax.numpy as jnp
import pytest

from znicz_tpu.ops import activations, linear, sgd

ACTS = [activations.LINEAR, activations.TANH, activations.RELU,
        activations.STRICT_RELU, activations.SIGMOID]


@pytest.mark.parametrize("act", ACTS)
def test_activation_derivative_matches_numeric(act):
    rng = np.random.default_rng(0)
    # keep away from the strict_relu kink where the numeric diff is invalid
    x = rng.uniform(0.1, 2.0, 64).astype(np.float64) * \
        np.where(rng.uniform(size=64) < 0.5, -1.0, 1.0)
    eps = 1e-6
    y = activations.forward(np, act, x)
    dy = activations.derivative_from_output(np, act, y)
    num = (activations.forward(np, act, x + eps) -
           activations.forward(np, act, x - eps)) / (2 * eps)
    np.testing.assert_allclose(dy, num, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("act", ACTS)
def test_activation_numpy_vs_jnp(act):
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 16)).astype(np.float32)
    got = np.asarray(activations.forward(jnp, act, jnp.asarray(x)))
    want = activations.forward(np, act, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_all2all_forward_golden():
    x = np.array([[1.0, 2.0]], np.float32)
    w = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
    b = np.array([0.5, -0.5], np.float32)
    y = linear.forward(np, x, w, b)
    np.testing.assert_allclose(y, [[1.5, 1.5]])


def test_softmax_forward_rows_sum_to_one():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 6)).astype(np.float32)
    w = rng.normal(size=(6, 3)).astype(np.float32)
    b = rng.normal(size=(3,)).astype(np.float32)
    y, idx = linear.softmax_forward(np, x, w, b)
    np.testing.assert_allclose(y.sum(axis=1), np.ones(4), rtol=1e-6)
    v = x @ w + b
    np.testing.assert_array_equal(idx, v.argmax(axis=1))
    yj, idxj = linear.softmax_forward(jnp, jnp.asarray(x), jnp.asarray(w),
                                      jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(yj), y, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(idxj), idx)


@pytest.mark.parametrize("act", ACTS)
def test_all2all_backward_numeric_gradient(act):
    """Analytic err_input / grad_w / grad_b vs central differences of a
    scalar loss L = sum(y * r) (r fixed random)."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(3, 5)).astype(np.float64)
    w = rng.normal(size=(5, 4)).astype(np.float64)
    b = rng.normal(size=(4,)).astype(np.float64)
    r = rng.normal(size=(3, 4)).astype(np.float64)

    def loss(x_, w_, b_):
        return float((linear.forward(np, x_, w_, b_, act) * r).sum())

    y = linear.forward(np, x, w, b, act)
    err_in, gw, gb = linear.backward(np, x, y, w, r, act)

    eps = 1e-6
    for arr, grad in ((x, err_in), (w, gw), (b, gb)):
        it = np.nditer(arr, flags=["multi_index"])
        for _ in it:
            i = it.multi_index
            orig = arr[i]
            arr[i] = orig + eps
            lp = loss(x, w, b)
            arr[i] = orig - eps
            lm = loss(x, w, b)
            arr[i] = orig
            np.testing.assert_allclose(
                grad[i], (lp - lm) / (2 * eps), rtol=1e-4, atol=1e-6)


def test_sgd_update_momentum_and_decay():
    w = np.full((4,), 2.0)
    grad = np.full((4,), 8.0)
    vel = np.full((4,), 1.0)
    # g = 8/4 + 0.1*w = 2.2 ; vel = 0.5*1 + 0.1*2.2 = 0.72 ; w = 2 - 0.72
    w2, vel2 = sgd.update(np, w, grad, vel, learning_rate=0.1,
                          weights_decay=0.1, l1_vs_l2=0.0,
                          gradient_moment=0.5, batch_size=4)
    np.testing.assert_allclose(vel2, 0.72)
    np.testing.assert_allclose(w2, 1.28)
    # jnp twin
    w2j, vel2j = sgd.update(jnp, jnp.asarray(w), jnp.asarray(grad),
                            jnp.asarray(vel), 0.1, 0.1, 0.0, 0.5, 4)
    np.testing.assert_allclose(np.asarray(w2j), w2, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(vel2j), vel2, rtol=1e-6)


def test_sgd_update_preserves_narrow_vel_dtype():
    """The primitive's dtype contract: math in w's dtype, vel_new
    returned in vel's storage dtype, weight apply uses the wide vel."""
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    g = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    v32 = jnp.asarray(rng.normal(size=(8, 16)) * 0.1, jnp.float32)
    v16 = v32.astype(jnp.bfloat16)
    args = dict(learning_rate=0.05, weights_decay=1e-3, l1_vs_l2=0.2,
                gradient_moment=0.9, batch_size=16.0)
    w_ref, v_ref = sgd.update(jnp, w, g, v16.astype(jnp.float32), **args)
    w_n, v_n = sgd.update(jnp, w, g, v16, **args)
    assert v_n.dtype == jnp.bfloat16 and w_n.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(w_n), np.asarray(w_ref))
    np.testing.assert_array_equal(
        np.asarray(v_n, dtype=np.float32),
        np.asarray(v_ref.astype(jnp.bfloat16), dtype=np.float32))


def test_maxpool_nonoverlap_matches_select_and_scatter():
    """The non-overlapping fast path (reshape-max forward, elementwise
    first-winner backward) is EXACTLY the reduce_window/select-and-
    scatter route — values and gradients, ties included — so swapping
    implementations moves no pins."""
    import jax
    from jax import lax
    from znicz_tpu.ops import pooling as P

    def sas(x, k):
        pb, pr = P._border_pad(x.shape[1], x.shape[2], k, k, k, k)
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, k, k, 1), (1, k, k, 1),
            ((0, 0), (0, pb), (0, pr), (0, 0)))

    rng = np.random.default_rng(0)
    for shape, k in (((4, 8, 8, 3), 2), ((2, 12, 12, 5), 3),
                     ((3, 16, 8, 4), 2)):
        x = rng.normal(size=shape).astype(np.float32)
        xq = np.round(x * 2) / 2          # quantized -> frequent ties
        xq[0, :4, :4, :] = 0.5            # constant block -> full-window tie
        for arr in (x, xq):
            xj = jnp.asarray(arr)
            y_new, vjp_new = jax.vjp(
                lambda t: P._maxpool_nonoverlap(t, k, k), xj)
            y_old, vjp_old = jax.vjp(lambda t: sas(t, k), xj)
            np.testing.assert_array_equal(np.asarray(y_new),
                                          np.asarray(y_old))
            g = jnp.asarray(
                rng.normal(size=y_new.shape).astype(np.float32))
            np.testing.assert_array_equal(np.asarray(vjp_new(g)[0]),
                                          np.asarray(vjp_old(g)[0]))
    # dispatch: every geometry routes away from reduce_window now (the
    # non-overlap reshape path or the general strided-taps path)
    for k, s in ((2, 2), (3, 2)):
        jx = str(jax.make_jaxpr(
            lambda t: P.max_forward_fast(t, k, k, s, s))(
                jnp.zeros((1, 8, 8, 2))))
        assert "reduce_window" not in jx and "custom_vjp" in jx, (k, s)


def test_maxpool_taps_matches_select_and_scatter():
    """The general strided-taps path vs the reduce_window/select-and-
    scatter route, overlapping windows, partial borders, stride>kernel,
    kernel>input: values EXACT; gradients route to the identical input
    positions (support equality) with only float sum-order differences
    where an input wins several windows (1-ULP scale)."""
    import jax
    from jax import lax
    from znicz_tpu.ops import pooling as P

    def sas(x, ky, kx, sy, sx):
        pb, pr = P._border_pad(x.shape[1], x.shape[2], ky, kx, sy, sx)
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, ky, kx, 1), (1, sy, sx, 1),
            ((0, 0), (0, pb), (0, pr), (0, 0)))

    rng = np.random.default_rng(0)
    geoms = [((2, 55, 55, 8), 3, 3, 2, 2),   # AlexNet pool, exact fit
             ((2, 8, 8, 3), 3, 3, 2, 2),     # partial border windows
             ((2, 9, 7, 4), 3, 2, 2, 3),     # asymmetric + partial
             ((2, 10, 10, 2), 2, 2, 3, 3),   # stride > kernel
             ((1, 11, 11, 1), 2, 2, 4, 4),   # stride>kernel, last window
                                             # ends BEFORE the input
             ((2, 5, 5, 2), 7, 7, 1, 1)]     # kernel > input
    for shape, ky, kx, sy, sx in geoms:
        x = rng.normal(size=shape).astype(np.float32)
        xq = np.round(x)                     # heavy in-window ties
        for arr in (x, xq):
            xj = jnp.asarray(arr)
            y_new, vjp_new = jax.vjp(
                lambda t: P._maxpool_taps(t, ky, kx, sy, sx), xj)
            y_old, vjp_old = jax.vjp(
                lambda t: sas(t, ky, kx, sy, sx), xj)
            np.testing.assert_array_equal(np.asarray(y_new),
                                          np.asarray(y_old))
            g = jnp.asarray(
                rng.normal(size=y_new.shape).astype(np.float32))
            dn = np.asarray(vjp_new(g)[0])
            do = np.asarray(vjp_old(g)[0])
            np.testing.assert_array_equal(dn != 0, do != 0)
            np.testing.assert_allclose(dn, do, rtol=1e-6, atol=1e-6)


def test_maxabs_taps_matches_twin_reduce_window():
    """maxabs via strided-taps folds + shared first-winner VJP vs the
    old twin-reduce_window route: values exact, gradient support
    identical (winner routing incl. branch and tie choices), magnitudes
    within float sum-order tolerance."""
    import jax
    from jax import lax
    from znicz_tpu.ops import pooling as P

    def old(x, ky, kx, sy, sx):
        pb, pr = P._border_pad(x.shape[1], x.shape[2], ky, kx, sy, sx)
        dims, strides = (1, ky, kx, 1), (1, sy, sx, 1)
        pad = ((0, 0), (0, pb), (0, pr), (0, 0))
        pos = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        neg = lax.reduce_window(-x, -jnp.inf, lax.max, dims, strides,
                                pad)
        return jnp.where(pos >= neg, pos, -neg)

    rng = np.random.default_rng(0)
    for shape, ky, kx, sy, sx in [((2, 8, 8, 3), 3, 3, 2, 2),
                                  ((2, 9, 7, 4), 3, 2, 2, 3),
                                  ((1, 11, 11, 1), 2, 2, 4, 4),
                                  ((2, 6, 6, 2), 2, 2, 2, 2),
                                  ((2, 5, 5, 2), 7, 7, 1, 1)]:
        x = rng.normal(size=shape).astype(np.float32)
        xq = np.round(x)                   # ties, incl. across signs
        for arr in (x, xq):
            xj = jnp.asarray(arr)
            yn, vn = jax.vjp(
                lambda t: P.maxabs_forward_fast(t, ky, kx, sy, sx), xj)
            yo, vo = jax.vjp(lambda t: old(t, ky, kx, sy, sx), xj)
            np.testing.assert_array_equal(np.asarray(yn),
                                          np.asarray(yo))
            g = jnp.asarray(
                rng.normal(size=yn.shape).astype(np.float32))
            dn = np.asarray(vn(g)[0])
            do = np.asarray(vo(g)[0])
            np.testing.assert_array_equal(dn != 0, do != 0)
            np.testing.assert_allclose(dn, do, rtol=1e-6, atol=1e-6)
