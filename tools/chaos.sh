#!/bin/bash
# Chaos verify — run the resilience plane's fault-injection suite
# standalone, INCLUDING the slow soak tests tier-1 deselects:
#   bash tools/chaos.sh             # full chaos suite
#   bash tools/chaos.sh -k hang     # one scenario
# Drives the real code paths (workflow step loop, snapshot save path,
# serve engine) through znicz_tpu/resilience/faults.py hook sites; see
# docs/RESILIENCE.md for the fault model and how to add a scenario.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly "$@"
