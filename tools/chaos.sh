#!/bin/bash
# Chaos verify — run the resilience plane's fault-injection suite
# standalone, INCLUDING the slow soak tests tier-1 deselects:
#   bash tools/chaos.sh             # full chaos suite
#   bash tools/chaos.sh -k hang     # one scenario
# Drives the real code paths (workflow step loop, snapshot save path,
# serve engine, elastic worker processes) through
# znicz_tpu/resilience/faults.py hook sites; see docs/RESILIENCE.md for
# the fault model and how to add a scenario.  tests/test_elastic.py is
# the multi-PROCESS half: real workers SIGKILL'd and resumed by the
# fleet supervisor.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py \
    tests/test_elastic.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly "$@"
