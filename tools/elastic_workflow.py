"""Elastic-drill workflow — the worker program for the multi-process
kill-and-resume drills (tests/test_elastic.py, tools/elastic_smoke.py,
and the docs/RESILIENCE.md CLI example).

Run under the fleet supervisor:

    python -m znicz_tpu elastic --workers 2 --snap-dir /tmp/snaps \\
        tools/elastic_workflow.py

Reads the fleet's env contract (resilience/elastic.py): the snapshotter
writes into ``$ZNICZ_TPU_SNAP_DIR`` (rank 0 writes, other ranks verify),
``$ZNICZ_TPU_ELASTIC_EPOCHS`` overrides the epoch budget, and on natural
completion each worker drops ``history_<rank>.json`` — the drill's
bit-exactness evidence — next to the snapshots.  A SIGTERM'd worker
exits 143 inside ``main()`` and deliberately never writes a history.

The loader is deliberately noisy (spread 1.2, noise 2.0) so the error
curve stays NON-zero across epochs: a resume bug cannot hide behind a
history of all-zero metrics.
"""

import json
import os

from znicz_tpu.standard_workflow import StandardWorkflow

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
     "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
    {"type": "softmax", "->": {"output_sample_shape": 4},
     "<-": {"learning_rate": 0.02, "gradient_moment": 0.9}},
]
LOADER = {"n_classes": 4, "sample_shape": (8, 8), "n_train": 120,
          "n_valid": 60, "minibatch_size": 30, "spread": 1.2, "noise": 2.0}


def build():
    snap_dir = os.environ.get("ZNICZ_TPU_SNAP_DIR")
    snap_cfg = None
    if snap_dir:
        snap_cfg = {"directory": snap_dir, "prefix": "ew",
                    "only_improved": False, "keep_all": True,
                    "verify_timeout": 2.0}
    return StandardWorkflow(
        name="ElasticDrill", layers=LAYERS, loss_function="softmax",
        loader_name="synthetic_classifier", loader_config=LOADER,
        decision_config={
            "max_epochs": int(os.environ.get("ZNICZ_TPU_ELASTIC_EPOCHS",
                                             "4"))},
        snapshotter_config=snap_cfg)


def run(load, main):
    workflow, _ = load(build)
    main()
    snap_dir = os.environ.get("ZNICZ_TPU_SNAP_DIR")
    if snap_dir:
        rank = os.environ.get("ZNICZ_TPU_ELASTIC_RANK", "0")
        out = os.path.join(snap_dir, f"history_{rank}.json")
        with open(out, "w") as f:
            json.dump({"rank": int(rank),
                       "history": workflow.decision.metrics_history},
                      f, default=float)
