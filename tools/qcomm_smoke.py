"""Quantized-collectives smoke for tools/t1.sh (ISSUE 18): on a forced
4-device CPU mesh, (a) a ``quantized_collectives={"mode": "off"}`` run
must produce the BIT-IDENTICAL seeded metric history to a build that
never passed the config (the off path compiles today's program), (b) an
int8+error-feedback shard_params run must read a ~4x compression ratio
from the ``znicz_qcomm_*`` counters on BOTH collectives (gradient psum
and ZeRO gather; int8 payload + f32 chunk scales ≈ 3.98x), train to a
finite history, and publish a nonzero residual norm.

``ZNICZ_TPU_COMPILE_CACHE=off`` per the box note (the persistent cache
intermittently segfaults single-process workers here).
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") +
    " --xla_force_host_platform_device_count=4").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("ZNICZ_TPU_COMPILE_CACHE", "off")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

N_DEV = 4


def fail(msg: str) -> None:
    print(f"qcomm_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def run_once(quantized_collectives, shard_params: bool = False):
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.observe import registry
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    prng.seed_all(31)
    w = build_fused(max_epochs=2, layers=(32,), minibatch_size=16,
                    n_train=96, n_valid=32,
                    mesh=data_parallel_mesh(N_DEV), optimizer="adam",
                    shard_params=shard_params,
                    quantized_collectives=quantized_collectives)
    w.initialize(device=TPUDevice())
    w.run()
    hist = [h["metric_validation"] for h in w.decision.metrics_history]

    def counters(coll):
        wire = registry.REGISTRY.get("znicz_qcomm_bytes_on_wire_total") \
            .labels(unit="FusedStep", collective=coll).get()
        exact = registry.REGISTRY.get("znicz_qcomm_bytes_exact_total") \
            .labels(unit="FusedStep", collective=coll).get()
        return wire, exact

    stats = {coll: counters(coll) for coll in ("grad_psum", "zero_gather")}
    residual = registry.REGISTRY.get("znicz_qcomm_residual_norm") \
        .labels(unit="FusedStep").get()
    w.stop()
    return hist, stats, residual


def main() -> None:
    hist_base, stats_base, _ = run_once(None)
    if any(v for wire_exact in stats_base.values() for v in wire_exact):
        fail(f"baseline run incremented qcomm counters: {stats_base}")

    hist_off, stats_off, _ = run_once({"mode": "off"})
    if hist_off != hist_base:
        fail(f"mode=off diverged from baseline: {hist_off} != {hist_base}")
    if any(v for wire_exact in stats_off.values() for v in wire_exact):
        fail(f"mode=off incremented qcomm counters: {stats_off}")

    qc = {"mode": "int8", "error_feedback": True}
    hist_q, stats_q, residual = run_once(qc, shard_params=True)
    if len(hist_q) != len(hist_base):
        fail(f"int8 run history length {len(hist_q)} != {len(hist_base)}")
    ratios = {}
    for coll, (wire, exact) in stats_q.items():
        if wire <= 0 or exact <= 0:
            fail(f"{coll}: counters not live (wire={wire}, exact={exact})")
        ratios[coll] = exact / wire
        # int8 payload + one f32 scale per balanced chunk: ~3.98x; the
        # window catches both a broken codec (~1x) and a miscounted
        # exact figure (>4x is impossible for int8+scales)
        if not 3.5 <= ratios[coll] <= 4.0:
            fail(f"{coll}: compression ratio {ratios[coll]:.3f} outside "
                 f"[3.5, 4.0] (wire={wire:.0f}, exact={exact:.0f})")
    if residual <= 0:
        fail(f"error-feedback residual norm not published: {residual}")
    print(f"qcomm_smoke: OK — mode=off history identical over "
          f"{len(hist_base)} epochs; int8 ratios "
          f"grad_psum {ratios['grad_psum']:.2f}x, "
          f"zero_gather {ratios['zero_gather']:.2f}x; "
          f"residual norm {residual:.3e}")


if __name__ == "__main__":
    main()
