"""Serving-fleet smoke for tools/t1.sh (ISSUE 13).

Boots the REAL ``python -m znicz_tpu fleet`` CLI in a fresh process —
which itself spawns 2 real ``generate --serve`` worker processes from
one exported LM package — then, over the wire only:

- streams generations THROUGH the router under light threaded traffic
  (readiness-gated least-loaded routing, X-Request-Id minted at the
  router);
- performs one rolling weight update via ``POST /rollout`` onto a
  second package and polls ``GET /rollout`` to completion;
- asserts ZERO lost requests: every admitted stream carries exactly
  one terminal event (completed or error-sentinel), the router ledger
  closes (admitted == completed + failed + client_gone), and rejected
  requests were refused at admission (503), never silently dropped;
- asserts the fleet CONVERGED: every worker reports the new package's
  sha256 on ``/readyz``, and steady-state decode compiles nothing
  (compile_count delta 0 across post-rollout traffic);
- asserts the merged ``/fleet/metrics.prom`` carries the
  ``znicz_router_*`` families beside the workers' rank-labeled series.

jax-on-CPU; the compile cache is pinned off (the PR 9 box note).
Every failure prints a ``fleet_router_smoke:``-prefixed line, exits 1.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> "None":
    print(f"fleet_router_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def build_packages(tmp: str):
    import numpy as np

    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.utils.export import export_lm
    from znicz_tpu.utils.naming import package_fingerprint

    charmap = list("abcdefghijklmnopqrstuvwxyz .,!?")
    paths = []
    for seed, name in ((31, "lm_v1"), (32, "lm_v2")):
        params = init_params(np.random.default_rng(seed), 2, 32, 4, 64,
                             len(charmap))
        path = os.path.join(tmp, f"{name}.npz")
        export_lm(params, path, heads=4, charmap=charmap, name=name)
        paths.append(path)
    return paths[0], paths[1], package_fingerprint(paths[1])


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def get_json(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="znicz_fleet_router_smoke_")
    proc = None
    stop = threading.Event()
    results = []
    res_lock = threading.Lock()
    try:
        pkg_a, pkg_b, fp_b = build_packages(tmp)
        port = free_port()
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   ZNICZ_TPU_COMPILE_CACHE="off")
        proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "fleet", pkg_a,
             "--workers", "2", "--port", str(port),
             "--run-dir", os.path.join(tmp, "fleet"),
             "--", "--slots", "2", "--max-len", "48"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 240
        while True:
            if proc.poll() is not None:
                out = (proc.stdout.read() or "")[-2000:]
                fail(f"fleet CLI exited rc={proc.returncode} before "
                     f"ready: {out}")
            try:
                if get_json(base + "/readyz", 5)["status"] == "ready":
                    break
            except (urllib.error.URLError, urllib.error.HTTPError,
                    OSError, ValueError):
                pass
            if time.monotonic() > deadline:
                fail("router never reported a ready worker within 240s")
            time.sleep(0.5)

        def client(cid: int) -> None:
            n = 0
            while not stop.is_set():
                n += 1
                req = urllib.request.Request(
                    base + "/generate",
                    data=json.dumps(
                        {"prompt": "ab" if cid % 2 else "cd",
                         "max_tokens": 5, "timeout_s": 30}).encode(),
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=90) as r:
                        lines = [json.loads(raw) for raw in r]
                except urllib.error.HTTPError as exc:
                    exc.read()
                    with res_lock:          # refused at admission:
                        results.append(("rejected", exc.code))
                    time.sleep(0.05)        # not admitted, not lost
                    continue
                except Exception as exc:  # noqa: BLE001
                    with res_lock:
                        results.append(("broken", repr(exc)))
                    continue
                terminals = [ln for ln in lines if ln.get("done")]
                with res_lock:
                    if len(terminals) != 1:
                        results.append(("bad_terminal", lines))
                    elif "error" in terminals[0]:
                        results.append(("errored", terminals[0]))
                    else:
                        results.append(("completed", n))

        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True) for c in range(3)]
        for t in threads:
            t.start()
        time.sleep(1.0)                     # traffic flowing pre-roll

        # -- the rolling weight update, over the wire ----------------
        req = urllib.request.Request(
            base + "/rollout",
            data=json.dumps({"package": pkg_b}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            if r.status != 202:
                fail(f"POST /rollout answered {r.status}")
        deadline = time.monotonic() + 300
        while True:
            status = get_json(base + "/rollout", 15)
            if status["state"] == "done":
                break
            if status["state"] == "failed":
                fail(f"rollout failed: {status}")
            if time.monotonic() > deadline:
                fail(f"rollout did not finish within 300s: {status}")
            time.sleep(0.5)
        time.sleep(1.0)                     # a post-roll traffic tail
        stop.set()
        for t in threads:
            t.join(timeout=120)

        if status.get("fingerprint", {}).get("sha256") != \
                fp_b["sha256"]:
            fail(f"rollout fingerprint mismatch: {status}")
        with res_lock:
            kinds: dict = {}
            for kind, _ in results:
                kinds[kind] = kinds.get(kind, 0) + 1
        if kinds.get("broken", 0) or kinds.get("bad_terminal", 0):
            fail(f"lost/garbled streams during the rollout: {kinds}; "
                 f"tail: {results[-6:]}")
        if kinds.get("completed", 0) < 8:
            fail(f"too little completed traffic to trust the drill: "
                 f"{kinds}")

        # ledger closes + fleet converged on the new fingerprint
        meta = get_json(base + "/metrics", 15)
        ledger = meta["router"]
        if ledger["admitted"] != ledger["completed"] + \
                ledger["failed"] + ledger["client_gone"]:
            fail(f"router ledger does not close: {ledger}")
        workers = meta["pool"]["workers"]
        shas = {(w.get("fingerprint") or {}).get("sha256")
                for w in workers}
        if shas != {fp_b["sha256"]}:
            fail(f"fleet serves a torn mix after the rollout: "
                 f"{workers}")

        # steady state: decode compiles nothing across fresh traffic
        bases = [w["base"] for w in workers]
        before = [get_json(b + "/metrics", 15)["decoder"]
                  ["compile_count"] for b in bases]
        for _ in range(4):
            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps({"prompt": "ef",
                                 "max_tokens": 4}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=90) as r:
                lines = [json.loads(raw) for raw in r]
            if not lines or not lines[-1].get("done") or \
                    "error" in lines[-1]:
                fail(f"post-rollout stream did not complete: {lines}")
        after = [get_json(b + "/metrics", 15)["decoder"]
                 ["compile_count"] for b in bases]
        if before != after:
            fail(f"steady-state decode recompiled after the rollout: "
                 f"{before} -> {after}")

        # merged telemetry: router families beside rank-labeled workers
        prom = urllib.request.urlopen(base + "/fleet/metrics.prom",
                                      timeout=15).read().decode()
        for needle in ("znicz_router_requests_total",
                       "znicz_fleet_scale_workers",
                       'znicz_generate_tokens_total{rank="'):
            if needle not in prom:
                fail(f"{needle!r} missing from /fleet/metrics.prom")

        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=90)
        except subprocess.TimeoutExpired:
            proc.kill()
            fail("fleet CLI did not drain within 90s of SIGTERM")
        if rc != 0:
            fail(f"fleet CLI exited rc={rc} on SIGTERM drain")
        proc = None
        print(f"fleet_router_smoke: ok — rolled {len(workers)} workers "
              f"onto {os.path.basename(pkg_b)} under traffic, "
              f"{kinds.get('completed', 0)} completed / "
              f"{kinds.get('errored', 0)} errored / "
              f"{kinds.get('rejected', 0)} rejected, zero lost, "
              f"ledger closed, compile delta 0")
        return 0
    finally:
        stop.set()
        if proc is not None and proc.poll() is None:
            proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
