#!/bin/bash
# Persistent chip watcher: cheap probe every 5 min; on success runs the
# evidence sequence (compiled Pallas parity sweep, full bench, profiled
# AlexNet/CIFAR passes), each stage in its own process with a hard
# timeout.  A stage timeout means `timeout` SIGTERM'd a claim-holding
# python — that wedges the lease for a long time (docs/BENCH_LOG.md,
# 04:18 UTC 2026-07-31 entry) — so the cycle BAILS back to the probe
# loop instead of burning the remaining stages against a dead pool.
# The cycle only marks itself done (`.scratch/cycle_done`) when every
# stage ran to completion and the bench landed result lines; partial
# evidence keeps the watcher alive for the next window.
#
# Start at session begin (pool access comes and goes in short windows):
#   nohup bash tools/chip_watch.sh > /dev/null 2>&1 &
set -u
cd /root/repo
mkdir -p .scratch
log() { echo "[$(date -u +%H:%M:%S)] $*" >> .scratch/watch.log; }
probe() {
  timeout 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones(4).sum(); x.block_until_ready()
print(float(x))
" > /dev/null 2>&1
}

run_stage() {  # name timeout_s logfile python_args...
  local name=$1 tmo=$2 logf=$3; shift 3
  log "stage: $name"
  timeout "$tmo" "$@" > "$logf" 2>&1
  local rc=$?
  log "stage $name rc=$rc"
  return $rc
}

cycle() {
  run_stage parity 700 .scratch/parity_r4.log \
    python -c "
import bench
bench._enable_compile_cache()
bench.bench_pallas_parity()
" || return 1
  # raised child budget: this session changed every compiled program, so
  # the first hardware run pays ~20-40 s remote-compile per phase; the
  # driver's later default-budget run reuses the cache this run warms
  run_stage bench 2400 .scratch/bench_full_r4.log \
    env BENCH_TPU_TIMEOUT=1500 BENCH_TPU_RETRY_TIMEOUT=600 \
    python bench.py || return 1
  grep -q '"metric"' .scratch/bench_full_r4.log || {
    log "bench landed no result lines"; return 1; }
  run_stage alexnet_prof 700 .scratch/alexnet_prof2_r4.log \
    env BENCH_PROFILE=.scratch/trace_alexnet2 python -c "
import bench
bench._enable_compile_cache()
bench.bench_alexnet(K=8, reps=1)
" || return 1
  run_stage cifar_prof 700 .scratch/cifar_prof_r4.log \
    env BENCH_PROFILE=.scratch/trace_cifar python -c "
import bench
bench._enable_compile_cache()
bench.bench_cifar(K=16, reps=1)
" || return 1
  return 0
}

# Optional WATCH_DEADLINE_EPOCH (unix seconds): exit before the driver's
# round-end bench so a watcher stage never holds the chip against it.
while [ ! -f .scratch/cycle_done ]; do
  if [ -n "${WATCH_DEADLINE_EPOCH:-}" ] && \
     [ "$(date +%s)" -ge "$WATCH_DEADLINE_EPOCH" ]; then
    log "deadline reached — exiting to leave the chip to the driver"
    break
  fi
  if probe; then
    log "probe OK — running evidence sequence"
    if cycle; then
      touch .scratch/cycle_done
      # .scratch/ is gitignored: export the evidence somewhere tracked so
      # a round-end commit (driver or next session) preserves it
      {
        echo "# chip_watch evidence cycle completed $(date -u +%FT%TZ)"
        echo "# parity sweep:"
        grep -a "pallas_hw_parity\|\"metric\"" .scratch/parity_r4.log
        echo "# full bench result lines:"
        grep -a '"metric"' .scratch/bench_full_r4.log
        echo "# profiled AlexNet top ops:"
        grep -a "# prof" .scratch/alexnet_prof2_r4.log
        echo "# profiled CIFAR top ops:"
        grep -a "# prof" .scratch/cifar_prof_r4.log
      } > docs/bench_hw_r4_watcher.jsonl 2>&1
      log "cycle complete — full evidence landed (exported to docs/)"
    else
      log "cycle incomplete (stage failed/timed out); back to probing"
    fi
  else
    log "probe blocked/failed; sleeping"
  fi
  sleep 300
done
