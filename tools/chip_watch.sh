#!/bin/bash
# Persistent chip watcher: cheap probe every 5 min; on success runs the
# evidence sequence (compiled Pallas parity sweep, full bench, profiled
# AlexNet/CIFAR passes), each stage in its own process with a hard
# timeout — a mid-sequence pool wedge costs one stage, not the cycle.
# Stops after one full successful cycle (`.scratch/cycle_done` marker).
#
# Start at session begin (pool access comes and goes in short windows —
# docs/BENCH_LOG.md):   mkdir -p .scratch && nohup bash \
#   tools/chip_watch.sh > /dev/null 2>&1 &
# NEVER kill a process that holds the chip claim: a SIGTERM'd holder
# wedges the lease for a long time (04:18 UTC 2026-07-31 entry).
set -u
cd /root/repo
log() { echo "[$(date -u +%H:%M:%S)] $*" >> .scratch/watch.log; }
probe() {
  timeout 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones(4).sum(); x.block_until_ready()
import jax as j; print(float(x))
" > /dev/null 2>&1
}

while [ ! -f .scratch/cycle_done ]; do
  if probe; then
    log "probe OK — running evidence sequence"
    log "stage: parity sweep"
    timeout 700 python -c "
import bench
bench._enable_compile_cache()
bench.bench_pallas_parity()
" > .scratch/parity_r4.log 2>&1
    log "parity rc=$?"
    log "stage: full bench"
    timeout 1700 python bench.py > .scratch/bench_full_r4.log 2>&1
    log "bench rc=$?"
    log "stage: alexnet profile"
    timeout 700 env BENCH_PROFILE=.scratch/trace_alexnet2 python -c "
import bench
bench._enable_compile_cache()
bench.bench_alexnet(K=8, reps=1)
" > .scratch/alexnet_prof2_r4.log 2>&1
    log "alexnet profile rc=$?"
    log "stage: cifar profile"
    timeout 700 env BENCH_PROFILE=.scratch/trace_cifar python -c "
import bench
bench._enable_compile_cache()
bench.bench_cifar(K=16, reps=1)
" > .scratch/cifar_prof_r4.log 2>&1
    log "cifar profile rc=$?"
    if grep -q '"metric"' .scratch/bench_full_r4.log; then
      touch .scratch/cycle_done
      log "cycle complete — results landed"
    else
      log "bench produced no result lines; will retry next probe"
    fi
  else
    log "probe blocked/failed; sleeping"
  fi
  sleep 300
done
