#!/bin/bash
# Persistent chip watcher (round 5): cheap probe every 3 min; on success
# runs the evidence sequence (compiled Pallas parity sweep, full bench,
# profiled AlexNet/CIFAR/transformer passes), each stage in its own
# process with a hard timeout.  A stage timeout means `timeout`
# SIGTERM'd a claim-holding python — that wedges the lease for a long
# time (docs/BENCH_LOG.md, 04:18 UTC 2026-07-31 entry) — so the cycle
# BAILS back to the probe loop instead of burning the remaining stages
# against a dead pool.
#
# Round-5 lesson from the r4 verdict: evidence must land in a TRACKED
# artifact.  So after every cycle — complete or not — whatever stage
# logs exist are exported to docs/bench_hw_r5_watcher.jsonl and that one
# file is committed (path-scoped commit; retries around transient index
# locks).  Partial windows still make history.
#
# Start at session begin (pool access comes and goes in short windows):
#   nohup bash tools/chip_watch.sh > /dev/null 2>&1 &
set -u
cd /root/repo
mkdir -p .scratch
EVIDENCE=docs/bench_hw_r5_watcher.jsonl
log() { echo "[$(date -u +%H:%M:%S)] $*" >> .scratch/watch.log; }
probe() {
  timeout 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones(4).sum(); x.block_until_ready()
print(float(x))
" > /dev/null 2>&1
}

past_deadline() {
  [ -n "${WATCH_DEADLINE_EPOCH:-}" ] && \
    [ "$(date +%s)" -ge "$WATCH_DEADLINE_EPOCH" ]
}

run_stage() {  # name timeout_s logfile python_args...
  local name=$1 tmo=$2 logf=$3; shift 3
  # re-check the deadline before EVERY stage: a cycle started just
  # before the deadline must not hold the chip ~90 min into the
  # driver's round-end bench
  if past_deadline; then
    log "deadline reached mid-cycle — skipping stage $name"
    return 1
  fi
  log "stage: $name"
  timeout "$tmo" "$@" > "$logf" 2>&1
  local rc=$?
  log "stage $name rc=$rc"
  return $rc
}

export_evidence() {
  # APPEND a per-cycle section (never truncate): if a partial export's
  # commit failed, the next window must not destroy the previous
  # window's only copy of its evidence
  {
    echo "# chip_watch r5 evidence export $(date -u +%FT%TZ) (cycle status: $1)"
    for f in parity bench_full alexnet_prof cifar_prof transformer_prof; do
      [ -f ".scratch/${f}_r5.log" ] || continue
      echo "# --- stage: $f (log mtime $(date -u -r ".scratch/${f}_r5.log" +%FT%TZ)) ---"
      grep -a "pallas_hw_parity\|\"metric\"\|# prof\|FAIL\|attention" \
        ".scratch/${f}_r5.log"
    done
  } >> "$EVIDENCE" 2>&1
  for i in 1 2 3 4 5 6 7 8 9 10; do
    if git add "$EVIDENCE" >> .scratch/watch.log 2>&1 && \
       git commit -q -m "Watcher: hardware evidence export ($1)" \
         -- "$EVIDENCE" >> .scratch/watch.log 2>&1; then
      log "evidence committed"; return
    fi
    log "commit attempt $i failed (stderr above)"
    sleep 20
  done
  log "evidence export written but commit failed (left for round-end)"
}

cycle() {
  # fresh stage logs: export_evidence must never re-attribute a previous
  # window's logs to this cycle
  rm -f .scratch/parity_r5.log .scratch/bench_full_r5.log \
        .scratch/alexnet_prof_r5.log .scratch/cifar_prof_r5.log \
        .scratch/transformer_prof_r5.log
  run_stage parity 900 .scratch/parity_r5.log \
    python -c "
import bench
bench._enable_compile_cache()
bench.bench_pallas_parity()
" || return 1
  # raised child budget: first hardware run of changed programs pays
  # ~20-40 s remote-compile per phase; the driver's later default-budget
  # run reuses the cache this run warms
  run_stage bench_full 2400 .scratch/bench_full_r5.log \
    env BENCH_TPU_TIMEOUT=1500 BENCH_TPU_RETRY_TIMEOUT=600 \
        BENCH_ALEXNET_B256=1 \
    python bench.py || return 1
  grep -q '"metric"' .scratch/bench_full_r5.log || {
    log "bench landed no result lines"; return 1; }
  run_stage alexnet_prof 700 .scratch/alexnet_prof_r5.log \
    env BENCH_PROFILE=.scratch/trace_alexnet_r5 python -c "
import bench
bench._enable_compile_cache()
bench.bench_alexnet(K=8, reps=1)
" || return 1
  run_stage cifar_prof 700 .scratch/cifar_prof_r5.log \
    env BENCH_PROFILE=.scratch/trace_cifar_r5 python -c "
import bench
bench._enable_compile_cache()
bench.bench_cifar(K=16, reps=1)
" || return 1
  run_stage transformer_prof 900 .scratch/transformer_prof_r5.log \
    env BENCH_PROFILE=.scratch/trace_transformer_r5 python -c "
import bench
bench._enable_compile_cache()
bench.bench_transformer(K=4, reps=1)
" || return 1
  return 0
}

# Optional WATCH_DEADLINE_EPOCH (unix seconds): exit before the driver's
# round-end bench so a watcher stage never holds the chip against it.
while [ ! -f .scratch/cycle_done_r5 ]; do
  if [ -n "${WATCH_DEADLINE_EPOCH:-}" ] && \
     [ "$(date +%s)" -ge "$WATCH_DEADLINE_EPOCH" ]; then
    log "deadline reached — exiting to leave the chip to the driver"
    break
  fi
  if probe; then
    log "probe OK — running evidence sequence"
    if cycle; then
      touch .scratch/cycle_done_r5
      export_evidence complete
      log "cycle complete — full evidence landed + committed"
    else
      export_evidence partial
      log "cycle incomplete (stage failed/timed out); partial evidence exported; back to probing"
    fi
  else
    log "probe blocked/failed; sleeping"
  fi
  sleep 180
done
