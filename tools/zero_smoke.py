"""ZeRO shard_params smoke for tools/t1.sh (ISSUE 15): on a forced
4-device CPU mesh, a dp(4)+shard_params(adam) run must (a) read per-chip
``znicz_zero_param_bytes + znicz_zero_opt_state_bytes`` at ~1/4 of the
replicated run's figure (padding epsilon allowed), (b) report nonzero
on-demand gather traffic, and (c) produce the SAME seeded metric history
as the replicated run — the memory win with the numerics pinned, end to
end through the real workflow loop.

``ZNICZ_TPU_COMPILE_CACHE=off`` per the box note (the persistent cache
intermittently segfaults single-process workers here).
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") +
    " --xla_force_host_platform_device_count=4").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("ZNICZ_TPU_COMPILE_CACHE", "off")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

N_DEV = 4


def fail(msg: str) -> None:
    print(f"zero_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def run_once(shard_params: bool):
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.observe import registry
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    prng.seed_all(31)
    w = build_fused(max_epochs=2, layers=(32,), minibatch_size=16,
                    n_train=96, n_valid=32,
                    mesh=data_parallel_mesh(N_DEV), optimizer="adam",
                    shard_params=shard_params)
    w.initialize(device=TPUDevice())
    w.run()
    hist = [h["metric_validation"] for h in w.decision.metrics_history]

    def gauge(name):
        return registry.REGISTRY.get(name).labels(unit="FusedStep").get()

    bytes_per_chip = (gauge("znicz_zero_param_bytes") +
                      gauge("znicz_zero_opt_state_bytes"))
    gathered = gauge("znicz_zero_gathered_bytes_total")
    n_sharded = sum(1 for leaf in w.step._params
                    for k in leaf if w.step._leaf_sharded(k))
    w.stop()
    return hist, bytes_per_chip, gathered, n_sharded


def main() -> None:
    hist_rep, bytes_rep, gathered_rep, _ = run_once(False)
    if bytes_rep <= 0:
        fail(f"replicated run reports {bytes_rep} state bytes")
    if gathered_rep != 0:
        fail(f"replicated run counted {gathered_rep} gathered bytes")

    hist_sp, bytes_sp, gathered_sp, n_sharded = run_once(True)
    if hist_sp != hist_rep:
        fail(f"seeded metric history diverged: shard_params {hist_sp} "
             f"!= replicated {hist_rep}")
    if gathered_sp <= 0:
        fail("shard_params run counted no gathered bytes")
    # acceptance: per-chip bytes <= 1/n of replicated + padding epsilon
    # (at most n-1 padded f32 elements per sharded leaf)
    eps = 4 * (N_DEV - 1) * n_sharded
    if bytes_sp > bytes_rep / N_DEV + eps:
        fail(f"per-chip bytes {bytes_sp} > replicated/{N_DEV} "
             f"({bytes_rep / N_DEV:.0f}) + padding eps {eps}")
    print(f"zero_smoke: OK — per-chip state {int(bytes_sp)}B vs "
          f"replicated {int(bytes_rep)}B (<= 1/{N_DEV} + {eps}B pad), "
          f"gathered {int(gathered_sp)}B on demand, seeded history "
          f"identical over {len(hist_sp)} epochs")


if __name__ == "__main__":
    main()
