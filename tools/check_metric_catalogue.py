"""Static metric-catalogue check for tools/t1.sh (ISSUE 6): every
`znicz_*` metric family used in `znicz_tpu/` source must appear in the
docs/OBSERVABILITY.md catalogue, and every `znicz_*` name the catalogue
lists must still exist in code — a renamed metric that leaves a stale
dashboard row, or a new one nobody documented, fails tier-1 loudly.

"Used in code" is collected two ways, both from the AST (docstrings
and comments don't count):

- declarations: the first string argument of a `counter(` / `gauge(` /
  `histogram(` call;
- references: any other string literal starting with `znicz_` — SLO
  rule targets like `znicz_workflow_step_seconds_p95` or
  `'znicz_resilience_events_total{kind="nan_guard"}'`.

Derived flat-key suffixes (`_count`, `_sum`, `_bucket`, `_p50`, `_p95`,
`_p99` — what `snapshot_flat()` appends to a histogram family) and
`{label="..."}` filters are normalized away on BOTH sides before
comparing, so the catalogue documents families, not every derived key.

On top of the docs<->code sync, every REFERENCED family must also be
DECLARED somewhere (a `counter(`/`gauge(`/`histogram(` call) — an SLO
rule or smoke assertion naming a counter that no code registers would
otherwise pass this check while scraping nothing at runtime.

Exit 0 when the catalogue and the code agree; otherwise print one
`check_metric_catalogue:`-prefixed line per discrepancy and exit 1.
"""

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "znicz_tpu")
CATALOGUE = os.path.join(REPO, "docs", "OBSERVABILITY.md")

#: snapshot_flat()-derived suffixes a reference may carry on top of the
#: declared family name
DERIVED_SUFFIXES = ("_count", "_sum", "_bucket", "_p50", "_p95", "_p99")

#: znicz_-prefixed literals that are NOT metric families (module paths,
#: logger names); the package itself is znicz_tpu so one prefix covers
#: every module-ish string
NON_METRIC_PREFIXES = ("znicz_tpu",)

#: exact non-metric literals: __main__.py's importlib module name for
#: user workflow files
NON_METRIC_NAMES = {"znicz_workflow"}

#: families emitted straight into a merged/flat view without a registry
#: object — the fleet federator synthesizes these per source, so no
#: counter()/gauge()/histogram() declaration exists (or should)
SYNTHETIC_FAMILIES = {"znicz_fleet_worker_up"}

_NAME_RE = re.compile(r"^znicz_[a-z0-9_]+$")
_DOC_NAME_RE = re.compile(r"`(znicz_[a-z0-9_{}=\",. ]*?)`")

_DECL_FUNCS = {"counter", "gauge", "histogram"}


def normalize(name: str) -> str:
    """Family name for one code/docs reference: strip a label filter
    and at most one derived suffix."""
    name = name.partition("{")[0].strip()
    for suffix in DERIVED_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if base != "znicz":
                return base
    return name


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _docstring_nodes(tree: ast.AST) -> set:
    """ids of the Constant nodes that are docstrings."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def collect_code_families() -> tuple:
    """``({family: first 'path:line' seen}, {declared family: 'path:line'})``
    for every znicz_ metric name used in znicz_tpu/ source.  The first
    dict covers ALL uses (declarations and references); the second only
    the names declared by a `counter(`/`gauge(`/`histogram(` call."""
    families: dict = {}
    declared: dict = {}
    for dirpath, dirnames, filenames in os.walk(PACKAGE):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
            docstrings = _docstring_nodes(tree)
            rel = os.path.relpath(path, REPO)
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) and \
                        _call_name(node) in _DECL_FUNCS and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    name = normalize(node.args[0].value)
                    if _NAME_RE.match(name):
                        declared.setdefault(
                            name, f"{rel}:{node.args[0].lineno}")
                if not isinstance(node, ast.Constant) or \
                        not isinstance(node.value, str):
                    continue
                if id(node) in docstrings:
                    continue
                name = normalize(node.value)
                if not _NAME_RE.match(name) or name in NON_METRIC_NAMES:
                    continue
                if any(name == p or name.startswith(p + "_")
                       for p in NON_METRIC_PREFIXES):
                    continue
                families.setdefault(name, f"{rel}:{node.lineno}")
    return families, declared


def collect_doc_families() -> dict:
    """``{family: line number}`` for every backticked znicz_ name in
    the catalogue doc."""
    families: dict = {}
    with open(CATALOGUE, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            for raw in _DOC_NAME_RE.findall(line):
                name = normalize(raw)
                if _NAME_RE.match(name) and \
                        not name.startswith("znicz_tpu"):
                    families.setdefault(name, lineno)
    return families


def main() -> int:
    code, declared = collect_code_families()
    docs = collect_doc_families()
    rc = 0
    for name in sorted(set(code) - set(docs)):
        print(f"check_metric_catalogue: {name} (used at {code[name]}) "
              f"is MISSING from docs/OBSERVABILITY.md",
              file=sys.stderr)
        rc = 1
    for name in sorted(set(docs) - set(code)):
        print(f"check_metric_catalogue: {name} "
              f"(docs/OBSERVABILITY.md:{docs[name]}) is documented but "
              f"no longer used anywhere in znicz_tpu/", file=sys.stderr)
        rc = 1
    for name in sorted(set(code) - set(declared) - SYNTHETIC_FAMILIES):
        print(f"check_metric_catalogue: {name} (referenced at "
              f"{code[name]}) is never declared by a counter()/gauge()/"
              f"histogram() call in znicz_tpu/ — it would scrape "
              f"nothing at runtime", file=sys.stderr)
        rc = 1
    if rc == 0:
        print(f"check_metric_catalogue: ok — {len(code)} metric "
              f"families ({len(declared)} declared), catalogue in sync")
    return rc


if __name__ == "__main__":
    sys.exit(main())
