"""Zero-JIT serve boot smoke for tools/t1.sh (ISSUE 7).

Exports a tiny forward package, embeds ahead-of-time executables
(`attach_aot`), then boots the real `python -m znicz_tpu serve` CLI in
a FRESH process (no in-process jit/trace cache warmth to hide behind),
scrapes `GET /metrics`, and asserts the engine compiled **nothing**:
`compile_count == 0` with every bucket served from its deserialized
AOT executable.  One `POST /predict` round-trip proves the zero-JIT
boot actually serves.

jax-on-CPU by design (the caller pins JAX_PLATFORMS=cpu); the AOT
fingerprint is captured and checked on the same box, so the match is
exact.  Every failure prints an `aot_smoke:`-prefixed line and exits
nonzero.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> "None":
    print(f"aot_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def build_package(tmp: str) -> str:
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.standard_workflow import StandardWorkflow
    from znicz_tpu.utils.export import attach_aot, export_forward

    prng.seed_all(23)
    w = StandardWorkflow(
        name="AotSmoke", loss_function="softmax",
        layers=[{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
                {"type": "softmax", "->": {"output_sample_shape": 3}}],
        loader_name="synthetic_classifier",
        loader_config={"n_classes": 3, "sample_shape": (6,), "n_train": 60,
                       "n_valid": 0, "minibatch_size": 20},
        decision_config={"max_epochs": 1})
    w.initialize(device=TPUDevice())
    w.run()
    pkg = os.path.join(tmp, "aot_smoke.npz")
    export_forward(w, pkg)
    meta = attach_aot(pkg, max_batch=8)
    if meta["buckets"] != [1, 2, 4, 8]:
        fail(f"unexpected AOT buckets {meta['buckets']}")
    return pkg


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def scrape(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="znicz_aot_smoke_")
    proc = None
    try:
        # hermetic persistent cache: the smoke must not depend on (or
        # pollute) the developer's ~/.cache warmth
        os.environ["ZNICZ_TPU_COMPILE_CACHE"] = os.path.join(tmp, "xla")
        pkg = build_package(tmp)
        port = free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "serve", pkg,
             "--port", str(port), "--max-batch", "8"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        base = f"http://127.0.0.1:{port}"
        deadline = time.time() + 90
        while True:
            if proc.poll() is not None:
                _, err = proc.communicate()
                fail(f"serve exited rc={proc.returncode}: "
                     f"{err.strip().splitlines()[-3:]}")
            try:
                if scrape(f"{base}/healthz")["status"] == "ok":
                    break
            except (urllib.error.URLError, OSError, ConnectionError):
                pass
            if time.time() > deadline:
                fail("serve did not come up within 90s")
            time.sleep(0.25)
        metrics = scrape(f"{base}/metrics")
        engine = metrics.get("engine", {})
        if engine.get("compile_count") != 0:
            fail(f"AOT boot compiled {engine.get('compile_count')} "
                 f"buckets (want 0) — engine stats: {engine}")
        if engine.get("aot_count") != 4:
            fail(f"expected 4 AOT-served buckets, got "
                 f"{engine.get('aot_count')} — engine stats: {engine}")
        req = urllib.request.Request(
            f"{base}/predict",
            data=json.dumps({"input": [[0.0] * 6, [1.0] * 6]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        if len(out["output"]) != 2 or len(out["output"][0]) != 3:
            fail(f"bad predict shape: {out}")
        after = scrape(f"{base}/metrics")["engine"]
        if after.get("compile_count") != 0:
            fail("the predict round-trip itself compiled a bucket")
        print(f"aot_smoke: ok — zero-JIT boot served on :{port} "
              f"(compile_count=0, aot_count=4, "
              f"run_count={after.get('run_count')})")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
