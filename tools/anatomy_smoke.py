"""Step-anatomy smoke for tools/t1.sh (ISSUE 20): on a forced 4-device
CPU mesh, a dp(4)+shard_params+int8-collectives anatomy run must (a)
pre-touch every ``znicz_anatomy_*`` child at init (the PR 11 delta-rule
lesson: a family that first appears mid-run trips fleet rules as a fake
spike, or never), (b) attribute per-phase seconds whose sum lands within
10% of the measured step wall time, (c) read a nonzero
``znicz_anatomy_mfu`` (peak FLOPs pinned via $ZNICZ_TPU_PEAK_FLOPS —
the honest CPU-fallback denominator, docs/OBSERVABILITY.md), and (d)
trip the per-rank straggler rule for exactly the one artificially
delayed rank in a deterministic-tick fleet fixture.  Also asserts
``znicz_goodput_*`` pre-touch materializes every category child at 0.

``ZNICZ_TPU_COMPILE_CACHE=off`` per the box note (the persistent cache
intermittently segfaults single-process workers here).
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") +
    " --xla_force_host_platform_device_count=4").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("ZNICZ_TPU_COMPILE_CACHE", "off")
# nominal peak so the MFU gauge has a denominator on CPU (peak_flops()
# is honestly None here; the figure is only meaningful RELATIVE to the
# pinned nominal — docs/OBSERVABILITY.md spells the caveat out)
os.environ.setdefault("ZNICZ_TPU_PEAK_FLOPS", "1e12")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

N_DEV = 4


def fail(msg: str) -> None:
    print(f"anatomy_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def check_anatomy_run():
    """(b)+(c) on the real fused workflow, (a) asserted at init."""
    from znicz_tpu.core import prng
    from znicz_tpu.core.backends import TPUDevice
    from znicz_tpu.models.mnist_fc import build_fused
    from znicz_tpu.observe import registry
    from znicz_tpu.observe.anatomy import TRAIN_PHASES
    from znicz_tpu.parallel.mesh import data_parallel_mesh

    prng.seed_all(31)
    w = build_fused(max_epochs=2, layers=(32,), minibatch_size=16,
                    n_train=96, n_valid=32,
                    mesh=data_parallel_mesh(N_DEV), optimizer="adam",
                    shard_params=True, anatomy=True,
                    quantized_collectives={"mode": "int8",
                                           "error_feedback": True})
    w.initialize(device=TPUDevice())

    # (a) pre-touch: every anatomy child of the fused plane must exist
    # at init, BEFORE any step ran, so fleet delta rules see a baseline
    flat = registry.REGISTRY.snapshot_flat(skip_zero=False)
    if flat.get('znicz_anatomy_steps_total{plane="fused"}') != 0.0:
        fail("znicz_anatomy_steps_total not pre-touched at 0 at init")
    for phase in TRAIN_PHASES:
        key = ('znicz_anatomy_phase_seconds_count'
               f'{{plane="fused",phase="{phase}"}}')
        if flat.get(key) != 0.0:
            fail(f"phase child {phase!r} not pre-touched at init "
                 f"(missing key {key})")
    if flat.get('znicz_anatomy_mfu{plane="fused"}') != 0.0:
        fail("znicz_anatomy_mfu not pre-touched at 0 at init")

    w.run()
    flat = registry.REGISTRY.snapshot_flat(skip_zero=False)
    phase_sum = sum(
        v for k, v in flat.items()
        if k.startswith('znicz_anatomy_phase_seconds_sum{plane="fused"'))
    step_sum = flat.get('znicz_anatomy_step_seconds_sum{plane="fused"}',
                        0.0)
    steps = flat.get('znicz_anatomy_steps_total{plane="fused"}', 0.0)
    mfu = flat.get('znicz_anatomy_mfu{plane="fused"}', 0.0)
    w.stop()
    if steps <= 0:
        fail("anatomy run counted no steps")
    if step_sum <= 0:
        fail("anatomy run measured no step wall time")
    # (b) the phases must tile the step: unattributed time past 10%
    # means a dispatch point lost its stamp
    if abs(phase_sum - step_sum) > 0.10 * step_sum:
        fail(f"phase seconds {phase_sum:.4f} vs step wall "
             f"{step_sum:.4f}: {abs(phase_sum / step_sum - 1):.1%} "
             f"apart (> 10%)")
    if not (0.0 < mfu):
        fail(f"znicz_anatomy_mfu is {mfu} with "
             f"$ZNICZ_TPU_PEAK_FLOPS={os.environ['ZNICZ_TPU_PEAK_FLOPS']}")
    return phase_sum, step_sum, steps, mfu


def check_goodput_pretouch():
    """(a) for the goodput families: every category child per rank at
    0, ratio gauge present."""
    from znicz_tpu.observe import probe, registry

    probe.goodput_pretouch(range(2))
    flat = registry.REGISTRY.snapshot_flat(skip_zero=False)
    for cat in ("productive", "lost", "snapshot", "idle"):
        for rank in (0, 1):
            key = f'znicz_goodput_{cat}_seconds_total{{rank="{rank}"}}'
            if flat.get(key) != 0.0:
                fail(f"goodput child not pre-touched: {key}")
    if "znicz_goodput_ratio" not in flat:
        fail("znicz_goodput_ratio gauge not pre-touched")


def check_straggler_rule():
    """(d) deterministic ticks: 3 synthetic rank registries, rank 2
    delayed 5x — exactly its rule must trip."""
    from znicz_tpu.observe import federation as fed
    from znicz_tpu.observe.registry import Registry

    regs = []
    for _ in range(3):
        r = Registry()
        r.histogram("znicz_anatomy_step_seconds", "step wall",
                    labelnames=("plane",), buckets=(0.05, 0.2, 1.0))
        regs.append(r)
    agg = fed.FleetAggregator(min_refresh_s=0.0)
    for i, r in enumerate(regs):
        agg.add_source(i, r.render_prometheus)
    rules = fed.add_straggler_rules(agg, spread=1.5, window_s=60.0,
                                    min_count=4)
    try:
        ts = 5000.0
        for r in regs:
            r.get("znicz_anatomy_step_seconds").labels(plane="fused")
        agg.tower.observe_now(ts=ts)
        for _ in range(8):
            for i, r in enumerate(regs):
                r.get("znicz_anatomy_step_seconds") \
                    .labels(plane="fused") \
                    .observe(0.5 if i == 2 else 0.1)
        agg.tower.observe_now(ts=ts + 5)
        agg.tower.observe_now(ts=ts + 10)
        tripped = [r.trips > 0 for r in rules]
        if tripped != [False, False, True]:
            fail(f"straggler rule trip pattern {tripped}, expected "
                 f"only the delayed rank 2 "
                 f"(last_values {[r.last_value for r in rules]})")
    finally:
        agg.close()


def main() -> None:
    phase_sum, step_sum, steps, mfu = check_anatomy_run()
    check_goodput_pretouch()
    check_straggler_rule()
    print(f"anatomy_smoke: OK — {int(steps)} steps, phase seconds "
          f"{phase_sum:.4f} vs step wall {step_sum:.4f} "
          f"({abs(phase_sum / step_sum - 1):.2%} apart), mfu {mfu:.3e} "
          f"vs nominal peak, goodput children pre-touched, straggler "
          f"rule tripped only for the delayed rank")


if __name__ == "__main__":
    main()
