"""Elastic kill-and-resume smoke for tools/t1.sh (ISSUE 9): start 2 CPU
worker processes under the fleet supervisor, SIGKILL the snapshot-writer
at a seeded step, resume at world size 1, and assert the fleet
completed, dumped >= 1 flight artifact, and counted >= 1 resume.

Fast by construction: 3 epochs of the tiny drill workflow
(tools/elastic_workflow.py), compile cache off, one restart round.
Exit 0 on success; any failure prints one ``elastic_smoke:`` line and
exits 1.
"""

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from znicz_tpu.observe import probe
    from znicz_tpu.resilience import faults
    from znicz_tpu.resilience.elastic import run_elastic
    from znicz_tpu.resilience.supervisor import SupervisorPolicy

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    # XLA's concurrent persistent-cache writes are flaky on this box
    # (see tests/conftest.py) — the smoke must not inherit that risk
    env["ZNICZ_TPU_COMPILE_CACHE"] = "off"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ZNICZ_TPU_ELASTIC_EPOCHS"] = "3"
    # kill the WRITER (rank 0) mid-epoch-2: deterministic resume point
    plan = faults.FaultPlan(seed=99).kill_at("elastic.worker", at_hit=40)
    counts0 = probe.elastic_counts()
    with tempfile.TemporaryDirectory(prefix="znicz_elastic_smoke_") as tmp:
        snap_dir = os.path.join(tmp, "snaps")
        try:
            report = run_elastic(
                [os.path.join(REPO, "tools", "elastic_workflow.py")],
                snap_dir, workers=2, world_sizes=[2, 1], prefix="ew",
                policy=SupervisorPolicy(max_restarts=2,
                                        backoff_base=0.01),
                env=env, fault_plans={0: plan}, term_grace=8.0,
                round_timeout=240.0)
        except Exception as exc:  # noqa: BLE001 — one-line verdict
            print(f"elastic_smoke: FAILED — fleet raised {exc!r}")
            return 1
        counts = probe.elastic_counts()
        problems = []
        if not report.completed:
            problems.append("fleet did not complete")
        if report.restarts < 1:
            problems.append("seeded kill never caused a restart")
        flights = [p for p in report.flights if os.path.isfile(p)]
        if not flights:
            problems.append("no flight artifact dumped")
        if counts["resumes"] - counts0["resumes"] < 1:
            problems.append("znicz_elastic_resumes_total did not move")
        if not os.path.isfile(os.path.join(snap_dir, "history_0.json")):
            problems.append("resumed worker wrote no history")
        if problems:
            print(f"elastic_smoke: FAILED — {'; '.join(problems)}; "
                  f"report={report.as_dict()}")
            return 1
        print(f"elastic_smoke: ok — {report.restarts} restart, "
              f"resumed at world size {report.world_size}, "
              f"{len(flights)} flight artifact(s), counters "
              f"{counts['restarts'] - counts0['restarts']}/"
              f"{counts['worker_deaths'] - counts0['worker_deaths']}/"
              f"{counts['resumes'] - counts0['resumes']} "
              f"(restarts/deaths/resumes)")
        return 0


if __name__ == "__main__":
    sys.exit(main())
