"""Generative serving smoke for tools/t1.sh (ISSUE 10).

Exports a tiny LM package (random transformer params + a charmap),
then boots the real ``python -m znicz_tpu generate --serve`` CLI in a
FRESH process (no in-process warmth to hide behind), streams one short
generation over HTTP, and asserts:

- the ndjson stream carries non-empty token lines and EXACTLY ONE
  terminal ``done`` line (the stream contract the chaos drill pins);
- ``GET /metrics`` shows the request completed and tokens counted;
- ``GET /metrics.prom`` exposes the ``znicz_generate_*`` metric
  families (the observability satellite, end to end over the wire).

jax-on-CPU by design (the caller pins JAX_PLATFORMS=cpu); the compile
cache is pinned off — XLA's persistent cache intermittently segfaults
single-process workers on this box (PR 9 note).  Every failure prints
a ``generate_smoke:``-prefixed line and exits nonzero.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> "None":
    print(f"generate_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def build_package(tmp: str) -> str:
    import numpy as np

    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.utils.export import export_lm

    charmap = list("abcdefghijklmnopqrstuvwxyz .,!?")
    params = init_params(np.random.default_rng(23), 2, 32, 4, 64,
                         len(charmap))
    pkg = os.path.join(tmp, "lm_smoke.npz")
    export_lm(params, pkg, heads=4, charmap=charmap, name="smoke_lm")
    return pkg


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def scrape(url: str, timeout: float = 5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="znicz_generate_smoke_")
    proc = None
    try:
        pkg = build_package(tmp)
        port = free_port()
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   ZNICZ_TPU_COMPILE_CACHE="off")
        proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "generate", pkg,
             "--serve", "--port", str(port), "--slots", "2",
             "--max-len", "64"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 120
        while True:
            if proc.poll() is not None:
                out = (proc.stdout.read() or "")[-2000:]
                fail(f"server exited rc={proc.returncode} before "
                     f"healthy: {out}")
            try:
                if json.loads(scrape(f"{base}/healthz"))["status"] == \
                        "ok":
                    break
            except (urllib.error.URLError, OSError,
                    json.JSONDecodeError):
                pass
            if time.monotonic() > deadline:
                fail("server never became healthy within 120s")
            time.sleep(0.25)

        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps({"prompt": "hello world", "max_tokens": 8,
                             "temperature": 0.8, "top_k": 5,
                             "seed": 7}).encode(),
            headers={"Content-Type": "application/json"})
        lines = []
        with urllib.request.urlopen(req, timeout=60) as r:
            if r.headers["Content-Type"] != "application/x-ndjson":
                fail(f"unexpected content type "
                     f"{r.headers['Content-Type']!r}")
            for raw in r:
                lines.append(json.loads(raw))
        tokens = [ln for ln in lines if "token" in ln]
        terminals = [ln for ln in lines if ln.get("done")]
        if len(tokens) != 8:
            fail(f"wanted 8 streamed tokens, got {len(tokens)}: {lines}")
        if not all("text" in ln for ln in tokens):
            fail(f"token lines missing charmap text: {tokens[:3]}")
        if len(terminals) != 1 or terminals[0].get("reason") != \
                "length" or lines[-1] is not terminals[0]:
            fail(f"stream must end with exactly one done line: {lines}")

        snap = json.loads(scrape(f"{base}/metrics"))
        gen = snap.get("generate", {})
        if gen.get("completed") != 1 or gen.get("tokens") != 8:
            fail(f"metrics did not count the generation: {gen}")
        if snap.get("decoder", {}).get("prefill_count", 0) < 1:
            fail(f"decoder stats missing prefill: {snap.get('decoder')}")

        prom = scrape(f"{base}/metrics.prom").decode()
        for family in ("znicz_generate_tokens_total",
                       "znicz_generate_requests_total",
                       "znicz_generate_ttft_seconds",
                       "znicz_generate_active_slots"):
            if family not in prom:
                fail(f"{family} missing from /metrics.prom")

        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            fail("server did not drain within 60s of SIGTERM")
        if rc != 0:
            fail(f"server exited rc={rc} on SIGTERM drain")
        proc = None
        print(f"generate_smoke: ok — streamed {len(tokens)} tokens, "
              f"terminal line + metrics families verified")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
