"""Generative serving smoke for tools/t1.sh (ISSUE 10).

Exports a tiny LM package (random transformer params + a charmap),
then boots the real ``python -m znicz_tpu generate --serve`` CLI in a
FRESH process (no in-process warmth to hide behind), streams one short
generation over HTTP, and asserts:

- the ndjson stream carries non-empty token lines and EXACTLY ONE
  terminal ``done`` line (the stream contract the chaos drill pins);
- ``GET /metrics`` shows the request completed and tokens counted;
- ``GET /metrics.prom`` exposes the ``znicz_generate_*`` metric
  families (the observability satellite, end to end over the wire),
  including the paged-arena occupancy gauges (the CLI serves from the
  block-paged KV arena by default, ISSUE 12).

Invoked with ``--speculative`` it runs the ISSUE 12 exactness leg
instead: two fresh-process boots from one draft-carrying package —
speculation off, then on — must stream BYTE-IDENTICAL greedy text, and
the ``znicz_generate_spec_tokens_total`` family must be live.

jax-on-CPU by design (the caller pins JAX_PLATFORMS=cpu); the compile
cache is pinned off — XLA's persistent cache intermittently segfaults
single-process workers on this box (PR 9 note).  Every failure prints
a ``generate_smoke:``-prefixed line and exits nonzero.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> "None":
    print(f"generate_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def build_package(tmp: str, with_draft: bool = False) -> str:
    import numpy as np

    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.serve.paged import truncate_draft
    from znicz_tpu.utils.export import export_lm

    charmap = list("abcdefghijklmnopqrstuvwxyz .,!?")
    params = init_params(np.random.default_rng(23), 2, 32, 4, 64,
                         len(charmap))
    pkg = os.path.join(tmp, "lm_smoke.npz")
    export_lm(params, pkg, heads=4, charmap=charmap, name="smoke_lm",
              draft_params=truncate_draft(params, 1) if with_draft
              else None)
    return pkg


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def scrape(url: str, timeout: float = 5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def boot(pkg: str, extra_args=()) -> "tuple":
    """Start a fresh-process `generate --serve` worker; returns
    ``(proc, base_url)`` once /healthz answers ok."""
    port = free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ZNICZ_TPU_COMPILE_CACHE="off")
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "generate", pkg,
         "--serve", "--port", str(port), "--slots", "2",
         "--max-len", "64", *extra_args],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    base = f"http://127.0.0.1:{port}"
    deadline = time.monotonic() + 120
    while True:
        if proc.poll() is not None:
            out = (proc.stdout.read() or "")[-2000:]
            fail(f"server exited rc={proc.returncode} before "
                 f"healthy: {out}")
        try:
            if json.loads(scrape(f"{base}/healthz"))["status"] == "ok":
                return proc, base
        except (urllib.error.URLError, OSError, json.JSONDecodeError):
            pass
        if time.monotonic() > deadline:
            proc.kill()
            fail("server never became healthy within 120s")
        time.sleep(0.25)


def drain(proc) -> None:
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        fail("server did not drain within 60s of SIGTERM")
    if rc != 0:
        fail(f"server exited rc={rc} on SIGTERM drain")


def generate_text(base: str, prompt: str, n: int = 12) -> str:
    """One GREEDY streamed generation; returns the concatenated text."""
    req = urllib.request.Request(
        f"{base}/generate",
        data=json.dumps({"prompt": prompt, "max_tokens": n,
                         "temperature": 0.0}).encode(),
        headers={"Content-Type": "application/json"})
    lines = []
    with urllib.request.urlopen(req, timeout=60) as r:
        for raw in r:
            lines.append(json.loads(raw))
    if not lines or not lines[-1].get("done") or \
            "error" in lines[-1]:
        fail(f"greedy stream did not end cleanly: {lines}")
    return "".join(ln["text"] for ln in lines if "token" in ln)


def speculative_leg() -> int:
    """ISSUE 12 satellite: the decoded text must be BYTE-IDENTICAL with
    speculation on vs off — two fresh-process boots from one package
    carrying a truncated draft, same greedy request, compared exactly;
    plus the spec/pages metric families live over the wire."""
    tmp = tempfile.mkdtemp(prefix="znicz_generate_smoke_spec_")
    proc = None
    try:
        pkg = build_package(tmp, with_draft=True)
        proc, base = boot(pkg)
        plain = generate_text(base, "hello world")
        drain(proc)
        proc, base = boot(pkg, ("--speculative", "--spec-k", "3"))
        meta = json.loads(scrape(base))
        if not meta.get("speculative") or not meta.get("paged"):
            fail(f"speculative boot meta wrong: {meta}")
        spec = generate_text(base, "hello world")
        if spec != plain:
            fail(f"speculative text diverged: {spec!r} != {plain!r}")
        prom = scrape(f"{base}/metrics.prom").decode()
        for family in ("znicz_generate_spec_tokens_total",
                       "znicz_generate_cache_pages_used",
                       "znicz_generate_cache_pages_total"):
            if family not in prom:
                fail(f"{family} missing from /metrics.prom")
        snap = json.loads(scrape(f"{base}/metrics"))["generate"]
        if snap["spec_accepted"] + snap["spec_rejected"] < 1:
            fail(f"verify pass judged no draft tokens: {snap}")
        drain(proc)
        proc = None
        print(f"generate_smoke: ok — speculative text byte-identical "
              f"({plain!r}), spec families live")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="znicz_generate_smoke_")
    proc = None
    try:
        pkg = build_package(tmp)
        proc, base = boot(pkg)

        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps({"prompt": "hello world", "max_tokens": 8,
                             "temperature": 0.8, "top_k": 5,
                             "seed": 7}).encode(),
            headers={"Content-Type": "application/json"})
        lines = []
        with urllib.request.urlopen(req, timeout=60) as r:
            if r.headers["Content-Type"] != "application/x-ndjson":
                fail(f"unexpected content type "
                     f"{r.headers['Content-Type']!r}")
            for raw in r:
                lines.append(json.loads(raw))
        tokens = [ln for ln in lines if "token" in ln]
        terminals = [ln for ln in lines if ln.get("done")]
        if len(tokens) != 8:
            fail(f"wanted 8 streamed tokens, got {len(tokens)}: {lines}")
        if not all("text" in ln for ln in tokens):
            fail(f"token lines missing charmap text: {tokens[:3]}")
        if len(terminals) != 1 or terminals[0].get("reason") != \
                "length" or lines[-1] is not terminals[0]:
            fail(f"stream must end with exactly one done line: {lines}")

        snap = json.loads(scrape(f"{base}/metrics"))
        gen = snap.get("generate", {})
        if gen.get("completed") != 1 or gen.get("tokens") != 8:
            fail(f"metrics did not count the generation: {gen}")
        if snap.get("decoder", {}).get("prefill_count", 0) < 1:
            fail(f"decoder stats missing prefill: {snap.get('decoder')}")

        prom = scrape(f"{base}/metrics.prom").decode()
        for family in ("znicz_generate_tokens_total",
                       "znicz_generate_requests_total",
                       "znicz_generate_ttft_seconds",
                       "znicz_generate_active_slots",
                       # ISSUE 12: the CLI defaults to the paged arena,
                       # so its occupancy gauges must be live
                       "znicz_generate_cache_pages_used",
                       "znicz_generate_cache_pages_total"):
            if family not in prom:
                fail(f"{family} missing from /metrics.prom")

        drain(proc)
        proc = None
        print(f"generate_smoke: ok — streamed {len(tokens)} tokens, "
              f"terminal line + metrics families verified")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(speculative_leg() if "--speculative" in sys.argv[1:]
             else main())
