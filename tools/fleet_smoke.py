"""Fleet telemetry smoke for tools/t1.sh (ISSUE 11).

Boots TWO real ``python -m znicz_tpu generate --serve`` workers in
fresh processes (rank env set, the elastic fleet contract), streams one
short generation through each so request phase spans exist on both,
then stands up a :class:`FleetAggregator` over their HTTP endpoints and
asserts end to end over the wire:

- ``/fleet/metrics.prom`` carries ``znicz_generate_*`` families with
  BOTH ``rank="0"`` and ``rank="1"`` labels, and the merged text
  re-parses cleanly (no torn exposition);
- the merged fleet trace (aggregator ``trace_doc`` AND the
  ``python -m znicz_tpu trace --fleet`` CLI) carries request phase
  spans (``generate.prefill``) from both ranks on one timeline;
- the fleet watchtower sees the merged view (a trivial rule over
  ``znicz_generate_tokens_total`` summed across ranks evaluates).

jax-on-CPU; the compile cache is pinned off (the PR 9 box note).
Every failure prints a ``fleet_smoke:``-prefixed line and exits 1.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> "None":
    print(f"fleet_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def build_package(tmp: str) -> str:
    import numpy as np

    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.utils.export import export_lm

    charmap = list("abcdefghijklmnopqrstuvwxyz .,!?")
    params = init_params(np.random.default_rng(29), 2, 32, 4, 64,
                         len(charmap))
    pkg = os.path.join(tmp, "lm_fleet.npz")
    export_lm(params, pkg, heads=4, charmap=charmap, name="fleet_lm")
    return pkg


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_healthy(proc, base: str, deadline_s: float = 120.0) -> None:
    deadline = time.monotonic() + deadline_s
    while True:
        if proc.poll() is not None:
            out = (proc.stdout.read() or "")[-2000:]
            fail(f"worker exited rc={proc.returncode} before healthy: "
                 f"{out}")
        try:
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=5) as r:
                if json.load(r)["status"] == "ok":
                    return
        except (urllib.error.URLError, OSError, json.JSONDecodeError):
            pass
        if time.monotonic() > deadline:
            fail(f"worker at {base} never became healthy within "
                 f"{deadline_s:.0f}s")
        time.sleep(0.25)


def stream_one(base: str, prompt: str) -> None:
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps({"prompt": prompt, "max_tokens": 4}).encode(),
        headers={"Content-Type": "application/json"})
    lines = []
    with urllib.request.urlopen(req, timeout=60) as r:
        if not r.headers.get("X-Request-Id"):
            fail("stream response missing the X-Request-Id header")
        for raw in r:
            lines.append(json.loads(raw))
    if not lines or not lines[-1].get("done"):
        fail(f"stream from {base} did not end with a done line: {lines}")


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="znicz_fleet_smoke_")
    procs = []
    try:
        pkg = build_package(tmp)
        bases = []
        for rank in range(2):
            port = free_port()
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       ZNICZ_TPU_COMPILE_CACHE="off",
                       ZNICZ_TPU_ELASTIC_RANK=str(rank),
                       ZNICZ_TPU_ELASTIC_WORLD="2")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "znicz_tpu", "generate", pkg,
                 "--serve", "--port", str(port), "--slots", "2",
                 "--max-len", "64"],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
            bases.append(f"http://127.0.0.1:{port}")
        for proc, base in zip(procs, bases):
            wait_healthy(proc, base)
        for i, base in enumerate(bases):
            stream_one(base, "hello" if i == 0 else "world")

        from znicz_tpu.observe import federation as fed

        agg = fed.FleetAggregator()
        for rank, base in enumerate(bases):
            agg.add_http_source(rank, base)
        # a fleet rule over the merged view must actually evaluate
        rule = agg.add_rule(fed.Rule(
            "smoke_fleet_tokens", "znicz_generate_tokens_total",
            lambda v: v >= 8))
        agg.tower.observe_now()
        if not rule.matching or rule.trips != 1:
            fail(f"fleet rule over merged tokens did not evaluate/trip: "
                 f"{rule.snapshot()}")

        prom = agg.render_prometheus()
        _, samples = fed.parse_prometheus(prom)   # must re-parse whole
        for family in ("znicz_generate_tokens_total",
                       "znicz_generate_requests_total",
                       "znicz_generate_ttft_seconds_count"):
            for rank in (0, 1):
                if not any(name == family and f'rank="{rank}"' in inner
                           for _, name, inner, _ in samples):
                    fail(f"{family} rank={rank} missing from "
                         f"/fleet/metrics.prom")

        merged = agg.trace_doc()
        pids = {e["pid"] for e in merged["traceEvents"]
                if e.get("name") == "generate.prefill"}
        if pids != {0, 1}:
            fail(f"merged trace is missing prefill spans from both "
                 f"ranks (pids {sorted(pids)})")
        rids = {e["args"]["rid"] for e in merged["traceEvents"]
                if e.get("name") == "generate.prefill"}
        if len(rids) < 2:
            fail(f"prefill spans are not rid-linked: {rids}")

        # the offline CLI merge must agree
        out_path = os.path.join(tmp, "fleet_trace.json")
        proc = subprocess.run(
            [sys.executable, "-m", "znicz_tpu", "trace", "--fleet",
             "-o", out_path] + bases,
            cwd=REPO, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            fail(f"trace --fleet exited {proc.returncode}: "
                 f"{proc.stderr.strip()[:300]}")
        with open(out_path) as f:
            cli_doc = json.load(f)
        cli_pids = {e["pid"] for e in cli_doc["traceEvents"]
                    if e.get("name") == "generate.prefill"}
        if cli_pids != {0, 1}:
            fail(f"CLI-merged trace missing ranks: {sorted(cli_pids)}")
        agg.close()

        for proc in procs:
            proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                rc = proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                fail("worker did not drain within 60s of SIGTERM")
            if rc != 0:
                fail(f"worker exited rc={rc} on SIGTERM drain")
        procs.clear()
        print(f"fleet_smoke: ok — 2 workers, per-rank labels merged, "
              f"fleet rule evaluated, merged trace carries both ranks "
              f"({sum(1 for e in cli_doc['traceEvents'] if e['ph'] != 'M')}"
              f" events)")
        return 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
