#!/usr/bin/env python3
"""Perf-regression sentinel over recorded bench rounds (ISSUE 20).

``BENCH_r*.json`` artifacts accumulate one per driver round, but nothing
reads them adversarially: a 20% throughput cliff lands in the repo as
quietly as an improvement, and the only guard — ``vs_baseline`` on each
emitted line — is advisory output a human has to notice.  This tool
closes that loop: it diffs the NEWEST round against the prior one per
scenario metric and fails loudly (exit 1) when any comparable metric
moved past a configurable band in the losing direction.

Usage::

    python tools/bench_sentinel.py                 # newest vs prior round
    python tools/bench_sentinel.py --band 0.15     # widen the band
    python tools/bench_sentinel.py --report-only   # print, always exit 0
    python tools/bench_sentinel.py OLD.json NEW.json   # explicit pair

Semantics:

- a round's metrics come from its ``tail`` JSON lines (the child's
  flushed result records; later lines win per metric — bench.py's own
  ``_prev_round_values`` discipline), falling back to the driver's
  ``parsed`` headline when the tail carries none;
- orientation is inferred per metric: ``seconds``/``latency``/``_time``
  metrics regress UP, throughput (``*/sec``, ``per_sec``) regresses
  DOWN — so the band check is direction-aware without any schema change
  to the recorded artifacts;
- a metric present in only one round is REPORTED (``new``/``dropped``)
  but never fails the run: scenario sets legitimately grow per PR and a
  one-sided row has nothing to diff;
- a record the emitter marked non-comparable (``reached_target`` false,
  ``vs_baseline`` == 0.0) or a non-positive value is skipped the same
  way, and a round with ``rc != 0`` still contributes whatever lines it
  flushed before dying (flagged in the report).

Stdlib only — the sentinel must run in CI and on the bench host without
importing jax.  bench.py imports :func:`compare` to print a per-scenario
``# sentinel:`` line in report-only mode after each scenario.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: default relative band: |new/prev - 1| beyond this in the losing
#: direction fails (0.10 = a 10% regression)
DEFAULT_BAND = 0.10

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def lower_is_better(metric: str, unit: str = "") -> bool:
    """Orientation from the metric/unit names alone: time-like metrics
    regress upward, everything else (throughput) regresses downward."""
    u = str(unit or "").lower()
    m = str(metric or "").lower()
    if "/sec" in u or "/s" == u or "per_sec" in m or "per_second" in m:
        return False
    if u in ("seconds", "s", "ms", "us") or "latency" in m or \
            m.endswith("_seconds") or m.endswith("_time") or \
            "_seconds_" in m:
        return True
    return False


def load_round(path: str) -> dict:
    """``metric -> record`` for one BENCH artifact: tail JSON lines
    (later lines win), else the driver's ``parsed`` headline; plus the
    pseudo-entry ``"__rc__"`` carrying the round's exit code."""
    with open(path) as f:
        doc = json.load(f)
    records: dict = {}
    for line in str(doc.get("tail", "")).splitlines():
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(r, dict) and "metric" in r and "value" in r:
            records[str(r["metric"])] = r
    if not records:
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed and \
                "value" in parsed:
            records[str(parsed["metric"])] = parsed
    records["__rc__"] = {"rc": doc.get("rc")}
    return records


def discover_rounds(repo: str = REPO) -> list:
    """Sorted ``(round_no, path)`` for every BENCH_r*.json present."""
    out = []
    for path in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        m = _ROUND_RE.search(path)
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def _comparable(rec: dict) -> bool:
    try:
        value = float(rec["value"])
    except (KeyError, TypeError, ValueError):
        return False
    if value <= 0.0:
        return False
    if rec.get("reached_target") is False:
        return False
    # the emitter stamps vs_baseline 0.0 on runs it judged
    # non-comparable (trend_valid=False) — honor that verdict
    if rec.get("vs_baseline") == 0.0:
        return False
    return True


def compare(prev: dict, new: dict, band: float = DEFAULT_BAND) -> list:
    """Diff two ``metric -> record`` maps -> finding dicts, each
    ``{"metric", "kind", "detail", ...}`` with ``kind`` one of
    ``regression`` / ``improvement`` / ``new`` / ``dropped`` /
    ``skipped``.  Only ``regression`` findings should fail a caller."""
    findings = []
    prev = {k: v for k, v in prev.items() if k != "__rc__"}
    new = {k: v for k, v in new.items() if k != "__rc__"}
    for metric in sorted(set(prev) | set(new)):
        p, n = prev.get(metric), new.get(metric)
        if p is None:
            findings.append({"metric": metric, "kind": "new",
                             "detail": "no prior round to diff against"})
            continue
        if n is None:
            findings.append({"metric": metric, "kind": "dropped",
                             "detail": "present in prior round only"})
            continue
        if not _comparable(p) or not _comparable(n):
            findings.append({"metric": metric, "kind": "skipped",
                             "detail": "non-comparable record "
                                       "(missing/zero value or marked "
                                       "not-reached)"})
            continue
        pv, nv = float(p["value"]), float(n["value"])
        lower = lower_is_better(metric, n.get("unit", p.get("unit", "")))
        ratio = nv / pv
        # loss is always expressed as a positive fraction past the band
        loss = (ratio - 1.0) if lower else (1.0 - ratio)
        base = {"metric": metric, "prev": pv, "new": nv,
                "ratio": round(ratio, 4),
                "orientation": "lower" if lower else "higher"}
        if loss > band:
            findings.append({**base, "kind": "regression",
                             "detail": f"{loss:+.1%} past the "
                                       f"{band:.0%} band"})
        elif loss < -band:
            findings.append({**base, "kind": "improvement",
                             "detail": f"{-loss:+.1%}"})
        else:
            findings.append({**base, "kind": "ok",
                             "detail": f"within band ({loss:+.1%})"})
    return findings


def render(findings: list, label: str = "") -> str:
    head = f"sentinel{f' [{label}]' if label else ''}: "
    if not findings:
        return head + "nothing to diff"
    lines = []
    for f in findings:
        bits = f"{f['kind'].upper():11s} {f['metric']}"
        if "prev" in f:
            bits += (f"  {f['prev']:g} -> {f['new']:g} "
                     f"(x{f['ratio']:g}, {f['orientation']}-is-better)")
        lines.append(head + bits + f" — {f['detail']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="diff the newest BENCH_r*.json against the prior "
                    "round and fail past the regression band")
    p.add_argument("files", nargs="*",
                   help="explicit OLD.json NEW.json pair (default: the "
                        "two newest BENCH_r*.json in the repo)")
    p.add_argument("--band", type=float, default=DEFAULT_BAND,
                   help=f"relative regression band "
                        f"(default {DEFAULT_BAND:g})")
    p.add_argument("--report-only", action="store_true",
                   help="print findings but always exit 0")
    p.add_argument("--repo", default=REPO,
                   help="repo root to scan for BENCH_r*.json")
    args = p.parse_args(argv)

    if args.files:
        if len(args.files) != 2:
            p.error("pass exactly two files: OLD.json NEW.json")
        old_path, new_path = args.files
        label = (f"{os.path.basename(old_path)} -> "
                 f"{os.path.basename(new_path)}")
    else:
        rounds = discover_rounds(args.repo)
        if len(rounds) < 2:
            print("sentinel: fewer than two BENCH rounds recorded; "
                  "nothing to diff", file=sys.stderr)
            return 0
        (_, old_path), (_, new_path) = rounds[-2], rounds[-1]
        label = (f"r{rounds[-2][0]:02d} -> r{rounds[-1][0]:02d}")

    prev, new = load_round(old_path), load_round(new_path)
    for name, rec in (("prior", prev), ("newest", new)):
        rc = rec.get("__rc__", {}).get("rc")
        if rc not in (0, None):
            print(f"sentinel: {name} round exited rc={rc}; diffing "
                  f"whatever it flushed", file=sys.stderr)
    findings = compare(prev, new, band=args.band)
    print(render(findings, label=label))
    regressions = [f for f in findings if f["kind"] == "regression"]
    if regressions and not args.report_only:
        print(f"sentinel: {len(regressions)} regression(s) past the "
              f"{args.band:.0%} band", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
