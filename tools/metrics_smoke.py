"""Post-test scrape smoke for tools/t1.sh (ISSUE 5 + 6): boot a
WebStatus, hit `/metrics`, `/trace.json` and `/timeseries.json` over
real HTTP, dump a flight artifact and round-trip it through
`python -m znicz_tpu flight`, and fail LOUDLY on a non-200 status, an
unparseable body, an empty registry/trace/ring, or a flight viewer
that can't read its own recorder's output.  Kept jax-free (observe +
web_status are stdlib-only) so the smoke costs milliseconds after a
10-minute tier-1 run.

Exit 0 on success; any failure prints one `metrics_smoke:`-prefixed
line to stderr and exits 1.
"""

import json
import os
import subprocess
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def fail(msg: str) -> None:
    print(f"metrics_smoke: FAILED — {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    from znicz_tpu import observe
    from znicz_tpu.web_status import WebStatus

    # exercise one of each instrument so the scrape carries live values
    observe.counter("znicz_smoke_total", "t1.sh scrape smoke").inc()
    with observe.span("smoke.step", step=1):
        pass
    observe.instant("smoke.event")
    # ... and one watchtower sample so /timeseries.json has a ring entry
    observe.WATCHTOWER.observe_now()

    status = WebStatus(port=0)
    port = status.start()
    try:
        base = f"http://127.0.0.1:{port}"

        resp = urllib.request.urlopen(base + "/metrics", timeout=10)
        if resp.status != 200:
            fail(f"GET /metrics -> {resp.status}")
        body = resp.read().decode()
        type_lines = [ln for ln in body.splitlines()
                      if ln.startswith("# TYPE znicz_")]
        if not type_lines:
            fail("GET /metrics served an EMPTY registry (no znicz_ "
                 "family declarations)")
        if "znicz_smoke_total 1" not in body:
            fail("counter written before the scrape is missing from "
                 "the exposition")

        resp = urllib.request.urlopen(base + "/trace.json", timeout=10)
        if resp.status != 200:
            fail(f"GET /trace.json -> {resp.status}")
        doc = json.load(resp)
        names = {e.get("name") for e in doc.get("traceEvents", [])}
        if not {"smoke.step", "smoke.event"} <= names:
            fail(f"trace ring is missing the smoke events "
                 f"(got {sorted(n for n in names if n)[:8]}...)")

        # ISSUE 6: the watchtower's retained ring must actually serve
        resp = urllib.request.urlopen(base + "/timeseries.json",
                                      timeout=10)
        if resp.status != 200:
            fail(f"GET /timeseries.json -> {resp.status}")
        ts_doc = json.load(resp)
        if not ts_doc.get("samples"):
            fail("GET /timeseries.json served an EMPTY ring (sample "
                 "taken before the scrape is missing)")
        replay = dict(ts_doc["base"])
        for row in ts_doc["samples"]:
            replay.update(row["delta"])
        if replay.get("znicz_smoke_total") != 1:
            fail("replaying /timeseries.json base+deltas did not "
                 "reconstruct the smoke counter")
    finally:
        status.stop()

    # ISSUE 6: a flight dump must round-trip through the CLI viewer
    from znicz_tpu.observe import flight

    with tempfile.TemporaryDirectory() as tmp:
        path = flight.dump(dir=tmp, reason="t1_smoke")
        try:
            flight.load(path)            # raises ValueError on a bad schema
        except ValueError as exc:
            fail(f"flight.load() rejected its own dump: {exc}")
        proc = subprocess.run(
            [sys.executable, "-m", "znicz_tpu", "flight", path],
            capture_output=True, text=True, timeout=60)
        if proc.returncode != 0:
            fail(f"`python -m znicz_tpu flight` exited "
                 f"{proc.returncode}: {proc.stderr.strip()[:200]}")
        if "t1_smoke" not in proc.stdout:
            fail("flight viewer output is missing the dump reason")

    print(f"metrics_smoke: ok — {len(type_lines)} registry families, "
          f"{sum(1 for e in doc['traceEvents'] if e['ph'] != 'M')} "
          f"trace events, {len(ts_doc['samples'])} ring samples, "
          f"flight round-trip ok")


if __name__ == "__main__":
    main()
