"""Post-test scrape smoke for tools/t1.sh (ISSUE 5): boot a WebStatus,
hit `/metrics` and `/trace.json` over real HTTP, and fail LOUDLY on a
non-200 status, an unparseable body, or an empty registry/trace.  Kept
jax-free (observe + web_status are stdlib-only) so the smoke costs
milliseconds after a 10-minute tier-1 run.

Exit 0 on success; any failure prints one `metrics_smoke:`-prefixed
line to stderr and exits 1.
"""

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def fail(msg: str) -> None:
    print(f"metrics_smoke: FAILED — {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    from znicz_tpu import observe
    from znicz_tpu.web_status import WebStatus

    # exercise one of each instrument so the scrape carries live values
    observe.counter("znicz_smoke_total", "t1.sh scrape smoke").inc()
    with observe.span("smoke.step", step=1):
        pass
    observe.instant("smoke.event")

    status = WebStatus(port=0)
    port = status.start()
    try:
        base = f"http://127.0.0.1:{port}"

        resp = urllib.request.urlopen(base + "/metrics", timeout=10)
        if resp.status != 200:
            fail(f"GET /metrics -> {resp.status}")
        body = resp.read().decode()
        type_lines = [ln for ln in body.splitlines()
                      if ln.startswith("# TYPE znicz_")]
        if not type_lines:
            fail("GET /metrics served an EMPTY registry (no znicz_ "
                 "family declarations)")
        if "znicz_smoke_total 1" not in body:
            fail("counter written before the scrape is missing from "
                 "the exposition")

        resp = urllib.request.urlopen(base + "/trace.json", timeout=10)
        if resp.status != 200:
            fail(f"GET /trace.json -> {resp.status}")
        doc = json.load(resp)
        names = {e.get("name") for e in doc.get("traceEvents", [])}
        if not {"smoke.step", "smoke.event"} <= names:
            fail(f"trace ring is missing the smoke events "
                 f"(got {sorted(n for n in names if n)[:8]}...)")
    finally:
        status.stop()

    print(f"metrics_smoke: ok — {len(type_lines)} registry families, "
          f"{sum(1 for e in doc['traceEvents'] if e['ph'] != 'M')} "
          f"trace events")


if __name__ == "__main__":
    main()
