"""Train-while-serve smoke for tools/t1.sh (ISSUE 14).

Boots the REAL ``python -m znicz_tpu learn`` CLI in a fresh process —
which itself spawns 2 real ``generate --serve`` worker processes (each
appending accepted traffic to the shared feedback spool) and ONE
trainer process under the elastic supervisor — in ``--smoke-test``
mode: the CLI drives throttled self-traffic through its router, the
trainer consumes the spool and publishes after ``--publish-every``
epochs, and the adoption bridge rolls the fleet onto the published
package.

The CLI's JSON verdict is re-asserted here:

- at least one publish was ADOPTED (polled rollout ran to done);
- the fleet CONVERGED: every worker reports the published package's
  sha256 (and it differs from the base package's — the loop actually
  moved the weights);
- the router ledger CLOSED (admitted == completed + failed +
  client_gone) with zero broken streams — zero lost requests.

jax-on-CPU; the compile cache is pinned off (the PR 9 box note).
Every failure prints a ``learn_smoke:``-prefixed line, exits 1.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> "None":
    print(f"learn_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def build_package(tmp: str) -> str:
    import numpy as np

    from znicz_tpu.parallel.transformer import init_params
    from znicz_tpu.utils.export import export_lm

    charmap = list("abcdefgh .,!?")
    params = init_params(np.random.default_rng(31), 2, 32, 4, 64,
                         len(charmap))
    path = os.path.join(tmp, "lm.npz")
    export_lm(params, path, heads=4, charmap=charmap, name="lm_base")
    return path


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="znicz_learn_smoke_")
    try:
        pkg = build_package(tmp)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   ZNICZ_TPU_COMPILE_CACHE="off")
        proc = subprocess.run(
            [sys.executable, "-m", "znicz_tpu", "learn", pkg,
             "--workers", "2", "--port", "0", "--smoke-test",
             "--max-epochs", "2", "--publish-every", "2",
             "--records-per-epoch", "6", "--seq-len", "8",
             "--run-dir", os.path.join(tmp, "learn"),
             "--", "--slots", "2", "--max-len", "48"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=660)
        verdict = None
        for line in (proc.stdout or "").strip().splitlines():
            if line.startswith("{"):
                try:
                    verdict = json.loads(line)
                except json.JSONDecodeError:
                    pass
        if proc.returncode != 0 or verdict is None:
            fail(f"learn CLI rc={proc.returncode}; stdout tail: "
                 f"{(proc.stdout or '')[-1500:]!r}; stderr tail: "
                 f"{(proc.stderr or '')[-1500:]!r}")
        if verdict.get("smoke") != "ok":
            fail(f"CLI verdict bad: {verdict}")
        if verdict.get("adoptions", 0) < 1 or \
                not verdict.get("converged"):
            fail(f"no adopted publish / fleet not converged: {verdict}")
        if verdict.get("fingerprint") == verdict.get(
                "base_fingerprint"):
            fail(f"fleet still serves the BASE weights — the loop "
                 f"never moved them: {verdict}")
        ledger = verdict.get("ledger") or {}
        if ledger.get("admitted") != ledger.get("completed", 0) + \
                ledger.get("failed", 0) + ledger.get("client_gone", 0):
            fail(f"router ledger does not close: {ledger}")
        traffic = verdict.get("traffic") or {}
        if traffic.get("broken"):
            fail(f"broken client streams during the loop: {traffic}")
        print(f"learn_smoke: ok — {verdict['adoptions']} publish(es) "
              f"adopted (latency "
              f"{verdict.get('adoption_latency_s'):.1f}s), fleet on "
              f"sha {verdict['fingerprint']}, ledger closed over "
              f"{ledger.get('admitted')} routed requests "
              f"({traffic})")
        return 0
    except subprocess.TimeoutExpired as exc:
        fail(f"learn CLI did not finish within 660s; stdout tail: "
             f"{(exc.stdout or b'')[-1200:]!r}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
