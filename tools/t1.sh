#!/bin/bash
# Tier-1 verify — the ROADMAP.md command verbatim.  Run from anywhere:
#   bash tools/t1.sh
# Exit code is pytest's; DOTS_PASSED echoes the passed-test count the
# driver greps for.
cd "$(dirname "$0")/.." || exit 1
if ! python -c "import pytest" 2>/dev/null; then
    echo "tools/t1.sh: pytest is not importable in this Python" \
         "($(command -v python || echo 'python not found')) — install it" \
         "or activate the right environment" >&2
    exit 2
fi
# pytest wall budget: the suite measured 1033s on a CLEAN seed checkout
# under this box's current contention (457s at PR 15 — same tests, 2x+
# theft, see the bench notes), so the old 870 s cap truncated the run
# before the summary; 1500 keeps the old ~30% headroom over measured
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# ISSUE 5+6 smoke: the telemetry scrape surfaces must actually serve —
# boot a WebStatus, hit /metrics + /trace.json + /timeseries.json, and
# round-trip a flight artifact through `python -m znicz_tpu flight`
# (jax-free, milliseconds)
if ! timeout -k 5 60 python tools/metrics_smoke.py; then
    echo "tools/t1.sh: telemetry scrape smoke FAILED (see metrics_smoke" \
         "lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 6 static pass: every znicz_* metric family used in znicz_tpu/
# must be in the docs/OBSERVABILITY.md catalogue, and vice versa
if ! timeout -k 5 60 python tools/check_metric_catalogue.py; then
    echo "tools/t1.sh: metric catalogue check FAILED (see" \
         "check_metric_catalogue lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 7 smoke: zero-JIT serve boot — export an AOT package, boot the
# real serve CLI in a fresh jax-on-CPU process, scrape /metrics, assert
# the engine compile counter is 0 (docs/COMPILE.md)
if ! timeout -k 5 240 env JAX_PLATFORMS=cpu python tools/aot_smoke.py; then
    echo "tools/t1.sh: AOT zero-JIT serve smoke FAILED (see aot_smoke" \
         "lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 10 smoke: generative serving — boot the real `generate --serve`
# CLI from an exported LM package in a fresh process, stream a short
# generation over HTTP (ndjson tokens + exactly one terminal line),
# assert the znicz_generate_* metric families are live
# (docs/SERVING.md "Generative serving")
if ! timeout -k 5 240 env JAX_PLATFORMS=cpu python tools/generate_smoke.py; then
    echo "tools/t1.sh: generative serving smoke FAILED (see" \
         "generate_smoke lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 12 smoke: speculative decoding exactness — two fresh-process
# boots from one draft-carrying LM package must stream BYTE-IDENTICAL
# greedy text with speculation on vs off, and the spec/pages metric
# families must be live (docs/SERVING.md "Speculative decoding";
# ZNICZ_TPU_COMPILE_CACHE=off per the box note)
if ! timeout -k 5 300 env JAX_PLATFORMS=cpu python tools/generate_smoke.py --speculative; then
    echo "tools/t1.sh: speculative decoding smoke FAILED (see" \
         "generate_smoke lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 11 smoke: fleet telemetry — boot 2 real generate workers with
# rank env, aggregate their /metrics.prom into one rank-labeled fleet
# view, assert a fleet rule evaluates over the merged series and the
# merged Perfetto trace carries request phase spans from both ranks
# (docs/OBSERVABILITY.md "Fleet telemetry"; ZNICZ_TPU_COMPILE_CACHE=off
# per the PR 9 box note)
if ! timeout -k 5 300 env JAX_PLATFORMS=cpu python tools/fleet_smoke.py; then
    echo "tools/t1.sh: fleet telemetry smoke FAILED (see fleet_smoke" \
         "lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 13 smoke: serving fleet — the real `fleet` CLI boots a router
# + 2 real generate workers from one LM package, streams through the
# router under threaded traffic, performs one rolling weight update via
# POST /rollout, and asserts zero lost requests + fleet convergence on
# the new fingerprint + steady-state compile delta 0
# (docs/SERVING.md "Fleet topology"; ZNICZ_TPU_COMPILE_CACHE=off per
# the PR 9 box note)
if ! timeout -k 5 400 env JAX_PLATFORMS=cpu python tools/fleet_router_smoke.py; then
    echo "tools/t1.sh: serving-fleet router smoke FAILED (see" \
         "fleet_router_smoke lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 14 smoke: train-while-serve — the real `learn` CLI closes the
# whole loop in fresh processes: 2 serve workers feed the spool, 1
# supervised trainer consumes it and publishes, the bridge rolls the
# fleet; asserts an adopted publish + fleet-wide new fingerprint +
# closed router ledger (docs/LEARNING.md; ZNICZ_TPU_COMPILE_CACHE=off
# per the PR 9 box note)
if ! timeout -k 5 700 env JAX_PLATFORMS=cpu python tools/learn_smoke.py; then
    echo "tools/t1.sh: train-while-serve smoke FAILED (see learn_smoke" \
         "lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 15 smoke: ZeRO shard_params — dp(4)+shard_params(adam) on a
# forced 4-device CPU mesh must read per-chip znicz_zero_* bytes at
# ~1/4 of the replicated run's with an identical seeded metric history
# (docs/TUNING.md "ZeRO modes"; ZNICZ_TPU_COMPILE_CACHE=off per the
# PR 9 box note)
if ! timeout -k 5 240 env JAX_PLATFORMS=cpu python tools/zero_smoke.py; then
    echo "tools/t1.sh: ZeRO shard_params smoke FAILED (see zero_smoke" \
         "lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 18 smoke: quantized collectives — on a forced 4-device CPU
# mesh, mode=off must reproduce the baseline seeded history
# bit-identically and an int8+error-feedback shard_params run must read
# ~4x compression from the znicz_qcomm_* counters on both collectives
# (docs/TUNING.md "Quantized collectives"; ZNICZ_TPU_COMPILE_CACHE=off
# per the PR 9 box note)
if ! timeout -k 5 240 env JAX_PLATFORMS=cpu python tools/qcomm_smoke.py; then
    echo "tools/t1.sh: quantized-collectives smoke FAILED (see" \
         "qcomm_smoke lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 20 smoke: step anatomy — on a forced 4-device CPU mesh a
# dp(4)+shard_params+int8 anatomy run must pre-touch every
# znicz_anatomy_* child at init, attribute per-phase seconds summing to
# within 10% of the measured step wall, read a nonzero mfu gauge (peak
# pinned via $ZNICZ_TPU_PEAK_FLOPS), and trip the per-rank straggler
# rule for exactly one artificially delayed rank
# (docs/OBSERVABILITY.md "Step anatomy & goodput";
# ZNICZ_TPU_COMPILE_CACHE=off per the PR 9 box note)
if ! timeout -k 5 240 env JAX_PLATFORMS=cpu python tools/anatomy_smoke.py; then
    echo "tools/t1.sh: step-anatomy smoke FAILED (see anatomy_smoke" \
         "lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
# ISSUE 9 smoke: elastic kill-and-resume — 2 CPU worker processes, the
# snapshot writer SIGKILL'd at a seeded step, fleet resumes at world
# size 1; asserts completion + >= 1 flight artifact + resumes counter
# (docs/RESILIENCE.md "Elastic multi-process")
if ! timeout -k 5 300 env JAX_PLATFORMS=cpu python tools/elastic_smoke.py; then
    echo "tools/t1.sh: elastic kill-and-resume smoke FAILED (see" \
         "elastic_smoke lines above)" >&2
    [ $rc -eq 0 ] && rc=1
fi
exit $rc
