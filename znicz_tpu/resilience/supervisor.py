"""Supervised auto-resume training.

``run_supervised(workflow_factory, snap_dir, policy)`` is the in-process
analog of a cluster supervisor restarting a failed trainer (TensorFlow's
supervisor/monitored-session shape, arXiv 1605.08695): run the workflow,
catch crashes, restore the newest *valid* snapshot into a freshly built
workflow, resume — under a bounded restart budget with backed-off
restarts.  A watchdog thread detects a hung step (no control-graph
progress within ``step_timeout``) and treats it as a crash.

Correctness contract (pinned by tests/test_resilience.py): because the
snapshotter's resume is bit-exact, a run killed at any point and
auto-resumed by the supervisor reproduces the uninterrupted run's metric
history *exactly* — recovery is verifiable, not best-effort.

Poison snapshots: ``find_latest_valid_snapshot`` checksum-verifies
candidates newest-first (``snapshotter.verify_snapshot``) and falls back
to the previous valid one, so a snapshot torn by the very crash being
recovered from (or corrupted on disk) is rejected instead of trusted.

The factory owns seeding and construction: it must return a freshly
built, *initialized* workflow each call (re-seeding any global PRNG it
uses, exactly like a fresh process would) — the same discipline the
snapshotter tests already follow.
"""

from __future__ import annotations

import glob
import os
import re
import sys
import threading
import time
import traceback
from typing import Callable, Optional

import numpy as np

from znicz_tpu.core.logger import Logger
from znicz_tpu.observe import flight as _flight
from znicz_tpu.observe import probe as _probe
from znicz_tpu.resilience import faults
from znicz_tpu.snapshotter import restore_state, verify_snapshot


class SupervisorExhausted(RuntimeError):
    """Restart budget spent without a completed run."""


class StepHangError(RuntimeError):
    """Watchdog: no control-graph progress within ``step_timeout``."""


class SupervisorPolicy:
    """Knobs for :func:`run_supervised`.

    max_restarts:  restarts allowed after the initial attempt.
    backoff_base/backoff_multiplier/backoff_max: restart delay schedule
                   (exponential, seconds).
    backoff_jitter: +/- fraction of the delay, drawn from a generator
                   seeded with ``seed`` (deterministic in tests).
    step_timeout:  watchdog stall threshold in seconds (None = watchdog
                   off; the workflow runs on the calling thread).
    hang_grace:    after interrupting injected hangs, how long to wait
                   for the worker thread to die before abandoning it.
    flight_recorder: dump a flight artifact (observe/flight.py: span
                   tail + time series + registry + log tail) into the
                   snapshot directory before every restore-and-resume
                   and on budget exhaustion, so the post-mortem
                   survives the process.
    sleep:         injectable clock for tests.
    """

    def __init__(self, max_restarts: int = 3, backoff_base: float = 0.05,
                 backoff_multiplier: float = 2.0, backoff_max: float = 5.0,
                 backoff_jitter: float = 0.25, seed: int = 0,
                 step_timeout: Optional[float] = None,
                 hang_grace: float = 2.0, flight_recorder: bool = True,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got "
                             f"{max_restarts}")
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_multiplier = float(backoff_multiplier)
        self.backoff_max = float(backoff_max)
        self.backoff_jitter = float(backoff_jitter)
        self.step_timeout = step_timeout
        self.hang_grace = float(hang_grace)
        self.flight_recorder = bool(flight_recorder)
        self.sleep = sleep
        self._rng = np.random.default_rng(seed)

    def restart_delay(self, restart: int) -> float:
        """Backoff before restart ``restart`` (1-based), jittered."""
        d = min(self.backoff_max,
                self.backoff_base * self.backoff_multiplier ** (restart - 1))
        if self.backoff_jitter:
            d *= 1.0 + self.backoff_jitter * float(
                self._rng.uniform(-1.0, 1.0))
        return d


class SupervisorReport:
    """What happened: restart count, snapshots resumed from, snapshots
    rejected as invalid, hang events, the failures caught, the flight
    artifacts dumped per failure, and the final workflow (its
    ``decision.metrics_history`` is the training record)."""

    def __init__(self) -> None:
        self.restarts = 0
        self.resumed_from: list[str] = []
        self.rejected_snapshots: list[str] = []
        self.hang_events = 0
        self.failures: list[str] = []
        self.flights: list[str] = []
        self.workflow = None

    def as_dict(self) -> dict:
        return {"restarts": self.restarts,
                "resumed_from": list(self.resumed_from),
                "rejected_snapshots": list(self.rejected_snapshots),
                "hang_events": self.hang_events,
                "failures": list(self.failures),
                "flights": list(self.flights)}


_EPOCH_RE = re.compile(r"_(\d+)\.npz$")


def _snapshot_candidates(snap_dir: str, prefix: Optional[str]) -> list[str]:
    """Real snapshot files newest-first: ``*_latest.npz`` pointers are
    skipped (they alias a numbered file), order is by embedded epoch
    number when present, mtime otherwise."""
    pattern = f"{prefix}_*.npz" if prefix else "*.npz"
    paths = [p for p in glob.glob(os.path.join(snap_dir, pattern))
             if not p.endswith("_latest.npz") and not os.path.islink(p)]

    def key(p):
        m = _EPOCH_RE.search(os.path.basename(p))
        return (1, int(m.group(1))) if m else (0, os.path.getmtime(p))

    return sorted(paths, key=key, reverse=True)


def find_latest_valid_snapshot(snap_dir: str, prefix: Optional[str] = None,
                               rejected: Optional[list] = None
                               ) -> Optional[str]:
    """Newest snapshot in ``snap_dir`` that passes checksum verification;
    invalid ones (torn writes, bit rot, poison) are appended to
    ``rejected`` and skipped — the previous valid snapshot wins."""
    if not os.path.isdir(snap_dir):
        return None
    for path in _snapshot_candidates(snap_dir, prefix):
        if verify_snapshot(path):
            return path
        if rejected is not None:
            rejected.append(path)
    return None


class _Watchdog:
    """Run ``workflow.run()`` on a worker thread while the supervisor
    thread polls the workflow's ``signals_dispatched`` progress counter.
    A stall beyond ``step_timeout`` aborts injected hangs (cooperative)
    and, failing that, abandons the daemon worker — either way the run
    is declared failed with :class:`StepHangError`."""

    def __init__(self, workflow, step_timeout: float,
                 hang_grace: float) -> None:
        self.workflow = workflow
        self.step_timeout = step_timeout
        self.hang_grace = hang_grace
        self.error: Optional[BaseException] = None
        #: the hung worker thread's stack, captured at stall-detection
        #: time (BEFORE the hang interrupt unwinds it) — the flight
        #: artifact's answer to "WHERE did the step stall", not just
        #: "that it did"
        self.hung_stack: list[str] = []
        self._done = threading.Event()

    def _capture_stack(self, thread: threading.Thread) -> None:
        try:
            frame = sys._current_frames().get(thread.ident)
            if frame is not None:
                self.hung_stack = traceback.format_stack(frame)
        except Exception:  # noqa: BLE001 — diagnostics must not fail the
            pass           # failure path

    def _worker(self) -> None:
        try:
            self.workflow.run()
        except BaseException as exc:  # noqa: BLE001 — reported to caller
            self.error = exc
        finally:
            self._done.set()

    def run(self) -> Optional[BaseException]:
        t = threading.Thread(target=self._worker, daemon=True)
        t.start()
        last = -1
        last_change = time.monotonic()
        while not self._done.wait(timeout=min(0.05, self.step_timeout / 4)):
            now = time.monotonic()
            progress = self.workflow.signals_dispatched
            if progress != last:
                last, last_change = progress, now
            elif now - last_change > self.step_timeout:
                self._capture_stack(t)     # where is it stuck, exactly?
                faults.interrupt_hangs()   # cooperative: injected hangs die
                t.join(self.hang_grace)
                if t.is_alive():
                    # a real (non-injected) hang: abandon the daemon
                    # thread — the restarted attempt uses fresh objects
                    return StepHangError(
                        f"no progress for {self.step_timeout}s "
                        f"(stuck at {progress} signals); worker abandoned")
                if self._done.is_set() and self.error is None:
                    # the "stall" was a long single step (e.g. an XLA
                    # compile) that finished inside the grace window —
                    # not a hang; size step_timeout above the worst
                    # compile+step time to avoid tripping this at all
                    return None
                return self.error or StepHangError(
                    f"no progress for {self.step_timeout}s; worker "
                    f"stopped after hang interrupt")
        return self.error


def run_supervised(workflow_factory: Callable, snap_dir: str,
                   policy: Optional[SupervisorPolicy] = None,
                   prefix: Optional[str] = None) -> SupervisorReport:
    """Train to completion under supervision; returns the report (the
    final workflow rides on ``report.workflow``).

    Each attempt: build a fresh workflow via ``workflow_factory()``
    (initialized, freshly seeded), restore the newest valid snapshot from
    ``snap_dir`` when one exists, run.  A crash or detected hang consumes
    one restart from the budget and backs off before the next attempt;
    when the budget is spent, :class:`SupervisorExhausted` is raised from
    the last failure.
    """
    policy = policy or SupervisorPolicy()
    report = SupervisorReport()
    log = Logger()
    attempt = 0
    while True:
        attempt += 1
        workflow = workflow_factory()
        if not workflow.initialized:
            raise RuntimeError("workflow_factory must return an "
                               "initialized workflow")
        snap = find_latest_valid_snapshot(
            snap_dir, prefix, rejected=report.rejected_snapshots)
        if snap is not None:
            restore_state(workflow, snap)
            report.resumed_from.append(snap)
            _probe.resilience_event("snapshot_resume", attempt=attempt,
                                    snapshot=os.path.basename(snap))
            log.info(f"supervisor: attempt {attempt} resumes from {snap}")
        error: Optional[BaseException] = None
        hung_stack: list[str] = []
        if policy.step_timeout is None:
            try:
                workflow.run()
            except Exception as exc:  # noqa: BLE001 — supervised surface
                error = exc
        else:
            watchdog = _Watchdog(workflow, policy.step_timeout,
                                 policy.hang_grace)
            error = watchdog.run()
            hung_stack = watchdog.hung_stack
        if error is None and bool(workflow.decision.complete):
            report.workflow = workflow
            return report
        if error is None:
            error = RuntimeError("workflow.run returned without "
                                 "decision.complete (control graph "
                                 "drained early)")
        if isinstance(error, StepHangError) or \
                isinstance(error, faults.HangInterrupted):
            report.hang_events += 1
            _probe.resilience_event("hang", attempt=attempt)
        report.failures.append(repr(error))
        report.restarts += 1
        # restart on the shared timeline: the instant sits between the
        # last step span of the crashed attempt and the first of the next
        _probe.resilience_event("restart", attempt=attempt,
                                error=type(error).__name__)
        exhausted = report.restarts > policy.max_restarts
        if policy.flight_recorder:
            # post-mortem BEFORE restore-and-resume (or the final
            # raise): the next attempt overwrites in-memory telemetry,
            # so this artifact is the only record of the crashed one.
            # Recorder failures degrade to a warning — they must not
            # consume another restart.
            try:
                extra = {"attempt": attempt, "restarts": report.restarts,
                         "error": repr(error),
                         "error_type": type(error).__name__}
                if hung_stack:
                    # the post-mortem shows WHERE the step stalled
                    extra["hung_stack"] = hung_stack
                report.flights.append(_flight.dump(
                    dir=snap_dir,
                    reason="exhausted" if exhausted else "restart",
                    extra=extra))
            except Exception as flight_exc:  # noqa: BLE001
                log.warning(f"supervisor: flight dump failed: "
                            f"{flight_exc!r}")
        log.warning(f"supervisor: attempt {attempt} failed: {error!r}")
        if exhausted:
            raise SupervisorExhausted(
                f"gave up after {report.restarts - 1} restarts "
                f"({policy.max_restarts} allowed); failures: "
                f"{report.failures}") from error
        policy.sleep(policy.restart_delay(report.restarts))
