"""Deterministic, seeded fault injection.

A :class:`FaultPlan` arms faults at named *sites* — fixed hook points the
production code calls explicitly:

===================  ======================================================
site                 hook location
===================  ======================================================
``workflow.step``    ``core/workflow.py`` run loop, once per control-graph
                     signal delivery (context: ``workflow``, ``unit``)
``snapshot.write``   ``snapshotter.write_snapshot``, before the atomic
                     publish (context: ``path``)
``serve.run``        ``serve/engine.py`` ``BatchEngine.run`` entry
``generate.step``    ``serve/continuous.py`` decode loop, once per
                     batched decode step (context: ``batcher``) — a
                     crash fails every ACTIVE stream with its terminal
                     error sentinel and the worker keeps serving
``pipeline.fetch``   ``pipeline/prefetcher.py`` worker loop, once per
                     prefetched batch (context: ``loader``, ``batch``);
                     a crash here re-raises on the consumer — the
                     supervisor sees an ordinary failed step
``step.loss``        ``parallel/step.py`` metric publish — value-poison
                     site (NaN into the published loss)
``step.params``      ``parallel/step.py`` after a train dispatch —
                     value-poison site (NaN into the param pytree, the
                     observable effect of NaN gradients)
``elastic.worker``   ``core/workflow.py`` run loop, same cadence as
                     ``workflow.step`` but with NO context kwargs — the
                     cross-process site: elastic-fleet drills arm it via
                     the ``ZNICZ_TPU_FAULT_PLAN`` env (``at_hit`` only;
                     predicates cannot cross a process boundary), usually
                     with the ``kill`` action
===================  ======================================================

Chaos tests therefore exercise the *real* step loop / save path / serving
path, never a mock.  Every fault triggers on a deterministic condition: an
absolute hit count of its site (``at_hit``) and/or a predicate over the
hook context (``when``), so a seeded test reproduces exactly.  The plan's
own ``rng`` (``numpy`` Generator seeded from the constructor) is how tests
derive "a random epoch" reproducibly.

The module-level registry is process-global and *off by default*: with no
plan installed every hook is a single ``None`` check.  ``install(plan)`` /
``uninstall()`` or the ``active(plan)`` context manager flip it.

Fault actions:

- ``crash``   — raise :class:`FaultInjected` (not retryable: simulates a
  process death / assertion failure)
- ``oserror`` — raise ``OSError`` (retryable by the default I/O
  :class:`~znicz_tpu.resilience.retry.RetryPolicy`: simulates flaky
  filesystem / network)
- ``hang``    — block for ``seconds``, *cooperatively*: the sleep polls
  the plan's abort event so a supervisor watchdog can interrupt it
  (raising :class:`HangInterrupted`) instead of leaking a stuck thread
- ``nan``     — value-poison: ``poison(site, value)`` returns a NaN-filled
  copy at the armed hit (scalars and array pytrees)
- ``kill``    — ``SIGKILL`` the OWN process: no exception, no cleanup, no
  atexit, no snapshot — the honest simulation of an OOM-killed / preempted
  worker for multi-process drills.  Never arm it in-process in a test
  runner; it is meant for worker subprocesses via the env plan.

Cross-process plans: the elastic fleet supervisor serializes a plan into
each worker's environment as ``ZNICZ_TPU_FAULT_PLAN`` (``plan.to_env()`` /
``install_from_env()``, called by ``python -m znicz_tpu`` at boot).  Only
deterministic triggers survive the boundary — ``site``/``action``/
``at_hit``/``seconds``/``once`` — so a seeded kill drill reproduces
exactly in the worker; plans with ``when`` predicates refuse to
serialize.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import threading
import time
from typing import Callable, Optional

import numpy as np

from znicz_tpu.observe import flight as _flight
from znicz_tpu.observe import probe as _probe


class FaultInjected(RuntimeError):
    """An armed ``crash`` fault fired (simulated process death)."""


class HangInterrupted(FaultInjected):
    """An armed ``hang`` fault was aborted by the supervisor watchdog."""


class _Fault:
    __slots__ = ("site", "action", "at_hit", "when", "seconds", "fired",
                 "once")

    def __init__(self, site: str, action: str, at_hit: Optional[int],
                 when: Optional[Callable], seconds: float, once: bool):
        self.site = site
        self.action = action
        self.at_hit = at_hit
        self.when = when
        self.seconds = seconds
        self.once = once
        self.fired = 0


class FaultPlan:
    """A seeded set of armed faults plus per-site hit counters."""

    ACTIONS = ("crash", "oserror", "hang", "nan", "kill")

    def __init__(self, seed: int = 0) -> None:
        #: seeded generator for tests to derive "random" trigger points
        #: (epochs, hit counts) reproducibly
        self.seed = int(seed)
        self.rng = np.random.default_rng(seed)
        self.hits: dict[str, int] = {}
        self.log: list[dict] = []       # every fired fault, for assertions
        self._faults: list[_Fault] = []
        self._abort = threading.Event()
        self._lock = threading.Lock()

    # -- arming --------------------------------------------------------------
    def arm(self, site: str, action: str = "crash", *,
            at_hit: Optional[int] = None,
            when: Optional[Callable] = None,
            seconds: float = 30.0, once: bool = True) -> "FaultPlan":
        """Arm one fault at ``site``.  It fires when the site's hit count
        equals ``at_hit`` (1-based) and/or ``when(**context)`` is true; with
        neither condition it fires on every hit.  ``once=True`` (default)
        disarms after the first firing — the restarted run proceeds."""
        if action not in self.ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; known: "
                             f"{self.ACTIONS}")
        self._faults.append(_Fault(site, action, at_hit, when, seconds, once))
        return self

    def crash_at(self, site: str, at_hit: Optional[int] = None,
                 **kw) -> "FaultPlan":
        return self.arm(site, "crash", at_hit=at_hit, **kw)

    def hang_at(self, site: str, at_hit: Optional[int] = None,
                seconds: float = 30.0, **kw) -> "FaultPlan":
        return self.arm(site, "hang", at_hit=at_hit, seconds=seconds, **kw)

    def oserror_at(self, site: str, at_hit: Optional[int] = None,
                   **kw) -> "FaultPlan":
        return self.arm(site, "oserror", at_hit=at_hit, **kw)

    def nan_at(self, site: str, at_hit: Optional[int] = None,
               **kw) -> "FaultPlan":
        return self.arm(site, "nan", at_hit=at_hit, **kw)

    def kill_at(self, site: str, at_hit: Optional[int] = None,
                **kw) -> "FaultPlan":
        return self.arm(site, "kill", at_hit=at_hit, **kw)

    # -- cross-process serialization (ZNICZ_TPU_FAULT_PLAN) ------------------
    def to_env(self) -> str:
        """Serialize for a worker subprocess's environment.  Only the
        deterministic trigger survives (``at_hit``); a plan carrying a
        ``when`` predicate refuses loudly — closures cannot cross a
        process boundary, and silently dropping the condition would turn
        a seeded drill into fire-on-every-hit."""
        specs = []
        for f in self._faults:
            if f.when is not None:
                raise ValueError(
                    f"fault at {f.site!r} has a `when` predicate; "
                    f"predicates cannot be serialized into a worker env "
                    f"— arm with at_hit instead")
            specs.append({"site": f.site, "action": f.action,
                          "at_hit": f.at_hit, "seconds": f.seconds,
                          "once": f.once})
        return json.dumps({"seed": self.seed, "faults": specs})

    @classmethod
    def from_env(cls, text: str) -> "FaultPlan":
        doc = json.loads(text)
        plan = cls(seed=int(doc.get("seed", 0)))
        for spec in doc["faults"]:
            plan.arm(spec["site"], spec["action"],
                     at_hit=spec.get("at_hit"),
                     seconds=float(spec.get("seconds", 30.0)),
                     once=bool(spec.get("once", True)))
        return plan

    # -- watchdog integration ------------------------------------------------
    def interrupt_hangs(self) -> None:
        """Abort any in-flight (and future) injected hangs — the
        supervisor watchdog calls this when it declares a stall."""
        self._abort.set()

    def reset_abort(self) -> None:
        self._abort.clear()

    # -- firing --------------------------------------------------------------
    def _matches(self, f: _Fault, hit: int, ctx: dict) -> bool:
        if f.once and f.fired:
            return False
        if f.at_hit is not None and hit != f.at_hit:
            return False
        if f.when is not None and not f.when(**ctx):
            return False
        return True

    def _record(self, f: _Fault, hit: int) -> None:
        f.fired += 1
        self.log.append({"site": f.site, "action": f.action, "hit": hit})

    def trip(self, site: str, **ctx) -> None:
        """Count one hit of ``site``; execute the FIRST armed
        crash/oserror/hang whose condition matches (one fault per hook
        call, so N identically-armed faults survive N restarts)."""
        with self._lock:
            hit = self.hits[site] = self.hits.get(site, 0) + 1
            fault = next((f for f in self._faults
                          if f.site == site and f.action != "nan" and
                          self._matches(f, hit, ctx)), None)
            if fault is not None:
                self._record(fault, hit)
        if fault is None:
            return
        # telemetry plane: every firing lands as a counter + an instant
        # event on the step timeline (emitted OUTSIDE the plan lock —
        # the registry/tracer must never nest under it); with the flight
        # recorder configured, the firing also freezes a post-mortem
        # artifact (no-op + rate-limited otherwise)
        _probe.resilience_event("fault", site=site, action=fault.action,
                                hit=hit)
        _flight.auto_dump("fault", site=site, action=fault.action,
                          hit=hit)
        if fault.action == "kill":
            # simulated SIGKILL: die NOW, exactly like the OOM killer —
            # the elastic fleet's post-mortem comes from its own side.
            # Flush stdio first so a worker's last log lines reach the
            # supervisor's pump threads.
            import sys
            for stream in (sys.stdout, sys.stderr):
                try:
                    stream.flush()
                except Exception:  # noqa: BLE001
                    pass
            os.kill(os.getpid(), _signal.SIGKILL)
        if fault.action == "crash":
            raise FaultInjected(f"injected crash at {site} hit {hit}")
        if fault.action == "oserror":
            raise OSError(f"injected I/O failure at {site} hit {hit}")
        self._hang(fault, site, hit)

    def _hang(self, f: _Fault, site: str, hit: int) -> None:
        deadline = time.monotonic() + f.seconds
        while time.monotonic() < deadline:
            if self._abort.wait(timeout=0.02):
                raise HangInterrupted(
                    f"injected hang at {site} hit {hit} aborted by "
                    f"watchdog")
        # an un-aborted hang just ends after its duration (a stall, not a
        # crash) — the run continues

    def poison(self, site: str, value, **ctx):
        """Count one hit of ``site``; return ``value`` NaN-poisoned if an
        armed ``nan`` fault matches, unchanged otherwise.  Handles float
        scalars, numpy/jax arrays, and pytrees of arrays."""
        with self._lock:
            hit = self.hits[site] = self.hits.get(site, 0) + 1
            fault = next((f for f in self._faults
                          if f.site == site and f.action == "nan" and
                          self._matches(f, hit, ctx)), None)
            if fault is not None:
                self._record(fault, hit)
        if fault is None:
            return value
        _probe.resilience_event("fault", site=site, action="nan", hit=hit)
        return _nan_like(value)


def _nan_like(value):
    if isinstance(value, (int, float)):
        return float("nan")
    if isinstance(value, np.ndarray):
        return np.full_like(value, np.nan)
    # jax arrays / pytrees: multiply by NaN on device (keeps sharding)
    import jax

    return jax.tree.map(lambda a: a * np.float32(np.nan), value)


# -- process-global registry -------------------------------------------------
_PLAN: Optional[FaultPlan] = None

#: worker subprocesses receive their armed plan through this variable
#: (set by resilience/elastic.py, consumed by ``python -m znicz_tpu``)
PLAN_ENV_VAR = "ZNICZ_TPU_FAULT_PLAN"


def install_from_env() -> Optional[FaultPlan]:
    """Install the plan serialized in ``$ZNICZ_TPU_FAULT_PLAN`` when one
    is set (no-op otherwise).  A malformed plan raises — a kill drill
    whose plan was silently dropped would "pass" by never killing."""
    text = os.environ.get(PLAN_ENV_VAR)
    if not text:
        return None
    try:
        plan = FaultPlan.from_env(text)
    except (ValueError, KeyError, TypeError) as exc:
        raise ValueError(
            f"malformed {PLAN_ENV_VAR} ({exc!r}): {text[:200]!r}") from exc
    return install(plan)


def install(plan: FaultPlan) -> FaultPlan:
    global _PLAN
    _PLAN = plan
    return plan


def uninstall() -> None:
    global _PLAN
    _PLAN = None


def get_plan() -> Optional[FaultPlan]:
    return _PLAN


class active:
    """``with active(plan): ...`` — install for the block, always
    uninstall after (chaos tests must never leak faults into the suite)."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return install(self.plan)

    def __exit__(self, *exc) -> None:
        uninstall()


def fault_hook(site: str, **ctx) -> None:
    """Production-code hook: a single ``None`` check when no plan is
    installed (the hot-loop cost of the resilience plane is one global
    load per site visit)."""
    if _PLAN is not None:
        _PLAN.trip(site, **ctx)


def poison_hook(site: str, value, **ctx):
    """Value-poison variant of :func:`fault_hook`."""
    if _PLAN is not None:
        return _PLAN.poison(site, value, **ctx)
    return value


def interrupt_hangs() -> None:
    """Watchdog helper: abort injected hangs if a plan is installed
    (no-op otherwise — real hangs cannot be interrupted, only abandoned)."""
    if _PLAN is not None:
        _PLAN.interrupt_hangs()
