"""Per-step NaN/Inf health guard with graceful degradation.

A NaN that reaches the weights is unrecoverable without a checkpoint; a
NaN *detected as it appears* costs at most a couple of minibatches.  ``HealthGuard``
sits in the control graph right after the Decision and checks the
freshest training metrics (fused step: ``loss``/``mse``; eager MSE:
``evaluator.mse``; optionally the gradient buffers) every minibatch.

Degradation modes:

- ``mode="skip"`` (skip-batch): keep host copies of the params,
  double-buffered — a copy is promoted to the restorable "good" state
  only once a LATER finite metric certifies it (the loss published at a
  step is computed from the params *before* that step's update, so the
  freshest copy is never yet proven clean; restoring it could re-install
  the very poison being skipped).  On a non-finite metric the certified
  copy is restored — at most two batches are lost.  The copy costs one
  host sync per ``store_interval`` observations (default every
  observation — a debugging/resilience mode, not a peak-throughput
  mode; raise the interval to amortize).
- ``mode="rollback"``: delegate to a linked
  :class:`~znicz_tpu.units.nn_rollback.NNRollback` — restore its
  last-good (best-validation) state and cut the learning rates, the
  reference's divergence response, but triggered per-step instead of
  per-epoch.

Trip counters (``snapshot()``) are surfaced through
``WebStatus.register_health`` next to the serving metrics, so a
dashboard shows NaN trips alongside QPS.

Scope note: the guard protects the *parameters*.  Metrics already
published to the Decision for the poisoned minibatch stay as observed
(softmax Decisions watch integer error counts, which cannot be NaN; MSE
histories may record the one poisoned entry).
"""

from __future__ import annotations

import math

import numpy as np

from znicz_tpu.core.units import Unit
from znicz_tpu.observe import flight as _flight
from znicz_tpu.observe import probe as _probe


class HealthGuard(Unit):
    """NaN/Inf watchdog over the training metrics; see module docstring."""

    MODES = ("skip", "rollback")

    def __init__(self, workflow=None, mode: str = "skip",
                 check_grads: bool = False, store_interval: int = 1,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if mode not in self.MODES:
            raise ValueError(f"unknown HealthGuard mode {mode!r}; known: "
                             f"{self.MODES}")
        if store_interval < 1:
            raise ValueError(f"store_interval must be >= 1, got "
                             f"{store_interval}")
        self.mode = mode
        self.check_grads = bool(check_grads)
        self.store_interval = int(store_interval)
        self.target_workflow = None
        self.rollback = None            # NNRollback, for mode="rollback"
        #: double buffer: _candidate holds the freshest copy (not yet
        #: certified finite by a later metric); _good holds the newest
        #: CERTIFIED copy — the only one ever restored
        self._good: dict[str, np.ndarray] = {}
        self._candidate: dict[str, np.ndarray] = {}
        self._runs = 0
        self._observations = 0
        # trip counters (WebStatus.register_health surfaces these)
        self.nan_trips = 0
        self.skipped_batches = 0
        self.rollbacks_forced = 0
        self.last_trip_run = None

    def link_workflow_state(self, workflow) -> "HealthGuard":
        self.target_workflow = workflow
        return self

    def link_rollback(self, rollback) -> "HealthGuard":
        """Attach the NNRollback unit ``mode="rollback"`` delegates to."""
        self.rollback = rollback
        return self

    # -- observation ---------------------------------------------------------
    def _observed_metrics(self):
        """(name, value) pairs of the freshest per-step training metrics.
        Zero-size deferred publishes (mid-pass placeholders) are skipped —
        their zeroed metrics carry no information."""
        w = self.target_workflow
        step = getattr(w, "step", None)
        if step is not None:
            if int(getattr(step, "minibatch_size", 0)) > 0:
                yield "loss", float(step.loss)
                yield "mse", float(step.mse)
            return
        ev = getattr(w, "evaluator", None)
        if ev is not None:
            mse = getattr(ev, "mse", None)
            if mse is not None:
                yield "mse", float(mse)

    def _grads_finite(self) -> bool:
        for gd in getattr(self.target_workflow, "gds", []) or []:
            for attr in ("gradient_weights", "gradient_bias"):
                arr = getattr(gd, attr, None)
                if arr and not np.isfinite(arr.map_read()).all():
                    return False
        return True

    def _observe(self) -> tuple[bool, bool]:
        """-> (observed_anything, all_finite).  A run with no fresh
        metrics (deferred-metrics mid-pass placeholder publishes) is a
        non-observation: the guard neither stores a param copy (the
        params could already be poisoned without an observable metric
        yet) nor trips.  ``check_grads`` only AUGMENTS a metric
        observation — it never creates one, since in fused workflows the
        gradient buffers are not refreshed per step and a vacuous
        "grads fine" must not certify anything."""
        observed = list(self._observed_metrics())
        finite = all(math.isfinite(v) for _, v in observed)
        if observed and self.check_grads:
            finite = finite and self._grads_finite()
        return bool(observed), finite

    # -- control -------------------------------------------------------------
    def run(self) -> None:
        from znicz_tpu.units.nn_rollback import capture_params, \
            restore_params

        self._runs += 1
        observed, finite = self._observe()
        if not observed:
            return
        self._observations += 1
        if finite:
            if self.mode == "skip":
                # this finite metric was computed from the params the
                # CANDIDATE captured (the published loss is a pre-update
                # forward) — certify it as restorable; capture the
                # still-unproven current params as the next candidate on
                # the store interval
                if self._candidate:
                    self._good = self._candidate
                    self._candidate = {}
                if (self._observations - 1) % self.store_interval == 0:
                    self._candidate = capture_params(self.target_workflow)
            return
        self.nan_trips += 1
        self.last_trip_run = self._runs
        _probe.resilience_event("nan_guard", action=self.mode,
                                run=self._runs, trip=self.nan_trips)
        # a NaN trip is exactly the "what led up to this" moment the
        # flight recorder exists for (no-op unless flight.configure()
        # opted in)
        _flight.auto_dump("nan_guard", mode=self.mode, run=self._runs,
                          trip=self.nan_trips)
        if self.mode == "skip":
            # the candidate may be the poison itself (captured after the
            # update this metric is now flagging) — drop it
            self._candidate = {}
            if self._good:
                restore_params(self.target_workflow, self._good)
                self.skipped_batches += 1
                self.warning(f"health: non-finite metric at run "
                             f"{self._runs}; batch skipped (params "
                             f"restored, trip #{self.nan_trips})")
            else:
                self.warning(f"health: non-finite metric at run "
                             f"{self._runs} before any certified state "
                             f"was captured; nothing restored")
            return
        if self.rollback is None:
            raise RuntimeError('HealthGuard(mode="rollback") needs '
                               'link_rollback(NNRollback) before run')
        self.rollback.force_rollback()
        self.rollbacks_forced += 1
        self.warning(f"health: non-finite metric at run {self._runs}; "
                     f"forced rollback #{self.rollbacks_forced}")

    # -- observability -------------------------------------------------------
    def snapshot(self) -> dict:
        """Counters for ``WebStatus.register_health``."""
        return {"mode": self.mode,
                "runs": self._runs,
                "nan_trips": self.nan_trips,
                "skipped_batches": self.skipped_batches,
                "rollbacks_forced": self.rollbacks_forced,
                "last_trip_run": self.last_trip_run}
