"""Bounded retries with exponential backoff and seeded jitter.

One reusable :class:`RetryPolicy` covers every flaky-I/O surface in the
tree: loader file reads (``loader/image.py``, ``loader/pickles.py``),
snapshot writes (``snapshotter.py``, ``parallel/checkpoint.py``) and the
RESTful client (``loader/restful.py :: predict_remote``).  The policy is
deliberately *dumb and deterministic*: attempt count, exponential delay,
jitter from a seeded generator (two policies with the same seed back off
identically — chaos tests pin the schedule), an exception filter so
programming errors (``ValueError``, architecture mismatches) never get
retried, and an optional per-attempt timeout for calls that can wedge.

Injected clocks (``sleep=``, ``clock=``) make the unit tests instant.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Callable, Optional, Tuple, Type

import numpy as np

from znicz_tpu.observe import probe as _probe


class AttemptTimeout(Exception):
    """One attempt exceeded the policy's per-attempt ``timeout``.

    Always counts as retryable — a wedged call is the textbook transient.
    The timed-out attempt keeps running in its daemon thread (Python
    cannot kill threads); the policy abandons it and tries again.
    """


class RetryPolicy:
    """``policy.call(fn, *args, **kwargs)`` with bounded retries.

    Parameters
    ----------
    max_attempts:  total tries including the first (>= 1).
    base_delay:    backoff before the 2nd attempt, seconds.
    multiplier:    exponential growth factor per further attempt.
    max_delay:     backoff ceiling, seconds.
    jitter:        +/- fraction of the delay drawn from the seeded rng
                   (0.25 -> delay * U[0.75, 1.25]); 0 disables.
    retryable:     exception classes worth retrying; anything else
                   propagates immediately.  ``AttemptTimeout`` is always
                   retryable.
    timeout:       per-attempt wall-clock limit (None = unbounded).
    seed:          jitter stream seed (deterministic schedules).
    sleep/clock:   injectable for tests (fake clock).
    """

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.05,
                 multiplier: float = 2.0, max_delay: float = 2.0,
                 jitter: float = 0.25,
                 retryable: Tuple[Type[BaseException], ...] = (OSError,),
                 timeout: Optional[float] = None, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got "
                             f"{max_attempts}")
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.multiplier = float(multiplier)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.retryable = tuple(retryable)
        self.timeout = timeout
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self._sleep = sleep
        self._clock = clock
        # observability (read by tests and the supervisor report)
        self.total_attempts = 0
        self.total_retries = 0
        self.last_delays: list[float] = []

    def delay_for(self, attempt: int) -> float:
        """Backoff after failed attempt ``attempt`` (1-based), jittered."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter:
            d *= 1.0 + self.jitter * float(self._rng.uniform(-1.0, 1.0))
        return d

    def _attempt(self, fn, args, kwargs):
        if self.timeout is None:
            return fn(*args, **kwargs)
        box: dict = {}

        def runner():
            try:
                box["value"] = fn(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                box["error"] = exc

        t = threading.Thread(target=runner, daemon=True)
        start = self._clock()
        t.start()
        t.join(self.timeout)
        if t.is_alive():
            raise AttemptTimeout(
                f"attempt exceeded {self.timeout}s "
                f"(elapsed {self._clock() - start:.3f}s)")
        if "error" in box:
            raise box["error"]
        return box.get("value")

    def call(self, fn: Callable, *args, **kwargs):
        self.last_delays = []
        for attempt in range(1, self.max_attempts + 1):
            self.total_attempts += 1
            try:
                return self._attempt(fn, args, kwargs)
            except (self.retryable + (AttemptTimeout,)) as exc:
                if attempt == self.max_attempts:
                    raise
                self.total_retries += 1
                # telemetry plane: each retry is a counter + timeline
                # instant so flaky-I/O storms correlate with the steps
                # they stall
                _probe.resilience_event(
                    "retry", site=getattr(fn, "__name__", repr(fn)),
                    attempt=attempt, error=type(exc).__name__)
                d = self.delay_for(attempt)
                self.last_delays.append(d)
                self._sleep(d)

    def wrap(self, fn: Callable) -> Callable:
        """Decorator form: ``decoded = policy.wrap(_decode)(path, shape)``."""

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return wrapped


#: shared default for loader file reads and snapshot writes: 3 attempts,
#: 50 ms -> 100 ms backoff, retries OSError only (a corrupt pickle or an
#: architecture mismatch is not transient).  Instantiated once so its
#: counters aggregate process-wide I/O flakiness.
DEFAULT_IO_RETRY = RetryPolicy(max_attempts=3, base_delay=0.05,
                               multiplier=2.0, max_delay=1.0,
                               retryable=(OSError,), seed=0)
