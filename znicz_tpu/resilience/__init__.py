"""Resilience plane — fault injection, retries, supervised auto-resume
training, and health guards (beyond-reference; the training-side
counterpart of the serve/ plane).

The reference framework treated worker failure as fatal: an exception
anywhere tore the whole process down, and recovery meant a human
re-launching ``veles -w snap.pickle.gz``.  Production-scale training
systems treat failure as routine (TensorFlow, arXiv 1605.08695): the
supervisor catches the crash, restores the newest *valid* checkpoint and
resumes — and the snapshotter's bit-exact resume contract is exactly what
makes that recovery verifiable.

Modules:

- :mod:`znicz_tpu.resilience.faults` — deterministic, seeded fault
  injection (``FaultPlan``) with explicit hook sites in the production
  code paths, so chaos tests drive real code, not mocks.
- :mod:`znicz_tpu.resilience.retry` — ``RetryPolicy`` (bounded attempts,
  exponential backoff with seeded jitter, retryable-exception filter,
  per-attempt timeout) applied to the flaky-I/O surfaces.
- :mod:`znicz_tpu.resilience.supervisor` — ``run_supervised``: in-process
  crash/hang supervision with checkpoint auto-resume, poison-snapshot
  rejection, and a bounded restart budget.
- :mod:`znicz_tpu.resilience.health` — per-step NaN/Inf guard with
  skip-batch or rollback degradation, trip counters surfaced through
  ``WebStatus``.
- :mod:`znicz_tpu.resilience.elastic` — ``run_elastic``: the
  multi-PROCESS fleet supervisor (heartbeat + exit-code watch, SIGKILL
  a worker and the fleet resumes from the newest valid snapshot at a
  possibly different world size).
"""

import importlib

#: public name -> defining submodule.  Resolution is lazy (PEP 562): the
#: fault/retry hook sites live in import-weight-sensitive modules
#: (core/workflow.py, serve/engine.py — the latter must stay importable
#: without JAX for the native serving path), and the supervisor pulls
#: the snapshotter (and thus jax) in; eager re-exports here would drag
#: that into every hook site's import chain.
_EXPORTS = {
    "FaultInjected": "faults", "HangInterrupted": "faults",
    "FaultPlan": "faults", "fault_hook": "faults", "poison_hook": "faults",
    "install": "faults", "uninstall": "faults", "active": "faults",
    "get_plan": "faults", "interrupt_hangs": "faults",
    "AttemptTimeout": "retry", "RetryPolicy": "retry",
    "DEFAULT_IO_RETRY": "retry",
    "StepHangError": "supervisor", "SupervisorExhausted": "supervisor",
    "SupervisorPolicy": "supervisor", "SupervisorReport": "supervisor",
    "find_latest_valid_snapshot": "supervisor",
    "run_supervised": "supervisor",
    "HealthGuard": "health",
    "ElasticExhausted": "elastic", "ElasticReport": "elastic",
    "run_elastic": "elastic", "start_heartbeat": "elastic",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)
